#!/usr/bin/env python
"""Diff static memory-feasibility ceilings against SCALE_BUDGET.json —
the OOM-regression gate that needs no chip.

Traces every auditable entry point (the jaxpr prong's registry) at its
toy shape, prices the program as a footprint polynomial in N
(ringpop_tpu/analysis/ranges.buffer_poly), and binary-searches the
largest N* whose abstract footprint fits the per-chip HBM budget (see
ringpop_tpu/analysis/scale_budget.py).  A refactor that adds an [N,N]
temp, widens a dtype, or raises the polynomial degree fails the diff.

Usage::

    python scripts/check_scale_budget.py                    # diff, exit 1 on drift
    python scripts/check_scale_budget.py --write            # regenerate manifest
    python scripts/check_scale_budget.py --entries a,b,c    # subset (diff only)
    python scripts/check_scale_budget.py --rtol 0.02

``--write`` REFUSES to commit a manifest containing entries that failed
to trace or analyze — a broken entry point is a finding, not a budget.
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from ringpop_tpu.analysis import scale_budget  # noqa: E402
from ringpop_tpu.analysis.findings import render_text  # noqa: E402


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        "--write",
        action="store_true",
        help="analyze the entry points and (re)write SCALE_BUDGET.json",
    )
    parser.add_argument(
        "--budget",
        default=None,
        help="manifest path (default: SCALE_BUDGET.json at repo root)",
    )
    parser.add_argument(
        "--entries",
        default=None,
        help="comma-separated entry-name subset (diff mode only)",
    )
    parser.add_argument(
        "--rtol",
        type=float,
        default=scale_budget.DEFAULT_RTOL,
        help="relative N* drift tolerance (default %g)"
        % scale_budget.DEFAULT_RTOL,
    )
    args = parser.parse_args(argv)
    path = Path(args.budget) if args.budget else None
    names = (
        [n.strip() for n in args.entries.split(",") if n.strip()]
        if args.entries
        else None
    )

    if args.write:
        if names is not None:
            parser.error(
                "--write regenerates the FULL manifest; drop --entries"
            )
        actual = scale_budget.collect_budgets()
        out = scale_budget.write_manifest(actual, path)
        bound = sum(
            1 for e in actual.values() if not e.get("ceiling_bound")
        )
        print(
            "wrote %s (%d entries, %d memory-bound below their declared "
            "ceiling)" % (out, len(actual), bound)
        )
        return 0

    findings = scale_budget.check_against_manifest(
        entry_names=names, path=path, rtol=args.rtol
    )
    print(render_text(findings))
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
