#!/usr/bin/env python3
"""Write a hosts.json bootstrap file (reference: scripts/generate-hosts.js)."""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from ringpop_tpu.api.tick_cluster import generate_hosts  # noqa: E402


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="generate-hosts")
    p.add_argument("-n", type=int, default=5, help="number of hosts")
    p.add_argument("--base-port", type=int, default=3000)
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--output", "-o", default="hosts.json")
    args = p.parse_args(argv)
    hosts = generate_hosts(args.output, args.n, args.base_port, args.host)
    print(json.dumps(hosts))
    return 0


if __name__ == "__main__":
    sys.exit(main())
