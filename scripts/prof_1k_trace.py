#!/usr/bin/env python3
"""Per-op XLA profile of the 1k full-fidelity fast-mode scan on TPU.

Captures a jax.profiler trace of the 32-tick bench scan, then parses the
perfetto trace JSON for the top ops by device self-time — the data the
1k chip-vs-CPU gap decision needs (RESULTS_TPU_r04: 22.2k node-ticks/s
TPU vs 50.8k CPU; batched vmap made it WORSE, so the cost lives in
specific ops, not launch overhead).

Writes PROF_1K_OPS.json: [{"op": ..., "total_ms": ..., "count": ...}].
"""

from __future__ import annotations

import glob
import gzip
import json
import os
import sys
import time
from collections import defaultdict

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

OUT = os.environ.get("PROF_1K_OUT", "PROF_1K_OPS.json")
TRACE_DIR = "/tmp/jax_trace_1k"


def main() -> int:
    from ringpop_tpu.utils.util import scrub_repo_pythonpath, wait_for_tpu

    scrub_repo_pythonpath(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    import ringpop_tpu  # noqa: F401

    wait_for_tpu(__file__, "PROF_1K_ATTEMPT", 90, 20.0)
    import jax

    from ringpop_tpu.models.sim import engine
    from ringpop_tpu.models.sim.cluster import EventSchedule, SimCluster

    n, ticks = 1024, 32
    sim = SimCluster(
        n=n, params=engine.SimParams(n=n, checksum_mode="fast")
    )
    sim.bootstrap()
    sched = EventSchedule(ticks=ticks, n=n)
    sim.run(sched)  # compile + warm
    jax.block_until_ready(sim.state)

    t0 = time.perf_counter()
    with jax.profiler.trace(TRACE_DIR):
        sim.run(sched)
        jax.block_until_ready(sim.state)
    wall = time.perf_counter() - t0

    # parse the perfetto trace for TPU-lane op events
    paths = glob.glob(
        os.path.join(TRACE_DIR, "**", "*.trace.json.gz"), recursive=True
    )
    agg = defaultdict(lambda: [0.0, 0])
    if paths:
        with gzip.open(sorted(paths)[-1], "rt") as f:
            trace = json.load(f)
        events = trace.get("traceEvents", [])
        # find TPU/device process ids (names contain 'TPU' or 'Device')
        pid_names = {}
        for e in events:
            if e.get("ph") == "M" and e.get("name") == "process_name":
                pid_names[e.get("pid")] = e.get("args", {}).get("name", "")
        dev_pids = {
            p
            for p, name in pid_names.items()
            if "TPU" in name or "/device:" in name or "Device" in name
        }
        for e in events:
            if e.get("ph") != "X" or e.get("pid") not in dev_pids:
                continue
            dur = e.get("dur", 0) / 1e3  # us -> ms
            name = e.get("name", "?")
            agg[name][0] += dur
            agg[name][1] += 1
    top = sorted(
        (
            {"op": k, "total_ms": round(v[0], 2), "count": v[1]}
            for k, v in agg.items()
        ),
        key=lambda d: -d["total_ms"],
    )[:60]
    out = {
        "wall_s": round(wall, 3),
        "n": n,
        "ticks": ticks,
        "device": str(jax.devices()[0]),
        "pid_names": sorted(set(pid_names.values())) if paths else [],
        "top_ops": top,
    }
    with open(OUT, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps({"wall_s": out["wall_s"], "n_ops": len(top)}))
    for d in top[:25]:
        print(json.dumps(d))
    return 0


if __name__ == "__main__":
    sys.exit(main())
