#!/usr/bin/env python3
"""Round-4 chip diagnosis: (A) where the 1k fast scan's wall time goes
now that device compute collapsed (~15 ms device vs ~1.4 s wall per
32-tick run — per-run transport/launch overhead suspected), and (B)
which ingredient of the parity-mode graph trips the tunnel's
remote-compile helper 500 (deterministic across 12+ attempts at 1k
while n=64 parity compiled fine in round 3).

Writes DIAG_1K.json.
"""

from __future__ import annotations

import functools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

OUT = os.environ.get("DIAG_1K_OUT", "DIAG_1K.json")


def main() -> int:
    from ringpop_tpu.utils.util import scrub_repo_pythonpath, wait_for_tpu

    scrub_repo_pythonpath(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    import ringpop_tpu  # noqa: F401

    wait_for_tpu(__file__, "DIAG_1K_ATTEMPT", 90, 20.0)
    import jax
    import numpy as np

    from ringpop_tpu.models.sim import engine
    from ringpop_tpu.models.sim.cluster import EventSchedule, SimCluster

    res = {"device": str(jax.devices()[0])}

    # ---- A: wall-time decomposition of the fast scan -------------------
    n = 1024
    for ticks in (32, 256):
        sim = SimCluster(
            n=n, params=engine.SimParams(n=n, checksum_mode="fast")
        )
        sim.bootstrap()
        sched = EventSchedule(ticks=ticks, n=n)
        sim.run(sched)  # compile + warm (uploads + memoizes inputs)
        jax.block_until_ready(sim.state)
        walls = []
        for _ in range(5):
            t0 = time.perf_counter()
            sim.run(sched)
            jax.block_until_ready(sim.state)
            walls.append(time.perf_counter() - t0)
        res["fast_scan_%dticks" % ticks] = {
            "wall_s_runs": [round(w, 3) for w in walls],
            "best_node_ticks_per_sec": round(n * ticks / min(walls), 1),
        }
        print(
            json.dumps({("fast_%d" % ticks): res["fast_scan_%dticks" % ticks]}),
            flush=True,
        )

    # ---- B: parity-graph compile bisect --------------------------------
    from ringpop_tpu.ops import checksum_encode as ce

    def attempt(name, fn):
        try:
            t0 = time.perf_counter()
            out = fn()
            jax.block_until_ready(out)
            res[name] = {"ok": True, "s": round(time.perf_counter() - t0, 2)}
        except Exception as e:
            res[name] = {"ok": False, "error": str(e)[:300]}
        print(json.dumps({name: res[name]}), flush=True)

    def parity_sim(ticks, **pkw):
        params = engine.SimParams(n=n, checksum_mode="farmhash", **pkw)
        sim = SimCluster(n=n, params=params)
        sim.bootstrap()
        sched = EventSchedule(ticks=ticks, n=n)
        m = sim.run(sched)
        return sim.state.checksum

    # one non-scanned parity tick
    def parity_single_tick():
        params = engine.SimParams(n=n, checksum_mode="farmhash")
        sim = SimCluster(n=n, params=params)
        sim.bootstrap()  # bootstrap itself runs one jitted parity tick
        return sim.state.checksum

    attempt("parity_single_tick", parity_single_tick)
    attempt("parity_scan4", lambda: parity_sim(4))
    attempt("parity_scan32", lambda: parity_sim(32))
    attempt(
        "parity_scan32_dirty64", lambda: parity_sim(32, dirty_batch=64)
    )
    attempt(
        "parity_scan32_nogate",
        lambda: parity_sim(32, gate_phases=False),
    )

    with open(OUT, "w") as f:
        json.dump(res, f, indent=1)
    print(json.dumps(res))
    return 0


if __name__ == "__main__":
    sys.exit(main())
