#!/usr/bin/env python3
"""North-star #1 artifact: engine vs host-oracle checksum parity at scale.

Runs the batched engine (farmhash mode) and the host object oracle through
the same schedule — bootstrap, churn (kills + revives), quiet convergence —
asserting bit-identical per-node checksums after every tick, and writes a
JSON report.  The 1k-node configuration is the BASELINE.md parity target.

Usage: python scripts/parity_check.py [-n 1024] [--ticks 40] [-o PARITY.json]
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="parity-check")
    p.add_argument("-n", type=int, default=1024)
    p.add_argument("--ticks", type=int, default=40)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--output", "-o", default=None)
    args = p.parse_args(argv)

    # the oracle comparison is a host-side workload; pin CPU before any
    # backend init (the env var alone is captured at sitecustomize import
    # and ignored afterwards — RESULTS.md round 4)
    from ringpop_tpu.utils.util import pin_cpu_platform

    pin_cpu_platform()
    import jax  # noqa: F401
    import numpy as np

    from ringpop_tpu.models.sim import engine
    from ringpop_tpu.models.sim.cluster import default_addresses
    from ringpop_tpu.ops import checksum_encode as ce
    from ringpop_tpu.parity import OracleCluster

    n = args.n
    params = engine.SimParams(n=n, checksum_mode="farmhash")
    addresses = default_addresses(n)
    universe = ce.Universe.from_addresses(addresses)
    state = engine.init_state(params, seed=args.seed, universe=universe)
    oracle = OracleCluster(params, addresses, seed=args.seed)
    tick = jax.jit(lambda s, i: engine.tick(s, i, params, universe))

    rng = np.random.default_rng(args.seed)
    schedule = [{"join": np.ones(n, bool)}]
    down: list = []
    quiet_tail = max(args.ticks // 3, 12)  # reconvergence window at the end
    for t in range(1, args.ticks):
        ev = {}
        if t % 10 == 5 and t < args.ticks - quiet_tail:
            # churn pulse: kill a few, revive earlier victims
            kill = np.zeros(n, bool)
            victims = rng.choice(n, size=max(1, n // 200), replace=False)
            kill[victims] = True
            ev["kill"] = kill
            if down:
                rv = np.zeros(n, bool)
                rv[down.pop()] = True
                ev["revive"] = rv
            down.append(victims)
        schedule.append(ev)

    t0 = time.time()
    mismatch_ticks = 0
    for t, ev in enumerate(schedule):
        inputs = engine.TickInputs.quiet(n)._replace(
            **{k: jax.numpy.asarray(v) for k, v in ev.items()}
        )
        state, metrics = tick(state, inputs)
        got = np.asarray(state.checksum).astype(np.uint32)
        res = oracle.tick(ev)
        bad = np.flatnonzero(got != res.checksums)
        if bad.size:
            mismatch_ticks += 1
            print(
                json.dumps(
                    {
                        "tick": t,
                        "mismatched_nodes": bad[:8].tolist(),
                        "engine": [int(x) for x in got[bad[:4]]],
                        "oracle": [int(x) for x in res.checksums[bad[:4]]],
                    }
                ),
                file=sys.stderr,
            )

    report = {
        "metric": "checksum_parity_engine_vs_host_oracle",
        "n_nodes": n,
        "ticks": len(schedule),
        "checksum_comparisons": n * len(schedule),
        "mismatched_ticks": mismatch_ticks,
        "parity": mismatch_ticks == 0,
        "converged_at_end": bool(np.asarray(metrics.converged)),
        "elapsed_s": round(time.time() - t0, 1),
        "checksum_mode": "farmhash (bit-exact reference strings)",
    }
    print(json.dumps(report))
    if args.output:
        with open(args.output, "w") as f:
            json.dump(report, f)
    return 0 if mismatch_ticks == 0 else 1


if __name__ == "__main__":
    sys.exit(main())
