#!/usr/bin/env python
"""Diff XLA static costs of the compiled entry points against
COST_BUDGET.json — the perf-regression gate that needs no chip.

Compiles every auditable entry point (the jaxpr prong's registry) at
its toy shape and compares ``cost_analysis()`` flops/bytes and
``memory_analysis()`` sizes to the committed manifest (see
ringpop_tpu/analysis/cost.py).  An accidental O(N^2) blowup, a widened
dtype, or a new temp buffer fails the diff.

Usage::

    python scripts/check_cost_budget.py                    # diff, exit 1 on drift
    python scripts/check_cost_budget.py --write            # regenerate manifest
    python scripts/check_cost_budget.py --entries a,b,c    # subset (diff only)
    python scripts/check_cost_budget.py --rtol 0.05

``--write`` REFUSES to commit a manifest containing entries that failed
to trace or compile — a broken entry point is a finding, not a budget.
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from ringpop_tpu.analysis import cost  # noqa: E402
from ringpop_tpu.analysis.findings import render_text  # noqa: E402


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        "--write",
        action="store_true",
        help="compile the entry points and (re)write COST_BUDGET.json",
    )
    parser.add_argument(
        "--budget",
        default=None,
        help="manifest path (default: COST_BUDGET.json at repo root)",
    )
    parser.add_argument(
        "--entries",
        default=None,
        help="comma-separated entry-name subset (diff mode only)",
    )
    parser.add_argument(
        "--rtol",
        type=float,
        default=cost.DEFAULT_RTOL,
        help="relative drift tolerance (default %g)" % cost.DEFAULT_RTOL,
    )
    args = parser.parse_args(argv)
    path = Path(args.budget) if args.budget else None
    names = (
        [n.strip() for n in args.entries.split(",") if n.strip()]
        if args.entries
        else None
    )

    if args.write:
        if names is not None:
            parser.error("--write regenerates the FULL manifest; drop --entries")
        actual = cost.collect_costs()
        out = cost.write_manifest(actual, path)
        flops = sum(e.get("flops", 0) for e in actual.values())
        print(
            "wrote %s (%d entries, %d total budgeted flops)"
            % (out, len(actual), flops)
        )
        return 0

    findings = cost.check_against_manifest(
        entry_names=names, path=path, rtol=args.rtol
    )
    print(render_text(findings))
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
