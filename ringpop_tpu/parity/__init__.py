"""Checksum-parity harness: host-object oracle run in lockstep with the
batched device engine, asserting bitwise-identical per-node membership
checksums every tick (the BASELINE.md north-star #1 contract)."""

from ringpop_tpu.parity.oracle import OracleCluster, OracleTickResult

__all__ = ["OracleCluster", "OracleTickResult"]
