"""Lockstep host oracle for the batched SWIM engine.

One host :class:`~ringpop_tpu.models.membership.host.Membership` instance per
simulated node — the object model mirrored from the reference
(lib/membership/member.js precedence rules, lib/membership/index.js:48-123
checksum strings hashed with the C++ FarmHash oracle) — driven through the
exact per-tick phase schedule of :mod:`ringpop_tpu.models.sim.engine`:

    kill/revive -> join -> iterator target selection -> sender piggyback ->
    delivery -> receiver apply -> receiver piggyback -> responses/full-sync ->
    ping-req -> suspicion expiry -> checksums

The *decision plane* (who pings whom, which packets drop, ping-req fanout
picks, iterator reshuffles) reuses the engine's own deterministic RNG
helpers (``engine._uniform`` / ``engine._fold``) on host, so both sides see
the identical message schedule.  Everything *semantic* — SWIM update
precedence, refutation, new-member acceptance, dissemination budgets and
expiry, the receiver-origin filter, full-sync, suspicion timers, checksum
string construction and FarmHash32 — runs through the independent host
object model.  ``tick()`` returns per-node uint32 checksums that must equal
``SimState.checksum`` bit-for-bit every tick; any divergence in either
implementation's protocol semantics surfaces as a checksum mismatch.

Reference contracts validated transitively: membership checksum
(lib/membership/index.js:48-123), SWIM precedence (member.js:171-202),
refute (member.js:76-81,155-169), dissemination budget/filter/full-sync
(lib/gossip/dissemination.js:38-114,133-176), suspicion (suspicion.js),
convergence = all live checksums equal
(benchmarks/convergence-time/scenario-runner.js:152-170).
"""

from __future__ import annotations

import dataclasses
import random
from typing import Dict, List, Optional, Sequence

import numpy as np

from ringpop_tpu.models.membership.host import Membership, Status
from ringpop_tpu.models.membership.host import Update as HostUpdate
from ringpop_tpu.models.sim import engine
from ringpop_tpu.ops import checksum_encode as ce
from ringpop_tpu.ops import native
from ringpop_tpu.utils.config import Config
from ringpop_tpu.utils.util import null_logger

STATUS_STR = ce.STATUS_STRINGS  # code -> string
STATUS_CODE = {s: i for i, s in enumerate(STATUS_STR)}


def _np_uniform(rng: np.ndarray, shape, salt: int) -> np.ndarray:
    """Engine ``_uniform`` evaluated on host (same ops, same bits)."""
    return np.asarray(engine._uniform(rng, shape, salt))


def _np_fold(rng: np.ndarray, salt: int) -> np.ndarray:
    return np.asarray(engine._fold(rng, salt))


def _digits(x: int) -> int:
    """Integer digit count — engine ``_max_piggyback``'s inner loop."""
    return sum(1 for k in range(10) if x >= 10**k)


class _Ctx:
    """Per-node ringpop stub for the host Membership (clock-controlled,
    always ready — the engine applies updates directly, no stashing)."""

    def __init__(self, address: str):
        self.host_port = address
        self.is_ready = True
        self.logger = null_logger()
        self.config = Config(self)
        self._now_ms = 0

    def whoami(self) -> str:
        return self.host_port

    def now(self) -> int:
        return self._now_ms

    def stat(self, *a, **k) -> None:
        pass


@dataclasses.dataclass
class _Change:
    """Dissemination change-table entry (dissemination.js ``this.changes``)."""

    status: int  # code
    inc: int
    source: int  # node index
    source_inc: int
    pb: int = 0


@dataclasses.dataclass
class OracleTickResult:
    checksums: np.ndarray  # [N] uint32
    converged: bool
    distinct_checksums: int
    full_syncs: int
    pings_sent: int


class _Node:
    def __init__(self, cluster: "OracleCluster", idx: int, now_ms: int):
        self.idx = idx
        addr = cluster.addresses[idx]
        self.ctx = _Ctx(addr)
        self.ctx._now_ms = now_ms
        self.membership = Membership(self.ctx, rng=random.Random(idx))
        # the Member state machine reaches back through the ringpop context
        # for LocalMemberLeaveEvent emission (host.py Member.evaluate_update)
        self.ctx.membership = self.membership
        self.membership.make_alive(addr, now_ms)
        self.changes: Dict[int, _Change] = {}
        self.susp: Dict[int, int] = {}  # subject -> deadline tick


class OracleCluster:
    """N host-membership nodes stepped in engine phase order.

    Mirror of ``engine.init_state`` + ``engine.tick`` at the semantic level;
    see module docstring.  ``seed`` must match the engine's so the decision
    plane coincides.
    """

    def __init__(self, params: engine.SimParams, addresses: Sequence[str], seed: int = 0):
        if len(addresses) != params.n:
            raise ValueError("addresses must have length params.n")
        self.params = params
        self.addresses = tuple(sorted(addresses))
        self.addr_idx = {a: i for i, a in enumerate(self.addresses)}
        n = params.n
        # engine.init_state's exact RNG draws (same numpy generator)
        rng = np.random.default_rng(seed)
        self.perm = np.stack([rng.permutation(n) for _ in range(n)]).astype(np.int32)
        self.rng = rng.integers(1, 2**32 - 1, size=(n, 2), dtype=np.uint32)
        self.iter_pos = np.zeros(n, np.int32)
        self.tick_index = 0
        self.proc_alive = np.ones(n, bool)
        self.ready = np.zeros(n, bool)
        self.gossip_on = np.ones(n, bool)
        self.partition = np.zeros(n, np.int32)
        self.checksum = np.zeros(n, np.uint32)  # cached, as engine caches
        self.nodes = [_Node(self, i, params.epoch_ms) for i in range(n)]

    # -- view helpers -----------------------------------------------------

    def _views(self):
        """(known, status, inc) [N, N] arrays from the host memberships."""
        n = self.params.n
        known = np.zeros((n, n), bool)
        status = np.zeros((n, n), np.int32)
        inc = np.zeros((n, n), np.int64)
        for i, node in enumerate(self.nodes):
            for m in node.membership.members:
                j = self.addr_idx[m.address]
                known[i, j] = True
                status[i, j] = STATUS_CODE[m.status]
                inc[i, j] = m.incarnation_number
        return known, status, inc

    def _self_inc(self, i: int) -> int:
        m = self.nodes[i].membership.find_member_by_address(self.addresses[i])
        return m.incarnation_number if m is not None else 0

    def _apply(self, i: int, updates: List[dict], tick_next: int) -> List:
        """Apply updates through node i's host Membership; maintain the
        change table + suspicion deadlines like engine._apply_updates."""
        node = self.nodes[i]
        applied = node.membership.update(updates)
        p = self.params
        for u in applied:
            j = self.addr_idx[u.address]
            node.changes[j] = _Change(
                status=STATUS_CODE[u.status],
                inc=u.incarnation_number,
                source=self.addr_idx.get(u.source, -1),
                source_inc=u.source_incarnation_number
                if u.source_incarnation_number is not None
                else 0,
                pb=0,
            )
            if u.status == Status.suspect and j != i:
                node.susp[j] = tick_next + p.suspicion_ticks
            elif u.status != Status.suspect:
                node.susp.pop(j, None)
        return applied

    def _compute_checksums(self) -> np.ndarray:
        out = np.zeros(self.params.n, np.uint32)
        for i, node in enumerate(self.nodes):
            s = node.membership.generate_checksum_string()
            out[i] = native.hash32(s)
        return out

    # -- the tick ---------------------------------------------------------

    def tick(self, inputs: Optional[dict] = None) -> OracleTickResult:
        p = self.params
        n = p.n
        inputs = inputs or {}
        kill = np.asarray(inputs.get("kill", np.zeros(n, bool)), bool)
        revive = np.asarray(inputs.get("revive", np.zeros(n, bool)), bool)
        join_in = np.asarray(inputs.get("join", np.zeros(n, bool)), bool)
        part_in = np.asarray(
            inputs.get("partition", np.full(n, -1, np.int32)), np.int32
        )

        leave_in = np.asarray(inputs.get("leave", np.zeros(n, bool)), bool)
        resume_in = np.asarray(inputs.get("resume", np.zeros(n, bool)), bool)

        tick_next = self.tick_index + 1
        now_ms = p.epoch_ms + tick_next * p.period_ms
        for node in self.nodes:
            node.ctx._now_ms = now_ms

        # ---- phase 0: fault plane --------------------------------------
        prev_alive = self.proc_alive.copy()
        self.proc_alive = (self.proc_alive & ~kill) | revive | resume_in
        self.partition = np.where(part_in >= 0, part_in, self.partition)
        rv = revive & ~prev_alive
        for i in np.flatnonzero(rv):
            self.nodes[i] = _Node(self, int(i), now_ms)
            self.nodes[i].ctx._now_ms = now_ms
            self.ready[i] = False
            self.gossip_on[i] = True
        self.tick_index = tick_next

        # ---- phase 0.5: graceful leave + rejoin-from-leave -------------
        # (engine phase 0.5; makeLeave at current incarnation, gossip off;
        # rejoin = alive with fresh incarnation, gossip back on)
        for i in np.flatnonzero(leave_in & self.proc_alive & self.ready):
            mem = self.nodes[i].membership
            m = mem.find_member_by_address(self.addresses[i])
            if m is None or m.status == Status.leave:
                continue
            self._apply(
                i,
                [
                    {
                        "address": self.addresses[i],
                        "status": Status.leave,
                        "incarnationNumber": m.incarnation_number,
                        "source": self.addresses[i],
                        "sourceIncarnationNumber": m.incarnation_number,
                    }
                ],
                tick_next,
            )
            self.gossip_on[i] = False
        for i in np.flatnonzero(join_in & self.proc_alive & self.ready):
            m = self.nodes[i].membership.find_member_by_address(
                self.addresses[i]
            )
            if m is None or m.status != Status.leave:
                continue
            self._apply(
                i,
                [
                    {
                        "address": self.addresses[i],
                        "status": Status.alive,
                        "incarnationNumber": now_ms,
                        "source": self.addresses[i],
                        "sourceIncarnationNumber": now_ms,
                    }
                ],
                tick_next,
            )
            self.gossip_on[i] = True

        # ---- phase 1: join ----------------------------------------------
        joiner = (join_in | rv) & self.proc_alive & ~self.ready
        known0, status0, inc0 = self._views()  # pre-join snapshot
        eye = np.eye(n, dtype=bool)
        conn = self.partition[:, None] == self.partition[None, :]
        can_join = joiner[:, None] & self.proc_alive[None, :] & ~eye & conn
        jrand = _np_uniform(self.rng, (n, n), salt=101)
        jscore = np.where(can_join, jrand, np.float32(2.0))
        jorder = np.argsort(jscore, axis=1, kind="stable")[:, : p.join_size]
        jvalid = np.take_along_axis(jscore, jorder, axis=1) < 1.5

        joined = joiner & jvalid.any(axis=1)
        for i in np.flatnonzero(joined):
            node = self.nodes[i]
            mem = node.membership
            # key-max merge of targets' views into the joiner's view,
            # bypassing the precedence gate — join installs the aggregated
            # response verbatim (join-sender aggregate + join-response-merge;
            # engine phase 1 direct overwrite).  The joiner's own entry is
            # protected (engine keep_self).
            for k in range(p.join_size):
                if not jvalid[i, k]:
                    continue
                t = int(jorder[i, k])
                for j in np.flatnonzero(known0[t]):
                    if j == i:
                        continue
                    addr = self.addresses[j]
                    key_t = int(inc0[t, j]) * 4 + int(status0[t, j])
                    m = mem.find_member_by_address(addr)
                    if m is None:
                        u = HostUpdate(
                            addr,
                            int(inc0[t, j]),
                            STATUS_STR[status0[t, j]],
                            source=self.addresses[i],
                            source_incarnation_number=self._self_inc(i),
                        )
                        m = mem._create_member(u)
                        mem.members.insert(mem.get_join_position(), m)
                        mem.members_by_address[addr] = m
                    elif key_t > m.incarnation_number * 4 + STATUS_CODE[m.status]:
                        m.status = STATUS_STR[status0[t, j]]
                        m.incarnation_number = int(inc0[t, j])
            mem.compute_checksum()
            self.ready[i] = True
            # record every known non-self member as a change
            # (set handler -> dissemination.recordChange, engine `learned`)
            own_inc = self._self_inc(i)
            for m in mem.members:
                j = self.addr_idx[m.address]
                if j == i:
                    continue
                node.changes[j] = _Change(
                    status=STATUS_CODE[m.status],
                    inc=m.incarnation_number,
                    source=i,
                    source_inc=own_inc,
                    pb=0,
                )

        # contacted targets makeAlive(joiner) (server/protocol/join.js:126)
        ja: Dict[int, List[dict]] = {}
        for i in np.flatnonzero(joined):
            own_inc = self._self_inc(i)
            for k in range(p.join_size):
                if not jvalid[i, k]:
                    continue
                t = int(jorder[i, k])
                ja.setdefault(t, []).append(
                    {
                        "address": self.addresses[i],
                        "status": Status.alive,
                        "incarnationNumber": own_inc,
                        "source": self.addresses[i],
                        "sourceIncarnationNumber": own_inc,
                    }
                )
        for t, ups in ja.items():
            self._apply(t, ups, tick_next)

        advertised = self.checksum.copy()
        # sender self-incarnation at ping-build time (rides in the ping
        # body) — the phase-5/6 origin filters compare against this, not
        # the post-receive value (engine: sent_self_inc)
        diag_inc_sent = np.array(
            [self._self_inc(i) for i in range(n)], np.int64
        )

        # ---- phase 2: target selection ----------------------------------
        known1, status1, inc1 = self._views()
        participating = self.proc_alive & self.ready & self.gossip_on
        pingable = (
            known1 & ((status1 == engine.ALIVE) | (status1 == engine.SUSPECT)) & ~eye
        )
        k_arange = np.arange(n)[None, :]
        pos = (self.iter_pos[:, None] + k_arange) % n
        cand = np.take_along_axis(self.perm, pos, axis=1)
        cand_pingable = np.take_along_axis(pingable, cand, axis=1)
        first_k = np.argmax(cand_pingable, axis=1).astype(np.int32)
        has_target = cand_pingable.any(axis=1)
        target = np.take_along_axis(cand, first_k[:, None], axis=1)[:, 0]
        target = np.where(participating & has_target, target, -1)
        wrapped = (self.iter_pos + first_k) >= n
        self.iter_pos = np.where(
            participating & has_target,
            (self.iter_pos + first_k + 1) % n,
            self.iter_pos,
        )
        resh = wrapped & participating
        if resh.any():  # engine skips the draw on wrap-free ticks too
            # affine re-indexing of a hashed base permutation — mirrors
            # engine._reshuffled bitwise (same f32 uniforms, same int math)
            base = np.argsort(
                _np_uniform(self.rng, (n,), salt=77), kind="stable"
            ).astype(np.int32)
            r = _np_uniform(self.rng, (n, 2), salt=7)
            cops, _ = engine._coprimes_of(n)
            k_cop = np.int32(len(cops))
            a = cops[
                np.clip((r[:, 0] * k_cop).astype(np.int32), 0, k_cop - 1)
            ]
            b = (r[:, 1] * np.float32(n)).astype(np.int32) % n
            idx = (
                a[:, None] * np.arange(n, dtype=np.int32) + b[:, None]
            ) % n
            new_perm = base[idx]
            self.perm = np.where(resh[:, None], new_perm, self.perm)
        valid_send = target >= 0

        # ---- phase 3: sender piggyback bump (issueAsSender) -------------
        server_count = (
            known1 & ((status1 == engine.ALIVE) | (status1 == engine.SUSPECT))
        ).sum(axis=1)
        max_pb = np.array(
            [p.piggyback_factor * _digits(int(c)) for c in server_count], np.int32
        )
        sendable: List[Dict[int, _Change]] = [dict() for _ in range(n)]
        for i in np.flatnonzero(valid_send):
            node = self.nodes[i]
            for j in list(node.changes.keys()):
                ch = node.changes[j]
                ch.pb += 1
                if ch.pb > max_pb[i]:
                    del node.changes[j]
                else:
                    sendable[i][j] = dataclasses.replace(ch)

        # ---- phase 4: delivery ------------------------------------------
        loss = _np_uniform(self.rng, (n,), salt=13) < p.packet_loss
        tgt = np.clip(target, 0, n - 1)
        tgt_ok = np.where(valid_send, self.proc_alive[tgt], False)
        conn_t = np.where(valid_send, self.partition == self.partition[tgt], False)
        delivered = valid_send & tgt_ok & conn_t & ~loss

        # ---- phase 5: receivers apply (winner-combine per subject) ------
        inbox: Dict[int, Dict[int, tuple]] = {}  # recv -> subject -> (key, s, ch)
        for s in np.flatnonzero(delivered):
            r = int(target[s])
            box = inbox.setdefault(r, {})
            for j, ch in sendable[s].items():
                key = ch.inc * 4 + ch.status
                cur = box.get(j)
                if cur is None or key > cur[0] or (key == cur[0] and s < cur[1]):
                    box[j] = (key, int(s), ch)
        for r, box in inbox.items():
            ups = [
                {
                    "address": self.addresses[j],
                    "status": STATUS_STR[ch.status],
                    "incarnationNumber": ch.inc,
                    "source": self.addresses[ch.source] if ch.source >= 0 else None,
                    "sourceIncarnationNumber": ch.source_inc,
                }
                for j, (_, _, ch) in sorted(box.items())
            ]
            self._apply(r, ups, tick_next)

        # receiver-side piggyback bump: one issueAsReceiver per ping, with
        # the receiver-origin filter applied BEFORE the bump (dissemination
        # .js:147-160) — the originating sender's own ping doesn't bump
        diag_inc_post5 = np.array([self._self_inc(i) for i in range(n)], np.int64)
        nrecv = np.zeros(n, np.int64)
        for s in np.flatnonzero(delivered):
            nrecv[target[s]] += 1
        respondable: List[Dict[int, _Change]] = [dict() for _ in range(n)]
        for r in np.flatnonzero(nrecv > 0):
            node = self.nodes[r]
            for j in list(node.changes.keys()):
                ch = node.changes[j]
                origin_hit = (
                    ch.source >= 0
                    and delivered[ch.source]
                    and target[ch.source] == r
                    and ch.source_inc == diag_inc_sent[ch.source]
                )
                ch.pb += int(nrecv[r]) - int(origin_hit)
                if ch.pb > max_pb[r]:
                    del node.changes[j]
                else:
                    respondable[r][j] = dataclasses.replace(ch)

        mid_checksum = self._compute_checksums()

        # ---- phase 6: responses + full-sync -----------------------------
        # the engine applies every sender's response in ONE batched update;
        # payloads must therefore come from the phase-6-start snapshot, not
        # from state mutated by an earlier sender's application
        known5, status5, inc5 = self._views()
        full_syncs = 0
        for s in np.flatnonzero(delivered):
            t = int(target[s])
            # drop changes the pinging sender originated
            # (dissemination.js:91-98; engine resp_filter)
            resp = {
                j: ch
                for j, ch in respondable[t].items()
                if not (ch.source == s and ch.source_inc == diag_inc_sent[s])
            }
            if resp:
                ups = [
                    {
                        "address": self.addresses[j],
                        "status": STATUS_STR[ch.status],
                        "incarnationNumber": ch.inc,
                        "source": self.addresses[ch.source]
                        if ch.source >= 0
                        else None,
                        "sourceIncarnationNumber": ch.source_inc,
                    }
                    for j, ch in sorted(resp.items())
                ]
                self._apply(s, ups, tick_next)
            elif mid_checksum[t] != advertised[s]:
                # full sync (dissemination.js:101-114) — target's snapshot view
                full_syncs += 1
                ups = [
                    {
                        "address": self.addresses[j],
                        "status": STATUS_STR[status5[t, j]],
                        "incarnationNumber": int(inc5[t, j]),
                        "source": self.addresses[t],
                        "sourceIncarnationNumber": int(diag_inc_post5[t]),
                    }
                    for j in np.flatnonzero(known5[t])
                ]
                self._apply(s, ups, tick_next)

        # ---- phase 7: ping-req ------------------------------------------
        # carries dissemination both ways, mirroring engine phase 7: the
        # probing sender piggybacks issueAsSender on each body
        # (ping-req-sender.js:74-79), intermediaries apply and answer
        # issueAsReceiver (origin filter, bump, full-sync —
        # server/protocol/ping-req.js:46,62-66), the sender applies the
        # responses, THEN judges reachability (ping-req-sender.js:132-139,
        # 249-262).  Same envelope as the engine: the relay ping to the
        # target is reachability-only.
        K_pr = p.ping_req_size
        need_pr = valid_send & ~delivered
        pr_rand = _np_uniform(self.rng, (n, n), salt=29)
        pr_ok = pingable & (np.arange(n)[None, :] != target[:, None]) & need_pr[:, None]
        pr_score = np.where(pr_ok, pr_rand, np.float32(2.0))
        pr_sel = np.argsort(pr_score, axis=1, kind="stable")[:, :K_pr]
        pr_valid = np.take_along_axis(pr_score, pr_sel, axis=1) < 1.5
        m_alive = self.proc_alive[pr_sel]
        m_conn = self.partition[pr_sel] == self.partition[:, None]
        loss1 = _np_uniform(self.rng, (n, K_pr), salt=31) < p.packet_loss
        responder = pr_valid & m_alive & m_conn & ~loss1
        t_alive = np.where(need_pr, self.proc_alive[tgt], False)
        t_conn = self.partition[pr_sel] == self.partition[tgt][:, None]
        loss2 = _np_uniform(self.rng, (n, K_pr), salt=37) < p.packet_loss
        reached = responder & t_alive[:, None] & t_conn & ~loss2
        mark_suspect = need_pr & responder.any(axis=1) & ~reached.any(axis=1)

        # body sourceIncarnationNumber: read at build time, post-phase-6
        pr_self_inc = np.array(
            [self._self_inc(i) for i in range(n)], np.int64
        )

        # leg 1: issueAsSender per valid slot, sequentially (each slot
        # bumps every still-active change, reachable intermediary or not)
        pr_bodies: Dict[tuple, Dict[int, _Change]] = {}
        for i in np.flatnonzero(need_pr):
            node_i = self.nodes[i]
            for k in range(K_pr):
                if not pr_valid[i, k]:
                    continue
                body: Dict[int, _Change] = {}
                for j in list(node_i.changes.keys()):
                    ch = node_i.changes[j]
                    ch.pb += 1
                    if ch.pb > max_pb[i]:
                        del node_i.changes[j]
                    else:
                        body[j] = dataclasses.replace(ch)
                pr_bodies[(int(i), k)] = body

        # leg 2: intermediaries apply (winner-combine per subject; ties
        # keep the lowest (sender, slot) pair — engine flat-id order)
        inbox_pr: Dict[int, Dict[int, tuple]] = {}
        for i in np.flatnonzero(need_pr):
            for k in range(K_pr):
                if not responder[i, k]:
                    continue
                m = int(pr_sel[i, k])
                box = inbox_pr.setdefault(m, {})
                flat = int(i) * K_pr + k
                for j, ch in pr_bodies[(int(i), k)].items():
                    key = ch.inc * 4 + ch.status
                    cur = box.get(j)
                    if cur is None or key > cur[0] or (
                        key == cur[0] and flat < cur[1]
                    ):
                        box[j] = (key, flat, ch)
        for m, box in sorted(inbox_pr.items()):
            ups = [
                {
                    "address": self.addresses[j],
                    "status": STATUS_STR[ch.status],
                    "incarnationNumber": ch.inc,
                    "source": self.addresses[ch.source] if ch.source >= 0 else None,
                    "sourceIncarnationNumber": ch.source_inc,
                }
                for j, (_, _, ch) in sorted(box.items())
            ]
            self._apply(m, ups, tick_next)

        # full-sync decisions use MID-TICK checksums on both sides — the
        # engine's serialization choice (a fresh post-leg-2 recompute
        # would be a third encode per tick; see engine phase 7's note)

        # leg 3a: receiver bumps on the intermediary (issueAsReceiver per
        # arriving ping-req, origin filter before the bump; aggregated
        # like the ping path's phase 5.5)
        prrecv = np.zeros(n, np.int64)
        cnt_sm = np.zeros((n, n), np.int64)
        for i in np.flatnonzero(need_pr):
            for k in range(K_pr):
                if responder[i, k]:
                    m = int(pr_sel[i, k])
                    prrecv[m] += 1
                    cnt_sm[m, i] += 1
        pr_respondable: List[Dict[int, _Change]] = [dict() for _ in range(n)]
        for m in np.flatnonzero(prrecv > 0):
            node_m = self.nodes[m]
            for j in list(node_m.changes.keys()):
                ch = node_m.changes[j]
                hits = 0
                if ch.source >= 0 and ch.source_inc == pr_self_inc[ch.source]:
                    hits = int(cnt_sm[m, ch.source])
                ch.pb += int(prrecv[m]) - hits
                if ch.pb > max_pb[m]:
                    del node_m.changes[j]
                else:
                    pr_respondable[m][j] = dataclasses.replace(ch)

        # leg 3b: responses, winner-combined at the sender (max key; ties
        # keep the lowest slot).  Payloads come from the post-leg-2
        # snapshot, exactly like the engine builds every slot's content
        # before one batched apply.
        known7, status7, inc7 = self._views()
        diag_inc_7 = np.array(
            [self._self_inc(i) for i in range(n)], np.int64
        )
        pr_fs = 0
        for i in np.flatnonzero(need_pr):
            best: Dict[int, tuple] = {}
            for k in range(K_pr):
                if not responder[i, k]:
                    continue
                m = int(pr_sel[i, k])
                resp = {
                    j: ch
                    for j, ch in pr_respondable[m].items()
                    if not (
                        ch.source == i and ch.source_inc == pr_self_inc[i]
                    )
                }
                if resp:
                    content = [
                        (
                            j,
                            ch.inc * 4 + ch.status,
                            {
                                "address": self.addresses[j],
                                "status": STATUS_STR[ch.status],
                                "incarnationNumber": ch.inc,
                                "source": self.addresses[ch.source]
                                if ch.source >= 0
                                else None,
                                "sourceIncarnationNumber": ch.source_inc,
                            },
                        )
                        for j, ch in resp.items()
                    ]
                elif mid_checksum[m] != mid_checksum[i]:
                    pr_fs += 1
                    content = [
                        (
                            j,
                            int(inc7[m, j]) * 4 + int(status7[m, j]),
                            {
                                "address": self.addresses[j],
                                "status": STATUS_STR[status7[m, j]],
                                "incarnationNumber": int(inc7[m, j]),
                                "source": self.addresses[m],
                                "sourceIncarnationNumber": int(diag_inc_7[m]),
                            },
                        )
                        for j in np.flatnonzero(known7[m])
                    ]
                else:
                    content = []
                for j, key, upd in content:
                    cur = best.get(j)
                    if cur is None or key > cur[0]:
                        best[j] = (key, upd)
            if best:
                self._apply(
                    i,
                    [upd for j, (_, upd) in sorted(best.items())],
                    tick_next,
                )

        # suspect verdict on post-response state (reference: makeSuspect
        # after every ping-req callback applied its changes)
        for i in np.flatnonzero(mark_suspect):
            t = int(tgt[i])
            m = self.nodes[i].membership.find_member_by_address(self.addresses[t])
            cur_inc = m.incarnation_number if m is not None else 0
            self._apply(
                i,
                [
                    {
                        "address": self.addresses[t],
                        "status": Status.suspect,
                        "incarnationNumber": cur_inc,
                        "source": self.addresses[i],
                        "sourceIncarnationNumber": int(self._self_inc(i)),
                    }
                ],
                tick_next,
            )
        full_syncs += pr_fs

        # ---- phase 8: suspicion expiry ----------------------------------
        for i in range(n):
            if not participating[i]:
                continue
            node = self.nodes[i]
            due = [j for j, dl in node.susp.items() if 0 <= dl <= tick_next]
            if not due:
                continue
            ups = []
            for j in sorted(due):
                node.susp.pop(j, None)
                m = node.membership.find_member_by_address(self.addresses[j])
                cur_inc = m.incarnation_number if m is not None else 0
                ups.append(
                    {
                        "address": self.addresses[j],
                        "status": Status.faulty,
                        "incarnationNumber": cur_inc,
                        "source": self.addresses[i],
                        "sourceIncarnationNumber": int(diag_inc_post5[i]),
                    }
                )
            self._apply(i, ups, tick_next)

        # ---- phase 9: checksums -----------------------------------------
        self.checksum = self._compute_checksums()
        part = self.proc_alive & self.ready
        live_cs = self.checksum[part]
        distinct = len(set(live_cs.tolist())) if live_cs.size else 0

        self.rng = _np_fold(self.rng, 0x5EED)
        return OracleTickResult(
            checksums=self.checksum.copy(),
            converged=distinct <= 1,
            distinct_checksums=distinct,
            full_syncs=full_syncs,
            pings_sent=int(valid_send.sum()),
        )
