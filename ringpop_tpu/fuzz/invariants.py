"""Machine-checked SWIM protocol invariants over fuzzed runs.

The checker consumes what a :class:`ringpop_tpu.fuzz.executor.FuzzRun`
carries — decoded flight-recorder streams, final state snapshots,
per-tick metrics, and the (known) fault schedule — and asserts the
protocol properties SURVEY §7's hard-parts list marks as the places
reproductions rot:

full-fidelity engine (event-stream grain):

==========================  ================================================
invariant                   property
==========================  ================================================
incarnation-monotonic       per (observer, subject) view, the incarnation
                            stamp never decreases within one observer
                            lifetime (hard part 3: event-time -> tick-time
                            incarnation discipline)
view-continuity             consecutive view-change events chain exactly:
                            event k's old_status == event k-1's new_status
                            (the recorder and the trajectory cannot desync)
alive-after-faulty-refute   a FAULTY -> ALIVE view flip requires the
                            subject to have REFUTED at exactly that
                            incarnation (member.js:76-81), or to have been
                            revived/rejoined by the fault plane
self-view-alive             a node never holds ITSELF suspect or faulty —
                            it refutes instead (member.js:76-81); a
                            suppressed refute path surfaces here
suspicion-lower-bound       an expiry-marked faulty fires no earlier than
                            suspicion_ticks after the observer's latest
                            suspect arming (suspicion.js:111-113)
suspicion-upper-bound       ... and exactly ON the deadline when the
                            observer was undisturbed in between
piggyback-ceiling           active dissemination entries never exceed
                            15*ceil(log10(n+1)) piggybacks
                            (dissemination.js:41; hard part 5)
refute-reachability         every refute is preceded by a defamation whose
                            accuser could REACH the subject through the
                            partition groups in effect since (the
                            checkpoint.py defame_by gate, generalized to
                            temporal reachability over the schedule)
metrics-reconcile           event-stream sums == TickMetrics window totals
                            (obs.events.reconcile, every fuzzed run)
event-overflow              the stream is drop-free (a truncated stream
                            can hide any of the above)
event-stream-valid          obs.events.validate_event_stream problems
==========================  ================================================

scalable engine (state + metrics grain):

==========================  ================================================
scalable-checksum-exact     the incrementally-maintained in-tick checksums
                            equal a full O(N*U) recompute, bitwise
scalable-proc-alive         final process-liveness equals the fault
                            schedule folded exactly
suspicion-lower-bound       a faulty batch at tick t requires a suspect
                            batch at some tick <= t - suspicion_ticks
refutes-need-defamation     a refute batch requires an earlier
                            suspect/faulty batch
pings-conserved             pings_delivered <= pings_sent per tick
==========================  ================================================

host-level (checked by :mod:`ringpop_tpu.fuzz.crash`, not here — the
property spans two driver processes, not one run's event stream):

==========================  ================================================
resume-bitwise              a driver preempted at an arbitrary tick
                            (including mid-checkpoint-write, leaving a
                            torn/corrupt newest checkpoint) and restarted
                            through recovery (newest VALID checkpoint, or
                            clean restart) reaches a final state bitwise
                            equal to the uninterrupted run's
==========================  ================================================

Every checker is pure host-side numpy over already-fetched arrays; a
violation names its invariant (the shrinker minimizes against those
names, and the mutation-gate tests assert them).
"""

from __future__ import annotations

from typing import Any, Dict, List, NamedTuple, Optional, Sequence

import numpy as np

from ringpop_tpu.fuzz.scenarios import FULL, SCALABLE
from ringpop_tpu.obs import events as ev

ALIVE, SUSPECT, FAULTY, LEAVE = 0, 1, 2, 3


class Violation(NamedTuple):
    invariant: str
    instance: int  # batch index within the run
    message: str


def _v(name: str, instance: int, msg: str) -> Violation:
    return Violation(name, instance, msg)


# -- schedule-derived traces -------------------------------------------------


def _liveness_trace(schedule, ticks: int, n: int):
    """(alive[T+1, N], reset[T, N], disturbed[T, N]) from the fault plane.

    ``alive[t]`` is process liveness entering schedule row t; ``reset``
    marks revive-of-dead rows (full state reset); ``disturbed`` marks any
    operator touch of the node at that row (kill/revive/resume/leave/
    join) — the suspicion upper bound is only exact for undisturbed
    observers."""
    alive = np.ones((ticks + 1, n), bool)
    reset = np.zeros((ticks, n), bool)
    disturbed = np.zeros((ticks, n), bool)
    kill = np.asarray(schedule.kill)
    revive = np.asarray(schedule.revive)
    resume = getattr(schedule, "resume", None)
    leave = getattr(schedule, "leave", None)
    join = getattr(schedule, "join", None)
    for t in range(ticks):
        cur = alive[t]
        reset[t] = revive[t] & ~cur
        nxt = (cur & ~kill[t]) | revive[t]
        if resume is not None:
            nxt = nxt | np.asarray(resume)[t]
        alive[t + 1] = nxt
        disturbed[t] = kill[t] | revive[t]
        if resume is not None:
            disturbed[t] |= np.asarray(resume)[t]
        if leave is not None:
            disturbed[t] |= np.asarray(leave)[t]
        if join is not None:
            disturbed[t] |= np.asarray(join)[t]
    return alive, reset, disturbed


def _group_trace(schedule, ticks: int, n: int) -> np.ndarray:
    """[T, N] partition-group assignment in effect at each schedule row
    (the engines apply the row's regroup before any exchange)."""
    out = np.zeros((ticks, n), np.int32)
    part = getattr(schedule, "partition", None)
    cur = np.zeros(n, np.int32)
    for t in range(ticks):
        if part is not None:
            row = np.asarray(part)[t]
            cur = np.where(row >= 0, row, cur).astype(np.int32)
        out[t] = cur
    return out


def _reachable(groups: np.ndarray, src: int, t0: int, dst: int, t1: int) -> bool:
    """Could information flow from ``src`` at schedule row t0 to ``dst``
    by row t1, hopping only between same-group nodes each row?

    Deliberately an OVER-approximation of the engines' channels (any
    same-group pair MAY exchange in a row; liveness is ignored): the
    checker must never flag a flow the engine could have made, only
    flows no partition-respecting path could carry."""
    n = groups.shape[1]
    frontier = np.zeros(n, bool)
    frontier[src] = True
    for t in range(max(t0, 0), min(t1 + 1, groups.shape[0])):
        g = groups[t]
        touched = np.unique(g[frontier])
        frontier = frontier | np.isin(g, touched)
        if frontier[dst]:
            return True
    return bool(frontier[dst])


# -- full-fidelity checker ---------------------------------------------------


def _event_arrays(events: Any) -> Dict[str, np.ndarray]:
    arrs = ev._as_arrays(events)
    return {k: np.asarray(v) for k, v in arrs.items()}


def check_full_instance(
    events: Any,
    final_state: Any,  # this instance's SimState slice (numpy pytree)
    metrics: Any,  # TickMetrics of [T] arrays for this instance
    schedule: Any,  # EventSchedule driving the instance
    params: Any,  # the params the run executed under
    instance: int = 0,
    contract: Optional[Any] = None,  # params the PROTOCOL demands
    drops: int = 0,
) -> List[Violation]:
    """All full-engine invariants for one scenario instance."""
    contract = contract if contract is not None else params
    out: List[Violation] = []
    n, ticks = schedule.n, schedule.ticks
    alive_tr, reset_tr, disturbed_tr = _liveness_trace(schedule, ticks, n)
    groups = _group_trace(schedule, ticks, n)

    if drops:
        out.append(
            _v(
                "event-overflow",
                instance,
                "flight recorder dropped %d events — the stream cannot "
                "witness the remaining invariants" % drops,
            )
        )
    if isinstance(events, (list, tuple)) and events and isinstance(
        events[0], dict
    ):
        problems = ev.validate_event_stream(events)
        for p in problems[:4]:
            out.append(_v("event-stream-valid", instance, p))

    a = _event_arrays(events)
    tick_a = a["tick"]
    kind = a["kind"]
    obs_a = a["observer"]
    subj = a["subject"]
    old_st = a["old_status"]
    new_st = a["new_status"]
    inc = a["inc"]

    # event tick T corresponds to schedule row T-1 (tick_index starts 0,
    # the first scanned row records tick 1)
    def row_of(t: int) -> int:
        return int(t) - 1

    # refute events by subject: (tick, inc) pairs, plus fault-plane
    # rebirth rows — the two legitimate sources of fresh ALIVE stamps.
    # A rebirth stamp is minted by the revive reset / rejoin write
    # itself (row r mints stamp r+2), NOT by a later successful join —
    # a revived node whose join finds no reachable candidate still
    # carries its fresh self-incarnation, and other nodes' join merges
    # may pick the unready process up (handleJoin never checks
    # readiness), so the stamp can disseminate without any EV_JOIN.
    ref_sel = kind == ev.EV_REFUTE
    refutes_by = {}
    for i in np.nonzero(ref_sel)[0]:
        refutes_by.setdefault(int(obs_a[i]), []).append(
            (int(tick_a[i]), int(inc[i]))
        )
    rebirth_rows = {}  # subject -> rows minting a fresh ALIVE stamp
    join_plane = np.asarray(schedule.join)
    for s in range(n):
        rows = set(np.nonzero(reset_tr[:, s])[0].tolist())
        rows |= set(np.nonzero(join_plane[:, s])[0].tolist())
        rebirth_rows[s] = rows

    # -- per-(observer, subject) view-change sequences -------------------
    st_sel = np.nonzero(kind == ev.EV_STATUS)[0]
    order = st_sel[
        np.lexsort(
            (st_sel, tick_a[st_sel], subj[st_sel], obs_a[st_sel])
        )
    ]
    prev_of: Dict[tuple, int] = {}
    for i in order:
        o, s, t = int(obs_a[i]), int(subj[i]), int(tick_a[i])
        if o == s and int(new_st[i]) in (SUSPECT, FAULTY):
            out.append(
                _v(
                    "self-view-alive",
                    instance,
                    "node %d holds itself %s at tick %d instead of "
                    "refuting"
                    % (o, "SUSPECT" if int(new_st[i]) == SUSPECT else "FAULTY", t),
                )
            )
        key = (o, s)
        j = prev_of.get(key)
        prev_of[key] = i
        fresh = int(old_st[i]) == -1
        if j is None or fresh:
            continue
        # observer reset (revive-of-dead) between the two events starts a
        # new lifetime even when the relearn reuses the stale row view
        # (a same-tick revive+rejoin reads the pre-crash view as old)
        t_prev = int(tick_a[j])
        seg = reset_tr[max(row_of(t_prev), 0): row_of(t) + 1, o].any()
        if seg:
            continue
        if int(inc[i]) < int(inc[j]):
            out.append(
                _v(
                    "incarnation-monotonic",
                    instance,
                    "observer %d's view of %d regressed inc %d -> %d at "
                    "tick %d (prev event tick %d)"
                    % (o, s, int(inc[j]), int(inc[i]), t, t_prev),
                )
            )
        if int(old_st[i]) != int(new_st[j]):
            out.append(
                _v(
                    "view-continuity",
                    instance,
                    "observer %d's view of %d jumped %d -> old %d at tick "
                    "%d without an event for the change"
                    % (o, s, int(new_st[j]), int(old_st[i]), t),
                )
            )
        # FAULTY -> ALIVE needs a refute at exactly the new incarnation,
        # or a fault-plane rebirth of the subject no later than the flip
        if int(new_st[j]) == FAULTY and int(new_st[i]) == ALIVE:
            a_inc = int(inc[i])
            ok = any(
                rt <= t and rinc == a_inc
                for rt, rinc in refutes_by.get(s, ())
            )
            if not ok:
                # rebirth row r mints stamp r+2 at event tick r+1
                ok = any(
                    r + 1 <= t and a_inc == r + 2
                    for r in rebirth_rows.get(s, ())
                )
            if not ok:
                out.append(
                    _v(
                        "alive-after-faulty-refute",
                        instance,
                        "observer %d flipped %d FAULTY -> ALIVE@inc %d at "
                        "tick %d with no refute/rebirth minting that "
                        "incarnation" % (o, s, a_inc, t),
                    )
                )

    # -- suspicion timeout bounds ---------------------------------------
    # arms: status events ending SUSPECT; fires: EV_FAULTY (expiry-applied)
    arm_ticks: Dict[tuple, List[int]] = {}
    for i in order:
        if int(new_st[i]) == SUSPECT:
            arm_ticks.setdefault(
                (int(obs_a[i]), int(subj[i])), []
            ).append(int(tick_a[i]))
    sus_ticks = int(contract.suspicion_ticks)
    for i in np.nonzero(kind == ev.EV_FAULTY)[0]:
        o, s, t = int(obs_a[i]), int(subj[i]), int(tick_a[i])
        arms = [ta for ta in arm_ticks.get((o, s), ()) if ta < t]
        if not arms:
            out.append(
                _v(
                    "suspicion-lower-bound",
                    instance,
                    "observer %d expired %d faulty at tick %d without any "
                    "prior suspect arming" % (o, s, t),
                )
            )
            continue
        t_arm = max(arms)
        if t - t_arm < sus_ticks:
            out.append(
                _v(
                    "suspicion-lower-bound",
                    instance,
                    "observer %d expired %d faulty %d ticks after arming "
                    "(tick %d -> %d), contract requires >= %d"
                    % (o, s, t - t_arm, t_arm, t, sus_ticks),
                )
            )
        else:
            win = disturbed_tr[
                max(row_of(t_arm) + 1, 0): row_of(t) + 1, o
            ]
            if not win.any() and t - t_arm != sus_ticks:
                out.append(
                    _v(
                        "suspicion-upper-bound",
                        instance,
                        "undisturbed observer %d expired %d at tick %d, "
                        "%d ticks after arming at %d (deadline is exactly "
                        "%d)" % (o, s, t, t - t_arm, t_arm, sus_ticks),
                    )
                )

    # -- piggyback ceiling (final-state snapshot) ------------------------
    ch_active = np.asarray(final_state.ch_active)
    ch_pb = np.asarray(final_state.ch_pb)
    digits = len(str(n))  # ceil(log10(n+1)) for n >= 1
    ceiling = int(contract.piggyback_factor) * digits
    over = ch_active & (ch_pb > ceiling)
    if over.any():
        o, s = np.argwhere(over)[0]
        out.append(
            _v(
                "piggyback-ceiling",
                instance,
                "active change (%d, %d) carries piggyback count %d > "
                "ceiling %d" % (int(o), int(s), int(ch_pb[o, s]), ceiling),
            )
        )

    # -- partition-reachability of refuted defamations -------------------
    # each refute needs SOME defamation of the subject whose accuser
    # could reach the subject through the groups in effect since
    defam: Dict[int, List[tuple]] = {}
    for i in order:
        if int(new_st[i]) in (SUSPECT, FAULTY) and int(obs_a[i]) != int(
            subj[i]
        ):
            defam.setdefault(int(subj[i]), []).append(
                (int(tick_a[i]), int(obs_a[i]))
            )
    for i in np.nonzero(ref_sel)[0]:
        s, t = int(obs_a[i]), int(tick_a[i])
        cands = [d for d in defam.get(s, ()) if d[0] <= t]
        if not cands:
            out.append(
                _v(
                    "refute-reachability",
                    instance,
                    "node %d refuted at tick %d with no prior defamation "
                    "event anywhere" % (s, t),
                )
            )
            continue
        if not any(
            _reachable(groups, o, row_of(t0), s, row_of(t))
            for t0, o in cands
        ):
            out.append(
                _v(
                    "refute-reachability",
                    instance,
                    "node %d refuted at tick %d but no defaming accuser "
                    "could reach it through the partition groups"
                    % (s, t),
                )
            )

    # -- metrics <-> event-stream reconciliation -------------------------
    rec = ev.reconcile(a, metrics)
    for field, row in rec.items():
        if not row["match"]:
            out.append(
                _v(
                    "metrics-reconcile",
                    instance,
                    "%s: events=%d metrics=%d"
                    % (field, row["events"], row["metrics"]),
                )
            )
    return out


# -- scalable checker --------------------------------------------------------


def check_scalable_instance(
    final_state: Any,  # numpy pytree slice of ScalableState
    metrics: Any,  # ScalableMetrics of [T] arrays
    schedule: Any,  # StormSchedule
    params: Any,
    instance: int = 0,
    contract: Optional[Any] = None,
    recomputed_checksum: Optional[np.ndarray] = None,
) -> List[Violation]:
    contract = contract if contract is not None else params
    out: List[Violation] = []
    n, ticks = schedule.n, schedule.ticks

    # incremental in-tick checksums == full O(N*U) recompute, bitwise
    if recomputed_checksum is not None and bool(params.checksum_in_tick):
        got = np.asarray(final_state.checksum)
        want = np.asarray(recomputed_checksum)
        if not np.array_equal(got, want):
            bad = int(np.nonzero(got != want)[0][0])
            out.append(
                _v(
                    "scalable-checksum-exact",
                    instance,
                    "incremental checksum diverged from full recompute at "
                    "node %d: 0x%08x != 0x%08x"
                    % (bad, int(got[bad]), int(want[bad])),
                )
            )

    # final process-liveness is the schedule folded exactly
    alive_tr, _, _ = _liveness_trace(schedule, ticks, n)
    got_alive = np.asarray(final_state.proc_alive)
    if not np.array_equal(got_alive, alive_tr[ticks]):
        bad = int(np.nonzero(got_alive != alive_tr[ticks])[0][0])
        out.append(
            _v(
                "scalable-proc-alive",
                instance,
                "node %d liveness %r but the fault schedule folds to %r"
                % (bad, bool(got_alive[bad]), bool(alive_tr[ticks][bad])),
            )
        )

    sus = np.asarray(metrics.suspects_published)
    fau = np.asarray(metrics.faulties_published)
    ref = np.asarray(metrics.refutes_published)
    sus_ticks = int(contract.suspicion_ticks)
    for t in np.nonzero(fau > 0)[0]:
        # row t runs engine tick t+1; a faulty batch needs a suspect
        # batch whose clock had >= suspicion_ticks to run
        if t < sus_ticks or not (sus[: t - sus_ticks + 1] > 0).any():
            out.append(
                _v(
                    "suspicion-lower-bound",
                    instance,
                    "faulty batch at row %d without a suspect batch >= %d "
                    "ticks earlier" % (int(t), sus_ticks),
                )
            )
    # refutes answer defamations: revive/rejoin rows publish in the same
    # alive batch but are counted separately (refutes_published counts
    # only the refuter mask)
    for t in np.nonzero(ref > 0)[0]:
        if not (sus[: t + 1] > 0).any() and not (fau[: t + 1] > 0).any():
            out.append(
                _v(
                    "refutes-need-defamation",
                    instance,
                    "refute batch at row %d before any suspect/faulty "
                    "batch" % int(t),
                )
            )
    sent = np.asarray(metrics.pings_sent)
    deliv = np.asarray(metrics.pings_delivered)
    if (deliv > sent).any():
        t = int(np.nonzero(deliv > sent)[0][0])
        out.append(
            _v(
                "pings-conserved",
                instance,
                "row %d delivered %d pings of %d sent"
                % (t, int(deliv[t]), int(sent[t])),
            )
        )
    return out


# -- run-level driver --------------------------------------------------------


def _instance_leaf(a, b):  # jaxgate: host — post-run numpy slicing
    return np.asarray(a)[b]


def _instance_slice(tree: Any, b: int) -> Any:
    import functools

    import jax

    return jax.tree.map(functools.partial(_instance_leaf, b=b), tree)


def _prefix_leaf(a, k):  # jaxgate: host — post-run numpy slicing
    return np.asarray(a)[:k]


def _instance_prefix(tree: Any, k: int) -> Any:
    """First ``k`` instances of every [B, ...] leaf (host numpy)."""
    import functools

    import jax

    return jax.tree.map(functools.partial(_prefix_leaf, k=k), tree)


def check_run(
    run: Any,  # executor.FuzzRun
    contract: Optional[Any] = None,
) -> Dict[int, List[Violation]]:
    """Check every instance of a batched run; returns {batch index:
    violations} for instances with at least one violation."""
    import jax

    out: Dict[int, List[Violation]] = {}
    b_count = len(run.schedules)
    recomputed = None
    if run.engine == SCALABLE and bool(run.params.checksum_in_tick):
        from ringpop_tpu.models.sim import engine_scalable as es

        recomputed = np.asarray(
            jax.vmap(lambda st: es.compute_checksums(st, run.params))(
                run.final_state
            )
        )
    # fetch the whole batch to host ONCE — per-instance slicing below is
    # then pure numpy views, not B separate device-to-host transfers of
    # the full [B, ...] state (O(B^2) bytes for a wide sweep)
    final_host = jax.device_get(run.final_state)
    metrics_host = jax.device_get(run.metrics)
    for b in range(b_count):
        fs = _instance_slice(final_host, b)
        ms = _instance_slice(metrics_host, b)
        if run.engine == FULL:
            vs = check_full_instance(
                run.events[b],
                fs,
                ms,
                run.schedules[b],
                run.params,
                instance=b,
                contract=contract,
                drops=run.drops[b] if run.drops else 0,
            )
        else:
            vs = check_scalable_instance(
                fs,
                ms,
                run.schedules[b],
                run.params,
                instance=b,
                contract=contract,
                recomputed_checksum=(
                    recomputed[b] if recomputed is not None else None
                ),
            )
        if vs:
            out[b] = vs
    return out


def violation_names(
    violations: Sequence[Violation],
) -> List[str]:
    return sorted({v.invariant for v in violations})
