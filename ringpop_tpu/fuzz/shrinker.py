"""Schedule shrinker: failing seed -> minimal reproducing fault schedule.

Strategy (all candidates run at the ORIGINAL [T, N] shapes so the whole
shrink reuses one compiled executor program — no per-candidate
recompiles):

1. **tick-tail bisect** — binary-search the shortest schedule prefix
   whose faults alone still violate (faults at later rows zeroed; the
   trailing quiet ticks stay in the program but carry nothing), then
2. **per-tick fault-set ddmin** — delta-debug the surviving sparse fault
   cells (remove chunks, keep the removal whenever the violation
   survives, halve the granularity when stuck) down to a 1-minimal set.

Candidate evaluation is BATCHED: each ddmin round packs its candidate
fault subsets into one executor pass (the executor is vmapped over
instances anyway), so a shrink costs a handful of device dispatches.

The result serializes as a regression fixture (JSON): engine, shapes,
the minimal sparse fault list, the violated invariant names, and the
init seed — ``replay_fixture`` rebuilds the schedule, re-runs it, and
re-checks the invariants, so a shrunk storm found against one build
becomes a permanent cheap test against every later build
(tests/fuzz/test_fixtures.py replays every committed fixture).
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, NamedTuple, Optional, Sequence, Tuple

import numpy as np

from ringpop_tpu.fuzz import invariants, scenarios
from ringpop_tpu.fuzz.scenarios import FULL, ScenarioConfig

Fault = Tuple[str, int, int, int]

FIXTURE_FORMAT = 1


class ShrinkResult(NamedTuple):
    config: ScenarioConfig
    seed: int  # init-state seed the instance ran under
    packet_loss: float
    faults: Tuple[Fault, ...]  # the minimal reproducing fault set
    violations: Tuple[invariants.Violation, ...]  # on the minimal schedule
    evaluations: int  # schedules executed during the shrink
    original_faults: int

    @property
    def invariant_names(self) -> List[str]:
        return invariants.violation_names(self.violations)


def _check_batch(
    executor: Any,
    fault_sets: Sequence[Sequence[Fault]],
    seed: int,
    contract: Optional[Any],
    target: Optional[set],
) -> List[Tuple[bool, List[invariants.Violation]]]:
    """Run a batch of candidate fault sets; per candidate, does the run
    still violate (restricted to ``target`` invariant names if given)?"""
    cfg = executor.config
    scheds = [
        scenarios.schedule_from_faults(
            cfg.engine, cfg.n, cfg.ticks, list(fs), config=cfg
        )
        for fs in fault_sets
    ]
    # pad the candidate batch to a power of two (repeats of the first
    # candidate, results discarded): every distinct batch size B is a
    # fresh vmapped-scan compile, so bounding the B menu to powers of two
    # keeps a whole shrink to a handful of compiles
    want = 1
    while want < len(scheds):
        want *= 2
    padded = scheds + [scheds[0]] * (want - len(scheds))
    run = executor.run_schedules(padded, seeds=[seed] * len(padded))
    # trim the padding duplicates BEFORE checking: the invariant pass is
    # the host-side cost of a shrink round, and the padded instances'
    # results are discarded anyway
    k = len(fault_sets)
    run = run._replace(
        seeds=run.seeds[:k],
        schedules=run.schedules[:k],
        final_state=invariants._instance_prefix(run.final_state, k),
        metrics=invariants._instance_prefix(run.metrics, k),
        events=None if run.events is None else run.events[:k],
        drops=None if run.drops is None else run.drops[:k],
    )
    by_instance = invariants.check_run(run, contract=contract)
    out = []
    for b in range(len(fault_sets)):
        vs = by_instance.get(b, [])
        if target is not None:
            vs = [v for v in vs if v.invariant in target]
        out.append((bool(vs), vs))
    return out


def shrink(
    executor: Any,  # a fuzz executor (its batch shape is reused as-is)
    faults: Sequence[Fault],
    seed: int,
    contract: Optional[Any] = None,
    target: Optional[Sequence[str]] = None,
    max_rounds: int = 24,
) -> ShrinkResult:
    """Minimize ``faults`` while the run keeps violating.

    ``target`` restricts "still failing" to the named invariants (so the
    shrink cannot wander onto an unrelated violation); default: the
    invariants the full fault set violates."""
    faults = sorted(faults)
    n_original = len(set(faults))
    tgt = set(target) if target is not None else None
    evaluations = 0

    def failing(cands: Sequence[Sequence[Fault]]):
        nonlocal evaluations
        evaluations += len(cands)
        return _check_batch(executor, cands, seed, contract, tgt)

    (fails0, vs0), = failing([faults])
    if not fails0:
        raise ValueError(
            "schedule does not violate the target invariants — nothing "
            "to shrink"
        )
    if tgt is None:
        tgt = set(invariants.violation_names(vs0))

    # -- stage 1: tick-tail bisect --------------------------------------
    def prefix(fs: Sequence[Fault], rows: int) -> List[Fault]:
        return [f for f in fs if f[1] < rows]

    lo, hi = 1, executor.config.ticks  # smallest prefix that still fails
    while lo < hi:
        mid = (lo + hi) // 2
        (bad, _), = failing([prefix(faults, mid)])
        if bad:
            hi = mid
        else:
            lo = mid + 1
    faults = prefix(faults, lo)

    # -- stage 2: ddmin over the fault cells ----------------------------
    chunks = 2
    rounds = 0
    while len(faults) > 1 and rounds < max_rounds:
        rounds += 1
        size = max(1, len(faults) // chunks)
        complements = []
        spans = []
        for start in range(0, len(faults), size):
            keep = faults[:start] + faults[start + size:]
            if keep:
                complements.append(keep)
                spans.append((start, start + size))
        if not complements:
            break
        results = failing(complements)
        for (bad, _), keep in zip(results, complements):
            if bad:
                faults = keep
                chunks = max(chunks - 1, 2)
                break
        else:
            if size == 1:
                break
            chunks = min(len(faults), chunks * 2)

    (bad, vs), = failing([faults])
    assert bad, "shrink invariant: the minimal schedule must still fail"
    return ShrinkResult(
        config=executor.config,
        seed=int(seed),
        packet_loss=float(getattr(executor.params, "packet_loss", 0.0)),
        faults=tuple(faults),
        violations=tuple(vs),
        evaluations=evaluations,
        original_faults=n_original,
    )


def shrink_seed(
    executor: Any,
    seed: int,
    contract: Optional[Any] = None,
    target: Optional[Sequence[str]] = None,
) -> ShrinkResult:
    """Shrink the schedule that ``generate(seed)`` produces."""
    sched = scenarios.generate(seed, executor.config)
    faults = scenarios.sparse_faults(sched, executor.config.engine)
    return shrink(executor, faults, seed, contract=contract, target=target)


# -- fixture serialization ---------------------------------------------------


def fixture_dict(result: ShrinkResult, note: str = "") -> Dict[str, Any]:
    cfg = result.config
    return {
        "format": FIXTURE_FORMAT,
        "engine": cfg.engine,
        "n": cfg.n,
        "ticks": cfg.ticks,
        "seed": result.seed,
        "packet_loss": result.packet_loss,
        "use_leave": cfg.use_leave,
        "use_resume": cfg.use_resume,
        "faults": [list(f) for f in result.faults],
        "invariants": result.invariant_names,
        "note": note,
    }


def save_fixture(result: ShrinkResult, path: str, note: str = "") -> None:
    with open(path, "w") as f:
        json.dump(fixture_dict(result, note), f, indent=2, sort_keys=True)
        f.write("\n")


def load_fixture(path: str) -> Dict[str, Any]:
    with open(path) as f:
        doc = json.load(f)
    if doc.get("format") != FIXTURE_FORMAT:
        raise ValueError(
            "%s: fixture format %r, this build reads %d"
            % (path, doc.get("format"), FIXTURE_FORMAT)
        )
    return doc


def replay_fixture(
    path_or_doc: Any,
    contract: Optional[Any] = None,
    shared_cache: bool = True,
) -> List[invariants.Violation]:
    """Rebuild a fixture's minimal schedule, run it on the CURRENT
    engines, and return the violations (empty == the bug stayed fixed)."""
    from ringpop_tpu.fuzz import executor as ex

    doc = (
        load_fixture(path_or_doc)
        if isinstance(path_or_doc, str)
        else path_or_doc
    )
    cfg = ScenarioConfig(
        engine=doc["engine"],
        n=int(doc["n"]),
        ticks=int(doc["ticks"]),
        use_leave=bool(doc.get("use_leave", True)),
        use_resume=bool(doc.get("use_resume", True)),
    )
    executor = ex.executor_for(
        cfg,
        packet_loss=float(doc.get("packet_loss", 0.0)),
        shared_cache=shared_cache,
    )
    sched = scenarios.schedule_from_faults(
        cfg.engine,
        cfg.n,
        cfg.ticks,
        [tuple(f) for f in doc["faults"]],
        config=cfg,
    )
    run = executor.run_schedules([sched], seeds=[int(doc.get("seed", 0))])
    return invariants.check_run(run, contract=contract).get(0, [])
