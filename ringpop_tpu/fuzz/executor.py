"""Batched scenario executor: B storms per device pass, one compiled scan.

One scenario instance is tiny (n = 8-64): a single run leaves the device
almost idle, and a fuzz campaign wants thousands of them.  The executors
here vmap B independent instances — per-instance initial state AND
per-instance fault schedule (``in_axes=(0, 0)``, unlike
``models/sim/batched.py`` whose B clusters share one schedule) — through
one ``lax.scan`` over the [T, B, ...] input planes, so a whole batch of
storms costs one device dispatch.

Full-fidelity instances run with the flight recorder ON: the per-instance
event buffers come back [B, cap, 8] and are decoded into per-instance
streams for the invariant layer (ringpop_tpu/fuzz/invariants.py).  The
scalable engine has no event plane; its invariants check final state +
per-tick metrics.

``gate_phases`` is forced off exactly as in ``BatchedSimClusters``: under
vmap a cond with a batched (state-derived) predicate lowers to a
run-both select anyway, and the two settings are bitwise-identical in
trajectory.

Executables are shared per (params, universe, B, T) via ``lru_cache`` —
mutation-gate tests that monkeypatch engine internals MUST pass
``shared_cache=False`` so their broken traces never enter the shared
cache (the persistent XLA cache is safe either way: a mutated trace has
a different fingerprint).
"""

from __future__ import annotations

import functools
from typing import Any, List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ringpop_tpu.fuzz import scenarios
from ringpop_tpu.fuzz.scenarios import FULL, SCALABLE, ScenarioConfig
from ringpop_tpu.models.sim import engine
from ringpop_tpu.models.sim import engine_scalable as es
from ringpop_tpu.models.sim.cluster import default_addresses
from ringpop_tpu.ops import checksum_encode as ce


def event_capacity_for(n: int, ticks: int) -> int:
    """Per-instance event-buffer bound: the EXACT per-tick emission
    ceiling (flight.max_events_per_tick — the sum of every emission
    mask's lanes) times the window, rounded up to a power of two.
    Sized so a fuzzed storm never truncates — the invariant layer
    treats drops as a violation (an honest-but-truncated stream can
    hide protocol bugs)."""
    from ringpop_tpu.models.sim import flight

    need = (ticks + 1) * flight.max_events_per_tick(n)
    cap = 1024
    while cap < need:
        cap *= 2
    return cap


def default_full_params(
    n: int, ticks: int, packet_loss: float = 0.0
) -> engine.SimParams:
    """Fuzz-campaign engine config: flight recorder on (the whole point),
    "fast" checksum mode (the FarmHash string pipeline is the parity
    suite's job — fuzz wants cheap compiles and big batches), short
    suspicion so suspect->faulty->refute cycles fit small windows."""
    params = engine.SimParams(
        n=n,
        checksum_mode="fast",
        hash_impl="scan",
        suspicion_ticks=6,
        packet_loss=packet_loss,
        gate_phases=False,
        flight_recorder=True,
        event_capacity=event_capacity_for(n, ticks),
    )
    # resolve the trace-time "auto" knobs exactly as SimCluster would, so
    # a fuzz instance and a single-cluster replay of it share one params
    # value (and therefore one executable-cache key family)
    return engine.resolve_auto_parity(params, jax.default_backend())


def default_scalable_params(
    n: int, packet_loss: float = 0.0, enable_leave: bool = True
) -> es.ScalableParams:
    digits = len(str(n))
    spt = es.SLOTS_PER_TICK + (1 if enable_leave else 0)
    need = spt * (15 * digits + 8 + 2)
    u = 128
    while u < need:
        u *= 2
    return es.ScalableParams(
        n=n,
        u=u,
        suspicion_ticks=6,
        packet_loss=packet_loss,
        enable_leave=enable_leave,
        gate_phases=False,
        perm_impl="sortless",
        fused_exchange="off",
    )


# -- the traced entry points (jaxgate: registered in analysis/) -------------


def scenario_scan_full(states, inputs, params, universe):
    """[B]-stacked states + [T, B, N] input planes -> (final [B] states,
    [T, B] metrics): vmapped full-fidelity tick under one scan."""

    def vtick(st, inp):
        return jax.vmap(
            lambda s, i: engine.tick(s, i, params, universe)
        )(st, inp)

    return jax.lax.scan(vtick, states, inputs)


def scenario_scan_scalable(states, inputs, params):
    """The scalable twin: [B] states + [T, B, N] churn planes."""

    def vtick(st, inp):
        return jax.vmap(lambda s, i: es.tick(s, i, params))(st, inp)

    return jax.lax.scan(vtick, states, inputs)


@functools.lru_cache(maxsize=None)
def _full_scan_fn(params: engine.SimParams, universe: ce.Universe):
    return jax.jit(
        functools.partial(
            scenario_scan_full, params=params, universe=universe
        )
    )


@functools.lru_cache(maxsize=None)
def _scalable_scan_fn(params: es.ScalableParams):
    return jax.jit(functools.partial(scenario_scan_scalable, params=params))


def clear_executable_cache() -> None:
    _full_scan_fn.cache_clear()
    _scalable_scan_fn.cache_clear()


class FuzzRun(NamedTuple):
    """One batched pass: everything the invariant layer consumes."""

    engine: str
    params: Any  # SimParams | ScalableParams
    config: ScenarioConfig
    seeds: Tuple[int, ...]  # per-instance init/schedule seeds
    schedules: Tuple[Any, ...]  # per-instance schedule objects
    final_state: Any  # [B, ...]-stacked engine state
    metrics: Any  # [B, T]-stacked per-tick metrics
    events: Optional[Tuple[Any, ...]]  # per-instance decoded streams (full)
    drops: Optional[Tuple[int, ...]]  # per-instance overflow counts


def _to_instance_major(a):  # jaxgate: host — post-run numpy transpose
    return np.moveaxis(np.asarray(a), 0, 1)


# obs-only planes per engine state class — the ISSUE-15 single-source
# registries (the noninterference analysis prong proves these fields
# cannot feed the trajectory; the executor drains exactly these)
_OBS_FIELDS = {
    "SimState": engine.SIM_OBS_ONLY_FIELDS,
    "ScalableState": es.SCALABLE_OBS_ONLY_FIELDS,
}


def split_obs(state):
    """Partition an engine state into (trajectory view, obs planes).

    The obs-plane names come from the single-source field registries
    next to the state classes, so a renamed/added telemetry field breaks
    HERE (and in the registry gate) instead of silently vanishing from
    the drained streams.  The trajectory view has the obs planes set to
    None — the shape invariant checks compare."""
    obs_names = _OBS_FIELDS.get(type(state).__name__, frozenset())
    obs = {
        f: getattr(state, f)
        for f in obs_names
        if getattr(state, f) is not None
    }
    traj = state._replace(**{f: None for f in obs_names})
    return traj, obs


def _stack_states(states: Sequence[Any]) -> Any:
    return jax.tree.map(lambda *xs: jnp.stack(xs), *states)


def _stack_inputs(inputs_list: Sequence[Any]) -> Any:
    """Per-instance [T, N] input pytrees -> one [T, B, N] pytree.
    Optional planes must agree (all None or none None) — guaranteed by
    the campaign's single ``_blank_schedule`` shape."""
    return jax.tree.map(
        lambda *xs: jnp.stack(xs, axis=1), *inputs_list
    )


class _FuzzExecutorBase:
    """Shared run plumbing; subclasses bind the engine specifics."""

    engine_name: str = ""

    def __init__(self, config: ScenarioConfig, params, shared_cache: bool):
        self.config = config
        self.params = params
        self._shared_cache = shared_cache
        self._fn = None  # built lazily (or fetched from the shared cache)

    # subclass hooks ----------------------------------------------------
    def _init_state(self, seed: int):
        raise NotImplementedError

    def _build_fn(self):
        raise NotImplementedError

    def _decode(self, final_state):
        return None, None

    # driver ------------------------------------------------------------
    def _scan(self):
        if self._fn is None:
            self._fn = self._build_fn()
        return self._fn

    def run_seeds(self, seeds: Sequence[int]) -> FuzzRun:
        """Generate + run one schedule per seed (seed also seeds the
        engine's init rng, so an instance is fully determined by it)."""
        scheds = [scenarios.generate(s, self.config) for s in seeds]
        return self.run_schedules(scheds, seeds)

    def run_schedules(
        self, schedules: Sequence[Any], seeds: Optional[Sequence[int]] = None
    ) -> FuzzRun:
        if seeds is None:
            seeds = [0] * len(schedules)
        if len(seeds) != len(schedules):
            raise ValueError("len(seeds) != len(schedules)")
        states = _stack_states([self._init_state(s) for s in seeds])
        inputs = _stack_inputs([s.as_inputs() for s in schedules])
        final, metrics = self._scan()(states, inputs)
        # metrics arrive scan-major [T, B]; instance-major is what the
        # per-instance checks want
        metrics = jax.tree.map(_to_instance_major, metrics)
        events, drops = self._decode(final)
        return FuzzRun(
            engine=self.engine_name,
            params=self.params,
            config=self.config,
            seeds=tuple(int(s) for s in seeds),
            schedules=tuple(schedules),
            final_state=final,
            metrics=metrics,
            events=events,
            drops=drops,
        )


class FullFuzzExecutor(_FuzzExecutorBase):
    engine_name = FULL

    def __init__(
        self,
        config: ScenarioConfig,
        params: Optional[engine.SimParams] = None,
        packet_loss: float = 0.0,
        shared_cache: bool = True,
    ):
        if params is None:
            params = default_full_params(
                config.n, config.ticks, packet_loss
            )
        if not params.flight_recorder:
            raise ValueError(
                "the fuzz executor drains flight-recorder streams — "
                "construct params with flight_recorder=True"
            )
        self.universe = ce.Universe.from_addresses(
            default_addresses(config.n)
        )
        super().__init__(config, params, shared_cache)

    def _init_state(self, seed: int):
        return engine.init_state(
            self.params, seed=int(seed), universe=self.universe
        )

    def _build_fn(self):
        if self._shared_cache:
            return _full_scan_fn(self.params, self.universe)
        return jax.jit(
            functools.partial(
                scenario_scan_full,
                params=self.params,
                universe=self.universe,
            )
        )

    def _decode(self, final_state):
        from ringpop_tpu.obs import events as obs_events

        # drained planes named by the single-source obs registry
        _, obs = split_obs(final_state)
        bufs = np.asarray(obs["ev_buf"])
        heads = np.asarray(obs["ev_head"])
        drops = np.asarray(obs["ev_drops"])
        streams = tuple(
            obs_events.decode_events(bufs[b], heads[b], drops[b])
            for b in range(bufs.shape[0])
        )
        return streams, tuple(int(d) for d in drops)


class ScalableFuzzExecutor(_FuzzExecutorBase):
    engine_name = SCALABLE

    def __init__(
        self,
        config: ScenarioConfig,
        params: Optional[es.ScalableParams] = None,
        packet_loss: float = 0.0,
        shared_cache: bool = True,
    ):
        if params is None:
            params = default_scalable_params(
                config.n, packet_loss, enable_leave=config.use_leave
            )
        super().__init__(config, params, shared_cache)

    def _init_state(self, seed: int):
        return es.init_state(self.params, seed=int(seed))

    def _build_fn(self):
        if self._shared_cache:
            return _scalable_scan_fn(self.params)
        return jax.jit(
            functools.partial(scenario_scan_scalable, params=self.params)
        )


def executor_for(
    config: ScenarioConfig,
    packet_loss: float = 0.0,
    shared_cache: bool = True,
) -> _FuzzExecutorBase:
    cls = FullFuzzExecutor if config.engine == FULL else ScalableFuzzExecutor
    return cls(config, packet_loss=packet_loss, shared_cache=shared_cache)


def sweep(
    seeds: Sequence[int],
    config: ScenarioConfig,
    shared_cache: bool = True,
) -> List[FuzzRun]:
    """Run every seed, bucketed by its packet-loss level so each level
    shares one compiled executor; returns one FuzzRun per bucket.
    Feed the runs to :func:`ringpop_tpu.fuzz.invariants.check_run`."""
    buckets: dict = {}
    for s in seeds:
        buckets.setdefault(scenarios.packet_loss_of(s, config), []).append(s)
    runs: List[FuzzRun] = []
    for loss in sorted(buckets):
        ex = executor_for(config, packet_loss=loss, shared_cache=shared_cache)
        runs.append(ex.run_seeds(buckets[loss]))
    return runs
