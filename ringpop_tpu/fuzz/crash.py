"""Crash-injection harness: preempt a storm, recover, prove bit-exact.

The ``crash_resume`` move of the scenario catalog
(:mod:`ringpop_tpu.fuzz.scenarios`): a driver — full-fidelity
``SimCluster``, scalable ``ScalableCluster``, or the coupled
``RoutedStorm`` — is run with a checkpoint cadence, killed at a
seed-drawn tick (``crash_plan_of``), *including mid-checkpoint-write*
(the kill leaves a torn manifest, a truncated or bit-flipped array
file, or a missing shard — simulated at the file layer on a real
checkpoint the save just committed), then restarted cold.  Recovery is
the production path, not a test shim: ``restore_latest()`` scans the
checkpoint family newest-first, falls back past every corrupt artifact
(named ``CheckpointError``s, ``ckpt.corrupt`` events), resumes from the
newest valid one — or restarts clean when nothing valid survived — and
replays the rest of the SAME schedule.

The gate is the ``resume-bitwise`` invariant: the recovered final state
(every engine-state field; for RoutedStorm also the routing carry and
the materialized truth ring) must equal the uninterrupted twin's
**bitwise**.  Violations ride the fuzz layer's
:class:`~ringpop_tpu.fuzz.invariants.Violation` shape so the sweep
driver (scripts/fuzz_sweep.py ``crash``) and the mutation-gate tests
report them uniformly.
"""

from __future__ import annotations

import os
from itertools import count as _count
from typing import Dict, List, NamedTuple, Optional, Tuple

import numpy as np

from ringpop_tpu.fuzz import scenarios
from ringpop_tpu.fuzz.invariants import Violation
from ringpop_tpu.fuzz.scenarios import (
    FULL,
    SCALABLE,
    CrashPlan,
    ScenarioConfig,
    crash_plan_of,
)
from ringpop_tpu.models.sim import checkpoint as ckpt

# third driver kind: the scalable engine + routing plane under one scan
# (schedules are the SCALABLE shape; the carry adds the route state)
ROUTED = "routed"
DRIVERS = (FULL, SCALABLE, ROUTED)

RESUME_BITWISE = "resume-bitwise"

_EXERCISE_SEQ = _count()


class CrashReport(NamedTuple):
    """One crash-and-recover exercise, everything the gates assert on."""

    violations: List[Violation]
    kill_tick: int
    corrupt: str  # damage mode applied ("none" = clean preemption)
    resumed_tick: Optional[int]  # None = no valid checkpoint, clean restart
    skipped_errors: Tuple[str, ...]  # CheckpointError class names fallen past
    checkpoints_after: int  # family size after recovery ran to completion
    damaged_file: Optional[str]


# -- drivers -----------------------------------------------------------------


def default_crash_sim_params(n: int):
    """Full-engine config for crash exercises: cheap compiles ("fast"
    checksum mode — the FarmHash parity pipeline has its own suite),
    short suspicion so suspect->faulty->refute cycles fit the window."""
    from ringpop_tpu.models.sim import engine

    return engine.SimParams(
        n=n, checksum_mode="fast", hash_impl="scan", suspicion_ticks=6
    )


def default_crash_scalable_params(n: int, enable_leave: bool = True):
    from ringpop_tpu.fuzz.executor import default_scalable_params

    return default_scalable_params(n, enable_leave=enable_leave)


def default_crash_route_params(n: int):
    from ringpop_tpu.models.route.plane import RouteParams

    return RouteParams(n=n, queries_per_tick=256, key_space=1 << 10)


def build_driver(driver: str, config: ScenarioConfig, seed: int):
    """A fresh driver, fully determined by (driver, config, seed) — the
    restarted process must reconstruct the exact same initial state."""
    if driver == FULL:
        from ringpop_tpu.models.sim.cluster import SimCluster

        return SimCluster(
            n=config.n, params=default_crash_sim_params(config.n), seed=seed
        )
    if driver == SCALABLE:
        from ringpop_tpu.models.sim.storm import ScalableCluster

        return ScalableCluster(
            n=config.n,
            params=default_crash_scalable_params(
                config.n, enable_leave=config.use_leave
            ),
            seed=seed,
        )
    if driver == ROUTED:
        from ringpop_tpu.models.route.plane import RoutedStorm

        return RoutedStorm(
            n=config.n,
            params=default_crash_scalable_params(
                config.n, enable_leave=config.use_leave
            ),
            route=default_crash_route_params(config.n),
            seed=seed,
        )
    raise ValueError("driver must be one of %r, got %r" % (DRIVERS, driver))


def schedule_config(driver: str, config: ScenarioConfig) -> ScenarioConfig:
    """The generator config behind a crash driver: RoutedStorm consumes
    scalable StormSchedules."""
    return config._replace(engine=FULL if driver == FULL else SCALABLE)


def snapshot(driver_kind: str, drv) -> Dict[str, np.ndarray]:
    """Host snapshot of everything the resume-bitwise gate compares.

    Copies, not ``np.asarray`` views: on CPU a view can alias the live
    device buffer, and this snapshot is held across OTHER drivers'
    donating dispatches — the documented aliasing hazard (see
    tests/models/test_scalable_partition.py's device_get note)."""
    out: Dict[str, np.ndarray] = {}
    if driver_kind == ROUTED:
        state = drv.cluster.state
        carry = drv._route_carry()
        out["route.mask"] = np.array(carry.mask, copy=True)
        out["route.rng"] = np.array(carry.rng, copy=True)
        out["route.truth_ring"] = np.array(drv.truth_ring(), copy=True)
    else:
        state = drv.state
    for f in state._fields:
        v = getattr(state, f)
        if v is not None:
            out["state.%s" % f] = np.array(v, copy=True)
    return out


# -- file-layer damage (the mid-write kill) ----------------------------------


def corrupt_checkpoint(
    path: str, mode: str, frac: float
) -> Optional[str]:
    """Damage a COMMITTED checkpoint directory the way a kill mid-write
    (or bit-rot between write and read) would: truncate the manifest or
    an array file at ``frac`` of its length, flip one byte, or drop a
    shard file.  Returns the damaged file's path (None for mode
    "none")."""
    if mode == "none":
        return None
    manifest_path = os.path.join(path, ckpt.MANIFEST_NAME)

    def _array_files() -> List[str]:
        names = sorted(
            f for f in os.listdir(path) if f.endswith(".npz")
        )
        # prefer a shard file (named shard errors) over common
        shards = [f for f in names if f.startswith("shard-")]
        return [os.path.join(path, f) for f in (shards or names)]

    if mode == "torn-manifest":
        target = manifest_path
        size = os.path.getsize(target)
        with open(target, "r+b") as fh:
            fh.truncate(max(1, int(size * frac)))
        return target
    if mode == "torn-array":
        target = _array_files()[0]
        size = os.path.getsize(target)
        with open(target, "r+b") as fh:
            fh.truncate(max(1, int(size * frac)))
        return target
    if mode == "flip-byte":
        target = _array_files()[0]
        size = os.path.getsize(target)
        # land inside stored array bytes, past the zip local header (npz
        # members are STORED, not deflated, so a mid-file byte is data)
        off = min(size - 1, max(128, int(size * frac)))
        with open(target, "r+b") as fh:
            fh.seek(off)
            b = fh.read(1)
            fh.seek(off)
            fh.write(bytes([b[0] ^ 0xFF]))
        return target
    if mode == "missing-shard":
        files = _array_files()
        shards = [f for f in files if os.path.basename(f).startswith("shard-")]
        if shards:
            os.remove(shards[-1])
            return shards[-1]
        # single-file checkpoint: the nearest analog is a torn array
        return corrupt_checkpoint(path, "torn-array", frac)
    raise ValueError(
        "corrupt mode must be one of %r, got %r"
        % (scenarios.CRASH_CORRUPT_MODES, mode)
    )


# -- the harness -------------------------------------------------------------


def run_crash_resume(
    seed: int,
    workdir: str,
    *,
    driver: str = SCALABLE,
    config: Optional[ScenarioConfig] = None,
    every: int = 3,
    keep: int = 3,
    shards: int = 1,
    plan: Optional[CrashPlan] = None,
) -> CrashReport:
    """One full crash-and-recover exercise for ``seed``.

    1. run the seed's storm schedule uninterrupted -> reference final
       state;
    2. re-run under a checkpoint cadence and preempt at
       ``plan.kill_tick``; when the plan damages the newest checkpoint,
       force the save the kill interrupts and corrupt it at the file
       layer;
    3. restart cold: ``restore_latest()`` auto-discovers the newest
       valid checkpoint (falling back past corrupt ones, or restarting
       clean), then replays the remaining schedule window;
    4. gate every state field bitwise against the reference.

    Deterministic in (seed, config, plan, every, shards) — a failing
    report replays exactly.
    """
    config = schedule_config(
        driver, config or ScenarioConfig(n=16, ticks=12)
    )
    plan = plan or crash_plan_of(seed, config)
    if not (1 <= plan.kill_tick <= config.ticks):
        raise ValueError(
            "kill_tick %d outside [1, %d]" % (plan.kill_tick, config.ticks)
        )
    sched = scenarios.generate(seed, config)
    # per-exercise family dir: two exercises sharing (driver, seed) must
    # not resume from each other's checkpoints
    ckdir = os.path.join(
        workdir,
        "crash-%s-seed%d-%04d" % (driver, seed, next(_EXERCISE_SEQ)),
    )

    # 1. uninterrupted twin (no checkpoint plane at all: proves the
    # cadence machinery itself is trajectory-neutral)
    ref = build_driver(driver, config, seed)
    ref.run(sched.window(0, config.ticks))
    want = snapshot(driver, ref)

    # 2. the preempted run
    victim = build_driver(driver, config, seed)
    victim.enable_checkpoints(ckdir, every=every, keep=keep, shards=shards)
    victim.run(sched.window(0, plan.kill_tick))
    damaged = None
    if plan.corrupt != "none":
        # the save the preemption interrupts: committed, then damaged at
        # the file layer exactly as a mid-write kill would leave it
        newest = victim.checkpoint_now()
        damaged = corrupt_checkpoint(newest, plan.corrupt, plan.frac)
    del victim  # the process is gone

    # 3. cold restart + auto-recovery
    recovered = build_driver(driver, config, seed)
    mgr = recovered.enable_checkpoints(
        ckdir, every=every, keep=keep, shards=shards
    )
    resumed_tick = recovered.restore_latest()
    skipped = tuple(type(e).__name__ for _, _, e in mgr.last_errors)
    start = 0 if resumed_tick is None else resumed_tick
    recovered.run(sched.window(start, config.ticks))
    got = snapshot(driver, recovered)

    # 4. the resume-bitwise gate
    violations: List[Violation] = []
    for key in sorted(want):
        if key not in got:
            violations.append(
                Violation(RESUME_BITWISE, 0, "field %s missing after resume" % key)
            )
            continue
        a, b = want[key], got[key]
        if a.shape != b.shape or a.dtype != b.dtype or not np.array_equal(a, b):
            where = (
                np.argwhere(a != b)[:4].tolist()
                if a.shape == b.shape
                else "shape %r vs %r" % (a.shape, b.shape)
            )
            violations.append(
                Violation(
                    RESUME_BITWISE,
                    0,
                    "field %s diverged after crash-resume (kill_tick=%d, "
                    "corrupt=%s, resumed=%s): %s"
                    % (key, plan.kill_tick, plan.corrupt, resumed_tick, where),
                )
            )
    for key in sorted(set(got) - set(want)):
        violations.append(
            Violation(RESUME_BITWISE, 0, "spurious field %s after resume" % key)
        )
    return CrashReport(
        violations=violations,
        kill_tick=plan.kill_tick,
        corrupt=plan.corrupt,
        resumed_tick=resumed_tick,
        skipped_errors=skipped,
        checkpoints_after=len(mgr.list_checkpoints()),
        damaged_file=damaged,
    )


def sweep_crash(
    seeds,
    workdir: str,
    *,
    driver: str = SCALABLE,
    config: Optional[ScenarioConfig] = None,
    every: int = 3,
    keep: int = 3,
    shards: int = 1,
) -> Dict[int, CrashReport]:
    """Crash-and-recover every seed; returns seed -> report (the sweep
    CLI and the bench fuzz gate iterate the violation lists)."""
    return {
        int(s): run_crash_resume(
            int(s),
            workdir,
            driver=driver,
            config=config,
            every=every,
            keep=keep,
            shards=shards,
        )
        for s in seeds
    }
