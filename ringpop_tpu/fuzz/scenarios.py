"""Seeded scenario generator: uint32 seed -> adversarial storm schedule.

A scenario is a composition of the engines' EXISTING fault-injection
primitives into a storm plan over ``ticks`` protocol periods:

- full-fidelity engine (``engine.TickInputs`` via ``EventSchedule``):
  kill/revive (process restart + rejoin), suspend/resume (SIGSTOP /
  SIGCONT — state kept, refutes on return), graceful leave + rejoin,
  partition regroups;
- scalable engine (``es.ChurnInputs`` via ``StormSchedule``): process
  kills/revives, graceful leaves, partition regroups.

Packet loss is a trace-time static (``params.packet_loss``), so it is a
per-scenario CONFIG axis rather than a per-tick plane: each seed draws
its loss level from ``config.loss_levels`` (``packet_loss_of``), and the
sweep driver groups seeds by level so every level reuses one compiled
executor (ringpop_tpu/fuzz/executor.py).

Everything here is a pure function of ``(seed, config)``: the move
catalog is drawn from ``np.random.default_rng(seed)`` only — no clocks,
no global state — so any failing seed replays exactly, shrinks
deterministically, and commits as a fixture (ringpop_tpu/fuzz/shrink.py).

Storm move catalog (composed 1..max_moves per scenario):

==================  ========================================================
move                shape
==================  ========================================================
churn_burst         kill a victim set at t0, revive it d ticks later
                    (suspect -> faulty escalation + rejoin wave)
suspect_pileup      kill a larger set with NO revive — suspicion clocks
                    pile up and expire together
flap                one node killed/revived on a short period — rumor
                    births faster than dissemination retires them
split_brain         partition into g groups at t0, heal at t1 (cross-side
                    false suspects, post-heal refute cleanup)
partial_regroup     move a node subset to another group mid-run (the
                    ``partition >= 0`` partial-merge path)
leave_rejoin        graceful leave at t0, rejoin at t1 (admin plane)
stall_resume        full engine only: SIGSTOP at t0, SIGCONT at t1 —
                    the node returns with stale state and must refute
crash_resume        HOST-level move (ringpop_tpu/fuzz/crash.py): the
                    *driver process* is preempted at a seed-drawn tick —
                    optionally mid-checkpoint-write, leaving a torn or
                    bit-rotted newest checkpoint — then restarted; it
                    must auto-recover from the newest valid checkpoint
                    and replay to a final state bitwise-identical to the
                    uninterrupted run (the ``resume-bitwise`` invariant)
==================  ========================================================

``crash_resume`` composes with the device-plane moves: the preemption
point and checkpoint damage are drawn by :func:`crash_plan_of` (a pure
seed derivation like :func:`packet_loss_of`, so the storm schedule
stream is unchanged by it), and the harness replays the SAME generated
schedule through kill and recovery.
"""

from __future__ import annotations

from typing import List, NamedTuple, Tuple, Union

import numpy as np

from ringpop_tpu.models.sim.cluster import EventSchedule
from ringpop_tpu.models.sim.storm import StormSchedule

Schedule = Union[EventSchedule, StormSchedule]

FULL = "full"
SCALABLE = "scalable"

# bool fault planes per engine + the int32 partition plane; sparse-fault
# tuples (plane, tick, node, value) use these names
BOOL_PLANES = {
    FULL: ("kill", "revive", "join", "resume", "leave"),
    SCALABLE: ("kill", "revive", "leave"),
}
PARTITION_PLANE = "partition"


class ScenarioConfig(NamedTuple):
    """Static shape of a fuzz campaign (shared by every seed in it)."""

    engine: str = FULL
    n: int = 8
    ticks: int = 24
    # storm moves composed per scenario (1..max_moves drawn per seed)
    max_moves: int = 4
    # packet-loss menu: each seed draws ONE level (packet_loss_of); the
    # sweep driver buckets seeds by level so the executor count stays
    # bounded (params.packet_loss is trace-time static)
    loss_levels: Tuple[float, ...] = (0.0, 0.05, 0.2)
    max_groups: int = 3
    # leave/resume planes can be disabled (e.g. scalable runs without
    # enable_leave's 4th rumor slot)
    use_leave: bool = True
    use_resume: bool = True


def packet_loss_of(seed: int, config: ScenarioConfig) -> float:
    """The seed's packet-loss level — an independent derivation (not the
    move rng) so the schedule stream is unchanged by the loss menu."""
    if not config.loss_levels:
        return 0.0
    mixed = (((int(seed) & 0xFFFFFFFF) * 0x9E3779B9) & 0xFFFFFFFF) >> 16
    return float(config.loss_levels[mixed % len(config.loss_levels)])


class CrashPlan(NamedTuple):
    """The ``crash_resume`` move's host-level shape: when the driver is
    preempted and what the interrupted checkpoint write left behind."""

    kill_tick: int  # driver preempted after this many driven ticks
    # damage to the NEWEST checkpoint (the save the kill interrupted):
    # "none" = clean preemption between saves; the rest model a torn or
    # bit-rotted artifact the recovery scan must fall back past
    corrupt: str
    frac: float  # truncation offset / flip position, as a size fraction


CRASH_CORRUPT_MODES = (
    "none",
    "torn-manifest",
    "torn-array",
    "flip-byte",
    "missing-shard",
)


def crash_plan_of(seed: int, config: ScenarioConfig) -> CrashPlan:
    """Pure ``(seed, config) -> CrashPlan`` — an independent derivation
    (not the move rng), so the storm schedule stream is unchanged by the
    crash plane, exactly like :func:`packet_loss_of`."""
    if config.ticks < 2:
        # kill_tick draws from [1, ticks); shorter windows would surface
        # as an opaque numpy low >= high error (generate() has the same
        # guard shape for its move draws)
        raise ValueError(
            "crash planning needs ticks >= 2, got %d" % config.ticks
        )
    rng = np.random.default_rng(
        (int(np.uint32(seed)) * 0x9E3779B9 + 0x5CA1AB1E) & 0xFFFFFFFF
    )
    kill_tick = int(rng.integers(1, config.ticks))
    corrupt = CRASH_CORRUPT_MODES[
        int(rng.integers(0, len(CRASH_CORRUPT_MODES)))
    ]
    frac = float(rng.uniform(0.05, 0.95))
    return CrashPlan(kill_tick=kill_tick, corrupt=corrupt, frac=frac)


def _blank_schedule(config: ScenarioConfig) -> Schedule:
    """All-quiet schedule with every usable plane dense (ONE pytree
    structure per campaign: the batched executor stacks instances, so no
    per-seed structure drift is allowed)."""
    t, n = config.ticks, config.n
    if config.engine == FULL:
        sched = EventSchedule(ticks=t, n=n)
        if config.use_resume:
            sched.resume = np.zeros((t, n), bool)
        if config.use_leave:
            sched.leave = np.zeros((t, n), bool)
        # bootstrap harness row, not a fault: every node joins at tick 0
        # (the tick-cluster 'j' command); the shrinker never removes it
        sched.join[0, :] = True
        return sched
    if config.engine != SCALABLE:
        raise ValueError("engine must be full|scalable, got %r" % (config.engine,))
    sched = StormSchedule(ticks=t, n=n)
    sched.partition = np.full((t, n), -1, np.int32)
    if config.use_leave:
        sched.leave = np.zeros((t, n), bool)
    return sched


def _victims(rng: np.random.Generator, n: int, lo: int, hi: int) -> np.ndarray:
    k = int(rng.integers(lo, max(lo, hi) + 1))
    return rng.choice(n, size=min(k, n), replace=False)


def _move_churn_burst(rng, sched, config):
    t0 = int(rng.integers(1, config.ticks - 1))
    d = int(rng.integers(2, max(3, config.ticks // 2)))
    victims = _victims(rng, config.n, 1, max(1, config.n // 4))
    sched.kill[t0, victims] = True
    t1 = t0 + d
    if t1 < config.ticks and rng.random() < 0.8:
        sched.revive[t1, victims] = True


def _move_suspect_pileup(rng, sched, config):
    t0 = int(rng.integers(1, config.ticks - 1))
    victims = _victims(rng, config.n, 2, max(2, config.n // 3))
    sched.kill[t0, victims] = True


def _move_flap(rng, sched, config):
    victim = int(rng.integers(0, config.n))
    period = int(rng.integers(2, 6))
    t = int(rng.integers(1, config.ticks - 1))
    up = False
    while t < config.ticks:
        (sched.revive if up else sched.kill)[t, victim] = True
        up = not up
        t += period


def _partition_plane(sched):
    # EventSchedule's partition plane is always dense; StormSchedule's is
    # made dense by _blank_schedule
    return sched.partition


def _move_split_brain(rng, sched, config):
    t0 = int(rng.integers(1, config.ticks - 1))
    g = int(rng.integers(2, config.max_groups + 1))
    groups = rng.integers(0, g, size=config.n)
    groups[int(rng.integers(0, config.n))] = 0  # group 0 is never empty
    plane = _partition_plane(sched)
    plane[t0, :] = groups.astype(np.int32)
    d = int(rng.integers(2, max(3, config.ticks // 2)))
    t1 = t0 + d
    if t1 < config.ticks and rng.random() < 0.8:
        plane[t1, :] = 0  # heal


def _move_partial_regroup(rng, sched, config):
    t0 = int(rng.integers(1, config.ticks - 1))
    movers = _victims(rng, config.n, 1, max(1, config.n // 3))
    g = int(rng.integers(0, config.max_groups))
    plane = _partition_plane(sched)
    plane[t0, movers] = np.int32(g)


def _move_leave_rejoin(rng, sched, config):
    if sched.leave is None:
        return _move_churn_burst(rng, sched, config)
    t0 = int(rng.integers(1, config.ticks - 1))
    victim = int(rng.integers(0, config.n))
    sched.leave[t0, victim] = True
    t1 = t0 + int(rng.integers(2, max(3, config.ticks // 2)))
    if t1 < config.ticks and rng.random() < 0.8:
        # rejoin: fresh incarnation + gossip restart — join input on the
        # full engine (server/admin/member.js:44-51), revive on the
        # scalable engine (its revive doubles as admin rejoin)
        if config.engine == FULL:
            sched.join[t1, victim] = True
        else:
            sched.revive[t1, victim] = True


def _move_stall_resume(rng, sched, config):
    if config.engine != FULL or sched.resume is None:
        return _move_suspect_pileup(rng, sched, config)
    t0 = int(rng.integers(1, config.ticks - 1))
    victims = _victims(rng, config.n, 1, max(1, config.n // 4))
    sched.kill[t0, victims] = True  # SIGSTOP (state kept)
    t1 = t0 + int(rng.integers(2, max(3, config.ticks // 2)))
    if t1 < config.ticks:
        sched.resume[t1, victims] = True  # SIGCONT: stale state, refutes


_MOVES = (
    _move_churn_burst,
    _move_suspect_pileup,
    _move_flap,
    _move_split_brain,
    _move_partial_regroup,
    _move_leave_rejoin,
    _move_stall_resume,
)


def generate(seed: int, config: ScenarioConfig) -> Schedule:
    """Pure ``(uint32 seed, config) -> schedule``.  Same seed, same
    planes, bit for bit — the property every downstream piece (batched
    sweep, shrinker, committed fixtures) leans on."""
    if config.ticks < 3:
        # every move draws from integers(1, ticks - 1); shorter windows
        # would surface as an opaque numpy low >= high error
        raise ValueError(
            "scenario generation needs ticks >= 3, got %d" % config.ticks
        )
    rng = np.random.default_rng(int(np.uint32(seed)))
    sched = _blank_schedule(config)
    n_moves = int(rng.integers(1, config.max_moves + 1))
    for _ in range(n_moves):
        move = _MOVES[int(rng.integers(0, len(_MOVES)))]
        move(rng, sched, config)
    return sched


# -- sparse fault form (the shrinker/fixture representation) ----------------


def sparse_faults(
    sched: Schedule, engine: str
) -> List[Tuple[str, int, int, int]]:
    """Schedule -> sorted list of (plane, tick, node, value) fault cells.

    The full engine's tick-0 bootstrap join row is harness, not fault —
    it is excluded here and re-added by :func:`schedule_from_faults`."""
    out: List[Tuple[str, int, int, int]] = []
    for plane in BOOL_PLANES[engine]:
        arr = getattr(sched, plane, None)
        if arr is None:
            continue
        ts, ns = np.nonzero(arr)
        for t, node in zip(ts.tolist(), ns.tolist()):
            if engine == FULL and plane == "join" and t == 0:
                continue  # bootstrap row
            out.append((plane, t, node, 1))
    part = getattr(sched, "partition", None)
    if part is not None:
        ts, ns = np.nonzero(np.asarray(part) >= 0)
        for t, node in zip(ts.tolist(), ns.tolist()):
            out.append((PARTITION_PLANE, t, node, int(part[t, node])))
    return sorted(out)


def schedule_from_faults(
    engine: str,
    n: int,
    ticks: int,
    faults: List[Tuple[str, int, int, int]],
    config: "ScenarioConfig | None" = None,
) -> Schedule:
    """Rebuild a schedule from its sparse fault list (fixture replay).

    ``config`` defaults to a campaign config matching (engine, n, ticks)
    with every plane enabled — the planes present must be a superset of
    the planes the faults name."""
    if config is None:
        config = ScenarioConfig(engine=engine, n=n, ticks=ticks)
    else:
        config = config._replace(engine=engine, n=n, ticks=ticks)
    sched = _blank_schedule(config)
    for plane, t, node, value in faults:
        if plane == PARTITION_PLANE:
            _partition_plane(sched)[t, node] = np.int32(value)
        else:
            arr = getattr(sched, plane, None)
            if arr is None:
                raise ValueError(
                    "fault names plane %r which this config disables" % plane
                )
            arr[t, node] = bool(value)
    return sched
