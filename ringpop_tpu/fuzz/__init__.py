"""Scenario fuzzer: adversarial storms + machine-checked SWIM invariants.

The corners SURVEY §7's hard-parts list calls out — incarnation races,
suspicion-timer edge cases, piggyback-budget overflows, split-brain
defamation/refute cycles — are exactly where hand-written scenario suites
run out.  This package turns the flight-recorder stream (PR 4) into an
adversarial correctness harness:

- :mod:`scenarios` — a seeded generator composing the engines' existing
  fault-injection primitives (kill/revive/join/leave/resume/partition,
  packet loss) into arbitrary storm schedules, as pure functions of a
  uint32 seed.
- :mod:`executor` — batched executors that vmap B scenario instances
  through one compiled ``lax.scan`` per engine and drain the per-instance
  flight-recorder streams.
- :mod:`invariants` — the machine-checked protocol-invariant layer over
  decoded events + state snapshots (incarnation monotonicity,
  alive-after-faulty ⇒ refute, suspicion-timeout bounds, piggyback
  ceilings, partition-reachability of defamations, metrics↔event
  reconciliation).
- :mod:`shrinker` — bisects a failing seed's schedule (tick tail, then
  per-tick fault sets) to a minimal reproducing schedule and emits it as
  a committed regression fixture.
- :mod:`crash` — the ``crash_resume`` move: preempt a checkpointing
  driver at a seed-drawn tick (including mid-checkpoint-write, leaving a
  torn/bit-rotted artifact), restart cold, auto-recover from the newest
  valid checkpoint, and gate the final state bitwise against the
  uninterrupted run (the ``resume-bitwise`` invariant).
"""

from ringpop_tpu.fuzz.scenarios import (  # noqa: F401
    CrashPlan,
    ScenarioConfig,
    crash_plan_of,
    generate,
    packet_loss_of,
    schedule_from_faults,
    sparse_faults,
)
from ringpop_tpu.fuzz.crash import (  # noqa: F401
    RESUME_BITWISE,
    CrashReport,
    run_crash_resume,
    sweep_crash,
)
from ringpop_tpu.fuzz.executor import (  # noqa: F401
    FullFuzzExecutor,
    FuzzRun,
    ScalableFuzzExecutor,
    executor_for,
    sweep,
)
from ringpop_tpu.fuzz.invariants import (  # noqa: F401
    Violation,
    check_run,
)
from ringpop_tpu.fuzz.shrinker import (  # noqa: F401
    ShrinkResult,
    load_fixture,
    replay_fixture,
    save_fixture,
    shrink,
    shrink_seed,
)
