"""SimTracerHost — TracerStore/Tracer against the simulation engines.

The reference's trace taps (lib/trace/) attach to a live ringpop node's
internal emitters.  The simulation drivers (SimCluster /
BatchedSimClusters / ScalableCluster) have no facade, so this adapter
provides the minimal surface ``Tracer``/``TracerStore`` need — a
``logger``, a ``timers`` plane, an optional ``channel`` for forwarding
sinks, and named emitters — and re-publishes per-tick metric rows as
``tickMetrics`` events (the ``sim.tick.metrics`` trace event in
utils/trace.py TRACE_EVENTS).
"""

from __future__ import annotations

from typing import Any, Optional

from ringpop_tpu.net.timers import Timers
from ringpop_tpu.utils.config import EventEmitter
from ringpop_tpu.utils.stats import NullLogger
from ringpop_tpu.utils.trace import TracerStore


class SimTracerHost:
    """Adapter: a simulation driver wearing enough of the Ringpop facade
    for the trace subsystem (and other observers) to attach."""

    def __init__(
        self,
        cluster: Any = None,
        logger: Any = None,
        timers: Optional[Timers] = None,
        channel: Any = None,
    ):
        self.cluster = cluster
        self.logger = logger or NullLogger()
        self.timers = timers or Timers()
        self.channel = channel
        # the sim.tick.metrics trace event sources from this emitter
        self.sim_events = EventEmitter()
        self.tracers = TracerStore(self)

    def publish_tick_metrics(self, metrics: Any, start_tick: int = 0) -> int:
        """Re-publish a metrics row or stacked [T]-series as one
        ``tickMetrics`` event per tick.  Returns ticks published."""
        from ringpop_tpu.obs.recorder import _jsonable, iter_tick_rows

        published = 0
        for t, row in enumerate(iter_tick_rows(metrics)):
            self.sim_events.emit(
                "tickMetrics",
                {"tick": start_tick + t, "metrics": _jsonable(row)},
            )
            published += 1
        return published

    def publish_flight_events(
        self, events: Any, drops: int = 0, batch: int = 256
    ) -> int:
        """Re-publish a decoded flight-recorder stream (obs/events.py)
        as ``flightEvents`` emissions (the ``sim.flight.events`` trace
        event), batched so a long drain does not flood forwarding sinks
        one datagram per protocol event.  Returns events published."""
        events = list(events)
        for lo in range(0, len(events), batch):
            self.sim_events.emit(
                "flightEvents",
                {
                    "events": events[lo : lo + batch],
                    "dropped": int(drops),
                    "offset": lo,
                    "total": len(events),
                },
            )
        return len(events)

    def destroy(self) -> None:
        self.tracers.destroy()
