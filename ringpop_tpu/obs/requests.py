"""Request-trace registry + host-side decoding for the routing plane.

The device half (models/route/reqtrace.py) appends one fixed-width
int32 record per SAMPLED routed request into a linear buffer carried
through the routed scan — the flight-recorder mechanics
(models/sim/flight.py) applied to the request plane, under the SAME
masks that drive ``RouteMetrics``.  This module is the HOST half: the
record layout both sides share, the decoder, reconciliation against
the device-side sampled counters AND the window's ``RouteMetrics``
totals (the honesty gate, obs/events.py style), per-key span trees,
the Perfetto request-lifecycle export, and the ``reqtrace.drain``
runlog row.

Record layout (one row = one sampled request, ``RECORD_WIDTH`` int32
slots)::

    [tick, key, sender, dest, owner_truth,
     misroute, reroute, retry_depth, multi, outcome]

- ``tick``        — 1-based routing-plane tick (RouteState.req_tick
  after the tick ran; monotone across drain windows).
- ``key``         — the uint32 ring-position key hash, bitcast to
  int32 (``np.uint32(key)`` recovers it).  Sampling is a pure function
  of this value, so every request for a sampled key is traced — the
  per-key span tree is complete, Dapper-style.
- ``sender``      — the requesting node.
- ``dest``        — the node the request was sent to (the stale-view
  owner; ``sendable`` guarantees one existed).
- ``owner_truth`` — the post-churn truth owner (-1 = none: the key's
  whole replica set left the ring this tick).
- ``misroute``    — 1 when the stale and truth owners disagree.
- ``reroute``     — retry re-lookup verdict: 0 none, 1 local (the
  retry landed on the sender itself, send.js:190-198), 2 remote
  (re-forwarded to a new remote owner, send.js:181-189).
- ``retry_depth`` — retry rounds taken (0 or 1 — the modeled single
  stale->truth retry; matches the ``retry_depth`` histogram track).
- ``multi``       — 1 when a second key rode the envelope (both keys
  agreed under the stale view).
- ``outcome``     — bitmask: 1 = envelope/dest checksums differed,
  2 = enforce_consistency rejected the request, 4 = the retry found
  the multi-key pair diverged (keys-diverged abort, send.js:91-104).
  0 = clean delivery.

Sampled-counter plane: alongside the records the device keeps
``len(COUNT_FIELDS)`` int32 counters — each RouteMetrics analog summed
over ``mask & sampled`` — so reconciliation is EXACT even when the
record buffer overflowed: decoded records reconcile against the
counters (drop-free windows), the counters reconcile against the
window's RouteMetrics totals (sampled <= total always; equal at
sample_log2=0).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

RECORD_WIDTH = 10
FIELDS = (
    "tick",
    "key",
    "sender",
    "dest",
    "owner_truth",
    "misroute",
    "reroute",
    "retry_depth",
    "multi",
    "outcome",
)
# field slot indices (device and host must agree)
(
    F_TICK,
    F_KEY,
    F_SENDER,
    F_DEST,
    F_OWNER_TRUTH,
    F_MISROUTE,
    F_REROUTE,
    F_RETRY_DEPTH,
    F_MULTI,
    F_OUTCOME,
) = range(RECORD_WIDTH)

# reroute codes
RR_NONE = 0
RR_LOCAL = 1
RR_REMOTE = 2

# outcome bitmask
OUT_CHECKSUMS_DIFFER = 1
OUT_CHECKSUM_REJECT = 2
OUT_KEYS_DIVERGED = 4

# Device-side sampled-subset counters (RouteState.req_counts slots, in
# order): each is the matching RouteMetrics counter restricted to
# sampled requests — ``cnt(mask & sampled)`` on device under the SAME
# mask the metric sums.  scripts/check_metrics_schema.py pins the
# ``reqtrace.drain`` row's counts object to this tuple (lockstep test:
# tests/obs/test_runlog_schema.py).
COUNT_FIELDS = (
    "queries",
    "misroutes",
    "reroute_local",
    "reroute_remote",
    "keys_diverged",
    "checksums_differ",
    "checksum_rejects",
)

# count field -> the RouteMetrics field it is the sampled restriction of
METRIC_FIELDS: Dict[str, str] = {
    "queries": "route_queries",
    "misroutes": "route_misroutes",
    "reroute_local": "route_reroute_local",
    "reroute_remote": "route_reroute_remote",
    "keys_diverged": "route_keys_diverged",
    "checksums_differ": "route_checksums_differ",
    "checksum_rejects": "route_checksum_rejects",
}


def decode_arrays(buf: Any, head: Any) -> Dict[str, np.ndarray]:
    """Device buffer -> {field: np.ndarray} over the ``head`` valid
    rows (the cheap columnar form; ``key`` is returned as uint32)."""
    buf = np.asarray(buf)
    if buf.ndim != 2 or buf.shape[1] != RECORD_WIDTH:
        raise ValueError(
            "request buffer must be [cap, %d] int32, got %r"
            % (RECORD_WIDTH, buf.shape)
        )
    head = int(np.asarray(head))
    head = max(0, min(head, buf.shape[0]))
    rows = buf[:head]
    out = {name: rows[:, i].copy() for i, name in enumerate(FIELDS)}
    out["key"] = rows[:, F_KEY].astype(np.int32).view(np.uint32).copy()
    return out


def decode_requests(
    buf: Any, head: Any, drops: Any = 0
) -> List[Dict[str, int]]:
    """Device buffer -> list of per-request dicts.  A nonzero ``drops``
    (RouteState.req_drops) annotates every row: the buffer filled and
    the TAIL of the stream is missing — new records are dropped, never
    overwritten, so the prefix is honest."""
    arrs = decode_arrays(buf, head)
    out: List[Dict[str, int]] = []
    for i in range(len(arrs["tick"])):
        out.append({name: int(arrs[name][i]) for name in FIELDS})
    if int(np.asarray(drops)):
        for req in out:
            req.setdefault("truncated_stream", True)
    return out


def counts_dict(req_counts: Any) -> Dict[str, int]:
    """RouteState.req_counts -> {COUNT_FIELDS name: int}."""
    arr = np.asarray(req_counts).reshape(-1)
    if arr.shape[0] != len(COUNT_FIELDS):
        raise ValueError(
            "req_counts must have %d slots, got %r"
            % (len(COUNT_FIELDS), arr.shape)
        )
    return {name: int(arr[i]) for i, name in enumerate(COUNT_FIELDS)}


# how to derive each sampled counter from the decoded record stream
_RECORD_DERIVE = {
    "queries": lambda a: int(len(a["tick"])),
    "misroutes": lambda a: int(np.sum(a["misroute"])),
    "reroute_local": lambda a: int(np.sum(a["reroute"] == RR_LOCAL)),
    "reroute_remote": lambda a: int(np.sum(a["reroute"] == RR_REMOTE)),
    "keys_diverged": lambda a: int(
        np.sum((a["outcome"] & OUT_KEYS_DIVERGED) != 0)
    ),
    "checksums_differ": lambda a: int(
        np.sum((a["outcome"] & OUT_CHECKSUMS_DIFFER) != 0)
    ),
    "checksum_rejects": lambda a: int(
        np.sum((a["outcome"] & OUT_CHECKSUM_REJECT) != 0)
    ),
}


def reconcile_records(
    buf: Any, head: Any, req_counts: Any
) -> Dict[str, Dict[str, object]]:
    """Decoded records vs the device-side sampled counters.  On a
    drop-free window every field must match exactly; with drops the
    records are a prefix, so records <= counts.  Returns
    {field: {"records": n, "counts": n, "match": bool}}."""
    arrs = decode_arrays(buf, head)
    counts = counts_dict(req_counts)
    out: Dict[str, Dict[str, object]] = {}
    for field in COUNT_FIELDS:
        r = _RECORD_DERIVE[field](arrs)
        c = counts[field]
        out[field] = {"records": r, "counts": c, "match": r == c}
    return out


def reconcile_metrics(
    req_counts: Any, metrics: Any
) -> Dict[str, Dict[str, object]]:
    """Sampled counters vs the window's RouteMetrics totals: the
    sampled restriction can never exceed the full count, and at
    sample_log2=0 (sample everything) the two are EQUAL.  Returns
    {count field: {"sampled": n, "total": n, "ok": bool}} where ok
    means sampled <= total."""
    counts = counts_dict(req_counts)
    if hasattr(metrics, "_asdict"):
        metrics = metrics._asdict()
    out: Dict[str, Dict[str, object]] = {}
    for field, mfield in METRIC_FIELDS.items():
        if mfield not in metrics:
            continue
        total = int(np.asarray(metrics[mfield]).sum())
        sampled = counts[field]
        out[field] = {
            "sampled": sampled,
            "total": total,
            "ok": sampled <= total,
        }
    return out


# -- per-key span trees ------------------------------------------------------


def outcome_label(req: Dict[str, int]) -> str:
    """One human label per request, worst outcome first."""
    o = int(req["outcome"])
    if o & OUT_KEYS_DIVERGED:
        return "abort.keys-diverged"
    if o & OUT_CHECKSUM_REJECT:
        return "reject.checksum"
    r = int(req["reroute"])
    if r == RR_REMOTE:
        return "reroute.remote"
    if r == RR_LOCAL:
        return "reroute.local"
    if int(req["misroute"]):
        return "misroute"
    return "ok"


def request_span(req: Dict[str, int]) -> Dict[str, Any]:
    """One request's span tree: the root send span plus one child span
    per lifecycle stage that fired (checksum mismatch, retry, reroute,
    abort) — the requestProxy story send.js tells per request, rebuilt
    from one record."""
    children: List[Dict[str, Any]] = []
    o = int(req["outcome"])
    if o & OUT_CHECKSUMS_DIFFER:
        children.append(
            {
                "name": "checksums-differ",
                "rejected": bool(o & OUT_CHECKSUM_REJECT),
            }
        )
    if int(req["retry_depth"]) > 0:
        retry: Dict[str, Any] = {"name": "retry", "children": []}
        r = int(req["reroute"])
        if r == RR_LOCAL:
            retry["children"].append(
                {"name": "reroute.local", "dest": int(req["sender"])}
            )
        elif r == RR_REMOTE:
            retry["children"].append(
                {"name": "reroute.remote", "dest": int(req["owner_truth"])}
            )
        if o & OUT_KEYS_DIVERGED:
            retry["children"].append({"name": "abort.keys-diverged"})
        children.append(retry)
    return {
        "name": "request",
        "tick": int(req["tick"]),
        "key": int(req["key"]),
        "sender": int(req["sender"]),
        "dest": int(req["dest"]),
        "outcome": outcome_label(req),
        "multi": bool(req["multi"]),
        "children": children,
    }


def span_trees(requests: Any) -> Dict[int, List[Dict[str, Any]]]:
    """Decoded requests grouped into per-key span trees: {key hash:
    [request span, ...]} ordered by tick.  Sampling is per KEY, so a
    sampled key's list is its complete traced lifecycle across the
    window."""
    if requests and isinstance(requests[0], dict):
        reqs = requests
    else:
        raise TypeError(
            "span_trees wants decode_requests output (list of dicts)"
        )
    by_key: Dict[int, List[Dict[str, Any]]] = {}
    for req in sorted(reqs, key=lambda r: (r["tick"], r["sender"])):
        by_key.setdefault(int(req["key"]), []).append(request_span(req))
    return by_key


# -- Perfetto export ---------------------------------------------------------

REQ_PID = 2  # request tracks ride their own process (cluster = 1, host = 0)


def export_request_trace(
    requests: List[Dict[str, int]],
    n: int,
    period_ms: int = 200,
    pid: int = REQ_PID,
) -> Dict[str, Any]:
    """Decoded sampled requests -> Trace Event Format dict: one track
    (thread) per SENDER node, one complete ``"X"`` span per request
    (duration scales with retry depth — a retried request spans two
    protocol periods), flow arrows (``"s"``/``"t"``) from the sender's
    span to the truth owner's track for remote reroutes.  Merges
    cleanly with the flight-recorder export (distinct pid)."""
    out: List[Dict[str, Any]] = [
        {
            "ph": "M",
            "pid": pid,
            "tid": 0,
            "name": "process_name",
            "args": {"name": "routed requests (sampled, n=%d)" % n},
        }
    ]
    senders = sorted({int(r["sender"]) for r in requests})
    for s in senders:
        out.append(
            {
                "ph": "M",
                "pid": pid,
                "tid": s,
                "name": "thread_name",
                "args": {"name": "sender %d" % s},
            }
        )
    us = int(period_ms) * 1000
    for i, req in enumerate(requests):
        ts = int(req["tick"]) * us
        depth = int(req["retry_depth"])
        span = {
            "ph": "X",
            "pid": pid,
            "tid": int(req["sender"]),
            "ts": ts,
            "dur": us * (1 + depth),
            "name": outcome_label(req),
            "cat": "request",
            "args": {k: int(req[k]) for k in FIELDS},
        }
        out.append(span)
        if int(req["reroute"]) == RR_REMOTE and int(req["owner_truth"]) >= 0:
            fid = "req-%d" % i
            out.append(
                {
                    "ph": "s",
                    "pid": pid,
                    "tid": int(req["sender"]),
                    "ts": ts,
                    "id": fid,
                    "name": "reroute",
                    "cat": "request",
                }
            )
            out.append(
                {
                    "ph": "t",
                    "pid": pid,
                    "tid": int(req["owner_truth"]),
                    "ts": ts + us,
                    "id": fid,
                    "name": "reroute",
                    "cat": "request",
                }
            )
    return {"traceEvents": out}


# -- drain -------------------------------------------------------------------


def drain_row(
    source: str,
    records: int,
    drops: int,
    cap: int,
    sample_log2: int,
    counts: Dict[str, int],
    **extra: object,
) -> Dict[str, object]:
    """The ``reqtrace.drain`` runlog event row (field set validated by
    scripts/check_metrics_schema.py)."""
    row: Dict[str, object] = {
        "source": source,
        "records": int(records),
        "drops": int(drops),
        "cap": int(cap),
        "sample_log2": int(sample_log2),
        "counts": dict(counts),
    }
    row.update(extra)
    return row


def drain(
    buf: Any,
    head: Any,
    drops: Any,
    req_counts: Any,
    sample_log2: int,
    source: str = "route",
    recorder=None,
    statsd=None,
) -> Dict[str, object]:
    """The host half of ``RoutedStorm.drain_requests()``: decode the
    window, log the ``reqtrace.drain`` event row on ``recorder`` (a
    RunRecorder), emit the sampled counters through ``statsd`` (a
    StatsdBridge).  Returns {"records": [...], "drops", "cap",
    "counts", ...}; the CALLER owns the device-side reset — sinks run
    first, so a raising sink leaves the window on device for a retry
    (the drain contract obs.histograms.drain pins)."""
    cap = int(np.asarray(buf).shape[0])
    records = decode_requests(buf, head, drops)
    counts = counts_dict(req_counts)
    n_drops = int(np.asarray(drops))
    row = drain_row(
        source, len(records), n_drops, cap, sample_log2, counts
    )
    if recorder is not None:
        recorder.record_event("reqtrace.drain", **row)
    if statsd is not None:
        statsd.emit_reqtrace_drain(row)
    out = dict(row)
    out["records"] = records
    return out


__all__ = [
    "COUNT_FIELDS",
    "FIELDS",
    "METRIC_FIELDS",
    "RECORD_WIDTH",
    "counts_dict",
    "decode_arrays",
    "decode_requests",
    "drain",
    "drain_row",
    "export_request_trace",
    "outcome_label",
    "reconcile_metrics",
    "reconcile_records",
    "request_span",
    "span_trees",
]
