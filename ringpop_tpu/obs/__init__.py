"""Unified telemetry layer: run recording, stats exposition, sim taps.

The reference treats observability as a first-class subsystem — statsd
emission on every protocol action (index.js:527-541), a protocol-period
histogram feeding the adaptive gossip delay (lib/gossip/index.js:37,52-55)
and remotely attachable trace taps (lib/trace/).  This package is the
TPU port's host-side counterpart for the *simulation* plane: the scanned
engines already return per-tick metrics time-series
(``TickMetrics``/``ScalableMetrics`` stacked by ``lax.scan``); here they
become durable and queryable:

- :mod:`ringpop_tpu.obs.recorder` — ``RunRecorder``: folds stacked
  metrics into the ``Meter``/``Histogram`` primitives and writes an
  append-only JSONL run log (config, per-tick rows, wall-clock phases,
  convergence tick, backend provenance) so BENCH_*/PARITY_* artifacts
  are generated, not hand-curated.
- :mod:`ringpop_tpu.obs.statsd_bridge` — maps device counters onto the
  reference's statsd key names through ``Ringpop.stat()``'s
  ``ringpop.<host_port>.`` scheme.
- :mod:`ringpop_tpu.obs.prometheus` — Prometheus text exposition for
  live nodes (the ``/admin/metrics`` endpoint) and for recorded runs.
- :mod:`ringpop_tpu.obs.sim_tap` — adapter letting ``TracerStore`` /
  ``Tracer`` attach to simulation drivers (the ``sim.tick.metrics`` and
  ``sim.flight.events`` trace events).
- :mod:`ringpop_tpu.obs.events` — flight-recorder event registry,
  decoder, TickMetrics reconciliation, rumor-wavefront derivations
  (device half: models/sim/flight.py).
- :mod:`ringpop_tpu.obs.chrome_trace` — Chrome-trace/Perfetto JSON
  export of decoded flight-recorder streams (per-node tracks,
  status-transition spans, rumor flow arrows) + schema validation,
  plus the round-15 host-timeline track (``add_host_timeline``).
- :mod:`ringpop_tpu.obs.histograms` — host half of the device latency
  histograms (ops.histogram): exact p50/p95/p99 extraction,
  ``hist.drain`` rows, and the ``computeProtocolDelay``-style adaptive
  period consumer.
- :mod:`ringpop_tpu.obs.perf` — dispatch timers around the compiled
  entry points (fenced, donation-safe, compile/execute split via the
  jit-cache probe), ``perf.phase`` rows and the shared bench
  warm-then-measure loop (``timed_window``).
- :mod:`ringpop_tpu.obs.exchange_stats` — host half of the round-17
  mesh exchange telemetry (ops.exchange counter/histogram planes):
  exact wire-byte pricing, ``mesh.exchange.drain`` rows, the
  ``sharded.exchange.*`` statsd keys, and the measured-vs-model
  ``traffic_reconcile`` verdict every drained window ships.
- :mod:`ringpop_tpu.obs.requests` — host half of the round-19 request
  observatory (models/route/reqtrace.py): sampled per-request record
  decoding, reconciliation against the device counters and
  RouteMetrics, per-key span trees, the Perfetto request-lifecycle
  export, and ``reqtrace.drain`` rows.
- :mod:`ringpop_tpu.obs.slo` — sliding-window SLO plane: ring-buffered
  per-window histogram deltas into windowed p50/p95/p99 + success
  rate, declarative targets with error-budget burn rate, schema-gated
  ``slo.window``/``slo.breach`` rows, and the burn-rate backpressure
  consumer hook.
- :mod:`ringpop_tpu.obs.xprof` — profiler trace harness:
  ``jax.profiler.trace`` capture with the warmup fenced outside the
  span, per-HLO-op self-time tables fuzzily keyed to COST_BUDGET
  entries, schema-gated ``xprof.capture`` rows (failures are rows,
  never exceptions).
"""

from ringpop_tpu.obs.recorder import (  # noqa: F401
    RunRecorder,
    read_run_log,
    validate_run_log,
)
from ringpop_tpu.obs.statsd_bridge import StatsdBridge  # noqa: F401
from ringpop_tpu.obs.prometheus import (  # noqa: F401
    render_ringpop_metrics,
    render_tick_series,
)
from ringpop_tpu.obs.sim_tap import SimTracerHost  # noqa: F401
from ringpop_tpu.obs.events import (  # noqa: F401
    decode_events,
    dissemination_summary,
    reconcile,
    rumor_wavefronts,
    scalable_wavefront_summary,
    validate_event_stream,
)
from ringpop_tpu.obs.chrome_trace import (  # noqa: F401
    add_host_timeline,
    export_chrome_trace,
    validate_chrome_trace,
    write_chrome_trace,
)
from ringpop_tpu.obs.histograms import (  # noqa: F401
    AdaptiveProtocolPeriod,
    compute_protocol_delay,
    summarize as summarize_histograms,
)
from ringpop_tpu.obs.perf import (  # noqa: F401
    DispatchTimer,
    timed_window,
    wrap_cluster,
)
