"""Chrome-trace (Perfetto-loadable) JSON export of flight-recorder runs.

Turns a decoded flight-recorder event stream (obs/events.py) into the
Trace Event Format consumed by ``chrome://tracing`` and
https://ui.perfetto.dev — one process for the simulated cluster, one
track (thread) per node:

- **status-transition spans**: each node's own liveness story (its view
  of ITSELF: alive / suspect / faulty / leave) renders as complete
  ``"X"`` span events on its track, so a churn wave reads as colored
  bands.
- **rumor flow arrows**: each rumor's dissemination renders as a flow
  (``"s"``/``"t"`` events, one flow id per rumor) from the origin node
  to every node's first-heard adoption — the epidemic wavefront as
  literal arrows across tracks.
- **instant events**: suspect/faulty verdicts, refutes, full syncs and
  joins as ``"i"`` instants; pings are opt-in (``include_pings``) —
  every tick emits N of them and Perfetto renders the rest fine without.

Times: one engine tick is one protocol period (``period_ms``), so
``ts = tick * period_ms * 1000`` microseconds.  The exporter is pure
host-side JSON assembly — no jax, no engine imports.
"""

from __future__ import annotations

import json
from typing import Any, Dict, Iterable, List, Optional

from ringpop_tpu.obs import events as ev

STATUS_NAMES = ("alive", "suspect", "faulty", "leave")

# stable Perfetto color names per status span
_STATUS_COLORS = {
    "alive": "good",
    "suspect": "bad",
    "faulty": "terrible",
    "leave": "grey",
}


def _ts(tick: int, period_ms: int) -> int:
    return int(tick) * int(period_ms) * 1000


def _status_name(code: int) -> str:
    return (
        STATUS_NAMES[code]
        if 0 <= code < len(STATUS_NAMES)
        else "status-%d" % code
    )


def export_chrome_trace(
    events: Any,
    n: int,
    period_ms: int = 200,
    addresses: Optional[List[str]] = None,
    include_pings: bool = False,
    pid: int = 1,
) -> Dict[str, Any]:
    """Decoded events -> a Trace Event Format dict (``json.dump`` ready).

    ``events`` accepts anything :func:`obs.events._as_arrays` does —
    the decoded dict list, the columnar arrays, or a raw (buf, head)
    pair."""
    arrs = ev._as_arrays(events)
    ticks = arrs["tick"]
    kinds = arrs["kind"]
    observers = arrs["observer"]
    subjects = arrs["subject"]
    new_status = arrs["new_status"]
    incs = arrs["inc"]
    auxes = arrs["aux"]
    out: List[Dict[str, Any]] = []

    # track metadata: one named thread per node
    out.append(
        {
            "ph": "M",
            "pid": pid,
            "tid": 0,
            "name": "process_name",
            "args": {"name": "ringpop-sim cluster (n=%d)" % n},
        }
    )
    for i in range(n):
        label = addresses[i] if addresses else "node %d" % i
        out.append(
            {
                "ph": "M",
                "pid": pid,
                "tid": i,
                "name": "thread_name",
                "args": {"name": label},
            }
        )

    max_tick = int(ticks.max()) if len(ticks) else 0
    end_ts = _ts(max_tick + 1, period_ms)

    # -- per-node self-status spans ------------------------------------
    # a node's own story: status events where observer == subject, plus
    # refutes (self re-assert alive).  Each transition closes the
    # previous span and opens the next; every node starts alive at 0.
    transitions: Dict[int, List] = {i: [(0, 0)] for i in range(n)}
    order = ticks.argsort(kind="stable")
    for i in order:
        k = int(kinds[i])
        o = int(observers[i])
        if o < 0 or o >= n:
            continue
        if k == ev.EV_STATUS and int(subjects[i]) == o:
            transitions[o].append((int(ticks[i]), int(new_status[i])))
        elif k == ev.EV_REFUTE:
            transitions[o].append((int(ticks[i]), 0))
    for node, trs in transitions.items():
        for j, (t0, status) in enumerate(trs):
            # collapse repeated same-status transitions
            if j > 0 and trs[j - 1][1] == status:
                continue
            t1 = next(
                (t for t, s in trs[j + 1 :] if s != status), max_tick + 1
            )
            name = _status_name(status)
            out.append(
                {
                    "ph": "X",
                    "pid": pid,
                    "tid": node,
                    "ts": _ts(t0, period_ms),
                    "dur": max(_ts(t1, period_ms) - _ts(t0, period_ms), 1),
                    "cat": "status",
                    "name": name,
                    "cname": _STATUS_COLORS.get(name),
                    "args": {"status": name},
                }
            )

    # -- rumor flow arrows ---------------------------------------------
    wavefronts = ev.rumor_wavefronts(arrs)
    flow_id = 0
    for rid, wf in sorted(wavefronts.items()):
        if len(wf["first_heard"]) < 2:
            continue
        flow_id += 1
        subject, status, inc = rid
        name = "rumor %s(%d)@%d" % (_status_name(status), subject, inc)
        origin = min(wf["first_heard"], key=lambda o: (wf["first_heard"][o], o))
        out.append(
            {
                "ph": "s",
                "pid": pid,
                "tid": origin,
                "ts": _ts(wf["birth"], period_ms),
                "cat": "rumor",
                "name": name,
                "id": flow_id,
            }
        )
        for o, t in sorted(wf["first_heard"].items()):
            if o == origin:
                continue
            out.append(
                {
                    "ph": "t",
                    "pid": pid,
                    "tid": o,
                    "ts": _ts(t, period_ms),
                    "cat": "rumor",
                    "name": name,
                    "id": flow_id,
                }
            )

    # -- protocol instants ---------------------------------------------
    _INSTANT = {
        ev.EV_SUSPECT: "suspect",
        ev.EV_FAULTY: "faulty",
        ev.EV_FULL_SYNC: "full-sync",
        ev.EV_REFUTE: "refute",
        ev.EV_JOIN: "join",
    }
    if include_pings:
        _INSTANT = dict(_INSTANT)
        _INSTANT[ev.EV_PING] = "ping"
    for i in range(len(ticks)):
        k = int(kinds[i])
        label = _INSTANT.get(k)
        if label is None:
            continue
        o = int(observers[i])
        if o < 0 or o >= n:
            continue
        out.append(
            {
                "ph": "i",
                "pid": pid,
                "tid": o,
                "ts": _ts(int(ticks[i]), period_ms),
                "s": "t",  # thread-scoped instant
                "cat": "protocol",
                "name": "%s(%d)" % (label, int(subjects[i])),
                "args": {
                    "subject": int(subjects[i]),
                    "inc": int(incs[i]),
                    "aux": int(auxes[i]),
                },
            }
        )

    return {
        "traceEvents": out,
        "displayTimeUnit": "ms",
        "otherData": {
            "generator": "ringpop_tpu.obs.chrome_trace",
            "n": n,
            "period_ms": period_ms,
            "end_ts_us": end_ts,
        },
    }


HOST_PID = 0  # the host-timeline process id (cluster tracks ride pid=1)


def add_host_timeline(
    trace: Dict[str, Any],
    timer,
    label: str = "host dispatch",
) -> Dict[str, Any]:
    """Merge a DispatchTimer's host-timeline track (obs.perf) into an
    exported flight trace IN PLACE: one ``pid=HOST_PID`` process with
    the per-phase dispatch spans, so Perfetto shows wall-clock host
    phases above the per-node protocol tracks.  Host spans are
    wall-relative (timer birth = 0) while the cluster tracks are
    tick-relative — the two clocks share an origin, not a rate, which
    is exactly what a dispatch-vs-protocol timeline wants to show.
    Returns the trace dict."""
    evs = trace.setdefault("traceEvents", [])
    evs.append(
        {
            "ph": "M",
            "pid": HOST_PID,
            "tid": 0,
            "name": "process_name",
            "args": {"name": label},
        }
    )
    evs.extend(timer.chrome_trace_events(pid=HOST_PID, tid=0))
    return trace


_KNOWN_PHASES = {"B", "E", "X", "i", "I", "M", "s", "t", "f", "C"}


def validate_chrome_trace(trace: Any) -> List[str]:
    """Minimal Trace Event Format schema check; returns problems (empty
    == valid).  Accepts the dict form or a JSON string/loaded list."""
    problems: List[str] = []
    if isinstance(trace, str):
        try:
            trace = json.loads(trace)
        except ValueError as e:
            return ["not JSON: %s" % e]
    if isinstance(trace, dict):
        evs = trace.get("traceEvents")
        if not isinstance(evs, list):
            return ["object form must carry a traceEvents list"]
    elif isinstance(trace, list):
        evs = trace
    else:
        return ["trace must be an object or array"]
    open_flows = set()
    for i, e in enumerate(evs):
        if not isinstance(e, dict):
            problems.append("event %d: not an object" % i)
            continue
        ph = e.get("ph")
        if ph not in _KNOWN_PHASES:
            problems.append("event %d: unknown phase %r" % (i, ph))
            continue
        if not isinstance(e.get("pid"), int) or not isinstance(
            e.get("tid"), int
        ):
            problems.append("event %d: pid/tid must be ints" % i)
        if ph != "M":
            ts = e.get("ts")
            if not isinstance(ts, (int, float)) or ts < 0:
                problems.append("event %d: bad ts %r" % (i, ts))
        if ph == "X":
            dur = e.get("dur")
            if not isinstance(dur, (int, float)) or dur <= 0:
                problems.append("event %d: X event needs dur > 0" % i)
        if ph in ("s", "t", "f"):
            fid = e.get("id")
            if fid is None:
                problems.append("event %d: flow event needs an id" % i)
            elif ph == "s":
                open_flows.add(fid)
            elif fid not in open_flows:
                problems.append(
                    "event %d: flow step id %r has no start" % (i, fid)
                )
        if ph == "M" and e.get("name") not in (
            "process_name",
            "thread_name",
            "process_labels",
            "thread_sort_index",
            "process_sort_index",
        ):
            problems.append(
                "event %d: unknown metadata name %r" % (i, e.get("name"))
            )
    return problems


def write_chrome_trace(trace: Dict[str, Any], path: str) -> str:
    """Validate + write; raises on schema problems so a broken exporter
    can never land an artifact."""
    problems = validate_chrome_trace(trace)
    if problems:
        raise ValueError(
            "chrome trace failed validation:\n" + "\n".join(problems)
        )
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(trace, fh, sort_keys=True)
    return path
