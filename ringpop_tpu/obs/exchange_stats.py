"""Host half of the mesh exchange telemetry (ISSUE 16 tentpole a+b).

Device half: the ``exch``/``exch_hist`` planes on
:class:`ringpop_tpu.models.sim.engine_scalable.ScalableState` — per-shard
uint32 counters in :data:`ringpop_tpu.ops.exchange.EXCH_COUNTERS` order
plus per-direction cap-utilization log2 histograms — accumulated either
by the metrics-carrying shard_map plane
(``parallel.mesh.make_exchange_plane(metrics=True)``) or the inline twin
(``engine_scalable._exchange_obs_update``).  This module drains those
counters to the host, prices the wire bytes exactly
(:func:`ringpop_tpu.ops.exchange.drain_exchange_counters`), logs one
``mesh.exchange.drain`` runlog row per shard, emits
``sharded.exchange.*`` statsd keys, and reconciles the measured bytes
against the analytic traffic model
(:func:`ringpop_tpu.ops.exchange.cross_shard_traffic_bytes`) — the
(S-1)/S cross-fraction claim as a checked number, gated by
scripts/check_traffic_model.py against the committed TRAFFIC_BUDGET.json.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from ringpop_tpu.obs import histograms as oh
from ringpop_tpu.ops import exchange as _exch

# the runlog event name (field set pinned by scripts/check_metrics_schema
# and tests/obs/test_runlog_schema.py in lockstep with
# ExchangeMetrics._fields)
EXCHANGE_DRAIN_EVENT = "mesh.exchange.drain"
# extras every drain row carries next to the ExchangeMetrics fields
EXCHANGE_DRAIN_EXTRAS = ("source", "shards", "w", "cap", "local_rows")
# one measured-vs-model reconciliation row per drained window (the
# reconcile() dict + a source tag; schema-gated like the drain rows)
TRAFFIC_RECONCILE_EVENT = "traffic_reconcile"

# counter fields summed across shards into the drain summary's totals
# (every ExchangeMetrics field except the shard id)
TOTAL_FIELDS = tuple(
    f for f in _exch.ExchangeMetrics._fields if f != "shard"
)


def totals(rows: Sequence[_exch.ExchangeMetrics]) -> Dict[str, int]:
    """Cross-shard sums of every counter field (+ ``shards``): the
    aggregate view the statsd bridge and the traffic gate consume."""
    out: Dict[str, int] = {"shards": len(rows)}
    for f in TOTAL_FIELDS:
        out[f] = int(sum(getattr(r, f) for r in rows))
    return out


def measured_interconnect_bytes(tot: Dict[str, int]) -> int:
    """Wire bytes that actually crossed shard boundaries: the drained
    byte totals price the FULL a2a/all-gather buffers (every slot,
    self-shard bucket included); exactly the (S-1)/S cross fraction of
    those slots leaves the source shard — the same fraction the
    analytic model charges (``cross_shard_traffic_bytes``)."""
    s = int(tot["shards"])
    if s <= 1:
        return 0
    full = int(tot["wire_bytes_pull"]) + int(tot["wire_bytes_push"])
    # exact: full is a multiple of s by construction (s buckets/shard)
    return full * (s - 1) // s


def reconcile(
    tot: Dict[str, int],
    *,
    n: int,
    w: int,
    cap: Optional[int] = None,
) -> Dict[str, object]:
    """Measured-vs-model interconnect reconciliation for one drained
    window: measured bytes (from the device counters) against
    ``cross_shard_traffic_bytes(...)["interconnect_total"] * ticks``.
    Exact equality (ratio 1.0) whenever every trip took the a2a path;
    fallback trips are surfaced so the gate can band or forbid them."""
    s = int(tot["shards"])
    ticks = int(tot["ticks"]) // s if s else 0
    model = _exch.cross_shard_traffic_bytes(n, w, s, cap=cap)
    model_bytes = int(model["interconnect_total"]) * ticks
    measured = measured_interconnect_bytes(tot)
    return {
        "shards": s,
        "n": int(n),
        "w": int(w),
        "cap": int(model["cap"]),
        "ticks": ticks,
        "measured_interconnect": measured,
        "model_interconnect": model_bytes,
        "ratio": (measured / model_bytes) if model_bytes else None,
        "fallback_trips": int(tot["fallback_pull"])
        + int(tot["fallback_push"]),
    }


def drain(
    counters,
    hist=None,
    *,
    w: int,
    local_rows: int,
    source: str,
    cap: Optional[int] = None,
    recorder=None,
    statsd=None,
    qs: Sequence[float] = oh.DEFAULT_QS,
) -> Dict[str, object]:
    """The ONE host half of every driver's ``drain_exchange_metrics()``
    (ShardedStorm and the single-device ScalableCluster twin): price the
    device counters into per-shard :class:`ExchangeMetrics` rows, log
    one ``mesh.exchange.drain`` event per shard on ``recorder``, emit
    the summed ``sharded.exchange.*`` keys through ``statsd``, and
    return ``{"shards": [row dicts], "totals": {...}, "cap_util":
    {...}}``.  Sinks run before any caller-side reset — a raising sink
    leaves the window on device for a retry (the drain_events
    contract, same as obs.histograms.drain)."""
    counters = np.asarray(counters)
    rows = _exch.drain_exchange_counters(
        counters, w=w, cap=cap, local_rows=local_rows
    )
    shards = len(rows)
    cap_r = (
        _exch.exchange_cap(local_rows, shards) if cap is None else int(cap)
    )
    cap_util = (
        None
        if hist is None
        else oh.summarize_batched(
            np.asarray(hist), _exch.EXCH_HIST_TRACKS, qs
        )
    )
    tot = totals(rows)
    # n is recoverable from the drain identity (local_rows x shards), so
    # every drained window ships its own measured-vs-model verdict
    rec = reconcile(tot, n=int(local_rows) * shards, w=w, cap=cap)
    if recorder is not None:
        for r in rows:
            recorder.record_event(
                EXCHANGE_DRAIN_EVENT,
                source=source,
                shards=shards,
                w=int(w),
                cap=cap_r,
                local_rows=int(local_rows),
                **r._asdict(),
            )
        recorder.record_event(
            TRAFFIC_RECONCILE_EVENT, source=source, **rec
        )
    if statsd is not None:
        statsd.emit_exchange_drain(tot)
        if cap_util is not None:
            from ringpop_tpu.obs.statsd_bridge import EXCHANGE_HIST_KEYS

            statsd.emit_hist_summary(cap_util, key_map=EXCHANGE_HIST_KEYS)
    return {
        "shards": [r._asdict() for r in rows],
        "totals": tot,
        "cap_util": cap_util,
        "reconcile": rec,
    }


__all__: List[str] = [
    "EXCHANGE_DRAIN_EVENT",
    "EXCHANGE_DRAIN_EXTRAS",
    "TOTAL_FIELDS",
    "TRAFFIC_RECONCILE_EVENT",
    "drain",
    "measured_interconnect_bytes",
    "reconcile",
    "totals",
]
