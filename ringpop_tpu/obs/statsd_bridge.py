"""Statsd bridge: device-side tick counters -> reference statsd keys.

The reference emits a statsd stat on every protocol action through
``RingPop.stat()``'s per-key fq-name cache (index.js:527-541), with keys
namespaced ``ringpop.<host_port with . and : -> _>.<key>``
(index.js:162-164).  The simulation engines compute the same counters on
device (``TickMetrics``/``ScalableMetrics``); this bridge replays a
recorded tick (or a whole stacked series) onto a statsd client under the
reference's key names, so existing dashboards/collectors written against
ringpop-node keys read the simulated cluster unchanged.
"""

from __future__ import annotations

import re
from typing import Any, Dict, Optional, Tuple

# TickMetrics / ScalableMetrics field -> (stat_type, reference key).
# Key names follow the reference emission sites: ping/ping-req send+recv
# (ping-sender.js, server/protocol/*.js), full-sync
# (dissemination.js:101-114), membership-update.<status>
# (on_membership_event.js), refuted-update (member.js:76-81), join
# completion (join-sender.js).  Fields with no reference analog (the
# sim-only diagnostics) ride under the "sim." namespace.
TICK_KEY_MAP: Dict[str, Tuple[str, str]] = {
    # full-fidelity engine (TickMetrics)
    "pings_sent": ("increment", "ping.send"),
    "pings_delivered": ("increment", "ping.recv"),
    "ping_reqs": ("increment", "ping-req.send"),
    "full_syncs": ("increment", "full-sync"),
    "changes_applied": ("increment", "changes.apply"),
    "suspects_marked": ("increment", "membership-update.suspect"),
    "faulties_marked": ("increment", "membership-update.faulty"),
    "refutes": ("increment", "refuted-update"),
    "piggyback_drops": ("increment", "changes.drop"),
    "full_sync_records": ("increment", "full-sync.records"),
    "ping_req_inconclusive": ("increment", "ping-req.inconclusive"),
    "join_merges": ("increment", "join.complete"),
    "distinct_checksums": ("gauge", "checksums.distinct"),
    "dirty_rows": ("gauge", "sim.checksum.dirty-rows"),
    "parity_overflow": ("increment", "sim.parity.overflow"),
    # scalable engine (ScalableMetrics) — shared fields above apply too
    "live_nodes": ("gauge", "num-members"),
    "active_rumors": ("gauge", "sim.rumors.active"),
    "suspects_published": ("increment", "membership-update.suspect"),
    "faulties_published": ("increment", "membership-update.faulty"),
    "refutes_published": ("increment", "refuted-update"),
    "leaves_published": ("increment", "membership-update.leave"),
    "rumors_retired": ("increment", "changes.drop"),
    "mean_heard_frac": ("gauge", "sim.rumors.mean-heard-frac"),
    # routing plane (RouteMetrics, models/route/plane.py) — mapped onto
    # the reference's requestProxy.* emission sites (send.js:91-208,
    # request-proxy/index.js:186-193); ring-maintenance diagnostics ride
    # the sim. namespace
    "route_queries": ("increment", "requestProxy.requests.outgoing"),
    "route_misroutes": ("increment", "sim.route.misroutes"),
    "route_reroute_local": ("increment", "requestProxy.retry.reroute.local"),
    "route_reroute_remote": (
        "increment",
        "requestProxy.retry.reroute.remote",
    ),
    "route_keys_diverged": ("increment", "requestProxy.retry.aborted"),
    "route_checksums_differ": ("increment", "requestProxy.checksumsDiffer"),
    "route_checksum_rejects": ("increment", "sim.route.checksum-rejects"),
    "route_ring_changed": ("increment", "sim.route.ring.changed-servers"),
    "route_ring_dirty_buckets": ("gauge", "sim.route.ring.dirty-buckets"),
    "route_ring_full_rebuilds": ("increment", "sim.route.ring.full-rebuilds"),
    "route_ring_points": ("gauge", "sim.route.ring.points"),
}

# Host-side phase timers (obs.perf DispatchTimer) -> reference TIMING
# keys (statsd ``|ms`` wire type).  The reference's getStats surfaces
# protocol-period duration, ping round-trip and checksum-computation
# timing histograms (SURVEY "Protocol timing profiling": protocol.delay
# / ping / compute-checksum); our host phases map onto them — one
# scanned/jitted tick IS one ping round, and the adaptive-period
# consumer emits the computed protocol.delay.  Unmapped phases ride
# ``sim.perf.<phase>``.
PERF_TIMER_KEYS: Dict[str, str] = {
    "tick": "ping",
    "scan": "ping",
    "checksum": "compute-checksum",
    "protocol_delay": "protocol.delay",
}

# Device-side latency-histogram tracks (ops.histogram, drained via
# obs.histograms) -> timing keys.  Reference analogs where they exist
# (requestProxy retry accounting, send.js:91-208); sim-only
# distributions ride the sim. namespace.  Values are in TICKS for the
# engine tracks (one tick == one protocol period) and counts for the
# routing tracks; the |ms wire type is kept so statsd dashboards
# aggregate them as timer series like the reference's.
HIST_TIMER_KEYS: Dict[str, str] = {
    # engines
    "rumor_age": "dissemination.rumor-age",
    "retired_age": "dissemination.rumor-retired-age",
    "suspicion_duration": "membership-update.suspicion-duration",
    "dirty_rows": "sim.checksum.dirty-rows.dist",
    # routing plane
    "retry_depth": "requestProxy.retry.depth",
    "reroute_hops": "requestProxy.hops",
    "dirty_buckets": "sim.route.ring.dirty-buckets.dist",
}

# Mesh exchange telemetry (obs.exchange_stats.drain over the device-side
# ExchangeMetrics counters, ISSUE 16): cross-shard SUMS emit as deltas
# under ``sharded.exchange.*`` — the mesh-collective analog of the
# reference's per-instance ringpop.<host_port>.* discipline; the shard
# count rides as a gauge.  Keys are keyed by ExchangeMetrics field name
# (lockstep pinned in tests/obs/test_statsd_bridge.py).
EXCHANGE_KEY_MAP: Dict[str, Tuple[str, str]] = {
    "ticks": ("increment", "sharded.exchange.ticks"),
    "a2a_pull": ("increment", "sharded.exchange.a2a.pull"),
    "a2a_push": ("increment", "sharded.exchange.a2a.push"),
    "fallback_pull": ("increment", "sharded.exchange.fallback.pull"),
    "fallback_push": ("increment", "sharded.exchange.fallback.push"),
    "pull_rows": ("increment", "sharded.exchange.rows.pull"),
    "push_rows": ("increment", "sharded.exchange.rows.push"),
    "dest_shards_pull": ("increment", "sharded.exchange.spread.pull"),
    "dest_shards_push": ("increment", "sharded.exchange.spread.push"),
    "wire_bytes_pull": ("increment", "sharded.exchange.wire-bytes.pull"),
    "wire_bytes_push": ("increment", "sharded.exchange.wire-bytes.push"),
    "shards": ("gauge", "sharded.exchange.shards"),
}

# Cap-utilization histogram tracks (EXCH_HIST_TRACKS) -> timer keys for
# emit_hist_summary (statsd ``|ms`` wire type, like HIST_TIMER_KEYS).
EXCHANGE_HIST_KEYS: Dict[str, str] = {
    "cap_util_pull": "sharded.exchange.cap-util.pull",
    "cap_util_push": "sharded.exchange.cap-util.push",
}

# Profiler trace harness (obs.xprof): capture wall time emits as a TIMER
# (|ms), the attributed-op count as a gauge.
XPROF_KEY_MAP: Dict[str, Tuple[str, str]] = {
    "wall_s": ("timing", "xprof.capture"),
    "ops": ("gauge", "xprof.ops"),
}

# Request-trace drain telemetry (obs.requests.drain over the device-side
# sampled per-request buffer, ISSUE 19): the drained record/drop volume
# and the sampled-subset counters emit as deltas under the sim.reqtrace
# namespace (the reference's per-request requestProxy stats are already
# claimed by the RouteMetrics rows above — these are the SAMPLED view);
# the configured sampling rate rides as a gauge.  Counter keys are keyed
# by obs.requests.COUNT_FIELDS name (lockstep pinned in
# tests/obs/test_statsd_bridge.py).
REQTRACE_KEY_MAP: Dict[str, Tuple[str, str]] = {
    "records": ("increment", "sim.reqtrace.records"),
    "drops": ("increment", "sim.reqtrace.drops"),
    "sample_log2": ("gauge", "sim.reqtrace.sample-log2"),
    "queries": ("increment", "sim.reqtrace.sampled.queries"),
    "misroutes": ("increment", "sim.reqtrace.sampled.misroutes"),
    "reroute_local": ("increment", "sim.reqtrace.sampled.reroute.local"),
    "reroute_remote": ("increment", "sim.reqtrace.sampled.reroute.remote"),
    "keys_diverged": ("increment", "sim.reqtrace.sampled.keys-diverged"),
    "checksums_differ": (
        "increment",
        "sim.reqtrace.sampled.checksums-differ",
    ),
    "checksum_rejects": (
        "increment",
        "sim.reqtrace.sampled.checksum-rejects",
    ),
}

# Sliding-window SLO plane (obs.slo.SLOWindowPlane): per-window rows
# emit under ``slo.<target>.<suffix>`` — windowed percentiles as TIMER
# samples (|ms wire type, matching the histogram-summary discipline),
# health ratios as gauges, breaches as counters.  Suffixes are keyed by
# slo.window row field (lockstep pinned in
# tests/obs/test_statsd_bridge.py).
SLO_KEY_MAP: Dict[str, Tuple[str, str]] = {
    "p50": ("timing", "p50"),
    "p95": ("timing", "p95"),
    "p99": ("timing", "p99"),
    "success_rate": ("gauge", "success-rate"),
    "burn_rate": ("gauge", "burn-rate"),
    "queries": ("increment", "window.queries"),
    "errors": ("increment", "window.errors"),
}
SLO_BREACH_KEY = "breach"

# Recovery-plane lifecycle counters (models/sim/recovery.py): emitted by
# CheckpointManager directly (they are per-event, not per-tick, so they
# ride their own map rather than TICK_KEY_MAP).  The reference has no
# checkpoint analog — a restarted ringpop rebuilds via join full-sync —
# so these live under the sim. namespace.
CKPT_KEY_MAP: Dict[str, str] = {
    "ckpt.saved": "sim.ckpt.saved",
    "ckpt.corrupt": "sim.ckpt.corrupt",
    "ckpt.resumed": "sim.ckpt.resumed",
    "ckpt.gc": "sim.ckpt.gc",
}


def stat_prefix(host_port: str) -> str:
    """The reference's stats identity: ``ringpop.<host_port>`` with
    non-alphanumeric separators flattened (index.js:162-164) — must stay
    in lockstep with ``Ringpop.__init__``."""
    return "ringpop.%s" % re.sub(r"[.:]", "_", host_port)


class StatsdBridge:
    """Emits tick counters through a ``Ringpop.stat``-style sink.

    Construct with a live facade (``StatsdBridge(ringpop=rp)`` — every
    emission rides ``rp.stat()`` and therefore its fq-key cache), or
    standalone with ``StatsdBridge(statsd=client, host_port="h:p")``,
    which replicates the same ``ringpop.<host_port>.`` scheme for
    simulation runs that have no facade.
    """

    def __init__(
        self,
        ringpop: Any = None,
        statsd: Any = None,
        host_port: Optional[str] = None,
        key_map: Optional[Dict[str, Tuple[str, str]]] = None,
    ):
        if ringpop is None and (statsd is None or host_port is None):
            raise ValueError("need ringpop=, or statsd= AND host_port=")
        self.key_map = dict(key_map or TICK_KEY_MAP)
        if ringpop is not None:
            self._stat = ringpop.stat
        else:
            prefix = stat_prefix(host_port)
            fq: Dict[str, str] = {}

            def _stat(stat_type: str, key: str, value: Any = None) -> None:
                fq_key = fq.get(key)
                if fq_key is None:
                    fq_key = fq[key] = "%s.%s" % (prefix, key)
                if stat_type == "increment":
                    statsd.increment(
                        fq_key, value if value is not None else 1
                    )
                elif stat_type == "gauge":
                    statsd.gauge(fq_key, value)
                elif stat_type == "timing":
                    statsd.timing(fq_key, value)

            self._stat = _stat

    def increment(self, key: str, value: int = 1) -> None:
        """Emit one COUNTER delta under the bridge's fq-key scheme — the
        public seam for driver-level aggregate counts (the mesh exchange
        drain's summed ``sharded.exchange.*`` deltas)."""
        self._stat("increment", key, int(value))

    def gauge(self, key: str, value) -> None:
        """Emit one gauge under the bridge's fq-key scheme — the public
        seam for driver-level one-shot stats (e.g. the mesh driver's
        ``sharded.exchange.*`` resolution note, round 14) so callers
        never reach into the internal ``_stat`` dispatch."""
        self._stat("gauge", key, value)

    def timing(self, key: str, value) -> None:
        """Emit one TIMER sample (statsd ``|ms`` wire type) under the
        bridge's fq-key scheme — the reference emits its protocol.delay
        / ping / compute-checksum timing histograms this way (getStats
        timing keys).  The bridge was counters/gauges-only before the
        performance observatory (round 15)."""
        self._stat("timing", key, value)

    def emit_hist_summary(
        self,
        summary: Dict[str, Dict[str, Any]],
        key_map: Optional[Dict[str, str]] = None,
    ) -> int:
        """Drained device-histogram summaries (obs.histograms.summarize)
        -> timer keys: per track, the p50/p95/p99 upper bounds emit as
        ``<key>.p50`` / ``.p95`` / ``.p99`` timing samples (empty tracks
        emit nothing).  Track names map through ``key_map`` (default
        HIST_TIMER_KEYS; unmapped tracks ride ``sim.hist.<track>``).
        Returns the number of emissions."""
        key_map = HIST_TIMER_KEYS if key_map is None else key_map
        emitted = 0
        for track, stats in summary.items():
            key = key_map.get(track, "sim.hist.%s" % track)
            for q in ("p50", "p95", "p99"):
                v = stats.get(q)
                if v is not None:
                    self.timing("%s.%s" % (key, q), v)
                    emitted += 1
        return emitted

    def emit_exchange_drain(
        self,
        tot: Dict[str, Any],
        key_map: Optional[Dict[str, Tuple[str, str]]] = None,
    ) -> int:
        """One drained exchange-telemetry window's cross-shard totals
        (obs.exchange_stats.totals) -> ``sharded.exchange.*``: counters
        emit only when nonzero (statsd increments are deltas), the shard
        count always emits as a gauge.  Returns the number of
        emissions."""
        key_map = EXCHANGE_KEY_MAP if key_map is None else key_map
        emitted = 0
        for field, value in tot.items():
            mapped = key_map.get(field)
            if mapped is None:
                continue
            stat_type, key = mapped
            if stat_type == "increment":
                if value:
                    self.increment(key, int(value))
                    emitted += 1
            else:
                self._stat(stat_type, key, value)
                emitted += 1
        return emitted

    def emit_reqtrace_drain(
        self,
        row: Dict[str, Any],
        key_map: Optional[Dict[str, Tuple[str, str]]] = None,
    ) -> int:
        """One drained request-trace window (obs.requests.drain_row) ->
        ``sim.reqtrace.*``: record/drop volume and the sampled-subset
        counters emit only when nonzero (statsd increments are deltas);
        the sampling rate always emits as a gauge.  Returns the number
        of emissions."""
        key_map = REQTRACE_KEY_MAP if key_map is None else key_map
        flat = dict(row)
        flat.update(flat.pop("counts", {}) or {})
        emitted = 0
        for field, value in flat.items():
            mapped = key_map.get(field)
            if mapped is None:
                continue
            stat_type, key = mapped
            if stat_type == "increment":
                if value:
                    self.increment(key, int(value))
                    emitted += 1
            else:
                self._stat(stat_type, key, value)
                emitted += 1
        return emitted

    def emit_slo_window(
        self,
        row: Dict[str, Any],
        key_map: Optional[Dict[str, Tuple[str, str]]] = None,
    ) -> int:
        """One ``slo.window`` row (obs.slo.SLOWindowPlane.window_row) ->
        ``slo.<target>.<suffix>``: windowed percentiles as timer samples
        (empty windows skip them), success/burn rates as gauges, window
        query/error volume as counter deltas.  Returns the number of
        emissions."""
        key_map = SLO_KEY_MAP if key_map is None else key_map
        prefix = "slo.%s" % row["target"]
        emitted = 0
        for field, (stat_type, suffix) in key_map.items():
            value = row.get(field)
            if value is None:
                continue
            key = "%s.%s" % (prefix, suffix)
            if stat_type == "increment":
                if value:
                    self.increment(key, int(value))
                    emitted += 1
            else:
                self._stat(stat_type, key, value)
                emitted += 1
        return emitted

    def emit_slo_breach(self, target: str) -> None:
        """One SLO breach -> ``slo.<target>.breach`` counter tick."""
        self.increment("slo.%s.%s" % (target, SLO_BREACH_KEY))

    def emit_tick(self, row: Any) -> int:
        """One tick's metrics (NamedTuple or dict).  Counters emit only
        when nonzero (statsd increments are deltas); gauges always emit.
        A [B]-vector value (the vmapped driver's per-cluster axis) is
        summed for counters — aggregate events across the batch — and
        skipped for gauges, which have no single-key meaning there.
        Returns the number of emissions."""
        if hasattr(row, "_asdict"):
            row = row._asdict()
        emitted = 0
        for field, value in row.items():
            mapped = self.key_map.get(field)
            if mapped is None:
                continue
            stat_type, key = mapped
            if getattr(value, "ndim", 0) > 0:
                if stat_type != "increment":
                    continue
                value = value.sum()
            if hasattr(value, "item"):
                value = value.item()
            if isinstance(value, bool):
                value = int(value)
            if not isinstance(value, (int, float)):
                continue
            if stat_type == "increment":
                if value:
                    self._stat("increment", key, int(value))
                    emitted += 1
            else:
                self._stat(stat_type, key, value)
                emitted += 1
        return emitted

    def emit_series(self, metrics: Any) -> int:
        """A stacked [T]- (or vmapped [T, B]-) series, as the scan
        drivers return: emits every tick in order.  Returns total
        emissions."""
        from ringpop_tpu.obs.recorder import iter_tick_rows

        return sum(self.emit_tick(row) for row in iter_tick_rows(metrics))
