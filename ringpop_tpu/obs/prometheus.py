"""Prometheus text exposition (text format 0.0.4) for live nodes and
recorded runs.

The reference exposes its state over ``/admin/stats`` as a JSON blob;
modern collectors want the Prometheus text format instead, so the
``/admin/metrics`` channel endpoint (api/server.py) renders the same
state — request-rate meters, membership, protocol timing, ring size —
as ``# HELP``/``# TYPE``-annotated samples.  No client library exists in
the image, so the renderer is a minimal purpose-built writer.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple


def _escape_label(v: Any) -> str:
    return (
        str(v)
        .replace("\\", "\\\\")
        .replace("\n", "\\n")
        .replace('"', '\\"')
    )


def _escape_help(v: Any) -> str:
    """HELP-line escaping per the exposition format 0.0.4: backslash and
    newline only (double quotes are NOT escaped outside label values).
    Unescaped, a newline in help text would split the line and corrupt
    every sample after it."""
    return str(v).replace("\\", "\\\\").replace("\n", "\\n")


def _fmt_value(v: Any) -> str:
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, float):
        return repr(v)
    return str(v)


class PromWriter:
    """Accumulates samples; renders the exposition text.

    Samples are buffered per metric family and rendered grouped (HELP,
    TYPE, then every sample of that family), in first-seen family order:
    the text format requires all lines of one metric to form a single
    group, even when the caller interleaves families (e.g. a per-plane
    loop emitting two families per iteration)."""

    def __init__(self) -> None:
        self._families: Dict[str, Dict[str, Any]] = {}
        self._order: List[str] = []

    def sample(
        self,
        name: str,
        value: Any,
        help_: Optional[str] = None,
        type_: str = "gauge",
        labels: Optional[Dict[str, Any]] = None,
    ) -> None:
        if value is None:
            return
        fam = self._families.get(name)
        if fam is None:
            fam = self._families[name] = {
                "help": help_,
                "type": type_,
                "samples": [],
            }
            self._order.append(name)
        label_str = ""
        if labels:
            label_str = "{%s}" % ",".join(
                '%s="%s"' % (k, _escape_label(v))
                for k, v in sorted(labels.items())
            )
        fam["samples"].append(
            "%s%s %s" % (name, label_str, _fmt_value(value))
        )

    def histogram(
        self,
        name: str,
        bucket_counts: Any,
        help_: Optional[str] = None,
        labels: Optional[Dict[str, Any]] = None,
        sum_value: Optional[float] = None,
    ) -> None:
        """One native Prometheus histogram family from log2 bucket
        counts (ops.histogram layout: bucket 0 holds {0}, bucket b holds
        [2^(b-1), 2^b-1]).

        Renders the cumulative ``<name>_bucket{le="..."}`` series — one
        line per log2 bucket up to the last occupied one, bounds at the
        bucket upper edges, plus the mandatory ``le="+Inf"`` line — and
        the ``<name>_sum`` / ``<name>_count`` samples.  The true sum is
        not recoverable from bucket counts, so ``_sum`` defaults to the
        conservative upper-bound estimate ``sum(count * bucket_hi)``
        unless the caller tracked it (``sum_value``)."""
        from ringpop_tpu.ops import histogram as hg

        counts = [int(c) for c in bucket_counts]
        fam = self._families.get(name)
        if fam is None:
            fam = self._families[name] = {
                "help": help_,
                "type": "histogram",
                "samples": [],
            }
            self._order.append(name)

        def label_str(extra: Optional[Dict[str, Any]] = None) -> str:
            merged = dict(labels or {})
            if extra:
                merged.update(extra)
            if not merged:
                return ""
            return "{%s}" % ",".join(
                '%s="%s"' % (k, _escape_label(v))
                for k, v in sorted(merged.items())
            )

        last = max((b for b, c in enumerate(counts) if c), default=0)
        cum = 0
        for b in range(last + 1):
            cum += counts[b]
            fam["samples"].append(
                "%s_bucket%s %d"
                % (name, label_str({"le": str(hg.bucket_hi(b))}), cum)
            )
        total = sum(counts)
        fam["samples"].append(
            "%s_bucket%s %d" % (name, label_str({"le": "+Inf"}), total)
        )
        if sum_value is None:
            sum_value = float(
                sum(c * hg.bucket_hi(b) for b, c in enumerate(counts))
            )
        fam["samples"].append(
            "%s_sum%s %s" % (name, label_str(), _fmt_value(sum_value))
        )
        fam["samples"].append(
            "%s_count%s %d" % (name, label_str(), total)
        )

    def render(self) -> str:
        lines: List[str] = []
        for name in self._order:
            fam = self._families[name]
            if fam["help"]:
                lines.append(
                    "# HELP %s %s" % (name, _escape_help(fam["help"]))
                )
            lines.append("# TYPE %s %s" % (name, fam["type"]))
            lines.extend(fam["samples"])
        return "\n".join(lines) + ("\n" if lines else "")


def render_ringpop_metrics(ringpop: Any) -> str:
    """The ``/admin/metrics`` body for a live node: meters, membership,
    protocol histogram, ring and dissemination state."""
    w = PromWriter()
    labels = {"app": ringpop.app, "instance": ringpop.whoami()}

    import time as _time

    w.sample(
        "ringpop_uptime_seconds",
        (_time.time() - ringpop.start_time) if ringpop.start_time else 0.0,
        "Seconds since bootstrap completed",
        "gauge",
        labels,
    )
    w.sample(
        "ringpop_ready",
        1 if ringpop.is_ready else 0,
        "1 once bootstrap completed",
        "gauge",
        labels,
    )

    for plane, meter in (
        ("client", ringpop.client_rate),
        ("server", ringpop.server_rate),
        ("total", ringpop.total_rate),
    ):
        d = meter.to_dict()
        plane_labels = dict(labels, plane=plane)
        w.sample(
            "ringpop_requests_total",
            d["count"],
            "Requests seen per plane (index.js:158-160 meters)",
            "counter",
            plane_labels,
        )
        w.sample(
            "ringpop_request_rate_1m",
            d["m1"],
            "1-minute EWMA request rate",
            "gauge",
            plane_labels,
        )

    membership = ringpop.membership
    w.sample(
        "ringpop_members",
        len(membership.members),
        "Known members in the membership list",
        "gauge",
        labels,
    )
    status_counts: Dict[str, int] = {}
    for m in membership.members:
        status_counts[m.status] = status_counts.get(m.status, 0) + 1
    for status, count in sorted(status_counts.items()):
        w.sample(
            "ringpop_members_by_status",
            count,
            "Known members by SWIM status",
            "gauge",
            dict(labels, status=status),
        )
    if membership.checksum is not None:
        w.sample(
            "ringpop_membership_checksum",
            membership.checksum,
            "FarmHash32 membership checksum (membership/index.js:48-75)",
            "gauge",
            labels,
        )

    w.sample(
        "ringpop_ring_servers",
        len(ringpop.ring.servers),
        "Servers currently on the consistent-hash ring",
        "gauge",
        labels,
    )
    if getattr(ringpop.ring, "checksum", None) is not None:
        w.sample(
            "ringpop_ring_checksum",
            ringpop.ring.checksum,
            "Checksum over sorted ring server names",
            "gauge",
            labels,
        )

    # protocol-period timing histogram (gossip/index.js:37,52-55)
    proto = ringpop.gossip.get_stats()
    timing = proto.get("protocolTiming") or {}
    for q in ("p50", "p95", "p99"):
        w.sample(
            "ringpop_protocol_period_ms",
            timing.get(q),
            "Protocol period duration percentiles",
            "gauge",
            dict(labels, quantile=q),
        )
    w.sample(
        "ringpop_protocol_periods_total",
        proto.get("protocolPeriods"),
        "Protocol periods completed",
        "counter",
        labels,
    )
    w.sample(
        "ringpop_changes_disseminated_total",
        proto.get("numChangesDisseminated"),
        "Membership changes disseminated on gossip bodies",
        "counter",
        labels,
    )

    # dissemination pressure
    dissemination = getattr(ringpop, "dissemination", None)
    if dissemination is not None:
        w.sample(
            "ringpop_dissemination_changes",
            len(getattr(dissemination, "changes", {}) or {}),
            "Changes pending dissemination",
            "gauge",
            labels,
        )
    return w.render()


# -- recorded-run rendering ------------------------------------------------

_COUNTERISH = (
    "pings_sent",
    "pings_delivered",
    "ping_reqs",
    "full_syncs",
    "changes_applied",
    "suspects_marked",
    "faulties_marked",
    "refutes",
    "piggyback_drops",
    "full_sync_records",
    "ping_req_inconclusive",
    "join_merges",
    "parity_overflow",
    "suspects_published",
    "faulties_published",
    "refutes_published",
    "leaves_published",
    "rumors_retired",
    "dirty_rows",
)


def render_device_histograms(
    hist: Any,
    tracks: Any,
    prefix: str = "ringpop_sim_",
    labels: Optional[Dict[str, Any]] = None,
) -> str:
    """Prometheus text for a drained device histogram bank: one native
    histogram family per track (``<prefix><track>``), rendered from the
    [len(tracks), NBUCKETS] log2 bucket counts the engines carry
    (obs.histograms drain layout)."""
    import numpy as np

    arr = np.asarray(hist)
    w = PromWriter()
    for i, track in enumerate(tracks):
        w.histogram(
            prefix + str(track),
            arr[i],
            "Device-side log2 histogram track %s" % track,
            labels,
        )
    return w.render()


def render_slo_plane(
    plane: Any,
    tick: int = 0,
    prefix: str = "ringpop_slo_",
    labels: Optional[Dict[str, Any]] = None,
) -> str:
    """Prometheus text for one obs.slo.SLOWindowPlane: the pooled
    sliding-window bucket counts as a native histogram plus the window
    row's health gauges (success rate, burn rate, breach flag) and
    volume counters, all labeled by SLO target name."""
    w = PromWriter()
    row = plane.window_row(tick)
    slo_labels = dict(labels or {}, target=row["target"])
    w.histogram(
        prefix + "window",
        plane.window_counts(),
        "Pooled sliding-window observations feeding the SLO verdict",
        slo_labels,
    )
    w.sample(
        prefix + "window_queries",
        row["queries"],
        "Requests in the sliding window",
        "gauge",
        slo_labels,
    )
    w.sample(
        prefix + "window_errors",
        row["errors"],
        "Failed requests in the sliding window",
        "gauge",
        slo_labels,
    )
    w.sample(
        prefix + "success_rate",
        row["success_rate"],
        "Windowed success rate",
        "gauge",
        slo_labels,
    )
    w.sample(
        prefix + "burn_rate",
        row["burn_rate"],
        "Error-budget burn rate (1.0 = sustainable)",
        "gauge",
        slo_labels,
    )
    w.sample(
        prefix + "breach",
        1 if row["breach"] else 0,
        "1 while the sliding window violates the SLO",
        "gauge",
        slo_labels,
    )
    return w.render()


def render_tick_series(
    metrics: Any,
    prefix: str = "ringpop_sim_",
    labels: Optional[Dict[str, Any]] = None,
) -> str:
    """Prometheus text for a stacked metrics series (or one tick):
    counter fields render as window totals (``<prefix><field>_total``),
    everything else as last-value gauges."""
    import numpy as np

    if hasattr(metrics, "_asdict"):
        metrics = metrics._asdict()
    w = PromWriter()
    for field, arr in metrics.items():
        a = np.asarray(arr)
        if a.dtype == object:
            continue
        if field in _COUNTERISH:
            w.sample(
                prefix + field + "_total",
                int(a.sum()),
                "Window total of per-tick %s" % field,
                "counter",
                labels,
            )
        else:
            last = a.reshape(-1)[-1] if a.ndim else a
            w.sample(
                prefix + field,
                float(last) if a.dtype.kind == "f" else int(last),
                "Last-tick value of %s" % field,
                "gauge",
                labels,
            )
    return w.render()
