"""Host half of the device latency histograms: exact percentile
extraction, track summaries, and the ``hist.drain`` runlog row shape.

Device half: :mod:`ringpop_tpu.ops.histogram` (log2-bucketed
``[tracks, NBUCKETS]`` uint32 counters carried through the scan).  This
module drains those counters to the host and answers the questions the
reference's ``metrics.Histogram`` answers — count/min/max/p50/p95/p99 —
plus the one consumer that makes the distribution load-bearing: the
reference's adaptive protocol period (``computeProtocolDelay``,
lib/gossip/index.js:42-50: ``max(p50 * 2, minProtocolPeriod)``).

Percentile semantics (exact, given the bucketization): the q-th
percentile is the nearest-rank order statistic — the ``ceil(q/100 * N)``
-th smallest observation.  Bucketization is monotone, so the bucket
found by walking cumulative bucket counts to that rank is EXACTLY the
bucket containing the true order statistic of the raw values; the
returned ``lo``/``hi`` bracket it, and ``value`` (the reported scalar)
is the conservative upper bound ``hi``.  Pinned against a raw-value
numpy oracle in tests/obs/test_histograms.py, including empty and
top-bucket (overflow-range) cases.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Sequence

import numpy as np

from ringpop_tpu.ops import histogram as hg

DEFAULT_QS = (50, 95, 99)


def percentile_bucket(counts: np.ndarray, q: float) -> Optional[int]:
    """Bucket index holding the nearest-rank q-th percentile, or None
    for an empty histogram.  ``counts`` is one track's [NBUCKETS]."""
    counts = np.asarray(counts, np.int64)
    total = int(counts.sum())
    if total == 0:
        return None
    if not (0 < q <= 100):
        raise ValueError("q must be in (0, 100], got %r" % (q,))
    rank = max(1, math.ceil(q / 100.0 * total))
    cum = np.cumsum(counts)
    return int(np.searchsorted(cum, rank, side="left"))


def percentile(counts: np.ndarray, q: float) -> Optional[Dict[str, int]]:
    """{"bucket", "lo", "hi", "value"} for the q-th percentile (value ==
    the bucket upper bound hi), or None when the track is empty."""
    b = percentile_bucket(counts, q)
    if b is None:
        return None
    return {
        "bucket": b,
        "lo": hg.bucket_lo(b),
        "hi": hg.bucket_hi(b),
        "value": hg.bucket_hi(b),
    }


def summarize_track(
    counts: np.ndarray, qs: Sequence[float] = DEFAULT_QS
) -> Dict[str, object]:
    """One track's summary: count, occupied-bucket min/max bounds, and
    the requested percentiles as ``p<q>`` entries."""
    counts = np.asarray(counts, np.int64)
    total = int(counts.sum())
    out: Dict[str, object] = {"count": total}
    nz = np.nonzero(counts)[0]
    out["min_lo"] = int(hg.bucket_lo(int(nz[0]))) if total else None
    out["max_hi"] = int(hg.bucket_hi(int(nz[-1]))) if total else None
    for q in qs:
        p = percentile(counts, q)
        key = "p%g" % q
        out[key] = None if p is None else p["value"]
        out[key + "_lo"] = None if p is None else p["lo"]
    return out


def summarize(
    hist,
    tracks: Sequence[str],
    qs: Sequence[float] = DEFAULT_QS,
) -> Dict[str, Dict[str, object]]:
    """Track-name-keyed summaries of one drained ``[H, NBUCKETS]``
    counter array (device or host)."""
    arr = np.asarray(hist)
    if arr.ndim != 2:
        raise ValueError(
            "summarize wants one [tracks, buckets] array, got shape %r "
            "(use summarize_batched for a vmapped [B, H, NB] drain)"
            % (arr.shape,)
        )
    if arr.shape[0] != len(tracks):
        raise ValueError(
            "hist has %d tracks but %d names given"
            % (arr.shape[0], len(tracks))
        )
    return {
        name: summarize_track(arr[i], qs) for i, name in enumerate(tracks)
    }


def summarize_batched(
    hist,
    tracks: Sequence[str],
    qs: Sequence[float] = DEFAULT_QS,
    aggregate: bool = True,
) -> object:
    """A vmapped driver's ``[B, H, NBUCKETS]`` (or deeper-batched)
    histogram stack.  ``aggregate=True`` sums the batch axes first —
    bucket counts are additive, so the aggregate percentiles are exactly
    the percentiles of the pooled observations; ``False`` returns a list
    of per-instance summaries (leading axes flattened)."""
    arr = np.asarray(hist)
    if arr.ndim < 2:
        raise ValueError("batched hist needs >= 2 dims, got %r" % (arr.shape,))
    if arr.ndim == 2:
        return summarize(arr, tracks, qs)
    flat = arr.reshape(-1, arr.shape[-2], arr.shape[-1])
    if aggregate:
        return summarize(flat.sum(axis=0), tracks, qs)
    return [summarize(h, tracks, qs) for h in flat]


def drain_row(
    source: str,
    summary: Dict[str, Dict[str, object]],
    **extra: object,
) -> Dict[str, object]:
    """The ``hist.drain`` runlog event row (field set validated by
    scripts/check_metrics_schema.py): source + per-track summaries."""
    row: Dict[str, object] = {"source": source, "tracks": summary}
    row.update(extra)
    return row


def drain(
    hist,
    tracks: Sequence[str],
    source: str,
    recorder=None,
    statsd=None,
    qs: Sequence[float] = DEFAULT_QS,
) -> Dict[str, Dict[str, object]]:
    """The ONE host half of every driver's ``drain_histograms()``:
    summarize the device counters, log the ``hist.drain`` event row on
    ``recorder`` (a RunRecorder), emit percentile timer keys through
    ``statsd`` (a StatsdBridge).  Returns the summary; the CALLER owns
    the device-side reset — sinks run first, so a raising sink leaves
    the window on device for a retry (the drain_events contract)."""
    summary = summarize(hist, tracks, qs)
    if recorder is not None:
        recorder.record_event("hist.drain", **drain_row(source, summary))
    if statsd is not None:
        statsd.emit_hist_summary(summary)
    return summary


# -- host-side log2 histogram (the perf timers' accumulator) --------------


class HostHistogram:
    """A host-side twin of the device counters: same log2 buckets, same
    percentile extraction — used by obs.perf's dispatch timers so
    wall-clock distributions and device-side latency distributions share
    one summary/rendering path.  Values are bucketized at a caller-chosen
    resolution (``unit`` — e.g. 1e-4 s per unit keeps sub-millisecond
    timing resolution in the low buckets)."""

    def __init__(self, unit: float = 1.0):
        if unit <= 0:
            raise ValueError("unit must be positive")
        self.unit = unit
        self.counts = np.zeros(hg.NBUCKETS, np.int64)

    def observe(self, value: float) -> None:
        if value < 0:
            return
        b = int(hg.bucket_index_np(np.int64(value / self.unit)))
        self.counts[b] += 1

    def summary(self, qs: Sequence[float] = DEFAULT_QS) -> Dict[str, object]:
        out = summarize_track(self.counts, qs)
        # scale the bucket bounds back to value units
        for k, v in list(out.items()):
            if k != "count" and v is not None:
                out[k] = v * self.unit
        return out


# -- the load-bearing consumer: adaptive protocol period ------------------


def compute_protocol_delay(
    p50: Optional[float], min_protocol_period: float = 200.0
) -> float:
    """The reference's ``computeProtocolDelay`` formula
    (lib/gossip/index.js:42-50): twice the ping-timing histogram's
    median, floored at the minimum protocol period.  ``p50 = None``
    (no observations yet) keeps the floor — exactly the reference's
    behavior before the first ping lands a timing sample."""
    if p50 is None:
        return float(min_protocol_period)
    return float(max(2.0 * p50, min_protocol_period))


class AdaptiveProtocolPeriod:
    """Host-side adaptive-period model fed from a ping-latency
    histogram — ``computeProtocolDelay``-style, OFF by default (nothing
    constructs one unless asked; the engines' discrete clock stays
    fixed).  Feed per-ping (per-tick dispatch) latencies in ms via
    ``observe``; ``period_ms()`` is ``max(2 * p50, min_period_ms)``
    with p50 read from the log2 histogram's conservative upper bound."""

    def __init__(self, min_period_ms: float = 200.0, unit_ms: float = 1.0):
        self.min_period_ms = float(min_period_ms)
        self.hist = HostHistogram(unit=unit_ms)

    def observe(self, latency_ms: float) -> None:
        self.hist.observe(latency_ms)

    def p50_ms(self) -> Optional[float]:
        s = self.hist.summary(qs=(50,))
        return s["p50"]

    def period_ms(self) -> float:
        return compute_protocol_delay(self.p50_ms(), self.min_period_ms)


__all__: List[str] = [
    "AdaptiveProtocolPeriod",
    "HostHistogram",
    "compute_protocol_delay",
    "drain",
    "drain_row",
    "percentile",
    "percentile_bucket",
    "summarize",
    "summarize_batched",
    "summarize_track",
]
