"""Flight-recorder event registry + host-side decoding and derivations.

The device-side protocol flight recorder (models/sim/flight.py) appends
fixed-width int32 records into a linear on-device buffer carried through
the scanned tick — written with masked scatters under the *same masks
that drive the trajectory*, so enabling it is trajectory-neutral and
callback-free (the jaxpr auditor gates the recorder-enabled tick).  This
module is the HOST half: the kind registry, the decoder, reconciliation
against ``TickMetrics`` counters, and the rumor-wavefront derivations
(dissemination latency, infection hop counts, per-rumor convergence
curves) that turn the SWIM O(log n) epidemic-broadcast claim into a
measured artifact.

Record layout (one row = one event, ``RECORD_WIDTH`` int32 slots)::

    [tick, kind, observer, subject, old_status, new_status, inc, aux]

- ``tick``       — 1-based engine tick index (SimState.tick_index after
  the tick ran).
- ``kind``       — code from :data:`EVENT_KINDS`.
- ``observer``   — the node whose view/action the event describes.
- ``subject``    — the other node involved (-1 when not applicable).
- ``old_status`` — observer's view of subject at the START of the tick
  (-1 = unknown member; only meaningful for view-change kinds).
- ``new_status`` — observer's view at the END of the tick (-1 n/a).
- ``inc``        — the engine's int32 incarnation STAMP attached to the
  event (0 when not applicable); ``engine.stamp_to_ms`` converts.
- ``aux``        — kind-specific (see the table below).

Event kinds and their aux semantics:

=============  ====  =======================================================
name           code  meaning (observer / subject / aux)
=============  ====  =======================================================
ping              0  direct ping sent: sender / target / aux=1 if delivered
status            1  view change applied: observer / subject / aux=phase
                     bitmask (1 ping-recv, 2 response, 4 ping-req, 8 join,
                     16 suspicion-expiry, 32 admin leave/rejoin self-write)
suspect           2  ping-req verdict marked subject suspect:
                     observer / subject / aux=0
faulty            3  suspicion expiry marked subject faulty:
                     observer / subject / aux=0
full_sync         4  full membership sync received: the pinging sender /
                     the responding node / aux=member records carried
refute            5  node saw itself defamed and re-asserted alive:
                     observer == subject / aux=phase bitmask (as above)
join              6  joiner merged target views and became ready:
                     joiner / -1 / aux=members learned
=============  ====  =======================================================

Rumor identity: a rumor is born when a member first asserts (or is
asserted at) a new ``(subject, status, incarnation)`` triple; every
``status`` event carrying that triple is one node's first adoption of the
rumor, so the event stream IS the wavefront (``rumor_wavefronts``).
"""

from __future__ import annotations

from collections import Counter
from typing import Any, Dict, Iterable, List, Optional, Sequence

import numpy as np

RECORD_WIDTH = 8
FIELDS = (
    "tick",
    "kind",
    "observer",
    "subject",
    "old_status",
    "new_status",
    "inc",
    "aux",
)
# field slot indices (device and host must agree)
(
    F_TICK,
    F_KIND,
    F_OBSERVER,
    F_SUBJECT,
    F_OLD_STATUS,
    F_NEW_STATUS,
    F_INC,
    F_AUX,
) = range(RECORD_WIDTH)

EV_PING = 0
EV_STATUS = 1
EV_SUSPECT = 2
EV_FAULTY = 3
EV_FULL_SYNC = 4
EV_REFUTE = 5
EV_JOIN = 6

EVENT_KINDS: Dict[int, str] = {
    EV_PING: "ping",
    EV_STATUS: "status",
    EV_SUSPECT: "suspect",
    EV_FAULTY: "faulty",
    EV_FULL_SYNC: "full_sync",
    EV_REFUTE: "refute",
    EV_JOIN: "join",
}
KIND_CODES: Dict[str, int] = {v: k for k, v in EVENT_KINDS.items()}

# status-event aux bitmask: which tick phase(s) applied the change
PHASE_PING_RECV = 1
PHASE_RESPONSE = 2
PHASE_PING_REQ = 4
PHASE_JOIN = 8
PHASE_EXPIRY = 16
# operator-plane self-transitions: graceful leave / rejoin write the
# origin's OWN view outside the gossip apply masks — without this bit
# the rumor's birth would be misattributed to its first OTHER hearer
PHASE_ADMIN = 32


def decode_arrays(buf: Any, head: Any) -> Dict[str, np.ndarray]:
    """Device buffer -> {field: np.ndarray} over the ``head`` valid rows.

    The cheap columnar form — reconciliation and wavefront math stay in
    numpy instead of per-event dicts."""
    buf = np.asarray(buf)
    if buf.ndim != 2 or buf.shape[1] != RECORD_WIDTH:
        raise ValueError(
            "event buffer must be [cap, %d] int32, got %r"
            % (RECORD_WIDTH, buf.shape)
        )
    head = int(np.asarray(head))
    head = max(0, min(head, buf.shape[0]))
    rows = buf[:head]
    return {name: rows[:, i].copy() for i, name in enumerate(FIELDS)}


def decode_events(buf: Any, head: Any, drops: Any = 0) -> List[Dict[str, int]]:
    """Device buffer -> list of per-event dicts (with ``kind_name``).

    ``drops`` (SimState.ev_drops) is not part of the rows; it is threaded
    through so callers see overflow honesty in one place — a nonzero
    value means the buffer filled and the TAIL of the stream is missing
    (the recorder drops new events rather than overwriting old ones)."""
    arrs = decode_arrays(buf, head)
    out: List[Dict[str, int]] = []
    for i in range(len(arrs["tick"])):
        ev = {name: int(arrs[name][i]) for name in FIELDS}
        ev["kind_name"] = EVENT_KINDS.get(ev["kind"], "unknown-%d" % ev["kind"])
        out.append(ev)
    if int(np.asarray(drops)):
        # annotate rather than raise: a truncated stream is still usable
        # for every derivation over its prefix
        for ev in out:
            ev.setdefault("truncated_stream", True)
    return out


def _as_arrays(events: Any) -> Dict[str, np.ndarray]:
    """Accept decode_arrays output, decode_events output, or a raw
    (buf, head) pair."""
    if isinstance(events, dict):
        missing = [f for f in FIELDS if f not in events]
        if missing:
            # a field-incomplete dict would otherwise surface as a bare
            # KeyError deep inside a derivation lambda
            raise ValueError(
                "columnar events dict is missing fields %r" % (missing,)
            )
        return events
    if isinstance(events, (list, tuple)) and events and isinstance(
        events[0], dict
    ):
        for i, e in enumerate(events):
            missing = [f for f in FIELDS if f not in e]
            if missing:
                raise ValueError(
                    "event %d is missing fields %r" % (i, missing)
                )
        return {
            name: np.asarray([ev[name] for ev in events], np.int64)
            for name in FIELDS
        }
    if isinstance(events, (list, tuple)) and len(events) in (2, 3):
        return decode_arrays(events[0], events[1])
    if not events:
        return {name: np.zeros(0, np.int64) for name in FIELDS}
    raise TypeError("unsupported events representation: %r" % type(events))


# -- reconciliation against TickMetrics -------------------------------------

# TickMetrics field -> (how to compute the same total from the stream)
_RECONCILE: Dict[str, Any] = {
    "pings_sent": lambda a: int(np.sum(a["kind"] == EV_PING)),
    "pings_delivered": lambda a: int(
        np.sum(a["aux"][a["kind"] == EV_PING])
    ),
    "suspects_marked": lambda a: int(np.sum(a["kind"] == EV_SUSPECT)),
    "faulties_marked": lambda a: int(np.sum(a["kind"] == EV_FAULTY)),
    "full_syncs": lambda a: int(np.sum(a["kind"] == EV_FULL_SYNC)),
    "full_sync_records": lambda a: int(
        np.sum(a["aux"][a["kind"] == EV_FULL_SYNC])
    ),
    "refutes": lambda a: int(np.sum(a["kind"] == EV_REFUTE)),
    "join_merges": lambda a: int(np.sum(a["kind"] == EV_JOIN)),
}


def reconcile(events: Any, metrics: Any) -> Dict[str, Dict[str, int]]:
    """Decoded event stream vs ``TickMetrics`` window totals.

    Returns {field: {"events": n, "metrics": n, "match": bool}} for every
    counter with a defined event-stream equivalent — the honesty gate the
    acceptance criteria pin (tests/models/test_flight_recorder.py)."""
    arrs = _as_arrays(events)
    if hasattr(metrics, "_asdict"):
        metrics = metrics._asdict()
    out: Dict[str, Dict[str, int]] = {}
    for field, derive in _RECONCILE.items():
        if field not in metrics:
            continue
        m_total = int(np.asarray(metrics[field]).sum())
        e_total = derive(arrs)
        out[field] = {
            "events": e_total,
            "metrics": m_total,
            "match": e_total == m_total,
        }
    return out


# -- rumor wavefront derivations --------------------------------------------


def rumor_wavefronts(events: Any) -> Dict[tuple, Dict[str, Any]]:
    """Group ``status`` events into rumor wavefronts.

    A rumor is a ``(subject, new_status, inc)`` triple; a node's FIRST
    ``status`` event carrying the triple is its first-heard tick.
    Returns ``{rumor: {"birth": tick, "first_heard": {observer: tick},
    "convergence_curve": [(tick, cumulative observers)], ...}}``."""
    arrs = _as_arrays(events)
    sel = arrs["kind"] == EV_STATUS
    ticks = arrs["tick"][sel]
    obs = arrs["observer"][sel]
    subj = arrs["subject"][sel]
    status = arrs["new_status"][sel]
    inc = arrs["inc"][sel]

    first: Dict[tuple, Dict[int, int]] = {}
    order = np.argsort(ticks, kind="stable")
    for i in order:
        rid = (int(subj[i]), int(status[i]), int(inc[i]))
        fh = first.setdefault(rid, {})
        o = int(obs[i])
        if o not in fh:
            fh[o] = int(ticks[i])
    out: Dict[tuple, Dict[str, Any]] = {}
    for rid, fh in first.items():
        birth = min(fh.values())
        # one pass over counts-by-tick, not a rescan of fh per distinct
        # tick — the curve build is O(observers) per rumor
        by_tick = Counter(fh.values())
        waves = sorted(by_tick)
        curve: List[tuple] = []
        seen = 0
        for t in waves:
            seen += by_tick[t]
            curve.append((t, seen))
        wave_rank = {t: k for k, t in enumerate(waves)}
        out[rid] = {
            "subject": rid[0],
            "status": rid[1],
            "inc": rid[2],
            "birth": birth,
            "first_heard": fh,
            "convergence_curve": curve,
            "convergence_tick": max(fh.values()),
            # dissemination latency per observer, in ticks since birth
            "latency": {o: t - birth for o, t in fh.items()},
            # infection hop count: which epidemic generation (distinct
            # adoption wave) the observer joined in — generation 0 is
            # the rumor's origin tick
            "hops": {o: wave_rank[t] for o, t in fh.items()},
        }
    return out


def dissemination_summary(
    wavefronts: Dict[tuple, Dict[str, Any]],
    min_observers: int = 2,
) -> Dict[str, Any]:
    """Aggregate dissemination-latency statistics across rumors.

    ``min_observers`` filters single-observer rumors (a change that never
    disseminated has no latency distribution).  Returns a JSON-ready dict
    with a latency histogram (ticks-to-hear counts), per-rumor
    convergence ticks, and hop-count distribution."""
    lat_hist: Dict[int, int] = {}
    hop_hist: Dict[int, int] = {}
    per_rumor: List[Dict[str, Any]] = []
    for rid, wf in sorted(wavefronts.items()):
        if len(wf["first_heard"]) < min_observers:
            continue
        for v in wf["latency"].values():
            lat_hist[v] = lat_hist.get(v, 0) + 1
        for v in wf["hops"].values():
            hop_hist[v] = hop_hist.get(v, 0) + 1
        per_rumor.append(
            {
                "subject": wf["subject"],
                "status": wf["status"],
                "inc": wf["inc"],
                "birth": wf["birth"],
                "observers": len(wf["first_heard"]),
                "convergence_tick": wf["convergence_tick"],
                "convergence_latency": wf["convergence_tick"] - wf["birth"],
                "convergence_curve": [list(p) for p in wf["convergence_curve"]],
            }
        )
    return {
        "rumors": per_rumor,
        "latency_histogram_ticks": {
            str(k): v for k, v in sorted(lat_hist.items())
        },
        "hop_histogram": {str(k): v for k, v in sorted(hop_hist.items())},
    }


def scalable_wavefront_summary(
    first_heard: Any,  # [N, U] int32, -1 = never heard
    r_birth: Any,  # [U] int32
    r_active: Any,  # [U] bool
    live: Optional[Any] = None,  # [N] bool — restrict to live nodes
) -> Dict[str, Any]:
    """The scalable engine's wavefront view: per active rumor slot, the
    first-heard tick distribution over nodes -> latency histogram +
    convergence curves (same JSON shape as ``dissemination_summary``)."""
    fh = np.asarray(first_heard)
    births = np.asarray(r_birth)
    active = np.asarray(r_active)
    live_mask = (
        np.ones(fh.shape[0], bool) if live is None else np.asarray(live)
    )
    lat_hist: Dict[int, int] = {}
    per_rumor: List[Dict[str, Any]] = []
    for r in np.nonzero(active)[0]:
        heard = fh[live_mask, r]
        heard = heard[heard >= 0]
        if heard.size == 0:
            continue
        birth = int(births[r])
        lats = heard - birth
        for v in lats.tolist():
            lat_hist[v] = lat_hist.get(v, 0) + 1
        ticks, counts = np.unique(heard, return_counts=True)
        per_rumor.append(
            {
                "slot": int(r),
                "birth": birth,
                "observers": int(heard.size),
                "convergence_tick": int(heard.max()),
                "convergence_latency": int(heard.max()) - birth,
                "convergence_curve": [
                    [int(t), int(c)]
                    for t, c in zip(ticks, np.cumsum(counts))
                ],
            }
        )
    return {
        "rumors": per_rumor,
        "latency_histogram_ticks": {
            str(k): v for k, v in sorted(lat_hist.items())
        },
    }


# -- sidecar schema ---------------------------------------------------------


def validate_event_stream(events: Iterable[Dict[str, Any]]) -> List[str]:
    """Schema check for a decoded event stream (the JSON sidecar form):
    required fields, known kinds, monotonically non-decreasing ticks."""
    problems: List[str] = []
    last_tick = None
    for i, ev in enumerate(events):
        if not isinstance(ev, dict):
            problems.append("event %d: not an object" % i)
            continue
        for f in FIELDS:
            if f not in ev:
                problems.append("event %d: missing field %r" % (i, f))
        kind = ev.get("kind")
        if kind not in EVENT_KINDS:
            problems.append("event %d: unknown kind %r" % (i, kind))
        t = ev.get("tick")
        if not isinstance(t, int):
            problems.append("event %d: tick must be int" % i)
        elif last_tick is not None and t < last_tick:
            problems.append(
                "event %d: tick %d decreases (prev %d)" % (i, t, last_tick)
            )
        else:
            last_tick = t
    return problems
