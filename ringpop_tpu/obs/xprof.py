"""Profiler trace capture harness (ISSUE 16 tentpole c).

Wraps ``jax.profiler.trace`` around the shared warm-then-measure loop
(:func:`ringpop_tpu.obs.perf.timed_window`), then digests the captured
Chrome-format trace into a per-op time-attribution table — top-K ops by
self-time, fuzzily keyed to the COST_BUDGET entry names where an op name
carries one — and stamps the artifact as an ``xprof.capture`` runlog row
(schema-gated by scripts/check_metrics_schema.py).  Consumers:
``BENCH_XPROF=1`` on bench.py's scalable/mesh/full phases and
tpu_measure.py's ``mesh_observatory`` phase, so a chip session banks
per-op attribution next to the wall clocks instead of re-deriving it
from memory later.

Everything here is defensive by contract: a backend without profiler
support, an empty capture, or an unparseable trace file yields an
``ok=False`` row with the failure reason — never an exception into the
measurement run it rides.
"""

from __future__ import annotations

import glob
import gzip
import json
import os
import re
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

XPROF_EVENT = "xprof.capture"
# required xprof.capture row fields (lockstep-pinned by
# scripts/check_metrics_schema.py and tests/obs/test_runlog_schema.py)
XPROF_FIELDS = (
    "phase",
    "ok",
    "wall_s",
    "trace_dir",
    "num_trace_files",
    "total_self_us",
    "ops",
)
DEFAULT_TOP_K = 10

_TOKEN_RE = re.compile(r"[a-z0-9]+")


def find_trace_files(trace_dir: str) -> List[str]:
    """Chrome-format trace files under a ``jax.profiler.trace`` output
    dir (``plugins/profile/<run>/*.trace.json.gz``), newest first."""
    paths = glob.glob(
        os.path.join(trace_dir, "**", "*.trace.json.gz"), recursive=True
    )
    return sorted(paths, key=lambda p: os.path.getmtime(p), reverse=True)


def load_trace_events(path: str) -> List[Dict[str, Any]]:
    """The ``traceEvents`` list of one (gzipped or plain) Chrome trace."""
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt", encoding="utf-8", errors="replace") as f:
        doc = json.load(f)
    if isinstance(doc, list):  # bare event-array form
        return doc
    return list(doc.get("traceEvents", []))


def op_table(
    events: Sequence[Dict[str, Any]],
    top_k: int = DEFAULT_TOP_K,
    budget_entries: Optional[Sequence[str]] = None,
) -> Tuple[List[Dict[str, Any]], float]:
    """Aggregate complete ("X"-phase) events by name into the top-K
    self-time table: ``[{"name", "self_us", "count", "budget_entry"},
    ...]`` plus the total attributed microseconds.  Metadata events and
    zero-duration markers drop out."""
    agg: Dict[str, List[float]] = {}
    for ev in events:
        if ev.get("ph") != "X":
            continue
        dur = ev.get("dur")
        name = ev.get("name")
        if not name or not isinstance(dur, (int, float)) or dur <= 0:
            continue
        row = agg.setdefault(str(name), [0.0, 0])
        row[0] += float(dur)
        row[1] += 1
    total = sum(v[0] for v in agg.values())
    ranked = sorted(agg.items(), key=lambda kv: kv[1][0], reverse=True)
    out = []
    for name, (self_us, count) in ranked[: max(0, int(top_k))]:
        out.append(
            {
                "name": name,
                "self_us": round(self_us, 3),
                "count": int(count),
                "budget_entry": match_budget_entry(name, budget_entries),
            }
        )
    return out, round(total, 3)


def match_budget_entry(
    op_name: str, entries: Optional[Sequence[str]]
) -> Optional[str]:
    """Fuzzy op-name -> COST_BUDGET entry-name key: the entry whose
    token set overlaps the op name most (HLO op names carry fusion/op
    hints like ``all-to-all`` or ``fusion.pallas_exchange``, budget
    names read ``exchange-plane`` / ``engine-scalable-tick``).  None
    when nothing overlaps — most ops are anonymous fusions."""
    if not entries:
        return None
    op_tokens = set(_TOKEN_RE.findall(op_name.lower()))
    if not op_tokens:
        return None
    best, best_score = None, 0
    for entry in entries:
        tokens = set(_TOKEN_RE.findall(entry.lower()))
        score = len(op_tokens & tokens)
        if score > best_score:
            best, best_score = entry, score
    return best


def _budget_entry_names() -> List[str]:
    try:
        from ringpop_tpu.analysis import cost

        manifest = cost.load_manifest()
        return sorted((manifest or {}).get("entries", {}).keys())
    except Exception:
        return []


def capture(
    run: Callable[[], Any],
    trace_dir: str,
    *,
    phase: str = "xprof",
    warmup: int = 1,
    repeats: int = 1,
    top_k: int = DEFAULT_TOP_K,
    recorder=None,
    statsd=None,
    **extra: Any,
) -> Dict[str, Any]:
    """Profile ``repeats`` fenced calls of ``run`` (after ``warmup``
    unprofiled compile calls) under ``jax.profiler.trace(trace_dir)``,
    digest the capture into the top-K op table, and stamp one
    ``xprof.capture`` row on ``recorder`` / ``xprof.*`` statsd keys.
    Returns the row dict (``ok=False`` + ``error`` on any capture or
    parse failure; the measurement itself always completes)."""
    from ringpop_tpu.obs import perf

    os.makedirs(trace_dir, exist_ok=True)
    row: Dict[str, Any] = {
        "phase": phase,
        "ok": False,
        "wall_s": None,
        "trace_dir": trace_dir,
        "num_trace_files": 0,
        "total_self_us": 0.0,
        "ops": [],
    }
    row.update(extra)
    # compile outside the profiled span: traces should attribute steady-
    # state execution, not tracing/lowering
    for _ in range(max(0, warmup)):
        perf.fence(run())
    try:
        import jax

        with jax.profiler.trace(trace_dir):
            _, wall = perf.timed_window(run, warmup=0, repeats=repeats)
        row["wall_s"] = wall
    except Exception as e:
        row["error"] = "profiler capture failed: %s" % (str(e)[:300],)
        _emit(row, recorder, statsd)
        return row
    try:
        files = find_trace_files(trace_dir)
        row["num_trace_files"] = len(files)
        if not files:
            row["error"] = "no trace files captured under %s" % trace_dir
            _emit(row, recorder, statsd)
            return row
        events: List[Dict[str, Any]] = []
        for p in files:
            events.extend(load_trace_events(p))
        ops, total = op_table(
            events, top_k=top_k, budget_entries=_budget_entry_names()
        )
        row["ops"] = ops
        row["total_self_us"] = total
        row["ok"] = True
    except Exception as e:
        row["error"] = "trace parse failed: %s" % (str(e)[:300],)
    _emit(row, recorder, statsd)
    return row


def _emit(row: Dict[str, Any], recorder, statsd) -> None:
    if recorder is not None:
        recorder.record_event(XPROF_EVENT, **row)
    if statsd is not None:
        from ringpop_tpu.obs.statsd_bridge import XPROF_KEY_MAP

        if row.get("wall_s") is not None:
            stat_type, key = XPROF_KEY_MAP["wall_s"]
            getattr(statsd, "timing")(key, float(row["wall_s"]) * 1e3)
        stat_type, key = XPROF_KEY_MAP["ops"]
        statsd.gauge(key, len(row.get("ops") or []))


def render_table(row: Dict[str, Any]) -> str:
    """Console rendering of one capture row — the bench's human view."""
    lines = [
        "xprof[%s]: ok=%s files=%d total_self=%.1fus"
        % (
            row.get("phase"),
            row.get("ok"),
            row.get("num_trace_files", 0),
            row.get("total_self_us") or 0.0,
        )
    ]
    if row.get("error"):
        lines.append("  error: %s" % row["error"])
    for op in row.get("ops") or []:
        lines.append(
            "  %10.1fus x%-5d %s%s"
            % (
                op["self_us"],
                op["count"],
                op["name"][:80],
                (
                    "  [%s]" % op["budget_entry"]
                    if op.get("budget_entry")
                    else ""
                ),
            )
        )
    return "\n".join(lines)


__all__: List[str] = [
    "DEFAULT_TOP_K",
    "XPROF_EVENT",
    "XPROF_FIELDS",
    "capture",
    "find_trace_files",
    "load_trace_events",
    "match_budget_entry",
    "op_table",
    "render_table",
]
