"""Sliding-window SLO plane over the routing plane's drained telemetry.

A served RoutedStorm (ROADMAP item 1) needs more than cumulative
counters: an operator asks "what is the p99 and the success rate over
the LAST window, and how fast am I burning the error budget?"  This
module answers with the standard serving-stack machinery:

- **sliding window**: a ring buffer of per-window deltas — each
  ``observe()`` pushes one drained window (log2 histogram bucket-count
  delta + query/error counter deltas) and evicts the oldest once
  ``window_len`` windows are held.  Bucket counts are additive, so the
  sliding totals are exactly the pooled observations of the covered
  ticks — windowed percentiles come from the same nearest-rank
  extraction the cumulative drain uses (obs.histograms.percentile).
- **declarative SLO targets** (:class:`SLOTarget`): a success-rate
  objective, an optional p99 ceiling, and a burn-rate alert threshold.
- **error-budget burn rate**: ``(errors/queries) / (1 - objective)`` —
  1.0 means the budget is being consumed exactly at the sustainable
  rate; an SRE-style fast-burn alert fires at ``burn_alert``.
- **schema-gated rows**: every ``observe()`` emits one ``slo.window``
  event row on the attached recorder (field set validated by
  scripts/check_metrics_schema.py); a breach additionally emits
  ``slo.breach`` naming every violated clause.
- **consumer hook**: :class:`SLOBackpressure`, the
  ``AdaptiveProtocolPeriod``-style consumer — turns the burn rate into
  a protocol-period/backpressure factor so item 1's serving loop has
  its sensor ready (off by default: nothing constructs one unless
  asked).

Feeding it: drain the route histogram every W ticks with reset=True
(the drained counts ARE the window delta) and pass the per-window
RouteMetrics counter deltas — scripts/export_request_trace.py shows
the loop; windowed percentiles are pinned against a host-numpy
nearest-rank oracle in tests/obs/test_slo.py.
"""

from __future__ import annotations

from collections import deque
from typing import Any, Dict, List, NamedTuple, Optional

import numpy as np

from ringpop_tpu.obs import histograms as oh
from ringpop_tpu.ops import histogram as hg

WINDOW_QS = (50, 95, 99)


class SLOTarget(NamedTuple):
    """One declarative SLO: a named objective over a request stream.

    ``success_objective`` is the fraction of requests that must succeed
    (errors are whatever counter the feeder passes as ``errors``);
    ``p99_max`` (optional) caps the windowed p99 in track value units;
    ``burn_alert`` is the error-budget burn-rate multiple that fires a
    breach even while the success rate still clears the objective (the
    SRE fast-burn alert)."""

    name: str = "route"
    success_objective: float = 0.999
    p99_max: Optional[int] = None
    burn_alert: float = 2.0


def burn_rate(
    errors: int, queries: int, success_objective: float
) -> float:
    """Error-budget burn rate: observed error fraction over the budget
    fraction ``1 - objective``.  1.0 = consuming the budget exactly at
    the sustainable rate; 0 queries burns nothing; a 100% objective has
    zero budget, so any error burns at +inf."""
    if queries <= 0 or errors <= 0:
        return 0.0
    frac = errors / queries
    budget = 1.0 - success_objective
    if budget <= 0.0:
        return float("inf")
    return frac / budget


class SLOWindowPlane:
    """Ring-buffered sliding-window SLO evaluator (one histogram track
    + one error counter against one :class:`SLOTarget`)."""

    def __init__(
        self,
        target: SLOTarget = SLOTarget(),
        window_len: int = 8,
        recorder=None,
        statsd=None,
        consumer=None,
    ):
        if window_len < 1:
            raise ValueError("window_len must be >= 1")
        self.target = target
        # the ring buffer: (ticks, bucket-count delta, queries, errors)
        self._ring: deque = deque(maxlen=window_len)
        self.recorder = recorder
        self.statsd = statsd
        self.consumer = consumer
        self.breaches = 0

    # -- feeding ----------------------------------------------------------

    def observe(
        self,
        tick: int,
        counts_delta: Any,  # [NBUCKETS] — one window's bucket deltas
        queries: int,
        errors: int,
        ticks: int = 1,
    ) -> Dict[str, Any]:
        """Push one drained window's deltas; evaluate the sliding
        window; emit ``slo.window`` (and, on a breach, ``slo.breach``)
        rows; feed the consumer hook.  Returns the window row."""
        counts = np.asarray(counts_delta, np.int64).reshape(-1)
        if counts.shape[0] != hg.NBUCKETS:
            raise ValueError(
                "counts_delta must be one [%d] bucket-count window, "
                "got %r" % (hg.NBUCKETS, counts.shape)
            )
        self._ring.append((int(ticks), counts, int(queries), int(errors)))
        row = self.window_row(tick)
        if self.recorder is not None:
            self.recorder.record_event("slo.window", **row)
        if self.statsd is not None:
            self.statsd.emit_slo_window(row)
        if row["breach"]:
            self.breaches += 1
            breach = {
                "target": row["target"],
                "tick": row["tick"],
                "window_ticks": row["window_ticks"],
                "reason": row["breach_reason"],
                "burn_rate": row["burn_rate"],
                "success_rate": row["success_rate"],
                "p99": row["p99"],
            }
            if self.recorder is not None:
                self.recorder.record_event("slo.breach", **breach)
            if self.statsd is not None:
                self.statsd.emit_slo_breach(row["target"])
        if self.consumer is not None:
            self.consumer.update(row)
        return row

    def observe_route_window(
        self,
        tick: int,
        hist,  # [len(ROUTE_HIST_TRACKS), NBUCKETS] — drained window
        rm,  # RouteMetrics window stack (per-tick [T] arrays)
        track: str = "retry_depth",
    ) -> Dict[str, Any]:
        """Convenience feeder for the routing plane: one drained route
        histogram window (the counts BETWEEN resets — drain with
        reset=True each window) + the same window's RouteMetrics stack.
        Errors = retried-or-aborted requests (misroutes + consistency
        rejects + keys-diverged aborts — the requestProxy failure
        surface)."""
        from ringpop_tpu.models.route.plane import ROUTE_HIST_TRACKS

        arr = np.asarray(hist)
        counts = arr[ROUTE_HIST_TRACKS.index(track)]
        md = rm._asdict() if hasattr(rm, "_asdict") else dict(rm)
        queries = int(np.asarray(md["route_queries"]).sum())
        errors = int(
            np.asarray(md["route_misroutes"]).sum()
            + np.asarray(md["route_checksum_rejects"]).sum()
            + np.asarray(md["route_keys_diverged"]).sum()
        )
        ticks = int(np.asarray(md["route_queries"]).reshape(-1).shape[0])
        return self.observe(tick, counts, queries, errors, ticks=ticks)

    # -- evaluation -------------------------------------------------------

    def window_counts(self) -> np.ndarray:
        """[NBUCKETS] pooled bucket counts over the held windows."""
        out = np.zeros(hg.NBUCKETS, np.int64)
        for _, counts, _, _ in self._ring:
            out += counts
        return out

    def window_row(self, tick: int) -> Dict[str, Any]:
        """Evaluate the current sliding window into one ``slo.window``
        row: nearest-rank percentiles (conservative bucket upper
        bounds, obs.histograms semantics), success rate, burn rate,
        and the breach verdict with its reasons."""
        t = self.target
        window_ticks = sum(w[0] for w in self._ring)
        queries = sum(w[2] for w in self._ring)
        errors = sum(w[3] for w in self._ring)
        counts = self.window_counts()
        row: Dict[str, Any] = {
            "target": t.name,
            "tick": int(tick),
            "window_ticks": int(window_ticks),
            "windows": len(self._ring),
            "queries": int(queries),
            "errors": int(errors),
        }
        for q in WINDOW_QS:
            p = oh.percentile(counts, q)
            row["p%d" % q] = None if p is None else p["value"]
        success = 1.0 if queries <= 0 else 1.0 - errors / queries
        burn = burn_rate(errors, queries, t.success_objective)
        row["success_rate"] = success
        row["burn_rate"] = burn
        reasons: List[str] = []
        if queries > 0 and success < t.success_objective:
            reasons.append("success-rate")
        if t.p99_max is not None and row["p99"] is not None:
            if row["p99"] > t.p99_max:
                reasons.append("p99")
        if burn >= t.burn_alert:
            reasons.append("burn-rate")
        row["breach"] = bool(reasons)
        row["breach_reason"] = ",".join(reasons)
        return row


# -- the consumer hook: burn-rate backpressure ------------------------------


class SLOBackpressure:
    """``AdaptiveProtocolPeriod``-style consumer of ``slo.window`` rows:
    scales a base protocol period by the error-budget burn rate while
    the target is breaching (more backpressure = longer period = less
    offered load), snapping back to the base once the window clears —
    the sensor-to-actuator seam ROADMAP item 1's serving loop plugs
    into.  ``factor()`` is clamped to [1, max_factor]."""

    def __init__(
        self, base_period_ms: float = 200.0, max_factor: float = 8.0
    ):
        if max_factor < 1.0:
            raise ValueError("max_factor must be >= 1")
        self.base_period_ms = float(base_period_ms)
        self.max_factor = float(max_factor)
        self._factor = 1.0

    def update(self, row: Dict[str, Any]) -> float:
        """Feed one window row; returns the new period in ms."""
        if row.get("breach"):
            burn = float(row.get("burn_rate") or 1.0)
            self._factor = min(max(burn, 1.0), self.max_factor)
        else:
            self._factor = 1.0
        return self.period_ms()

    def factor(self) -> float:
        return self._factor

    def period_ms(self) -> float:
        return self.base_period_ms * self._factor


__all__ = [
    "SLOBackpressure",
    "SLOTarget",
    "SLOWindowPlane",
    "WINDOW_QS",
    "burn_rate",
]
