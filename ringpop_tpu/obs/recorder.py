"""RunRecorder — append-only JSONL run logs for simulation/bench runs.

Every epidemic run, parity replay and bench invocation gets a durable,
queryable telemetry trail: the scanned engines return per-tick metric
time-series ([T]-shaped ``TickMetrics``/``ScalableMetrics``); the
recorder folds them into the existing ``Meter``/``Histogram`` primitives
(utils/stats.py) and streams JSONL rows to disk as they arrive — an
append-only log, one JSON object per line, so a crashed run still leaves
its prefix readable.

Row kinds (``kind`` field):

- ``header``  — schema version, run id, config, backend provenance.
  Always the first row.
- ``tick``    — one engine tick's metrics (possibly strided; the last
  tick of every recorded batch is always kept so convergence is visible).
- ``phase``   — a named wall-clock phase (compile, warm, measure, ...).
- ``event``   — free-form annotations (replays, faults injected, ...).
- ``summary`` — totals, convergence tick, histogram digests.  Always the
  last row of a finished log.

The schema is validated by :func:`validate_run_log` (also exposed via
``scripts/check_metrics_schema.py`` and the tier-1 test
``tests/obs/test_runlog_schema.py``).
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, IO, List, Optional

from itertools import count as _count

from ringpop_tpu.utils.stats import Histogram, Meter

SCHEMA_VERSION = 1

# per-process sequence: two recorders born in the same wall-clock second
# (e.g. bench retry loops) must not share a default run_id — the second
# would append a mid-file header to the first's log
_RUN_SEQ = _count()

# kind -> required fields (beyond "kind")
_REQUIRED: Dict[str, tuple] = {
    "header": ("schema", "run_id", "config", "provenance"),
    "tick": ("tick", "metrics"),
    "phase": ("name", "wall_s"),
    "event": ("name",),
    "summary": ("ticks_recorded", "totals"),
}


def _jsonable(v: Any) -> Any:
    """numpy/jax scalars and arrays -> plain python for json.dumps."""
    if hasattr(v, "item") and getattr(v, "ndim", None) == 0:
        return v.item()
    if hasattr(v, "tolist"):
        return v.tolist()
    if isinstance(v, dict):
        return {k: _jsonable(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_jsonable(x) for x in v]
    return v


def iter_tick_rows(metrics: Any):
    """Yield per-tick row dicts from a metrics pytree — a NamedTuple or
    dict whose leaves are scalars (one row), [T]-arrays, or [T, B]-arrays
    (vmapped drivers; rows then hold [B]-vectors).  The ONE unstacking
    loop shared by the recorder, the statsd bridge and the sim trace tap.

    Every leaf must agree on the leading (time) dimension: a ragged
    pytree — some leaves scalar, some [T], or [T]s of different T —
    would silently mis-slice (leaf ``v[t]`` reads a different tick's
    value, or IndexErrors mid-stream), so it raises up front instead."""
    import numpy as np

    if hasattr(metrics, "_asdict"):
        metrics = metrics._asdict()
    arrs = {k: np.asarray(v) for k, v in metrics.items()}
    if not arrs:
        return
    lead_dims = {k: (v.shape[0] if v.ndim else None) for k, v in arrs.items()}
    distinct = set(lead_dims.values())
    if len(distinct) > 1:
        raise ValueError(
            "ragged metrics pytree: leaves disagree on the leading "
            "(time) dimension — %s"
            % ", ".join(
                "%s: %s" % (k, "scalar" if d is None else "[%d]" % d)
                for k, d in sorted(lead_dims.items())
            )
        )
    lead = next(iter(arrs.values()))
    if lead.ndim == 0:
        yield arrs
        return
    for t in range(lead.shape[0]):
        yield {k: v[t] for k, v in arrs.items()}


def _backends_initialized() -> bool:
    """True when a jax backend already exists, WITHOUT initializing one.

    Reaches into jax internals, so it is deliberately version-tolerant:
    jax 0.4.x keeps a ``jax._src.xla_bridge._backends`` dict, newer
    releases have renamed/moved the registry more than once.  Any probe
    that fails falls through to the next; when every probe fails the
    answer is False — provenance then simply omits platform fields
    rather than risking a backend grab on a host-only run."""
    try:
        from jax._src import xla_bridge

        backends = getattr(xla_bridge, "_backends", None)
        if isinstance(backends, dict):
            return bool(backends)
        probe = getattr(xla_bridge, "backends_are_initialized", None)
        if callable(probe):
            return bool(probe())
    except Exception:
        pass
    try:  # newer layouts keep the registry on jax._src.backends
        from jax._src import backends as _jb  # type: ignore

        backends = getattr(_jb, "_backends", None)
        if isinstance(backends, dict):
            return bool(backends)
    except Exception:
        pass
    return False


def backend_provenance() -> Dict[str, Any]:
    """Best-effort backend/platform provenance.  Never raises and never
    *initializes* a backend that is not already up: a recorder attached
    to a host-only run must not grab the (single-client) TPU tunnel."""
    prov: Dict[str, Any] = {"pid": os.getpid()}
    try:
        import jax

        prov["jax_version"] = jax.__version__
        # only read devices if a backend already exists — jax.devices()
        # would otherwise initialize one as a side effect
        if _backends_initialized():
            prov["platform"] = jax.default_backend()
            prov["device_count"] = jax.device_count()
    except Exception:  # pragma: no cover — provenance is best-effort
        pass
    env = os.environ.get("JAX_PLATFORMS")
    if env:
        prov["jax_platforms_env"] = env
    return prov


class RunRecorder:
    """Folds per-tick metric series into Meters/Histograms and writes an
    append-only JSONL run log.

    ``path`` may be a file path (used as-is) or a directory (the log
    becomes ``<dir>/<run_id>.runlog.jsonl``).  ``stride`` keeps every
    k-th tick row (plus the last row of each recorded batch); totals and
    histograms always fold EVERY tick regardless of stride.
    """

    def __init__(
        self,
        path: str,
        run_id: Optional[str] = None,
        config: Optional[Dict[str, Any]] = None,
        stride: int = 1,
        clock=time.time,
    ):
        if stride < 1:
            raise ValueError("stride must be >= 1")
        self._clock = clock
        self.run_id = run_id or "run-%d-%d-%d" % (
            int(clock()),
            os.getpid(),
            next(_RUN_SEQ),
        )
        if os.path.isdir(path) or path.endswith(os.sep):
            os.makedirs(path, exist_ok=True)
            path = os.path.join(path, "%s.runlog.jsonl" % self.run_id)
        self.path = path
        self.stride = stride
        self.config = dict(config or {})
        self.meters: Dict[str, Meter] = {}
        self.histograms: Dict[str, Histogram] = {}
        self.totals: Dict[str, float] = {}
        self.ticks_recorded = 0
        self.convergence_tick: Optional[int] = None
        self._next_tick = 0
        self._finished = False
        self._header_written = False
        self._fh: Optional[IO[str]] = open(path, "a", encoding="utf-8")

    # -- low-level --------------------------------------------------------

    def _ensure_header(self) -> None:
        # header deferred to the first row so config enrichment by the
        # driver (SimCluster.attach_recorder et al.) lands in it
        if self._header_written or self._fh is None:
            return
        self._header_written = True
        self._fh.write(
            json.dumps(
                {
                    "kind": "header",
                    "schema": SCHEMA_VERSION,
                    "run_id": self.run_id,
                    "created_unix": self._clock(),
                    "config": _jsonable(self.config),
                    "provenance": backend_provenance(),
                },
                sort_keys=True,
            )
            + "\n"
        )

    def _write(self, row: Dict[str, Any]) -> None:
        if self._fh is None:
            raise RuntimeError("recorder already closed")
        self._ensure_header()
        self._fh.write(json.dumps(row, sort_keys=True) + "\n")
        self._fh.flush()

    def describe(self, engine: str, n: int, params: Any, **extra: Any) -> None:
        """The ONE header-enrichment contract shared by every driver's
        attach_recorder (and bench.py): stamp the engine name, cluster
        size and static params into the header config.  setdefault
        semantics — the first describer wins, so a multi-window log
        keeps its original identity."""
        self.config.setdefault("engine", engine)
        self.config.setdefault("n", n)
        if hasattr(params, "_asdict"):
            params = params._asdict()
        self.config.setdefault("params", params)
        for k, v in extra.items():
            self.config.setdefault(k, v)

    # -- metrics ingestion ------------------------------------------------

    def _fold(self, field: str, value: float) -> None:
        self.totals[field] = self.totals.get(field, 0) + value
        hist = self.histograms.get(field)
        if hist is None:
            hist = self.histograms[field] = Histogram()
        hist.update(value)
        meter = self.meters.get(field)
        if meter is None:
            meter = self.meters[field] = Meter(now=self._clock)
        meter.mark(int(value) if float(value).is_integer() else 1)

    def record_tick(self, row: Dict[str, Any], tick: Optional[int] = None) -> int:
        """One tick's metrics (a plain dict of scalars).  Returns the
        tick index assigned.  Every tick folds into totals/histograms;
        only stride-selected ticks (and batch tails, via record_ticks)
        get their own JSONL row."""
        return self._record_tick(row, tick, force_row=True)

    def _record_tick(
        self, row: Dict[str, Any], tick: Optional[int], force_row: bool
    ) -> int:
        if tick is None:
            tick = self._next_tick
        self._next_tick = tick + 1
        clean = {k: _jsonable(v) for k, v in row.items()}
        for k, v in clean.items():
            if isinstance(v, bool):
                v = int(v)
            if isinstance(v, (int, float)):
                self._fold(k, v)
        conv = clean.get("converged")
        if isinstance(conv, list):
            # vmapped [B]-row: converged means EVERY cluster converged
            # (an empty or any-False list must not read as truthy)
            conv = bool(conv) and all(conv)
        if (
            self.convergence_tick is None
            and isinstance(conv, (bool, int))
            and conv
        ):
            self.convergence_tick = tick
        self.ticks_recorded += 1
        if force_row or tick % self.stride == 0:
            self._write({"kind": "tick", "tick": tick, "metrics": clean})
        return tick

    def record_ticks(self, metrics: Any, start_tick: Optional[int] = None) -> int:
        """A stacked metrics series — a NamedTuple (or dict) of
        [T]-shaped arrays, exactly what the ``lax.scan`` drivers return
        ([T, B] under the vmapped driver: per-cluster vectors are kept
        in the row as lists; only scalars fold into totals).  Folds
        every tick; writes stride-selected rows plus the batch's last
        row.  Returns the number of ticks ingested."""
        rows = list(iter_tick_rows(metrics))
        tick0 = self._next_tick if start_tick is None else start_tick
        for t, row in enumerate(rows):
            self._record_tick(
                row, tick0 + t, force_row=(t == len(rows) - 1)
            )
        return len(rows)

    # -- phases / events --------------------------------------------------

    def phase(self, name: str) -> "_PhaseTimer":
        """Context manager timing one named wall-clock phase."""
        return _PhaseTimer(self, name)

    def record_phase(self, name: str, wall_s: float, **extra: Any) -> None:
        row = {"kind": "phase", "name": name, "wall_s": wall_s}
        row.update(_jsonable(extra))
        self._write(row)

    def record_event(self, name: str, **extra: Any) -> None:
        row = {"kind": "event", "name": name}
        row.update(_jsonable(extra))
        self._write(row)

    def record_trace_sidecar(
        self, trace: Dict[str, Any], name: str = "flight"
    ) -> str:
        """Write a Chrome-trace JSON sidecar next to this run log and
        link it with a ``trace_sidecar`` event row (relative path, so
        the pair stays valid when the runlog directory moves).  The
        sidecar is schema-validated before writing (obs.chrome_trace);
        the CI gate (scripts/check_metrics_schema.py) re-validates both
        the link and the file."""
        from ringpop_tpu.obs.chrome_trace import write_chrome_trace

        base = self.path
        suffix = ".runlog.jsonl"
        if base.endswith(suffix):
            base = base[: -len(suffix)]
        sidecar = "%s.%s.trace.json" % (base, name)
        write_chrome_trace(trace, sidecar)
        self.record_event(
            "trace_sidecar",
            sidecar=name,
            path=os.path.basename(sidecar),
        )
        return sidecar

    # -- teardown ---------------------------------------------------------

    def finish(self, **extra: Any) -> Dict[str, Any]:
        """Write the summary row and close the log.  Idempotent, and a
        no-op on an already-closed recorder (a log sealed early — e.g.
        before a re-exec — stays header-valid without a summary)."""
        if self._finished or self._fh is None:
            return {}
        summary = {
            "kind": "summary",
            "ticks_recorded": self.ticks_recorded,
            "convergence_tick": self.convergence_tick,
            "totals": _jsonable(self.totals),
            "histograms": {
                k: _jsonable(h.to_dict()) for k, h in self.histograms.items()
            },
        }
        summary.update(_jsonable(extra))
        self._write(summary)
        self._finished = True
        self.close()
        return summary

    def close(self) -> None:
        if self._fh is not None:
            self._ensure_header()  # even an aborted run has a valid log
            self._fh.close()
            self._fh = None

    def __enter__(self) -> "RunRecorder":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc_type is None:
            self.finish()
        else:
            self.close()


class _PhaseTimer:
    def __init__(self, recorder: RunRecorder, name: str):
        self.recorder = recorder
        self.name = name

    def __enter__(self) -> "_PhaseTimer":
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.recorder.record_phase(
            self.name,
            time.perf_counter() - self._t0,
            **({"error": repr(exc)} if exc is not None else {}),
        )


# -- reading + schema validation ------------------------------------------


def read_run_log(path: str) -> Dict[str, Any]:
    """Round-trip reader: {header, ticks, phases, events, summary}."""
    out: Dict[str, Any] = {
        "header": None,
        "ticks": [],
        "phases": [],
        "events": [],
        "summary": None,
    }
    with open(path, encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            row = json.loads(line)
            kind = row.get("kind")
            if kind == "header":
                out["header"] = row
            elif kind == "tick":
                out["ticks"].append(row)
            elif kind == "phase":
                out["phases"].append(row)
            elif kind == "event":
                out["events"].append(row)
            elif kind == "summary":
                out["summary"] = row
    return out


def validate_run_log(path: str) -> List[str]:
    """Schema check; returns a list of human-readable problems (empty ==
    valid).  A missing summary row is allowed (crashed/in-flight runs
    keep their readable prefix), a missing or late header is not."""
    problems: List[str] = []
    saw_header = False
    last_tick = None
    with open(path, encoding="utf-8") as fh:
        for ln, line in enumerate(fh, 1):
            line = line.strip()
            if not line:
                continue
            try:
                row = json.loads(line)
            except ValueError as e:
                problems.append("%s:%d: not JSON (%s)" % (path, ln, e))
                continue
            if not isinstance(row, dict):
                problems.append("%s:%d: row is not an object" % (path, ln))
                continue
            kind = row.get("kind")
            if kind not in _REQUIRED:
                problems.append(
                    "%s:%d: unknown kind %r" % (path, ln, kind)
                )
                continue
            if ln == 1 and kind != "header":
                problems.append(
                    "%s:1: first row must be the header, got %r"
                    % (path, kind)
                )
            for field in _REQUIRED[kind]:
                if field not in row:
                    problems.append(
                        "%s:%d: %s row missing %r" % (path, ln, kind, field)
                    )
            if kind == "header":
                saw_header = True
                if row.get("schema") != SCHEMA_VERSION:
                    problems.append(
                        "%s:%d: schema %r != %d"
                        % (path, ln, row.get("schema"), SCHEMA_VERSION)
                    )
            elif kind == "tick":
                t = row.get("tick")
                if not isinstance(t, int):
                    problems.append(
                        "%s:%d: tick index must be int" % (path, ln)
                    )
                elif last_tick is not None and t <= last_tick:
                    problems.append(
                        "%s:%d: tick %d not increasing (prev %d)"
                        % (path, ln, t, last_tick)
                    )
                else:
                    last_tick = t
                if not isinstance(row.get("metrics"), dict):
                    problems.append(
                        "%s:%d: tick metrics must be an object" % (path, ln)
                    )
    if not saw_header:
        problems.append("%s: no header row" % path)
    return problems
