"""Host-side phase timing: dispatch timers around the jitted entry points.

The performance observatory's wall-clock plane (device-side latency
distributions live in ops.histogram / obs.histograms).  Three pieces:

- :class:`DispatchTimer` — wraps compiled callables (a driver's
  ``_tick``/``_scanned`` executables) with a properly-fenced timer:
  every call is timed to ``block_until_ready`` on its OUTPUTS (donation-
  safe — the fence never touches the possibly-donated inputs), and the
  jit cache size (the retrace prong's ``_cache_size`` machinery) is
  probed around the call so compile-carrying calls are split from warm
  executes and silent retraces are visible per phase.  The exact
  per-call warm walls are retained (ring-bounded), so the reported
  p50/p95/p99 are true nearest-rank order statistics of the measured
  dispatches — not bucket bounds.
- ``perf.phase`` runlog rows (:meth:`DispatchTimer.emit`) and a host-
  timeline Chrome-trace track (:meth:`DispatchTimer.chrome_trace_events`)
  that merges into the existing Perfetto export.
- :func:`timed_window` — the ONE warmup/measure loop shared by bench.py's
  phases (each previously hand-rolled warm-run/`perf_counter`/fence
  sequences) and benchmarks/tpu_measure.py.

``wrap_cluster`` instruments a driver (SimCluster / ScalableCluster /
RoutedStorm) non-invasively by rebinding its ``_tick``/``_scanned``
attributes — the underlying shared executables (module-level lru caches)
are untouched, so other instances keep their unwrapped handles.
"""

from __future__ import annotations

import contextlib
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ringpop_tpu.obs.histograms import (
    DEFAULT_QS,
    compute_protocol_delay,
)


@contextlib.contextmanager
def stopwatch(sink: Dict[str, float], key: str):
    """Accumulate the wall time of a ``with`` block into ``sink[key]``.

    The host-side sibling of :class:`DispatchTimer` for code that is not
    a dispatch (the analysis CLI's per-prong wall clocks, host phases of
    bench plumbing): seconds, monotonic, additive across re-entries."""
    t0 = time.perf_counter()
    try:
        yield
    finally:
        sink[key] = sink.get(key, 0.0) + (time.perf_counter() - t0)


def fence(value: Any) -> Any:
    """Block until every array in a pytree is ready; returns the value.
    The output-side fence is the donation-safe synchronization point —
    blocking on inputs that were donated to the call would read deleted
    buffers."""
    import jax

    return jax.block_until_ready(value)


def _cache_size(fn: Any) -> Optional[int]:
    probe = getattr(fn, "_cache_size", None)
    if probe is None:
        return None
    try:
        return int(probe())
    except Exception:
        return None


class PhaseStats:
    """Accumulated timing for one named phase.  The exact per-call warm
    walls are retained (ring-bounded) so the reported percentiles are
    true nearest-rank order statistics of the measured dispatches —
    not bucket bounds."""

    def __init__(self, name: str, keep_walls: int = 4096):
        self.name = name
        self.calls = 0
        self.total_s = 0.0
        self.compile_calls = 0  # calls that grew the jit cache
        self.compile_s = 0.0  # wall spent in those calls (trace+compile+run)
        self.cache_hits = 0  # calls OBSERVED warm via the cache probe
        self.warm_walls: List[float] = []  # exact walls, non-compile calls
        self._keep_walls = keep_walls
        self.last_s: Optional[float] = None

    def observe(self, wall_s: float, compiled: Optional[bool]) -> None:
        """``compiled`` is tri-state: True = the call grew the jit
        cache, False = the probe confirmed a cache hit, None = no probe
        (plain callables, host spans) — counted warm but never as a
        cache hit."""
        self.calls += 1
        self.total_s += wall_s
        self.last_s = wall_s
        if compiled:
            self.compile_calls += 1
            self.compile_s += wall_s
        else:
            if compiled is False:
                self.cache_hits += 1
            self.warm_walls.append(wall_s)
            if len(self.warm_walls) > self._keep_walls:
                del self.warm_walls[: -self._keep_walls]

    def warm_s(self) -> float:
        return self.total_s - self.compile_s

    def warm_calls(self) -> int:
        return self.calls - self.compile_calls

    def summary(self, qs: Sequence[float] = DEFAULT_QS) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "phase": self.name,
            "calls": self.calls,
            "wall_s": self.total_s,
            "compile_calls": self.compile_calls,
            "compile_s": self.compile_s,
            "cache_hits": self.cache_hits,
            "warm_calls": self.warm_calls(),
            "warm_s": self.warm_s(),
        }
        out.update(percentiles_exact(self.warm_walls, qs))
        return out


class DispatchTimer:
    """Per-phase dispatch timing with compile/execute split and a host
    timeline."""

    def __init__(
        self,
        clock: Callable[[], float] = time.perf_counter,
        keep_spans: int = 4096,
    ):
        self._clock = clock
        self._t0 = clock()
        self.phases: Dict[str, PhaseStats] = {}
        # (name, start_s, end_s, compiled) relative to timer birth; ring-
        # bounded so a long storm cannot grow the host timeline unboundedly
        self.spans: List[Tuple[str, float, float, bool]] = []
        self._keep_spans = keep_spans

    def _stats(self, name: str) -> PhaseStats:
        st = self.phases.get(name)
        if st is None:
            st = self.phases[name] = PhaseStats(name)
        return st

    def _note(
        self, name: str, t0: float, t1: float, compiled: Optional[bool]
    ) -> None:
        self._stats(name).observe(t1 - t0, compiled)
        self.spans.append(
            (name, t0 - self._t0, t1 - self._t0, bool(compiled))
        )
        if len(self.spans) > self._keep_spans:
            del self.spans[: -self._keep_spans]

    def wrap(self, name: str, fn: Callable) -> Callable:
        """Timed twin of a compiled callable: fence on outputs, cache-
        size probe around the call (None-tolerant for plain callables)."""

        def timed_call(*args, **kwargs):
            before = _cache_size(fn)
            t0 = self._clock()
            out = fence(fn(*args, **kwargs))
            t1 = self._clock()
            after = _cache_size(fn)
            compiled = (
                None
                if before is None or after is None
                else after > before
            )
            self._note(name, t0, t1, compiled)
            return out

        timed_call.__name__ = "timed_%s" % name
        timed_call.__wrapped__ = fn
        # sentinel for wrap_cluster's idempotence check: jax.jit
        # wrappers already carry __wrapped__, so that attr can't tell
        # "already timed" from "plain jitted".  The bound timer rides
        # along so a re-instrumentation can recover it.
        timed_call.__perf_timed__ = True
        timed_call.__perf_timer__ = self
        return timed_call

    def phase(self, name: str):
        """Context manager timing an arbitrary host-side span.  No
        cache probe exists here, so ``compiled`` is recorded as None
        (unknown) — the span counts warm but NEVER as a cache hit
        (cache_hits must mean an observed probe, not an assumption)."""
        timer = self

        class _Span:
            def __enter__(self_inner):
                self_inner._s0 = timer._clock()
                return self_inner

            def __exit__(self_inner, exc_type, exc, tb):
                timer._note(name, self_inner._s0, timer._clock(), None)

        return _Span()

    # -- reporting --------------------------------------------------------

    def summary(self, qs: Sequence[float] = DEFAULT_QS) -> List[Dict[str, Any]]:
        return [
            self.phases[name].summary(qs) for name in sorted(self.phases)
        ]

    def emit(self, recorder, qs: Sequence[float] = DEFAULT_QS, **extra) -> int:
        """One ``perf.phase`` event row per phase onto a RunRecorder
        (field set validated by scripts/check_metrics_schema.py)."""
        rows = 0
        for row in self.summary(qs):
            recorder.record_event("perf.phase", **row, **extra)
            rows += 1
        return rows

    def emit_statsd(self, bridge, key_map: Optional[Dict[str, str]] = None) -> int:
        """Per-phase warm p50/p95/p99 as statsd TIMER samples through a
        StatsdBridge (``|ms`` wire type) — phase names mapped onto the
        reference timing-key scheme via ``key_map`` (default:
        obs.statsd_bridge.PERF_TIMER_KEYS, unmapped phases ride
        ``sim.perf.<phase>``)."""
        from ringpop_tpu.obs.statsd_bridge import PERF_TIMER_KEYS

        key_map = PERF_TIMER_KEYS if key_map is None else key_map
        emitted = 0
        for row in self.summary():
            key = key_map.get(row["phase"], "sim.perf.%s" % row["phase"])
            for q in ("p50_ms", "p95_ms", "p99_ms"):
                v = row.get(q)
                if v is not None:
                    bridge.timing("%s.%s" % (key, q[:-3]), v)
                    emitted += 1
        return emitted

    def chrome_trace_events(self, pid: int = 0, tid: int = 0) -> List[dict]:
        """The host-timeline track: complete ("X") Trace Event Format
        events, microsecond timestamps, one per recorded span — merged
        into the flight-recorder Perfetto export by
        obs.chrome_trace.add_host_timeline."""
        events = []
        for name, s0, s1, compiled in self.spans:
            events.append(
                {
                    "name": name,
                    "ph": "X",
                    "pid": pid,
                    "tid": tid,
                    "ts": s0 * 1e6,
                    # >= 1 us: the trace schema requires X spans dur > 0
                    "dur": max((s1 - s0) * 1e6, 1.0),
                    "cat": "host",
                    "args": {"compiled": compiled},
                }
            )
        return events

    # -- the load-bearing consumer ---------------------------------------

    def protocol_delay_ms(
        self, phase: str = "tick", min_period_ms: float = 200.0
    ) -> float:
        """computeProtocolDelay over a phase's exact warm-dispatch
        walls: ``max(2 * p50, minProtocolPeriod)``
        (lib/gossip/index.js:42-50).  The phase wall IS the simulated
        ping round's host latency."""
        st = self.phases.get(phase)
        p50 = None
        if st is not None:
            p50 = percentiles_exact(st.warm_walls, (50,))["p50_ms"]
        return compute_protocol_delay(p50, min_period_ms)


def wrap_cluster(cluster, timer: Optional[DispatchTimer] = None) -> DispatchTimer:
    """Instrument a driver's compiled entry points in place: rebinds the
    instance's ``_tick`` / ``_scanned`` attributes (SimCluster /
    BatchedSimClusters / ScalableCluster / RoutedStorm — a RoutedStorm's
    inner cluster handles stay untouched; the routed driver dispatches
    through its own ``_tick``/``_scanned``).  Drivers that dispatch
    through structure-keyed module caches instead of instance handles
    (ShardedStorm) get their public ``step``/``run`` wrapped — same
    phase names and fencing, no jit-cache probe (compile split reads
    None there).  Returns the timer; re-instrumenting an
    already-wrapped driver without an explicit ``timer`` returns the
    ORIGINAL bound timer (the one the dispatches flow into), never a
    fresh disconnected one."""
    # ShardedSim names its scan handle _scan, the other drivers _scanned
    _HANDLES = (("_tick", "tick"), ("_scanned", "scan"), ("_scan", "scan"))
    if timer is None:
        for attr, _ in _HANDLES + (("step", "tick"), ("run", "scan")):
            fn = getattr(cluster, attr, None)
            if fn is not None and getattr(fn, "__perf_timed__", False):
                timer = fn.__perf_timer__
                break
    timer = timer or DispatchTimer()
    wrapped = False
    for attr, phase in _HANDLES:
        fn = getattr(cluster, attr, None)
        if fn is not None:
            wrapped = True
            if not getattr(fn, "__perf_timed__", False):
                setattr(cluster, attr, timer.wrap(phase, fn))
    if not wrapped:
        for attr, phase in (("step", "tick"), ("run", "scan")):
            fn = getattr(cluster, attr, None)
            if fn is not None and not getattr(fn, "__perf_timed__", False):
                setattr(cluster, attr, timer.wrap(phase, fn))
    return timer


def timed_window(
    run: Callable[[], Any],
    warmup: int = 1,
    repeats: int = 1,
    recorder=None,
    phase: Optional[str] = None,
    timer: Optional[DispatchTimer] = None,
    **extra: Any,
) -> Tuple[Any, float]:
    """The shared warm-then-measure loop (bench.py phases previously
    hand-rolled this): call ``run`` ``warmup`` times (compile + first
    dispatch, unmeasured), then ``repeats`` times fenced and timed.
    Returns ``(last_result, measured_wall_s)`` — the wall covers ALL
    measured repeats.  With ``recorder`` + ``phase`` a ``perf.phase``
    event row is stamped (calls/warm percentiles from the per-repeat
    walls); ``timer`` accumulates into an existing DispatchTimer
    instead of a throwaway one."""
    for _ in range(warmup):
        fence(run())
    timer = timer or DispatchTimer()
    out = None
    t0 = time.perf_counter()
    for _ in range(repeats):
        with timer.phase(phase or "window"):
            out = fence(run())
    wall = time.perf_counter() - t0
    if recorder is not None and phase is not None:
        row = timer.phases[phase].summary()
        row.update(extra)
        recorder.record_event("perf.phase", **row)
    return out, wall


def percentiles_exact(walls_s: Sequence[float], qs=DEFAULT_QS) -> Dict[str, float]:
    """Exact (un-bucketed) nearest-rank percentiles of raw wall samples
    in ms — for callers that kept the per-call walls."""
    arr = np.sort(np.asarray(list(walls_s), np.float64))
    out = {}
    for q in qs:
        if arr.size == 0:
            out["p%g_ms" % q] = None
        else:
            rank = max(1, int(np.ceil(q / 100.0 * arr.size)))
            out["p%g_ms" % q] = float(arr[rank - 1] * 1e3)
    return out
