"""Device-side protocol flight recorder — the in-tick event emitter.

The scanned SWIM tick (models/sim/engine.py) is a pure function; nothing
host-side can observe WHICH node learned WHICH rumor WHEN without either
a host callback in the scan (forbidden — the jaxgate purity contract) or
per-tick state dumps (O(N^2) transfers per tick).  This module is the
third way: a fixed-capacity structured event buffer carried through the
scan as ordinary ``SimState`` fields, appended to with masked scatters
under the *same masks that drive the trajectory* — so the recorder is
trajectory-neutral by construction (pinned by the gate-equivalence test
in tests/models/test_flight_recorder.py), compiles to pure scatter ops
(audited callback-free by the jaxpr prong's recorder-enabled entry), and
drains to the host once per ``run()``/``step()`` instead of per tick.

Buffer contract: a LINEAR buffer of ``event_capacity`` fixed-width int32
records (layout: obs/events.py) plus a write head and a drop counter.
On overflow, NEW events are dropped and counted — never silently
overwritten — so a truncated stream is an honest prefix
(``SimState.ev_drops`` nonzero flags the truncation).

Write mechanics: each emission flattens a trajectory mask, enumerates
the selected lanes with a cumulative sum (``rank = cumsum(mask) - 1``),
scatters the records at ``head + rank`` with out-of-capacity lanes
routed to a dropped scatter slot (``mode="drop"``), and advances the
head — static shapes throughout, no ``nonzero``, scan-safe.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from ringpop_tpu.obs import events as ev

ALIVE, SUSPECT, FAULTY = 0, 1, 2


def max_events_per_tick(n: int, ping_req_size: int = 3) -> int:
    """Exact upper bound on records one tick can append — the sum of
    every emission mask's lane count in :func:`record_tick_events`:
    pings [N] + status [N, N] + suspect [N, N] + faulty [N, N] +
    full-sync [N] + ping-req full-sync [N, K] + 4 refute lanes [N] +
    joins [N].  Consumers sizing drop-free buffers (the fuzz executor's
    ``event_capacity_for``) derive from THIS so the contract lives next
    to the emitters."""
    return 3 * n * n + (7 + ping_req_size) * n


def init_recorder_fields(n: int, capacity: int):
    """(ev_buf, ev_head, ev_drops, first_heard) initial values.

    ``first_heard[i, j]`` is the tick at which observer i first adopted
    j's CURRENT rumor (-1 = holds only what it was born with; the self
    view is born at tick 0) — the device-resident wavefront matrix that
    survives even when the event buffer overflows."""
    import numpy as np

    eye = np.eye(n, dtype=bool)
    return (
        jnp.zeros((capacity, ev.RECORD_WIDTH), jnp.int32),
        jnp.int32(0),
        jnp.int32(0),
        jnp.asarray(np.where(eye, 0, -1).astype(np.int32)),
    )


def append_events(
    buf: jax.Array,  # [cap, RECORD_WIDTH] int32
    head: jax.Array,  # scalar int32
    drops: jax.Array,  # scalar int32
    mask: jax.Array,  # [M] bool — which candidate lanes are real events
    tick,  # scalar int32
    kind: int,  # static kind code
    observer,  # [M] int32 (or scalar, broadcast)
    subject,  # [M] int32 (or scalar)
    old_status,  # [M] int32 (or scalar)
    new_status,  # [M] int32 (or scalar)
    inc,  # [M] int32 (or scalar)
    aux,  # [M] int32 (or scalar)
):
    """Masked append of up to M candidate events.  Returns the updated
    (buf, head, drops).  Event order within one append follows lane
    order (flattened row-major for [N, N] masks) — deterministic."""
    cap = buf.shape[0]
    m = mask.shape[0]
    mask_i = mask.astype(jnp.int32)
    # dtype pinned: under x64, sum/cumsum of int32 promote to int64 —
    # which would widen the scan carry (ev_head) and break carry-type
    # equality between tick input and output
    total = jnp.sum(mask_i, dtype=jnp.int32)
    rank = jnp.cumsum(mask_i, dtype=jnp.int32) - 1  # selected: 0..total-1
    pos = head + rank
    tgt = jnp.where(mask & (pos < cap), pos, cap)  # cap drops

    def lane(v):
        arr = jnp.asarray(v, dtype=jnp.int32)
        return jnp.broadcast_to(arr, (m,))

    rec = jnp.stack(
        [
            lane(tick),
            lane(jnp.int32(kind)),
            lane(observer),
            lane(subject),
            lane(old_status),
            lane(new_status),
            lane(inc),
            lane(aux),
        ],
        axis=1,
    )
    buf = buf.at[tgt].set(rec, mode="drop")
    head_new = jnp.minimum(head + total, cap)
    drops = drops + jnp.maximum(head + total - cap, 0)
    return buf, head_new, drops


class TickEventMasks(NamedTuple):
    """Everything the end-of-tick emission needs, gathered from the
    phase outputs (all derived from the masks that drove the
    trajectory; the emission itself reads — never writes — protocol
    state)."""

    valid_send: jax.Array  # [N] bool
    target: jax.Array  # [N] int32
    delivered: jax.Array  # [N] bool
    applied_ping: jax.Array  # [N, N] bool
    applied_resp: jax.Array  # [N, N] bool
    applied_pr: jax.Array  # [N, N] bool
    ja_applied: jax.Array  # [N, N] bool
    applied_sus: jax.Array  # [N, N] bool
    applied_faulty: jax.Array  # [N, N] bool
    joined: jax.Array  # [N] bool
    full_sync: jax.Array  # [N] bool (ping path, indexed by sender)
    fs_rec_rows: jax.Array  # [N] int32 — records per ping-path full sync
    pr_fs_mask: jax.Array  # [N, K] bool — ping-req full syncs
    pr_fs_recs: jax.Array  # [N, K] int32 — records per ping-req full sync
    pr_sel: jax.Array  # [N, K] int32 — selected intermediaries
    refute_recv: jax.Array  # [N] bool — self-refutes in the receive phase
    refute_resp: jax.Array  # [N] bool — ... in the response phase
    refute_prm: jax.Array  # [N] bool — ... at ping-req intermediaries leg
    refute_prr: jax.Array  # [N] bool — ... at ping-req responses leg
    revived: jax.Array  # [N] bool — process restarted (views reset)
    left: jax.Array  # [N] bool — graceful-leave self-write this tick
    rejoined: jax.Array  # [N] bool — rejoin-of-left self-write this tick


def record_tick_events(
    state,  # engine.SimState AFTER the tick's phases ran
    tick,  # scalar int32 — this tick's index (state.tick_index)
    prev_known: jax.Array,  # [N, N] bool — views at tick START
    prev_status: jax.Array,  # [N, N] int32
    masks: TickEventMasks,
):
    """Append this tick's events; returns state with updated ev_* and
    first_heard fields.  Emission order is fixed (pings, status changes,
    verdicts, full syncs, refutes, joins) so decoded streams are
    deterministic and stable across gate_phases settings."""
    n = prev_known.shape[0]
    ids = jnp.arange(n, dtype=jnp.int32)
    row = jnp.broadcast_to(ids[:, None], (n, n)).reshape(-1)
    col = jnp.broadcast_to(ids[None, :], (n, n)).reshape(-1)
    is_self = ids[:, None] == ids[None, :]

    buf, head, drops = state.ev_buf, state.ev_head, state.ev_drops
    zero = jnp.int32(0)
    none = jnp.int32(-1)

    # 1. pings: one event per initiated direct probe
    buf, head, drops = append_events(
        buf, head, drops,
        masks.valid_send,
        tick, ev.EV_PING,
        observer=ids,
        subject=jnp.clip(masks.target, 0, n - 1),
        old_status=none, new_status=none, inc=zero,
        aux=masks.delivered.astype(jnp.int32),
    )

    # 2. view changes: the union of every apply mask, with a phase
    # bitmask in aux.  old view is the tick-start view (-1 = unknown),
    # new view is the END-of-tick view — a cell touched by several
    # phases emits ONE event carrying its final value for the tick.
    join_learned = masks.joined[:, None] & state.known & ~is_self
    # operator-plane leave/rejoin write the origin's OWN view outside
    # the gossip apply masks — fold their diagonal cells in so the
    # rumor's birth event exists (chrome-trace self-status spans, and
    # rumor_wavefronts hop-0 attribution, both key off it)
    admin_self = (masks.left | masks.rejoined)[:, None] & is_self
    phase_bits = (
        masks.applied_ping.astype(jnp.int32) * ev.PHASE_PING_RECV
        + masks.applied_resp.astype(jnp.int32) * ev.PHASE_RESPONSE
        + masks.applied_pr.astype(jnp.int32) * ev.PHASE_PING_REQ
        + (masks.ja_applied | join_learned).astype(jnp.int32) * ev.PHASE_JOIN
        + masks.applied_faulty.astype(jnp.int32) * ev.PHASE_EXPIRY
        + admin_self.astype(jnp.int32) * ev.PHASE_ADMIN
    )
    changed = phase_bits > 0
    old_st = jnp.where(prev_known, prev_status, -1)
    buf, head, drops = append_events(
        buf, head, drops,
        changed.reshape(-1),
        tick, ev.EV_STATUS,
        observer=row, subject=col,
        old_status=old_st.reshape(-1),
        new_status=state.status.reshape(-1),
        inc=state.inc.reshape(-1),
        aux=phase_bits.reshape(-1),
    )

    # 3/4. detection verdicts (subsets of the status events above, kept
    # as distinct kinds so the failure-detection plane reconciles
    # one-to-one with suspects_marked / faulties_marked)
    buf, head, drops = append_events(
        buf, head, drops,
        masks.applied_sus.reshape(-1),
        tick, ev.EV_SUSPECT,
        observer=row, subject=col,
        old_status=old_st.reshape(-1),
        new_status=jnp.int32(SUSPECT),
        inc=state.inc.reshape(-1),
        aux=zero,
    )
    buf, head, drops = append_events(
        buf, head, drops,
        masks.applied_faulty.reshape(-1),
        tick, ev.EV_FAULTY,
        observer=row, subject=col,
        old_status=old_st.reshape(-1),
        new_status=jnp.int32(FAULTY),
        inc=state.inc.reshape(-1),
        aux=zero,
    )

    # 5. full syncs: ping path (sender <- target), then ping-req path
    # (sender <- intermediary), aux = member records carried
    buf, head, drops = append_events(
        buf, head, drops,
        masks.full_sync,
        tick, ev.EV_FULL_SYNC,
        observer=ids,
        subject=jnp.clip(masks.target, 0, n - 1),
        old_status=none, new_status=none, inc=zero,
        aux=masks.fs_rec_rows,
    )
    k = masks.pr_fs_mask.shape[1]
    obs_k = jnp.broadcast_to(ids[:, None], (n, k)).reshape(-1)
    buf, head, drops = append_events(
        buf, head, drops,
        masks.pr_fs_mask.reshape(-1),
        tick, ev.EV_FULL_SYNC,
        observer=obs_k,
        subject=jnp.clip(masks.pr_sel, 0, n - 1).reshape(-1),
        old_status=none, new_status=none, inc=zero,
        aux=masks.pr_fs_recs.reshape(-1),
    )

    # 6. refutes: one event per phase a node re-asserted itself in, so
    # the count reconciles exactly with TickMetrics.refutes (which sums
    # per-phase refute cells)
    self_inc = jnp.diagonal(state.inc)
    for phase_bit, mask in (
        (ev.PHASE_PING_RECV, masks.refute_recv),
        (ev.PHASE_RESPONSE, masks.refute_resp),
        (ev.PHASE_PING_REQ, masks.refute_prm),
        (ev.PHASE_PING_REQ, masks.refute_prr),
    ):
        buf, head, drops = append_events(
            buf, head, drops,
            mask,
            tick, ev.EV_REFUTE,
            observer=ids, subject=ids,
            old_status=none,
            new_status=jnp.int32(ALIVE),
            inc=self_inc,
            aux=jnp.int32(phase_bit),
        )

    # 7. joins: aux = members learned in the merge
    buf, head, drops = append_events(
        buf, head, drops,
        masks.joined,
        tick, ev.EV_JOIN,
        observer=ids, subject=none,
        old_status=none, new_status=none, inc=zero,
        aux=jnp.sum(join_learned, axis=1, dtype=jnp.int32),
    )

    # device-resident wavefront matrix: first-heard tick of the CURRENT
    # rumor per (observer, subject) — every adoption this tick stamps
    # it.  A revived process lost its views, so its row resets (the
    # reborn self view is born this tick)
    rv2 = masks.revived[:, None]
    first_heard = jnp.where(
        rv2, jnp.where(is_self, tick, -1), state.first_heard
    )
    first_heard = jnp.where(changed, tick, first_heard)
    return state._replace(
        ev_buf=buf, ev_head=head, ev_drops=drops, first_heard=first_heard
    )
