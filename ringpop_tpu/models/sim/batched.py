"""BatchedSimClusters — B independent full-fidelity clusters, ONE program.

At tick-cluster scale (n ~ 1k) the full [N, N] engine's ops are a few MB
each and a single cluster leaves the chip >90% idle — the tick is op-
overhead-bound, not bandwidth-bound (RESULTS.md, PROF_R4.json).  Batching
B clusters on a leading axis via ``jax.vmap`` turns every [N, N] op into a
[B, N, N] op at the same op count, so aggregate throughput scales toward
the hardware roofline while each cluster's trajectory remains EXACTLY the
single-cluster trajectory for its seed (vmap is semantics-preserving;
asserted in tests/models/test_batched.py).

This is the analog of running B tick-cluster harnesses side by side
(/root/reference/scripts/tick-cluster.js spawns one OS process per node;
B clusters means B*N processes for the reference — the batched simulator
runs them all in one compiled scan).

The engine's rare-phase conds are disabled (``gate_phases=False``): under
vmap a ``lax.cond`` with a BATCHED predicate lowers to a run-both
``select``, and the heavy phases' predicates are state-derived (ping
failures, suspicion expiry, checksum mismatch) and therefore batched —
gating could survive vmap only for predicates drawn purely from the
unmapped shared schedule.  Trajectories are unaffected (the two settings
are bitwise-identical; tests/models/test_sim.py).

Measured consequence (round 4, CPU-pinned so tunnel noise is excluded):
straight-line costs ~5x a gated tick at 1k (60 vs 12 ms — the rare
phases dominate when they run every tick), so batched aggregate
throughput currently LOSES to one gated cluster (9.1k vs 86k CPU
node-ticks/s; same ordering on the chip).  The utilization configuration
only pays off if the rare phases get cheap enough to run always-on;
until then the single-cluster gated engine is the throughput
configuration and this runner is for trajectory-exact ensemble runs
(B seeds, one program), not speed.
"""

from __future__ import annotations

import functools
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ringpop_tpu.models.sim import engine
from ringpop_tpu.models.sim.cluster import EventSchedule, default_addresses
from ringpop_tpu.ops import checksum_encode as ce


@functools.lru_cache(maxsize=None)
def _vtick_fn(params: engine.SimParams, universe: ce.Universe):
    step = functools.partial(engine.tick, params=params, universe=universe)
    return jax.jit(jax.vmap(step, in_axes=(0, None)))


@functools.lru_cache(maxsize=None)
def _vscanned_fn(params: engine.SimParams, universe: ce.Universe):
    step = functools.partial(engine.tick, params=params, universe=universe)
    vstep = jax.vmap(step, in_axes=(0, None))

    @jax.jit
    def _scanned(state, inputs):
        return jax.lax.scan(vstep, state, inputs)

    return _scanned


def clear_executable_cache() -> None:
    _vtick_fn.cache_clear()
    _vscanned_fn.cache_clear()


class BatchedSimClusters:
    def __init__(
        self,
        b: int,
        n: int,
        params: Optional[engine.SimParams] = None,
        seed: int = 0,
    ):
        self.b, self.n = b, n
        addresses = default_addresses(n)
        self.universe = ce.Universe.from_addresses(addresses)
        from ringpop_tpu.models.sim.cluster import _resolve_hash_impl

        base = params or engine.SimParams(n=n, checksum_mode="fast")
        self.params = _resolve_hash_impl(
            base._replace(n=n, gate_phases=False)
        )
        if (
            self.params.checksum_mode == "farmhash"
            and self.params.parity_recompute == "bounded"
        ):
            # this runner has no overflow-replay plumbing (a per-cluster
            # overflow would need per-cluster replays under vmap); pin the
            # straight-line exact shape instead — same philosophy as
            # gate_phases=False above
            self.params = self.params._replace(parity_recompute="full")
        states: List[engine.SimState] = [
            engine.init_state(self.params, seed=seed + i, universe=self.universe)
            for i in range(b)
        ]
        # [B, ...] leading axis on every state field
        self.state = jax.tree.map(lambda *xs: jnp.stack(xs), *states)

        # shared per-(params, universe) executables, as in SimCluster
        self._scanned = _vscanned_fn(self.params, self.universe)
        self._vtick = _vtick_fn(self.params, self.universe)
        # optional telemetry sink (obs.RunRecorder via attach_recorder)
        self.recorder = None

    def attach_recorder(self, recorder) -> None:
        """Attach an obs.RunRecorder; bootstrap()/run() metrics fold into
        it.  Rows carry per-cluster [B] vectors per counter (the vmapped
        leading axis); totals fold only the scalar fields."""
        recorder.describe("sim.engine[batched]", self.n, self.params, b=self.b)
        self.recorder = recorder

    def bootstrap(self) -> engine.TickMetrics:
        inputs = engine.TickInputs.quiet(self.n)._replace(
            join=jnp.ones(self.n, bool)
        )
        self.state, m = self._vtick(self.state, inputs)
        m = jax.tree.map(np.asarray, m)
        if self.recorder is not None:
            self.recorder.record_ticks(jax.tree.map(lambda a: a[None], m))
        return m

    def run(self, schedule: EventSchedule) -> engine.TickMetrics:
        """Scan the same [T, N] event schedule through every cluster;
        metrics come back [T, B]-shaped."""
        self.state, ms = self._scanned(self.state, schedule.as_inputs())
        ms = jax.tree.map(np.asarray, ms)
        if self.recorder is not None:
            self.recorder.record_ticks(ms)
        return ms

    def checksums(self) -> np.ndarray:
        """[B, N] per-cluster membership checksums."""
        return np.asarray(self.state.checksum)

    # -- flight recorder (SimParams.flight_recorder) ----------------------

    def drain_events(self, reset: bool = True):
        """Per-cluster flight-recorder drain: returns a list of B
        decoded event streams (the vmapped buffers carry a [B] leading
        axis).  Feeds the attached RunRecorder one ``flight_drain``
        event row with per-cluster counts."""
        if self.state.ev_buf is None:
            raise ValueError(
                "flight recorder is off — construct with "
                "SimParams(flight_recorder=True)"
            )
        from ringpop_tpu.obs import events as obs_events

        bufs = np.asarray(self.state.ev_buf)
        heads = np.asarray(self.state.ev_head)
        drops = np.asarray(self.state.ev_drops)
        streams = [
            obs_events.decode_events(bufs[b], heads[b], drops[b])
            for b in range(self.b)
        ]
        if self.recorder is not None:
            self.recorder.record_event(
                "flight_drain",
                events=[len(s) for s in streams],
                drops=drops.tolist(),
            )
        # reset LAST: a raising recorder sink leaves the window on
        # device for a retry instead of silently losing it
        if reset:
            self.state = self.state._replace(
                ev_head=jnp.zeros(self.b, jnp.int32),
                ev_drops=jnp.zeros(self.b, jnp.int32),
            )
        return streams
