"""The batched SWIM protocol period — one gossip tick for N nodes at once.

This is the TPU-native heart of the framework: the reference's event-loop of
timers, callbacks and RPCs (lib/gossip/index.js tick at :135-192, ping/
ping-req senders, dissemination, suspicion) becomes ONE pure function
``tick(state, inputs, params) -> (state, metrics)`` over dense arrays with an
N-node axis, scanned by ``lax.scan`` and shardable over a device mesh.

State model (full-fidelity mode): node i's *view* of node j is
``(known, status, incarnation)[i, j]``; the dissemination change table
(dissemination.js ``this.changes``) is the ``ch_*[i, j]`` arrays; suspicion
timers are per-(i, j) deadline ticks.  The SWIM member update rules
(member.js:71-202) are a vectorized precedence gate; conflicting same-tick
updates from multiple senders are combined with a (incarnation, status-rank)
key-max before gating — see ``_overrides`` for the exact table.

Discrete-time model and its documented deviation envelope:

- One tick == one protocol period for every live node simultaneously (the
  reference staggers first ticks by 0..200 ms and adapts period length;
  under a controlled schedule those only permute message interleavings).
- Incarnation clock: ``now_ms = epoch_ms + tick_index * period_ms`` replaces
  ``Date.now()`` so trajectories are exactly reproducible.  Because every
  incarnation value lies on that grid, the engine stores incarnations as
  int32 *stamps* (0 = unknown; stamp s > 0 <=> epoch_ms + (s-1)*period_ms)
  — TPUs emulate 64-bit integer ops, so keeping the hot [N, N] state and
  the segment-max combine in 32-bit lanes is the difference between the
  chip winning and losing vs the host CPU.  The int64 ms value is
  reconstructed (``stamp_to_ms``) only inside the dirty-row parity
  checksum encode and at host inspection boundaries.
- A failed direct ping triggers ping-req *within the same tick* (the
  reference's 1.5s/5s timeouts span protocol periods; the sender's gossip
  loop blocks on the exchange either way, gossip/index.js:61-87).
- Ping-req carries dissemination both ways like the reference (sender
  piggyback out, issueAsReceiver + full-sync back — see phase 7); the
  one remaining envelope: the intermediary's relay ping to the TARGET is
  modeled as reachability only (no piggyback on the M->T leg), and one
  loss draw covers each sender<->intermediary round trip.
- Within a tick, phases apply in a fixed order: join -> ping send ->
  receiver apply -> responses (incl. full-sync) -> sender apply -> ping-req
  -> suspicion expiry -> checksums.  The reference's per-message ordering is
  a race among sockets; any serialization of the same messages is inside its
  nondeterminism envelope.
- New members enter iteration order at an effectively random position: the
  per-node round-robin permutation is drawn over the whole universe up
  front, unknown members are skipped (the reference inserts new members at
  a random list position, membership/index.js:285).

Cited reference behavior preserved exactly:
- piggyback bump-even-on-failed-send (dissemination.js:142-155 TODO quirk),
  drop at ``count > 15 * ceil(log10(serverCount + 1))`` (dissemination.js:41).
- receiver filters changes originated by the pinging sender
  (dissemination.js:91-98); full membership sync when no changes remain and
  checksums disagree (dissemination.js:101-114).
- refute: a node seeing itself suspect/faulty re-asserts alive with a fresh
  incarnation (member.js:76-81) — and the refuted update keeps the original
  update's source, matching `_.defaults` there.
- suspect -> 5s (in ticks) -> faulty with the member's *current* incarnation
  (suspicion.js:65-70); timers restart on re-suspect, stop on non-suspect
  updates (on_membership_event.js:86-104).
- ping-req: k=3 random pingable members excluding the target
  (ping-req-sender.js:293-296); all-responders-say-unreachable => suspect
  (ping-req-sender.js:249-262); no responders => inconclusive, no-op; the
  exchange carries dissemination both ways (issueAsSender per body,
  ping-req-sender.js:74-79; issueAsReceiver + full-sync in the answer,
  server/protocol/ping-req.js:62-66) and the suspect verdict lands after
  the response changes apply (ping-req-sender.js:132-139).
"""

from __future__ import annotations

import math
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ringpop_tpu.ops import checksum_encode as ce
from ringpop_tpu.ops import fused_apply as fap
from ringpop_tpu.ops import fused_piggyback as fpb
from ringpop_tpu.ops import jax_farmhash as jfh
from ringpop_tpu.ops import toolkit
from ringpop_tpu.ops.exchange import popcount_u32
from ringpop_tpu.models.sim.gating import phase as _phase
from ringpop_tpu.ops.record_mix import record_mix

# status codes (== ce.STATUS_*): rank order IS override priority at equal
# incarnation: alive < suspect < faulty < leave
ALIVE, SUSPECT, FAULTY, LEAVE = 0, 1, 2, 3

# numpy scalar, not jnp: module import must not initialize a backend
# (the ambient env registers a single-client TPU tunnel that can be
# broken/held; device init belongs to callers)
NO_TARGET = np.int32(-1)

# Latency-histogram track layout (SimParams.histograms; SimState.hist
# rows, in order).  Observation units are TICKS (one tick == one
# protocol period):
# - rumor_age: age of an adopted alive-assertion rumor at first-heard —
#   gossip-apply cells landing status ALIVE, age = adoption tick + 1 -
#   incarnation stamp.  Exact for alive-class rumors because a fresh
#   incarnation's stamp IS its mint tick (the discrete-clock identity in
#   the module docstring); suspect/faulty rumors reuse the member's
#   older incarnation, so they are deliberately excluded rather than
#   recorded with overstated ages.
# - retired_age: same stamp-age of a change at piggyback retirement
#   (the dissemination.js:41 drop) — cells active before the
#   dissemination phases and inactive after them.  Envelope: a change
#   both recorded AND retired within one tick's phases 5-7 is counted;
#   one re-activated after an earlier drop in the same tick is not (no
#   net retirement).
# - suspicion_duration: ticks a (observer, subject) suspicion timer ran
#   when it stopped — refuted/overridden (member alive again) or
#   expired to faulty.  Revive view-resets are excluded (the timer
#   didn't resolve; the observer forgot it).
# - dirty_rows: per-tick dirty-row recompute batch size (the checksum
#   pipeline's work distribution) — one observation per tick.
HIST_TRACKS = ("rumor_age", "retired_age", "suspicion_duration", "dirty_rows")


class SimParams(NamedTuple):
    """Static protocol constants (compile-time)."""

    n: int
    period_ms: int = 200  # gossip/index.js:194-196
    epoch_ms: int = 1414142122274
    suspicion_ticks: int = 25  # 5000 ms / 200 ms — suspicion.js:111-113
    ping_req_size: int = 3  # index.js:113
    join_size: int = 3  # join-sender.js:52
    piggyback_factor: int = 15  # dissemination.js:180
    max_digits: int = 14  # incarnation digit bound (ms epoch timestamps)
    packet_loss: float = 0.0
    # parity-mode checksum recompute: when <= this many rows are dirty,
    # only THOSE rows are gathered, encoded, and hashed (a bounded batch
    # keeps shapes static); beyond it the full-membership recompute runs.
    # An epidemic wave's per-tick newly-dirty counts are 1,2,4,...,N/2, so
    # the batch bound matters up to fairly large values: measured at 1k
    # nodes under churn, K=64 -> 1116 ms/tick, K=256 -> 509 (sweet spot),
    # K=512 -> 717, old always-full recompute -> 1524
    dirty_batch: int = 256
    # "farmhash": bit-exact reference checksum (membership/index.js:48-75) —
    # required for parity runs.  "fast": commutative per-record hash sum with
    # identical equality semantics (equal views <=> equal sums, w.h.p.) —
    # the throughput mode; the serial 20-byte FarmHash block walk over a
    # ~40KB string per node per tick is the single hottest op otherwise.
    checksum_mode: str = "farmhash"
    # FarmHash block-loop lowering for the in-tick checksum hash:
    # "env" = read RINGPOP_TPU_PALLAS when the tick TRACES (the direct-
    # engine default), or an explicit jax_farmhash impl name.  SimCluster
    # resolves "env" to the concrete impl at construction so the shared
    # executable caches key on it — a trace-time env read would race with
    # toggles between construction and first call.
    hash_impl: str = "env"
    # parity-mode checksum recompute shape: "gated" = dirty-chunk
    # while_loop, skipping clean ticks entirely (the CPU win); "full" =
    # straight-line full-membership recompute every tick, NO control
    # flow — bit-identical (a clean row's recompute reproduces its
    # cached value), required on the axon tunnel whose compile helper
    # 500s on large bodies nested under while/cond (DIAG_PARITY_N.json +
    # the round-4 fine bisect: encode+hash compiles straight-line at any
    # size, fails inside while_loop).  "bounded" = ONE K-row
    # (dirty_batch) encode+hash chunk with no loop: the chunk body is
    # straight-line, optionally cond-gated off clean ticks like any
    # other phase — the TPU-compilable shape of the dirty-row win.  Ticks
    # with more than K dirty rows OVERFLOW (counted in
    # TickMetrics.parity_overflow); the driver must then discard the
    # run and replay it under an exact shape (SimCluster does this
    # automatically), because rows past the chunk would have kept stale
    # checksums and checksums feed full-sync decisions.  "auto" =
    # resolved to the backend's right answer at SimCluster construction.
    parity_recompute: str = "auto"
    # Fused encode+hash parity pipeline (ops.fused_checksum): "on" keeps
    # a per-(observer, subject) record-byte cache in SimState, re-encodes
    # only cells whose (known, status, incarnation) changed in the tick
    # (a churn wave touches O(wave) records, not O(N*N) bytes), and
    # hashes dirty rows with the gridless streaming Pallas kernel that
    # assembles the checksum string in VMEM — the [N, row_bytes] buffer
    # and its ~100 MB/s XLA byte-assembly floor are gone.  "off" is the
    # classic membership_rows + hash32_rows composition.  "auto" resolves
    # at SimCluster/ShardedSim construction (resolve_auto_parity): "on"
    # for farmhash mode on TPU, "off" elsewhere.  Bitwise-identical
    # checksums either way (pinned by tests/ops/test_fused_checksum.py
    # and the lockstep suite).
    fused_checksum: str = "auto"
    # fused bounded-parity cell chunk: the per-tick changed-cell
    # re-encode covers up to this many (observer, subject) cells in ONE
    # straight-line gather/encode/scatter; more changed cells than this
    # overflow exactly like dirty_batch row overflow (same replay
    # contract, counted in the same TickMetrics.parity_overflow).
    cell_batch: int = 16384
    # True: rare phases (revive, rejoin, join, reshuffle, piggyback,
    # apply, responses, ping-req, expiry) run under lax.cond and cost
    # nothing on ticks with nothing to do — the right call on CPU, where
    # skipped work is pure savings.  False: the same phases run
    # unconditionally as straight-line code.  Every gated branch is a
    # masked no-op on empty inputs (that WAS the engine before the
    # round-3 cond refactor, and the draws inside are salt-pure), so the
    # two settings are bitwise-identical in trajectory; on TPU the cond
    # boundaries block fusion and serialize the program, and vmapped
    # multi-cluster batching turns conds into run-both selects anyway.
    gate_phases: bool = True
    # Fused full-fidelity tick (round 16, ops/fused_apply.py +
    # ops/fused_piggyback.py): route the tick's six membership-update
    # application sites and four piggyback-budget sites through the
    # toolkit's fused row-streaming ops instead of the classic
    # phase-by-phase temporaries.  "pallas" = the gridless streaming
    # kernels ([N_tile, N] VMEM tiles; interpret off-TPU), "xla" = the
    # bit-exact pure-XLA twins (the CPU production path — the fused
    # sites return per-row/scalar reductions instead of dense [N, N]
    # started/refuted/applied masks, so far fewer full planes cross the
    # phase cond boundaries per tick), "off" = the classic shape (the
    # A/B baseline).  "auto" resolves at SimCluster construction
    # (resolve_fused_tick): "pallas" on TPU, "xla" elsewhere.
    # Bitwise-identical trajectories and metrics in every mode — pinned
    # by tests/models/test_fused_tick.py across gate_phases x
    # histograms x flight_recorder.
    fused_tick: str = "auto"
    # Device-side protocol flight recorder (models/sim/flight.py +
    # obs/events.py): when True the tick appends structured int32 event
    # records — pings, view changes, suspect/faulty verdicts, full
    # syncs, refutes, joins — into a fixed-capacity buffer carried
    # through the scan (SimState.ev_buf/ev_head/ev_drops) and maintains
    # the first-heard wavefront matrix (SimState.first_heard).  Written
    # with masked scatters under the same masks that drive the
    # trajectory: trajectory-neutral (gate-equivalence-tested) and
    # callback-free (jaxpr-audited).  Off by default: zero cost.
    flight_recorder: bool = False
    # event buffer capacity in records; overflow DROPS new events and
    # counts them (SimState.ev_drops) instead of overwriting — a
    # truncated stream is an honest prefix.  65536 records = 2 MB.
    event_capacity: int = 65536
    # Device-side latency histograms (ops/histogram.py + the
    # performance observatory's host half, obs/histograms.py): when True
    # the tick bumps log2-bucketed counters — rumor age at adoption and
    # at piggyback retirement (in ticks, measured against the
    # incarnation stamp-as-mint-time identity; see HIST_TRACKS),
    # suspicion duration at timer stop, per-tick dirty-row recompute
    # sizes — under the same masks that drive the trajectory.
    # Write-only within the tick (SimState.hist), trajectory-neutral
    # (gate-equivalence-tested) and callback-free (jaxpr-audited).
    # Off by default: zero cost.
    histograms: bool = False


class SimState(NamedTuple):
    """Per-node views + protocol state. All [N]- or [N, N]-shaped."""

    tick_index: jax.Array  # scalar int32
    # process-level (fault injection plane, not SWIM state)
    proc_alive: jax.Array  # [N] bool
    ready: jax.Array  # [N] bool (bootstrapped)
    gossip_on: jax.Array  # [N] bool
    partition: jax.Array  # [N] int32 — group id; unequal groups can't talk
    # membership views (incarnations are int32 stamps — see module docstring)
    known: jax.Array  # [N, N] bool
    status: jax.Array  # [N, N] int32
    inc: jax.Array  # [N, N] int32 stamp
    # dissemination change table (per node, keyed by subject)
    ch_active: jax.Array  # [N, N] bool
    ch_status: jax.Array  # [N, N] int32
    ch_inc: jax.Array  # [N, N] int32 stamp
    ch_source: jax.Array  # [N, N] int32
    ch_source_inc: jax.Array  # [N, N] int32 stamp
    ch_pb: jax.Array  # [N, N] int32 piggyback counts
    # suspicion deadlines (absolute tick; -1 inactive)
    susp_deadline: jax.Array  # [N, N] int32
    # iterator state, stored INVERSE: perm_inv[i, m] = position of member m
    # in node i's iteration order.  Target selection then needs only
    # elementwise walk-rank math + one argmin — no [N, N] gathers (TPU
    # gathers of permuted columns are far costlier than a row reduction)
    perm_inv: jax.Array  # [N, N] int32
    iter_pos: jax.Array  # [N] int32
    # per-node PRNG keys
    rng: jax.Array  # [N, 2] uint32
    # cached checksums
    checksum: jax.Array  # [N] uint32
    # fused-parity record cache (fused_checksum="on" only, else None):
    # rec_bytes[i, j] holds observer i's encoded "addr+status+inc+';'"
    # record for member j (zero-padded uint8, length rec_len[i, j]; 0 =
    # unknown member).  uint8 — not packed words — so the bounded-chunk
    # row gather can ride the one-hot f32 matmul (_rows) exactly.
    # Derivable from (known, status, inc): a loaded checkpoint without
    # the cache rebuilds it (SimCluster.load).
    rec_bytes: Optional[jax.Array] = None  # [N, N, R] uint8
    rec_len: Optional[jax.Array] = None  # [N, N] int32
    # flight-recorder plane (SimParams.flight_recorder only, else None):
    # write-only within the tick — nothing in the protocol reads these,
    # which is what makes the recorder trajectory-neutral by
    # construction.  Layout: obs/events.py.
    ev_buf: Optional[jax.Array] = None  # [event_capacity, 8] int32
    ev_head: Optional[jax.Array] = None  # scalar int32 — valid records
    ev_drops: Optional[jax.Array] = None  # scalar int32 — overflow count
    # first-heard wavefront matrix: tick at which observer i first
    # adopted j's current rumor (-1 = only the born-with view)
    first_heard: Optional[jax.Array] = None  # [N, N] int32
    # latency-histogram plane (SimParams.histograms only, else None):
    # [len(HIST_TRACKS), ops.histogram.NBUCKETS] uint32 log2-bucket
    # counters.  Write-only within the tick — trajectory-neutral by
    # construction; drained/reset host-side (SimCluster.drain_histograms)
    hist: Optional[jax.Array] = None


# Single-source field classification (ISSUE 15): every SimState field is
# either TRAJECTORY (part of the protocol state the gate-equivalence
# suites compare bitwise) or OBS-ONLY (a write-only telemetry plane that
# must be invisible to the trajectory).  The noninterference analysis
# prong (analysis/noninterference.py) proves STATICALLY, per traced
# entry point, that no obs-only input leaf reaches any trajectory output
# leaf — the structural form of the property the n=64/n=1k A/B suites
# sample dynamically.  A new field added to SimState MUST be classified
# in exactly one of these sets (tier-1 repo-scan gate:
# tests/analysis/test_state_registry.py).
SIM_OBS_ONLY_FIELDS = frozenset(
    {"ev_buf", "ev_head", "ev_drops", "first_heard", "hist"}
)
# spelled out (NOT derived as the complement) so that adding a SimState
# field without deciding its class fails the registry gate loudly
SIM_TRAJECTORY_FIELDS = frozenset(
    {
        "tick_index",
        "proc_alive",
        "ready",
        "gossip_on",
        "partition",
        "known",
        "status",
        "inc",
        "ch_active",
        "ch_status",
        "ch_inc",
        "ch_source",
        "ch_source_inc",
        "ch_pb",
        "susp_deadline",
        "perm_inv",
        "iter_pos",
        "rng",
        "checksum",
        "rec_bytes",
        "rec_len",
    }
)


class TickInputs(NamedTuple):
    """Per-tick event-schedule inputs (the fault-injection plane)."""

    kill: jax.Array  # [N] bool — SIGKILL/SIGSTOP this tick (proc_alive off)
    revive: jax.Array  # [N] bool — restart this tick (fresh state, rejoin)
    join: jax.Array  # [N] bool — bootstrap/join this tick
    partition: jax.Array  # [N] int32 — group assignment; -1 keeps current
    # [N] bool SIGCONT: bring the process back WITHOUT the state reset that
    # ``revive`` performs (tick-cluster 'l'/SIGSTOP + revive of a suspended
    # proc, scripts/tick-cluster.js:431-470); None = all-false
    resume: Optional[jax.Array] = None
    # [N] bool graceful leave: the node marks ITSELF status=leave at its
    # current incarnation and stops gossiping (membership.makeLeave +
    # LocalMemberLeaveEvent -> gossip.stop, on_membership_event.js:32-41).
    # The process stays up and keeps answering pings, so the leave change
    # disseminates via its ping responses.  A later `join` input on a left
    # node rejoins: alive with a fresh incarnation, gossip restarted
    # (server/admin/member.js:44-51).  None = all-false
    leave: Optional[jax.Array] = None

    @staticmethod
    def quiet(n: int) -> "TickInputs":
        # resume=None (not a dense array) keeps the pytree structure equal
        # to plain inputs — no jit retrace
        return TickInputs(
            kill=jnp.zeros(n, bool),
            revive=jnp.zeros(n, bool),
            join=jnp.zeros(n, bool),
            partition=jnp.full(n, -1, jnp.int32),
        )


class TickMetrics(NamedTuple):
    pings_sent: jax.Array
    pings_delivered: jax.Array
    ping_reqs: jax.Array
    full_syncs: jax.Array
    changes_applied: jax.Array
    suspects_marked: jax.Array
    faulties_marked: jax.Array
    distinct_checksums: jax.Array  # among participating (alive+ready) nodes
    converged: jax.Array  # bool
    # rows the "bounded" parity recompute could NOT cover this tick
    # (n_dirty - dirty_batch, clamped at 0; always 0 in other modes).
    # Nonzero means THIS TICK'S checksums are stale for the uncovered
    # rows and the trajectory from here is not parity-exact: the driver
    # must replay from the pre-run state with an exact recompute shape.
    parity_overflow: jax.Array
    # -- protocol counters the reference emits via statsd ---------------
    # (all scalar int32, derived from the same masks that drive the
    # trajectory — bitwise-identical under gate_phases True/False, and
    # identical across parity-recompute shapes)
    # applied self-refutes: a node saw itself suspect/faulty in an update
    # and re-asserted alive with a fresh incarnation (member.js:76-81)
    refutes: jax.Array
    # changes retired at the 15*ceil(log10(n+1)) piggyback bound
    # (dissemination.js:41), summed over the sender-select, receiver-bump
    # and both ping-req budget bumps
    piggyback_drops: jax.Array
    # member records carried inside full-sync responses this tick (the
    # bytes-equivalent of dissemination.js:101-114 full syncs; one record
    # ~= one "addr + status + incarnation" wire entry)
    full_sync_records: jax.Array
    # failed direct pings whose ping-req round had NO responding
    # intermediary: no verdict, no-op (ping-req-sender.js:249-262)
    ping_req_inconclusive: jax.Array
    # joiners that successfully merged a target's view this tick
    # (join-sender.js + join-response-merge)
    join_merges: jax.Array
    # rows whose view changed and therefore hit the checksum-recompute
    # path (mid-tick + end-of-tick dirty counts; which recompute SHAPE
    # runs is static per SimParams.parity_recompute/checksum_mode and is
    # recorded host-side by the run recorder)
    dirty_rows: jax.Array


# The exact SWIM precedence table (member.js:171-202), vectorized — the
# single source lives with the fused op (ops never imports upward);
# classic and fused paths share it by construction.
_overrides = fap.overrides


def stamp_to_ms(stamp: jax.Array, params: "SimParams") -> jax.Array:
    """int32 incarnation stamp -> the reference's int64 epoch-ms value.

    stamp 0 is the "never asserted" sentinel (encodes as decimal 0, exactly
    like the reference's zero incarnation); stamp s > 0 is
    ``epoch_ms + (s-1) * period_ms`` — the value ``Date.now()`` would have
    produced at that protocol period."""
    ms = (
        jnp.int64(params.epoch_ms)
        + (stamp.astype(jnp.int64) - 1) * params.period_ms
    )
    return jnp.where(stamp > 0, ms, jnp.int64(0))


def _pack_key(inc, status):
    """Winner-combine key: lexicographic (incarnation stamp, status-rank).

    Stamps are small (< ticks + 2), so the packed key stays well inside
    int32 — the phase-5 segment-max runs in 32-bit lanes on TPU."""
    return inc.astype(jnp.int32) * 4 + status.astype(jnp.int32)


def _self_view(mat: jax.Array) -> jax.Array:
    """Diagonal of an [N, N] per-observer matrix: each node's view of
    ITSELF (the self-incarnation reads scattered through the tick)."""
    return jnp.diagonal(mat)


def _max_piggyback(server_count: jax.Array, factor: int) -> jax.Array:
    """15 * ceil(log10(n + 1)) via integer digit count (dissemination.js:41)."""
    count = jnp.zeros(server_count.shape, jnp.int32)
    for k in range(10):  # server counts < 10^10
        count = count + (server_count >= 10**k).astype(jnp.int32)
    return factor * count


_COPRIME_CACHE: dict = {}


def _coprimes_of(n: int, k: int = 128):  # jaxgate: host
    """(coprimes, modular inverses): up to ``k`` integers coprime to ``n``,
    spread evenly over [1, n), plus their inverses mod n.

    Static per engine size (n is a compile-time constant): multipliers for
    the affine row permutations drawn at iterator reshuffle.  n*n must fit
    int32, which holds for every full-fidelity engine size (N^2 state caps
    N at a few thousand)."""
    got = _COPRIME_CACHE.get((n, k))
    if got is None:
        assert n < 46341, "affine reshuffle index math needs n*n < 2^31"
        cops = [a for a in range(1, n) if math.gcd(a, n) == 1]
        step = max(1, -(-len(cops) // k))  # ceil: even spread over [1, n)
        chosen = cops[::step][:k]
        got = (
            np.asarray(chosen, np.int32),
            np.asarray([pow(a, -1, n) for a in chosen], np.int32),
        )
        _COPRIME_CACHE[(n, k)] = got
    return got


def _fold(rng: jax.Array, salt: int) -> jax.Array:
    """Cheap per-node key derivation: [N, 2] uint32 -> new [N, 2] uint32."""
    k0 = rng[:, 0] * np.uint32(0x9E3779B9) + np.uint32(salt)
    k1 = rng[:, 1] ^ ((k0 << 13) | (k0 >> 19))
    k1 = k1 * np.uint32(0x85EBCA6B) + np.uint32(1)
    return jnp.stack([k1, k0 ^ k1], axis=1)


def _uniform(rng: jax.Array, shape, salt: int) -> jax.Array:
    """[N, ...] uniforms in [0, 1) derived per node (row i from rng[i])."""
    n = rng.shape[0]
    cols = math.prod(shape) // n
    base = rng[:, 0].astype(jnp.uint32)
    j = jnp.arange(cols, dtype=jnp.uint32)
    x = base[:, None] + j[None, :] * np.uint32(0x01000193) + np.uint32(salt)
    x ^= x >> 15
    x = x * np.uint32(0x2C1B3C6D)
    x ^= x >> 12
    x = x * np.uint32(0x297A2D39)
    x ^= x >> 15
    return (x.astype(jnp.float32) / np.float32(2**32)).reshape(shape)


def init_state(
    params: SimParams, seed: int = 0, universe: Optional[ce.Universe] = None
) -> SimState:
    """Every node knows only itself (alive, incarnation = epoch).

    ``universe`` seeds the per-node checksum cache with the real self-view
    checksums — REQUIRED in farmhash mode, where the tick only rehashes
    rows whose view changed (an idle node's pre-join checksum would
    otherwise stay at the zero placeholder)."""
    if params.checksum_mode == "farmhash" and universe is None:
        raise ValueError(
            "farmhash checksum mode needs the universe at init_state to "
            "seed the dirty-row checksum cache (pass universe=...)"
        )
    n = params.n
    eye = np.eye(n, dtype=bool)
    inc0 = np.where(eye, 1, 0).astype(np.int32)  # stamp 1 == epoch_ms
    rng = np.random.default_rng(seed)
    perm = np.stack([rng.permutation(n) for _ in range(n)]).astype(np.int32)
    perm_inv = np.argsort(perm, axis=1).astype(np.int32)  # same walk order
    keys = rng.integers(1, 2**32 - 1, size=(n, 2), dtype=np.uint32)
    state = SimState(
        tick_index=jnp.int32(0),
        proc_alive=jnp.ones(n, bool),
        ready=jnp.zeros(n, bool),
        gossip_on=jnp.ones(n, bool),
        partition=jnp.zeros(n, jnp.int32),
        known=jnp.asarray(eye),
        status=jnp.zeros((n, n), jnp.int32),
        inc=jnp.asarray(inc0),
        ch_active=jnp.zeros((n, n), bool),
        ch_status=jnp.zeros((n, n), jnp.int32),
        ch_inc=jnp.zeros((n, n), jnp.int32),
        ch_source=jnp.full((n, n), -1, jnp.int32),
        ch_source_inc=jnp.zeros((n, n), jnp.int32),
        ch_pb=jnp.zeros((n, n), jnp.int32),
        susp_deadline=jnp.full((n, n), -1, jnp.int32),
        perm_inv=jnp.asarray(perm_inv),
        iter_pos=jnp.zeros(n, jnp.int32),
        rng=jnp.asarray(keys),
        checksum=jnp.zeros(n, jnp.uint32),
    )
    # fused parity mode: seed the per-(observer, subject) record cache
    # with every row's self-view records (the cache is a pure function of
    # (known, status, inc) — see SimState.rec_bytes)
    if (
        resolve_fused_checksum(params, jax.default_backend()) == "on"
        and universe is not None
    ):
        from ringpop_tpu.ops import fused_checksum as fc

        rec_b, rec_l = fc.member_records(
            universe,
            state.known,
            state.status,
            stamp_to_ms(state.inc, params),
            params.max_digits,
        )
        state = state._replace(rec_bytes=rec_b, rec_len=rec_l)
    if params.flight_recorder:
        from ringpop_tpu.models.sim import flight

        ev_buf, ev_head, ev_drops, first_heard = (
            flight.init_recorder_fields(n, params.event_capacity)
        )
        state = state._replace(
            ev_buf=ev_buf,
            ev_head=ev_head,
            ev_drops=ev_drops,
            first_heard=first_heard,
        )
    if params.histograms:
        from ringpop_tpu.ops import histogram as hg

        state = state._replace(hist=hg.init(len(HIST_TRACKS)))
    # Fast mode never touches the universe in compute_checksums, so the
    # cache can (and must) be seeded even without one — a fast-mode caller
    # omitting universe would otherwise see stale zero checksums for rows
    # the dirty-gated tick never recomputes.
    if universe is not None or params.checksum_mode == "fast":
        state = state._replace(
            checksum=compute_checksums(state, universe, params)
        )
    return state


def compute_checksums(state: SimState, universe: ce.Universe, params: SimParams):
    if params.checksum_mode == "fast":
        n = state.known.shape[0]
        subject = jnp.arange(n, dtype=jnp.int32)[None, :]
        rec = record_mix(subject, state.status, state.inc)
        return jnp.sum(
            jnp.where(state.known, rec, 0), axis=1, dtype=jnp.uint32
        )
    bufs, lens = ce.membership_rows(
        universe,
        state.known,
        state.status,
        stamp_to_ms(state.inc, params),  # int64 only inside this branch
        max_digits=params.max_digits,
    )
    return jfh.hash32_rows(bufs, lens, impl=_hash_impl(params))


def _hash_impl(params: SimParams):
    """None = let hash32_rows read RINGPOP_TPU_PALLAS at trace time."""
    return None if params.hash_impl == "env" else params.hash_impl


def resolve_fused_checksum(params: "SimParams", backend: str) -> str:
    """Resolve ``fused_checksum="auto"`` to a concrete "on"/"off".

    "on" for farmhash mode on TPU — where the fused record-cache +
    streaming-kernel pipeline replaces the XLA byte-assembly floor — and
    "off" elsewhere (the CPU's gated dirty-chunk recompute already skips
    quiet ticks, and interpret-mode Pallas would be a slowdown).  An
    explicit "on"/"off" is honored as-is ("on" requires farmhash mode).
    Table mechanics: the shared toolkit resolver (ops.toolkit)."""
    if params.fused_checksum == "on" and params.checksum_mode != "farmhash":
        raise ValueError(
            "fused_checksum='on' requires checksum_mode='farmhash' "
            "(fast mode has no checksum strings to fuse)"
        )
    return toolkit.resolve_impl(
        "fused_checksum",
        params.fused_checksum,
        backend,
        auto={
            "tpu": "on" if params.checksum_mode == "farmhash" else "off",
            "*": "off",
        },
        allowed=("on", "off"),
    )


def resolve_fused_tick(params: "SimParams", backend: str) -> str:
    """Resolve ``fused_tick="auto"`` to a concrete "pallas"/"xla"/"off"
    (SimParams.fused_tick): the gridless streaming kernels on TPU; off
    TPU the bit-exact XLA twin from n >= 4096 — unlike the
    checksum/exchange knobs, the twin IS a CPU win at scale (the fused
    sites return per-row/scalar reductions and a packed applied-cells
    union instead of dense [N, N] started/refuted/applied masks, so
    the memory-bound tick crosses fewer plane boundaries): the
    BENCH_r15 dissemination ladder measured 1.15x at n=4096 and 1.05x
    at n=8192, but 0.94x at n=1024, where the accumulator bookkeeping
    outweighs the saved planes — small-n CPU auto therefore keeps the
    classic shape.  "off" is the classic phase-by-phase program, kept
    verbatim as the A/B baseline.  Table mechanics: the shared toolkit
    resolver (ops.toolkit)."""
    return toolkit.resolve_impl(
        "fused_tick",
        params.fused_tick,
        backend,
        auto={
            "tpu": "pallas",
            "*": "xla" if params.n >= 4096 else "off",
        },
        allowed=("pallas", "xla", "off"),
    )


def resolve_sharded_fused_tick(params: "SimParams", backend: str) -> str:
    """Resolve ``fused_tick`` for a MESH-sharded full engine
    (ShardedSim) — the round-14 lesson applied up front instead of
    re-learned: a ``pallas_call`` does not partition under GSPMD, so a
    sharded tick must never embed the streaming kernels.  The table:

    ==========  =======  ==========================================
    fused_tick  backend  resolves to
    ==========  =======  ==========================================
    auto        tpu      "xla" — the partitionable twin (the
                         single-device auto pick would be "pallas")
    auto        other    the single-device pick (the xla twin already
                         partitions; small-n keeps the classic shape)
    pallas      any      "xla" — there is no shard-local plane for the
                         full tick yet, so an explicit pallas drops to
                         the partitionable twin; the driver surfaces
                         the divergence via its op_resolution note
                         (never the PR-5 silent drop)
    xla / off   any      honored
    ==========  =======  ==========================================
    """
    resolved = resolve_fused_tick(params, backend)
    return "xla" if resolved == "pallas" else resolved


def resolve_exact_recompute(params: "SimParams", backend: str) -> str:
    """The exact (overflow-free) recompute shape for replay twins: fused
    runs always replay under "full" (the fused pipeline has no gated
    loop form — the bounded chunk IS its only sparse shape), unfused
    runs keep the per-backend choice (resolve_parity_recompute)."""
    if params.fused_checksum == "on":
        return "full"
    return resolve_parity_recompute(backend)


def resolve_parity_recompute(backend: str) -> str:
    """The EXACT recompute shape per backend — every dirty row covered,
    no overflow possible: "gated" (dirty-chunk while_loop, the CPU win)
    or "full" (straight-line full recompute, the shape the TPU tunnel's
    compile helper accepts).  Used for the overflow-replay fallback
    (SimCluster/ShardedSim ``_exact_params`` — which must NEVER resolve
    to "bounded", or a replay would overflow again and loop) and as
    _checksums_where's trace-time "auto" fallback for direct engine
    users, who have no replay plumbing.  Bit-identical trajectories
    either way."""
    return "full" if backend == "tpu" else "gated"


def resolve_auto_parity(params: "SimParams", backend: str) -> "SimParams":
    """Driver-level ``parity_recompute="auto"`` resolution (SimCluster /
    ShardedSim construction — contexts WITH overflow-replay plumbing):
    "bounded" on TPU — one straight-line K-row encode chunk per
    recompute — and "gated" elsewhere.  The TPU auto chunk is K=4: the
    round-5 chip ladder measured the 256-tick quiet-window median at
    K=256 -> compile-helper 500, K=64 -> 18.2k node-ticks/s, K=32 ->
    23.0k, K=16 -> 41.8k, K=8 -> 52.7k, K=4 -> 70.6k — per-chunk cost
    dominates, so smaller is faster.  The overflow cliff is
    K-indifferent at WINDOW granularity: every SWIM update disseminates
    to the whole cluster, so a wave whose per-tick dirty counts pass
    through [5, 31] keeps doubling past 32 within the same window — any
    window that overflows K=4 also overflows K=32, and the replay
    (which discards whole windows) costs the same.  Only per-STEP
    drivers see a difference (a K=4 step replays on the wave's first
    few ticks where K=32 wouldn't — each a cheap single-tick exact
    replay), and replay exactness covers both.  An explicit
    ``parity_recompute="bounded"`` keeps the caller's dirty_batch
    untouched (diagnostic sweeps need K above the auto pick).

    Fused re-tune — the K ladder collapses: the K=4 optimum above was
    measured against the XLA byte-assembly encode, whose per-chunk cost
    grew with K.  The fused streaming kernel processes rows in fixed
    [8, 128] = 1024-lane tiles, so every K <= 1024 runs the SAME kernel
    work; encode cost no longer scales with K at all (the bounded shape
    re-encodes changed CELLS into the record cache — cell_batch — and
    rows are only reassembled from cached bytes).  The auto chunk is
    therefore K = min(n, 1024): at headline scale (n <= 1024) the chunk
    covers EVERY row — row overflow is impossible by construction, the
    row gather/scatter drops out (k == n hashes rows in natural order),
    and a churn window can only replay on cell overflow (> cell_batch
    changed cells in one tick — bootstrap-scale merges, not SWIM churn
    waves).  Re-validate on-chip via benchmarks/tpu_measure.py's fused
    phase when the tunnel is up."""
    params = params._replace(
        fused_checksum=resolve_fused_checksum(params, backend),
        fused_tick=resolve_fused_tick(params, backend),
    )
    if params.parity_recompute == "auto":
        if params.fused_checksum == "on":
            params = params._replace(
                parity_recompute="bounded",
                dirty_batch=min(params.n, 1024),
            )
        elif backend == "tpu":
            params = params._replace(
                parity_recompute="bounded",
                dirty_batch=min(params.dirty_batch, 4),
            )
        else:
            params = params._replace(parity_recompute="gated")
    return params


def _checksums_where(
    state: SimState,
    universe: ce.Universe,
    params: SimParams,
    dirty: jax.Array,  # [N] bool — rows whose view changed since `cached`
    cached: jax.Array,  # [N] uint32
    changed: "Optional[jax.Array]" = None,  # [N, N] bool changed cells
):
    """Per-row checksum with dirty-row caching.

    Returns ``(checksum [N] uint32, overflow scalar int32, state)`` —
    overflow is nonzero only in "bounded" parity mode, when more rows
    were dirty than the one bounded chunk covers (or, fused mode, more
    cells changed than cell_batch — see SimParams.parity_recompute /
    fused_checksum); the returned state carries the updated fused record
    cache (untouched in unfused modes).

    The farmhash-parity string build + hash is by far the hottest op in the
    tick; a row's checksum only changes when its VIEW changed, so unchanged
    rows reuse the cache and a fully-quiet tick skips the whole encode+hash
    graph at runtime (``lax.cond``).  Fast mode uses the same dirty gating
    (recomputing an unchanged row reproduces the cached sum bit-for-bit,
    so skipping is trajectory-neutral).  Correctness is pinned by the
    lockstep parity suite, which asserts bit-equality against the host
    oracle on every tick of every scenario.
    """

    n_dirty = jnp.sum(dirty, dtype=jnp.int32)
    no_overflow = jnp.int32(0)

    def recompute_all(_):
        fresh = compute_checksums(state, universe, params)
        return jnp.where(dirty, fresh, cached)

    if params.checksum_mode == "fast":
        return (
            jax.lax.cond(
                n_dirty > 0, recompute_all, lambda _: cached, operand=None
            ),
            no_overflow,
            state,
        )

    import jax as _jax

    if (
        resolve_fused_checksum(params, _jax.default_backend()) == "on"
        and changed is not None
    ):
        return _fused_checksums_where(
            state, universe, params, dirty, cached, changed, n_dirty
        )

    recompute_shape = params.parity_recompute
    if recompute_shape == "auto":
        # direct engine users (not routed through SimCluster's
        # construction-time resolution) still must not trace the gated
        # loop on the tunnel backend that can't compile it
        import jax as _jax

        recompute_shape = resolve_parity_recompute(_jax.default_backend())
    if recompute_shape == "full":
        # straight-line: no cond, no while.  Recomputing a clean row is
        # bit-neutral, so dirty tracking is simply unused here.
        return compute_checksums(state, universe, params), no_overflow, state

    if recompute_shape == "bounded":
        # ONE bounded K-row chunk, no loop: gather the first K dirty rows
        # (by index), encode + hash just those, scatter into the cache.
        # Ticks with n_dirty > K overflow: the uncovered rows keep stale
        # checksums, so the caller MUST replay from pre-run state under
        # an exact shape (the returned overflow count, surfaced via
        # TickMetrics.parity_overflow, is the signal — SimCluster handles
        # it automatically).  On the axon tunnel the chunk always runs
        # STRAIGHT-LINE, even when the other phases are cond-gated: the
        # round-5 bisect (DIAG_BOUNDED.json) showed the compile helper
        # 500s on a cond whose body holds even the K-row encode — the
        # restriction is any control flow around an encode graph, not
        # just while_loops or doubled bodies.  Elsewhere the cond skips
        # clean ticks like every other phase.
        k = min(params.dirty_batch, params.n)
        n = params.n

        def recompute_bounded(_):
            (idx,) = jnp.nonzero(dirty, size=k, fill_value=0)
            idx = idx.astype(jnp.int32)
            lane_ok = jnp.arange(k, dtype=jnp.int32) < n_dirty
            bufs, lens = ce.membership_rows(
                universe,
                _rows(state.known, idx, n),
                _rows(state.status, idx, n),
                stamp_to_ms(_rows(state.inc, idx, n), params),
                max_digits=params.max_digits,
            )
            fresh = jfh.hash32_rows(bufs, lens, impl=_hash_impl(params))
            tgt = jnp.where(lane_ok, idx, n)  # n drops
            return cached.at[tgt].set(fresh, mode="drop")

        import jax as _jax

        chunk_gate = params.gate_phases and _jax.default_backend() != "tpu"
        out = _phase(
            chunk_gate,
            n_dirty > 0,
            recompute_bounded,
            lambda _: cached,
            None,
        )
        return out, jnp.maximum(n_dirty - k, 0), state

    k = min(params.dirty_batch, params.n)

    def recompute_chunked(_):
        # ONE bounded K-row encode+hash instantiation, driven by a
        # while_loop over K-sized chunks of the dirty set.  The previous
        # shape — a batch path PLUS a full-recompute fallback as separate
        # cond branches — embedded the encode graph twice (once at K
        # rows, once at N), and the combined program is what blew the
        # axon compile helper's resource limit from n=256 up
        # (DIAG_PARITY_N.json: full recompute alone compiles in 21 s,
        # _checksums_where 500s).  Chunking also makes program size
        # independent of N.  Chunk c covers dirty rows with rank in
        # [cK, cK+K); nonzero(size=K) pads with index 0 and padded lanes
        # are routed to a dropped scatter slot.
        rank = jnp.cumsum(dirty.astype(jnp.int32)) - 1

        def cond(carry):
            c, _ = carry
            return c * k < n_dirty

        def body(carry):
            c, acc = carry
            lo = c * k
            sel = dirty & (rank >= lo) & (rank < lo + k)
            (idx,) = jnp.nonzero(sel, size=k, fill_value=0)
            idx = idx.astype(jnp.int32)
            lane_ok = jnp.arange(k, dtype=jnp.int32) < jnp.minimum(
                n_dirty - lo, k
            )
            bufs, lens = ce.membership_rows(
                universe,
                state.known[idx],
                state.status[idx],
                stamp_to_ms(state.inc[idx], params),
                max_digits=params.max_digits,
            )
            fresh = jfh.hash32_rows(bufs, lens, impl=_hash_impl(params))
            tgt = jnp.where(lane_ok, idx, params.n)  # n drops
            return c + 1, acc.at[tgt].set(fresh, mode="drop")

        _, out = jax.lax.while_loop(cond, body, (jnp.int32(0), cached))
        return out

    return (
        jax.lax.cond(
            n_dirty > 0, recompute_chunked, lambda _: cached, operand=None
        ),
        no_overflow,
        state,
    )


def _fused_stream_impl(params: SimParams) -> "Optional[str]":
    """Streaming-kernel lowering for fused_hash_rows, derived from the
    same hash_impl knob the classic path uses: any Pallas variant ->
    the gridless streaming kernel, "scan" -> the scanned XLA twin,
    "env" -> backend default at trace time (None)."""
    if params.hash_impl == "env":
        return None
    return "pallas" if "pallas" in params.hash_impl else "xla"


def _fused_checksums_where(
    state: SimState,
    universe: ce.Universe,
    params: SimParams,
    dirty: jax.Array,  # [N] bool
    cached: jax.Array,  # [N] uint32
    changed: jax.Array,  # [N, N] bool — cells whose view changed
    n_dirty: jax.Array,
):
    """Fused-pipeline recompute: bounded changed-cell re-encode into the
    persistent record cache, then a K-dirty-row gather hashed by the
    streaming kernel.  Shapes: "bounded" (the production TPU shape) or
    "full" (the exact replay twin — dense re-encode of every cell, no
    overflow possible); "auto"/"gated" collapse to "full" (the fused
    pipeline's only exact shape; the gated dirty-chunk loop form has no
    fused equivalent and direct engine users get exactness, not replay
    plumbing).  Cell overflow (> cell_batch changed cells) and row
    overflow (> dirty_batch dirty rows) share one parity_overflow
    counter and the same driver replay contract."""
    from ringpop_tpu.ops import fused_checksum as fc

    n = params.n
    r = fc.record_width(universe, params.max_digits)
    impl = _fused_stream_impl(params)
    no_overflow = jnp.int32(0)
    if state.rec_bytes is None:
        raise ValueError(
            "fused_checksum='on' but the state carries no record cache — "
            "build the state with init_state(params, universe=...) or "
            "rebuild the cache after loading an unfused checkpoint"
        )

    shape = params.parity_recompute
    if shape in ("auto", "gated"):
        shape = "full"

    if shape == "full":
        rec_b, rec_l = fc.member_records(
            universe,
            state.known,
            state.status,
            stamp_to_ms(state.inc, params),
            params.max_digits,
        )
        fresh = fc.fused_hash_rows(
            fc.pack_record_words(rec_b), rec_l, impl=impl
        )
        return (
            fresh,
            no_overflow,
            state._replace(rec_bytes=rec_b, rec_len=rec_l),
        )

    # -- "bounded": ONE cell chunk + ONE row chunk, both straight-line --
    k = min(params.dirty_batch, n)
    cbatch = min(params.cell_batch, n * n)

    def update_and_hash(_):
        # 1. re-encode up to cell_batch changed cells into the cache
        flat = changed.reshape(-1)
        n_changed = jnp.sum(flat, dtype=jnp.int32)
        (cidx,) = jnp.nonzero(flat, size=cbatch, fill_value=n * n)
        cidx = cidx.astype(jnp.int32)
        crow = jnp.clip(cidx // n, 0, n - 1)
        ccol = jnp.clip(cidx % n, 0, n - 1)
        cell_b, cell_l = fc.member_records_at(
            universe,
            ccol,
            state.status[crow, ccol],
            stamp_to_ms(state.inc[crow, ccol], params),
            state.known[crow, ccol],
            params.max_digits,
        )
        rec_b = (
            state.rec_bytes.reshape(n * n, r)
            .at[cidx]
            .set(cell_b, mode="drop")  # fill cells target n*n: dropped
            .reshape(n, n, r)
        )
        rec_l = (
            state.rec_len.reshape(n * n)
            .at[cidx]
            .set(cell_l, mode="drop")
            .reshape(n, n)
        )
        cell_over = jnp.maximum(n_changed - cbatch, 0)

        # 2. hash the dirty rows' cached records with the streaming
        # kernel.  k == n (the auto pick at n <= 1024: one kernel row
        # tile covers the whole cluster) skips the gather/scatter and
        # hashes rows in natural order — rehashing a clean row is
        # bit-neutral, and row overflow is impossible.  k < n gathers
        # the first K dirty rows; the byte cache rides the one-hot f32
        # matmul row-select (_rows — exact for uint8), sidestepping the
        # ~0.4 GB/s TPU dynamic-gather path the round-4 trace found.
        if k == n:
            fresh = fc.fused_hash_rows(
                fc.pack_record_words(rec_b), rec_l, impl=impl
            )
            out = jnp.where(dirty, fresh, cached)
            return out, cell_over, rec_b, rec_l
        (idx,) = jnp.nonzero(dirty, size=k, fill_value=0)
        idx = idx.astype(jnp.int32)
        lane_ok = jnp.arange(k, dtype=jnp.int32) < n_dirty
        rows_b = _rows(rec_b.reshape(n, n * r), idx, n).reshape(k, n, r)
        rows_l = _rows(rec_l, idx, n)
        fresh = fc.fused_hash_rows(
            fc.pack_record_words(rows_b), rows_l, impl=impl
        )
        tgt = jnp.where(lane_ok, idx, n)  # n drops
        return (
            cached.at[tgt].set(fresh, mode="drop"),
            cell_over,
            rec_b,
            rec_l,
        )

    import jax as _jax

    chunk_gate = params.gate_phases and _jax.default_backend() != "tpu"
    out, cell_over, rec_b, rec_l = _phase(
        chunk_gate,
        n_dirty > 0,
        update_and_hash,
        lambda _: (cached, no_overflow, state.rec_bytes, state.rec_len),
        None,
    )
    return (
        out,
        jnp.maximum(n_dirty - k, 0) + cell_over,
        state._replace(rec_bytes=rec_b, rec_len=rec_l),
    )


def _connected(partition: jax.Array, a: jax.Array, b: jax.Array) -> jax.Array:
    return partition[a] == partition[b]


def _apply_updates(
    state: SimState,
    now: jax.Array,  # scalar int32 stamp for this tick
    recv_mask: jax.Array,  # [N, N] bool — update for (node, subject)
    u_status: jax.Array,  # [N, N] int32
    u_inc: jax.Array,  # [N, N] int32 stamp
    u_source: jax.Array,  # [N, N] int32
    u_source_inc: jax.Array,  # [N, N] int32 stamp
):
    """Vectorized Member.evaluateUpdate over (observer, subject) pairs.

    Returns (state', applied [N,N] bool, suspicion starts, suspicion
    stops, refutes [N,N] bool — the self-refute cells, always applied).
    """
    n = state.known.shape[0]
    node = jnp.arange(n, dtype=jnp.int32)[:, None]
    subject = jnp.arange(n, dtype=jnp.int32)[None, :]
    is_self = node == subject

    # local override (refute): self claimed suspect/faulty -> alive, fresh inc
    refute = recv_mask & is_self & ((u_status == SUSPECT) | (u_status == FAULTY))
    eff_status = jnp.where(refute, ALIVE, u_status)
    eff_inc = jnp.where(refute, now, u_inc)

    new_member = recv_mask & ~state.known
    gate = recv_mask & (
        refute
        | new_member
        | _overrides(eff_status, eff_inc, state.status, state.inc)
    )

    status = jnp.where(gate, eff_status, state.status)
    inc = jnp.where(gate, eff_inc, state.inc)
    known = state.known | new_member

    # record applied changes for dissemination (on_membership_event.js:58,
    # membership.update -> dissemination.recordChange)
    ch_active = state.ch_active | gate
    ch_status = jnp.where(gate, status, state.ch_status)
    ch_inc = jnp.where(gate, inc, state.ch_inc)
    ch_source = jnp.where(gate, u_source, state.ch_source)
    ch_source_inc = jnp.where(gate, u_source_inc, state.ch_source_inc)
    ch_pb = jnp.where(gate, 0, state.ch_pb)

    # suspicion timers (never for self): stops applied here, starts are
    # returned for the caller to stamp with tick + suspicion_ticks
    start_t = gate & (status == SUSPECT) & ~is_self
    stop_t = gate & (status != SUSPECT)
    susp = jnp.where(stop_t, -1, state.susp_deadline)

    new_state = state._replace(
        known=known,
        status=status,
        inc=inc,
        ch_active=ch_active,
        ch_status=ch_status,
        ch_inc=ch_inc,
        ch_source=ch_source,
        ch_source_inc=ch_source_inc,
        ch_pb=ch_pb,
        susp_deadline=susp,
    )
    return new_state, gate, start_t, stop_t, refute


def _apply_state_of(state: SimState) -> fap.ApplyState:
    """The ten planes an application site touches, in the fused op's
    field order."""
    return fap.ApplyState(
        known=state.known,
        status=state.status,
        inc=state.inc,
        ch_active=state.ch_active,
        ch_status=state.ch_status,
        ch_inc=state.ch_inc,
        ch_source=state.ch_source,
        ch_source_inc=state.ch_source_inc,
        ch_pb=state.ch_pb,
        susp_deadline=state.susp_deadline,
    )


def _with_apply_state(state: SimState, ast: fap.ApplyState) -> SimState:
    return state._replace(**ast._asdict())


def _apply_site(
    state: SimState,
    union: "Optional[jax.Array]",
    recv_mask: jax.Array,
    u_status: jax.Array,
    u_inc: jax.Array,
    u_source: jax.Array,
    u_source_inc: jax.Array,
    now: jax.Array,
    deadline: jax.Array,
    *,
    impl: str,
    want_masks: bool,
    want_count: bool = False,
    want_refute: bool = True,
    stamp: bool = True,
):
    """One membership-update application site, fused-tick aware.

    ``impl == "off"`` is the classic shape VERBATIM — the historical
    ``_apply_updates`` + caller-side deadline stamp (``stamp=False``
    reproduces the expiry/join sites, which never stamped) — so the
    "off" program is byte-for-byte the pre-fused tick and the bench A/B
    is honest.  Other impls run the fused op (``ops.fused_apply``),
    which folds the stamp in and returns reductions instead of dense
    masks.

    Returns ``(state, union, applied_mask_or_None, applied_rows,
    refute_diag, applied_count)`` — classic mode returns the dense mask
    with ``applied_rows``/``applied_count`` as None (its callers derive
    everything from the mask, exactly as before; the unused Nones cost
    nothing)."""
    if impl == "off":
        st2, applied, started, _, refuted = _apply_updates(
            state, now, recv_mask, u_status, u_inc, u_source, u_source_inc
        )
        if stamp:
            st2 = st2._replace(
                susp_deadline=jnp.where(
                    started, deadline, st2.susp_deadline
                )
            )
        return st2, union, applied, None, _self_view(refuted), None
    out = fap.apply_updates(
        _apply_state_of(state),
        recv_mask,
        u_status,
        u_inc,
        u_source,
        u_source_inc,
        now,
        deadline,
        union,
        impl=impl,
        want_masks=want_masks,
        want_count=want_count,
        want_refute=want_refute,
    )
    return (
        _with_apply_state(state, out.state),
        out.union,
        out.applied,
        out.applied_rows,
        out.refute_diag,
        out.applied_count,
    )


def _rows(m: jax.Array, idx: jax.Array, n: int) -> jax.Array:
    """``m[idx]`` — select whole rows of an [N, N] array by an [N] index.

    On TPU this is computed as a ONE-HOT f32 MATMUL on the MXU instead of
    a gather: the round-4 trace (PROF_1K_OPS.json) measured the [N, N]
    row-gathers of the receive/response phases at ~5-10 ms each at
    n=1024 (~0.4 GB/s — XLA's TPU dynamic-gather path), while the
    equivalent [N,N]x[N,N] selection matmul is tens of microseconds.
    Exact because every engine value riding this path — bools, status
    codes, node ids, int32 tick stamps — is an integer with |v| < 2^24,
    representable exactly in float32, and a one-hot row dot product is a
    pure selection (one term, no rounding).  XLA CSEs the repeated
    one-hot of the same index vector, so several matrices selected by
    one idx share one W build.  CPU (and n > 4096, where the n^3
    selection would dominate) keeps the gather.
    """
    if n > 4096 or jax.default_backend() != "tpu":
        return m[idx]
    w = (
        idx[:, None] == jnp.arange(n, dtype=jnp.int32)[None, :]
    ).astype(jnp.float32)
    # Precision.HIGHEST is REQUIRED for exactness: the TPU's default f32
    # matmul multiplies in bf16, which rounds the selected values to 8
    # mantissa bits (measured: default loses equality at 2^24-1 values,
    # HIGHEST restores it — the 3-pass bf16 split reproduces full f32).
    out = jnp.matmul(
        w, m.astype(jnp.float32), precision=jax.lax.Precision.HIGHEST
    )
    if m.dtype == jnp.bool_:
        return out > 0.5
    return out.astype(m.dtype)


def tick(
    state: SimState,
    inputs: TickInputs,
    params: SimParams,
    universe: ce.Universe,
) -> tuple[SimState, TickMetrics]:
    n = params.n
    gate = params.gate_phases  # static: picks cond vs straight-line phases
    # fused-tick resolution (static): "off" keeps the classic
    # phase-by-phase shape verbatim; "xla"/"pallas" route the apply and
    # piggyback sites through the toolkit's fused ops (direct engine
    # users may leave "auto" — drivers pinned a concrete value at
    # construction via resolve_auto_parity, like fused_checksum)
    ft = resolve_fused_tick(params, jax.default_backend())
    ft_on = ft != "off"
    # fused parity mode tracks WHICH cells changed (see changed_mid
    # below); hoisted here because the fused tick keys its mask
    # emission on it
    fused = params.checksum_mode == "farmhash" and (
        resolve_fused_checksum(params, jax.default_backend()) == "on"
    )
    # the obs planes and the fused-checksum cell tracker consume dense
    # per-site applied masks; without them the fused sites emit only
    # reductions and no per-site [N, N] mask ever materializes
    want_masks = params.flight_recorder or params.histograms or fused
    # tick-start views: the flight recorder's old_status baseline (and
    # nothing else — the protocol phases read live state as before)
    prev_known, prev_status = state.known, state.status
    # tick-start suspicion deadlines: the histogram plane's duration
    # baseline (a stopped timer's start tick = deadline - suspicion_ticks)
    prev_susp = state.susp_deadline
    # this tick's incarnation stamp: epoch_ms + tick_next*period_ms
    now = state.tick_index + 2
    node = jnp.arange(n, dtype=jnp.int32)[:, None]
    subject = jnp.arange(n, dtype=jnp.int32)[None, :]
    is_self = node == subject
    tick_next = state.tick_index + 1

    # ---- phase 0: fault-injection plane -------------------------------
    proc_alive = (state.proc_alive & ~inputs.kill) | inputs.revive
    if inputs.resume is not None:
        # SIGCONT: process returns with its pre-stop state intact
        proc_alive = proc_alive | inputs.resume
    partition = jnp.where(inputs.partition >= 0, inputs.partition, state.partition)
    # revive resets a node to fresh state (process restart); rare, so the
    # [N, N] view resets are cond-gated off the common tick
    rv = inputs.revive & ~state.proc_alive
    state = state._replace(
        proc_alive=proc_alive,
        partition=partition,
        ready=jnp.where(rv, False, state.ready),
        # a restarted process gossips again even if it had left pre-crash
        gossip_on=state.gossip_on | rv,
        tick_index=tick_next,
    )

    def _revive_reset(state):
        return state._replace(
            known=jnp.where(rv[:, None], is_self, state.known),
            status=jnp.where(rv[:, None], ALIVE, state.status),
            inc=jnp.where(
                rv[:, None] & is_self, now, jnp.where(rv[:, None], 0, state.inc)
            ),
            ch_active=jnp.where(rv[:, None], False, state.ch_active),
            susp_deadline=jnp.where(rv[:, None], -1, state.susp_deadline),
        )

    state = _phase(gate, jnp.any(rv), _revive_reset, lambda s: s, state)

    # ---- phase 0.5: graceful leave ------------------------------------
    # the node marks itself leave at its CURRENT incarnation (makeLeave,
    # membership/index.js:192), records the change, and stops gossiping;
    # the change disseminates via its ping responses
    lv = jnp.zeros(n, bool)  # flight recorder: leave self-writes this tick
    if inputs.leave is not None:
        diag = jnp.arange(n, dtype=jnp.int32)
        self_status = state.status[diag, diag]
        lv = (
            inputs.leave
            & state.proc_alive
            & state.ready
            & (self_status != LEAVE)
        )
        lv_mask = lv[:, None] & is_self
        own_inc = state.inc[diag, diag]
        state = state._replace(
            status=jnp.where(lv_mask, LEAVE, state.status),
            gossip_on=state.gossip_on & ~lv,
            ch_active=state.ch_active | lv_mask,
            ch_status=jnp.where(lv_mask, LEAVE, state.ch_status),
            ch_inc=jnp.where(lv_mask, own_inc[:, None], state.ch_inc),
            ch_source=jnp.where(lv_mask, node, state.ch_source),
            ch_source_inc=jnp.where(
                lv_mask, own_inc[:, None], state.ch_source_inc
            ),
            ch_pb=jnp.where(lv_mask, 0, state.ch_pb),
        )

    # rejoin of a left node: alive with a fresh incarnation, gossip back on
    # (server/admin/member.js:44-51) — no cluster-join round needed; the
    # [N, N] writes are cond-gated (rejoins are operator events)
    diag = jnp.arange(n, dtype=jnp.int32)
    rejoin = (
        inputs.join
        & state.proc_alive
        & state.ready
        & (state.status[diag, diag] == LEAVE)
    )

    def _rejoin_write(state):
        rj_mask = rejoin[:, None] & is_self
        return state._replace(
            status=jnp.where(rj_mask, ALIVE, state.status),
            inc=jnp.where(rj_mask, now, state.inc),
            gossip_on=state.gossip_on | rejoin,
            ch_active=state.ch_active | rj_mask,
            ch_status=jnp.where(rj_mask, ALIVE, state.ch_status),
            ch_inc=jnp.where(rj_mask, now, state.ch_inc),
            ch_source=jnp.where(rj_mask, node, state.ch_source),
            ch_source_inc=jnp.where(rj_mask, now, state.ch_source_inc),
            ch_pb=jnp.where(rj_mask, 0, state.ch_pb),
        )

    state = _phase(gate, jnp.any(rejoin), _rejoin_write, lambda s: s, state)

    # ---- phase 1: join/bootstrap --------------------------------------
    # Joiners (join input, or revived nodes) contact join_size ready nodes,
    # merge their full views (join-sender.js + join-response-merge), and the
    # contacted nodes makeAlive(joiner) (server/protocol/join.js:126).
    # Joins are rare (bootstrap / revive / rejoin ticks), so the whole
    # block — a [N, N] top-k, a 3-step merge scan, and a scatter loop —
    # runs under lax.cond and costs nothing on the steady-state tick.
    # (The jrand draw is a pure function of state.rng + salt; skipping it
    # changes no other randomness.)
    joiner = (inputs.join | rv) & state.proc_alive & ~state.ready

    def _join_phase(state):
        # any live process answers /protocol/join — including nodes that
        # are themselves mid-bootstrap (the reference's simultaneous
        # tick-cluster bootstrap relies on this; handleJoin never checks
        # readiness)
        join_candidates = state.proc_alive
        can_join_mask = (
            joiner[:, None]
            & join_candidates[None, :]
            & ~is_self
            & _connected(partition, node, subject)
        )
        jrand = _uniform(state.rng, (n, n), salt=101)
        jscore = jnp.where(can_join_mask, jrand, 2.0)
        # take up to join_size targets per joiner (top-k, not a full sort)
        neg_jtop, jorder = jax.lax.top_k(-jscore, params.join_size)
        jvalid = -neg_jtop < 1.5  # real candidates

        # merge targets' views into joiner via key-max over targets
        def merge_joins(carry, k):
            known_j, status_j, inc_j = carry
            tgt = jorder[:, k]
            ok = jvalid[:, k] & joiner
            t_known = _rows(state.known, tgt, n)
            t_status = _rows(state.status, tgt, n)
            t_inc = _rows(state.inc, tgt, n)
            take = ok[:, None] & t_known
            better = take & (
                ~known_j | (_pack_key(t_inc, t_status) > _pack_key(inc_j, status_j))
            )
            return (
                (known_j | take, jnp.where(better, t_status, status_j), jnp.where(better, t_inc, inc_j)),
                None,
            )

        (jk, js, ji), _ = jax.lax.scan(
            merge_joins,
            (state.known, state.status, state.inc),
            jnp.arange(params.join_size, dtype=jnp.int32),
        )
        joined = joiner & jnp.any(jvalid, axis=1)
        # don't let merged views downgrade the joiner's own liveness
        keep_self = is_self & joined[:, None]
        merged_known = jnp.where(joined[:, None], jk, state.known)
        merged_status = jnp.where(keep_self, ALIVE, jnp.where(joined[:, None], js, state.status))
        merged_inc = jnp.where(keep_self, state.inc, jnp.where(joined[:, None], ji, state.inc))
        # joiner records every learned member as a change (set handler,
        # on_membership_event.js:58)
        learned = joined[:, None] & merged_known & ~is_self
        state = state._replace(
            known=merged_known,
            status=merged_status,
            inc=merged_inc,
            ready=state.ready | joined,
            ch_active=state.ch_active | learned,
            ch_status=jnp.where(learned, merged_status, state.ch_status),
            ch_inc=jnp.where(learned, merged_inc, state.ch_inc),
            ch_source=jnp.where(learned, node, state.ch_source),
            ch_source_inc=jnp.where(
                learned, _self_view(merged_inc)[:, None], state.ch_source_inc
            ),
            ch_pb=jnp.where(learned, 0, state.ch_pb),
        )

        # contacted nodes makeAlive(joiner): scatter alive into targets
        ja_mask = jnp.zeros((n, n), bool)

        def scatter_join_alive(k, m):
            tgt = jorder[:, k]
            ok = jvalid[:, k] & joined
            upd = jnp.zeros((n, n), bool).at[tgt, jnp.arange(n, dtype=jnp.int32)].set(ok, mode="drop")
            return m | upd

        ja_mask = jax.lax.fori_loop(0, params.join_size, scatter_join_alive, ja_mask)
        self_inc = _self_view(state.inc)
        state, ja_applied, _, _, _ = _apply_updates(
            state,
            now,
            ja_mask,
            jnp.full((n, n), ALIVE, jnp.int32),
            jnp.broadcast_to(self_inc[None, :], (n, n)),
            jnp.broadcast_to(subject, (n, n)).astype(jnp.int32),  # source = joiner
            jnp.broadcast_to(self_inc[None, :], (n, n)),
        )
        if ft_on:
            # the fused tick's packed applied-cells union is seeded
            # INSIDE this cond — join-free ticks keep the loop-invariant
            # zeros accumulator and never touch a dense mask
            return (
                state,
                joined,
                ja_applied if want_masks else None,
                jnp.any(ja_applied, axis=1),
                union0 | toolkit.pack_bool_rows(ja_applied),
            )
        return state, joined, ja_applied

    if ft_on:
        # packed [N, ceil(N/32)] uint32 applied-cells accumulator
        # (toolkit.pack_bool_rows layout): every fused apply site except
        # suspicion expiry ORs its gate in-pass (faulty marks are
        # excluded from changes_applied, exactly like the classic union)
        union0 = jnp.zeros((n, toolkit.packed_width(n)), jnp.uint32)
        state, joined, ja_applied, ja_rows, union = _phase(
            gate,
            jnp.any(joiner),
            _join_phase,
            lambda s: (
                s,
                jnp.zeros(n, bool),
                jnp.zeros((n, n), bool) if want_masks else None,
                jnp.zeros(n, bool),
                union0,
            ),
            state,
        )
    else:
        union = ja_rows = None  # fused-tick-only accumulators
        state, joined, ja_applied = _phase(
            gate,
            jnp.any(joiner),
            _join_phase,
            lambda s: (s, jnp.zeros(n, bool), jnp.zeros((n, n), bool)),
            state,
        )

    # rows whose VIEW changed so far this tick (revive reset, leave/rejoin
    # self-updates, join merge, makeAlive of joiners) — drives the dirty-row
    # checksum cache in _checksums_where
    dirty = rv | rejoin | joined | (
        ja_rows if ft_on else jnp.any(ja_applied, axis=1)
    )
    if inputs.leave is not None:
        dirty = dirty | lv

    # fused parity mode additionally tracks WHICH cells changed, so the
    # record cache re-encodes O(changed cells), not O(dirty rows * N).
    # Conservative over-approximations (whole revived/joined rows) are
    # bit-neutral: re-encoding an unchanged cell reproduces its bytes.
    # (`fused` itself is resolved at the top of the tick.)
    changed_mid = None
    if fused:
        changed_mid = (
            rv[:, None]  # row reset: cells became unknown too
            | (joined[:, None] & state.known)
            | (rejoin[:, None] & is_self)
            | ja_applied
        )
        if inputs.leave is not None:
            changed_mid = changed_mid | (lv[:, None] & is_self)

    # change-table occupancy before the dissemination phases: the
    # histogram plane's retirement baseline (phases 3/5.5/7 only CLEAR
    # ch_active at the piggyback bound; applies only SET it — so
    # pre & ~post is exactly the net-retired cell set)
    pre_pb_active = state.ch_active if params.histograms else None

    # checksum each sender advertises in its ping body this tick — its value
    # as of the end of the previous tick (ping-sender.js:70-76 reads it at
    # message-build time, before any same-period receives land)
    advertised_checksum = state.checksum
    # the sender's self-incarnation rides in the same ping body, read at
    # the same build time: the phase-5/6 origin filters must compare a
    # change's sourceIncarnationNumber against THIS value, not the
    # post-receive one — a sender that refutes a defamation mid-tick bumps
    # its self-incarnation AFTER its ping body was already built
    sent_self_inc = _self_view(state.inc)

    # ---- phase 2: target selection (round-robin iterator) -------------
    participating = state.proc_alive & state.ready & state.gossip_on
    pingable = (
        state.known
        & ((state.status == ALIVE) | (state.status == SUSPECT))
        & ~is_self
    )
    # first pingable member in walk order == the pingable member with the
    # smallest walk rank; rank is elementwise from the stored inverse
    # permutation, so the whole selection is one [N, N] compare plus a
    # row argmin — no gathers.  The mod-n is an add-if-negative: TPU
    # vector units have no integer divide, and an [N, N] `%` lowers to a
    # ~10 ms fusion at n=1024 (round-4 trace, PROF_1K_OPS.json) where
    # this select costs microseconds — bitwise-identical for the
    # difference's (-n, n) range.
    _wr = state.perm_inv - state.iter_pos[:, None]
    walk_rank = _wr + jnp.where(_wr < 0, n, 0)
    masked_rank = jnp.where(pingable, walk_rank, n)
    first_k = jnp.min(masked_rank, axis=1).astype(jnp.int32)
    has_target = first_k < n
    target = jnp.argmin(masked_rank, axis=1).astype(jnp.int32)
    target = jnp.where(participating & has_target, target, NO_TARGET)
    wrapped = has_target & ((state.iter_pos + first_k) >= n)
    iter_pos = jnp.where(
        participating & has_target, (state.iter_pos + first_k + 1) % n, state.iter_pos
    )
    # reshuffle permutation on wrap (membership/iterator.js:38-41).  The
    # reference Fisher-Yates-shuffles the member list; any fresh pseudo-
    # random permutation per wrapped row is inside its nondeterminism
    # envelope.  A full [N, N] argsort here was the hottest op in the
    # steady-state tick (a 1k-node cluster wraps ~one row per tick, firing
    # the cond almost always), so rows are instead re-drawn as affine
    # re-indexings of one shared hashed base permutation:
    #   new_perm[i, j] = base[(a_i * j + b_i) mod n]
    # with a_i drawn from the (static) coprimes of n — a permutation for
    # every (a_i, b_i), no sort, one [N, N] gather.  base itself is an [N]
    # argsort of fresh uniforms, so the family is re-randomized each wrap
    # tick.  Skipped entirely on wrap-free ticks (the draws are pure
    # functions of state.rng, so skipping changes no other randomness).
    # The host oracle mirrors this arithmetic bitwise (parity/oracle.py).
    # Deviation envelope caveat: rows that wrap on the SAME tick share one
    # base permutation, so their walk orders are affinely correlated
    # (the reference Fisher-Yates-shuffles each node independently).  In
    # steady state ~one row wraps per tick and the correlation is moot;
    # after a synchronized mass wrap (e.g. right after bootstrap, where
    # all iter_pos start equal) correlated walks can skew target-selection
    # collision statistics for a few rounds until wrap ticks desynchronize
    # (rows wrap at iter_pos + first_k >= n, and first_k varies per row).
    resh = wrapped & participating
    coprimes, coprime_invs = _coprimes_of(n)  # static [K] int32 each

    def _reshuffled(_):
        # perm[i, j] = base[(a_i*j + b_i) mod n]  (oracle materializes this
        # directly); stored inverse: perm_inv[i, m] =
        # a_i^-1 * (base_inv[m] - b_i) mod n — all elementwise
        base = jnp.argsort(_uniform(state.rng, (n,), salt=77)).astype(
            jnp.int32
        )
        base_inv = (
            jnp.zeros(n, jnp.int32)
            .at[base]
            .set(jnp.arange(n, dtype=jnp.int32))
        )
        r = _uniform(state.rng, (n, 2), salt=7)
        k_cop = np.int32(len(coprimes))
        a_idx = jnp.clip((r[:, 0] * k_cop).astype(jnp.int32), 0, k_cop - 1)
        a_inv = jnp.asarray(coprime_invs)[a_idx]
        b = (r[:, 1] * np.float32(n)).astype(jnp.int32) % n
        # the two [N, N] mod-n ops here were the HOTTEST device code in
        # the whole 1k scan (round-4 trace: ~18 ms per firing tick — TPU
        # has no integer divide).  (base_inv - b) spans (-n, n): mod is
        # an add-if-negative.  a_inv * d spans [0, n^2): for n <= 4096
        # every value is exact in float32, so quotient-by-float-division
        # with a one-step correction reproduces integer mod bit-for-bit
        # (the host oracle's plain % arithmetic is matched exactly).
        d = base_inv[None, :] - b[:, None]
        d = d + jnp.where(d < 0, n, 0)
        x = a_inv[:, None] * d
        if n <= 4096:  # n*n < 2^24: f32-exact path
            q = jnp.floor(
                x.astype(jnp.float32) / np.float32(n)
            ).astype(jnp.int32)
            idx = x - q * n
            idx = idx + jnp.where(idx < 0, n, 0)
            idx = idx - jnp.where(idx >= n, n, 0)
        else:  # [N, N] engines beyond 4k nodes are memory-bound anyway
            idx = x % n
        return jnp.where(resh[:, None], idx, state.perm_inv)

    perm_inv = _phase(
        gate,
        jnp.any(resh), _reshuffled, lambda _: state.perm_inv, None
    )
    state = state._replace(perm_inv=perm_inv, iter_pos=iter_pos)

    valid_send = target >= 0

    # ---- phase 3: sender piggyback selection (issueAsSender) ----------
    # max_pb is hoisted OUT of the phase-3 cond: the receiver-side bump in
    # phase 5.5 reuses it, and while phase 5 can only create changes when
    # phase 3 produced sendable content TODAY, a future phase inserted
    # between them would otherwise inherit an all-zero max_pb from the
    # skipped cond and instantly retire every new change.  The [N] digit
    # count from an [N, N] reduce is cheap at this engine's n <= a few k.
    server_count = jnp.sum(
        state.known & ((state.status == ALIVE) | (state.status == SUSPECT)),
        axis=1,
        dtype=jnp.int32,
    )
    max_pb = _max_piggyback(server_count, params.piggyback_factor)

    # nothing to select or bump when every change table is empty (the
    # converged steady state) — cond-gated like the other rare phases
    def _sender_piggyback(state):
        if ft_on:
            out = fpb.pb_budget(
                state.ch_active,
                state.ch_pb,
                valid_send.astype(jnp.int32),
                max_pb,
                impl=ft,
            )
            state = state._replace(
                ch_pb=out.ch_pb, ch_active=out.ch_active
            )
            return state, out.content, out.drops
        bump = valid_send[:, None] & state.ch_active
        ch_pb = state.ch_pb + bump.astype(jnp.int32)
        over = state.ch_active & (ch_pb > max_pb[:, None])
        sendable = bump & ~over  # message content mask [sender, subject]
        state = state._replace(
            ch_pb=ch_pb, ch_active=state.ch_active & ~over
        )
        return state, sendable, jnp.sum(over, dtype=jnp.int32)

    state, sendable, pb_drops_send = _phase(
        gate,
        jnp.any(state.ch_active),
        _sender_piggyback,
        lambda s: (s, jnp.zeros((n, n), bool), jnp.int32(0)),
        state,
    )

    # ---- phase 4: delivery mask ---------------------------------------
    loss = _uniform(state.rng, (n,), salt=13) < params.packet_loss
    tgt_ok = jnp.where(target >= 0, state.proc_alive[target], False)
    conn = jnp.where(
        target >= 0, partition == partition[jnp.clip(target, 0, n - 1)], False
    )
    delivered = valid_send & tgt_ok & conn & ~loss

    # ---- phase 5: receivers apply ping changes ------------------------
    # the segment-max winner-combine + apply runs only when some delivered
    # ping actually CARRIES changes; on a converged quiet tick every
    # change table is empty and the whole block cond-skips
    seg = jnp.where(delivered, target, n)  # undelivered -> dropped segment
    msg_content = sendable & delivered[:, None]

    # the suspicion-deadline stamp every in-tick start uses (classic
    # sites computed it inline; one shared traced value, CSE'd anyway)
    deadline = tick_next + params.suspicion_ticks

    def _combine_ping(state):
        """The ping-message winner-combine (shared by the classic and
        fused receive shapes — only the APPLY differs between them)."""
        keys = jnp.where(
            msg_content,
            _pack_key(state.ch_inc, state.ch_status),
            jnp.int32(-1),
        )
        recv_key = jax.ops.segment_max(
            keys, seg, num_segments=n + 1, indices_are_sorted=False
        )[:n]
        recv_mask = recv_key >= 0
        # winning sender (lowest index among ties) recovers source fields
        is_winner = (
            keys == _rows(recv_key, jnp.clip(target, 0, n - 1), n)
        ) & msg_content
        sender_ids = jnp.broadcast_to(node, (n, n))
        winner_sender = jax.ops.segment_min(
            jnp.where(is_winner, sender_ids, n), seg, num_segments=n + 1
        )[:n]
        u_status = (recv_key % 4).astype(jnp.int32)
        u_inc = recv_key // 4
        # winner's source fields WITHOUT a general [N, N] gather (the
        # round-4 trace's hottest ops): mark the unique winning (sender,
        # subject) cell — the min-id sender among max-key holders, found
        # by selecting each sender's own segment row of winner_sender —
        # and segment-reduce the source fields over that singleton mask.
        # Exact: exactly one final winner per delivered (receiver,
        # subject); undelivered segments reduce to the sentinel and are
        # masked by recv_mask downstream, as before.
        wsrow = _rows(winner_sender, jnp.clip(target, 0, n - 1), n)
        final_w = is_winner & (sender_ids == wsrow)
        NEG = jnp.int32(-(2**31))
        u_source = jax.ops.segment_max(
            jnp.where(final_w, state.ch_source, NEG),
            seg,
            num_segments=n + 1,
        )[:n]
        u_source_inc = jax.ops.segment_max(
            jnp.where(final_w, state.ch_source_inc, NEG),
            seg,
            num_segments=n + 1,
        )[:n]
        return recv_mask, u_status, u_inc, u_source, u_source_inc

    def _receive_phase(state, union=None):
        upd = _combine_ping(state)
        state, union, applied_ping, rows, refute_diag, _cnt = _apply_site(
            state, union, *upd, now, deadline, impl=ft,
            want_masks=want_masks,
        )
        # refute cells live on the diagonal only (is_self), so the [N]
        # diagonal carries the full mask — the flight recorder's
        # per-refuter view; metrics sum it (identical to the old matrix
        # sum)
        if ft_on:
            return state, union, applied_ping, rows, refute_diag
        return state, applied_ping, refute_diag

    if ft_on:
        state, union, applied_ping, rows_ping, refute_recv = _phase(
            gate,
            jnp.any(msg_content),
            _receive_phase,
            lambda s, u: (
                s,
                u,
                jnp.zeros((n, n), bool) if want_masks else None,
                jnp.zeros(n, bool),
                jnp.zeros(n, bool),
            ),
            state,
            union,
        )
        dirty = dirty | rows_ping
    else:
        state, applied_ping, refute_recv = _phase(
            gate,
            jnp.any(msg_content),
            _receive_phase,
            lambda s: (s, jnp.zeros((n, n), bool), jnp.zeros(n, bool)),
            state,
        )
        dirty = dirty | jnp.any(applied_ping, axis=1)
    if fused:
        changed_mid = changed_mid | applied_ping

    # receiver-side piggyback bump: one issueAsReceiver per delivered ping.
    # The receiver-origin filter runs BEFORE the bump (dissemination.js:
    # 147-160), so a change does not burn budget on pings from the sender
    # that originated it.  A change has exactly one recorded origin, hence
    # at most one of this tick's pinging senders can be filtered for it.
    # Cond-gated: with no active changes anywhere there is nothing to bump.
    nrecv = jax.ops.segment_sum(
        delivered.astype(jnp.int32), seg, num_segments=n + 1
    )[:n]

    def _receiver_bump(state):
        # the origin filter's per-cell gathers by ch_source stay in XLA
        # either way (the toolkit convention: dynamic gathers never
        # live inside a row-tiled kernel)
        src_c = jnp.clip(state.ch_source, 0, n - 1)
        origin_hit = (
            state.ch_active
            & (state.ch_source >= 0)
            & delivered[src_c]
            & (target[src_c] == node)
            & (state.ch_source_inc == sent_self_inc[src_c])
        )
        if ft_on:
            out = fpb.pb_budget(
                state.ch_active,
                state.ch_pb,
                nrecv,
                max_pb,
                origin_hit.astype(jnp.int32),
                impl=ft,
            )
            state = state._replace(
                ch_pb=out.ch_pb, ch_active=out.ch_active
            )
            return state, out.content, out.drops
        bump_r = (nrecv[:, None] > 0) & state.ch_active
        nbump = jnp.where(
            bump_r, nrecv[:, None] - origin_hit.astype(jnp.int32), 0
        )
        ch_pb = state.ch_pb + nbump
        over_r = state.ch_active & (ch_pb > max_pb[:, None])
        respondable = bump_r & ~over_r
        state = state._replace(
            ch_pb=ch_pb, ch_active=state.ch_active & ~over_r
        )
        return state, respondable, jnp.sum(over_r, dtype=jnp.int32)

    state, respondable, pb_drops_recv = _phase(
        gate,
        jnp.any(state.ch_active),
        _receiver_bump,
        lambda s: (s, jnp.zeros((n, n), bool), jnp.int32(0)),
        state,
    )

    # mid-tick checksums (receivers respond with post-update checksums);
    # only rows whose view changed since last tick's cache are rehashed
    mid_checksum, mid_overflow, state = _checksums_where(
        state, universe, params, dirty, state.checksum, changed_mid
    )

    # ---- phase 6: responses (issueAsReceiver + full-sync) -------------
    tgt = jnp.clip(target, 0, n - 1)
    cur_self_inc = _self_view(state.inc)
    # a response can only exist where the target holds respondable changes
    # or its checksum disagrees with the ping body's — cond-gate the row
    # gathers + apply off the converged quiet tick
    resp_possible = delivered & (
        jnp.any(respondable, axis=1)[tgt]
        | (mid_checksum[tgt] != advertised_checksum)
    )

    def _response_phase(state):
        # filter: drop changes the sender itself originated
        # (dissemination.js:91-98) — matched against the ping-body
        # incarnation (sent_self_inc)
        resp_filter = (
            (_rows(state.ch_source, tgt, n) == node)
            & (_rows(state.ch_source_inc, tgt, n) == sent_self_inc[:, None])
        )
        resp_mask = delivered[:, None] & _rows(respondable, tgt, n) & ~resp_filter
        any_resp_change = jnp.any(resp_mask, axis=1)
        # full-sync: no changes to send back AND checksums differ
        # (sender's checksum rides in the ping body, ping-sender.js:70-76)
        full_sync = delivered & ~any_resp_change & (
            mid_checksum[tgt] != advertised_checksum
        )
        fs_mask = full_sync[:, None] & _rows(state.known, tgt, n)
        r_status = jnp.where(
            fs_mask, _rows(state.status, tgt, n), _rows(state.ch_status, tgt, n)
        )
        r_inc = jnp.where(
            fs_mask, _rows(state.inc, tgt, n), _rows(state.ch_inc, tgt, n)
        )
        r_source = jnp.where(
            fs_mask,
            jnp.broadcast_to(target[:, None], (n, n)),
            _rows(state.ch_source, tgt, n),
        )
        r_source_inc = jnp.where(
            fs_mask,
            state.inc[tgt, tgt][:, None],
            _rows(state.ch_source_inc, tgt, n),
        )
        apply_resp = resp_mask | fs_mask
        state, union_r, applied_resp, rows, refute_diag, _cnt = (
            _apply_site(
                state,
                union,
                apply_resp,
                r_status,
                r_inc,
                r_source,
                r_source_inc,
                now,
                deadline,
                impl=ft,
                want_masks=want_masks,
            )
        )
        # per-sender record counts (rows of the full-sync payloads);
        # the scalar metric is their sum, the flight recorder wants
        # them per event
        fs_rec = jnp.sum(fs_mask, axis=1, dtype=jnp.int32)
        if ft_on:
            return (
                state,
                union_r,
                applied_resp,
                rows,
                full_sync,
                refute_diag,
                fs_rec,
            )
        return state, applied_resp, full_sync, refute_diag, fs_rec

    if ft_on:
        (
            state,
            union,
            applied_resp,
            rows_resp,
            full_sync,
            refute_resp,
            fs_rec_rows,
        ) = _phase(
            gate,
            jnp.any(resp_possible),
            _response_phase,
            lambda s: (
                s,
                union,
                jnp.zeros((n, n), bool) if want_masks else None,
                jnp.zeros(n, bool),
                jnp.zeros(n, bool),
                jnp.zeros(n, bool),
                jnp.zeros(n, jnp.int32),
            ),
            state,
        )
    else:
        state, applied_resp, full_sync, refute_resp, fs_rec_rows = _phase(
            gate,
            jnp.any(resp_possible),
            _response_phase,
            lambda s: (
                s,
                jnp.zeros((n, n), bool),
                jnp.zeros(n, bool),
                jnp.zeros(n, bool),
                jnp.zeros(n, jnp.int32),
            ),
            state,
        )
    fs_records = jnp.sum(fs_rec_rows, dtype=jnp.int32)

    # ---- phase 7: ping-req (indirect probe) ---------------------------
    # only nodes whose DIRECT ping failed probe indirectly; on a healthy
    # steady-state tick nobody does, so the [N, N] top-k and the whole
    # suspect-apply run under lax.cond (draws are salt-pure, skip-safe).
    # The exchange carries dissemination both ways, like the reference:
    # the probing sender piggybacks its changes on each ping-req body
    # (ping-req-sender.js:74-79 issueAsSender — one bump per selected
    # intermediary, bump-even-if-unreachable like the ping path's quirk),
    # the intermediary applies them (server/protocol/ping-req.js:46) and
    # answers with issueAsReceiver(source, sourceInc, checksum) — origin
    # filter, budget bump, full-sync on checksum mismatch — which the
    # sender applies before judging reachability
    # (ping-req-sender.js:132-139, server/protocol/ping-req.js:62-66).
    # Deviation envelope (documented): the intermediary's relay ping to
    # the target is modeled as reachability only — its OWN piggyback
    # exchange with the target (ping-sender semantics on the M->T leg)
    # is not carried; dissemination rides the A<->M legs above.  One
    # loss draw covers each A<->M round trip.
    need_pr = valid_send & ~delivered
    K_pr = params.ping_req_size

    # Checksum serialization (same envelope the ping path already uses —
    # advertised_checksum is last tick's value, the response compare is
    # the mid-tick value): BOTH sides of the ping-req full-sync decision
    # use mid-tick checksums.  A fresh post-leg-2 recompute would be a
    # THIRD encode per tick — it cannot live inside this phase's cond
    # (the tunnel's compile helper rejects any encode under control
    # flow, DIAG_BOUNDED.json) and hoisting it straight-line made the
    # full-mode tick heavy enough to kernel-fault the TPU worker at a
    # 32-tick scan.  The host oracle mirrors this choice bitwise.
    def _ping_req_phase(state):
        pr_rand = _uniform(state.rng, (n, n), salt=29)
        pr_ok = (
            pingable
            & (subject != target[:, None])
            & need_pr[:, None]
        )
        pr_score = jnp.where(pr_ok, pr_rand, 2.0)
        neg_prtop, pr_sel = jax.lax.top_k(-pr_score, K_pr)
        pr_valid = -neg_prtop < 1.5

        m_alive = state.proc_alive[pr_sel]
        m_conn = partition[pr_sel] == partition[:, None]
        loss1 = _uniform(state.rng, (n, K_pr), salt=31) < params.packet_loss
        responder = pr_valid & m_alive & m_conn & ~loss1  # intermediary ok
        t_alive = jnp.where(need_pr, state.proc_alive[tgt], False)
        t_conn = partition[pr_sel] == partition[tgt][:, None]
        loss2 = _uniform(state.rng, (n, K_pr), salt=37) < params.packet_loss
        reached = responder & t_alive[:, None] & t_conn & ~loss2

        any_responded = jnp.any(responder, axis=1)
        target_reached = jnp.any(reached, axis=1)
        mark_suspect = need_pr & any_responded & ~target_reached
        # no responders at all => inconclusive, no verdict
        # (ping-req-sender.js:249-262 only judges when responses arrived)
        pr_inconclusive = jnp.sum(
            need_pr & ~any_responded, dtype=jnp.int32
        )
        ping_req_count = jnp.sum(
            jnp.where(need_pr[:, None], pr_valid, False),
            dtype=jnp.int32,
        )
        # the ping-req body's sourceIncarnationNumber is read at BUILD
        # time — after this period's ping/response exchanges (phases 5-6)
        # may have refuted and bumped the sender's self-incarnation
        pr_self_inc = _self_view(state.inc)

        # -- leg 1: sender piggyback (issueAsSender per selected slot) --
        # slot k's body holds the changes still active at that call with
        # pb + k + 1 <= max_pb; every valid slot bumps whether or not the
        # intermediary is reachable (the dissemination.js:142-155 quirk)
        pb0, active0 = state.ch_pb, state.ch_active
        n_slots = jnp.sum(pr_valid, axis=1, dtype=jnp.int32)  # [N]
        if ft_on:
            # content mask unused at this site: slot-k message content
            # (send_k below) is computed from the PRE-bump planes
            out1 = fpb.pb_budget(
                active0, pb0, n_slots, max_pb, impl=ft, want_content=False
            )
            state = state._replace(
                ch_pb=out1.ch_pb, ch_active=out1.ch_active
            )
            pb_drops_pr = out1.drops
        else:
            new_pb = pb0 + jnp.where(active0, n_slots[:, None], 0)
            over_pr = active0 & (new_pb > max_pb[:, None])
            state = state._replace(
                ch_pb=new_pb, ch_active=active0 & ~over_pr
            )
            pb_drops_pr = jnp.sum(over_pr, dtype=jnp.int32)

        karange = jnp.arange(K_pr, dtype=jnp.int32)
        send_k = (  # [N, K, N]: slot-k message content per sender
            active0[:, None, :]
            & (
                pb0[:, None, :] + karange[None, :, None] + 1
                <= max_pb[:, None, None]
            )
            & pr_valid[:, :, None]
        )
        arrive = send_k & responder[:, :, None]

        # -- leg 2: intermediaries apply (winner-combine per subject) --
        nk = n * K_pr
        segf = jnp.where(responder, pr_sel, n).reshape(nk)
        keysf = jnp.where(
            arrive,
            _pack_key(state.ch_inc, state.ch_status)[:, None, :],
            jnp.int32(-1),
        ).reshape(nk, n)
        recv_key_pr = jax.ops.segment_max(
            keysf, segf, num_segments=n + 1
        )[:n]
        recv_mask_pr = recv_key_pr >= 0
        wrow = _rows(recv_key_pr, jnp.clip(segf, 0, n - 1), n)
        is_w = (keysf == wrow) & (keysf >= 0)
        flat_ids = jnp.broadcast_to(
            jnp.arange(nk, dtype=jnp.int32)[:, None], (nk, n)
        )
        winner_flat = jax.ops.segment_min(
            jnp.where(is_w, flat_ids, nk), segf, num_segments=n + 1
        )[:n]
        final_w = is_w & (flat_ids == _rows(winner_flat, jnp.clip(segf, 0, n - 1), n))
        NEG = jnp.int32(-(2**31))
        src3 = jnp.broadcast_to(
            state.ch_source[:, None, :], (n, K_pr, n)
        ).reshape(nk, n)
        srcinc3 = jnp.broadcast_to(
            state.ch_source_inc[:, None, :], (n, K_pr, n)
        ).reshape(nk, n)
        u_source_pr = jax.ops.segment_max(
            jnp.where(final_w, src3, NEG), segf, num_segments=n + 1
        )[:n]
        u_srcinc_pr = jax.ops.segment_max(
            jnp.where(final_w, srcinc3, NEG), segf, num_segments=n + 1
        )[:n]
        state, union_pr, applied_prm, rows_prm, refute_m, _cm = (
            _apply_site(
                state,
                union,
                recv_mask_pr,
                (recv_key_pr % 4).astype(jnp.int32),
                recv_key_pr // 4,
                u_source_pr,
                u_srcinc_pr,
                now,
                deadline,
                impl=ft,
                want_masks=want_masks,
            )
        )
        # -- leg 3: responses (issueAsReceiver per arriving ping-req) --
        # budget bump: one per arriving message, origin-filtered BEFORE
        # the bump (dissemination.js:147-160); aggregated like the ping
        # path's phase 5.5 (one respondable set per intermediary)
        cnt_sm = jnp.zeros((n, n), jnp.int32)  # [M, sender] arrivals
        for k in range(K_pr):
            cnt_sm = cnt_sm.at[
                pr_sel[:, k], jnp.arange(n, dtype=jnp.int32)
            ].add(jnp.where(responder[:, k], 1, 0), mode="drop")
        prrecv = jnp.sum(cnt_sm, axis=1, dtype=jnp.int32)
        src_c = jnp.clip(state.ch_source, 0, n - 1)
        hits = jnp.where(
            state.ch_active
            & (state.ch_source >= 0)
            & (state.ch_source_inc == pr_self_inc[src_c]),
            jnp.take_along_axis(cnt_sm, src_c, axis=1),
            0,
        )
        if ft_on:
            out3 = fpb.pb_budget(
                state.ch_active,
                state.ch_pb,
                prrecv,
                max_pb,
                hits,
                impl=ft,
            )
            respondable_pr = out3.content
            state = state._replace(
                ch_pb=out3.ch_pb, ch_active=out3.ch_active
            )
            pb_drops_pr = pb_drops_pr + out3.drops
        else:
            bump_pr = (prrecv[:, None] > 0) & state.ch_active
            nb = jnp.where(bump_pr, prrecv[:, None] - hits, 0)
            ch_pb2 = state.ch_pb + nb
            over2 = state.ch_active & (ch_pb2 > max_pb[:, None])
            respondable_pr = bump_pr & ~over2
            state = state._replace(
                ch_pb=ch_pb2, ch_active=state.ch_active & ~over2
            )
            pb_drops_pr = pb_drops_pr + jnp.sum(over2, dtype=jnp.int32)

        # response content per slot, winner-combined at the sender (max
        # key; ties keep the lowest slot): filtered changes, or the
        # intermediary's full membership when it has nothing to send and
        # the checksums disagree (dissemination.js:101-114)
        best_key = jnp.full((n, n), -1, jnp.int32)
        best_src = jnp.full((n, n), -1, jnp.int32)
        best_srcinc = jnp.zeros((n, n), jnp.int32)
        # per-slot full-sync masks + record counts, stacked [N, K] below:
        # the scalar metrics are their sums (bit-identical to the old
        # running scalars), the flight recorder emits them per event
        pr_fs_list = []
        pr_fs_rec_list = []
        for k in range(K_pr):
            mk = pr_sel[:, k]
            ex_k = responder[:, k]
            resp_k = (
                ex_k[:, None]
                & _rows(respondable_pr, mk, n)
                & ~(
                    (_rows(state.ch_source, mk, n) == node)
                    & (
                        _rows(state.ch_source_inc, mk, n)
                        == pr_self_inc[:, None]
                    )
                )
            )
            fs_k = ex_k & ~jnp.any(resp_k, axis=1) & (
                mid_checksum[mk] != mid_checksum
            )
            pr_fs_list.append(fs_k)
            fs_mask_k = fs_k[:, None] & _rows(state.known, mk, n)
            pr_fs_rec_list.append(
                jnp.sum(fs_mask_k, axis=1, dtype=jnp.int32)
            )
            mask_k = resp_k | fs_mask_k
            st_k = jnp.where(
                fs_mask_k,
                _rows(state.status, mk, n),
                _rows(state.ch_status, mk, n),
            )
            inc_k = jnp.where(
                fs_mask_k,
                _rows(state.inc, mk, n),
                _rows(state.ch_inc, mk, n),
            )
            src_k = jnp.where(
                fs_mask_k,
                jnp.broadcast_to(mk[:, None], (n, n)),
                _rows(state.ch_source, mk, n),
            )
            srcinc_k = jnp.where(
                fs_mask_k,
                state.inc[mk, mk][:, None],
                _rows(state.ch_source_inc, mk, n),
            )
            key_k = jnp.where(mask_k, _pack_key(inc_k, st_k), jnp.int32(-1))
            better = key_k > best_key
            best_key = jnp.where(better, key_k, best_key)
            best_src = jnp.where(better, src_k, best_src)
            best_srcinc = jnp.where(better, srcinc_k, best_srcinc)
        state, union_pr, applied_prr, rows_prr, refute_rr, _cr = (
            _apply_site(
                state,
                union_pr,
                best_key >= 0,
                (best_key % 4).astype(jnp.int32),
                best_key // 4,
                best_src,
                best_srcinc,
                now,
                deadline,
                impl=ft,
                want_masks=want_masks,
            )
        )

        # -- suspect verdict, on post-response state (the reference
        # makes the suspect AFTER every ping-req callback applied its
        # changes: ping-req-sender.js:249-262) --
        sus_mask = jnp.zeros((n, n), bool).at[jnp.arange(n, dtype=jnp.int32), tgt].set(mark_suspect)
        sus_inc = state.inc[jnp.arange(n, dtype=jnp.int32), tgt]  # member's current inc
        cur_self = _self_view(state.inc)
        state, union_pr, applied_sus, rows_sus, _rd, sus_cnt = _apply_site(
            state,
            union_pr,
            sus_mask,
            jnp.full((n, n), SUSPECT, jnp.int32),
            jnp.broadcast_to(sus_inc[:, None], (n, n)),
            jnp.broadcast_to(node, (n, n)).astype(jnp.int32),
            jnp.broadcast_to(cur_self[:, None], (n, n)),
            now,
            deadline,
            impl=ft,
            want_masks=want_masks,
            want_count=True,
            want_refute=False,
        )
        if ft_on:
            applied_pr = (
                (applied_prm | applied_prr | applied_sus)
                if want_masks
                else None
            )
            return (
                state,
                union_pr,
                applied_sus,
                applied_pr,
                rows_prm | rows_prr | rows_sus,
                sus_cnt,
                ping_req_count,
                pr_inconclusive,
                pb_drops_pr,
                refute_m,
                refute_rr,
                jnp.stack(pr_fs_list, axis=1),
                jnp.stack(pr_fs_rec_list, axis=1),
                pr_sel,
            )
        applied_pr = applied_prm | applied_prr | applied_sus
        return (
            state,
            applied_sus,
            applied_pr,
            ping_req_count,
            pr_inconclusive,
            pb_drops_pr,
            refute_m,
            refute_rr,
            jnp.stack(pr_fs_list, axis=1),
            jnp.stack(pr_fs_rec_list, axis=1),
            pr_sel,
        )

    if ft_on:
        (
            state,
            union,
            applied_sus,
            applied_pr,
            rows_pr,
            sus_count,
            ping_req_count,
            pr_inconclusive,
            pb_drops_pr,
            refute_prm,
            refute_prr,
            pr_fs_mask,
            pr_fs_recs,
            pr_sel,
        ) = _phase(
            gate,
            jnp.any(need_pr),
            _ping_req_phase,
            lambda s: (
                s,
                union,
                jnp.zeros((n, n), bool) if want_masks else None,
                jnp.zeros((n, n), bool) if want_masks else None,
                jnp.zeros(n, bool),
                jnp.int32(0),
                jnp.int32(0),
                jnp.int32(0),
                jnp.int32(0),
                jnp.zeros(n, bool),
                jnp.zeros(n, bool),
                jnp.zeros((n, K_pr), bool),
                jnp.zeros((n, K_pr), jnp.int32),
                jnp.zeros((n, K_pr), jnp.int32),
            ),
            state,
        )
    else:
        (
            state,
            applied_sus,
            applied_pr,
            ping_req_count,
            pr_inconclusive,
            pb_drops_pr,
            refute_prm,
            refute_prr,
            pr_fs_mask,
            pr_fs_recs,
            pr_sel,
        ) = _phase(
            gate,
            jnp.any(need_pr),
            _ping_req_phase,
            lambda s: (
                s,
                jnp.zeros((n, n), bool),
                jnp.zeros((n, n), bool),
                jnp.int32(0),
                jnp.int32(0),
                jnp.int32(0),
                jnp.zeros(n, bool),
                jnp.zeros(n, bool),
                jnp.zeros((n, K_pr), bool),
                jnp.zeros((n, K_pr), jnp.int32),
                jnp.zeros((n, K_pr), jnp.int32),
            ),
            state,
        )
    pr_fs_count = jnp.sum(pr_fs_mask, dtype=jnp.int32)
    pr_fs_records = jnp.sum(pr_fs_recs, dtype=jnp.int32)

    # ---- phase 8: suspicion expiry ------------------------------------
    # active suspicion deadlines exist only while suspects are in flight;
    # the expiry scan + faulty-apply is cond-gated off the common tick.
    # The gate mirrors the inner mask's participating filter exactly — a
    # due deadline held by a dead/stopped/left observer must not latch the
    # gate true forever (the deadline itself is kept: a SIGCONT-resumed
    # observer's suspicions expire then, like the reference's timers)
    any_deadline = jnp.any(
        (state.susp_deadline >= 0)
        & (state.susp_deadline <= tick_next)
        & participating[:, None]
    )

    def _expiry_phase(state):
        expired = (
            (state.susp_deadline >= 0)
            & (state.susp_deadline <= tick_next)
            & participating[:, None]
        )
        state = state._replace(
            susp_deadline=jnp.where(expired, -1, state.susp_deadline)
        )
        # faulty marks are excluded from the changes_applied union, so
        # the fused site runs with union=None (no accumulation); the
        # fused stamp fold is a no-op here by construction (a FAULTY
        # update can never start a suspicion timer)
        state, _u, applied_faulty, rows, _rd, cnt = _apply_site(
            state,
            None,
            expired,
            jnp.full((n, n), FAULTY, jnp.int32),
            state.inc,  # member's current incarnation (suspicion.js:67-70)
            jnp.broadcast_to(node, (n, n)).astype(jnp.int32),
            jnp.broadcast_to(cur_self_inc[:, None], (n, n)),
            now,
            deadline,
            impl=ft,
            want_masks=want_masks,
            want_count=True,
            want_refute=False,
            stamp=False,
        )
        if ft_on:
            return state, applied_faulty, rows, cnt
        return state, applied_faulty

    if ft_on:
        state, applied_faulty, rows_faulty, faulty_count = _phase(
            gate,
            any_deadline,
            _expiry_phase,
            lambda s: (
                s,
                jnp.zeros((n, n), bool) if want_masks else None,
                jnp.zeros(n, bool),
                jnp.int32(0),
            ),
            state,
        )
    else:
        state, applied_faulty = _phase(
            gate,
            any_deadline,
            _expiry_phase,
            lambda s: (s, jnp.zeros((n, n), bool)),
            state,
        )

    # ---- phase 9: checksums + metrics ---------------------------------
    # rows untouched since the mid-tick values reuse them; phases 6-8
    # dirty views via responses, the ping-req exchange, and expiry
    if ft_on:
        dirty_late = rows_resp | rows_pr | rows_faulty
    else:
        dirty_late = (
            jnp.any(applied_resp, axis=1)
            | jnp.any(applied_pr, axis=1)
            | jnp.any(applied_faulty, axis=1)
        )
    changed_late = None
    if fused:
        # fused-checksum cell tracking forces want_masks, so the dense
        # per-site masks exist in every tick mode
        changed_late = applied_resp | applied_pr | applied_faulty
    checksum, late_overflow, state = _checksums_where(
        state, universe, params, dirty_late, mid_checksum, changed_late
    )
    state = state._replace(checksum=checksum)

    part = state.proc_alive & state.ready
    # count distinct checksums among participants: sort, count boundaries
    cs = jnp.where(part, checksum, jnp.uint32(0xFFFFFFFF))
    cs_sorted = jnp.sort(cs)
    distinct = (
        jnp.sum(
            (cs_sorted[1:] != cs_sorted[:-1])
            & (cs_sorted[1:] != jnp.uint32(0xFFFFFFFF)),
            dtype=jnp.int32,
        )
        + (cs_sorted[0] != jnp.uint32(0xFFFFFFFF)).astype(jnp.int32)
    ).astype(jnp.int32)

    if ft_on:
        # the fused sites fed the union/count reductions in-pass; the
        # sums below are over the SAME cell sets the classic mask
        # expressions cover (integer sums — bitwise-identical)
        changes_applied = jnp.sum(popcount_u32(union), dtype=jnp.int32)
        suspects_marked = sus_count
        faulties_marked = faulty_count
    else:
        changes_applied = jnp.sum(
            (applied_ping | applied_resp | applied_pr | ja_applied).astype(
                jnp.int32
            )
        )
        suspects_marked = jnp.sum(applied_sus.astype(jnp.int32))
        faulties_marked = jnp.sum(applied_faulty.astype(jnp.int32))

    metrics = TickMetrics(
        pings_sent=jnp.sum(valid_send.astype(jnp.int32)),
        pings_delivered=jnp.sum(delivered.astype(jnp.int32)),
        ping_reqs=ping_req_count,
        full_syncs=jnp.sum(full_sync.astype(jnp.int32)) + pr_fs_count,
        changes_applied=changes_applied,
        suspects_marked=suspects_marked,
        faulties_marked=faulties_marked,
        distinct_checksums=distinct,
        converged=distinct <= 1,
        parity_overflow=mid_overflow + late_overflow,
        refutes=jnp.sum(refute_recv, dtype=jnp.int32)
        + jnp.sum(refute_resp, dtype=jnp.int32)
        + jnp.sum(refute_prm, dtype=jnp.int32)
        + jnp.sum(refute_prr, dtype=jnp.int32),
        piggyback_drops=pb_drops_send + pb_drops_recv + pb_drops_pr,
        full_sync_records=fs_records + pr_fs_records,
        ping_req_inconclusive=pr_inconclusive,
        join_merges=jnp.sum(joined, dtype=jnp.int32),
        dirty_rows=jnp.sum(dirty, dtype=jnp.int32)
        + jnp.sum(dirty_late, dtype=jnp.int32),
    )

    # ---- flight recorder (opt-in, trajectory-neutral) -----------------
    # appended AFTER every protocol phase from the same masks that drove
    # them; nothing below writes protocol state (models/sim/flight.py)
    if params.flight_recorder:
        from ringpop_tpu.models.sim import flight

        state = flight.record_tick_events(
            state,
            tick_next,
            prev_known,
            prev_status,
            flight.TickEventMasks(
                valid_send=valid_send,
                target=target,
                delivered=delivered,
                applied_ping=applied_ping,
                applied_resp=applied_resp,
                applied_pr=applied_pr,
                ja_applied=ja_applied,
                applied_sus=applied_sus,
                applied_faulty=applied_faulty,
                joined=joined,
                full_sync=full_sync,
                fs_rec_rows=fs_rec_rows,
                pr_fs_mask=pr_fs_mask,
                pr_fs_recs=pr_fs_recs,
                pr_sel=pr_sel,
                refute_recv=refute_recv,
                refute_resp=refute_resp,
                refute_prm=refute_prm,
                refute_prr=refute_prr,
                revived=rv,
                left=lv,
                rejoined=rejoin,
            ),
        )

    # ---- latency histograms (opt-in, trajectory-neutral) --------------
    # bumped AFTER every protocol phase from the same masks that drove
    # them; write-only (nothing below touches protocol state), so the
    # plane is trajectory-neutral by construction — pinned by the
    # gate-equivalence tests in tests/models/test_hist_neutral.py.
    # Track semantics: HIST_TRACKS at the top of this module.
    if params.histograms:
        from ringpop_tpu.ops import histogram as hg

        hist = state.hist
        # rumor age at first-heard: gossip adoptions landing ALIVE —
        # stamp-as-mint-time makes the age exact for alive-class rumors
        adopted = (
            (applied_ping | applied_resp | applied_pr)
            & ~is_self
            & (state.status == ALIVE)
            & (state.inc > 0)
        )
        age = tick_next + 1 - state.inc
        hist = hg.record(
            hist, HIST_TRACKS.index("rumor_age"), age, adopted
        )
        # rumor age at retirement: the piggyback drop (dissemination.js:41)
        retired_cells = pre_pb_active & ~state.ch_active
        ret_age = tick_next + 1 - state.ch_inc
        hist = hg.record(
            hist, HIST_TRACKS.index("retired_age"), ret_age, retired_cells
        )
        # suspicion duration at timer stop (refute/override or expiry);
        # revive view-resets forget timers rather than resolving them
        stopped = (
            (prev_susp >= 0)
            & (state.susp_deadline == -1)
            & ~rv[:, None]
        )
        dur = tick_next - prev_susp + params.suspicion_ticks
        hist = hg.record(
            hist, HIST_TRACKS.index("suspicion_duration"), dur, stopped
        )
        hist = hg.record_count(
            hist, HIST_TRACKS.index("dirty_rows"), metrics.dirty_rows
        )
        state = state._replace(hist=hist)

    state = state._replace(rng=_fold(state.rng, 0x5EED))
    return state, metrics
