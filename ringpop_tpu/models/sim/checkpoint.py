"""Array-state checkpointing for long simulator runs (SURVEY §5.4).

The reference persists nothing — a restarted node rebuilds via join
full-sync (server/protocol/join.js:131) — but multi-minute 100k/1M-node
sweeps deserve kill-and-resume.  Any engine state (``SimState``,
``ScalableState`` — any NamedTuple of arrays) round-trips through one
``.npz`` file; resuming from a checkpoint continues the exact trajectory
bit-for-bit (the engines are deterministic pure functions of state).
"""

from __future__ import annotations

import json
from typing import Any, Optional, Type, TypeVar

import jax.numpy as jnp
import numpy as np

T = TypeVar("T", bound=tuple)

_FORMAT_KEY = "__ringpop_tpu_state__"
_PARAMS_KEY = "__ringpop_tpu_params__"
# params that tune performance without touching the trajectory — a resume
# may change these freely, and a checkpoint from a build predating one of
# them must still load (its absence on either side is ignored)
_TRAJECTORY_NEUTRAL_PARAMS = frozenset(
    {
        "dirty_batch",
        "checksum_in_tick",
        "gate_phases",
        "hash_impl",
        "parity_recompute",
        # fused parity pipeline: bitwise-identical checksums (pinned by
        # tests/ops/test_fused_checksum.py), so a resume may toggle it —
        # the record cache is rebuilt from (known, status, inc) on load
        "fused_checksum",
        "cell_batch",
        # flight recorder / wavefront tracing: write-only telemetry
        # planes, trajectory-neutral by construction (nothing in the
        # protocol reads them) — a resume may toggle or resize freely;
        # the drivers rebuild/drop the buffers on load
        "flight_recorder",
        "event_capacity",
        "wavefront",
        # round-10 scalable hot path: both knobs are bit-identical by
        # the gate-equivalence tests (tests/models/test_scalable_perm.py),
        # and drivers pin backend-resolved values at construction — a
        # TPU-saved checkpoint (fused_exchange="pallas") must load on a
        # CPU resume ("off"), and pre-round-10 checkpoints lack the keys
        "perm_impl",
        "fused_exchange",
    }
)
# v2: incarnation fields are int32 tick stamps (engine.stamp_to_ms), not
# int64 epoch-ms values — a v1 checkpoint's ms incarnations would be
# silently misread as stamps, so loads reject version mismatches
_FORMAT_VERSION = 2
# fields added after checkpoints of the same format version shipped:
# loadable with a derived default (sibling field supplies shape/dtype).
# defame_by (scalable engine, round 4): defaulting to the node's own id
# makes the refute reachability gate (partition[defame_by] == partition)
# vacuously true, i.e. a pre-round-4 checkpoint's defamed nodes refute
# on the old, laxer rule — inside the envelope the new field narrows.
_FIELD_DEFAULTS = {
    "defame_by": (
        "defame_slot",
        lambda arr: np.arange(arr.shape[0], dtype=arr.dtype),
    ),
}


def save_state(path: str, state: Any, params: Any = None) -> None:
    """Write a NamedTuple-of-arrays engine state to ``path``.

    ``params`` (the engine's SimParams/ScalableParams NamedTuple) is stored
    alongside so a resume can verify it runs under the same protocol
    constants.  The literal path is used — no silent ``.npz`` suffixing —
    so ``save(p)`` / ``load(p)`` always round-trip.
    """
    fields = getattr(state, "_fields", None)
    if fields is None:
        raise TypeError("state must be a NamedTuple of arrays")
    # optional fields (e.g. the fused record cache) may be None — they
    # are simply not stored; load_state restores their None default, and
    # derived caches are rebuilt by the driver (SimCluster.load)
    arrays = {
        name: np.asarray(getattr(state, name))
        for name in fields
        if getattr(state, name) is not None
    }
    arrays[_FORMAT_KEY] = np.array(
        [type(state).__name__, str(_FORMAT_VERSION)]
    )
    if params is not None:
        arrays[_PARAMS_KEY] = np.array(
            [json.dumps(dict(params._asdict()), sort_keys=True)]
        )
    with open(path, "wb") as f:
        np.savez(f, **arrays)


def load_state(path: str, state_cls: Type[T], params: Any = None) -> T:
    """Rebuild ``state_cls`` from a checkpoint written by ``save_state``.

    Mismatched fields (older engine revision) or — when both sides provide
    them — mismatched params raise rather than resuming a silently wrong
    trajectory.
    """
    with np.load(path, allow_pickle=False) as data:
        meta = data.get(_FORMAT_KEY)
        if meta is None:
            raise ValueError("%s is not a ringpop_tpu checkpoint" % path)
        saved_name = str(meta[0])
        if saved_name != state_cls.__name__:
            raise ValueError(
                "checkpoint holds %s, expected %s" % (saved_name, state_cls.__name__)
            )
        saved_version = int(meta[1]) if len(meta) > 1 else 0
        if saved_version != _FORMAT_VERSION:
            raise ValueError(
                "checkpoint format v%d, this build reads v%d (incarnation "
                "representation changed; a cross-version resume would "
                "silently corrupt the trajectory)"
                % (saved_version, _FORMAT_VERSION)
            )
        if params is not None and _PARAMS_KEY in data.files:
            saved_params = json.loads(str(data[_PARAMS_KEY][0]))
            current = json.loads(
                json.dumps(dict(params._asdict()), sort_keys=True)
            )
            for neutral in _TRAJECTORY_NEUTRAL_PARAMS:
                saved_params.pop(neutral, None)
                current.pop(neutral, None)
            if saved_params != current:
                diff = {
                    k: (saved_params.get(k), current.get(k))
                    for k in set(saved_params) | set(current)
                    if saved_params.get(k) != current.get(k)
                }
                raise ValueError(
                    "checkpoint params differ from the resuming engine's "
                    "(saved, current): %r" % diff
                )
        optional = set(getattr(state_cls, "_field_defaults", {}))
        missing = [
            f
            for f in state_cls._fields
            if f not in data.files
            and f not in _FIELD_DEFAULTS
            and f not in optional
        ]
        extra = [
            f
            for f in data.files
            if f not in state_cls._fields and f not in (_FORMAT_KEY, _PARAMS_KEY)
        ]
        if missing or extra:
            raise ValueError(
                "checkpoint fields do not match %s (missing=%r, extra=%r)"
                % (state_cls.__name__, missing, extra)
            )
        out = {}
        for f in state_cls._fields:
            if f not in data.files:
                if f in _FIELD_DEFAULTS:
                    sibling, default_of = _FIELD_DEFAULTS[f]
                    out[f] = jnp.asarray(
                        default_of(np.asarray(data[sibling]))
                    )
                else:  # optional field: its NamedTuple default (None)
                    out[f] = state_cls._field_defaults[f]
                continue
            arr = jnp.asarray(data[f])
            if arr.dtype != data[f].dtype:
                # e.g. int64 incarnations truncated to int32 because JAX
                # x64 is disabled (RINGPOP_TPU_NO_X64): resuming would
                # silently wrap epoch-ms timestamps
                raise ValueError(
                    "checkpoint field %r is %s but this process loads it "
                    "as %s (is JAX x64 mode off?)"
                    % (f, data[f].dtype, arr.dtype)
                )
            out[f] = arr
        return state_cls(**out)
