"""Array-state checkpointing for long simulator runs (SURVEY §5.4).

The reference persists nothing — a restarted node rebuilds via join
full-sync (server/protocol/join.js:131) — but multi-minute 100k/1M-node
sweeps deserve kill-and-resume, and at weak-scaling scale (ROADMAP item
2) preemption is the norm: a checkpoint layer that can silently serve a
torn or bit-rotted file is worse than none.  Two formats live here:

- the **legacy single-file format** (``save_state``/``load_state``): one
  ``.npz`` per state.  Writes go through tmp + fsync + ``os.replace`` so
  an interrupted save never shadows a previous good checkpoint, but the
  file carries no content digests — corruption surfaces only as far as
  ``np.load`` notices.
- the **manifest format** (``save_checkpoint``/``load_checkpoint``): a
  checkpoint *directory* holding one or more ``.npz`` array files plus a
  ``manifest.json`` carrying per-file AND per-array CRC32 content
  digests, shapes, dtypes, params, and free-form meta (the driver's tick
  counter).  Every file is written atomically and the manifest is
  written LAST — a directory without a valid manifest is not a
  checkpoint, so a crash at ANY byte of the save leaves either the
  previous complete checkpoint or an ignorable partial, never a torn
  artifact at a valid path.  Truncation, bit-rot, missing shards, and
  format drift are detected at load with **named errors** (the
  ``CheckpointError`` taxonomy below) instead of a silently corrupt
  resume.  States may be **sharded**: node-axis fields split across
  per-shard files, restorable onto any shard count (the loader always
  reassembles full arrays; the driver re-places them on its own mesh),
  bitwise-identical to the single-file path
  (tests/models/test_checkpoint.py).

Resuming from either format continues the exact trajectory bit-for-bit
(the engines are deterministic pure functions of state); rotation,
cadence, and newest-valid discovery live in
:mod:`ringpop_tpu.models.sim.recovery`.
"""

from __future__ import annotations

import io
import json
import os
import zlib
from typing import Any, Dict, List, Mapping, Optional, Tuple, Type, TypeVar

import jax.numpy as jnp
import numpy as np

T = TypeVar("T", bound=tuple)

_FORMAT_KEY = "__ringpop_tpu_state__"
_PARAMS_KEY = "__ringpop_tpu_params__"
# params that tune performance without touching the trajectory — a resume
# may change these freely, and a checkpoint from a build predating one of
# them must still load (its absence on either side is ignored)
_TRAJECTORY_NEUTRAL_PARAMS = frozenset(
    {
        "dirty_batch",
        "checksum_in_tick",
        "gate_phases",
        "hash_impl",
        "parity_recompute",
        # fused parity pipeline: bitwise-identical checksums (pinned by
        # tests/ops/test_fused_checksum.py), so a resume may toggle it —
        # the record cache is rebuilt from (known, status, inc) on load
        "fused_checksum",
        "cell_batch",
        # fused full-fidelity tick (round 16): bitwise-identical
        # trajectories in every mode (tests/models/test_fused_tick.py),
        # and drivers pin backend-resolved values at construction — a
        # TPU-saved checkpoint (fused_tick="pallas") must load on a CPU
        # resume ("xla"/"off"), and pre-round-16 checkpoints lack the key
        "fused_tick",
        # flight recorder / wavefront tracing: write-only telemetry
        # planes, trajectory-neutral by construction (nothing in the
        # protocol reads them) — a resume may toggle or resize freely;
        # the drivers rebuild/drop the buffers on load
        "flight_recorder",
        "event_capacity",
        "wavefront",
        # latency-histogram plane (round 15): same write-only telemetry
        # contract as the flight recorder — counters start fresh on a
        # toggled resume (fixup_sim_state / fixup_scalable_state /
        # RoutedStorm._rebuild_route_state)
        "histograms",
        # per-shard exchange telemetry plane (round 17): write-only like
        # the histograms — a resume may toggle or re-shard freely and
        # fixup_scalable_state re-zeroes the counters
        "exchange_metrics",
        # round-10 scalable hot path: both knobs are bit-identical by
        # the gate-equivalence tests (tests/models/test_scalable_perm.py),
        # and drivers pin backend-resolved values at construction — a
        # TPU-saved checkpoint (fused_exchange="pallas") must load on a
        # CPU resume ("off"), and pre-round-10 checkpoints lack the keys
        "perm_impl",
        "fused_exchange",
        # routing plane (RouteParams): the ring REPRESENTATION is not
        # part of the checkpointed carry — RoutedStorm persists only the
        # membership mask + rng and rebuilds the bucketed (or flat) ring
        # under its own impl/caps on load, bit-identically
        # (tests/models/test_route_plane.py roundtrip)
        "ring_impl",
        "bucket_bits",
        "max_changed",
        "max_dirty",
        # request observatory (round 19): the sampled per-request trace
        # plane is write-only like the flight recorder — a resume may
        # toggle sampling or resize the buffer freely;
        # RoutedStorm._rebuild_route_state opens a fresh trace window
        "reqtrace",
        "req_capacity",
        "req_sample_log2",
        "req_salt",
    }
)
# v2: incarnation fields are int32 tick stamps (engine.stamp_to_ms), not
# int64 epoch-ms values — a v1 checkpoint's ms incarnations would be
# silently misread as stamps, so loads reject version mismatches
_FORMAT_VERSION = 2
# fields added after checkpoints of the same format version shipped:
# loadable with a derived default (sibling field supplies shape/dtype).
# defame_by (scalable engine, round 4): defaulting to the node's own id
# makes the refute reachability gate (partition[defame_by] == partition)
# vacuously true, i.e. a pre-round-4 checkpoint's defamed nodes refute
# on the old, laxer rule — inside the envelope the new field narrows.
_FIELD_DEFAULTS = {
    "defame_by": (
        "defame_slot",
        lambda arr: np.arange(arr.shape[0], dtype=arr.dtype),
    ),
}

# -- named load-failure taxonomy --------------------------------------------
# All subclass ValueError so pre-round-13 callers catching ValueError keep
# working; the recovery scan (recovery.CheckpointManager) catches
# CheckpointError specifically and falls back past the corrupt artifact.


class CheckpointError(ValueError):
    """Base: this path does not hold a loadable checkpoint."""


class CheckpointNotFoundError(CheckpointError):
    """No checkpoint here at all (missing path/manifest, foreign file)."""


class CheckpointTornError(CheckpointError):
    """Partial/interrupted write: truncated file, unparseable manifest,
    or an archive ``np.load`` cannot open."""


class CheckpointDigestError(CheckpointError):
    """Content digest mismatch at full length — bit-rot or tampering."""


class CheckpointShardError(CheckpointError):
    """Sharded-manifest inconsistency: missing shard file or shard
    list/count drift."""


class CheckpointVersionError(CheckpointError):
    """Format version mismatch (a cross-version resume would silently
    corrupt the trajectory)."""


class CheckpointFieldError(CheckpointError):
    """State class / field set / dtype does not match the resuming
    engine's."""


class CheckpointParamsError(CheckpointError):
    """Trajectory-relevant params differ between save and resume."""


# -- atomic writes -----------------------------------------------------------


def _fsync_dir(dirpath: str) -> None:
    """Best-effort fsync of a directory so the rename itself is durable
    (platforms without directory fds just skip)."""
    try:
        fd = os.open(dirpath, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def atomic_write_bytes(path: str, data: bytes) -> None:
    """tmp + fsync + ``os.replace``: ``path`` either keeps its previous
    content or holds all of ``data`` — never a prefix.  The tmp file
    lives in the same directory (rename must not cross filesystems) and
    carries a ``.tmp.<pid>`` suffix the checkpoint scanners ignore."""
    path = os.fspath(path)
    tmp = "%s.tmp.%d" % (path, os.getpid())
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    _fsync_dir(os.path.dirname(os.path.abspath(path)))


def _npz_bytes(arrays: Dict[str, np.ndarray]) -> bytes:
    bio = io.BytesIO()
    np.savez(bio, **arrays)
    return bio.getvalue()


def _crc(buf: bytes) -> int:
    return zlib.crc32(buf) & 0xFFFFFFFF


def _array_crc(arr: np.ndarray) -> int:
    return _crc(np.ascontiguousarray(arr).tobytes())


# -- legacy single-file format ----------------------------------------------


def save_state(path: str, state: Any, params: Any = None) -> None:
    """Write a NamedTuple-of-arrays engine state to ``path``.

    ``params`` (the engine's SimParams/ScalableParams NamedTuple) is stored
    alongside so a resume can verify it runs under the same protocol
    constants.  The literal path is used — no silent ``.npz`` suffixing —
    so ``save(p)`` / ``load(p)`` always round-trip.  The write is atomic
    (tmp + fsync + ``os.replace``): an interrupted save never shadows a
    previous good checkpoint with a torn file.
    """
    fields = getattr(state, "_fields", None)
    if fields is None:
        raise TypeError("state must be a NamedTuple of arrays")
    # optional fields (e.g. the fused record cache) may be None — they
    # are simply not stored; load_state restores their None default, and
    # derived caches are rebuilt by the driver (SimCluster.load)
    arrays = {
        name: np.asarray(getattr(state, name))
        for name in fields
        if getattr(state, name) is not None
    }
    arrays[_FORMAT_KEY] = np.array(
        [type(state).__name__, str(_FORMAT_VERSION)]
    )
    if params is not None:
        arrays[_PARAMS_KEY] = np.array(
            [json.dumps(dict(params._asdict()), sort_keys=True)]
        )
    atomic_write_bytes(path, _npz_bytes(arrays))


def _params_jsonable(params: Any) -> Any:
    return json.loads(json.dumps(dict(params._asdict()), sort_keys=True))


def _check_params(saved_params: Any, params: Any, where: str) -> None:
    """Raise CheckpointParamsError when trajectory-relevant params differ
    (the _TRAJECTORY_NEUTRAL_PARAMS set may differ freely on either
    side)."""
    saved = dict(saved_params)
    current = _params_jsonable(params)
    for neutral in _TRAJECTORY_NEUTRAL_PARAMS:
        saved.pop(neutral, None)
        current.pop(neutral, None)
    if saved != current:
        diff = {
            k: (saved.get(k), current.get(k))
            for k in set(saved) | set(current)
            if saved.get(k) != current.get(k)
        }
        raise CheckpointParamsError(
            "%s: checkpoint params differ from the resuming engine's "
            "(saved, current): %r" % (where, diff)
        )


def _reconcile_fields(
    state_cls: Type[T], available: Dict[str, Any], where: str
) -> T:
    """Shared field-matching half of both load paths: missing/extra field
    detection, derived defaults for fields added post-ship
    (_FIELD_DEFAULTS), optional (None-default) fields, and the
    dtype-truncation guard.  ``available`` maps field name -> np array
    (fields stored as None simply absent)."""
    optional = set(getattr(state_cls, "_field_defaults", {}))
    missing = [
        f
        for f in state_cls._fields
        if f not in available and f not in _FIELD_DEFAULTS and f not in optional
    ]
    extra = [f for f in available if f not in state_cls._fields]
    if missing or extra:
        raise CheckpointFieldError(
            "%s: checkpoint fields do not match %s (missing=%r, extra=%r)"
            % (where, state_cls.__name__, missing, extra)
        )
    out = {}
    for f in state_cls._fields:
        if f not in available:
            if f in _FIELD_DEFAULTS:
                sibling, default_of = _FIELD_DEFAULTS[f]
                # dtype comes from the stored sibling array by design
                out[f] = jnp.array(  # jaxgate: ignore[implicit-dtype]
                    default_of(np.asarray(available[sibling])), copy=True
                )
            else:  # optional field: its NamedTuple default (None)
                out[f] = state_cls._field_defaults[f]
            continue
        src = np.asarray(available[f])
        # copy=True: on CPU, jnp.asarray(np_array) may ZERO-COPY the
        # numpy buffer — a restored state handed to a donating tick
        # (storm._tick_fn donate_argnums) would then let XLA scribble
        # over (or read after free of) host memory numpy still owns.
        # The loaded state must be device-owned.
        # dtype deliberately inherited from the stored array — the x64
        # truncation check right below is the guard
        arr = jnp.array(src, copy=True)  # jaxgate: ignore[implicit-dtype]
        if arr.dtype != src.dtype:
            # e.g. int64 incarnations truncated to int32 because JAX
            # x64 is disabled (RINGPOP_TPU_NO_X64): resuming would
            # silently wrap epoch-ms timestamps
            raise CheckpointFieldError(
                "%s: checkpoint field %r is %s but this process loads it "
                "as %s (is JAX x64 mode off?)"
                % (where, f, src.dtype, arr.dtype)
            )
        out[f] = arr
    return state_cls(**out)


def load_state(path: str, state_cls: Type[T], params: Any = None) -> T:
    """Rebuild ``state_cls`` from a checkpoint written by ``save_state``.

    Mismatched fields (older engine revision) or — when both sides provide
    them — mismatched params raise named :class:`CheckpointError`
    subclasses rather than resuming a silently wrong trajectory.
    """
    if not os.path.exists(path):
        raise CheckpointNotFoundError("%s does not exist" % path)
    try:
        ctx = np.load(path, allow_pickle=False)
    except Exception as e:
        raise CheckpointTornError(
            "%s is not a readable npz archive (truncated or partial "
            "write?): %s" % (path, e)
        )
    with ctx as data:
        meta = data.get(_FORMAT_KEY)
        if meta is None:
            raise CheckpointNotFoundError(
                "%s is not a ringpop_tpu checkpoint" % path
            )
        saved_name = str(meta[0])
        if saved_name != state_cls.__name__:
            raise CheckpointFieldError(
                "checkpoint holds %s, expected %s" % (saved_name, state_cls.__name__)
            )
        saved_version = int(meta[1]) if len(meta) > 1 else 0
        if saved_version != _FORMAT_VERSION:
            raise CheckpointVersionError(
                "checkpoint format v%d, this build reads v%d (incarnation "
                "representation changed; a cross-version resume would "
                "silently corrupt the trajectory)"
                % (saved_version, _FORMAT_VERSION)
            )
        if params is not None and _PARAMS_KEY in data.files:
            _check_params(
                json.loads(str(data[_PARAMS_KEY][0])), params, path
            )
        try:
            available = {
                f: data[f]
                for f in data.files
                if f not in (_FORMAT_KEY, _PARAMS_KEY)
            }
        except Exception as e:
            raise CheckpointTornError(
                "%s: array member unreadable (truncated archive?): %s"
                % (path, e)
            )
        return _reconcile_fields(state_cls, available, path)


# -- manifest format ---------------------------------------------------------

MANIFEST_NAME = "manifest.json"
MANIFEST_FORMAT = "ringpop-tpu-ckpt"
MANIFEST_VERSION = 1
_COMMON_FILE = "common.npz"


def _shard_file(s: int, shards: int) -> str:
    return "shard-%05d-of-%05d.npz" % (s, shards)


def _as_state_map(states: Any) -> Dict[str, Any]:
    if hasattr(states, "_fields"):
        return {"state": states}
    if isinstance(states, Mapping):
        for name, st in states.items():
            if not hasattr(st, "_fields"):
                raise TypeError(
                    "state %r must be a NamedTuple of arrays" % name
                )
        return dict(states)
    raise TypeError("states must be a NamedTuple or a dict of NamedTuples")


def _per_state(value: Any, names, what: str) -> Dict[str, Any]:
    """Broadcast a singleton (params / sharded_fields) over state names,
    or validate an explicit per-state mapping."""
    if isinstance(value, Mapping) and not hasattr(value, "_fields"):
        unknown = set(value) - set(names)
        if unknown:
            raise ValueError("%s for unknown states %r" % (what, unknown))
        return {n: value.get(n) for n in names}
    return {n: value for n in names}


def save_checkpoint(
    path: str,
    states: Any,
    params: Any = None,
    *,
    shards: int = 1,
    sharded_fields: Any = None,
    meta: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    """Write a manifest-format checkpoint directory at ``path``.

    ``states`` is one NamedTuple-of-arrays (stored under the name
    ``"state"``) or a dict of them (e.g. RoutedStorm's ``{"sim": ...,
    "route": ...}``); ``params``/``sharded_fields`` may be singletons or
    per-state dicts.  With ``shards > 1``, every field named in
    ``sharded_fields`` is split along axis 0 into per-shard files
    (``np.array_split`` — restorable onto ANY shard count since the
    loader reassembles full arrays); everything else lands in
    ``common.npz``.  Every array file is written atomically, and
    ``manifest.json`` — carrying per-file and per-array CRC32 digests —
    is written LAST: the manifest IS the commit point, so a crash at any
    earlier byte leaves no valid checkpoint at ``path`` (the recovery
    scan skips it and falls back).  Returns the manifest dict.
    """
    if shards < 1:
        raise ValueError("shards must be >= 1, got %d" % shards)
    state_map = _as_state_map(states)
    params_map = _per_state(params, state_map, "params")
    shard_map = _per_state(sharded_fields, state_map, "sharded_fields")
    os.makedirs(path, exist_ok=True)

    common: Dict[str, np.ndarray] = {}
    shard_arrays: List[Dict[str, np.ndarray]] = [{} for _ in range(shards)]
    manifest_states: Dict[str, Any] = {}
    for name, state in state_map.items():
        split = frozenset(shard_map[name] or ()) if shards > 1 else frozenset()
        fields: Dict[str, Any] = {}
        for f in state._fields:
            v = getattr(state, f)
            if v is None:
                fields[f] = None  # optional field: restored as None
                continue
            arr = np.asarray(v)
            key = "%s.%s" % (name, f)
            entry = {
                "dtype": str(arr.dtype),
                "shape": list(arr.shape),
            }
            if f in split and arr.ndim >= 1:
                pieces = np.array_split(arr, shards, axis=0)
                for s, piece in enumerate(pieces):
                    shard_arrays[s][key] = piece
                entry["where"] = "shards"
                entry["crc32"] = [_array_crc(p) for p in pieces]
            else:
                common[key] = arr
                entry["where"] = "common"
                entry["crc32"] = _array_crc(arr)
            fields[f] = entry
        p = params_map[name]
        manifest_states[name] = {
            "class": type(state).__name__,
            "params": None if p is None else _params_jsonable(p),
            "fields": fields,
        }

    files: Dict[str, Any] = {}
    total = 0

    def _commit(fname: str, arrays: Dict[str, np.ndarray]) -> None:
        nonlocal total
        buf = _npz_bytes(arrays)
        atomic_write_bytes(os.path.join(path, fname), buf)
        files[fname] = {"nbytes": len(buf), "crc32": _crc(buf)}
        total += len(buf)

    _commit(_COMMON_FILE, common)
    shard_names = []
    for s in range(shards) if shards > 1 else ():
        fname = _shard_file(s, shards)
        _commit(fname, shard_arrays[s])
        shard_names.append(fname)

    manifest = {
        "format": MANIFEST_FORMAT,
        "version": MANIFEST_VERSION,
        "engine_version": _FORMAT_VERSION,
        "shards": shards,
        "common": _COMMON_FILE,
        "shard_files": shard_names,
        "files": files,
        "states": manifest_states,
        "nbytes": total,
        "meta": dict(meta or {}),
    }
    atomic_write_bytes(
        os.path.join(path, MANIFEST_NAME),
        json.dumps(manifest, sort_keys=True, indent=1).encode("utf-8"),
    )
    return manifest


def load_any(path: str, state_cls: Type[T], params: Any = None) -> T:
    """Format-dispatching single-state load: a directory is a manifest
    checkpoint, a file the legacy npz — the drivers' ``load(path)``
    entry point, so an operator can hand either artifact kind to any
    driver."""
    if os.path.isdir(path):
        return load_checkpoint(path, state_cls, params)
    return load_state(path, state_cls, params)


def read_manifest(path: str) -> Dict[str, Any]:
    """Parse + format-check ``path``'s manifest (no array I/O).  Raises
    the named taxonomy: missing -> NotFound, unparseable -> Torn,
    foreign format -> NotFound, version drift -> Version."""
    mpath = os.path.join(path, MANIFEST_NAME)
    if not os.path.isdir(path) or not os.path.exists(mpath):
        raise CheckpointNotFoundError(
            "%s holds no %s — not a (complete) checkpoint" % (path, MANIFEST_NAME)
        )
    try:
        with open(mpath, encoding="utf-8") as fh:
            manifest = json.load(fh)
    except (ValueError, OSError) as e:
        raise CheckpointTornError(
            "%s: manifest unparseable (interrupted write?): %s" % (path, e)
        )
    if not isinstance(manifest, dict) or manifest.get("format") != MANIFEST_FORMAT:
        raise CheckpointNotFoundError(
            "%s: manifest is not %r" % (path, MANIFEST_FORMAT)
        )
    if manifest.get("version") != MANIFEST_VERSION:
        raise CheckpointVersionError(
            "%s: manifest format v%r, this build reads v%d"
            % (path, manifest.get("version"), MANIFEST_VERSION)
        )
    if manifest.get("engine_version") != _FORMAT_VERSION:
        raise CheckpointVersionError(
            "%s: engine state format v%r, this build reads v%d "
            "(incarnation representation changed; a cross-version resume "
            "would silently corrupt the trajectory)"
            % (path, manifest.get("engine_version"), _FORMAT_VERSION)
        )
    shard_names = manifest.get("shard_files", [])
    shards = manifest.get("shards", 1)
    if shards > 1 and len(shard_names) != shards:
        raise CheckpointShardError(
            "%s: manifest names %d shard files for shards=%d"
            % (path, len(shard_names), shards)
        )
    return manifest


def _verify_file(path: str, fname: str, entry: Dict[str, Any], deep: bool) -> None:
    fpath = os.path.join(path, fname)
    is_shard = fname.startswith("shard-")
    if not os.path.exists(fpath):
        err = CheckpointShardError if is_shard else CheckpointTornError
        raise err("%s: missing array file %s" % (path, fname))
    size = os.path.getsize(fpath)
    if size != entry["nbytes"]:
        raise CheckpointTornError(
            "%s: %s is %d bytes, manifest says %d (truncated/partial write)"
            % (path, fname, size, entry["nbytes"])
        )
    if deep:
        with open(fpath, "rb") as fh:
            buf = fh.read()
        if _crc(buf) != entry["crc32"]:
            raise CheckpointDigestError(
                "%s: %s content digest mismatch (bit-rot or tampering: "
                "crc32 %08x != manifest %08x)"
                % (path, fname, _crc(buf), entry["crc32"])
            )


def verify_checkpoint(path: str, deep: bool = True) -> Dict[str, Any]:
    """Validate a manifest-format checkpoint without constructing states.

    ``deep=False``: manifest parse + file existence + exact sizes (the
    rotation scan's cheap validity probe).  ``deep=True``: additionally
    re-verify every file AND per-array digest (the CI validator).  Raises
    the named error; returns the manifest when valid."""
    manifest = read_manifest(path)
    names = [manifest["common"]] + list(manifest.get("shard_files", []))
    for fname in names:
        entry = manifest["files"].get(fname)
        if entry is None:
            raise CheckpointShardError(
                "%s: manifest lists no digest for %s" % (path, fname)
            )
        _verify_file(path, fname, entry, deep)
    if deep:
        _load_arrays(path, manifest)  # per-array digests + shapes
    return manifest


def _open_npz(path: str, fname: str, entry: Optional[Dict[str, Any]] = None):
    """Open an array file, verifying the manifest's whole-file digest
    first when given: ANY flipped byte on disk — array data, npy header
    padding, zip structure — is a named CheckpointDigestError before
    numpy parses a single byte."""
    fpath = os.path.join(path, fname)
    try:
        with open(fpath, "rb") as fh:
            buf = fh.read()
    except OSError as e:
        raise CheckpointTornError("%s: %s unreadable: %s" % (path, fname, e))
    if entry is not None:
        if len(buf) != entry["nbytes"]:
            raise CheckpointTornError(
                "%s: %s is %d bytes, manifest says %d (truncated/partial "
                "write)" % (path, fname, len(buf), entry["nbytes"])
            )
        if _crc(buf) != entry["crc32"]:
            raise CheckpointDigestError(
                "%s: %s content digest mismatch (bit-rot or tampering: "
                "crc32 %08x != manifest %08x)"
                % (path, fname, _crc(buf), entry["crc32"])
            )
    try:
        return np.load(io.BytesIO(buf), allow_pickle=False)
    except Exception as e:
        raise CheckpointTornError(
            "%s: %s unreadable as npz (truncated?): %s" % (path, fname, e)
        )


def _read_member(arch, path: str, key: str) -> np.ndarray:
    """Extract one npz member, folding the zip layer's own failure modes
    into the named taxonomy (zipfile raises BadZipFile mid-read on its
    per-member CRC — flipped bits — and assorted errors on truncated
    members)."""
    import zipfile

    try:
        return arch[key]
    except Exception as e:
        if isinstance(e, zipfile.BadZipFile) and "CRC" in str(e):
            raise CheckpointDigestError(
                "%s: member %r content digest mismatch (flipped bits on "
                "disk?): %s" % (path, key, e)
            )
        raise CheckpointTornError(
            "%s: member %r unreadable (truncated archive?): %s"
            % (path, key, e)
        )


def _load_arrays(
    path: str, manifest: Dict[str, Any]
) -> Dict[str, Dict[str, np.ndarray]]:
    """Read + digest-verify every stored array; reassemble sharded fields
    by concatenation along axis 0.  Returns {state: {field: array}}."""
    shards = manifest.get("shards", 1)
    files = manifest.get("files", {})
    archives = {
        _COMMON_FILE: _open_npz(
            path, manifest["common"], files.get(manifest["common"])
        )
    }
    for fname in manifest.get("shard_files", []):
        archives[fname] = _open_npz(path, fname, files.get(fname))
    try:
        out: Dict[str, Dict[str, np.ndarray]] = {}
        for sname, sdesc in manifest["states"].items():
            fields: Dict[str, np.ndarray] = {}
            for f, entry in sdesc["fields"].items():
                if entry is None:
                    continue  # stored None: optional-field default
                key = "%s.%s" % (sname, f)
                if entry["where"] == "shards":
                    pieces = []
                    for s in range(shards):
                        arch = archives[manifest["shard_files"][s]]
                        if key not in arch.files:
                            raise CheckpointShardError(
                                "%s: shard %d holds no %r" % (path, s, key)
                            )
                        piece = _read_member(arch, path, key)
                        if _array_crc(piece) != entry["crc32"][s]:
                            raise CheckpointDigestError(
                                "%s: field %r shard %d digest mismatch "
                                "(flipped bits on disk?)" % (path, key, s)
                            )
                        pieces.append(piece)
                    arr = np.concatenate(pieces, axis=0) if pieces else None
                else:
                    arch = archives[_COMMON_FILE]
                    if key not in arch.files:
                        raise CheckpointTornError(
                            "%s: common file holds no %r" % (path, key)
                        )
                    arr = _read_member(arch, path, key)
                    if _array_crc(arr) != entry["crc32"]:
                        raise CheckpointDigestError(
                            "%s: field %r digest mismatch (flipped bits "
                            "on disk?)" % (path, key)
                        )
                if list(arr.shape) != entry["shape"] or str(arr.dtype) != entry["dtype"]:
                    raise CheckpointFieldError(
                        "%s: field %r is %s%r, manifest says %s%r"
                        % (
                            path,
                            key,
                            arr.dtype,
                            arr.shape,
                            entry["dtype"],
                            tuple(entry["shape"]),
                        )
                    )
                fields[f] = arr
            out[sname] = fields
        return out
    finally:
        for arch in archives.values():
            arch.close()


def load_checkpoint(
    path: str, state_cls: Any, params: Any = None
) -> Any:
    """Rebuild state(s) from a manifest-format checkpoint directory.

    ``state_cls`` is a NamedTuple type (returns one state) or a dict
    name -> type matching the saved layout (returns a dict of states);
    ``params`` likewise.  Every file and array digest is re-verified —
    truncation raises :class:`CheckpointTornError`, flipped bits
    :class:`CheckpointDigestError`, missing shards
    :class:`CheckpointShardError`, and class/field/params drift their
    named errors — never a silent resume."""
    single = hasattr(state_cls, "_fields")
    cls_map = {"state": state_cls} if single else dict(state_cls)
    manifest = read_manifest(path)
    for fname in [manifest["common"]] + list(manifest.get("shard_files", [])):
        _verify_file(path, fname, manifest["files"][fname], deep=False)
    params_map = _per_state(params, cls_map, "params")
    for name, cls in cls_map.items():
        sdesc = manifest["states"].get(name)
        if sdesc is None:
            raise CheckpointFieldError(
                "%s: checkpoint holds states %r, requested %r"
                % (path, sorted(manifest["states"]), name)
            )
        if sdesc["class"] != cls.__name__:
            raise CheckpointFieldError(
                "%s: state %r holds %s, expected %s"
                % (path, name, sdesc["class"], cls.__name__)
            )
        extra = [f for f in sdesc["fields"] if f not in cls._fields]
        if extra:
            raise CheckpointFieldError(
                "%s: state %r carries fields %r unknown to %s (newer "
                "engine revision?)" % (path, name, extra, cls.__name__)
            )
        p = params_map[name]
        if p is not None and sdesc.get("params") is not None:
            _check_params(sdesc["params"], p, "%s[%s]" % (path, name))
    arrays = _load_arrays(path, manifest)
    out = {
        name: _reconcile_fields(cls, arrays[name], "%s[%s]" % (path, name))
        for name, cls in cls_map.items()
    }
    return out["state"] if single else out
