from ringpop_tpu.models.sim.engine import (
    SimParams,
    SimState,
    TickInputs,
    init_state,
    tick,
    compute_checksums,
)

__all__ = [
    "SimParams",
    "SimState",
    "TickInputs",
    "init_state",
    "tick",
    "compute_checksums",
]
