"""Shared cond-vs-straight-line phase dispatch for both sim engines.

Both engines gate their rare phases behind ``lax.cond`` so quiet ticks
skip the work (the CPU win), and both expose a ``gate_phases`` param to
run the same phases as straight-line code instead (the TPU/vmap win:
cond boundaries block fusion and carry a scalar-core sync cost, and
under ``jax.vmap`` a cond lowers to a run-both select anyway).  One
helper, one contract: the TRUE branch must be the general computation —
a masked no-op on empty inputs with salt-pure draws — so that running
it unconditionally is bitwise-identical to the gated program (pinned by
the gate-equivalence tests in tests/models/).
"""

from __future__ import annotations

import jax


def phase(gate: bool, pred, true_fn, false_fn, *ops):
    """``lax.cond(pred, true_fn, false_fn, *ops)`` when ``gate`` is True,
    else ``true_fn(*ops)`` unconditionally."""
    if gate:
        return jax.lax.cond(pred, true_fn, false_fn, *ops)
    return true_fn(*ops)
