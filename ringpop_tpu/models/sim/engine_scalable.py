"""Scalable SWIM engine: O(N·U) state for 100k-1M simulated nodes.

The full-fidelity engine (:mod:`ringpop_tpu.models.sim.engine`) keeps every
node's complete view — ``[N, N]`` arrays — which is exact but caps N at a few
thousand.  This engine is the large-scale mode behind the 100k epidemic-
broadcast and 1M churn-storm configs (BASELINE.md north-star table).  It
replaces per-node views with three pieces:

- **global truth** arrays ``[N]`` — each member's current status and
  incarnation as most recently asserted,
- a bounded table of **batch rumors**: one rumor per (tick, event class)
  covering the whole *set* of subjects that class touched this tick —
  suspect detections, suspicion expiries (faulty), and revive/rejoin
  (alive).  A rumor stores no member list: because the per-node checksum is
  an additive combine over member records, a rumor only needs the scalar
  **checksum delta** of its whole subject set, precomputed at publish time
  against the then-current truth, and
- per-node **heard bitmasks**, bit r of ``heard[i]`` = node i has received
  rumor r, packed 32 rumors per uint32 lane: ``[N, U/32] uint32``.

A node's checksum is ``base_sum + Σ_{heard ∩ active} r_delta[r]`` — equal
heard-sets give equal checksums, different heard-sets differ w.h.p., which is
exactly the discrimination the convergence views need (tick-cluster groups
nodes by checksum, scripts/tick-cluster.js:87-114; the convergence benchmark
declares convergence when all live checksums agree, benchmarks/convergence-
time/scenario-runner.js:152-170).  Bit-exact FarmHash string-checksum parity
is the full-fidelity engine's job at <=1k nodes.

Chained deltas compose: a suspect rumor's delta is taken against alive
truth, the follow-up faulty rumor's delta against suspect truth, so a node
that heard both holds exactly the faulty record's contribution regardless of
arrival order (the sum is commutative).  When a rumor ages out — the batched
analog of dropping a change once its piggyback count exceeds
``15·ceil(log10(n+1))`` (lib/gossip/dissemination.js:41) — its delta is
folded into ``base_sum``: by then dissemination has completed (age >>
O(log N) convergence), so every live node's checksum is unchanged by the
fold.  Slot allocation is deterministic round-robin, 3 slots per tick, so a
10% churn storm at 1M nodes costs the same table space as one lost ping.

Gossip exchange is **push-pull over random pairings**: each tick every live
node draws K partner permutations; it pushes its heard-set to partner 0 (the
direct ping's piggyback, ping-sender.js:70-76) and pulls the partner's set
back (the ack's issueAsReceiver changes, server/protocol/ping.js:46-49).  A
failed direct ping (dead or lossy partner) falls back to the K-1 indirect
partners (the ping-req fanout, k=3, ping-req-sender.js:293-296).
Permutation pairing keeps the exchange a dense gather + bitwise-OR — no
scatter conflicts, no segment reductions — the memory-bandwidth-bound shape
TPUs like.  Deviation envelope vs the reference's per-node round-robin
iterator is documented in SURVEY.md §7 (hard parts 4 and 6).

Failure detection follows the reference's evidence model, not a global
oracle: a node suspects its direct partner when the direct exchange fails
(dead process, packet loss, or partition) AND at least one indirect
ping-req intermediary responded but none reached the target
(ping-req-sender.js:249-262) — so packet loss and partitions produce
*false* suspects exactly as in the reference.  After ``suspicion_ticks``
(5s at 200ms periods, suspicion.js:111-113) a still-suspect subject joins
the faulty batch.  The counterpart is **refutation** (member.js:76-81): a
rumor subject is stamped with the slot that defamed it; when a live node
hears a suspect/faulty rumor naming itself, it publishes a refute-alive
rumor with a fresh incarnation in the same alive batch that carries
revive/rejoin (server/admin/member.js:44-51).  Revived nodes restart with
empty state (the reference rebuilds a restarted node entirely via join,
server/protocol/join.js:131).

Partition groups gate every exchange (gossip, ping-req probes), so a split
produces cross-side false suspects and checksum divergence between the
sides, and healing reconverges to a single all-alive view.  Cross-side
escalation is faithful: a subject refutes a defamation only while it can
currently reach its representative defamer (``defame_by``), so a
partitioned-away side's suspicions run their clocks and publish FAULTY
batches during the split — as the reference does ("Ringpop retains
members that are 'down'", docs/architecture_design.md; suspicion.js:67-70)
— and the defamed-but-live subjects clean themselves up with refutes
after the heal.  Deviation envelope: ``truth_*`` is a single global
chain, so the two sides' marks land in one merged truth rather than
per-observer views (both sides' views of the OTHER side go faulty, but a
third partition would see the union); exact per-observer split-brain
bookkeeping is the full-fidelity ``[N, N]`` engine's domain
(:mod:`ringpop_tpu.models.sim.engine`, parity-tested against the host
oracle including partitions in tests/parity/).
"""

from __future__ import annotations

import math
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ringpop_tpu.models.sim.gating import phase as _phase
# ops never imports models, so no cycle: the exchange megakernel module
# supplies both the fused op and the ONE shared SWAR popcount
from ringpop_tpu.ops import exchange as _exchange
from ringpop_tpu.ops.exchange import popcount_u32 as _popcount
from ringpop_tpu.ops.record_mix import record_mix

ALIVE, SUSPECT, FAULTY, LEAVE = 0, 1, 2, 3

WORD = 32
SLOTS_PER_TICK = 3  # suspect, faulty, alive (revive/refute/rejoin)

# Latency-histogram track layout (ScalableParams.histograms;
# ScalableState.hist rows, in order; observations in TICKS):
# - rumor_age: per newly-set heard bit, tick - r_birth of its rumor —
#   the dissemination wavefront's latency distribution (the histogram
#   twin of the wavefront matrix, without the [N, U] int32 state).
# - retired_age: per retired rumor slot, tick - r_birth at the aging
#   drop / recycle (the batched dissemination.js:41 analog).
# - suspicion_duration: per stopped suspicion clock, tick - susp_since
#   at refute-cancel or faulty expiry.
SCALABLE_HIST_TRACKS = ("rumor_age", "retired_age", "suspicion_duration")


def slots_per_tick(params: "ScalableParams") -> int:
    """3 rumor classes per tick, +1 (leave) when the feature is enabled —
    leave-free storms don't pay table capacity for an empty slot."""
    return SLOTS_PER_TICK + (1 if params.enable_leave else 0)


class ScalableParams(NamedTuple):
    n: int
    u: int = 512  # rumor table capacity; must cover SLOTS_PER_TICK * max_age
    ping_req_size: int = 3  # index.js:113
    suspicion_ticks: int = 25  # 5000 ms / 200 ms — suspicion.js:111-113
    piggyback_factor: int = 15  # dissemination.js:41
    age_slack: int = 8  # extra ticks beyond max piggyback before drop
    packet_loss: float = 0.0
    epoch: int = 1414142122274
    # checksums every tick cost O(N*U) bandwidth; 1M-node storms can compute
    # them on demand (compute_checksums) instead
    checksum_in_tick: bool = True
    # graceful-leave support allocates a 4th rumor slot per tick (raises
    # the minimum table capacity u by a third); off by default
    enable_leave: bool = False
    # True: rare phases (indirect exchange rounds, checksum diff/retire
    # reductions, publishes, distinct sort, coverage popcount) run under
    # lax.cond and cost nothing when there is nothing to do — the win on
    # quiet/converged ticks.  False: straight-line execution — during a
    # storm every phase fires anyway and TPU conds carry a scalar-core
    # sync cost per boundary.  Bitwise-identical trajectories either way
    # (each gated branch is a masked no-op on empty inputs).
    gate_phases: bool = True
    # Partner-permutation implementation (round 10): "sortless" evaluates
    # the per-tick base permutation as a keyed Feistel PRP over [0, N)
    # with an ANALYTIC inverse — no argsort, no inv = argsort(perm) (the
    # two dominant per-tick sorts at 1M, see the round-4 note at _perm).
    # "argsort" is the A/B + gate-equivalence twin: the SAME PRP values,
    # but the inverse materialized by argsort — bit-identical
    # trajectories (argsort of a bijection over [0, n) IS its inverse),
    # so the twin doubles as the device-level equivalence gate.  "auto"
    # resolves to "sortless" everywhere (resolve_perm_impl).
    perm_impl: str = "auto"
    # Fused exchange megakernel (round 10): "pallas" routes the direct
    # push-pull OR + new-bit diff + popcount + checksum delta-sum
    # through ops.exchange's gridless kernel (one HBM read of the heard
    # mask instead of one per phase); "xla" routes the same call through
    # the op's bit-exact pure-XLA twin; "off" keeps the classic inline
    # phases.  All three are bit-identical (exact mod-2^32 arithmetic
    # everywhere — the acceptance gate); "auto" resolves per backend
    # (resolve_fused_exchange): "pallas" on TPU, "off" elsewhere
    # (interpret-mode Pallas would be a slowdown, and the CPU's limb
    # matmul is already exact).
    fused_exchange: str = "auto"
    # Rumor wavefront tracing: when True the state carries a first-heard
    # tick matrix ``first_heard[i, r]`` — the tick node i's heard bit
    # for rumor slot r turned on (-1 = never; reset when the slot is
    # recycled).  With ``r_birth`` this yields per-rumor dissemination
    # latencies and convergence curves (obs.events.
    # scalable_wavefront_summary) without any host callback in the
    # scan.  Trajectory-neutral (nothing reads it) and opt-in: the
    # [N, U] int32 matrix and the per-tick bit expansion are real
    # memory/bandwidth at 1M nodes.
    wavefront: bool = False
    # Device-side latency histograms (ops/histogram.py; host half
    # obs/histograms.py): log2-bucketed counters for rumor age at
    # first-heard (per newly-set heard bit, vs r_birth), rumor age at
    # retirement (the batched dissemination.js:41 analog), and suspicion
    # duration at clock stop (refute-cancel or faulty expiry) — see
    # SCALABLE_HIST_TRACKS.  Write-only within the tick
    # (ScalableState.hist), trajectory-neutral and gate-equivalence-safe;
    # off by default (the per-tick [N, U] bit expansion is real
    # bandwidth at 1M nodes, same cost class as wavefront).
    histograms: bool = False
    # Per-shard exchange telemetry (round 17, the mesh observatory): 0 =
    # off; S > 0 carries ScalableState.exch/exch_hist — per-shard
    # push/pull row counts, a2a-vs-fallback trips, destination-shard
    # spread, and cap-utilization histograms for an S-shard exchange
    # plane (ops.exchange.EXCH_COUNTERS / EXCH_HIST_TRACKS; S must
    # divide n).  Under a mesh S must equal the mesh size and the
    # shard_map plane accumulates in-body; single-device runs model the
    # SAME S-shard routing analytically (the bitwise twin the drain
    # tests compare against).  Write-only, trajectory-neutral, off by
    # default — the obs-plane pattern (wavefront/histograms).
    exchange_metrics: int = 0


class ScalableState(NamedTuple):
    tick_index: jax.Array  # scalar int32
    proc_alive: jax.Array  # [N] bool — process up (fault plane)
    # gossiping flag: False after a graceful leave — the node stops
    # initiating exchanges and probes but keeps answering its partners
    # (makeLeave -> gossip.stop, on_membership_event.js:32-41)
    gossip_on: jax.Array  # [N] bool
    partition: jax.Array  # [N] int32 — group id; unequal groups can't talk
    truth_status: jax.Array  # [N] int32 — latest asserted status
    # latest asserted incarnation as an int32 tick STAMP (0 = never;
    # stamp s > 0 <=> epoch + (s-1)*200 ms) — every incarnation this
    # engine mints lies on the discrete tick grid, and TPUs emulate
    # 64-bit integer ops, so the [N] truth chain and the record_mix
    # feeding every rumor delta stay in 32-bit lanes
    truth_inc: jax.Array  # [N] int32 stamp
    # batch-rumor table
    r_active: jax.Array  # [U] bool
    r_delta: jax.Array  # [U] uint32 — checksum delta of the subject set
    r_birth: jax.Array  # [U] int32 — tick published
    # per-node reception bitmask
    heard: jax.Array  # [N, U/32] uint32
    # per-node failure-detection state (single in-flight suspicion per node)
    susp_subject: jax.Array  # [N] int32 — -1 or the suspected node
    susp_since: jax.Array  # [N] int32
    # slot of the most recent rumor defaming this node (-1 none, -2 the
    # defaming rumor's slot was recycled while still defamed): the hook a
    # live node uses to notice it has been called suspect/faulty and
    # refute (member.js:76-81)
    defame_slot: jax.Array  # [N] int32
    # representative detector/accuser behind that defamation (-1 none),
    # same-side preferred: a subject refutes only while it can currently
    # TALK to this node — split-brain correctness (same-tick defamations
    # from disconnected sides share one rumor slot, so the heard bit
    # alone cannot tell which side's accusation a subject learned of)
    defame_by: jax.Array  # [N] int32
    # commutative checksum base shared by all fully-caught-up nodes
    base_sum: jax.Array  # scalar uint32
    rng: jax.Array  # [2] uint32
    checksum: jax.Array  # [N] uint32
    # wavefront tracing (ScalableParams.wavefront only, else None):
    # first-heard tick per (node, rumor slot); -1 = never heard.
    # Write-only within the tick — trajectory-neutral by construction.
    first_heard: Optional[jax.Array] = None  # [N, U] int32
    # latency-histogram plane (ScalableParams.histograms only, else
    # None): [len(SCALABLE_HIST_TRACKS), NBUCKETS] uint32 counters,
    # write-only within the tick (drained by
    # ScalableCluster.drain_histograms)
    hist: Optional[jax.Array] = None
    # per-shard exchange telemetry plane (ScalableParams.exchange_metrics
    # = S only, else None): [S, len(EXCH_COUNTERS)] uint32 counters and
    # [S, len(EXCH_HIST_TRACKS), NBUCKETS] cap-utilization histograms
    # (ops/exchange.py layout; drained by drain_exchange_metrics on
    # ScalableCluster / ShardedStorm).  Write-only within the tick.
    exch: Optional[jax.Array] = None
    exch_hist: Optional[jax.Array] = None


# Single-source field classification (ISSUE 15): trajectory vs obs-only,
# consumed by the noninterference analysis prong exactly like
# engine.SIM_TRAJECTORY_FIELDS / SIM_OBS_ONLY_FIELDS (see the note
# there).  A new ScalableState field MUST land in exactly one set
# (tier-1 gate: tests/analysis/test_state_registry.py).
SCALABLE_OBS_ONLY_FIELDS = frozenset(
    {"first_heard", "hist", "exch", "exch_hist"}
)
SCALABLE_TRAJECTORY_FIELDS = frozenset(
    {
        "tick_index",
        "proc_alive",
        "gossip_on",
        "partition",
        "truth_status",
        "truth_inc",
        "r_active",
        "r_delta",
        "r_birth",
        "heard",
        "susp_subject",
        "susp_since",
        "defame_slot",
        "defame_by",
        "base_sum",
        "rng",
        "checksum",
    }
)


# ScalableState fields indexed by NODE along axis 0 — the single source
# for the mesh's P("nodes") shardings (parallel/mesh.py) and the sharded
# checkpoint split (models/sim/recovery.py).  Decided by NAME, not shape:
# u == n would make shape checks ambiguous.  Everything else — the
# bounded [U] rumor table, the scalar clock/base, the rng, the telemetry
# wavefront — replicates / stays in the common checkpoint file.
NODE_SHARDED_FIELDS = frozenset(
    {
        "proc_alive",
        "gossip_on",
        "partition",
        "truth_status",
        "truth_inc",
        "heard",
        "susp_subject",
        "susp_since",
        "defame_slot",
        "defame_by",
        "checksum",
    }
)


# ScalableState fields indexed by SHARD along axis 0 (the round-17
# exchange-telemetry planes): sharded P("nodes") on a mesh whose size
# equals params.exchange_metrics — each device carries its own [1, ...]
# counter slice so the shard_map plane bumps purely locally — and
# replicated otherwise (the single-device twin models S shards on one
# device; a GSPMD run with a mismatched S keeps the plane whole).
# Consumed by parallel.mesh.scalable_state_shardings; NOT in
# NODE_SHARDED_FIELDS, so checkpoints keep the tiny planes in the
# common file at any shard count.
SHARD_SHARDED_FIELDS = frozenset({"exch", "exch_hist"})


class ScalableMetrics(NamedTuple):
    live_nodes: jax.Array
    active_rumors: jax.Array
    mean_heard_frac: jax.Array  # mean fraction of active rumors heard
    full_coverage: jax.Array  # every live node heard every active rumor
    distinct_checksums: jax.Array
    suspects_published: jax.Array  # subjects newly suspected this tick
    faulties_published: jax.Array
    refutes_published: jax.Array  # live defamed nodes re-asserting alive
    leaves_published: jax.Array  # graceful leaves this tick
    # -- protocol counters (statsd-equivalent; all scalar int32, derived
    # from the trajectory masks — bitwise-identical under gate_phases) --
    pings_sent: jax.Array  # gossiping nodes initiating a direct exchange
    pings_delivered: jax.Array  # direct exchanges that succeeded
    # failed direct pings whose indirect round had NO responder: no
    # verdict this tick (ping-req-sender.js:249-262 judges only on
    # responses)
    ping_req_inconclusive: jax.Array
    # rumors retired this tick — aged past 15*ceil(log10(n+1)) (the
    # batched analog of dissemination.js:41 piggyback drops) or recycled
    rumors_retired: jax.Array


class ChurnInputs(NamedTuple):
    kill: jax.Array  # [N] bool
    # revive restarts a dead process (fresh state) OR rejoins a left node
    # (alive with fresh incarnation, gossip back on —
    # server/admin/member.js:44-51)
    revive: jax.Array  # [N] bool
    # [N] int32 group assignment, -1 keeps current; None = no change
    partition: Optional[jax.Array] = None
    # [N] bool graceful leave: publish status=leave at the current
    # incarnation and stop initiating gossip; None = no leaves
    leave: Optional[jax.Array] = None

    @staticmethod
    def quiet(n: int) -> "ChurnInputs":
        # partition=None (not a dense -1 array) keeps the pytree structure
        # identical to plain kill/revive inputs — no jit retrace
        return ChurnInputs(kill=jnp.zeros(n, bool), revive=jnp.zeros(n, bool))


def _rand_u32(key: jax.Array, shape, salt: int) -> jax.Array:
    size = math.prod(shape)
    i = jnp.arange(size, dtype=jnp.uint32)
    x = key[0] + i * jnp.uint32(0x01000193) + jnp.uint32(salt)
    x ^= key[1] >> 7
    x ^= x >> 15
    x *= jnp.uint32(0x2C1B3C6D)
    x ^= x >> 12
    x *= jnp.uint32(0x297A2D39)
    x ^= x >> 15
    return x.reshape(shape)


def _uniform(key, shape, salt):
    return _rand_u32(key, shape, salt).astype(jnp.float32) / np.float32(2**32)


def _fold(key: jax.Array, salt: int) -> jax.Array:
    k0 = key[0] * jnp.uint32(0x9E3779B9) + jnp.uint32(salt)
    k1 = key[1] ^ ((k0 << 13) | (k0 >> 19))
    return jnp.stack([k1 * jnp.uint32(0x85EBCA6B) + 1, k0 ^ k1])


def _perm(key: jax.Array, n: int, salt: int) -> jax.Array:
    """Random permutation of [0, n) via sort of per-index random keys.

    LEGACY family (pre-round-10), retained for the perm-cost
    measurement harness (scripts/prof_r4.py) and as the documented
    reference point of the deviation-envelope note below; the engine's
    tick now draws its base permutation from :func:`_prp_perm` (see
    ScalableParams.perm_impl and the round-10 note below) and nothing
    in the tick calls this."""
    r = _rand_u32(key, (n,), salt)
    return jnp.argsort(
        r.astype(jnp.uint32) ^ jnp.arange(n, dtype=jnp.uint32)
    ).astype(jnp.int32)


# NOTE (round-3 measurement): replacing the per-round argsort partner
# permutations with affine re-indexings of one shared base (analytic
# inverses, one argsort total) looked like an obvious win — index
# GENERATION is 7x cheaper — but the full exchange ran 2-3x SLOWER on
# this image's CPU at both 100k and 1M, reproducibly, with identical
# shapes/dtypes and equally-uniform index values.  Round 4 revisited
# this with ROTATIONS instead of general affine maps: partner_k[i] =
# base[(i + c_k) mod n] with static offsets c_k.  One argsort + one
# scatter-inverse per tick replaces 4 argsorts + 4 argsort-inverses
# (sorts of [N] are the dominant per-tick cost at 1M on TPU), and the
# rotation family is a fidelity IMPROVEMENT over independent draws: for
# a fixed node i the direct target and the K-1 indirect intermediaries
# are always K distinct nodes — the reference samples its ping-req
# members without replacement and excludes the ping target
# (ping-req-sender.js:293-296).  Deviation envelope: rounds within one
# tick are rotations of one permutation (cross-round correlation), and
# intermediary sets of nodes i and i+c coincide shifted — both inside
# the documented pseudo-randomness envelope (SURVEY.md §7 hard part 4);
# base is a fresh uniform permutation every tick.
#
# NOTE (round-10 measurement): even the ONE remaining argsort (+ the one
# inverse sort in "argsort" twin mode) is gone by default.  The base
# permutation is now a keyed 4-round Feistel PRP over the index bits
# with cycle-walking for non-power-of-two N (_prp_perm): O(N) elementwise
# uint32 mixing with an ANALYTIC inverse (run the rounds backwards, walk
# the cycle with the inverse map), replacing the O(N log N) sorts that
# the round-4 note identified as the dominant per-tick cost at 1M.  The
# rotation family above is UNCHANGED — it sits on top of whichever base
# the tick draws, so the K-distinct-partners fidelity property is
# preserved.  Deviation envelope vs the argsort-of-random-keys family:
# a 4-round Feistel with per-tick random round keys is a keyed bijection
# family, not a uniform draw over all n! permutations — its per-position
# marginals are statistically uniform (chi-square-pinned in
# tests/models/test_scalable_perm.py) and the key is folded fresh every
# tick, but permutations within the family carry the Feistel's algebraic
# structure.  This sits inside the same SURVEY.md §7 hard-part-4
# pseudo-randomness envelope as the rotation reuse above: the protocol
# consumes the permutation only as "K distinct pseudo-random partners
# per node per tick".  The argsort twin (perm_impl="argsort") keeps the
# SAME PRP values and materializes only the inverse by argsort — since
# the cycle-walked PRP is a bijection over [0, n), argsort of its value
# vector IS its inverse, so the two modes are bit-identical end to end
# (the gate-equivalence tests compare whole trajectories) and the twin
# doubles as the on-chip A/B baseline.

_PRP_ROUNDS = 4


def _prp_f(r: jax.Array, k: jax.Array, mask: jax.Array) -> jax.Array:
    """Feistel round function: uint32 mixing (lowbias32-style constants —
    deliberately NOT the FarmHash mixing constants, which seed the jaxpr
    auditor's hash-dataflow taint) truncated to the half-width."""
    x = r * jnp.uint32(0x7FEB352D) + k
    x ^= x >> 15
    x *= jnp.uint32(0x846CA68B)
    x ^= x >> 13
    return x & mask


def _prp_half_bits(n: int) -> int:
    """Half-width of the Feistel domain: smallest hb with 4^hb >= n."""
    return max(1, (max(n - 1, 1).bit_length() + 1) // 2)


def _prp_apply(
    v: jax.Array, keys: jax.Array, hb: int, inverse: bool = False
) -> jax.Array:
    """One full PRP pass over the 2*hb-bit domain (a bijection on
    [0, 4^hb)); ``inverse`` runs the rounds backwards."""
    mask = jnp.uint32((1 << hb) - 1)
    left = v >> hb
    right = v & mask
    if not inverse:
        for r in range(_PRP_ROUNDS):
            left, right = right, left ^ _prp_f(right, keys[r], mask)
    else:
        for r in reversed(range(_PRP_ROUNDS)):
            left, right = right ^ _prp_f(left, keys[r], mask), left
    return (left << hb) | right


def _prp_perm(
    key: jax.Array, n: int, salt: int, inverse: bool = False
) -> jax.Array:
    """[N] int32 keyed bijection over [0, n): 4-round Feistel on the
    index bits, cycle-walked back into range for ragged n (the domain is
    the next power of four, < 4n, so the expected walk is O(1) steps and
    the while_loop's worst case is the longest out-of-range run of the
    keyed cycle — O(log n) w.h.p.).  ``inverse=True`` evaluates the
    analytic inverse: the backwards rounds walked with the inverse map
    (cycle-walking inverts cycle-walking).  No argsort anywhere."""
    hb = _prp_half_bits(n)
    keys = _rand_u32(key, (_PRP_ROUNDS,), salt)
    nn = jnp.uint32(n)
    x = _prp_apply(jnp.arange(n, dtype=jnp.uint32), keys, hb, inverse)
    x = jax.lax.while_loop(
        lambda v: jnp.any(v >= nn),
        lambda v: jnp.where(v >= nn, _prp_apply(v, keys, hb, inverse), v),
        x,
    )
    return x.astype(jnp.int32)


def resolve_perm_impl(params: "ScalableParams", backend: str) -> str:
    """Resolve ``perm_impl="auto"`` to a concrete "sortless"/"argsort".
    Sortless everywhere: the PRP is O(N) elementwise on every backend
    and the values are identical either way — the argsort twin exists
    for A/B measurement and the gate-equivalence proof, not as a
    production choice."""
    if params.perm_impl != "auto":
        if params.perm_impl not in ("sortless", "argsort"):
            raise ValueError(
                "perm_impl must be auto|sortless|argsort, got %r"
                % (params.perm_impl,)
            )
        return params.perm_impl
    return "sortless"


def resolve_fused_exchange(params: "ScalableParams", backend: str) -> str:
    """Resolve ``fused_exchange="auto"`` per backend: "pallas" on TPU
    (the megakernel's one-HBM-pass win), "off" elsewhere — the CPU's
    inline phases + MXU-limb delta matmul are already exact and
    interpret-mode Pallas would be a slowdown.  "xla" (the op twin) is
    never auto-picked: it exists for A/B and the equivalence gates.
    Table mechanics: the shared toolkit resolver (ops.toolkit)."""
    from ringpop_tpu.ops import toolkit

    return toolkit.resolve_impl(
        "fused_exchange",
        params.fused_exchange,
        backend,
        auto={"tpu": "pallas", "*": "off"},
        allowed=("pallas", "xla", "off"),
    )


def resolve_sharded_exchange(
    params: "ScalableParams", backend: str, shards: int
) -> tuple:
    """Resolve ``fused_exchange`` for a MESH-sharded engine (round 14):
    ``(mode, kernel_impl)`` where ``mode`` is "shard_map" (the explicit
    collective exchange plane — parallel.mesh.make_exchange_plane —
    with ``kernel_impl`` running per shard) or "gspmd" (the classic
    whole-program partitioning with ``kernel_impl`` as the engine's
    fused_exchange value).  The FULL resolution table, pinned by
    tests/parallel/test_shard_exchange.py::test_resolution_table:

    ==============  =======  ==========================================
    fused_exchange  backend  resolves to
    ==============  =======  ==========================================
    auto            tpu      ("shard_map", "pallas") — the megakernel,
                             shard-local, one VMEM pass per shard
    auto            other    ("shard_map", "xla") — same plane, the
                             bit-exact twin per shard (interpret-mode
                             Pallas would be a slowdown off-TPU)
    pallas          any      ("shard_map", "pallas") — an explicit
                             pallas is honored; under the plane it
                             runs shard-local, so it partitions now
                             (pre-round-14 it meant a replicated kernel)
    xla             any      ("gspmd", "xla") — the partitionable XLA
                             twin under whole-program GSPMD: the
                             fallback GATE the plane is bitwise-
                             compared against
    off             any      ("gspmd", "off") — classic inline phases
                             under GSPMD
    ==============  =======  ==========================================

    Contrast with the single-device :func:`resolve_fused_exchange`
    ("pallas" on TPU, "off" elsewhere): "auto" under a mesh now picks
    the shard_map plane instead of the PR-5 silent drop-to-XLA.  The
    driver surfaces any auto divergence from the single-device pick as
    a runlog note + statsd field (ShardedStorm.attach_recorder).
    """
    if shards < 1:
        raise ValueError("shards must be >= 1, got %d" % (shards,))
    from ringpop_tpu.ops import toolkit

    fe = params.fused_exchange
    # same toolkit table mechanics as the single-device resolver — only
    # the auto row differs ("xla" off-TPU: under the plane the twin is
    # the partitionable form, not a slowdown)
    resolved = toolkit.resolve_impl(
        "fused_exchange",
        fe,
        backend,
        auto={"tpu": "pallas", "*": "xla"},
        allowed=("pallas", "xla", "off"),
    )
    if fe in ("auto", "pallas"):
        return ("shard_map", resolved)
    return ("gspmd", resolved)


def resolve_scalable_params(
    params: "ScalableParams", backend: str
) -> "ScalableParams":
    """Driver-level pin of the trace-time "auto" knobs (ScalableCluster /
    ShardedStorm construction), the engine_scalable analog of
    engine.resolve_auto_parity: the shared executable caches key on
    params, so drivers pin concrete values up front.  Direct engine
    users may keep "auto" — tick() resolves at trace time."""
    return params._replace(
        perm_impl=resolve_perm_impl(params, backend),
        fused_exchange=resolve_fused_exchange(params, backend),
    )


def _base_perm_pair(
    key: jax.Array, n: int, impl: str, salt: int
) -> tuple[jax.Array, jax.Array]:
    """The tick's base permutation and its inverse.  "sortless": both
    analytic (zero sorts).  "argsort": same forward values, inverse via
    argsort — bit-identical (argsort of a bijection over [0, n) is its
    inverse; on-chip the round-4 note's measured ~0.03 ms at 1M)."""
    fwd = _prp_perm(key, n, salt)
    if impl == "argsort":
        inv = jnp.argsort(fwd).astype(jnp.int32)
    else:
        inv = _prp_perm(key, n, salt, inverse=True)
    return fwd, inv


def _pack_mask(bits: jax.Array) -> jax.Array:
    """[U] bool -> [U/32] uint32, bit r of word r//32 = bits[r]."""
    u = bits.shape[0]
    w = bits.reshape(u // WORD, WORD)
    weights = (jnp.uint32(1) << jnp.arange(WORD, dtype=jnp.uint32))[None, :]
    return jnp.sum(jnp.where(w, weights, 0), axis=1, dtype=jnp.uint32)


def max_rumor_age(params: ScalableParams) -> int:
    """Worst-case rumor lifetime in ticks (at full live count)."""
    digits = len(str(params.n))
    return params.piggyback_factor * digits + params.age_slack


def init_state(params: ScalableParams, seed: int = 0) -> ScalableState:
    n, u = params.n, params.u
    assert u % WORD == 0, "rumor capacity must be a multiple of 32"
    need = slots_per_tick(params) * (max_rumor_age(params) + 2)
    if u < need:
        raise ValueError(
            "rumor table u=%d can recycle a slot before its rumor ages out "
            "(need u >= %d for n=%d): an undisseminated delta would fold "
            "into base_sum and erase real divergence" % (u, need, n)
        )
    rng = np.random.default_rng(seed)
    inc0 = jnp.ones(n, jnp.int32)  # stamp 1 == params.epoch
    subj = jnp.arange(n, dtype=jnp.int32)
    base = record_mix(subj, jnp.zeros(n, jnp.int32), inc0)
    first_heard = (
        jnp.full((n, u), -1, jnp.int32) if params.wavefront else None
    )
    hist = None
    if params.histograms:
        from ringpop_tpu.ops import histogram as hg

        hist = hg.init(len(SCALABLE_HIST_TRACKS))
    exch = exch_hist = None
    if params.exchange_metrics:
        s = int(params.exchange_metrics)
        if s < 1 or n % s:
            raise ValueError(
                "exchange_metrics=%d must be a positive divisor of n=%d "
                "(it models an S-shard exchange plane)" % (s, n)
            )
        exch = _exchange.init_exchange_counters(s)
        exch_hist = _exchange.init_exchange_hist(s)
    return ScalableState(
        first_heard=first_heard,
        hist=hist,
        exch=exch,
        exch_hist=exch_hist,
        tick_index=jnp.int32(0),
        proc_alive=jnp.ones(n, bool),
        gossip_on=jnp.ones(n, bool),
        partition=jnp.zeros(n, jnp.int32),
        truth_status=jnp.zeros(n, jnp.int32),
        truth_inc=inc0,
        r_active=jnp.zeros(u, bool),
        r_delta=jnp.zeros(u, jnp.uint32),
        r_birth=jnp.zeros(u, jnp.int32),
        heard=jnp.zeros((n, u // WORD), jnp.uint32),
        susp_subject=jnp.full(n, -1, jnp.int32),
        susp_since=jnp.full(n, -1, jnp.int32),
        defame_slot=jnp.full(n, -1, jnp.int32),
        defame_by=jnp.full(n, -1, jnp.int32),
        base_sum=jnp.sum(base, dtype=jnp.uint32),
        rng=jnp.asarray(rng.integers(1, 2**32 - 1, size=2, dtype=np.uint32)),
        # seeded to the no-rumors value: the in-tick checksum path
        # maintains this field INCREMENTALLY (publish adds, exchange-diff
        # adds, retirement adjustments) instead of recomputing O(N*U)
        # every tick, so it must start exact
        checksum=jnp.full(n, jnp.sum(base, dtype=jnp.uint32), jnp.uint32),
    )


def _publish_batch(
    state: ScalableState,
    csum: jax.Array,  # [N] uint32 — incrementally maintained checksums
    slot: jax.Array,  # scalar int32 — pre-cleared slot for this tick
    subj_mask: jax.Array,  # [N] bool — members this event touches
    new_status: jax.Array,  # [N] int32 (per subject)
    new_inc: jax.Array,  # [N] int32 stamp (per subject)
    hearer_mask: jax.Array,  # [N] bool — nodes that know at publish time
    tick: jax.Array,
) -> tuple[ScalableState, jax.Array]:
    """One batch rumor: scalar delta vs current truth, truth advance, and
    initial heard bits for the publishing nodes.  The hearers' checksums
    gain the rumor's delta in the same step (the slot was cleared during
    this tick's recycling, so no hearer can already hold its bit)."""
    n = state.proc_alive.shape[0]
    ids = jnp.arange(n, dtype=jnp.int32)
    prev_h = record_mix(ids, state.truth_status, state.truth_inc)
    new_h = record_mix(ids, new_status, new_inc)
    delta = jnp.sum(
        jnp.where(subj_mask, new_h - prev_h, 0), dtype=jnp.uint32
    )
    any_ev = jnp.any(subj_mask)
    hears = hearer_mask & any_ev
    # wavefront: publishers are the rumor's first hearers — stamp their
    # first-heard tick at publish time (the slot was recycled this tick,
    # so the column is already reset).  No-op when the batch is empty,
    # so cond-skipped and straight-line publishes stay bit-identical.
    fh = state.first_heard
    if fh is not None:
        fh = fh.at[:, slot].set(jnp.where(hears, tick, fh[:, slot]))
    # empty batch: leave the (inactive) slot's delta/birth untouched so a
    # straight-line publish is bit-identical to a cond-skipped one — the
    # fields are dead while r_active is False, but the gate-equivalence
    # tests compare raw state
    return state._replace(
        first_heard=fh,
        r_active=state.r_active.at[slot].set(any_ev),
        r_delta=state.r_delta.at[slot].set(
            jnp.where(any_ev, delta, state.r_delta[slot])
        ),
        r_birth=state.r_birth.at[slot].set(
            jnp.where(any_ev, tick, state.r_birth[slot])
        ),
        truth_status=jnp.where(subj_mask, new_status, state.truth_status),
        truth_inc=jnp.where(subj_mask, new_inc, state.truth_inc),
        heard=jnp.where(
            hears[:, None],
            state.heard.at[:, slot // WORD].set(
                state.heard[:, slot // WORD]
                | (jnp.uint32(1) << (slot % WORD).astype(jnp.uint32))
            ),
            state.heard,
        ),
    ), jnp.where(hears, csum + delta, csum)



def _representative_accuser(
    accuser: jax.Array,  # [N] bool — nodes defaming someone this tick
    subj_idx: jax.Array,  # [N] int32 — accuser i's subject (n = none)
    partition: jax.Array,  # [N] int32
    n: int,
) -> jax.Array:
    """[N] int32: per SUBJECT, one representative accuser id, same-side
    accusers preferred — keys in [0, n) are same-side accuser ids,
    [n, 2n) cross-side, so a scatter-min picks a same-side id whenever
    one exists.  The refute phase requires the subject to currently
    REACH this node (defame_by gate): a partitioned-away subject cannot
    legitimately learn it was defamed across the cut, even though
    same-tick defamations from both sides share one rumor slot (the
    slot carries no member list).  Entries for non-subjects decode from
    the untouched 2n sentinel and must be masked by the caller."""
    ids = jnp.arange(n, dtype=jnp.int32)
    same = accuser & (
        partition == partition[jnp.clip(subj_idx, 0, n - 1)]
    )
    key = jnp.where(same, ids, ids + n)
    rep_key = (
        jnp.full(n, 2 * n, jnp.int32).at[subj_idx].min(key, mode="drop")
    )
    rep_id = rep_key - jnp.where(rep_key >= n, n, 0)  # key in [0, 2n]
    return rep_id - jnp.where(rep_id >= n, n, 0)


def _publish_batch_gated(
    state: ScalableState,
    csum: jax.Array,
    slot: jax.Array,
    subj_mask: jax.Array,
    new_status: jax.Array,
    new_inc: jax.Array,
    hearer_mask: jax.Array,
    tick: jax.Array,
    gate: bool = True,
) -> tuple[ScalableState, jax.Array]:
    """Skip the whole publish when the subject set is empty (the common
    case for every batch on a healthy converged tick): with no subjects
    the publish writes r_active[slot]=False to an already-False slot,
    delta 0, no truth advance, and no heard bits — a pure no-op, but the
    two [N] record_mix chains it computes are measurably hot at 1M."""
    return _phase(
        gate,
        jnp.any(subj_mask),
        lambda st, c: _publish_batch(
            st, c, slot, subj_mask, new_status, new_inc, hearer_mask, tick
        ),
        lambda st, c: (st, c),
        state,
        csum,
    )


def _bit_delta_sum(
    words: jax.Array,  # [N, U/32] uint32 — bit r set => include r_delta[r]
    r_delta: jax.Array,  # [U] uint32
    u: int,
    _chunk_rows: int = 65536,
) -> jax.Array:
    """[N] uint32: per-row Σ of r_delta over the row's set bits, mod 2^32.

    The per-row sum is computed as a matmul on 8-bit limbs of the deltas:
    ``bits[C, U] @ limbs[U, 4]`` with bits in {0, 1} and limbs <= 255 keeps
    every dot product an exact integer (< 2^24 at U <= 65536) in float32,
    and recombining the four limb sums with wrapping uint32 shifts
    reproduces the mod-2^32 sum bit-for-bit.  This puts the O(N*U)
    reduction — the 1M-node storm's hottest op — on the MXU instead of a
    [C, W, 32] elementwise expansion.  Shared by the full recompute
    (compute_checksums) and the in-tick incremental paths (exchange-diff
    add, retirement adjustment), which feed it different bit masks."""
    # static capacity bound (params.u), checked at trace time
    assert u <= 65536, "limb dot exactness needs U*255 < 2^24"  # jaxgate: ignore[assert-on-traced]
    limbs = jnp.stack(
        [(r_delta >> (8 * k)) & jnp.uint32(0xFF) for k in range(4)],
        axis=1,
    ).astype(jnp.float32)  # [U, 4]
    bit_ids = jnp.arange(WORD, dtype=jnp.uint32)[None, None, :]

    def per_chunk(h):  # [C, W] uint32 -> [C] uint32
        c = h.shape[0]
        bits = ((h[:, :, None] >> bit_ids) & jnp.uint32(1)).astype(
            jnp.float32
        ).reshape(c, u)  # bit b of word w = rumor w*32+b (== _pack_mask)
        acc = (bits @ limbs).astype(jnp.uint32)  # [C, 4] exact limb sums
        return (
            acc[:, 0]
            + (acc[:, 1] << 8)
            + (acc[:, 2] << 16)
            + (acc[:, 3] << 24)  # uint32 shifts wrap: natural mod 2^32
        )

    n = words.shape[0]
    chunk = max(1, min(n, _chunk_rows))
    pad = (-n) % chunk
    rows = words
    if pad:
        rows = jnp.pad(rows, ((0, pad), (0, 0)))
    return jax.lax.map(
        per_chunk, rows.reshape(-1, chunk, rows.shape[1])
    ).reshape(-1)[:n]


def compute_checksums(
    state: ScalableState,
    params: ScalableParams,
    _chunk_rows: int = 65536,
) -> jax.Array:
    """checksum(i) = base_sum + Σ over active rumors i heard of r_delta.

    Full O(N*U) recompute from the current heard bitmask — the deferred-
    checksum entry point and the oracle the in-tick incremental updates
    are parity-tested against (tests/models/test_engine_scalable.py)."""
    active_words = _pack_mask(state.r_active)
    # no delta masking needed: inactive rumors' bits are zeroed by the
    # active_words AND, so their limbs never enter the dot product
    return state.base_sum + _bit_delta_sum(
        state.heard & active_words[None, :],
        state.r_delta,
        params.u,
        _chunk_rows,
    )


def farmhash_truth_checksum(
    state: ScalableState,
    universe,
    params: ScalableParams,
    max_digits: int = 14,
    impl: "str | None" = None,
) -> jax.Array:
    """Bit-exact reference FarmHash32 membership checksum of the TRUTH
    view — the parity tick of the scalable engine.

    The rumor model keeps no per-(observer, subject) matrix, so a
    per-observer string checksum does not exist at O(N*U); what IS
    defined bit-exactly is the checksum a fully-caught-up observer's
    reference ``Membership.computeChecksum()`` would report: every
    subject at its latest asserted ``(status, incarnation)`` — the
    ``truth_status`` / ``truth_inc`` chain.  Computed with the fused
    record encode + streaming hash (ops.fused_checksum), which at
    N = 100k-1M is the only formulation that doesn't materialize a
    multi-GB string buffer: the encode is O(N*R) elementwise and the
    stream walks record words straight from HBM through VMEM.

    Returns a scalar uint32.  Used by the parity spot-checks and the
    roofline capture (scripts/prof_parity_roofline.py); the engine's
    in-tick checksums remain the commutative record-mix sums (equal
    views <=> equal sums), exactly as documented in the module
    docstring.  The universe must hold the same addresses the cluster
    was built over (sorted order = checksum string order)."""
    from ringpop_tpu.ops import fused_checksum as fc

    n = params.n
    # stamp -> the reference's epoch-ms incarnation (period fixed at
    # 200 ms in this engine's clock — see ScalableState.truth_inc)
    inc_ms = jnp.where(
        state.truth_inc > 0,
        jnp.int64(params.epoch)
        + (state.truth_inc.astype(jnp.int64) - 1) * 200,
        jnp.int64(0),
    )
    rec_b, rec_l = fc.member_records(
        universe,
        jnp.ones((1, n), bool),
        state.truth_status[None, :],
        inc_ms[None, :],
        max_digits,
    )
    return fc.fused_hash_rows(
        fc.pack_record_words(rec_b), rec_l, impl=impl
    )[0]


def _exchange_obs_update(
    exch: jax.Array,  # [S, len(EXCH_COUNTERS)] uint32
    exch_hist: jax.Array,  # [S, len(EXCH_HIST_TRACKS), NBUCKETS] uint32
    direct_ok: jax.Array,  # [N] bool
    partner0: jax.Array,  # [N] int32 — push destination (fwd PRP)
    inv_base: jax.Array,  # [N] int32 — pull destination (inverse PRP)
    n: int,
) -> tuple[jax.Array, jax.Array]:
    """The single-device twin of the mesh plane's in-body telemetry
    bumps: model the S-shard routing of THIS tick's permutation
    analytically and accumulate the same per-shard counters bitwise
    (ops.exchange.EXCH_COUNTERS order; the drain tests compare the two
    planes row-for-row).  Every quantity is mask-independent except the
    delivered-row counts, which use exactly the direct_ok mask that
    drives the trajectory — the flight-recorder discipline.  The a2a-
    vs-fallback split prices the DEFAULT cap (exchange_cap), which is
    what the plane uses unless a test forces an override."""
    from ringpop_tpu.ops import histogram as hg

    s = exch.shape[0]
    local = n // s
    shard_ids = jnp.arange(s, dtype=jnp.int32)

    def _bucket_counts(dest):
        # [S, S] all_to_all bucket occupancy: rows of source shard src
        # addressed to destination shard dest//local (routing is
        # mask-independent — the plane routes every row, masking only
        # zeroes payloads)
        ds = (dest // jnp.int32(local)).reshape(s, local)
        return jnp.sum(
            (ds[:, :, None] == shard_ids[None, None, :]).astype(jnp.int32),
            axis=1,
        )

    cnt_pull = _bucket_counts(inv_base)  # pull: row p -> inv[p]
    cnt_push = _bucket_counts(partner0)  # push: row j -> partner0[j]
    cap = jnp.int32(_exchange.exchange_cap(local, s))
    # pmax-agreed in the plane: one global verdict per direction
    ovf_pull = jnp.any(cnt_pull > cap)
    ovf_push = jnp.any(cnt_push > cap)
    # receiver-side delivered rows: pulls accepted under the receiver's
    # own direct_ok; pushes delivered to row fwd[j] under sender j's ok
    # (ok[inv_base[r]] is row r's sender)
    # every sum pins dtype=uint32: under x64 jnp.sum would widen to
    # uint64 and break the scan carry (exch is a uint32 plane)
    pull_rows = jnp.sum(
        direct_ok.reshape(s, local).astype(jnp.uint32),
        axis=1,
        dtype=jnp.uint32,
    )
    push_rows = jnp.sum(
        direct_ok[inv_base].reshape(s, local).astype(jnp.uint32),
        axis=1,
        dtype=jnp.uint32,
    )
    one = jnp.ones((s,), jnp.uint32)
    bump = jnp.stack(
        [
            one,  # ticks
            one * (~ovf_pull).astype(jnp.uint32),  # a2a_pull
            one * (~ovf_push).astype(jnp.uint32),  # a2a_push
            one * ovf_pull.astype(jnp.uint32),  # fallback_pull
            one * ovf_push.astype(jnp.uint32),  # fallback_push
            pull_rows,
            push_rows,
            jnp.sum(
                (cnt_pull > 0).astype(jnp.uint32),
                axis=1,
                dtype=jnp.uint32,
            ),
            jnp.sum(
                (cnt_push > 0).astype(jnp.uint32),
                axis=1,
                dtype=jnp.uint32,
            ),
        ],
        axis=1,
    )
    track_pull = _exchange.EXCH_HIST_TRACKS.index("cap_util_pull")
    track_push = _exchange.EXCH_HIST_TRACKS.index("cap_util_push")
    all_on = jnp.ones((s,), bool)

    def _bump_hist(h, cp, cq):
        h = hg.record(h, track_pull, cp, all_on)
        return hg.record(h, track_push, cq, all_on)

    exch_hist = jax.vmap(_bump_hist)(exch_hist, cnt_pull, cnt_push)
    return exch + bump, exch_hist


def tick(
    state: ScalableState,
    inputs: ChurnInputs,
    params: ScalableParams,
    exchange_plane=None,
) -> tuple[ScalableState, ScalableMetrics]:
    """One protocol period.  ``exchange_plane`` is the round-14 seam for
    the direct push-pull round: when given, it is called as
    ``plane(heard, r_delta, active_words, direct_ok, partner0,
    inv_base) -> (new_heard, d_direct)`` and OWNS the partner-row
    delivery + fused exchange (the mesh driver passes the shard_map'd
    collective plane, which gathers cross-shard partner rows explicitly
    and runs the megakernel on pre-gathered, purely shard-local data).
    ``None`` keeps the inline path: whole-array gathers + the
    ``fused_exchange``-resolved op, exactly as before.  Both paths are
    bit-identical — the plane's contract is exact mod-2^32 delivery of
    the same pulled/pushed row sets (tests/parallel/
    test_shard_exchange.py pins whole trajectories)."""
    n, u = params.n, params.u
    gate = params.gate_phases  # static: cond-gated vs straight-line phases
    t = state.tick_index + 1
    now = t + 1  # int32 stamp == epoch + t*200 ms
    rng = state.rng
    ids = jnp.arange(n, dtype=jnp.int32)
    # latency-histogram plane (SCALABLE_HIST_TRACKS): recorded inline at
    # the sites below into this local, attached once at the end.  Every
    # bump is straight-line (never inside a _phase cond) from masks that
    # are identical across gate_phases settings — trajectory-neutral and
    # gate-equivalence-safe by construction.
    hist = state.hist if params.histograms else None
    if hist is not None:
        from ringpop_tpu.ops import histogram as hg

    # per-shard exchange telemetry plane (round 17): accumulated at the
    # gossip-exchange site below — in the shard_map plane's body under a
    # mesh, by the analytic S-shard twin inline — and attached at the
    # end.  Same straight-line, write-only discipline as hist.
    exch = state.exch if params.exchange_metrics else None
    exch_hist = state.exch_hist if params.exchange_metrics else None

    # ---- fault plane ---------------------------------------------------
    revived = inputs.revive & ~state.proc_alive
    # a live-but-left node revived == admin rejoin (alive, fresh inc,
    # gossip restarted — server/admin/member.js:44-51)
    rejoined = inputs.revive & state.proc_alive & ~state.gossip_on
    proc_alive = (state.proc_alive & ~inputs.kill) | inputs.revive
    gossip_on = (state.gossip_on | revived | rejoined) & proc_alive
    if inputs.partition is None:
        partition = state.partition
    else:
        partition = jnp.where(
            inputs.partition >= 0, inputs.partition, state.partition
        )
    # a restarted process loses all pre-crash state (the reference rebuilds
    # entirely via join full-sync, server/protocol/join.js:131)
    state = state._replace(
        proc_alive=proc_alive,
        gossip_on=gossip_on,
        partition=partition,
        tick_index=t,
        heard=jnp.where(revived[:, None], 0, state.heard),
        # a restarted process heard nothing yet (wavefront plane)
        first_heard=(
            None
            if state.first_heard is None
            else jnp.where(revived[:, None], -1, state.first_heard)
        ),
        susp_subject=jnp.where(revived, -1, state.susp_subject),
        susp_since=jnp.where(revived, -1, state.susp_since),
        defame_slot=jnp.where(revived, -1, state.defame_slot),
        defame_by=jnp.where(revived, -1, state.defame_by),
    )
    # incremental checksum: a revived node's heard set is empty, so its
    # checksum is exactly the current shared base (pre-fold; this tick's
    # retirement adjustment below treats its all-zero bits like any other
    # row's)
    csum = jnp.where(revived, state.base_sum, state.checksum)

    # ---- rumor aging + slot recycling ----------------------------------
    # aging: the batched analog of the per-change piggyback drop rule
    live_count = jnp.sum(proc_alive.astype(jnp.int32))
    # powers-of-ten as a host-built table: ``10 ** jnp.arange(10)`` lowers
    # to square-and-multiply whose masked x^16/x^32 lanes wrap int64
    pow10 = jnp.asarray([10 ** k for k in range(10)], jnp.int64)
    digits = jnp.sum(
        live_count.astype(jnp.int64) >= pow10,
        dtype=jnp.int32,
    )
    max_age = params.piggyback_factor * digits + params.age_slack
    aged = state.r_active & (t - state.r_birth > max_age)
    # this tick's three deterministic slots are recycled regardless of age
    spt = slots_per_tick(params)
    slots = (
        (spt * (t - 1) + jnp.arange(spt, dtype=jnp.int32)) % u
    ).astype(jnp.int32)
    recycled = jnp.zeros(u, bool).at[slots].set(True)
    retired = aged | (state.r_active & recycled)
    if hist is not None:
        # rumor age at retirement (r_birth still pre-publish here)
        hist = hg.record(
            hist,
            SCALABLE_HIST_TRACKS.index("retired_age"),
            t - state.r_birth,
            retired,
        )
    # a defame_slot pointer whose slot is recycled this tick would, after
    # the slot's reuse, read an unrelated rumor's heard bit — demote it
    # to the -2 "aged into base while still defamed" sentinel.  The
    # subject stays refute-eligible (aware) but still gated on currently
    # reaching its defamer: a cross-partition victim of an ultra-long
    # split must keep the pointer so it can clean itself up after heal.
    ds0 = state.defame_slot
    state = state._replace(
        defame_slot=jnp.where(
            (ds0 >= 0) & recycled[jnp.clip(ds0, 0, u - 1)], -2, ds0
        )
    )
    # fold retired deltas into the shared base (dissemination has long
    # completed by retirement age; every live node already counts them)
    retired_delta_total = jnp.sum(
        jnp.where(retired, state.r_delta, 0), dtype=jnp.uint32
    )
    base_sum = state.base_sum + retired_delta_total
    # incremental checksum, retirement adjustment: a node that HAD heard a
    # retiring rumor is unchanged by the fold (its bit contribution moves
    # into base), but a node that never heard it — a recently revived
    # process — gains that delta with the base.  Almost every tick no node
    # is missing any retiring rumor (that is the fold invariant), so the
    # O(N*U) masked reduction is cond-gated on the cheap bitwise check.
    retired_words = _pack_mask(retired)
    missing = retired_words[None, :] & ~state.heard

    def _retire_adjust(c):
        return c + _bit_delta_sum(
            missing,
            jnp.where(retired, state.r_delta, jnp.uint32(0)),
            u,
        )

    csum = _phase(
        gate, jnp.any(missing != 0), _retire_adjust, lambda c: c, csum
    )
    # recycled slots' stale heard bits must vanish before reuse; the
    # wavefront column resets with them (drain the snapshot BEFORE a
    # rumor's slot recycles — max_rumor_age ticks after birth — or its
    # first-heard history is gone with the bits)
    clear_words = _pack_mask(recycled)
    state = state._replace(
        r_active=state.r_active & ~retired,
        base_sum=base_sum,
        heard=state.heard & ~clear_words[None, :],
        first_heard=(
            None
            if state.first_heard is None
            else jnp.where(recycled[None, :], -1, state.first_heard)
        ),
    )

    # ---- gossip exchange: push-pull over K random pairings -------------
    # The K per-round pairings are ROTATIONS of one fresh base
    # permutation: partner_k[i] = base[(i + c_k) mod n] — for a fixed
    # node the direct target and the K-1 intermediaries are always
    # distinct, matching the reference's sample-without-replacement
    # ping-req member pick (ping-req-sender.js:293-296).  Since round 10
    # the base itself is SORTLESS by default: a keyed Feistel PRP with
    # an analytic inverse replaces the per-tick argsort + argsort-
    # inverse (the dominant per-tick cost at 1M).  See the deviation-
    # envelope notes at _perm; perm_impl="argsort" keeps the same values
    # with an argsort-materialized inverse as the A/B twin.
    k_total = 1 + params.ping_req_size
    perm_impl = resolve_perm_impl(params, jax.default_backend())
    base_perm, inv_base = _base_perm_pair(rng, n, perm_impl, salt=0xA11CE)
    offs = [(k * (n // k_total)) % n for k in range(k_total)]  # static

    # mod-n via range-correcting selects, not `%`: TPU vector units have
    # no integer divide (an [N] `%` at 1M costs milliseconds; a select is
    # free) — exact because both operands lie in (-n, 2n)
    def _partner(k):
        if offs[k] == 0:
            return base_perm
        v = ids + jnp.int32(offs[k])  # [0, 2n)
        return base_perm[v - jnp.where(v >= n, n, 0)]

    def _inv(k):  # inv_k[v] = (inv_base[v] - c_k) mod n
        if offs[k] == 0:
            return inv_base
        v = inv_base - jnp.int32(offs[k])  # (-n, n)
        return v + jnp.where(v < 0, n, 0)

    partner0 = base_perm
    # one loss outcome per (node, partner-round) message — shared by the
    # gossip data plane and the failure-detection evidence below, so the
    # single ping-req round-trip can't be "lost" for detection yet
    # "delivered" for dissemination
    losses = [
        _uniform(rng, (n,), salt=0xB0B0 + k) < params.packet_loss
        for k in range(k_total)
    ]
    active_words = _pack_mask(state.r_active)
    gossiping = proc_alive & state.gossip_on
    # direct round (the ping): always on
    conn0 = partition == partition[partner0]
    # only gossiping nodes INITIATE; a left node still answers when it
    # is the partner (the reference's left node keeps serving pings)
    direct_ok = gossiping & proc_alive[partner0] & conn0 & ~losses[0]
    # pull: i ORs partner's heard set; push: partner ORs i's set.  The
    # push scatter i -> partner[i] is a gather by the inverse
    # permutation (partner is a permutation: no write conflicts).
    fused_ex = resolve_fused_exchange(params, jax.default_backend())
    if exchange_plane is not None:
        # round-14 seam: the plane owns partner-row delivery (explicit
        # collectives under a mesh) AND the fused exchange on the
        # pre-gathered rows; it applies the direct_ok/active_words
        # masking internally with the same semantics as the inline path
        # below.  Delta accounting follows the fused shape (d_direct
        # from the plane, indirect diff summed separately) — exact mod
        # 2^32 either way.
        if exch is not None:
            # metrics-carrying plane (make_exchange_plane(metrics=True)):
            # the telemetry bumps happen INSIDE the shard_map body, where
            # the routing stats are already local — the driver pairs the
            # plane flavor with params.exchange_metrics (ShardedStorm)
            new_heard, d_direct, exch, exch_hist = exchange_plane(
                state.heard,
                state.r_delta,
                active_words,
                direct_ok,
                partner0,
                inv_base,
                exch,
                exch_hist,
            )
        else:
            new_heard, d_direct = exchange_plane(
                state.heard,
                state.r_delta,
                active_words,
                direct_ok,
                partner0,
                inv_base,
            )
        fused_ex = "plane"
    else:
        pulled = (
            jnp.where(direct_ok[:, None], state.heard[partner0], 0)
            & active_words[None, :]
        )
        pushed = (
            jnp.where(
                direct_ok[inv_base][:, None], state.heard[inv_base], 0
            )
            & active_words[None, :]
        )
        if fused_ex == "off":
            new_heard = state.heard | pulled | pushed
            d_direct = None
        else:
            # fused megakernel (ops.exchange): OR + new-bit diff +
            # popcount + checksum delta-sum in one pass over the mask —
            # the direct round's [N, U/32] temporaries never reach HBM.
            # Exact mod-2^32 arithmetic, so csum stays bit-identical to
            # the inline path.  want_counts=False: the tick consumes
            # only the mask + delta — the per-row popcount and its [N]
            # output drop out of the program
            new_heard, d_direct, _nb = _exchange.exchange(
                state.heard,
                pulled,
                pushed,
                state.r_delta,
                impl=fused_ex,
                want_counts=False,
            )
    if exch is not None and exchange_plane is None:
        # analytic S-shard twin of the plane's in-body bumps (the GSPMD
        # and single-device paths) — bitwise-equal counters by
        # construction, pinned in tests/parallel/test_shard_exchange.py
        exch, exch_hist = _exchange_obs_update(
            exch, exch_hist, direct_ok, partner0, inv_base, n
        )
    heard_after_direct = new_heard

    # indirect rounds (the ping-req fanout) + probe evidence: only nodes
    # whose direct ping failed participate, so on the common all-healthy
    # tick the 3 extra row-gathers and probe draws are skipped entirely
    need_ind = gossiping & ~direct_ok

    def _indirect(nh):
        any_responder = jnp.zeros(n, bool)
        any_reached = jnp.zeros(n, bool)
        for k in range(1, k_total):
            m = _partner(k)
            loss = losses[k]
            conn = partition == partition[m]
            use = need_ind & proc_alive[m] & conn & ~loss
            pulled = jnp.where(use[:, None], nh[m], 0)
            inv = _inv(k)
            pushed = jnp.where(use[inv][:, None], nh[inv], 0)
            nh = nh | (pulled & active_words[None, :]) | (
                pushed & active_words[None, :]
            )
            # i <-> intermediary leg: the same loss outcome the gossip
            # exchange used for this round
            responder = proc_alive[m] & conn & ~loss
            # intermediary -> target probe leg: its own independent loss
            loss_probe = (
                _uniform(rng, (n,), salt=0xD0DE + k) < params.packet_loss
            )
            reached = (
                responder
                & proc_alive[partner0]
                & (partition[m] == partition[partner0])
                & ~loss_probe
            )
            any_responder |= responder
            any_reached |= reached
        return nh, any_responder, any_reached

    new_heard, any_responder, any_reached = _phase(
        gate,
        jnp.any(need_ind),
        _indirect,
        lambda nh: (nh, jnp.zeros(n, bool), jnp.zeros(n, bool)),
        new_heard,
    )

    # incremental checksum, exchange diff: every newly-set heard bit adds
    # its rumor's delta.  Bits only turn ON in an exchange and only for
    # active rumors, so the XOR is exactly the new-bit mask; converged
    # ticks (no new bits anywhere) skip the O(N*U) reduction.  In fused
    # mode the direct round's delta already came back from the kernel
    # (one pass with the OR), so only the rare indirect rounds' new bits
    # remain — summing the two disjoint bit sets separately is exact mod
    # 2^32, hence bit-identical to the single-diff inline path.
    if fused_ex == "off":
        diff_all = new_heard ^ state.heard

        def _diff_add(c):
            return c + _bit_delta_sum(diff_all, state.r_delta, u)

        csum = _phase(
            gate, jnp.any(diff_all != 0), _diff_add, lambda c: c, csum
        )
    else:
        csum = csum + d_direct
        ind_diff = new_heard ^ heard_after_direct

        def _diff_add(c):
            return c + _bit_delta_sum(ind_diff, state.r_delta, u)

        csum = _phase(
            gate, jnp.any(ind_diff != 0), _diff_add, lambda c: c, csum
        )
        diff_all = None  # only the wavefront plane needs the full diff
    # wavefront: every newly-set heard bit stamps its first-heard tick.
    # Straight-line (not gated): the stamp is a masked no-op when no
    # bits turned on, so gatings stay bit-identical.
    fh = state.first_heard
    # the [N, U] bit expansion is shared by the wavefront stamp and the
    # rumor-age histogram — computed once when either plane is on
    new_bits = None
    if fh is not None or hist is not None:
        if diff_all is None:
            diff_all = new_heard ^ state.heard
        bit_ids = jnp.arange(WORD, dtype=jnp.uint32)[None, None, :]
        new_bits = (
            ((diff_all[:, :, None] >> bit_ids) & jnp.uint32(1)) != 0
        ).reshape(n, u)
    if fh is not None:
        fh = jnp.where(new_bits, t, fh)
    if hist is not None:
        # rumor age at first-heard: every newly-set heard bit is an
        # adoption of rumor r at age t - r_birth[r] (new bits only turn
        # on for active slots, whose r_birth is their publish tick)
        hist = hg.record(
            hist,
            SCALABLE_HIST_TRACKS.index("rumor_age"),
            jnp.broadcast_to(t - state.r_birth[None, :], (n, u)),
            new_bits,
        )
    state = state._replace(heard=new_heard, first_heard=fh)

    # ---- failure detection: suspect batch ------------------------------
    # cancel suspicion clocks whose subject is no longer suspect in truth —
    # refuted alive (reference stops timers on non-suspect updates,
    # on_membership_event.js:86-104) or already escalated faulty
    csubj = jnp.clip(state.susp_subject, 0, n - 1)
    cancel = (state.susp_subject >= 0) & (
        state.truth_status[csubj] != SUSPECT
    )
    if hist is not None:
        # suspicion duration at refute-cancel (clock read pre-reset)
        hist = hg.record(
            hist,
            SCALABLE_HIST_TRACKS.index("suspicion_duration"),
            t - state.susp_since,
            cancel,
        )
    state = state._replace(
        susp_subject=jnp.where(cancel, -1, state.susp_subject),
        susp_since=jnp.where(cancel, -1, state.susp_since),
    )
    # Evidence-based SWIM detection (not a liveness oracle): the direct
    # exchange failed — dead partner, packet loss, OR partition — and the
    # ping-req fanout's intermediaries answered but none reached the
    # target (ping-req-sender.js:249-262).  Packet loss / partitions thus
    # produce FALSE suspects, refuted later like the reference.  The
    # evidence masks are all-false when no direct ping failed (the cond
    # above was skipped), which is exactly when direct_fail is all-false.
    direct_fail = gossiping & ~direct_ok & (partner0 != ids)
    start_susp = (
        direct_fail
        & any_responder
        & ~any_reached
        & (state.susp_subject != partner0)
    )
    state = state._replace(
        susp_subject=jnp.where(start_susp, partner0, state.susp_subject),
        susp_since=jnp.where(start_susp, t, state.susp_since),
    )
    already_down = state.truth_status[jnp.clip(partner0, 0, n - 1)] >= SUSPECT
    detector = start_susp & ~already_down
    # subjects of this tick's suspect batch (dedup via boolean scatter)
    subj_idx = jnp.where(detector, partner0, n)
    suspect_subjects = jnp.zeros(n, bool).at[subj_idx].set(True, mode="drop")
    n_susp = jnp.sum(suspect_subjects.astype(jnp.int32))
    rep_id = _representative_accuser(detector, subj_idx, partition, n)
    state, csum = _publish_batch_gated(
        state,
        csum,
        slots[0],
        suspect_subjects,
        jnp.full(n, SUSPECT, jnp.int32),
        state.truth_inc,  # suspect keeps the member's incarnation
        detector,
        t,
        gate=gate,
    )
    state = state._replace(
        defame_slot=jnp.where(suspect_subjects, slots[0], state.defame_slot),
        defame_by=jnp.where(suspect_subjects, rep_id, state.defame_by),
    )

    # ---- suspicion expiry: faulty batch --------------------------------
    expire = (
        (state.susp_since >= 0)
        & (t - state.susp_since >= params.suspicion_ticks)
        & proc_alive
    )
    if hist is not None:
        # suspicion duration at expiry (a cancelled clock reset its
        # susp_since above, so no double count within the tick)
        hist = hg.record(
            hist,
            SCALABLE_HIST_TRACKS.index("suspicion_duration"),
            t - state.susp_since,
            expire,
        )
    esubj = jnp.clip(state.susp_subject, 0, n - 1)
    still_suspect = state.truth_status[esubj] == SUSPECT
    expirer = expire & still_suspect & (state.susp_subject >= 0)
    fs_idx = jnp.where(expirer, state.susp_subject, n)
    faulty_subjects = jnp.zeros(n, bool).at[fs_idx].set(True, mode="drop")
    n_faulty = jnp.sum(faulty_subjects.astype(jnp.int32))
    # representative accuser per faulty subject (same scheme as suspects)
    frep_id = _representative_accuser(expirer, fs_idx, partition, n)
    state = state._replace(
        susp_subject=jnp.where(expire, -1, state.susp_subject),
        susp_since=jnp.where(expire, -1, state.susp_since),
    )
    state, csum = _publish_batch_gated(
        state,
        csum,
        slots[1],
        faulty_subjects,
        jnp.full(n, FAULTY, jnp.int32),
        state.truth_inc,  # faulty with current incarnation (suspicion.js:67-70)
        expirer,
        t,
        gate=gate,
    )
    state = state._replace(
        defame_slot=jnp.where(faulty_subjects, slots[1], state.defame_slot),
        defame_by=jnp.where(faulty_subjects, frep_id, state.defame_by),
    )

    # ---- refute + rejoin: alive batch ----------------------------------
    # refute (member.js:76-81): a live node that has HEARD the rumor
    # defaming it re-asserts alive with a fresh incarnation.  "Heard" =
    # its bit for the defaming slot is set, the rumor already aged into
    # base_sum (then every live node counts it), or the slot was recycled
    # while still defamed (the -2 sentinel).  ADDITIONALLY the subject
    # must currently be able to TALK to its representative defamer
    # (defame_by): same-tick defamations from disconnected partition
    # sides share one rumor slot, so without this gate a partitioned-away
    # subject would refute an accusation it could never have heard —
    # split-brain faulty marks would cancel before escalating
    # (the reference retains per-observer faulty marks through a split,
    # docs/architecture_design.md, suspicion.js:67-70).
    ds = state.defame_slot
    ds_c = jnp.clip(ds, 0, u - 1)
    heard_bit = (
        state.heard[ids, ds_c // WORD]
        >> (ds_c % WORD).astype(jnp.uint32)
    ) & jnp.uint32(1)
    aware = (ds == -2) | (
        (ds >= 0) & (heard_bit.astype(bool) | ~state.r_active[ds_c])
    )
    db = state.defame_by
    reachable = (db >= 0) & (
        partition[jnp.clip(db, 0, n - 1)] == partition
    )
    defamed = (state.truth_status == SUSPECT) | (state.truth_status == FAULTY)
    refuter = proc_alive & ~revived & aware & reachable & defamed
    n_refute = jnp.sum(refuter.astype(jnp.int32))
    alive_subjects = revived | rejoined | refuter
    state, csum = _publish_batch_gated(
        state,
        csum,
        slots[2],
        alive_subjects,
        jnp.full(n, ALIVE, jnp.int32),
        jnp.full(n, now, jnp.int32),  # fresh incarnation (member.js:78-81)
        alive_subjects,
        t,
        gate=gate,
    )
    state = state._replace(
        defame_slot=jnp.where(alive_subjects, -1, state.defame_slot),
        defame_by=jnp.where(alive_subjects, -1, state.defame_by),
    )

    # ---- graceful leave: leave batch -----------------------------------
    # self-assertion of status=leave at the CURRENT incarnation
    # (membership.makeLeave); the leaver stops initiating gossip but keeps
    # answering, so the rumor still reaches it and everyone else
    if inputs.leave is not None:
        if not params.enable_leave:
            raise ValueError(
                "leave inputs require ScalableParams(enable_leave=True) "
                "(allocates the 4th rumor slot)"
            )
        leaver = (
            inputs.leave
            & proc_alive
            & state.gossip_on
            & (state.truth_status != LEAVE)
        )
        n_leave = jnp.sum(leaver.astype(jnp.int32))
        state, csum = _publish_batch_gated(
            state,
            csum,
            slots[3],
            leaver,
            jnp.full(n, LEAVE, jnp.int32),
            state.truth_inc,
            leaver,
            t,
            gate=gate,
        )
        # the reference stops gossip AND suspicion wholesale on leave
        # (on_membership_event.js:32-41 suspicion.stopAll) — a departed
        # node must not escalate a pre-leave suspicion to faulty
        state = state._replace(
            gossip_on=state.gossip_on & ~leaver,
            susp_subject=jnp.where(leaver, -1, state.susp_subject),
            susp_since=jnp.where(leaver, -1, state.susp_since),
        )
    else:
        n_leave = jnp.int32(0)

    # ---- checksums + metrics ------------------------------------------
    if params.checksum_in_tick:
        # the incrementally-maintained csum IS the checksum: every
        # mutation this tick (revive reset, retirement adjustment,
        # exchange-diff adds, publish adds) applied its exact uint32
        # delta, so csum == compute_checksums(state) bit-for-bit
        # (parity-asserted in tests/models/test_engine_scalable.py)
        checksum = csum
        view_sig = csum
    else:
        # membership checksums deferred to compute_checksums() on demand;
        # the distinct-view metric still needs a per-node view fingerprint,
        # which the active heard-set provides at O(N*U/32) cost
        checksum = state.checksum
        aw = _pack_mask(state.r_active)
        hw = state.heard & aw[None, :]
        pos = jnp.arange(hw.shape[1], dtype=jnp.uint32)[None, :]
        m = hw * jnp.uint32(0x9E3779B1) + pos * jnp.uint32(0x85EBCA77)
        m ^= m >> 15
        view_sig = jnp.sum(m * jnp.uint32(0x2C1B3C6D), axis=1, dtype=jnp.uint32)
    state = state._replace(checksum=checksum, rng=_fold(rng, 0x5CA1E))
    if hist is not None:
        state = state._replace(hist=hist)
    if exch is not None:
        state = state._replace(exch=exch, exch_hist=exch_hist)

    active_words2 = _pack_mask(state.r_active)
    n_active = jnp.sum(state.r_active.astype(jnp.int32))
    # full coverage == every live row's active-heard words equal the
    # active words — a bitwise compare; the per-row popcounts (the
    # heavier op) are only needed for the mean when coverage is partial
    hw_all = state.heard & active_words2[None, :]
    full_cov = jnp.all(
        jnp.where(
            proc_alive[:, None], hw_all == active_words2[None, :], True
        )
    )

    def _mean_frac(_):
        heard_counts = jnp.sum(_popcount(hw_all), axis=1, dtype=jnp.uint32)
        frac = jnp.where(
            n_active > 0,
            heard_counts.astype(jnp.float32) / jnp.maximum(n_active, 1),
            1.0,
        )
        return jnp.mean(jnp.where(proc_alive, frac, 1.0))

    # _phase runs the TRUE branch when ungated, so the general popcount
    # path is the true branch (under full coverage it returns exactly
    # 1.0, so both settings agree bitwise)
    mean_frac = _phase(
        gate, ~full_cov, _mean_frac, lambda _: jnp.float32(1.0), None
    )

    # distinct view count: the O(N log N) sort only runs when live
    # fingerprints actually differ — on a converged tick the min/max
    # check settles it (sorting [1M] every tick is measurable)
    cs = jnp.where(proc_alive, view_sig, jnp.uint32(0xFFFFFFFF))
    any_live = jnp.any(proc_alive)
    lo = jnp.min(jnp.where(proc_alive, view_sig, jnp.uint32(0xFFFFFFFF)))
    hi = jnp.max(jnp.where(proc_alive, view_sig, jnp.uint32(0)))

    def _distinct_sorted(c):
        s = jnp.sort(c)
        return (
            jnp.sum(
                (s[1:] != s[:-1]) & (s[1:] != jnp.uint32(0xFFFFFFFF)),
                dtype=jnp.int32,
            )
            + (s[0] != jnp.uint32(0xFFFFFFFF)).astype(jnp.int32)
        ).astype(jnp.int32)

    # general sort path is the true branch (ungated runs it always); the
    # false branch covers all-live-equal: 1 distinct view (0 when none
    # are live, or when the shared value collides with the dead-node
    # sentinel — matching the sort path, which never counts it)
    distinct = _phase(
        gate,
        (lo != hi) & any_live,
        _distinct_sorted,
        lambda c: (
            any_live & (hi != jnp.uint32(0xFFFFFFFF))
        ).astype(jnp.int32),
        cs,
    )

    metrics = ScalableMetrics(
        live_nodes=jnp.sum(proc_alive.astype(jnp.int32)),
        active_rumors=n_active,
        mean_heard_frac=mean_frac,
        full_coverage=full_cov,
        distinct_checksums=distinct,
        suspects_published=n_susp,
        faulties_published=n_faulty,
        refutes_published=n_refute,
        leaves_published=n_leave,
        pings_sent=jnp.sum(gossiping.astype(jnp.int32)),
        pings_delivered=jnp.sum(direct_ok.astype(jnp.int32)),
        # direct_fail ⊆ need_ind, so the cond-skipped (all-false)
        # any_responder and the straight-line unmasked one agree here
        ping_req_inconclusive=jnp.sum(
            (direct_fail & ~any_responder).astype(jnp.int32)
        ),
        rumors_retired=jnp.sum(retired.astype(jnp.int32)),
    )
    return state, metrics
