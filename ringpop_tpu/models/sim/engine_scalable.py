"""Scalable SWIM engine: O(N·U) state for 100k-1M simulated nodes.

The full-fidelity engine (:mod:`ringpop_tpu.models.sim.engine`) keeps every
node's complete view — ``[N, N]`` arrays — which is exact but caps N at a few
thousand.  This engine is the large-scale mode behind the 100k epidemic-
broadcast and 1M churn-storm configs (BASELINE.md north-star table): it
replaces per-node views with

- **global truth** arrays ``[N]`` — each member's current status and
  incarnation as asserted by the cluster's most recent update about it, and
- a bounded **rumor table** of the U most recent membership update events
  (the circulating dissemination set — the union of all nodes' piggyback
  change tables in the reference, lib/gossip/dissemination.js), and
- per-node **heard bitmasks**, bit r of ``heard[i]`` = node i has received
  rumor r, packed 32 rumors per uint32 lane: ``[N, U/32] uint32``.

Node i's implied membership view = (base snapshot) + (its heard rumors,
reduced per subject by the SWIM precedence key).  Per-node checksums use a
**commutative combine** (sum mod 2^32 of per-member record hashes) instead of
the reference's order-sensitive hash-of-joined-string — bit-exact checksum
parity is the job of the full-fidelity engine at <=1k nodes; at 100k+ the
checksum only needs to *discriminate views*, and a sum-combine does, while
costing O(U) per node instead of O(N).

Gossip exchange is **push-pull over random pairings**: each tick every live
node draws K partner permutations; pushes its heard-set to partner 0 (the
direct ping, dissemination piggyback) and pulls the partner's set back (the
ack's issueAsReceiver changes).  A failed direct ping (dead/partitioned/lossy
partner) falls back to K-1 indirect partners (the ping-req fanout, k=3,
ping-req-sender.js:293-296).  Permutation pairing keeps the exchange a dense
gather + bitwise-OR — no scatter conflicts, no segment reductions — which is
exactly the memory-bandwidth-bound shape TPUs like.  SWIM's randomized
round-robin probe order has the same pairing distribution; the deviation
envelope is documented in SURVEY.md §7 ("hard parts" 4 and 6).

Rumor lifecycle mirrors piggyback aging: a rumor is dropped once its age
exceeds ``15 * ceil(log10(n+1))`` ticks plus slack — at one ping per node per
tick, per-node piggyback count is bounded by ticks-since-heard, so global age
upper-bounds the reference's per-node drop rule (dissemination.js:41).
Failure detection: a node whose direct ping and all indirect probes fail to
reach a dead partner publishes a *suspect* rumor; after ``suspicion_ticks``
the suspect's surviving rumor escalates to *faulty* (suspicion.js:67-70).
Revived nodes publish an alive rumor with a fresh incarnation (the refute
path, member.js:76-81).
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ringpop_tpu.ops.record_mix import record_mix

ALIVE, SUSPECT, FAULTY, LEAVE = 0, 1, 2, 3

WORD = 32


class ScalableParams(NamedTuple):
    n: int
    u: int = 512  # rumor table capacity (power of 32 multiple)
    ping_req_size: int = 3  # index.js:113
    suspicion_ticks: int = 25  # 5000ms / 200ms
    piggyback_factor: int = 15  # dissemination.js:41
    age_slack: int = 8  # extra ticks beyond max piggyback before drop
    packet_loss: float = 0.0
    epoch: int = 1414142122274
    # checksums every tick cost O(N*U); storms at 1M nodes can compute them
    # on demand (compute_checksums) instead
    checksum_in_tick: bool = True


class ScalableState(NamedTuple):
    tick_index: jax.Array  # scalar int32
    # fault plane + truth
    proc_alive: jax.Array  # [N] bool — process up
    truth_status: jax.Array  # [N] int32 — latest asserted status
    truth_inc: jax.Array  # [N] int64 — latest asserted incarnation
    # rumor table (global, bounded)
    r_active: jax.Array  # [U] bool
    r_subject: jax.Array  # [U] int32
    r_status: jax.Array  # [U] int32
    r_inc: jax.Array  # [U] int64
    r_birth: jax.Array  # [U] int32 — tick the rumor was published
    r_hash: jax.Array  # [U] uint32 — record hash of (subject,status,inc)
    # per-node reception
    heard: jax.Array  # [N, U/32] uint32 bit-packed
    # per-node detection state: tick at which node started suspecting its
    # (single) currently-probed dead partner, -1 if none
    susp_subject: jax.Array  # [N] int32 — -1 or suspected node
    susp_since: jax.Array  # [N] int32
    # base (pre-rumor) commutative checksum common to all nodes
    base_sum: jax.Array  # scalar uint32
    rng: jax.Array  # [2] uint32 — global fold key
    checksum: jax.Array  # [N] uint32


class ScalableMetrics(NamedTuple):
    live_nodes: jax.Array
    active_rumors: jax.Array
    mean_heard_frac: jax.Array  # mean fraction of active rumors heard
    full_coverage: jax.Array  # bool — every live node heard every rumor
    distinct_checksums: jax.Array
    suspects_published: jax.Array
    faulties_published: jax.Array


# the commutative record hash shared with the full-fidelity engine's fast
# checksum mode (not FarmHash — at scale the checksum's job is view
# discrimination, not string parity; see module docstring)
_record_hash = record_mix


def _rand_u32(key: jax.Array, shape, salt: int) -> jax.Array:
    """Counter-based uniform uint32 stream from the global fold key."""
    size = int(np.prod(shape))
    i = jnp.arange(size, dtype=jnp.uint32)
    x = key[0] + i * jnp.uint32(0x01000193) + jnp.uint32(salt)
    x ^= key[1] >> 7
    x ^= x >> 15
    x *= jnp.uint32(0x2C1B3C6D)
    x ^= x >> 12
    x *= jnp.uint32(0x297A2D39)
    x ^= x >> 15
    return x.reshape(shape)


def _uniform(key, shape, salt):
    return _rand_u32(key, shape, salt).astype(jnp.float32) / np.float32(2**32)


def _fold(key: jax.Array, salt: int) -> jax.Array:
    k0 = key[0] * jnp.uint32(0x9E3779B9) + jnp.uint32(salt)
    k1 = key[1] ^ ((k0 << 13) | (k0 >> 19))
    return jnp.stack([k1 * jnp.uint32(0x85EBCA6B) + 1, k0 ^ k1])


def _perm(key: jax.Array, n: int, salt: int) -> jax.Array:
    """Random permutation of [0, n) via sort of random keys (device-side)."""
    r = _rand_u32(key, (n,), salt)
    return jnp.argsort(r.astype(jnp.uint32) ^ jnp.arange(n, dtype=jnp.uint32))


def init_state(params: ScalableParams, seed: int = 0) -> ScalableState:
    n, u = params.n, params.u
    assert u % WORD == 0, "rumor capacity must be a multiple of 32"
    rng = np.random.default_rng(seed)
    inc0 = np.full(n, params.epoch, np.int64)
    subj = jnp.arange(n, dtype=jnp.int32)
    base = _record_hash(subj, jnp.zeros(n, jnp.int32), jnp.asarray(inc0))
    return ScalableState(
        tick_index=jnp.int32(0),
        proc_alive=jnp.ones(n, bool),
        truth_status=jnp.zeros(n, jnp.int32),
        truth_inc=jnp.asarray(inc0),
        r_active=jnp.zeros(u, bool),
        r_subject=jnp.zeros(u, jnp.int32),
        r_status=jnp.zeros(u, jnp.int32),
        r_inc=jnp.zeros(u, jnp.int64),
        r_birth=jnp.zeros(u, jnp.int32),
        r_hash=jnp.zeros(u, jnp.uint32),
        heard=jnp.zeros((n, u // WORD), jnp.uint32),
        susp_subject=jnp.full(n, -1, jnp.int32),
        susp_since=jnp.full(n, -1, jnp.int32),
        base_sum=jnp.sum(base, dtype=jnp.uint32),
        rng=jnp.asarray(rng.integers(1, 2**32 - 1, size=2, dtype=np.uint32)),
        checksum=jnp.zeros(n, jnp.uint32),
    )


class ChurnInputs(NamedTuple):
    """Per-tick fault plane for the scalable engine."""

    kill: jax.Array  # [N] bool
    revive: jax.Array  # [N] bool

    @staticmethod
    def quiet(n: int) -> "ChurnInputs":
        return ChurnInputs(kill=jnp.zeros(n, bool), revive=jnp.zeros(n, bool))


def _publish(state: ScalableState, want: jax.Array, subject, status, inc, tick):
    """Allocate rumor slots for `want` events (one per node slot, [N] bool).

    Slot policy: overwrite the stalest slots (inactive first, then oldest
    birth).  Returns updated state.  Publishing nodes immediately hear their
    own rumor."""
    n = state.heard.shape[0]
    u = state.r_active.shape[0]
    # rank free/stale slots: inactive -> key 0..; active -> key by birth
    slot_key = jnp.where(
        state.r_active, state.r_birth.astype(jnp.int64) + (1 << 32), jnp.int64(0)
    )
    slot_order = jnp.argsort(slot_key)  # stalest first
    # rank events: which of the [N] want-flags get slots (at most u)
    ev_rank = jnp.cumsum(want.astype(jnp.int32)) - 1  # position among wanted
    has_slot = want & (ev_rank < u)
    slot_of_ev = slot_order[jnp.clip(ev_rank, 0, u - 1)]  # [N]
    # scatter index: non-publishers go out of bounds so mode='drop' discards
    # them (a clipped index would make every non-publisher write the OLD
    # value onto slot_order[0], clobbering real publishes)
    slot_idx = jnp.where(has_slot, slot_of_ev, u)

    new_hash = _record_hash(subject, status, inc)

    def upd(arr, val):
        return arr.at[slot_idx].set(val, mode="drop")

    r_active = upd(state.r_active, True)
    r_subject = upd(state.r_subject, subject)
    r_status = upd(state.r_status, status)
    r_inc = upd(state.r_inc, inc)
    r_birth = upd(state.r_birth, jnp.broadcast_to(tick, (n,)))
    r_hash = upd(state.r_hash, new_hash)

    # truth advances to the newest assertion (indexed by SUBJECT; concurrent
    # publishers about the same subject resolve arbitrarily, like racing
    # gossip messages)
    subj_idx = jnp.where(has_slot, subject, n)
    truth_status = state.truth_status.at[subj_idx].set(status, mode="drop")
    truth_inc = state.truth_inc.at[subj_idx].set(inc, mode="drop")

    # freshly (re)allocated slots must be cleared from every node's heard
    # mask (the old rumor that lived in the slot is gone), then each
    # publisher hears its own rumor
    reused = jnp.zeros(u, bool).at[slot_idx].set(True, mode="drop")
    clear_words = _pack_mask(reused)  # [U/32]
    heard = state.heard & ~clear_words[None, :]
    heard = _rehear_own(heard, slot_of_ev, has_slot, n)
    return state._replace(
        r_active=r_active,
        r_subject=r_subject,
        r_status=r_status,
        r_inc=r_inc,
        r_birth=r_birth,
        r_hash=r_hash,
        truth_status=truth_status,
        truth_inc=truth_inc,
        heard=heard,
    )


def _pack_mask(bits: jax.Array) -> jax.Array:
    """[U] bool -> [U/32] uint32 with bit r of word r//32 = bits[r]."""
    u = bits.shape[0]
    w = bits.reshape(u // WORD, WORD)
    weights = (jnp.uint32(1) << jnp.arange(WORD, dtype=jnp.uint32))[None, :]
    return jnp.sum(jnp.where(w, weights, 0), axis=1, dtype=jnp.uint32)


def _rehear_own(heard, slot_of_ev, has_slot, n):
    word = slot_of_ev // WORD
    bit = (slot_of_ev % WORD).astype(jnp.uint32)
    rows = jnp.arange(n)
    cur = heard[rows, word]
    return heard.at[rows, word].set(
        jnp.where(has_slot, cur | (jnp.uint32(1) << bit), cur)
    )


def compute_checksums(state: ScalableState, params: ScalableParams) -> jax.Array:
    """Per-node commutative view checksum, O(U) per node.

    checksum(i) = base_sum + sum over *effective* heard rumors of
    (new_hash - prev_hash).  "Effective": among heard rumors sharing a
    subject, only the one with the highest (inc, status-rank) key counts,
    and its prev_hash chain collapses to the subject's base record — so we
    sum (winner_hash - base_hash(subject)) per heard subject.  Implemented
    as a per-node segment-max over the U rumor slots grouped by subject.
    """
    u = state.r_active.shape[0]
    key = jnp.where(
        state.r_active,
        state.r_inc * 4 + state.r_status,
        jnp.int64(-1),
    )  # [U] — SWIM precedence key per rumor

    # remap subjects to dense group ids within the table: gid[r] = first slot
    # holding r's subject ([U, U] once — U is small, e.g. 512)
    same = (state.r_subject[None, :] == state.r_subject[:, None]) & (
        state.r_active[None, :] & state.r_active[:, None]
    )
    slot_ids = jnp.arange(u)
    gid = jnp.min(jnp.where(same, slot_ids[None, :], u), axis=1)  # [U]
    gid = jnp.where(state.r_active, gid, u)  # inactive -> dropped segment

    base_h = _record_hash(
        state.r_subject,
        jnp.zeros(u, jnp.int32),
        jnp.full(u, params.epoch, jnp.int64),
    )
    delta = (state.r_hash - base_h).astype(jnp.uint32)
    weights = (jnp.uint32(1) << jnp.arange(WORD, dtype=jnp.uint32))[None, None, :]

    def row_delta(hk_row):
        # hk_row: [U] precedence keys of rumors this node heard (-1 = not)
        gmax = jax.ops.segment_max(hk_row, gid, num_segments=u + 1)[:u]
        gfirst = jax.ops.segment_min(
            jnp.where(hk_row == gmax[jnp.clip(gid, 0, u - 1)], slot_ids, u),
            gid,
            num_segments=u + 1,
        )[:u]
        winner = (
            (hk_row >= 0)
            & (hk_row == gmax[jnp.clip(gid, 0, u - 1)])
            & (slot_ids == gfirst[jnp.clip(gid, 0, u - 1)])
        )
        return jnp.sum(jnp.where(winner, delta, 0), dtype=jnp.uint32)

    def per_chunk(heard_rows):
        # [C, U/32] uint32 -> [C, U] heard bools -> per-row winner delta sum
        h = (heard_rows[:, :, None] & weights) != 0
        hb = h.reshape(heard_rows.shape[0], u)
        hk = jnp.where(hb & state.r_active[None, :], key[None, :], jnp.int64(-1))
        return jax.vmap(row_delta)(hk)

    n = state.heard.shape[0]
    chunk = max(1, min(n, 8192))
    pads = (-n) % chunk
    rows = state.heard
    if pads:
        rows = jnp.pad(rows, ((0, pads), (0, 0)))
    deltas = jax.lax.map(
        per_chunk, rows.reshape(-1, chunk, rows.shape[1])
    ).reshape(-1)[:n]
    return state.base_sum + deltas


def tick(
    state: ScalableState, inputs: ChurnInputs, params: ScalableParams
) -> tuple[ScalableState, ScalableMetrics]:
    n, u = params.n, params.u
    t = state.tick_index + 1
    now = jnp.int64(params.epoch) + t.astype(jnp.int64) * 200
    rng = state.rng

    # ---- fault plane ---------------------------------------------------
    revived = inputs.revive & ~state.proc_alive
    proc_alive = (state.proc_alive & ~inputs.kill) | inputs.revive
    # a restarted process loses all pre-crash state (the reference rebuilds
    # entirely via join full-sync, server/protocol/join.js:131): zero its
    # heard set and detection state, then publish its fresh-incarnation
    # alive rumor (the refute/rejoin path)
    state = state._replace(
        proc_alive=proc_alive,
        tick_index=t,
        heard=jnp.where(revived[:, None], 0, state.heard),
        susp_subject=jnp.where(revived, -1, state.susp_subject),
        susp_since=jnp.where(revived, -1, state.susp_since),
    )
    subj_ids = jnp.arange(n, dtype=jnp.int32)
    state = _publish(
        state,
        revived,
        subj_ids,
        jnp.full(n, ALIVE, jnp.int32),
        jnp.full(n, now, jnp.int64),
        t,
    )

    # ---- rumor aging (piggyback drop rule upper bound) -----------------
    live_count = jnp.sum(proc_alive.astype(jnp.int32))
    digits = jnp.sum(
        live_count >= 10 ** jnp.arange(10, dtype=jnp.int64), dtype=jnp.int32
    )
    max_age = params.piggyback_factor * digits + params.age_slack
    expired = state.r_active & (t - state.r_birth > max_age)
    state = state._replace(r_active=state.r_active & ~expired)
    # expired rumors' bits stay set in heard; they're masked out by r_active
    # everywhere they're read.

    # ---- gossip exchange: push-pull over K random pairings -------------
    k_total = 1 + params.ping_req_size
    heard = state.heard
    live_f = proc_alive
    active_words = _pack_mask(state.r_active)

    new_heard = heard
    direct_ok = jnp.zeros(n, bool)
    for k in range(k_total):
        partner = _perm(rng, n, salt=0xA11CE + 7 * k)
        loss = _uniform(rng, (n,), salt=0xB0B0 + k) < params.packet_loss
        ok = live_f & live_f[partner] & ~loss
        if k == 0:
            direct_ok = ok
            use = ok
        else:
            # indirect probes only fire for nodes whose direct ping failed
            use = live_f & ~direct_ok & live_f[partner] & ~loss
        # pull: i ORs partner's heard set; push: partner ORs i's set.
        # The push scatter i -> partner[i] is a gather by the inverse
        # permutation (partner is a permutation, so no write conflicts).
        pulled = jnp.where(use[:, None], new_heard[partner], 0)
        inv = jnp.argsort(partner)
        pushed = jnp.where(use[inv][:, None], new_heard[inv], 0)
        new_heard = new_heard | (pulled & active_words[None, :]) | (
            pushed & active_words[None, :]
        )
    state = state._replace(heard=new_heard)

    # ---- failure detection --------------------------------------------
    # nodes whose direct partner was dead and no indirect path reached it:
    # with the partner dead, no probe reaches it by construction; publish
    # suspect if not already suspected by us
    partner0 = _perm(rng, n, salt=0xA11CE)
    tgt_dead = live_f & ~proc_alive[partner0]
    start_susp = tgt_dead & (state.susp_subject != partner0)
    susp_subject = jnp.where(start_susp, partner0, state.susp_subject)
    susp_since = jnp.where(start_susp, t, state.susp_since)
    # target already faulty in truth? then don't re-publish
    already_down = state.truth_status[jnp.clip(partner0, 0, n - 1)] >= SUSPECT
    publish_suspect = start_susp & ~already_down
    n_susp = jnp.sum(publish_suspect.astype(jnp.int32))
    state = state._replace(susp_subject=susp_subject, susp_since=susp_since)
    state = _publish(
        state,
        publish_suspect,
        partner0.astype(jnp.int32),
        jnp.full(n, SUSPECT, jnp.int32),
        state.truth_inc[jnp.clip(partner0, 0, n - 1)],
        t,
    )

    # suspicion expiry -> faulty rumor (by the original suspector)
    expire = (
        (state.susp_since >= 0)
        & (t - state.susp_since >= params.suspicion_ticks)
        & live_f
    )
    subj = jnp.clip(state.susp_subject, 0, n - 1)
    still_suspect = state.truth_status[subj] == SUSPECT
    publish_faulty = expire & still_suspect & (state.susp_subject >= 0)
    n_faulty = jnp.sum(publish_faulty.astype(jnp.int32))
    state = state._replace(
        susp_subject=jnp.where(expire, -1, state.susp_subject),
        susp_since=jnp.where(expire, -1, state.susp_since),
    )
    state = _publish(
        state,
        publish_faulty,
        subj.astype(jnp.int32),
        jnp.full(n, FAULTY, jnp.int32),
        state.truth_inc[subj],
        t,
    )

    # ---- checksums + metrics ------------------------------------------
    if params.checksum_in_tick:
        checksum = compute_checksums(state, params)
    else:
        checksum = state.checksum
    state = state._replace(checksum=checksum, rng=_fold(rng, 0x5CA1E))

    active_words2 = _pack_mask(state.r_active)
    n_active = jnp.sum(state.r_active.astype(jnp.int32))
    heard_counts = jnp.sum(
        _popcount(state.heard & active_words2[None, :]), axis=1
    )  # [N]
    frac = jnp.where(
        n_active > 0,
        heard_counts.astype(jnp.float32) / jnp.maximum(n_active, 1),
        1.0,
    )
    live_frac = jnp.where(live_f, frac, 1.0)
    full_cov = jnp.all(jnp.where(live_f, heard_counts == n_active, True))

    cs = jnp.where(live_f, checksum, jnp.uint32(0xFFFFFFFF))
    cs_sorted = jnp.sort(cs)
    distinct = (
        jnp.sum(
            (cs_sorted[1:] != cs_sorted[:-1])
            & (cs_sorted[1:] != jnp.uint32(0xFFFFFFFF))
        )
        + (cs_sorted[0] != jnp.uint32(0xFFFFFFFF)).astype(jnp.int32)
    ).astype(jnp.int32)

    metrics = ScalableMetrics(
        live_nodes=jnp.sum(live_f.astype(jnp.int32)),
        active_rumors=n_active,
        mean_heard_frac=jnp.mean(live_frac),
        full_coverage=full_cov,
        distinct_checksums=distinct,
        suspects_published=n_susp,
        faulties_published=n_faulty,
    )
    return state, metrics


def _popcount(x: jax.Array) -> jax.Array:
    x = x - ((x >> 1) & jnp.uint32(0x55555555))
    x = (x & jnp.uint32(0x33333333)) + ((x >> 2) & jnp.uint32(0x33333333))
    x = (x + (x >> 4)) & jnp.uint32(0x0F0F0F0F)
    return (x * jnp.uint32(0x01010101)) >> 24
