"""SimCluster — host-side driver for the batched SWIM simulator.

Plays the role of the reference's tick-cluster harness
(/root/reference/scripts/tick-cluster.js): spawn N (simulated) nodes, join
them, tick the gossip protocol, inject faults (kill/revive/partition), and
watch convergence via membership-checksum grouping
(tick-cluster.js:87-114 groups nodes by checksum; the convergence benchmark
declares convergence when every live node reports the same checksum,
benchmarks/convergence-time/scenario-runner.js:152-170).

Two stepping modes:
- ``step()`` — one compiled tick; keeps state on device, events supplied per
  call (interactive tick-cluster-style use).
- ``run(ticks)`` — ``lax.scan`` over a precompiled tick with a dense event
  schedule, the high-throughput path for benchmarks.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ringpop_tpu.models.sim import engine
from ringpop_tpu.models.sim.recovery import CheckpointableMixin, CheckpointSpec
from ringpop_tpu.models.sim.schedule import DeviceScheduleMixin
from ringpop_tpu.ops import checksum_encode as ce


def default_addresses(n: int, base_port: int = 3000, host: str = "127.0.0.1") -> List[str]:
    return ["%s:%d" % (host, base_port + i) for i in range(n)]


@dataclasses.dataclass
class EventSchedule(DeviceScheduleMixin):
    """Dense per-tick fault-injection plan for ``run()``."""

    ticks: int
    n: int
    kill: np.ndarray = None  # [T, N] bool
    revive: np.ndarray = None
    join: np.ndarray = None
    partition: np.ndarray = None  # [T, N] int32
    resume: np.ndarray = None  # [T, N] bool, or None (no SIGCONTs)
    leave: np.ndarray = None  # [T, N] bool, or None (no graceful leaves)

    def __post_init__(self):
        T, n = self.ticks, self.n
        if self.kill is None:
            self.kill = np.zeros((T, n), bool)
        if self.revive is None:
            self.revive = np.zeros((T, n), bool)
        if self.join is None:
            self.join = np.zeros((T, n), bool)
        if self.partition is None:
            self.partition = np.full((T, n), -1, np.int32)  # -1 keeps current

    def _build_inputs(self) -> engine.TickInputs:
        # resume/leave stay None (not dense zeros) when unused, keeping
        # the pytree structure of plain inputs — no jit retrace.
        # Memoization/freezing semantics: DeviceScheduleMixin.as_inputs.
        return engine.TickInputs(
            kill=jnp.asarray(self.kill),
            revive=jnp.asarray(self.revive),
            join=jnp.asarray(self.join),
            partition=jnp.asarray(self.partition),
            resume=None if self.resume is None else jnp.asarray(self.resume),
            leave=None if self.leave is None else jnp.asarray(self.leave),
        )

    @staticmethod
    def churn_window(
        ticks: int, n: int, victims: Optional[Sequence[int]] = None
    ) -> "EventSchedule":
        """The shared churn-capture shape: a kill wave early in the
        window, revive at mid-window (suspect -> faulty escalation and
        the rejoin dissemination both land INSIDE the measured window).
        One definition for bench.py's churn_parity_* capture and
        benchmarks/tpu_measure.py's fused_engine_churn phase, so the two
        published numbers stay comparable.  Clamped for short windows
        (ticks <= 5 still kills; the revive is dropped only when the
        window cannot fit it after the kill)."""
        sched = EventSchedule(ticks=ticks, n=n)
        if victims is None:
            victims = (3 % n, n // 2, max(0, n - 5))
        kill_at = min(4, ticks - 1)
        revive_at = min(max(kill_at + 1, ticks // 2), ticks - 1)
        for v in victims:
            sched.kill[kill_at, v % n] = True
            if revive_at > kill_at:
                sched.revive[revive_at, v % n] = True
        return sched


def _resolve_hash_impl(params: engine.SimParams) -> engine.SimParams:
    """Pin trace-environment-dependent params to CONCRETE values at
    construction.

    ``hash_impl="env"``: the RINGPOP_TPU_PALLAS toggle is otherwise read
    at trace time inside engine.tick's checksum path; with shared
    executable caches that read would race with toggles between
    construction and first call, silently serving a pre-toggle executable
    (or poisoning the cache with a post-toggle trace under the pre-toggle
    key).

    ``parity_recompute="auto"``: "gated" (dirty-chunk while_loop — skips
    clean ticks) on CPU, "bounded" (one straight-line K<=64-row chunk,
    overflow-replayed by the driver) on TPU, whose tunnel compile helper
    500s on loop- or cond-wrapped encodes AND on chunks past ~K=64.
    All shapes are bit-identical in trajectory (overflowed bounded
    windows are replayed under an exact shape before anyone observes
    them)."""
    if params.hash_impl == "env":
        from ringpop_tpu.ops.jax_farmhash import _impl_from_env

        params = params._replace(hash_impl=_impl_from_env())
    import jax

    return engine.resolve_auto_parity(params, jax.default_backend())


@functools.lru_cache(maxsize=None)
def _tick_fn(params: engine.SimParams, universe: ce.Universe):
    return jax.jit(
        functools.partial(engine.tick, params=params, universe=universe)
    )


@functools.lru_cache(maxsize=None)
def _scanned_fn(params: engine.SimParams, universe: ce.Universe):
    @jax.jit
    def _scanned(state, inputs):
        def body(st, inp):
            st, m = engine.tick(st, inp, params, universe)
            return st, m

        return jax.lax.scan(body, state, inputs)

    return _scanned


def clear_executable_cache() -> None:
    """Drop the shared compiled executables (e.g. between sweep phases —
    a 1M-node program pins ~55 s of compile output until cleared)."""
    _tick_fn.cache_clear()
    _scanned_fn.cache_clear()


def fixup_sim_state(
    state: engine.SimState, params: engine.SimParams, universe: ce.Universe
) -> engine.SimState:
    """Align a just-loaded SimState with the resuming engine's params —
    the ONE post-load fixup shared by SimCluster, ShardedSim and the
    recovery plane (checkpoint knobs in _TRAJECTORY_NEUTRAL_PARAMS may
    legally differ between save and resume)."""
    if params.fused_checksum == "on":
        # the record cache is a pure function of (known, status,
        # inc) — rebuild it UNCONDITIONALLY at this boundary.  A
        # checkpoint's stored cache cannot be trusted: an
        # intervening unfused resume (fused_checksum is
        # trajectory-neutral, checkpoint.py) carries the saved
        # cache through unchanged while the views evolve, and a
        # later fused resume hashing those stale bytes would
        # silently break the parity contract.
        from ringpop_tpu.ops import fused_checksum as fc

        rec_b, rec_l = fc.member_records(
            universe,
            state.known,
            state.status,
            engine.stamp_to_ms(state.inc, params),
            params.max_digits,
        )
        state = state._replace(rec_bytes=rec_b, rec_len=rec_l)
    elif state.rec_bytes is not None:
        # unfused resume of a fused checkpoint: drop the cache so
        # this run never saves forward bytes it does not maintain
        state = state._replace(rec_bytes=None, rec_len=None)
    # flight-recorder plane: telemetry, not trajectory — a resume may
    # toggle it freely.  Recorder-on resumes start a fresh (empty)
    # buffer when the checkpoint has none or its capacity differs;
    # recorder-off resumes drop the saved buffer so this run never
    # carries forward events it will not append to.
    if params.flight_recorder:
        buf = state.ev_buf
        if buf is None or buf.shape[0] != params.event_capacity:
            from ringpop_tpu.models.sim import flight

            ev_buf, ev_head, ev_drops, first_heard = (
                flight.init_recorder_fields(params.n, params.event_capacity)
            )
            if state.first_heard is not None:
                first_heard = state.first_heard  # keep wavefront
            state = state._replace(
                ev_buf=ev_buf,
                ev_head=ev_head,
                ev_drops=ev_drops,
                first_heard=first_heard,
            )
    elif state.ev_buf is not None:
        state = state._replace(
            ev_buf=None, ev_head=None, ev_drops=None, first_heard=None
        )
    # latency-histogram plane: telemetry like the flight recorder — a
    # resume may toggle it; counters start fresh either way
    if params.histograms and state.hist is None:
        from ringpop_tpu.ops import histogram as hg

        state = state._replace(hist=hg.init(len(engine.HIST_TRACKS)))
    elif not params.histograms and state.hist is not None:
        state = state._replace(hist=None)
    return state


class SimCluster(CheckpointableMixin):
    def __init__(
        self,
        n: Optional[int] = None,
        addresses: Optional[Sequence[str]] = None,
        params: Optional[engine.SimParams] = None,
        seed: int = 0,
    ):
        if addresses is None:
            if n is None:
                raise ValueError("need n or addresses")
            addresses = default_addresses(n)
        self.universe = ce.Universe.from_addresses(addresses)
        n = self.universe.n
        self.params = params or engine.SimParams(n=n)
        if self.params.n != n:
            self.params = self.params._replace(n=n)
        # pre-resolution requests, kept for the toolkit's op_resolution
        # observability notes (attach_recorder)
        self._requested_knobs = {
            "fused_checksum": self.params.fused_checksum,
            "fused_tick": self.params.fused_tick,
            "parity_recompute": self.params.parity_recompute,
        }
        self.params = _resolve_hash_impl(self.params)
        self.state = engine.init_state(self.params, seed=seed, universe=self.universe)
        # shared per-(params, universe) executables — a fresh SimCluster
        # over the same config reuses the compiled tick/scan instead of
        # re-tracing (Universe hashes by its address tuple)
        self._tick = _tick_fn(self.params, self.universe)
        self._scanned = _scanned_fn(self.params, self.universe)
        # count of bounded-parity overflow replays (measurement honesty:
        # a bench window that replayed paid the exact-shape cost too)
        self.parity_replays = 0
        # optional telemetry sink (obs.RunRecorder via attach_recorder):
        # every step()/run() folds its metrics into the run log
        self.recorder = None
        # optional trace tap (obs.SimTracerHost via attach_tracer):
        # drain_events() re-publishes decoded flight events through it
        self.tracer = None

    def attach_tracer(self, tracer_host) -> None:
        """Attach an obs.SimTracerHost; every drain_events() re-publishes
        the decoded flight-recorder stream through its ``flightEvents``
        emitter (the ``sim.flight.events`` trace event)."""
        self.tracer = tracer_host

    def attach_recorder(self, recorder) -> None:
        """Attach an obs.RunRecorder; subsequent step()/run() metrics are
        folded into it (per-tick rows + totals/histograms), and bounded-
        parity overflow replays are logged as events.  The recorder's
        config is enriched with this cluster's static telemetry context
        (engine params incl. which checksum-recompute path is compiled).
        Every backend-resolved fused-op knob lands as an
        ``op_resolution`` event row (the toolkit's shared observability
        shape, round 16)."""
        import jax as _jax

        from ringpop_tpu.ops import toolkit

        recorder.describe("sim.engine", self.params.n, self.params)
        backend = _jax.default_backend()
        for knob in ("fused_checksum", "fused_tick", "parity_recompute"):
            toolkit.emit_resolution(
                toolkit.resolution_note(
                    knob,
                    self._requested_knobs.get(knob, "auto"),
                    getattr(self.params, knob),
                    backend,
                ),
                recorder=recorder,
            )
        self.recorder = recorder

    def emit_resolution_stat(self, bridge) -> None:
        """Publish the resolved fused-op knobs to a statsd bridge — the
        toolkit's shared gauge shape (``sim.<knob>.*``)."""
        import jax as _jax

        from ringpop_tpu.ops import toolkit

        backend = _jax.default_backend()
        for knob in ("fused_checksum", "fused_tick"):
            toolkit.emit_resolution(
                toolkit.resolution_note(
                    knob,
                    self._requested_knobs.get(knob, "auto"),
                    getattr(self.params, knob),
                    backend,
                ),
                statsd=bridge,
                gauge_prefix="sim.%s" % knob,
            )

    # -- bounded-parity overflow fallback --------------------------------

    @property
    def _bounded_parity(self) -> bool:
        return (
            self.params.checksum_mode == "farmhash"
            and self.params.parity_recompute == "bounded"
        )

    def _exact_params(self) -> engine.SimParams:
        """The exact-recompute twin config for overflow replays: "full"
        for fused runs (dense cell re-encode, no overflow possible),
        "full" on TPU (the tunnel can't compile the gated loop), "gated"
        elsewhere.  Bit-identical trajectories every way."""
        import jax

        return self.params._replace(
            parity_recompute=engine.resolve_exact_recompute(
                self.params, jax.default_backend()
            )
        )

    def _replay_exact(self, pre_state, run, *args):
        """A bounded-parity tick/scan overflowed: rows past the K-chunk
        kept stale checksums, and checksums feed full-sync decisions, so
        the computed trajectory is NOT parity-exact.  Discard it and
        replay from the pre-run state under an exact recompute shape
        (state is immutable, so the pre-run snapshot is just a
        reference)."""
        self.parity_replays += 1
        if self.recorder is not None:
            self.recorder.record_event(
                "parity_overflow_replay",
                replays=self.parity_replays,
                shape=self._exact_params().parity_recompute,
            )
        return run(pre_state, *args)

    # -- lifecycle --------------------------------------------------------

    def bootstrap(self) -> engine.TickMetrics:
        """Join every node at once (the tick-cluster 'j' command)."""
        inputs = engine.TickInputs.quiet(self.params.n)._replace(
            join=jnp.ones(self.params.n, bool)
        )
        return self.step(inputs)

    def step(self, inputs: Optional[engine.TickInputs] = None) -> engine.TickMetrics:
        if inputs is None:
            inputs = engine.TickInputs.quiet(self.params.n)
        pre = self.state
        self.state, metrics = self._tick(pre, inputs)
        if self._bounded_parity and int(metrics.parity_overflow) > 0:
            self.state, metrics = self._replay_exact(
                pre, _tick_fn(self._exact_params(), self.universe), inputs
            )
        metrics = jax.tree.map(np.asarray, metrics)
        if self.recorder is not None:
            self.recorder.record_ticks(metrics)
        self._after_ticks(1)
        return metrics

    def run(self, schedule: EventSchedule):
        """Scan the tick over a dense event schedule; returns stacked
        per-tick metrics (a TickMetrics of [T]-arrays).  With a
        checkpoint cadence enabled (enable_checkpoints(every=k)) the
        scan is split at cadence boundaries — trajectory- and
        metrics-bitwise-neutral (tests/models/test_recovery.py)."""
        return self._run_chunked(schedule, self._run_window)

    def _run_window(self, schedule: EventSchedule):
        inputs = schedule.as_inputs()
        pre = self.state
        self.state, metrics = self._scanned(pre, inputs)
        if self._bounded_parity and int(
            np.asarray(metrics.parity_overflow).sum()
        ):
            self.state, metrics = self._replay_exact(
                pre, _scanned_fn(self._exact_params(), self.universe), inputs
            )
        metrics = jax.tree.map(np.asarray, metrics)
        if self.recorder is not None:
            self.recorder.record_ticks(metrics)
        return metrics

    def run_until_converged(self, max_ticks: int = 200, quiet_after: int = 0) -> int:
        """Tick until every live+ready node shares one checksum; returns the
        number of ticks taken (or -1 if not converged within max_ticks)."""
        for t in range(max_ticks):
            m = self.step()
            if t >= quiet_after and bool(m.converged):
                return t + 1
        return -1

    # -- fault injection (tick-cluster k/K/l keys) ------------------------

    def kill(self, indices: Sequence[int]) -> engine.TickMetrics:
        inputs = engine.TickInputs.quiet(self.params.n)
        kill = np.zeros(self.params.n, bool)
        kill[list(indices)] = True
        return self.step(inputs._replace(kill=jnp.asarray(kill)))

    def revive(self, indices: Sequence[int]) -> engine.TickMetrics:
        inputs = engine.TickInputs.quiet(self.params.n)
        rv = np.zeros(self.params.n, bool)
        rv[list(indices)] = True
        return self.step(inputs._replace(revive=jnp.asarray(rv)))

    def suspend(self, indices: Sequence[int]) -> engine.TickMetrics:
        """SIGSTOP: process stops answering but keeps its state (the
        tick-cluster 'l' key)."""
        return self.kill(indices)

    def resume(self, indices: Sequence[int]) -> engine.TickMetrics:
        """SIGCONT: suspended process returns with pre-stop state intact."""
        inputs = engine.TickInputs.quiet(self.params.n)
        rs = np.zeros(self.params.n, bool)
        rs[list(indices)] = True
        return self.step(inputs._replace(resume=jnp.asarray(rs)))

    def leave(self, indices: Sequence[int]) -> engine.TickMetrics:
        """Graceful leave (membership.makeLeave + gossip stop)."""
        inputs = engine.TickInputs.quiet(self.params.n)
        lv = np.zeros(self.params.n, bool)
        lv[list(indices)] = True
        return self.step(inputs._replace(leave=jnp.asarray(lv)))

    def rejoin(self, indices: Sequence[int]) -> engine.TickMetrics:
        """Rejoin left nodes: alive with fresh incarnation, gossip restart
        (server/admin/member.js:44-51)."""
        inputs = engine.TickInputs.quiet(self.params.n)
        j = np.zeros(self.params.n, bool)
        j[list(indices)] = True
        return self.step(inputs._replace(join=jnp.asarray(j)))

    def partition(self, groups: Sequence[int]) -> engine.TickMetrics:
        inputs = engine.TickInputs.quiet(self.params.n)
        return self.step(
            inputs._replace(partition=jnp.asarray(np.asarray(groups, np.int32)))
        )

    # -- flight recorder (SimParams.flight_recorder) ----------------------

    def drain_events(self, reset: bool = True):
        """Decode the device-side flight-recorder buffer into host event
        dicts (obs.events) and, by default, clear it for the next
        window.  Feeds the attached SimTracerHost (``flightEvents``) and
        logs a ``flight_drain`` event row on the attached RunRecorder.
        The reset touches ONLY the write head/drop counter — protocol
        state is untouched, so draining mid-run is trajectory-neutral."""
        if self.state.ev_buf is None:
            raise ValueError(
                "flight recorder is off — construct with "
                "SimParams(flight_recorder=True)"
            )
        from ringpop_tpu.obs import events as obs_events

        drops = int(np.asarray(self.state.ev_drops))
        decoded = obs_events.decode_events(
            self.state.ev_buf, self.state.ev_head, drops
        )
        if self.tracer is not None:
            self.tracer.publish_flight_events(decoded, drops=drops)
        if self.recorder is not None:
            self.recorder.record_event(
                "flight_drain", events=len(decoded), drops=drops
            )
        # reset LAST: a raising tracer/recorder sink leaves the window
        # on device for a retry instead of silently losing it
        if reset:
            self.state = self.state._replace(
                ev_head=jnp.int32(0), ev_drops=jnp.int32(0)
            )
        return decoded

    # -- latency histograms (SimParams.histograms) ------------------------

    def drain_histograms(self, reset: bool = True, statsd=None):
        """Drain the device-side latency histograms (SimState.hist) into
        per-track summaries with exact p50/p95/p99 extraction
        (obs.histograms).  Logs a ``hist.drain`` event row on the
        attached RunRecorder; ``statsd`` (a StatsdBridge) additionally
        emits the percentiles as timer keys.  ``reset`` zeroes the
        counters for the next window AFTER the sinks ran — protocol
        state is untouched, so draining mid-run is trajectory-neutral."""
        if self.state.hist is None:
            raise ValueError(
                "histograms are off — construct with "
                "SimParams(histograms=True)"
            )
        from ringpop_tpu.obs import histograms as oh

        summary = oh.drain(
            self.state.hist,
            engine.HIST_TRACKS,
            "sim.engine",
            recorder=self.recorder,
            statsd=statsd,
        )
        if reset:
            from ringpop_tpu.ops import histogram as hg

            self.state = self.state._replace(
                hist=hg.init(len(engine.HIST_TRACKS))
            )
        return summary

    def event_drops(self) -> int:
        """Overflow honesty: events dropped since the last drain."""
        if self.state.ev_drops is None:
            return 0
        return int(np.asarray(self.state.ev_drops))

    def first_heard(self) -> np.ndarray:
        """The device-resident wavefront matrix: tick at which observer
        i first adopted j's current rumor (-1 = born-with view only)."""
        if self.state.first_heard is None:
            raise ValueError(
                "flight recorder is off — construct with "
                "SimParams(flight_recorder=True)"
            )
        return np.asarray(self.state.first_heard)

    def export_flight_trace(self, events=None, include_pings: bool = False):
        """Chrome-trace/Perfetto JSON dict of a decoded event stream
        (drains the buffer when ``events`` is omitted)."""
        from ringpop_tpu.obs.chrome_trace import export_chrome_trace

        if events is None:
            events = self.drain_events()
        return export_chrome_trace(
            events,
            n=self.params.n,
            period_ms=self.params.period_ms,
            addresses=list(self.universe.addresses),
            include_pings=include_pings,
        )

    # -- inspection -------------------------------------------------------

    def checksums(self) -> np.ndarray:
        return np.asarray(self.state.checksum)

    def checksum_groups(self) -> Dict[int, List[str]]:
        """Group live+ready nodes by membership checksum — the tick-cluster
        convergence view (tick-cluster.js:87-114)."""
        cs = self.checksums()
        alive = np.asarray(self.state.proc_alive & self.state.ready)
        groups: Dict[int, List[str]] = {}
        for i, a in enumerate(self.universe.addresses):
            if alive[i]:
                groups.setdefault(int(cs[i]), []).append(a)
        return groups

    def membership_of(self, i: int) -> List[dict]:
        """Node i's member list (sorted by address), host-readable.

        Engine stamps are converted back to the reference's epoch-ms
        incarnation numbers at this boundary (engine.stamp_to_ms)."""
        known = np.asarray(self.state.known[i])
        status = np.asarray(self.state.status[i])
        inc = np.asarray(self.state.inc[i])
        p = self.params
        out = []
        for j, a in enumerate(self.universe.addresses):
            if known[j]:
                s = int(inc[j])
                out.append(
                    {
                        "address": a,
                        "status": ce.STATUS_STRINGS[int(status[j])],
                        "incarnationNumber": (
                            p.epoch_ms + (s - 1) * p.period_ms if s > 0 else 0
                        ),
                    }
                )
        return out

    def checksum_string_of(self, i: int) -> str:
        return ";".join(
            "%s%s%d" % (m["address"], m["status"], m["incarnationNumber"])
            for m in self.membership_of(i)
        )

    # -- checkpoint/resume (SURVEY §5.4) ---------------------------------

    def save(self, path: str) -> None:
        from ringpop_tpu.models.sim.checkpoint import save_state

        save_state(path, self.state, self.params)

    def load(self, path: str) -> None:
        """Resume from ``path`` — a legacy ``.npz`` file or a manifest
        checkpoint directory (any shard count) alike."""
        from ringpop_tpu.models.sim.checkpoint import load_any

        self.state = fixup_sim_state(
            load_any(path, engine.SimState, self.params),
            self.params,
            self.universe,
        )

    # -- recovery plane (models/sim/recovery.py) --------------------------

    def _ckpt_spec(self) -> CheckpointSpec:
        # sharded_fields=None -> dynamic: every non-scalar SimState field
        # is node-leading (parallel/mesh._spec_for shards them all)
        return CheckpointSpec(engine.SimState, self.params, None)

    def _ckpt_states(self):
        return self.state

    def _ckpt_install(self, state) -> None:
        self.state = fixup_sim_state(state, self.params, self.universe)
