"""ScalableCluster — driver for the O(N·U) engine at 100k-1M nodes.

The large-scale twin of :class:`ringpop_tpu.models.sim.cluster.SimCluster`,
covering BASELINE.md's last two north-star configs:

- 100k-node SWIM epidemic broadcast (k=3 ping-req fanout, packet loss),
- 1M-node churn storm: 10% fail/rejoin with ring rebalance + checksum.

No address-string universe at this scale: node identity is the integer index
and checksums/ring points use the commutative record-hash — string-parity
belongs to the full-fidelity engine at <=1k nodes (SURVEY.md §7 hard part 6:
per-node views are kept only as divergence digests, not N x N state).

The consistent-hash ring at scale is the same masked-sort design as
models/ring/device.py (replica points -> sorted (hash, owner) table,
lookup = searchsorted; lib/ring/index.js:50-58,145-154) but with replica
hashes generated on device from the integer node id instead of host-hashed
`addr + i` strings.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ringpop_tpu.models.ring.device import (  # noqa: F401 — re-exported
    build_ring,
    device_replica_hashes,
    ring_checksum,
)
from ringpop_tpu.models.sim import engine_scalable as es
from ringpop_tpu.models.sim.recovery import CheckpointableMixin, CheckpointSpec
from ringpop_tpu.models.sim.schedule import DeviceScheduleMixin


@dataclasses.dataclass
class StormSchedule(DeviceScheduleMixin):
    """Dense [T, N] churn plan."""

    ticks: int
    n: int
    kill: np.ndarray = None
    revive: np.ndarray = None
    # graceful leaves ([T, N] bool) or None; requires
    # ScalableParams(enable_leave=True)
    leave: np.ndarray = None
    # partition regroups ([T, N] int32, -1 keeps the current group) or
    # None — None (not a dense -1 plane) keeps the pytree structure of
    # plain kill/revive inputs, so partition-free storms share the
    # compiled executable (ChurnInputs.partition has the same contract)
    partition: np.ndarray = None

    def __post_init__(self):
        if self.kill is None:
            self.kill = np.zeros((self.ticks, self.n), bool)
        if self.revive is None:
            self.revive = np.zeros((self.ticks, self.n), bool)

    def _build_inputs(self) -> es.ChurnInputs:
        # leave/partition stay None when unused: identical pytree to
        # plain inputs.  Device arrays memoized — a [60, 1M] bool pair is
        # 120 MB of host->device transfer that must not repeat per run
        # (the storm bench's warm-then-measure pattern).  Freezing
        # semantics: DeviceScheduleMixin.as_inputs.
        return es.ChurnInputs(
            kill=jnp.asarray(self.kill),
            revive=jnp.asarray(self.revive),
            partition=(
                None if self.partition is None else jnp.asarray(self.partition)
            ),
            leave=None if self.leave is None else jnp.asarray(self.leave),
        )

    @staticmethod
    def churn_storm(
        ticks: int,
        n: int,
        fraction: float = 0.1,
        fail_tick: int = 1,
        rejoin_tick: Optional[int] = None,
        seed: int = 0,
    ) -> "StormSchedule":
        """Kill ``fraction`` of nodes at ``fail_tick``, revive them at
        ``rejoin_tick`` (default: halfway) — the 1M churn-storm config."""
        if rejoin_tick is None:
            rejoin_tick = ticks // 2
        rng = np.random.default_rng(seed)
        victims = rng.choice(n, size=max(1, int(n * fraction)), replace=False)
        sched = StormSchedule(ticks=ticks, n=n)
        sched.kill[fail_tick, victims] = True
        sched.revive[rejoin_tick, victims] = True
        return sched


def donate_state_argnums() -> tuple:
    """Donation policy for the storm/route carry (round 13): donate
    everywhere EXCEPT the CPU backend.

    Donation is the round-10 HBM win (the [N, U/32] heard mask updates
    in place instead of allocating a second copy per tick — 64 MB/copy
    at 1M nodes).  On this image's CPU backend, however, executables
    DESERIALIZED from the persistent compilation cache mis-execute
    buffer donation whenever another dispatch interleaves between calls
    (a checkpoint save's host reads, even an unrelated jnp.zeros):
    warm-cache runs silently compute a wrong trajectory — cold compiles
    are correct, and neither the legacy nor the thunk CPU runtime is
    immune (bisect: round-13 session; repro pattern preserved in
    tests/models/test_recovery.py's cadence tests, which flake within
    minutes if donation is re-enabled under the cache).  A host-RAM
    copy per tick is noise at CPU test/bench scales, so correctness
    wins; TPU keeps the in-place path.

    Since round 17 the whole donation surface is statically pinned:
    every driver jitting with this policy is compiled by the `donation`
    analysis prong and its input_output_alias map diffed against the
    committed DONATION_BUDGET.json (this gate shows up there as data —
    empty alias maps on CPU), and astlint's stale-ref-across-donation
    rule catches live bindings held across these dispatches.  README
    "Donation hazards" is the one write-up."""
    import jax as _jax

    return () if _jax.default_backend() == "cpu" else (0,)


@functools.lru_cache(maxsize=None)
def _tick_fn(params: es.ScalableParams):
    # donate the state (backend-gated, see donate_state_argnums): the
    # tick's output state reuses the input's buffers.  Drivers always
    # overwrite self.state with the result, so the donated input is
    # never re-read.
    return jax.jit(
        functools.partial(es.tick, params=params),
        donate_argnums=donate_state_argnums(),
    )


@functools.lru_cache(maxsize=None)
def _scanned_fn(params: es.ScalableParams):
    @functools.partial(jax.jit, donate_argnums=donate_state_argnums())
    def _scanned(state, inputs):
        def body(st, inp):
            return es.tick(st, inp, params)

        return jax.lax.scan(body, state, inputs)

    return _scanned


def clear_executable_cache() -> None:
    """Drop the shared compiled executables (a 1M-node storm program pins
    ~55 s of compile output until cleared).  The scalable engine has no
    env-read trace inputs, so params alone keys these caches."""
    _tick_fn.cache_clear()
    _scanned_fn.cache_clear()
    _ring_checksum_fn.cache_clear()


@functools.lru_cache(maxsize=None)
def _ring_checksum_fn(n: int, replica_points: int):
    @jax.jit
    def _ring_and_checksum(truth_status, proc_alive):
        # alive + suspect members stay in the ring
        # (on_membership_event.js:106-134 keeps alive/suspect servers)
        in_ring = proc_alive & (truth_status <= es.SUSPECT)
        reps = device_replica_hashes(n, replica_points)
        ring = build_ring(reps, in_ring)
        return ring_checksum(ring)

    return _ring_and_checksum


def fixup_scalable_state(
    state: es.ScalableState, params: es.ScalableParams
) -> es.ScalableState:
    """Align a just-loaded ScalableState with the resuming engine's
    params (shared by ScalableCluster, ShardedStorm and RoutedStorm).
    The wavefront plane is telemetry, not trajectory — a resume may
    toggle it regardless of what the checkpoint carried."""
    if params.wavefront and state.first_heard is None:
        state = state._replace(
            first_heard=jnp.full((params.n, params.u), -1, jnp.int32)
        )
    elif not params.wavefront and state.first_heard is not None:
        state = state._replace(first_heard=None)
    # latency-histogram plane: same telemetry contract as the wavefront
    if params.histograms and state.hist is None:
        from ringpop_tpu.ops import histogram as hg

        state = state._replace(
            hist=hg.init(len(es.SCALABLE_HIST_TRACKS))
        )
    elif not params.histograms and state.hist is not None:
        state = state._replace(hist=None)
    # per-shard exchange telemetry plane: same contract, plus a resume
    # under a DIFFERENT shard count re-zeroes (counter rows are keyed by
    # shard id — a foreign bucketization would mislabel the wire)
    if params.exchange_metrics:
        s = int(params.exchange_metrics)
        if state.exch is None or int(state.exch.shape[0]) != s:
            from ringpop_tpu.ops import exchange as _exchange

            state = state._replace(
                exch=_exchange.init_exchange_counters(s),
                exch_hist=_exchange.init_exchange_hist(s),
            )
    elif state.exch is not None:
        state = state._replace(exch=None, exch_hist=None)
    return state


class ScalableCluster(CheckpointableMixin):
    """Driver for the scalable engine (construction pins the trace-time
    knobs; step/run go through shared compiled executables).

    DONATION CAVEAT: the tick/scan executables donate the input state
    (round 10 — the [N, U/32] heard mask updates in place, 64 MB/copy at
    1M), so a reference held to ``cluster.state`` from BEFORE a
    ``step()``/``run()`` call is invalidated by that call ("Array has
    been deleted" on read).  Snapshot with ``np.asarray(...)`` /
    ``jax.device_get`` before stepping if you need before/after views —
    the wavefront/checksum accessors here already read post-step state
    only."""

    def __init__(
        self,
        n: int,
        params: Optional[es.ScalableParams] = None,
        replica_points: int = 16,
        seed: int = 0,
    ):
        self.params = params or es.ScalableParams(n=n)
        if self.params.n != n:
            self.params = self.params._replace(n=n)
        # the pre-resolution request, kept for the observability note
        # (exchange_resolution's "requested" field — same shape as the
        # mesh driver's)
        self._requested_fused_exchange = self.params.fused_exchange
        # pin the trace-time "auto" knobs (perm_impl, fused_exchange) to
        # concrete values: the shared executable caches below key on
        # params, so two clusters built under different default backends
        # must not alias one cache entry (engine.resolve_auto_parity's
        # scalable analog)
        self.params = es.resolve_scalable_params(
            self.params, jax.default_backend()
        )
        self.replica_points = replica_points
        self.state = es.init_state(self.params, seed=seed)
        # module-level lru_cache keyed by the (hashable) params: every
        # instance with the same params shares ONE traced+compiled
        # executable.  A 1M-node storm compile costs ~55 s through the
        # tunnel; per-instance @jax.jit made the bench's warm run (a fresh
        # cluster) recompile the identical program.
        self._tick = _tick_fn(self.params)
        self._scanned = _scanned_fn(self.params)
        self._ring_checksum = _ring_checksum_fn(
            self.params.n, self.replica_points
        )
        # optional telemetry sink (obs.RunRecorder via attach_recorder)
        self.recorder = None

    def exchange_resolution(self) -> dict:
        """The single-device fused-exchange resolution as a runlog-ready
        dict — the mesh driver's ShardedStorm.exchange_resolution()
        twin, so the satellite observability note can always compare
        "what a mesh resolved" against "what this backend resolves
        single-device" (round 14; the values were pinned concrete at
        construction by resolve_scalable_params)."""
        return {
            "requested": self._requested_fused_exchange,
            "mode": "inline",
            "impl": self.params.fused_exchange,
            "shards": 1,
            "cap": None,
            "single_device_resolution": self.params.fused_exchange,
            "differs_from_single_device": False,
        }

    def attach_recorder(self, recorder) -> None:
        """Attach an obs.RunRecorder; step()/run() metrics fold into it.
        The fused-exchange resolution lands as an ``op_resolution``
        event row (the toolkit's shared observability shape — the
        single-device analog of the mesh driver's
        ``mesh_exchange_resolution``)."""
        from ringpop_tpu.ops import toolkit

        recorder.describe("sim.engine_scalable", self.params.n, self.params)
        toolkit.emit_resolution(
            toolkit.resolution_note(
                "fused_exchange",
                self._requested_fused_exchange,
                self.params.fused_exchange,
                jax.default_backend(),
            ),
            recorder=recorder,
        )
        self.recorder = recorder

    def emit_resolution_stat(self, bridge) -> None:
        """Publish the fused-exchange resolution to a statsd bridge —
        the toolkit's shared gauge shape (``sim.fused_exchange.*``)."""
        from ringpop_tpu.ops import toolkit

        toolkit.emit_resolution(
            toolkit.resolution_note(
                "fused_exchange",
                self._requested_fused_exchange,
                self.params.fused_exchange,
                jax.default_backend(),
            ),
            statsd=bridge,
            gauge_prefix="sim.fused_exchange",
        )

    def step(self, inputs: Optional[es.ChurnInputs] = None):
        if inputs is None:
            inputs = es.ChurnInputs.quiet(self.params.n)
        self.state, m = self._tick(self.state, inputs)
        m = jax.tree.map(np.asarray, m)
        if self.recorder is not None:
            self.recorder.record_ticks(m)
        self._after_ticks(1)
        return m

    def run(self, schedule: StormSchedule):
        """Scan over the storm plan; with a checkpoint cadence enabled
        the scan is split at cadence boundaries (trajectory- and
        metrics-bitwise-neutral, tests/models/test_recovery.py)."""
        return self._run_chunked(schedule, self._run_window)

    def _run_window(self, schedule: StormSchedule):
        self.state, ms = self._scanned(self.state, schedule.as_inputs())
        ms = jax.tree.map(np.asarray, ms)
        if self.recorder is not None:
            self.recorder.record_ticks(ms)
        return ms

    def checksums(self) -> np.ndarray:
        if not bool(self.params.checksum_in_tick):
            return np.asarray(
                es.compute_checksums(self.state, self.params)
            )
        return np.asarray(self.state.checksum)

    def ring_checksum(self) -> int:
        """Rebuild the ring from current truth, return its digest."""
        return int(self._ring_checksum(self.state.truth_status, self.state.proc_alive))

    # -- latency histograms (ScalableParams.histograms) -------------------

    def drain_histograms(self, reset: bool = True, statsd=None):
        """Drain the device latency histograms (ScalableState.hist) into
        per-track summaries (exact p50/p95/p99, obs.histograms); logs a
        ``hist.drain`` event on the attached recorder and optionally
        emits timer keys through ``statsd`` (a StatsdBridge).  ``reset``
        zeroes the counters AFTER the sinks ran."""
        if self.state.hist is None:
            raise ValueError(
                "histograms are off — construct with "
                "ScalableParams(histograms=True)"
            )
        from ringpop_tpu.obs import histograms as oh

        summary = oh.drain(
            self.state.hist,
            es.SCALABLE_HIST_TRACKS,
            "sim.engine_scalable",
            recorder=self.recorder,
            statsd=statsd,
        )
        if reset:
            from ringpop_tpu.ops import histogram as hg

            self.state = self.state._replace(
                hist=hg.init(len(es.SCALABLE_HIST_TRACKS))
            )
        return summary

    # -- exchange telemetry (ScalableParams.exchange_metrics) -------------

    def drain_exchange_metrics(self, reset: bool = True, statsd=None):
        """Drain the per-shard exchange telemetry counters through the
        shared host half (obs.exchange_stats.drain) — the single-device
        twin of ShardedStorm.drain_exchange_metrics, counting against
        the DEFAULT exchange cap so per-shard rows sum bitwise to the
        mesh driver's under identical trajectories."""
        if self.state.exch is None:
            raise ValueError(
                "exchange telemetry is off — construct with "
                "ScalableParams(exchange_metrics=<shards>)"
            )
        from ringpop_tpu.obs import exchange_stats as oxs
        from ringpop_tpu.ops import exchange as _exchange

        counters = np.asarray(self.state.exch)
        hist = np.asarray(self.state.exch_hist)
        s = int(counters.shape[0])
        summary = oxs.drain(
            counters,
            hist,
            w=int(self.state.heard.shape[1]),
            local_rows=self.params.n // s,
            source="sim.engine_scalable",
            recorder=self.recorder,
            statsd=statsd,
        )
        if reset:
            self.state = self.state._replace(
                exch=_exchange.init_exchange_counters(s),
                exch_hist=_exchange.init_exchange_hist(s),
            )
        return summary

    # -- rumor wavefront tracing (ScalableParams.wavefront) ---------------

    def wavefront_snapshot(self) -> dict:
        """Host snapshot of the rumor wavefront plane: first-heard tick
        matrix + rumor birth ticks/active flags — everything
        ``obs.events.scalable_wavefront_summary`` needs.  Snapshot
        BEFORE rumors age out (max_rumor_age ticks after birth): a
        recycled slot's history resets with its heard bits."""
        if self.state.first_heard is None:
            raise ValueError(
                "wavefront tracing is off — construct with "
                "ScalableParams(wavefront=True)"
            )
        return {
            "tick": int(np.asarray(self.state.tick_index)),
            "first_heard": np.asarray(self.state.first_heard),
            "r_birth": np.asarray(self.state.r_birth),
            "r_active": np.asarray(self.state.r_active),
            "live": np.asarray(self.state.proc_alive),
        }

    def wavefront_summary(self) -> dict:
        """Per-rumor dissemination latencies + convergence curves from
        the current wavefront snapshot (obs.events)."""
        from ringpop_tpu.obs import events as obs_events

        snap = self.wavefront_snapshot()
        return obs_events.scalable_wavefront_summary(
            snap["first_heard"],
            snap["r_birth"],
            snap["r_active"],
            snap["live"],
        )

    # -- checkpoint/resume (SURVEY §5.4) ---------------------------------

    def save(self, path: str) -> None:
        from ringpop_tpu.models.sim.checkpoint import save_state

        save_state(path, self.state, self.params)

    def load(self, path: str) -> None:
        """Resume from ``path`` — a legacy ``.npz`` file or a manifest
        checkpoint directory (any shard count) alike."""
        from ringpop_tpu.models.sim.checkpoint import load_any

        self.state = fixup_scalable_state(
            load_any(path, es.ScalableState, self.params), self.params
        )

    # -- recovery plane (models/sim/recovery.py) --------------------------

    def _ckpt_spec(self) -> CheckpointSpec:
        return CheckpointSpec(
            es.ScalableState, self.params, es.NODE_SHARDED_FIELDS
        )

    def _ckpt_states(self):
        return self.state

    def _ckpt_install(self, state) -> None:
        self.state = fixup_scalable_state(state, self.params)
