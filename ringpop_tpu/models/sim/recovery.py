"""Preemption-safe recovery plane: cadence, rotation, newest-valid scan.

:mod:`checkpoint` knows how to write one atomic, self-verifying
checkpoint; this module makes long-running drivers *survive being
killed* with it:

- :class:`CheckpointManager` owns a checkpoint **directory family**
  (``ckpt-<tick>`` manifest directories under one root), saves on
  demand, rotates with keep-last-K garbage collection, and — the
  recovery half — scans newest-first at restore time, **falling back
  past corrupt checkpoints** (torn manifests, truncated or bit-rotted
  array files, missing shards) with each failure surfaced as a
  ``ckpt.corrupt`` event instead of a crash or a silent resume.
- :class:`CheckpointableMixin` gives every driver
  (``SimCluster``/``ScalableCluster``/``ShardedSim``/``ShardedStorm``/
  ``RoutedStorm``) the same three-call surface:
  ``enable_checkpoints(dir, every=..., keep=..., shards=...)``,
  ``restore_latest()`` and the internal cadence hook that splits a
  scanned ``run()`` at checkpoint boundaries.  Chunking a ``lax.scan``
  at tick k is trajectory-neutral (state threads through unchanged; the
  per-tick metric stacks are concatenated), pinned bitwise by
  tests/models/test_recovery.py.

Telemetry: ``ckpt.saved`` / ``ckpt.corrupt`` / ``ckpt.resumed`` /
``ckpt.gc`` flow as runlog event rows through an attached
``obs.RunRecorder`` and as counters through an attached statsd client
(key map in ``obs.statsd_bridge.CKPT_KEY_MAP``).
"""

from __future__ import annotations

import os
import re
import shutil
import time
from typing import Any, Dict, List, NamedTuple, Optional, Tuple

import numpy as np

from ringpop_tpu.models.sim import checkpoint as ckpt

CKPT_PREFIX = "ckpt-"
_CKPT_RE = re.compile(r"^ckpt-(\d{10})$")


def checkpoint_name(tick: int) -> str:
    return "%s%010d" % (CKPT_PREFIX, tick)


class CheckpointSpec(NamedTuple):
    """What a driver checkpoints: state classes, params, and which
    fields shard along the node axis (None = dynamic: every array field
    with ndim >= 1, the full-engine rule where EVERY non-scalar field is
    node-leading — parallel/mesh._spec_for)."""

    state_cls: Any  # Type | {name: Type}
    params: Any = None  # params NamedTuple | {name: params}
    sharded_fields: Any = None  # frozenset | {name: frozenset} | None


def _dynamic_sharded_fields(states: Any) -> Dict[str, frozenset]:
    """Per-state 'shard every non-scalar array field' fallback.  Reads
    ``.ndim`` straight off the (possibly device) arrays — no host
    transfer just to inspect a shape."""
    smap = states if isinstance(states, dict) else {"state": states}
    return {
        name: frozenset(
            f
            for f in st._fields
            if getattr(getattr(st, f), "ndim", 0) >= 1
        )
        for name, st in smap.items()
    }


class CheckpointManager:
    """Rotated, self-verifying checkpoint family under one directory.

    ``keep`` counts VALID checkpoints: garbage collection deletes
    everything strictly older than the keep-th newest valid one, so a
    corrupt newest checkpoint can never evict the good fallback behind
    it.  ``restore_latest`` returns ``(tick, states)`` from the newest
    checkpoint that loads clean, recording every corrupt one it skipped
    in :attr:`last_errors` (and as ``ckpt.corrupt`` events)."""

    def __init__(
        self,
        directory: str,
        spec: CheckpointSpec,
        *,
        keep: int = 3,
        shards: int = 1,
        recorder: Any = None,
        statsd: Any = None,
        clock=time.perf_counter,
    ):
        if keep < 1:
            raise ValueError("keep must be >= 1, got %d" % keep)
        if shards < 1:
            raise ValueError("shards must be >= 1, got %d" % shards)
        self.directory = directory
        self.spec = spec
        self.keep = keep
        self.shards = shards
        self.recorder = recorder
        self.statsd = statsd
        self._clock = clock
        # (tick, path, error) triples from the most recent restore scan
        self.last_errors: List[Tuple[int, str, ckpt.CheckpointError]] = []
        os.makedirs(directory, exist_ok=True)

    # -- telemetry --------------------------------------------------------

    def _emit(self, name: str, **fields: Any) -> None:
        if self.recorder is not None:
            self.recorder.record_event(name, **fields)
        if self.statsd is not None:
            from ringpop_tpu.obs.statsd_bridge import CKPT_KEY_MAP

            mapped = CKPT_KEY_MAP.get(name)
            if mapped is not None:
                self.statsd.increment(mapped, 1)

    # -- inventory --------------------------------------------------------

    def path_of(self, tick: int) -> str:
        return os.path.join(self.directory, checkpoint_name(tick))

    def list_checkpoints(self) -> List[Tuple[int, str]]:
        """All ``ckpt-*`` entries, ascending by tick (validity not
        checked here; tmp leftovers and foreign entries are ignored)."""
        out: List[Tuple[int, str]] = []
        for entry in os.listdir(self.directory):
            m = _CKPT_RE.match(entry)
            if m is not None:
                out.append((int(m.group(1)), os.path.join(self.directory, entry)))
        return sorted(out)

    # -- save + rotation --------------------------------------------------

    def save(self, tick: int, states: Any, meta: Optional[dict] = None) -> str:
        """Atomic manifest save at ``tick`` + keep-last-K rotation."""
        sharded = self.spec.sharded_fields
        if sharded is None and self.shards > 1:
            sharded = _dynamic_sharded_fields(states)
        path = self.path_of(tick)
        t0 = self._clock()
        manifest = ckpt.save_checkpoint(
            path,
            host_copy_states(states),
            self.spec.params,
            shards=self.shards,
            sharded_fields=sharded,
            meta=dict(meta or {}, tick=tick),
        )
        self._emit(
            "ckpt.saved",
            tick=tick,
            path=os.path.basename(path),
            nbytes=manifest["nbytes"],
            shards=self.shards,
            wall_s=self._clock() - t0,
        )
        self.gc()
        return path

    def gc(self) -> List[str]:
        """Delete checkpoints older than the keep-th newest VALID one
        (shallow validity: manifest parses, files exist at exact sizes).
        Corrupt entries newer than that boundary are kept for forensics
        until they age past it."""
        entries = self.list_checkpoints()
        valid_seen = 0
        boundary: Optional[int] = None
        for tick, path in reversed(entries):
            try:
                ckpt.verify_checkpoint(path, deep=False)
            except ckpt.CheckpointError:
                continue
            valid_seen += 1
            if valid_seen >= self.keep:
                boundary = tick
                break
        if boundary is None:
            return []
        removed = []
        for tick, path in entries:
            if tick < boundary:
                shutil.rmtree(path, ignore_errors=True)
                removed.append(path)
        if removed:
            self._emit(
                "ckpt.gc",
                removed=[os.path.basename(p) for p in removed],
                keep=self.keep,
            )
        return removed

    # -- recovery ---------------------------------------------------------

    def restore_latest(self) -> Optional[Tuple[int, Any]]:
        """Newest-first scan: load the first checkpoint that verifies
        clean, falling back past corrupt ones (each recorded in
        ``last_errors`` + emitted as ``ckpt.corrupt``).  Returns
        ``(tick, states)`` or None when nothing valid exists."""
        self.last_errors = []
        for tick, path in reversed(self.list_checkpoints()):
            try:
                states = ckpt.load_checkpoint(
                    path, self.spec.state_cls, self.spec.params
                )
            except ckpt.CheckpointError as e:
                self.last_errors.append((tick, path, e))
                self._emit(
                    "ckpt.corrupt",
                    tick=tick,
                    path=os.path.basename(path),
                    error=type(e).__name__,
                    message=str(e),
                )
                continue
            self._emit(
                "ckpt.resumed",
                tick=tick,
                path=os.path.basename(path),
                skipped_corrupt=len(self.last_errors),
            )
            return tick, states
        return None


def host_copy_states(states: Any) -> Any:
    """Deep host copies of a state (or dict of states): ``np.array(...,
    copy=True)`` per field, None preserved.  Checkpoint saves must not
    hold zero-copy numpy views over live device buffers — the drivers'
    ticks DONATE those buffers on the next dispatch (the documented CPU
    aliasing hazard), and a view read racing a donated write silently
    corrupts the artifact or the trajectory."""

    def _copy_state(st):
        return type(st)(
            **{
                f: (
                    None
                    if getattr(st, f) is None
                    else np.array(getattr(st, f), copy=True)
                )
                for f in st._fields
            }
        )

    if hasattr(states, "_fields"):
        return _copy_state(states)
    return {name: _copy_state(st) for name, st in states.items()}


def concat_metrics(windows: List[Any]) -> Any:
    """Concatenate per-window [T]-stacked metric pytrees along the time
    axis (the chunked-run driver's merge; NamedTuples and tuples of
    NamedTuples both work — jax.tree handles the structure)."""
    import jax

    if len(windows) == 1:
        return windows[0]
    return jax.tree.map(
        lambda *xs: np.concatenate([np.asarray(x) for x in xs], axis=0),
        *windows,
    )


class CheckpointableMixin:
    """Cadenced checkpointing for the storm drivers.

    Subclasses provide ``_ckpt_spec()`` (a :class:`CheckpointSpec`),
    ``_ckpt_states()`` (current host-readable states), and
    ``_ckpt_install(states)`` (place restored states, applying the
    driver's load fixups).  The mixin owns the tick counter, the cadence
    split of scanned runs, and the manager lifecycle."""

    _ckpt_manager: Optional[CheckpointManager] = None
    _ckpt_every: int = 0
    tick_count: int = 0

    # -- subclass hooks ---------------------------------------------------

    def _ckpt_spec(self) -> CheckpointSpec:
        raise NotImplementedError

    def _ckpt_states(self) -> Any:
        raise NotImplementedError

    def _ckpt_install(self, states: Any) -> None:
        raise NotImplementedError

    # -- public surface ---------------------------------------------------

    @property
    def checkpoint_manager(self) -> Optional[CheckpointManager]:
        return self._ckpt_manager

    def enable_checkpoints(
        self,
        directory: str,
        every: int = 0,
        keep: int = 3,
        shards: Optional[int] = None,
        statsd: Any = None,
    ) -> CheckpointManager:
        """Attach a checkpoint family: save every ``every`` driven ticks
        (0 = manual ``checkpoint_now()`` only), keep the last ``keep``
        valid checkpoints, split node-axis fields over ``shards`` files
        (default: the driver's natural shard count — mesh size for the
        sharded drivers, 1 elsewhere).  Events ride the already-attached
        obs recorder, counters the optional statsd client."""
        if every < 0:
            raise ValueError("every must be >= 0, got %d" % every)
        self._ckpt_manager = CheckpointManager(
            directory,
            self._ckpt_spec(),
            keep=keep,
            shards=self._default_ckpt_shards() if shards is None else shards,
            recorder=getattr(self, "recorder", None),
            statsd=statsd,
        )
        self._ckpt_every = every
        return self._ckpt_manager

    def _default_ckpt_shards(self) -> int:
        return 1

    def checkpoint_now(self) -> str:
        """Force a save at the current tick count."""
        if self._ckpt_manager is None:
            raise ValueError(
                "checkpointing is off — call enable_checkpoints() first"
            )
        return self._ckpt_manager.save(self.tick_count, self._ckpt_states())

    def restore_latest(self) -> Optional[int]:
        """Resume from the newest valid checkpoint: install its states,
        set the tick counter, return the resumed tick (None = nothing
        valid found; the driver keeps its freshly-initialized state, the
        clean-restart half of the recovery contract)."""
        if self._ckpt_manager is None:
            raise ValueError(
                "checkpointing is off — call enable_checkpoints() first"
            )
        got = self._ckpt_manager.restore_latest()
        if got is None:
            return None
        tick, states = got
        self._ckpt_install(states)
        self.tick_count = tick
        return tick

    # -- cadence plumbing -------------------------------------------------

    def _after_ticks(self, k: int) -> None:
        """Advance the driven-tick counter; save when the cadence line
        is crossed (chunked runs land exactly ON it by construction)."""
        self.tick_count += k
        if (
            self._ckpt_manager is not None
            and self._ckpt_every > 0
            and self.tick_count % self._ckpt_every == 0
            and k > 0
        ):
            self.checkpoint_now()

    def _run_chunked(self, schedule, run_window):
        """Split ``run_window(schedule)`` at checkpoint-cadence
        boundaries; trajectory- and metrics-bitwise-neutral (the scan of
        T ticks is the composition of its windows)."""
        total = schedule.ticks
        if (
            self._ckpt_manager is None
            or self._ckpt_every <= 0
            or total == 0
        ):
            out = run_window(schedule)
            self._after_ticks(total)
            return out
        windows = []
        t = 0
        while t < total:
            # stop at the next cadence line (tick_count-aligned, so a
            # run() resumed mid-interval still saves on the grid)
            step = self._ckpt_every - (self.tick_count % self._ckpt_every)
            t1 = min(total, t + step)
            windows.append(run_window(schedule.window(t, t1)))
            self._after_ticks(t1 - t)
            t = t1
        return concat_metrics(windows)
