"""Shared dense fault-schedule machinery for both engines' drivers.

``cluster.EventSchedule`` (full-fidelity engine) and ``storm.StormSchedule``
(scalable engine) are dense per-tick fault-injection plans with the same
driver contract:

- ``as_inputs()`` converts the host numpy planes into the engine's input
  pytree ONCE and memoizes the device arrays — re-running one schedule
  (the bench's warm-then-measure pattern) must not re-upload [T, N] host
  arrays through the device transport on every run.  A schedule is
  therefore FROZEN at its first run.
- ``invalidate()`` drops the memo after mutating the planes.

That memoization pattern used to be copy-pasted between the two schedule
classes; this mixin is its one home (the scenario fuzzer targets this one
API for both engines, ringpop_tpu/fuzz/).  Subclasses implement
``_build_inputs()`` returning the engine's input pytree; optional planes
must stay ``None`` (not dense zeros) when unused so the pytree structure
matches plain inputs — no jit retrace.
"""

from __future__ import annotations

import dataclasses


class DeviceScheduleMixin:
    """Memoized ``as_inputs()``/``invalidate()`` over ``_build_inputs()``."""

    def window(self, t0: int, t1: int):
        """A new schedule holding ticks ``[t0, t1)`` of this one — the
        checkpoint-cadence/crash-resume slice (recovery._run_chunked,
        fuzz/crash.py).  Dense [T, N] planes are sliced and copied
        (mutating the window never leaks into the parent or vice versa);
        optional ``None`` planes stay ``None`` so the window's input
        pytree structure matches the parent's — no jit retrace."""
        if not (0 <= t0 <= t1 <= self.ticks):
            raise ValueError(
                "window [%d, %d) outside schedule of %d ticks"
                % (t0, t1, self.ticks)
            )
        kw = {}
        for f in dataclasses.fields(self):
            v = getattr(self, f.name)
            kw[f.name] = v[t0:t1].copy() if hasattr(v, "ndim") else v
        kw["ticks"] = t1 - t0
        return type(self)(**kw)

    def as_inputs(self):
        """Engine input pytree for this schedule (memoized device arrays).

        The schedule is FROZEN at its first use — mutate the planes
        before running, or call :meth:`invalidate` after mutating."""
        cached = getattr(self, "_device_inputs", None)
        if cached is not None:
            return cached
        inputs = self._build_inputs()
        # object.__setattr__: works for frozen and unfrozen dataclasses
        # alike, and keeps the cache out of dataclass field semantics
        object.__setattr__(self, "_device_inputs", inputs)
        return inputs

    def invalidate(self) -> None:
        """Drop the memoized device inputs after mutating the schedule."""
        object.__setattr__(self, "_device_inputs", None)

    def _build_inputs(self):
        raise NotImplementedError
