"""Batched device-resident request-routing plane (round 11).

- :mod:`ring_kernel` — incremental hash-prefix-bucketed consistent-hash
  ring (dirty-bucket re-merge, no per-tick sort) + the bit-exact
  full-sort twin contract and the fixed-width ``lookup_n`` variant.
- :mod:`traffic` — device-side Zipf traffic generator (threefry).
- :mod:`plane` — the routing tick (misroute / reroute / keys-diverged /
  checksum-reject counters) and the :class:`~plane.RoutedStorm` driver
  coupling it to the scalable churn-storm engine.
"""

from ringpop_tpu.models.route.plane import (  # noqa: F401
    RoutedStorm,
    RouteMetrics,
    RouteParams,
    RouteState,
    init_route_state,
    resolve_ring_impl,
    resolve_route_params,
    route_tick,
)
from ringpop_tpu.models.route.ring_kernel import (  # noqa: F401
    RingBuckets,
    RingState,
    build_buckets,
    default_bucket_bits,
    full_rebuild,
    lookup,
    lookup_n_fixed,
    materialize,
    update,
)
