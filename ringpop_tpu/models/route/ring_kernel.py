"""Incremental hash-prefix-bucketed consistent-hash ring.

The classic device ring (models/ring/device.py, duplicated until round
11 in storm.py) rebuilds under churn with a full ``jnp.sort`` of all
N·R uint64 keys — at 1M nodes x 16 replica points that is a 1.6M-element
sort *every tick*, even when churn touched three servers.  This module
replaces the sort with a **bucketed ring**:

- the static ``[N, R]`` replica table is partitioned ONCE at init into
  ``2^B`` buckets by hash prefix (the top B bits of the replica-point
  hash).  Within a bucket the static points are pre-sorted by their full
  ``(hash << 32) | owner`` key — so a bucket's *active* subset, in
  order, is a mask-compaction of a pre-sorted list: **no sort anywhere**,
- the dynamic ring state caches, per bucket, the compacted active keys
  (front-aligned, padded with the bucket's upper-boundary PAD key).
  Churn touches few servers, so only the buckets holding a changed
  server's replica points are *dirty*: the per-tick update gathers the
  ``D`` dirty buckets (``jnp.nonzero(..., size=D)``), re-compacts those
  rows in O(D·M), and scatters them back — every clean bucket reuses its
  cached segment untouched.  When a tick's churn exceeds the static caps
  (``max_changed`` servers / ``max_dirty`` buckets) the update falls
  back to a full (still sortless, O(N·R)) re-compaction under
  ``lax.cond``,
- because a bucket's PAD key ``((b+1) << (64-B)) - 1`` is >= every real
  key of bucket b and < every real key of bucket b+1, the flattened
  ``[2^B * M]`` segment table is **globally non-decreasing** with the
  padding interleaved — one ``jnp.searchsorted`` over the flat table
  serves batched lookups with no per-query row gather.  A PAD hit (its
  owner field decodes to -1) means the query ran past its bucket's last
  active point; the owner is then the first active point of the next
  non-empty bucket (``next_owner``, an O(2^B) suffix-scan refreshed per
  update), wrapping to the global minimum exactly like the reference's
  ``upperBound``-with-wraparound (lib/ring/index.js:145-154).

Equivalence contract (the acceptance gate): :func:`materialize` compacts
the bucketed state into the flat sorted layout and must equal
``models/ring/device.build_ring(replica_hashes, mask)`` **bitwise** —
both are the ascending multiset of active keys padded with the all-ones
sentinel, and bucket-major/in-bucket order is global order because the
bucket id is the key's top bits.  Pinned under randomized churn by
tests/models/test_route_ring.py (n=64 tier-1, n>=64k slow) and by the
bench/tpu_measure rebuild A/B's bitwise ring gate.

:func:`lookup_n_fixed` is the vmap-friendly W-successor twin of the
device ring's ``lookup_n``: the reference walk is a data-dependent
``while_loop`` whose trip count degenerates to the worst case across a
batch under vmap; the fixed-width variant gathers ``width`` successor
slots and masks first-occurrence owners.  It returns bit-identical
owners whenever the window held ``n`` unique owners or covered the whole
ring (``width >= n_points``) — the documented envelope, proven in the
same test file.
"""

from __future__ import annotations

import math
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np

SENTINEL = np.uint64(0xFFFFFFFFFFFFFFFF)


class RingBuckets(NamedTuple):
    """Static hash-prefix partition of the [N, R] replica table (built
    once per universe by :func:`build_buckets`; every array is
    churn-independent).  ``2^B = keys.shape[0]``, ``M = keys.shape[1]``
    (max static bucket occupancy)."""

    keys: jax.Array  # [2^B, M] uint64 — static keys, in-bucket sorted; PAD-padded
    owners: jax.Array  # [2^B, M] int32 — key's owner (-1 on PAD slots)
    point_bucket: jax.Array  # [N, R] int32 — bucket id of each replica point


class RingState(NamedTuple):
    """Dynamic bucketed ring: per-bucket active keys compacted to the
    row front, PAD-padded; refreshed incrementally by :func:`update`."""

    seg_keys: jax.Array  # [2^B, M] uint64
    count: jax.Array  # [2^B] int32 — active points per bucket
    mask: jax.Array  # [N] bool — the membership this state reflects
    n_points: jax.Array  # scalar int32 — total active points
    first_owner: jax.Array  # scalar int32 — owner of the global min key (-1 empty)
    next_owner: jax.Array  # [2^B] int32 — first active owner strictly after b (wraps)


def default_bucket_bits(n: int, replica_points: int, target_load: int = 192) -> int:
    """B such that the mean bucket holds ~``target_load`` static points
    (clamped to [1, 16] — past 64k buckets the O(2^B) per-tick suffix
    scan stops being negligible)."""
    total = max(1, n * replica_points)
    return max(1, min(16, int(math.log2(max(2, total // target_load)))))


def _pad_rows(n_buckets: int, m: int) -> jax.Array:
    """[2^B, M] uint64 PAD keys: bucket b's pad is its upper boundary
    ``((b+1) << (64-B)) - 1`` — >= every real key of b, < every real key
    of b+1, owner field all-ones (decodes to -1)."""
    # n_buckets is always a static python int (a .shape[0]); the lint
    # cannot see through the parameter
    b_bits = int(math.log2(n_buckets))  # jaxgate: ignore[host-coerce]
    ids = jnp.arange(n_buckets, dtype=jnp.uint64)
    pads = ((ids + jnp.uint64(1)) << jnp.uint64(64 - b_bits)) - jnp.uint64(1)
    return jnp.broadcast_to(pads[:, None], (n_buckets, m))


def build_buckets(
    replica_hashes: np.ndarray, bucket_bits: int
) -> RingBuckets:  # jaxgate: host — one-time init partition, never traced
    """Partition the static replica table into 2^B hash-prefix buckets
    (host-side numpy, once per universe)."""
    if not (1 <= bucket_bits <= 20):
        raise ValueError("bucket_bits must be in [1, 20], got %d" % bucket_bits)
    hashes = np.asarray(replica_hashes, dtype=np.uint32)
    n, r = hashes.shape
    nb = 1 << bucket_bits
    owners = np.broadcast_to(
        np.arange(n, dtype=np.uint64)[:, None], (n, r)
    )
    keys = (hashes.astype(np.uint64) << np.uint64(32)) | owners
    bucket = (hashes >> np.uint32(32 - bucket_bits)).astype(np.int64)
    flat_keys = keys.reshape(-1)
    flat_bucket = bucket.reshape(-1)
    counts = np.bincount(flat_bucket, minlength=nb)
    cap = max(1, int(counts.max()))
    # global ascending key order == (bucket, in-bucket key) order, since
    # the bucket id is the key's top B bits
    order = np.argsort(flat_keys, kind="stable")
    sorted_bucket = flat_bucket[order]
    starts = np.zeros(nb + 1, dtype=np.int64)
    np.cumsum(counts, out=starts[1:])
    within = np.arange(flat_keys.size, dtype=np.int64) - starts[sorted_bucket]
    pad_vals = ((np.arange(nb, dtype=np.uint64) + 1) << np.uint64(
        64 - bucket_bits
    )) - np.uint64(1)
    skeys = np.broadcast_to(pad_vals[:, None], (nb, cap)).copy()
    sowners = np.full((nb, cap), -1, dtype=np.int32)
    skeys[sorted_bucket, within] = flat_keys[order]
    sowners[sorted_bucket, within] = (
        flat_keys[order] & np.uint64(0xFFFFFFFF)
    ).astype(np.int32)
    return RingBuckets(
        keys=jnp.asarray(skeys),
        owners=jnp.asarray(sowners),
        point_bucket=jnp.asarray(bucket.astype(np.int32)),
    )


def _compact_rows(
    keys: jax.Array,  # [K, M] uint64 static keys
    pads: jax.Array,  # [K, M] uint64 PAD values for these rows
    active: jax.Array,  # [K, M] bool
) -> Tuple[jax.Array, jax.Array]:
    """Per-row stable mask-compaction of pre-sorted keys: active keys
    move to the row front (order preserved), the tail is PAD.  The whole
    're-merge' of a dirty bucket — O(M), no sort."""
    k, m = keys.shape
    pos = jnp.cumsum(active.astype(jnp.int32), axis=1, dtype=jnp.int32) - 1
    rows = jnp.arange(k, dtype=jnp.int32)[:, None]
    tgt = jnp.where(active, rows * m + pos, jnp.int32(k * m))
    seg = (
        pads.reshape(-1)
        .at[tgt.reshape(-1)]
        .set(keys.reshape(-1), mode="drop")
        .reshape(k, m)
    )
    cnt = jnp.sum(active.astype(jnp.int32), axis=1, dtype=jnp.int32)
    return seg, cnt


def _derive(
    seg_keys: jax.Array, count: jax.Array, mask: jax.Array
) -> RingState:
    """Refresh the lookup helpers (first/next owner, totals) from the
    per-bucket segments — O(2^B), every update pays it."""
    nb = count.shape[0]
    firsts = jnp.where(
        count > 0,
        (seg_keys[:, 0] & jnp.uint64(0xFFFFFFFF)).astype(jnp.int32),
        jnp.int32(-1),
    )
    # smallest non-empty bucket index at-or-after b (suffix min of
    # masked indices), then shift for strictly-after + wraparound
    idx = jnp.where(
        count > 0, jnp.arange(nb, dtype=jnp.int32), jnp.int32(2 * nb)
    )
    at_or_after = jax.lax.associative_scan(
        jnp.minimum, idx, reverse=True
    )
    first_idx = at_or_after[0]  # global first non-empty bucket (or 2*nb)
    after = jnp.concatenate(
        [at_or_after[1:], jnp.full((1,), 2 * nb, jnp.int32)]
    )
    nxt_idx = jnp.where(after < 2 * nb, after, first_idx)
    ring_nonempty = first_idx < 2 * nb
    next_owner = jnp.where(
        ring_nonempty,
        firsts[jnp.clip(nxt_idx, 0, nb - 1)],
        jnp.int32(-1),
    )
    first_owner = jnp.where(
        ring_nonempty, firsts[jnp.clip(first_idx, 0, nb - 1)], jnp.int32(-1)
    )
    return RingState(
        seg_keys=seg_keys,
        count=count,
        mask=mask,
        n_points=jnp.sum(count, dtype=jnp.int32),
        first_owner=first_owner,
        next_owner=next_owner,
    )


def full_rebuild(buckets: RingBuckets, mask: jax.Array) -> RingState:
    """Recompact every bucket from the static table + current mask —
    O(N·R) elementwise, zero sorts.  The init path and the overflow
    fallback of :func:`update`; bit-identical to the incremental path by
    construction (same compaction, all rows)."""
    n = mask.shape[0]
    nb, m = buckets.keys.shape
    active = mask[jnp.clip(buckets.owners, 0, n - 1)] & (buckets.owners >= 0)
    seg, cnt = _compact_rows(buckets.keys, _pad_rows(nb, m), active)
    return _derive(seg, cnt, mask)


def dirty_stats(
    buckets: RingBuckets,
    changed: jax.Array,  # [N] bool — servers whose ring membership flipped
    max_changed: int,
    max_dirty: int,
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """(n_changed, dirty mask [2^B], n_dirty, overflow).  Shared by the
    incremental update and the full-sort twin's metrics so the two
    modes' RouteMetrics stay bitwise-identical: the stats describe what
    the incremental path WOULD do, whichever path runs."""
    n = changed.shape[0]
    nb = buckets.keys.shape[0]
    n_changed = jnp.sum(changed, dtype=jnp.int32)
    (c_idx,) = jnp.nonzero(changed, size=max_changed, fill_value=n)
    pb = buckets.point_bucket[jnp.clip(c_idx, 0, n - 1)]
    pb = jnp.where((c_idx < n)[:, None], pb, jnp.int32(nb))
    dirty = (
        jnp.zeros(nb, bool).at[pb.reshape(-1)].set(True, mode="drop")
    )
    n_dirty = jnp.sum(dirty, dtype=jnp.int32)
    overflow = (n_changed > max_changed) | (n_dirty > max_dirty)
    return n_changed, dirty, n_dirty, overflow


def update(
    buckets: RingBuckets,
    state: RingState,
    new_mask: jax.Array,
    *,
    max_changed: int,
    max_dirty: int,
) -> Tuple[RingState, jax.Array, jax.Array, jax.Array]:
    """Incremental ring maintenance: re-merge only the dirty buckets.

    Returns ``(state', n_changed, n_dirty, full_rebuilds)`` where
    ``full_rebuilds`` is 1 iff the churn overflowed the static caps and
    the update fell back to :func:`full_rebuild` (bit-identical either
    way).  Per-tick cost on the incremental path:
    O(max_changed·R + max_dirty·M + 2^B)."""
    n = new_mask.shape[0]
    nb, m = buckets.keys.shape
    changed = new_mask != state.mask
    n_changed, dirty, n_dirty, overflow = dirty_stats(
        buckets, changed, max_changed, max_dirty
    )

    def _incremental(st: RingState) -> RingState:
        (d_idx,) = jnp.nonzero(dirty, size=max_dirty, fill_value=nb)
        dc = jnp.clip(d_idx, 0, nb - 1)
        k_rows = buckets.keys[dc]
        o_rows = buckets.owners[dc]
        act = new_mask[jnp.clip(o_rows, 0, n - 1)] & (o_rows >= 0)
        seg_rows, cnt_rows = _compact_rows(
            k_rows, _pad_rows(nb, m)[dc], act
        )
        rows_tgt = jnp.where(d_idx < nb, d_idx, jnp.int32(nb))
        seg = st.seg_keys.at[rows_tgt].set(seg_rows, mode="drop")
        cnt = st.count.at[rows_tgt].set(cnt_rows, mode="drop")
        return _derive(seg, cnt, new_mask)

    new_state = jax.lax.cond(
        overflow,
        lambda st: full_rebuild(buckets, new_mask),
        _incremental,
        state,
    )
    return new_state, n_changed, n_dirty, overflow.astype(jnp.int32)


def materialize(state: RingState, total_points: int) -> jax.Array:
    """Flatten the bucketed state into the classic sorted ring layout —
    ``[total_points]`` uint64 ascending active keys, all-ones-sentinel
    padded — bitwise-equal to ``device.build_ring(replica_hashes,
    state.mask)`` (the equivalence gate; also the interop path for
    consumers of the flat layout like :func:`lookup_n_fixed`)."""
    nb, m = state.seg_keys.shape
    slot = jnp.arange(m, dtype=jnp.int32)[None, :]
    active = slot < state.count[:, None]
    starts = jnp.cumsum(state.count, dtype=jnp.int32) - state.count
    pos = starts[:, None] + slot
    tgt = jnp.where(active, pos, jnp.int32(total_points))
    return (
        jnp.full((total_points,), jnp.uint64(SENTINEL), jnp.uint64)
        .at[tgt.reshape(-1)]
        .set(state.seg_keys.reshape(-1), mode="drop")
    )


def lookup(state: RingState, key_hashes: jax.Array) -> jax.Array:
    """Batched owner lookup on the bucketed ring: [Q] uint32 key hashes
    -> [Q] int32 owners (-1 when the ring is empty).  One searchsorted
    over the flat segment table (globally sorted with PADs interleaved);
    a PAD hit routes through ``next_owner`` (successor in a later
    bucket, wrapping), an off-the-end hit wraps to ``first_owner`` —
    the reference's lower-bound-with-wraparound, identical to
    ``device.lookup`` on the materialized ring."""
    nb, m = state.seg_keys.shape
    total = nb * m
    q = key_hashes.astype(jnp.uint64) << jnp.uint64(32)
    flat = state.seg_keys.reshape(-1)
    i = jnp.searchsorted(flat, q).astype(jnp.int32)
    ic = jnp.clip(i, 0, total - 1)
    owner = (flat[ic] & jnp.uint64(0xFFFFFFFF)).astype(jnp.int32)
    own = jnp.where(owner < 0, state.next_owner[ic // m], owner)
    own = jnp.where(i >= total, state.first_owner, own)
    return jnp.where(state.n_points > 0, own, jnp.int32(-1))


def lookup_n_fixed(
    ring: jax.Array,  # flat sorted ring (device.build_ring / materialize)
    n_points: jax.Array,
    key_hash: jax.Array,  # scalar uint32 (vmap for batches)
    n: int,
    width: int,
) -> Tuple[jax.Array, jax.Array]:
    """Fixed-width W-successor twin of ``device.lookup_n``: gather
    ``width`` successor slots, mask first-occurrence owners, keep the
    first ``n``.  Returns ``(owners [n] int32 -1-padded, found)``.

    Bit-identical to the while_loop walk whenever ``found == n`` or
    ``width >= n_points`` (the window saw the whole ring) — the
    documented envelope; unlike the walk, the trip count is static, so
    a vmapped batch never degenerates to the slowest query's bound."""
    query = key_hash.astype(jnp.uint64) << jnp.uint64(32)
    start = jnp.searchsorted(ring, query).astype(jnp.int32)
    steps = jnp.arange(width, dtype=jnp.int32)
    npts = jnp.maximum(n_points, 1)
    idx = (start + steps) % npts
    owners = (ring[idx] & jnp.uint64(0xFFFFFFFF)).astype(jnp.int32)
    visited = steps < n_points
    owners = jnp.where(visited, owners, jnp.int32(-1))
    dup = jnp.tril(owners[:, None] == owners[None, :], k=-1).any(axis=1)
    is_new = visited & ~dup
    rank = jnp.cumsum(is_new.astype(jnp.int32), dtype=jnp.int32) - 1
    found = jnp.sum(is_new.astype(jnp.int32), dtype=jnp.int32)
    out = (
        jnp.full((n,), -1, jnp.int32)
        .at[jnp.where(is_new & (rank < n), rank, jnp.int32(n))]
        .set(owners, mode="drop")
    )
    empty = n_points <= 0
    return (
        jnp.where(empty, jnp.int32(-1), out),
        jnp.where(empty, jnp.int32(0), jnp.minimum(found, n)),
    )
