"""Device-side Zipf traffic generator for the routing plane.

Real request traffic is heavy-tailed; the routing plane models it as a
Zipf(s) draw over a static key universe of ``K`` keys, sampled entirely
on device (threefry counters via ``jax.random`` — no host RNG anywhere
in the scanned tick).  The CDF over the K ranks is computed once at
driver init (``zipf_cdf``) and sampling is one uniform draw + one
``searchsorted`` per query — the same batched-binary-search shape the
ring lookups use.

Key identity -> ring position goes through :func:`key_hashes`, the
integer-keyed record-mix analog of the reference hashing the key string
with FarmHash32 before the ring lookup (lib/ring/index.js:145-147) —
string-keyed bit-parity belongs to the full-fidelity host path
(api/request_proxy.py + models/ring/host.py)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ringpop_tpu.ops.record_mix import record_mix


def zipf_cdf(n_keys: int, s: float) -> jax.Array:
    """[K] float32 CDF of Zipf(s) over key ranks 1..K (trace-time
    constant; exact inverse-CDF sampling against it)."""
    ranks = jnp.arange(1, n_keys + 1, dtype=jnp.float32)
    w = ranks ** jnp.float32(-s)
    c = jnp.cumsum(w, dtype=jnp.float32)
    return c / c[-1]


def sample_keys(key: jax.Array, cdf: jax.Array, q: int) -> jax.Array:
    """[Q] int32 key ids drawn Zipf-distributed via inverse CDF."""
    u = jax.random.uniform(key, (q,), dtype=jnp.float32)
    ids = jnp.searchsorted(cdf, u, side="left").astype(jnp.int32)
    return jnp.clip(ids, 0, cdf.shape[0] - 1)


def key_hashes(key_ids: jax.Array, salt: int = 0x51C7E7) -> jax.Array:
    """[Q] uint32 ring-position hashes of integer key ids."""
    z = jnp.zeros_like(key_ids)
    return record_mix(key_ids, z + jnp.int32(salt), z)
