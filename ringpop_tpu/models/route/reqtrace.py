"""Device-side sampled per-request trace buffer for the routing plane.

The routing plane observes itself only in aggregate — RouteMetrics
counters and log2 histograms — but the reference's requestProxy tells a
per-request story (send.js retry accounting: forward, checksum check,
retry re-lookup, reroute or abort).  This module records that story on
device, Dapper-style: a deterministic hash-of-key Bernoulli sample
picks ~2^-sample_log2 of the key space, and every routed request whose
key is sampled appends one fixed-width int32 record into a linear
buffer carried through the scanned tick — the flight-recorder
mechanics (models/sim/flight.py append_events: masked cumsum-scatter,
overflow counts-never-overwrites) applied to the request plane.

Neutrality contract: the record mask is ``sendable & sampled`` — a pure
function of the same masks that drive the counters plus a hash of the
traffic draw — and every buffer field is write-only, registered
obs-only (plane.ROUTE_OBS_ONLY_FIELDS), proven non-interfering by the
analysis prong (route-tick-reqtrace entry) and A/B-gated bitwise in
tests/models/test_reqtrace.py.

Sampling is per KEY, not per request: ``sample_mask`` re-mixes the
ring-position key hash with a dedicated salt, so a sampled key's every
request is traced (complete per-key span trees, obs/requests.py) and
the sampled subset is an unbiased share of traffic even under Zipf
skew (chi-square-tested across salts).

Alongside the records, a small counter plane (``req_counts``, one slot
per obs.requests.COUNT_FIELDS) sums each RouteMetrics mask restricted
to the sampled subset — computed on device under the SAME masks — so
reconciliation stays exact even when the record buffer overflows.

Record layout and field registry: obs/requests.py (the host half).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from ringpop_tpu.obs import requests as rq
from ringpop_tpu.ops.record_mix import record_mix


def max_requests_per_tick(queries_per_tick: int) -> int:
    """Exact upper bound on records one routing tick can append: every
    query is sendable and every key sampled (sample_log2=0).  Consumers
    sizing drop-free buffers derive from THIS so the contract lives
    next to the emitter (the flight.max_events_per_tick discipline)."""
    return queries_per_tick


def req_capacity_for(queries_per_tick: int, ticks: int) -> int:
    """Drop-free capacity for a ``ticks``-tick window at worst case."""
    return ticks * max_requests_per_tick(queries_per_tick)


def init_reqtrace_fields(capacity: int):
    """(req_buf, req_head, req_drops, req_counts, req_tick) initial
    values — the RouteState request-trace plane."""
    return (
        jnp.zeros((capacity, rq.RECORD_WIDTH), jnp.int32),
        jnp.int32(0),
        jnp.int32(0),
        jnp.zeros(len(rq.COUNT_FIELDS), jnp.int32),
        jnp.int32(0),
    )


def sample_mask(
    key_hashes: jax.Array, salt: int, sample_log2: int
) -> jax.Array:
    """[Q] bool — deterministic hash-of-key Bernoulli sample at rate
    2^-sample_log2 (``sample_log2=0`` samples everything).

    The decision re-mixes the ring-position hash with a DEDICATED salt
    (record_mix — independent of the traffic generator's key_hashes
    salt), then keeps keys whose low ``sample_log2`` bits are zero:
    consistent per key across ticks and ring impls, uniform across the
    key space regardless of traffic skew."""
    if sample_log2 == 0:
        return jnp.ones(key_hashes.shape, bool)
    z = jnp.zeros_like(key_hashes)
    h = record_mix(key_hashes, z + jnp.uint32(salt), z)
    return (h & jnp.uint32((1 << sample_log2) - 1)) == 0


def append_requests(
    buf: jax.Array,  # [cap, RECORD_WIDTH] int32
    head: jax.Array,  # scalar int32
    drops: jax.Array,  # scalar int32
    mask: jax.Array,  # [Q] bool — which lanes append a record
    columns: Tuple[jax.Array, ...],  # RECORD_WIDTH lanes ([Q] or scalar)
):
    """Masked append of up to Q records (flight.append_events shape):
    selected lanes are enumerated with a cumulative sum and scattered
    at ``head + rank``; out-of-capacity lanes route to a dropped slot
    and bump the drop counter — overflow never overwrites, so the
    stored stream is an honest prefix.  Returns (buf, head, drops)."""
    cap = buf.shape[0]
    q = mask.shape[0]
    mask_i = mask.astype(jnp.int32)
    # dtype pinned: under x64, sum/cumsum of int32 promote to int64 —
    # which would widen the scan carry (req_head) and break carry-type
    # equality between tick input and output
    total = jnp.sum(mask_i, dtype=jnp.int32)
    rank = jnp.cumsum(mask_i, dtype=jnp.int32) - 1
    pos = head + rank
    tgt = jnp.where(mask & (pos < cap), pos, cap)  # cap drops

    def lane(v):
        return jnp.broadcast_to(jnp.asarray(v, dtype=jnp.int32), (q,))

    rec = jnp.stack([lane(c) for c in columns], axis=1)
    buf = buf.at[tgt].set(rec, mode="drop")
    head_new = jnp.minimum(head + total, cap)
    drops = drops + jnp.maximum(head + total - cap, 0)
    return buf, head_new, drops


def record_tick_requests(
    state,  # plane.RouteState AFTER the tick's route masks computed
    params,  # plane.RouteParams (reqtrace on)
    kh: jax.Array,  # [Q] uint32 — primary-key ring hashes
    senders: jax.Array,  # [Q] int32
    dest: jax.Array,  # [Q] int32 — stale-view owner (clipped)
    own_truth: jax.Array,  # [Q] int32 — truth owner (-1 = none)
    sendable: jax.Array,  # [Q] bool
    misroute: jax.Array,  # [Q] bool
    reroute_local: jax.Array,  # [Q] bool
    reroute_remote: jax.Array,  # [Q] bool
    differ: jax.Array,  # [Q] bool — checksums differed
    rejects: jax.Array,  # [Q] bool — ... and consistency rejected
    multi_ok: jax.Array,  # [Q] bool — second key rode the envelope
    diverged: jax.Array,  # [Q] bool — keys-diverged abort
    retried: jax.Array,  # [Q] bool — the stale->truth retry fired
):
    """Append this tick's sampled requests and bump the sampled-subset
    counters; returns state with updated req_* fields.  Every argument
    is one of route_tick's OWN masks/lanes — nothing is recomputed, so
    the records are by construction what the counters summed."""
    tick = state.req_tick + jnp.int32(1)
    sampled = sample_mask(kh, params.req_salt, params.req_sample_log2)
    rec_mask = sendable & sampled

    def b(m):  # bool -> int32 lane
        return m.astype(jnp.int32)

    reroute = b(reroute_local) * rq.RR_LOCAL + b(reroute_remote) * rq.RR_REMOTE
    outcome = (
        b(differ) * rq.OUT_CHECKSUMS_DIFFER
        + b(rejects) * rq.OUT_CHECKSUM_REJECT
        + b(diverged) * rq.OUT_KEYS_DIVERGED
    )
    key_lane = jax.lax.bitcast_convert_type(kh, jnp.int32)
    buf, head, drops = append_requests(
        state.req_buf,
        state.req_head,
        state.req_drops,
        rec_mask,
        (
            tick,  # broadcast scalar
            key_lane,
            senders,
            dest,
            own_truth,
            b(misroute),
            reroute,
            b(retried),
            b(multi_ok),
            outcome,
        ),
    )

    def cnt(m):
        return jnp.sum(m & sampled, dtype=jnp.int32)

    # slot order == obs.requests.COUNT_FIELDS
    counts = state.req_counts + jnp.stack(
        [
            cnt(sendable),
            cnt(misroute),
            cnt(reroute_local),
            cnt(reroute_remote),
            cnt(diverged),
            cnt(differ),
            cnt(rejects),
        ]
    )
    return state._replace(
        req_buf=buf,
        req_head=head,
        req_drops=drops,
        req_counts=counts,
        req_tick=tick,
    )
