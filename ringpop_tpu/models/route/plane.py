"""Batched device-resident request-routing plane.

The reference routes one request at a time on the host: hash the key,
look up the owner, forward, and on failure retry after re-looking the
keys up — rerouting when the ring moved, aborting when a multi-key
request's keys now map to more than one owner, rejecting on a
membership-checksum mismatch when ``enforceConsistency``
(lib/request-proxy/send.js:91-208, index.js:168-229; faithfully ported
host-side in api/request_proxy.py).  This module drives **millions of
key lookups per tick** against the live churn-storm membership and
measures those same semantics as device counters:

- a Zipf traffic draw (models/route/traffic.py, threefry on device)
  produces ``Q`` (sender, key) requests per tick,
- each request is routed twice: under the **stale view** (the ring as
  of the previous tick — the sender looked up before this tick's churn
  disseminated) and under the **truth ring** (post-churn).  A
  disagreement is a **misroute**: the retry's re-lookup reroutes it,
  locally when the new owner is the sender itself
  (send.js:190-198) or remotely otherwise (send.js:181-189),
- a ``multi_key_frac`` slice of requests carries a second key; the pair
  rides one envelope only when both keys agreed under the stale view
  (the reference forwards one request per destination group).  If a
  retry fires and the truth ring maps the pair to different owners,
  that is a **keys-diverged abort** (send.js:91-104),
- the request envelope carries the sender's membership checksum; the
  (stale-view) destination compares it with its own — a mismatch bumps
  the **checksums-differ** stat always and **rejects** the request only
  under ``enforce_consistency`` (index.js:186-193).  Mismatch rejects
  are retry triggers, like the reference's retryable checksum errors.

Deviation envelope (vs per-request host emulation): requests are
aggregates, not sessions — one retry round is modeled (stale -> truth),
the stale view is uniformly one tick old rather than per-sender
dissemination age, and per-node checksums come from the scalable
engine's commutative record-mix sums (equal views <=> equal sums), so
counter *rates* are the observable, not per-request traces.  The exact
per-request semantics stay pinned by the host proxy's test suite
(tests/integration/test_proxy.py) whose accounting these counters
mirror one-to-one (see the statsd key map in obs/statsd_bridge.py).

Ring maintenance is the perf headline: ``ring_impl="incremental"``
(the ``"auto"`` resolution everywhere) maintains the hash-prefix-
bucketed ring of models/route/ring_kernel.py — churn re-merges only
dirty buckets, no per-tick sort.  ``ring_impl="full"`` is the bit-exact
full-``jnp.sort`` twin (models/ring/device.py build_ring, the layout
this kernel replaced): same lookups, same metrics, bitwise-identical
materialized ring — the A/B baseline and the equivalence gate
(tests/models/test_route_plane.py; bench.py route phase).
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ringpop_tpu.models.ring import device as ringdev
from ringpop_tpu.models.route import ring_kernel as rk
from ringpop_tpu.models.route import traffic
from ringpop_tpu.models.sim import engine_scalable as es
from ringpop_tpu.models.sim.recovery import CheckpointableMixin, CheckpointSpec


# Latency-histogram track layout (RouteParams.histograms;
# RouteState.hist rows, in order):
# - retry_depth: per routed request, retry rounds taken — 0 (stale
#   owner == truth owner and no checksum reject) or 1 (the modeled
#   single stale->truth retry fired: misroute or consistency reject).
# - reroute_hops: per routed request, forwarding hops — 1 for a direct
#   hit or a local reroute (the retry lands on the sender itself,
#   send.js:190-198), 2 when the retry re-forwarded to a new remote
#   owner (send.js:181-189).
# - dirty_buckets: per tick, the incremental ring update's dirty-bucket
#   count (the re-merge work size) — one observation per tick.
ROUTE_HIST_TRACKS = ("retry_depth", "reroute_hops", "dirty_buckets")


class RouteParams(NamedTuple):
    n: int
    replica_points: int = 16
    # hash-prefix bucket count = 2^bucket_bits; 0 = auto
    # (ring_kernel.default_bucket_bits picks ~192 static points/bucket)
    bucket_bits: int = 0
    queries_per_tick: int = 4096
    key_space: int = 1 << 16
    zipf_s: float = 1.1
    # fraction of requests carrying a second key (keys-diverged plane)
    multi_key_frac: float = 0.125
    enforce_consistency: bool = True
    # "auto" -> "incremental"; "full" = per-tick jnp.sort twin (bitwise
    # A/B baseline); see resolve_ring_impl
    ring_impl: str = "auto"
    # static caps on per-tick incremental work — these ARE the
    # incremental path's cost (D dirty rows are gathered/re-merged
    # whether or not they exist), so they are sized for steady sparse
    # churn; a storm tick beyond either cap falls back to a (sortless)
    # full re-compaction under lax.cond, bit-identically
    max_changed: int = 128
    max_dirty: int = 512
    salt: int = 0x520337
    # Device-side latency histograms (ops/histogram.py; see
    # ROUTE_HIST_TRACKS): per-request retry depth and forwarding hop
    # counts + per-tick dirty-bucket sizes, recorded under the same
    # masks that drive the counters — identical across ring impls (the
    # masks are), write-only (RouteState.hist), off by default.
    histograms: bool = False
    # Sampled per-request trace records (models/route/reqtrace.py; host
    # half obs/requests.py): hash-of-key Bernoulli sampling at rate
    # 2^-req_sample_log2 appends one [RECORD_WIDTH] int32 record per
    # sampled request under the SAME masks that drive the counters.
    # Write-only (req_* RouteState fields), off by default; overflow
    # counts-never-overwrites (req_drops).  req_capacity sizing for a
    # drop-free window: reqtrace.req_capacity_for.
    reqtrace: bool = False
    req_capacity: int = 4096
    req_sample_log2: int = 4
    req_salt: int = 0x7E57A8


class RouteState(NamedTuple):
    # exactly one of (ring, flat_ring) is live, picked by the static
    # ring_impl: the bucketed state (incremental) or the previous tick's
    # flat sorted ring (full twin).  ``mask`` (membership the stale ring
    # reflects) rides inside ``ring`` for the incremental impl and here
    # for the full twin — never both (the scanned driver donates the
    # carry, and an aliased buffer cannot be donated twice).
    ring: Optional[rk.RingState]
    flat_ring: Optional[jax.Array]  # [N*R] uint64
    mask: Optional[jax.Array]  # [N] bool (full impl only)
    rng: jax.Array  # threefry key
    # latency-histogram plane (RouteParams.histograms only, else None):
    # [len(ROUTE_HIST_TRACKS), NBUCKETS] uint32, write-only — NOT part
    # of the checkpointed RouteCarry (telemetry resets on restore)
    hist: Optional[jax.Array] = None
    # sampled request-trace plane (RouteParams.reqtrace only, else
    # None): record buffer + write head + drop counter + sampled-subset
    # counter deltas + the plane's own tick stamp.  Write-only like
    # hist, and like it NOT checkpointed (a resume starts a fresh
    # trace window; req_tick restarts too)
    req_buf: Optional[jax.Array] = None  # [cap, RECORD_WIDTH] int32
    req_head: Optional[jax.Array] = None  # scalar int32
    req_drops: Optional[jax.Array] = None  # scalar int32
    req_counts: Optional[jax.Array] = None  # [len(COUNT_FIELDS)] int32
    req_tick: Optional[jax.Array] = None  # scalar int32


# Single-source field classification (ISSUE 15): trajectory vs obs-only,
# consumed by the noninterference analysis prong exactly like
# engine.SIM_TRAJECTORY_FIELDS (see the note there).  The ring
# representations and the traffic rng ARE trajectory: the route counters
# the gate-equivalence suites compare bitwise derive from them.  A new
# RouteState field MUST land in exactly one set (tier-1 gate:
# tests/analysis/test_state_registry.py).
ROUTE_OBS_ONLY_FIELDS = frozenset(
    {"hist", "req_buf", "req_head", "req_drops", "req_counts", "req_tick"}
)
ROUTE_TRAJECTORY_FIELDS = frozenset({"ring", "flat_ring", "mask", "rng"})


class RouteCarry(NamedTuple):
    """The checkpointed routing-plane carry: everything in
    :class:`RouteState` that is not a pure function of it.  The ring —
    bucketed or flat — is REBUILT from ``mask`` on load (rk.full_rebuild
    / device.build_ring are deterministic functions of (universe, mask)),
    so the checkpoint stays O(N) instead of O(2^B·M) and a resume may
    switch ``ring_impl``/bucket caps freely (those params are
    trajectory-neutral, checkpoint._TRAJECTORY_NEUTRAL_PARAMS)."""

    mask: jax.Array  # [N] bool — membership the stale ring reflects
    rng: jax.Array  # threefry key


class RouteMetrics(NamedTuple):
    """Per-tick routing counters (scalar int32; [T]-stacked under scan).
    Field names are the runlog schema: every ``route_*`` tick field and
    the statsd mapping in obs/statsd_bridge.py derive from here."""

    route_queries: jax.Array  # requests with a live sender and a route
    route_misroutes: jax.Array  # stale owner != truth owner
    route_reroute_local: jax.Array  # retry landed on the sender itself
    route_reroute_remote: jax.Array  # retry rerouted to a new remote owner
    route_keys_diverged: jax.Array  # multi-key retries aborted (>1 owner)
    route_checksums_differ: jax.Array  # envelope checksum != dest checksum
    route_checksum_rejects: jax.Array  # ... and enforce_consistency rejected
    route_ring_changed: jax.Array  # servers whose ring membership flipped
    route_ring_dirty_buckets: jax.Array  # buckets those flips touched
    route_ring_full_rebuilds: jax.Array  # 1 = churn overflowed the caps
    route_ring_points: jax.Array  # active replica points in the truth ring


def resolve_ring_impl(params: RouteParams, backend: str) -> str:
    """Resolve ``ring_impl="auto"`` -> "incremental" on every backend:
    the bucketed update is O(dirty) elementwise everywhere, and "full"
    (the per-tick jnp.sort twin) exists for A/B measurement and the
    bitwise equivalence gate, not as a production choice."""
    if params.ring_impl != "auto":
        if params.ring_impl not in ("full", "incremental"):
            raise ValueError(
                "ring_impl must be auto|full|incremental, got %r"
                % (params.ring_impl,)
            )
        return params.ring_impl
    return "incremental"


def resolve_route_params(params: RouteParams, backend: str) -> RouteParams:
    """Driver-level pin of the trace-time knobs (the storm analog of
    resolve_scalable_params): concrete ring_impl + bucket_bits so the
    shared executable caches key on fully-resolved params."""
    bits = params.bucket_bits
    if bits == 0:
        bits = rk.default_bucket_bits(params.n, params.replica_points)
    return params._replace(
        ring_impl=resolve_ring_impl(params, backend), bucket_bits=bits
    )


def init_route_state(
    params: RouteParams,
    buckets: rk.RingBuckets,
    reps: jax.Array,
    in_ring: jax.Array,
    seed: int = 0,
) -> RouteState:
    impl = resolve_ring_impl(params, jax.default_backend())
    rng = jax.random.PRNGKey(np.uint32(seed) ^ np.uint32(params.salt))
    hist = None
    if params.histograms:
        from ringpop_tpu.ops import histogram as hg

        hist = hg.init(len(ROUTE_HIST_TRACKS))
    req = _init_reqtrace(params)
    if impl == "incremental":
        return RouteState(
            ring=rk.full_rebuild(buckets, in_ring),
            flat_ring=None,
            mask=None,
            rng=rng,
            hist=hist,
            **req,
        )
    return RouteState(
        ring=None,
        flat_ring=ringdev.build_ring(reps, in_ring),
        mask=in_ring,
        rng=rng,
        hist=hist,
        **req,
    )


def _init_reqtrace(params: RouteParams) -> dict:
    """Fresh request-trace plane fields (empty dict when off)."""
    if not params.reqtrace:
        return {}
    from ringpop_tpu.models.route import reqtrace as rt

    buf, head, drops, counts, tick = rt.init_reqtrace_fields(
        params.req_capacity
    )
    return dict(
        req_buf=buf,
        req_head=head,
        req_drops=drops,
        req_counts=counts,
        req_tick=tick,
    )


def route_tick(
    state: RouteState,
    buckets: rk.RingBuckets,
    reps: jax.Array,
    cdf: jax.Array,
    in_ring: jax.Array,
    proc_alive: jax.Array,
    checksums: jax.Array,
    params: RouteParams,
) -> Tuple[RouteState, RouteMetrics]:
    """One routing tick: refresh the truth ring from ``in_ring``
    (incrementally or via the sort twin), route Q Zipf requests under
    the stale + truth views, and count the send.js/index.js semantics.
    Bitwise-identical metrics across ``ring_impl`` settings (the gate)."""
    impl = resolve_ring_impl(params, jax.default_backend())
    n = params.n
    q = params.queries_per_tick
    r = params.replica_points
    k_send, k_key1, k_key2, k_multi, rng_next = jax.random.split(
        state.rng, 5
    )

    prev_mask = state.ring.mask if impl == "incremental" else state.mask
    if impl == "incremental":
        # the update's OWN dirty stats feed the metrics: the reported
        # route_ring_* numbers are by construction what the kernel did
        truth_ring, n_changed, n_dirty, full_rebuilds = rk.update(
            buckets,
            state.ring,
            in_ring,
            max_changed=params.max_changed,
            max_dirty=params.max_dirty,
        )

        def lookup_stale(kh):
            return rk.lookup(state.ring, kh)

        def lookup_truth(kh):
            return rk.lookup(truth_ring, kh)

        ring_points = truth_ring.n_points
        new_state = RouteState(
            ring=truth_ring,
            flat_ring=None,
            mask=None,
            rng=rng_next,
            hist=state.hist,
        )
    else:  # "full": the per-tick jnp.sort twin
        # same stats the incremental path WOULD report (shared helper,
        # same caps) so the two impls' RouteMetrics stay bit-identical
        n_changed, _dirty, n_dirty, overflow = rk.dirty_stats(
            buckets, in_ring != prev_mask, params.max_changed,
            params.max_dirty,
        )
        full_rebuilds = overflow.astype(jnp.int32)
        stale_flat = state.flat_ring
        stale_points = ringdev.ring_size(prev_mask, r)
        truth_flat = ringdev.build_ring(reps, in_ring)
        ring_points = ringdev.ring_size(in_ring, r)

        def lookup_stale(kh):
            return ringdev.lookup(stale_flat, stale_points, kh)

        def lookup_truth(kh):
            return ringdev.lookup(truth_flat, ring_points, kh)

        new_state = RouteState(
            ring=None,
            flat_ring=truth_flat,
            mask=in_ring,
            rng=rng_next,
            hist=state.hist,
        )
    if params.reqtrace:
        # the request-trace plane rides the carry unchanged until the
        # end-of-tick emission below
        new_state = new_state._replace(
            req_buf=state.req_buf,
            req_head=state.req_head,
            req_drops=state.req_drops,
            req_counts=state.req_counts,
            req_tick=state.req_tick,
        )

    # -- traffic ---------------------------------------------------------
    senders = jax.random.randint(k_send, (q,), 0, n, dtype=jnp.int32)
    kh1 = traffic.key_hashes(traffic.sample_keys(k_key1, cdf, q))
    own1_stale = lookup_stale(kh1)
    own1_truth = lookup_truth(kh1)
    # a request exists when its sender process is up and the stale view
    # had an owner to send to
    sendable = proc_alive[senders] & (own1_stale >= 0)

    # -- misroute + retry reroute (send.js:91-208) -----------------------
    misroute = sendable & (own1_truth != own1_stale)
    reroute_local = misroute & (own1_truth == senders)
    reroute_remote = misroute & (own1_truth != senders) & (own1_truth >= 0)

    # -- checksum plane (index.js:168-229) -------------------------------
    dest = jnp.clip(own1_stale, 0, n - 1)
    differ = sendable & (checksums[senders] != checksums[dest])
    rejects = differ if params.enforce_consistency else jnp.zeros(q, bool)

    # -- keys-diverged (send.js:91-104) ----------------------------------
    kh2 = traffic.key_hashes(traffic.sample_keys(k_key2, cdf, q))
    own2_stale = lookup_stale(kh2)
    own2_truth = lookup_truth(kh2)
    is_multi = (
        jax.random.uniform(k_multi, (q,), dtype=jnp.float32)
        < jnp.float32(params.multi_key_frac)
    )
    # the pair rode one envelope only if both keys agreed at send time
    multi_ok = is_multi & sendable & (own2_stale == own1_stale)
    retried = misroute | rejects
    diverged = multi_ok & retried & (own1_truth != own2_truth)

    def cnt(mask):
        return jnp.sum(mask, dtype=jnp.int32)

    # -- latency histograms (opt-in; write-only; identical across ring
    # impls because every mask above is) --------------------------------
    if params.histograms and state.hist is not None:
        from ringpop_tpu.ops import histogram as hg

        hist = state.hist
        depth = retried.astype(jnp.int32)
        hist = hg.record(
            hist, ROUTE_HIST_TRACKS.index("retry_depth"), depth, sendable
        )
        hops = jnp.int32(1) + reroute_remote.astype(jnp.int32)
        hist = hg.record(
            hist, ROUTE_HIST_TRACKS.index("reroute_hops"), hops, sendable
        )
        hist = hg.record_count(
            hist, ROUTE_HIST_TRACKS.index("dirty_buckets"), n_dirty
        )
        new_state = new_state._replace(hist=hist)

    # -- sampled per-request trace records (opt-in; write-only; the
    # record mask is sendable & hash-of-key sampled — a pure function
    # of the same masks the counters sum, so identical across ring
    # impls) -------------------------------------------------------------
    if params.reqtrace and state.req_buf is not None:
        from ringpop_tpu.models.route import reqtrace as rt

        new_state = rt.record_tick_requests(
            new_state,
            params,
            kh=kh1,
            senders=senders,
            dest=dest,
            own_truth=own1_truth,
            sendable=sendable,
            misroute=misroute,
            reroute_local=reroute_local,
            reroute_remote=reroute_remote,
            differ=differ,
            rejects=rejects,
            multi_ok=multi_ok,
            diverged=diverged,
            retried=retried,
        )

    return new_state, RouteMetrics(
        route_queries=cnt(sendable),
        route_misroutes=cnt(misroute),
        route_reroute_local=cnt(reroute_local),
        route_reroute_remote=cnt(reroute_remote),
        route_keys_diverged=cnt(diverged),
        route_checksums_differ=cnt(differ),
        route_checksum_rejects=cnt(rejects),
        route_ring_changed=n_changed,
        route_ring_dirty_buckets=n_dirty,
        route_ring_full_rebuilds=full_rebuilds,
        route_ring_points=ring_points.astype(jnp.int32),
    )


def in_ring_mask(state: es.ScalableState) -> jax.Array:
    """Ring membership from scalable-engine truth: alive + suspect
    servers stay in the ring (on_membership_event.js:106-134)."""
    return state.proc_alive & (state.truth_status <= es.SUSPECT)


@functools.lru_cache(maxsize=None)
def _routed_fns(es_params: es.ScalableParams, route_params: RouteParams):
    """Shared compiled executables for the coupled membership+routing
    tick, keyed by the (fully-resolved) param pair — the storm driver's
    caching discipline (storm._tick_fn/_scanned_fn)."""

    def _body(carry, inp, buckets, reps, cdf):
        est, rst = carry
        est, em = es.tick(est, inp, es_params)
        rst, rm = route_tick(
            rst,
            buckets,
            reps,
            cdf,
            in_ring_mask(est),
            est.proc_alive,
            est.checksum,
            route_params,
        )
        return (est, rst), (em, rm)

    # donation backend-gated like the storm driver's (CPU warm-cache
    # executables mis-execute donation — storm.donate_state_argnums)
    from ringpop_tpu.models.sim.storm import donate_state_argnums

    @functools.partial(jax.jit, donate_argnums=donate_state_argnums())
    def _tick(carry, inputs, buckets, reps, cdf):
        return _body(carry, inputs, buckets, reps, cdf)

    @functools.partial(jax.jit, donate_argnums=donate_state_argnums())
    def _scanned(carry, inputs, buckets, reps, cdf):
        def body(c, inp):
            return _body(c, inp, buckets, reps, cdf)

        return jax.lax.scan(body, carry, inputs)

    return _tick, _scanned


def clear_executable_cache() -> None:
    """Drop the shared compiled executables (storm.clear_executable_cache
    analog for the routed driver)."""
    _routed_fns.cache_clear()


class RoutedStorm(CheckpointableMixin):
    """ScalableCluster + routing plane under one scanned program.

    Wraps a :class:`~ringpop_tpu.models.sim.storm.ScalableCluster` and
    threads the route state through the same ``lax.scan``: every
    membership tick is followed by a routing tick against the
    just-updated truth (current ring) and the pre-tick view (stale
    ring).  Metrics come back as ``(ScalableMetrics, RouteMetrics)``
    stacks; an attached obs.RunRecorder receives them as ONE row stream
    (tick rows carry both the sim and the ``route_*`` fields — the
    schema scripts/check_metrics_schema.py validates).

    DONATION CAVEAT: like ScalableCluster, step()/run() donate the
    carried state — snapshot before stepping for before/after views."""

    def __init__(
        self,
        n: int,
        params: Optional[es.ScalableParams] = None,
        route: Optional[RouteParams] = None,
        replica_points: int = 16,
        seed: int = 0,
    ):
        from ringpop_tpu.models.sim.storm import ScalableCluster

        self.cluster = ScalableCluster(
            n=n,
            params=params,
            replica_points=replica_points,
            seed=seed,
        )
        if not self.cluster.params.checksum_in_tick:
            raise ValueError(
                "the routing plane's checksum counters read the in-tick "
                "checksums — construct with checksum_in_tick=True"
            )
        route = route or RouteParams(n=n, replica_points=replica_points)
        if route.n != n or route.replica_points != replica_points:
            route = route._replace(n=n, replica_points=replica_points)
        self.route_params = resolve_route_params(
            route, jax.default_backend()
        )
        reps_np = np.asarray(
            ringdev.device_replica_hashes(n, replica_points)
        )
        self.buckets = rk.build_buckets(
            reps_np, self.route_params.bucket_bits
        )
        self.reps = jnp.asarray(reps_np)
        self.cdf = traffic.zipf_cdf(
            self.route_params.key_space, self.route_params.zipf_s
        )
        self.rstate = init_route_state(
            self.route_params,
            self.buckets,
            self.reps,
            in_ring_mask(self.cluster.state),
            seed=seed,
        )
        self._tick, self._scanned = _routed_fns(
            self.cluster.params, self.route_params
        )
        self.recorder = None

    # -- driving ----------------------------------------------------------

    def step(self, inputs: Optional[es.ChurnInputs] = None):
        if inputs is None:
            inputs = es.ChurnInputs.quiet(self.route_params.n)
        carry, (em, rm) = self._tick(
            (self.cluster.state, self.rstate),
            inputs,
            self.buckets,
            self.reps,
            self.cdf,
        )
        self.cluster.state, self.rstate = carry
        em = jax.tree.map(np.asarray, em)
        rm = jax.tree.map(np.asarray, rm)
        self._record(em, rm)
        self._after_ticks(1)
        return em, rm

    def run(self, schedule):
        """With a checkpoint cadence enabled the scan splits at cadence
        boundaries — trajectory- and metrics-bitwise-neutral."""
        return self._run_chunked(schedule, self._run_window)

    def _run_window(self, schedule):
        carry, (em, rm) = self._scanned(
            (self.cluster.state, self.rstate),
            schedule.as_inputs(),
            self.buckets,
            self.reps,
            self.cdf,
        )
        self.cluster.state, self.rstate = carry
        em = jax.tree.map(np.asarray, em)
        rm = jax.tree.map(np.asarray, rm)
        self._record(em, rm)
        return em, rm

    # -- telemetry --------------------------------------------------------

    def attach_recorder(self, recorder) -> None:
        recorder.describe(
            "sim.engine_scalable+route",
            self.route_params.n,
            self.cluster.params,
            route_params=self.route_params._asdict(),
        )
        self.recorder = recorder

    def _record(self, em, rm) -> None:
        if self.recorder is None:
            return
        rows = dict(em._asdict())
        rows.update(rm._asdict())
        self.recorder.record_ticks(rows)

    def drain_histograms(self, reset: bool = True, statsd=None):
        """Drain BOTH histogram planes — the routing plane's
        (RouteState.hist: retry depth / hops / dirty buckets) and the
        membership engine's (ScalableState.hist, when on) — into
        ``{"route": ..., "sim": ...}`` summaries.  One ``hist.drain``
        event row per present source on the attached recorder; ``statsd``
        (a StatsdBridge) additionally emits the percentiles as timer
        keys (requestProxy.retry.depth / requestProxy.hops / ...)."""
        from ringpop_tpu.obs import histograms as oh

        if self.rstate.hist is None and self.cluster.state.hist is None:
            raise ValueError(
                "histograms are off — construct with "
                "RouteParams(histograms=True) and/or "
                "ScalableParams(histograms=True)"
            )
        out = {}
        if self.rstate.hist is not None:
            out["route"] = oh.drain(
                self.rstate.hist,
                ROUTE_HIST_TRACKS,
                "route",
                recorder=self.recorder,
                statsd=statsd,
            )
            if reset:
                from ringpop_tpu.ops import histogram as hg

                self.rstate = self.rstate._replace(
                    hist=hg.init(len(ROUTE_HIST_TRACKS))
                )
        if self.cluster.state.hist is not None:
            # the engine half emits against the STORM recorder (the
            # inner cluster's is usually unset — RoutedStorm owns the log)
            out["sim"] = oh.drain(
                self.cluster.state.hist,
                es.SCALABLE_HIST_TRACKS,
                "sim.engine_scalable",
                recorder=self.recorder,
                statsd=statsd,
            )
            if reset:
                from ringpop_tpu.ops import histogram as hg

                self.cluster.state = self.cluster.state._replace(
                    hist=hg.init(len(es.SCALABLE_HIST_TRACKS))
                )
        return out

    def drain_requests(self, reset: bool = True, statsd=None):
        """Drain the sampled request-trace plane: decode the window's
        records, log ONE ``reqtrace.drain`` event row on the attached
        recorder, emit the sampled counters through ``statsd`` (a
        StatsdBridge).  Returns the obs.requests.drain dict (records +
        counts + drop honesty).  ``reset=True`` zeroes the buffer and
        counters for the next window — the plane's tick stamp keeps
        running, so records stay monotone across windows."""
        from ringpop_tpu.obs import requests as oreq

        if self.rstate.req_buf is None:
            raise ValueError(
                "request tracing is off — construct with "
                "RouteParams(reqtrace=True)"
            )
        out = oreq.drain(
            self.rstate.req_buf,
            self.rstate.req_head,
            self.rstate.req_drops,
            self.rstate.req_counts,
            sample_log2=self.route_params.req_sample_log2,
            source="route",
            recorder=self.recorder,
            statsd=statsd,
        )
        if reset:
            from ringpop_tpu.models.route import reqtrace as rt

            buf, head, drops, counts, _ = rt.init_reqtrace_fields(
                self.route_params.req_capacity
            )
            self.rstate = self.rstate._replace(
                req_buf=buf,
                req_head=head,
                req_drops=drops,
                req_counts=counts,
            )
        return out

    # -- inspection -------------------------------------------------------

    def truth_ring(self) -> jax.Array:
        """The flat sorted truth ring (materialized from the bucketed
        state under "incremental") — the bitwise A/B gate surface."""
        if self.route_params.ring_impl == "incremental":
            return rk.materialize(
                self.rstate.ring,
                self.route_params.n * self.route_params.replica_points,
            )
        return self.rstate.flat_ring

    def ring_checksum(self) -> int:
        return int(ringdev.ring_checksum(self.truth_ring()))

    # -- checkpoint/resume (models/sim/recovery.py) -----------------------
    # Two named states per checkpoint: "sim" (the scalable engine state,
    # node fields shardable) and "route" (the RouteCarry — stale-ring
    # membership mask + traffic rng).  On load the bucketed (or flat)
    # ring is rebuilt from the restored mask, bit-identically to the
    # incrementally-maintained one (tests/models/test_route_plane.py
    # roundtrip + the crash-resume gate).

    def _route_carry(self) -> RouteCarry:
        mask = (
            self.rstate.ring.mask
            if self.route_params.ring_impl == "incremental"
            else self.rstate.mask
        )
        return RouteCarry(mask=mask, rng=self.rstate.rng)

    def _rebuild_route_state(self, carry: RouteCarry) -> RouteState:
        mask = jnp.asarray(carry.mask)
        rng = jnp.asarray(carry.rng)
        hist = None
        if self.route_params.histograms:
            # telemetry, not trajectory: a restore starts fresh counters
            from ringpop_tpu.ops import histogram as hg

            hist = hg.init(len(ROUTE_HIST_TRACKS))
        # the request-trace plane is telemetry too: a resume starts a
        # fresh window (and tick stamp) under the CURRENT params
        req = _init_reqtrace(self.route_params)
        if self.route_params.ring_impl == "incremental":
            return RouteState(
                ring=rk.full_rebuild(self.buckets, mask),
                flat_ring=None,
                mask=None,
                rng=rng,
                hist=hist,
                **req,
            )
        return RouteState(
            ring=None,
            flat_ring=ringdev.build_ring(self.reps, mask),
            mask=mask,
            rng=rng,
            hist=hist,
            **req,
        )

    def _ckpt_spec(self) -> CheckpointSpec:
        return CheckpointSpec(
            state_cls={"sim": es.ScalableState, "route": RouteCarry},
            params={"sim": self.cluster.params, "route": self.route_params},
            sharded_fields={
                "sim": es.NODE_SHARDED_FIELDS,
                "route": frozenset({"mask"}),
            },
        )

    def _ckpt_states(self):
        return {"sim": self.cluster.state, "route": self._route_carry()}

    def _ckpt_install(self, states) -> None:
        from ringpop_tpu.models.sim.storm import fixup_scalable_state

        self.cluster.state = fixup_scalable_state(
            states["sim"], self.cluster.params
        )
        self.rstate = self._rebuild_route_state(states["route"])

    def save(self, path: str, shards: int = 1) -> None:
        """Manifest-format checkpoint directory at ``path``."""
        from ringpop_tpu.models.sim import checkpoint as ckpt

        spec = self._ckpt_spec()
        ckpt.save_checkpoint(
            path,
            self._ckpt_states(),
            spec.params,
            shards=shards,
            sharded_fields=spec.sharded_fields,
        )

    def load(self, path: str) -> None:
        from ringpop_tpu.models.sim import checkpoint as ckpt

        spec = self._ckpt_spec()
        self._ckpt_install(
            ckpt.load_checkpoint(path, spec.state_cls, spec.params)
        )
