"""Protocol models: membership state machine, consistent hash ring, gossip
engine, and the batched cluster simulator."""
