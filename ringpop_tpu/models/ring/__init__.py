from ringpop_tpu.models.ring.host import HashRing

__all__ = ["HashRing"]
