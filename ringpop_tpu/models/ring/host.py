"""Host-side consistent hash ring.

Same semantics as the reference's HashRing (/root/reference/lib/ring/index.js):
100 replica points per server hashed as ``hash32(server + str(i))``
(index.js:50-58), ``lookup`` = first ring point whose hash is >= the key's
hash with wraparound to the minimum (index.js:145-154 — note the rbtree's
``upperBound`` is, despite its name, a lower bound: rbtree.js:235-271, which
ring-test.js's '1000 lookups' depends on), ``lookupN`` walks unique
successors with a full-cycle corruption guard (index.js:157-189), and the
ring checksum is ``hash32`` of the sorted server names joined with ';'
(index.js:96-105).

TPU-first re-design: the reference's red-black tree exists solely to provide
ordered search plus in-order iteration; here the ring is a sorted numpy table
of (point hash, owner) and lookups are ``np.searchsorted`` — the same layout
the device ring (models/ring/device.py) uses, so host and device agree
structurally and numerically.  Where rbtree iteration order among *colliding*
replica points depends on insertion order, this ring orders collisions by
(hash, server name) — deterministic, history-independent, and identical to
the device ring's (hash, universe-index) order because the device universe is
address-sorted.  Each public mutation rebuilds the table with one
O(P log P) lexsort (P = servers x replica points); bulk add/remove pays a
single rebuild, mirroring the reference's one-checksum-per-bulk-change.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from ringpop_tpu.ops import native
from ringpop_tpu.utils.config import EventEmitter


class HashRing(EventEmitter):
    def __init__(self, replica_points: int = 100, hash_func=None):
        super().__init__()
        self.replica_points = replica_points
        self.hash_func = hash_func or native.hash32
        self._use_native_replicas = hash_func is None

        self.servers: Dict[str, bool] = {}
        self.checksum: Optional[int] = None
        # per-server replica hashes, keyed by name (uint32 [R])
        self._server_points: Dict[str, np.ndarray] = {}
        # sorted ring table (by hash, ties by server name); owners stored as
        # ranks into the sorted name list, rebuilt LAZILY on first lookup —
        # a burst of N individual add/remove calls (the reference pays an
        # rbtree insert each; we'd pay N full sorts) costs one sort total
        self._hashes = np.empty(0, dtype=np.uint64)
        self._owner_ranks = np.empty(0, dtype=np.int64)
        self._names: List[str] = []
        self._table_dirty = False

    # -- construction -----------------------------------------------------

    def _replica_hashes(self, server: str) -> np.ndarray:
        if self._use_native_replicas:
            return native.replica_hashes(server, self.replica_points)
        return np.array(
            [self.hash_func(server + str(i)) for i in range(self.replica_points)],
            dtype=np.uint64,
        )

    def _rebuild(self) -> None:
        self._table_dirty = False
        if not self._server_points:
            self._hashes = np.empty(0, dtype=np.uint64)
            self._owner_ranks = np.empty(0, dtype=np.int64)
            self._names = []
            return
        names = sorted(self._server_points.keys())
        hashes = np.concatenate([self._server_points[n] for n in names]).astype(
            np.uint64
        )
        owner_rank = np.repeat(np.arange(len(names)), self.replica_points)
        order = np.lexsort((owner_rank, hashes))
        self._hashes = hashes[order]
        self._owner_ranks = owner_rank[order]
        self._names = names

    def _ensure_table(self) -> None:
        if self._table_dirty:
            self._rebuild()

    def add_server(self, name: str) -> None:
        if self.has_server(name):
            return
        self.servers[name] = True
        self._server_points[name] = self._replica_hashes(name)
        self._table_dirty = True
        self.compute_checksum()
        self.emit("added", name)

    def remove_server(self, name: str) -> None:
        if not self.has_server(name):
            return
        del self.servers[name]
        del self._server_points[name]
        self._table_dirty = True
        self.compute_checksum()
        self.emit("removed", name)

    def add_remove_servers(
        self,
        servers_to_add: Optional[Sequence[str]] = None,
        servers_to_remove: Optional[Sequence[str]] = None,
    ) -> bool:
        servers_to_add = servers_to_add or []
        servers_to_remove = servers_to_remove or []
        added = False
        removed = False
        for s in servers_to_add:
            if not self.has_server(s):
                self.servers[s] = True
                self._server_points[s] = self._replica_hashes(s)
                added = True
        for s in servers_to_remove:
            if self.has_server(s):
                del self.servers[s]
                del self._server_points[s]
                removed = True
        changed = added or removed
        if changed:
            self._table_dirty = True
            self.compute_checksum()
        return changed

    # -- checksum ---------------------------------------------------------

    def compute_checksum(self) -> int:
        server_name_str = ";".join(sorted(self.servers.keys()))
        self.checksum = self.hash_func(server_name_str)
        # blob payload for the ring.checksum.computed trace tap
        self.emit(
            "checksumComputed",
            {"checksum": self.checksum, "serverCount": len(self.servers)},
        )
        return self.checksum

    # -- queries ----------------------------------------------------------

    def has_server(self, name: str) -> bool:
        return name in self.servers

    def get_server_count(self) -> int:
        return len(self.servers)

    def get_stats(self) -> dict:
        return {"checksum": self.checksum, "servers": list(self.servers.keys())}

    def _lower_bound(self, h: int) -> int:
        """Index of the first ring point with hash >= h (== size if none)."""
        return int(np.searchsorted(self._hashes, h, side="left"))

    def lookup(self, key) -> Optional[str]:
        self._ensure_table()
        if self._hashes.size == 0:
            return None
        h = self.hash_func(str(key))
        idx = self._lower_bound(h)
        if idx == self._hashes.size:
            idx = 0  # wraparound to min()
        return self._names[self._owner_ranks[idx]]

    def lookup_n(self, key, n: int) -> List[str]:
        """Up to ``n`` unique successor servers — ring/index.js:157-189."""
        self._ensure_table()
        server_count = self.get_server_count()
        n = min(n, server_count)
        if n <= 0 or self._hashes.size == 0:
            return []
        h = self.hash_func(str(key))
        start = self._lower_bound(h)
        result: List[str] = []
        seen = set()
        size = self._hashes.size
        # full-cycle guard mirrors the reference's firstVal check
        for step in range(size):
            name = self._names[self._owner_ranks[(start + step) % size]]
            if name not in seen:
                seen.add(name)
                result.append(name)
                if len(result) >= n:
                    break
        return result

    # -- device handoff ---------------------------------------------------

    def table(self):
        """The sorted (hash, owner-name) table — the layout the device ring
        consumes (models/ring/device.py)."""
        self._ensure_table()
        return (
            self._hashes.astype(np.uint32),
            [self._names[r] for r in self._owner_ranks],
        )
