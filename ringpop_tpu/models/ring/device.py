"""In-jit consistent hash ring over a static node universe.

The reference rebuilds its rbtree ring by inserting/removing 100 replica
points per server on every membership change (lib/ring/index.js:50-58,
135-143).  On TPU the ring is data, not a tree: the universe's replica-point
hashes are precomputed once ([N, R] uint32, host-side via the native FarmHash
oracle), and a "rebuild" under churn is a masked sort — active servers'
points get keys ``(hash << 32) | owner``, inactive ones get the +inf
sentinel, one ``jnp.sort`` yields the ring table.  ``lookup`` is
``searchsorted`` (the rbtree's upperBound with wraparound,
ring/index.js:145-154); ``lookup_n`` is a bounded successor walk collecting
unique owners (ring/index.js:157-189).

Everything here is shape-static and jit/vmap/shard_map-friendly; the ring
rebuild for every node's *own view* of the cluster is just a vmap over the
member mask axis.

Collision order: where the reference's rbtree breaks replica-point hash
ties by insertion order, both rings here order collisions by the full
``(hash, owner)`` key — the host ring's ``(hash, server name)`` lexsort
and this module's ``(hash << 32) | owner`` uint64 sort coincide because
the device universe is address-sorted (universe index == sorted-name
rank).  Deterministic, history-independent, and pinned bit-for-bit by
the host/device property test (tests/models/test_ring_parity.py).

This module is the ONE home of the ring kernels: the scalable storm
driver (models/sim/storm.py) and the incremental routing plane
(models/route/ring_kernel.py) import ``build_ring`` /
``device_replica_hashes`` / ``ring_checksum`` from here rather than
keeping copies.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ringpop_tpu.ops import native
from ringpop_tpu.ops.record_mix import record_mix

SENTINEL = np.uint64(0xFFFFFFFFFFFFFFFF)  # numpy: import stays device-free


def replica_table(addresses, replica_points: int = 100) -> np.ndarray:
    """Precompute [N, R] uint32 replica-point hashes hash32(addr + str(i))
    for the static universe (host-side, once per run)."""
    return np.stack(
        [native.replica_hashes(a, replica_points) for a in addresses]
    ).astype(np.uint32)


def build_ring(replica_hashes: jax.Array, mask: jax.Array) -> jax.Array:
    """Sorted uint64 key table of the active ring.

    ``replica_hashes``: [N, R] uint32; ``mask``: [N] bool (server in ring).
    Returns [N*R] uint64 keys ``(hash << 32) | owner`` with inactive entries
    pushed to the end as the all-ones sentinel.
    """
    n, r = replica_hashes.shape
    owners = jnp.broadcast_to(jnp.arange(n, dtype=jnp.uint64)[:, None], (n, r))
    keys = (replica_hashes.astype(jnp.uint64) << jnp.uint64(32)) | owners
    keys = jnp.where(mask[:, None], keys, SENTINEL)
    return jnp.sort(keys.reshape(-1))


def device_replica_hashes(n: int, replica_points: int) -> jax.Array:
    """[N, R] uint32 replica-point hashes from integer node ids (in-jit).

    The scale analog of :func:`replica_table`: no address-string universe
    at 100k-1M nodes, so replica points hash the integer node id instead
    of ``addr + str(i)`` (models/sim/storm.py's ring)."""
    ids = jnp.arange(n, dtype=jnp.int32)[:, None]
    reps = jnp.arange(replica_points, dtype=jnp.int32)[None, :]
    return record_mix(ids, reps, jnp.int64(0x5EED))


def ring_checksum(ring: jax.Array) -> jax.Array:
    """Order-sensitive uint32 digest of a ring table (the scale analog of
    hash32 over sorted server names, lib/ring/index.js:96-105)."""
    x = (ring & jnp.uint64(0xFFFFFFFF)).astype(jnp.uint32)
    y = (ring >> jnp.uint64(32)).astype(jnp.uint32)
    pos = jnp.arange(ring.shape[0], dtype=jnp.uint32)
    mixed = record_mix(pos, x, y.astype(jnp.int64))
    return jnp.sum(mixed, dtype=jnp.uint32)


def ring_size(mask: jax.Array, replica_points: int) -> jax.Array:
    return mask.sum().astype(jnp.int32) * replica_points


def _upper_bound(ring: jax.Array, key_hash: jax.Array) -> jax.Array:
    """First index with point hash >= key_hash.

    The reference rbtree's ``upperBound`` is, despite the name, a lower
    bound (rbtree.js:235-271); lookups that hit a replica point exactly
    return that point's owner.
    """
    query = key_hash.astype(jnp.uint64) << jnp.uint64(32)
    return jnp.searchsorted(ring, query).astype(jnp.int32)


def lookup(ring: jax.Array, n_points: jax.Array, key_hash: jax.Array) -> jax.Array:
    """Owner index for ``key_hash`` (int32; -1 when the ring is empty)."""
    idx = _upper_bound(ring, key_hash)
    idx = jnp.where(idx >= n_points, 0, idx)  # wraparound to min()
    owner = (ring[idx] & jnp.uint64(0xFFFFFFFF)).astype(jnp.int32)
    return jnp.where(n_points > 0, owner, -1)


def lookup_n(
    ring: jax.Array,
    n_points: jax.Array,
    key_hash: jax.Array,
    n: int,
) -> jax.Array:
    """Up to ``n`` unique successor owners (int32, -1 padded).

    Exact semantics of the reference's successor walk with full-cycle guard
    (ring/index.js:157-189): a ``while_loop`` advances until ``n`` unique
    owners are collected or every ring point has been visited — the trip
    count is data-dependent but bounded by ``n_points``, which XLA handles
    natively (no static over/under-estimate, no silent -1 holes).
    """
    start = _upper_bound(ring, key_hash)
    found = jnp.full((n,), -1, jnp.int32)

    def cond(state):
        _, count, step = state
        return (count < n) & (step < n_points)

    def body(state):
        found, count, step = state
        idx = (start + step) % jnp.maximum(n_points, 1)
        owner = (ring[idx] & jnp.uint64(0xFFFFFFFF)).astype(jnp.int32)
        is_new = jnp.all(found != owner)
        found = jnp.where(
            is_new, found.at[jnp.clip(count, 0, n - 1)].set(owner), found
        )
        count = count + is_new.astype(jnp.int32)
        return found, count, step + 1

    found, _, _ = jax.lax.while_loop(
        cond, body, (found, jnp.int32(0), jnp.int32(0))
    )
    return jnp.where(n_points > 0, found, -1)
