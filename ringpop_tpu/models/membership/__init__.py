from ringpop_tpu.models.membership.host import (
    Member,
    Membership,
    MembershipIterator,
    Status,
    Update,
    LeaveUpdate,
    merge_membership_changesets,
)

__all__ = [
    "Member",
    "Membership",
    "MembershipIterator",
    "Status",
    "Update",
    "LeaveUpdate",
    "merge_membership_changesets",
]
