"""Host-side SWIM membership state machine.

The reference's member/membership pair (/root/reference/lib/membership/
member.js, index.js) rebuilt in Python.  This is the *control-plane* model —
one real Ringpop node's membership list — and also the per-node parity oracle
the batched device simulator is lockstep-tested against
(ringpop_tpu/parity/oracle.py, tests/parity/).

Semantics preserved exactly:
- SWIM update precedence (member.js:171-202): alive/suspect/faulty/leave ×
  incarnation-number comparison, including the leave quirks (nothing but a
  newer alive — or first leave — overrides leave).
- Local override (member.js:155-169): a node told it is suspect/faulty
  refutes by re-asserting alive with a fresh incarnation number.
- Checksum string ``addr+status+incarnation`` sorted by address, joined ';'
  (index.js:100-123), hashed with FarmHash32.
- Stashed pre-ready updates applied atomically by ``set()`` (index.js:208-247)
  with merged changesets and members appended (not random-positioned).
- New members inserted at a random join position (index.js:285,129-131).
- Flap-damping scores: +penalty per update, exponential decay
  (member.js:45-66,133-153) — with the decay timer driven by the host clock.
"""

from __future__ import annotations

import math
import random
import threading
import time
import uuid
from typing import Any, Callable, Dict, List, Optional, Sequence, Union

from ringpop_tpu.ops import native
from ringpop_tpu.utils.config import EventEmitter


class Status:
    alive = "alive"
    faulty = "faulty"
    leave = "leave"
    suspect = "suspect"

    ALL = ("alive", "faulty", "leave", "suspect")


def _now_ms() -> int:
    return int(time.time() * 1000)


class Update:
    """Change record (lib/membership/update.js:26-40)."""

    def __init__(
        self,
        address: str,
        incarnation_number: Optional[int],
        status: str,
        local_member: Optional["Member"] = None,
        source: Optional[str] = None,
        source_incarnation_number: Optional[int] = None,
        id: Optional[str] = None,
        timestamp: Optional[int] = None,
        now: Callable[[], int] = _now_ms,
    ):
        self.address = address
        self.incarnation_number = incarnation_number
        self.status = status
        self.id = id or str(uuid.uuid4())
        if local_member is not None:
            self.source = local_member.address
            self.source_incarnation_number = local_member.incarnation_number
        else:
            self.source = source
            self.source_incarnation_number = source_incarnation_number
        self.timestamp = timestamp if timestamp is not None else now()

    def to_dict(self) -> Dict[str, Any]:
        return {
            "id": self.id,
            "source": self.source,
            "sourceIncarnationNumber": self.source_incarnation_number,
            "address": self.address,
            "status": self.status,
            "incarnationNumber": self.incarnation_number,
        }

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "Update":
        return Update(
            address=d.get("address"),
            incarnation_number=d.get("incarnationNumber"),
            status=d.get("status"),
            source=d.get("source"),
            source_incarnation_number=d.get("sourceIncarnationNumber"),
            id=d.get("id"),
            timestamp=d.get("timestamp"),
        )


class LeaveUpdate(Update):
    def __init__(self, address, incarnation_number, local_member=None, **kw):
        super().__init__(address, incarnation_number, Status.leave, local_member, **kw)


class Member(EventEmitter):
    """Per-member state + the SWIM precedence rules (member.js)."""

    Status = Status

    def __init__(self, ringpop: Any, update: Update):
        super().__init__()
        self.ringpop = ringpop
        self.id = update.address
        self.address = update.address
        self.status = update.status
        self.incarnation_number = update.incarnation_number
        initial = ringpop.config.get("dampScoringInitial")
        damp = getattr(update, "damp_score", None)
        self.damp_score = damp if isinstance(damp, (int, float)) else initial
        self.damped_timestamp = getattr(update, "damped_timestamp", None)
        self.last_update_timestamp: Optional[int] = None
        self.last_update_damp_score = self.damp_score
        # True once the score crossed dampScoringSuppressLimit; cleared (with
        # a 'suppressRecovered' event) when decay brings it back below
        # dampScoringReuseLimit — the recovery side of the reference's
        # planned flap-damping subprotocol (membership/index.js:415-417 is a
        # TODO there; the reuse limit is config.js:69's knob for it)
        self.suppressed = False
        # the decay loop runs on a timer thread under real Timers while
        # updates arrive on gossip/server threads; damp state is a
        # read-modify-write either way (the reference is single-threaded)
        self._damp_lock = threading.Lock()
        self.now: Callable[[], int] = getattr(ringpop, "now", _now_ms)

    # -- damping ----------------------------------------------------------

    def decay_damp_score(self) -> None:
        with self._damp_lock:
            events = self._decay_damp_score_locked()
        for name, *args in events:
            self.emit(name, *args)

    def _decay_damp_score_locked(self) -> list:
        """Returns the events to emit (emission happens outside the lock:
        listeners may re-enter membership)."""
        config = self.ringpop.config
        if self.damp_score is None:
            self.damp_score = config.get("dampScoringInitial")
            return []
        time_since = (self.now() - (self.last_update_timestamp or 0)) / 1000.0
        decay = math.e ** (-time_since * math.log(2) / config.get("dampScoringHalfLife"))
        old = self.damp_score
        self.damp_score = max(
            round(self.last_update_damp_score * decay), config.get("dampScoringMin")
        )
        events = [("dampScoreDecayed", self.damp_score, old)]
        if self.suppressed and self.damp_score < config.get(
            "dampScoringReuseLimit"
        ):
            self.suppressed = False
            events.append(("suppressRecovered", self.damp_score))
        return events

    def _apply_update_penalty(self) -> None:
        config = self.ringpop.config
        with self._damp_lock:
            events = self._decay_damp_score_locked()
            self.damp_score = min(
                self.damp_score + config.get("dampScoringPenalty"),
                config.get("dampScoringMax"),
            )
            # lastUpdateDampScore is recorded here, atomically with the
            # penalty (the reference assigns it in evaluateUpdate right
            # after, member.js:111 — same value, same order)
            self.last_update_damp_score = self.damp_score
            if self.damp_score > config.get("dampScoringSuppressLimit"):
                self.suppressed = True
                events.append(("suppressLimitExceeded",))
        for name, *args in events:
            self.emit(name, *args)
        if self.damp_score > config.get("dampScoringSuppressLimit"):
            self.ringpop.logger.info(
                "ringpop member damp score exceeded suppress limit"
            )

    # -- the SWIM rules ---------------------------------------------------

    def _is_local_override(self, update: Update) -> bool:
        # member.js:155-169
        return self.ringpop.whoami() == self.address and update.status in (
            Status.faulty,
            Status.suspect,
        )

    def _is_other_override(self, update: Update) -> bool:
        # member.js:171-202
        u, s = update, self
        if u.status == Status.alive:
            return s.status in Status.ALL and u.incarnation_number > s.incarnation_number
        if u.status == Status.suspect:
            return (
                (s.status == Status.suspect and u.incarnation_number > s.incarnation_number)
                or (s.status == Status.faulty and u.incarnation_number > s.incarnation_number)
                or (s.status == Status.alive and u.incarnation_number >= s.incarnation_number)
            )
        if u.status == Status.faulty:
            return (
                (s.status == Status.suspect and u.incarnation_number >= s.incarnation_number)
                or (s.status == Status.faulty and u.incarnation_number > s.incarnation_number)
                or (s.status == Status.alive and u.incarnation_number >= s.incarnation_number)
            )
        if u.status == Status.leave:
            return (
                s.status != Status.leave
                and u.incarnation_number >= s.incarnation_number
            )
        return False

    def evaluate_update(self, update: Union[Update, Dict[str, Any]]) -> bool:
        """Apply the update if the precedence rules allow (member.js:71-122)."""
        if isinstance(update, dict):
            update = Update.from_dict({"address": self.address, **update})
        if self._is_local_override(update):
            # Override intended update. Assert aliveness!  (member.js:76-81)
            update = Update(
                address=update.address,
                incarnation_number=self.now(),
                status=Status.alive,
                source=update.source,
                source_incarnation_number=update.source_incarnation_number,
                id=update.id,
                timestamp=update.timestamp,
            )
        elif not self._is_other_override(update):
            return False

        old_status = self.status
        if self.status != update.status:
            self.status = update.status
            if (
                self.address == self.ringpop.whoami()
                and self.status == Status.leave
            ):
                self.ringpop.membership.emit(
                    "event",
                    {"name": "LocalMemberLeaveEvent", "member": self, "oldStatus": old_status},
                )

        if self.incarnation_number != update.incarnation_number:
            self.incarnation_number = update.incarnation_number

        if (
            self.ringpop.config.get("dampScoringEnabled")
            and update.address != self.ringpop.whoami()
        ):
            self._apply_update_penalty()  # records last_update_damp_score

        self.emit("updated", update)
        self.last_update_timestamp = self.now()
        return True

    def get_stats(self) -> Dict[str, Any]:
        return {
            "address": self.address,
            "status": self.status,
            "incarnationNumber": self.incarnation_number,
            "dampScore": self.damp_score,
        }


def merge_membership_changesets(ringpop: Any, changesets: Sequence[Sequence[Update]]) -> List[Update]:
    """Keep the highest-incarnation change per address, skipping the local
    address (lib/membership/merge.js:22-51)."""
    merge_index: Dict[str, Update] = {}
    for changes in changesets:
        for change in changes:
            if change.address == ringpop.whoami():
                continue
            existing = merge_index.get(change.address)
            if existing is None or existing.incarnation_number < change.incarnation_number:
                merge_index[change.address] = change
    return list(merge_index.values())


class Membership(EventEmitter):
    """Ordered member list + by-address index (lib/membership/index.js)."""

    def __init__(self, ringpop: Any, rng: Optional[random.Random] = None):
        super().__init__()
        self.ringpop = ringpop
        self.members: List[Member] = []
        self.members_by_address: Dict[str, Member] = {}
        self.checksum: Optional[int] = None
        self.stashed_updates: Optional[List[List[Update]]] = []
        self.local_member: Optional[Member] = None
        self.rng = rng or random.Random()
        self.decay_timer = None
        # bumping this invalidates any in-flight decay callback: an
        # on_timeout that captured an older generation must neither decay
        # nor re-arm (stop() during a firing callback would otherwise be
        # lost, leaving the loop running — or doubled after a restart)
        self._decay_gen = 0

    # -- checksum ---------------------------------------------------------

    def compute_checksum(self) -> int:
        start = time.time()
        prev = self.checksum
        self.checksum = native.hash32(self.generate_checksum_string())
        self.emit("checksumComputed")
        self.ringpop.stat("timing", "compute-checksum", start)
        self.ringpop.stat("gauge", "checksum", self.checksum)
        if prev != self.checksum:
            self._emit_checksum_update()
        return self.checksum

    def _emit_checksum_update(self) -> None:
        counts = {s: 0 for s in Status.ALL}
        for m in self.members:
            counts[m.status] = counts.get(m.status, 0) + 1
        self.emit(
            "checksumUpdate",
            {
                "local": self.ringpop.whoami(),
                "timestamp": _now_ms(),
                "checksum": self.checksum,
                "membershipStatusCounts": counts,
            },
        )

    def generate_checksum_string(self) -> str:
        # membership/index.js:100-123 — sorted by address, no separator
        # between fields, ';' between members
        parts = []
        for m in sorted(self.members, key=lambda m: m.address):
            parts.append("%s%s%d" % (m.address, m.status, m.incarnation_number))
        return ";".join(parts)

    # -- queries ----------------------------------------------------------

    def find_member_by_address(self, address: str) -> Optional[Member]:
        return self.members_by_address.get(address)

    def get_incarnation_number(self) -> Optional[int]:
        return self.local_member.incarnation_number if self.local_member else None

    def get_join_position(self) -> int:
        return int(self.rng.random() * len(self.members))

    def get_member_at(self, index: int) -> Member:
        return self.members[index]

    def get_member_count(self) -> int:
        return len(self.members)

    def has_member(self, member: Member) -> bool:
        return self.find_member_by_address(member.address) is not None

    def is_pingable(self, member: Member) -> bool:
        return member.address != self.ringpop.whoami() and member.status in (
            Status.alive,
            Status.suspect,
        )

    def get_random_pingable_members(self, n: int, excluding: Sequence[str]) -> List[Member]:
        eligible = [
            m
            for m in self.members
            if m.address not in excluding and self.is_pingable(m)
        ]
        self.rng.shuffle(eligible)
        return eligible[:n]

    def get_stats(self) -> Dict[str, Any]:
        return {
            "checksum": self.checksum,
            "members": sorted(
                (m.get_stats() for m in self.members), key=lambda s: s["address"]
            ),
        }

    # -- mutations --------------------------------------------------------

    def make_alive(self, address: str, incarnation_number: int) -> List[Update]:
        self.ringpop.stat("increment", "make-alive")
        is_local = address == self.ringpop.whoami()
        return self._update_member(
            Update(address, incarnation_number, Status.alive, self.local_member),
            is_local,
        )

    def make_faulty(self, address: str, incarnation_number: int) -> List[Update]:
        self.ringpop.stat("increment", "make-faulty")
        return self._update_member(
            Update(address, incarnation_number, Status.faulty, self.local_member)
        )

    def make_leave(self, address: str, incarnation_number: int) -> List[Update]:
        self.ringpop.stat("increment", "make-leave")
        return self._update_member(
            LeaveUpdate(address, incarnation_number, self.local_member)
        )

    def make_suspect(self, address: str, incarnation_number: int) -> List[Update]:
        self.ringpop.stat("increment", "make-suspect")
        return self._update_member(
            Update(address, incarnation_number, Status.suspect, self.local_member)
        )

    def set(self) -> None:
        """Atomically apply stashed pre-bootstrap updates (index.js:208-247)."""
        if self.ringpop.is_ready or self.stashed_updates is None:
            return
        if not self.stashed_updates:
            return

        updates = merge_membership_changesets(self.ringpop, self.stashed_updates)

        for update in updates:
            member = self._create_member(update)
            self.members.append(member)
            self.members_by_address[member.address] = member

        self.stashed_updates = None
        self.compute_checksum()
        self.emit("set", updates)

    def update(self, changes, is_local: bool = False) -> List[Update]:
        if isinstance(changes, (Update, dict)):
            changes = [changes]
        changes = [
            Update.from_dict(c) if isinstance(c, dict) else c for c in changes
        ]
        self.ringpop.stat("gauge", "changes.apply", len(changes))
        if not changes:
            return []

        # Buffer updates until ready (index.js:258-265).
        if not is_local and not self.ringpop.is_ready:
            if self.stashed_updates is not None:
                self.stashed_updates.append(changes)
            return []

        updates: List[Update] = []

        for change in changes:
            member = self.find_member_by_address(change.address)
            if member is None:
                member = self._create_member(change)
                if member.address == self.ringpop.whoami():
                    self.local_member = member
                self.members.insert(self.get_join_position(), member)
                self.members_by_address[member.address] = member
                updates.append(change)
                continue

            applied: List[Update] = []
            handler = member.once("updated", lambda u: applied.append(u))
            member.evaluate_update(change)
            member.remove_listener("updated", handler)
            updates.extend(applied)

        if updates:
            self.compute_checksum()
            self.emit("updated", updates)

        return updates

    def shuffle(self) -> None:
        self.rng.shuffle(self.members)

    def to_list(self) -> List[str]:
        return [m.address for m in self.members]

    def _create_member(self, update: Update) -> Member:
        member = Member(self.ringpop, update)
        member.on(
            "suppressLimitExceeded",
            lambda: self.emit("memberSuppressLimitExceeded", member),
        )
        member.on(
            "suppressRecovered",
            lambda score: self.emit("memberSuppressRecovered", member, score),
        )
        return member

    def _update_member(self, update: Update, is_local: bool = False) -> List[Update]:
        updates = self.update(update, is_local)
        if updates:
            self.ringpop.logger.debug(
                "ringpop member declares other member %s" % update.status
            )
        return updates

    # -- damping decay loop (membership/index.js:330-383) ----------------

    def start_damp_score_decayer(self) -> None:
        """Start the periodic damp-score decay loop (membership/
        index.js:330-350, interval config.js:62 dampScoringDecayInterval):
        every interval, every member's flap-penalty score decays
        exponentially toward dampScoringMin, so suppressed members recover
        *between* updates rather than only lazily at the next penalty.
        Idempotent; a no-op when dampScoringDecayEnabled is off or the
        context has no timer plane (bare fixtures).

        The generation bump invalidates any IN-FLIGHT timeout callback
        from a previous loop: a callback that fired (clearing
        ``decay_timer``) concurrently with this start() would otherwise
        pass its stale-generation check and re-arm a SECOND live loop
        alongside the one armed here."""
        if self.decay_timer is not None:
            return
        self._decay_gen += 1
        self._schedule_decay()

    def stop_damp_score_decayer(self) -> None:
        """membership/index.js:352-357."""
        self._decay_gen += 1
        if self.decay_timer is not None:
            timers = getattr(self.ringpop, "timers", None)
            if timers is not None:
                timers.clear_timeout(self.decay_timer)
            self.decay_timer = None

    def _schedule_decay(self) -> None:
        config = self.ringpop.config
        if not config.get("dampScoringDecayEnabled"):
            return
        timers = getattr(self.ringpop, "timers", None)
        if timers is None:
            return
        gen = self._decay_gen

        def on_timeout() -> None:
            if gen != self._decay_gen:
                return  # stopped (or restarted) while in flight
            self.decay_timer = None
            self.decay_members_damp_score()
            if gen != self._decay_gen:
                return  # stopped by a decay listener
            self._schedule_decay()  # loop until stopped or disabled

        self.decay_timer = timers.set_timeout(
            on_timeout, config.get("dampScoringDecayInterval") / 1000.0
        )

    def decay_members_damp_score(self) -> None:
        # snapshot: the sweep runs on the timer thread while joins insert
        # into self.members at random positions (index.js:285)
        for m in list(self.members):
            m.decay_damp_score()


class MembershipIterator:
    """Round-robin pingable-member iterator with reshuffle each full round
    (lib/membership/iterator.js:22-51)."""

    def __init__(self, ringpop: Any):
        self.ringpop = ringpop
        self.current_index = -1
        self.current_round = 0

    def next(self) -> Optional[Member]:
        visited: Dict[str, bool] = {}
        membership = self.ringpop.membership
        max_to_visit = membership.get_member_count()

        while len(visited) < max_to_visit:
            self.current_index += 1
            if self.current_index >= membership.get_member_count():
                self.current_index = 0
                self.current_round += 1
                membership.shuffle()
            member = membership.get_member_at(self.current_index)
            visited[member.address] = True
            if membership.is_pingable(member):
                return member
        return None
