"""Tracer subsystem (lib/trace/ rebuilt): remotely attachable event taps.

``/trace/add`` subscribes a sink to a named internal event with a TTL;
sinks either log locally or forward the event blob to another node over the
channel (lib/trace/log.js, tchannel.js).  Wired events:
``membership.checksum.update`` — matching the reference
(lib/trace/config.js:22-36), sourced from Membership's ``checksumUpdate``
emission (lib/membership/index.js:77-94) — plus ``ring.checksum.computed``
(HashRing rebuilds) and ``sim.tick.metrics`` (per-tick simulation metric
rows via obs.sim_tap.SimTracerHost).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional

DEFAULT_TTL_MS = 60 * 1000
MAX_TTL_MS = 5 * 60 * 1000


class TraceError(Exception):
    pass


TRACE_EVENTS: Dict[str, Dict[str, str]] = {
    # event name -> (emitter attribute path, event it maps to)
    "membership.checksum.update": {
        "emitter": "membership",
        "event": "checksumUpdate",
    },
    # ring rebuilt + rehashed (models/ring/host.py compute_checksum; the
    # blob carries {checksum, serverCount})
    "ring.checksum.computed": {
        "emitter": "ring",
        "event": "checksumComputed",
    },
    # per-tick simulation metrics re-published by a SimTracerHost
    # (obs/sim_tap.py) — lets TracerStore work against the simulation
    # engines, not just live nodes
    "sim.tick.metrics": {
        "emitter": "sim_events",
        "event": "tickMetrics",
    },
    # decoded flight-recorder event batches re-published by a
    # SimTracerHost drain (obs/sim_tap.py publish_flight_events; event
    # layout in obs/events.py)
    "sim.flight.events": {
        "emitter": "sim_events",
        "event": "flightEvents",
    },
}


class Tracer:
    """One (event, sink) subscription with expiry."""

    def __init__(self, ringpop: Any, event_name: str, sink_spec: Dict[str, Any],
                 expires_in_ms: Optional[int] = None):
        spec = TRACE_EVENTS.get(event_name)
        if spec is None:
            raise TraceError("unknown traceable event: %r" % event_name)
        self.ringpop = ringpop
        self.event_name = event_name
        self.sink_spec = dict(sink_spec)
        # a known event may still be unavailable on THIS host: e.g.
        # sim.tick.metrics sources from a SimTracerHost's sim_events —
        # a live Ringpop facade has no such emitter, and the miss must
        # surface as a clean TraceError (-> ringpop.trace.invalid over
        # the wire), not an unhandled AttributeError
        self.emitter = getattr(ringpop, spec["emitter"], None)
        if self.emitter is None:
            raise TraceError(
                "event %r is not available on this node (no %r emitter)"
                % (event_name, spec["emitter"])
            )
        self.internal_event = spec["event"]
        ttl = min(expires_in_ms or DEFAULT_TTL_MS, MAX_TTL_MS)
        self.expires_at_ms = time.time() * 1000.0 + ttl
        self._send = self._resolve_sink(sink_spec)
        self._listener = None

    def _resolve_sink(self, spec: Dict[str, Any]) -> Callable[[Any], None]:
        kind = spec.get("type")
        if kind == "log":
            def log_sink(blob: Any) -> None:
                self.ringpop.logger.info(
                    "ringpop trace", extra={"event": self.event_name, "blob": blob}
                )
            return log_sink
        if kind == "channel":
            host_port = spec.get("hostPort")
            endpoint = spec.get("serviceName") or "/trace/sink"
            if not host_port:
                raise TraceError("channel sink requires hostPort")

            def channel_sink(blob: Any) -> None:
                try:
                    self.ringpop.channel.request(
                        host_port,
                        endpoint,
                        head={"event": self.event_name},
                        body=blob,
                        timeout_s=5.0,
                    )
                except Exception:
                    self.ringpop.logger.warning(
                        "ringpop trace channel sink failed",
                        extra={"sink": host_port},
                    )
            return channel_sink
        raise TraceError("unknown sink type: %r" % kind)

    @property
    def key(self) -> tuple:
        return (self.event_name, self.sink_spec.get("type"),
                self.sink_spec.get("hostPort"))

    def connect(self) -> None:
        def listener(blob=None, *a, **kw):
            self._send(blob)
        self._listener = listener
        self.emitter.on(self.internal_event, listener)

    def disconnect(self) -> None:
        if self._listener is not None:
            self.emitter.remove_listener(self.internal_event, self._listener)
            self._listener = None


class TracerStore:
    """Dedups tracers by (event, sink) and expires them (lib/trace/store.js)."""

    def __init__(self, ringpop: Any):
        self.ringpop = ringpop
        self.tracers: Dict[tuple, Tracer] = {}
        self._expiry_timer = None
        self._lock = threading.Lock()

    def add(self, tracer: Tracer) -> Tracer:
        with self._lock:
            existing = self.tracers.get(tracer.key)
            if existing is not None:
                existing.expires_at_ms = tracer.expires_at_ms
                return existing
            self.tracers[tracer.key] = tracer
        tracer.connect()
        self._schedule_expiry()
        return tracer

    def remove(self, event_name: str, sink_spec: Dict[str, Any]) -> bool:
        key = (event_name, sink_spec.get("type"), sink_spec.get("hostPort"))
        with self._lock:
            tracer = self.tracers.pop(key, None)
        if tracer is not None:
            tracer.disconnect()
            return True
        return False

    def _schedule_expiry(self) -> None:
        if self._expiry_timer is not None:
            self.ringpop.timers.clear_timeout(self._expiry_timer)
        self._expiry_timer = self.ringpop.timers.set_timeout(
            self._expire_due, 1.0
        )

    def _expire_due(self) -> None:
        now = time.time() * 1000.0
        with self._lock:
            due = [t for t in self.tracers.values() if t.expires_at_ms <= now]
            for t in due:
                del self.tracers[t.key]
        for t in due:
            t.disconnect()
        with self._lock:
            alive = bool(self.tracers)
        if alive:
            self._schedule_expiry()
        else:
            self._expiry_timer = None

    def destroy(self) -> None:
        if self._expiry_timer is not None:
            self.ringpop.timers.clear_timeout(self._expiry_timer)
            self._expiry_timer = None
        with self._lock:
            tracers = list(self.tracers.values())
            self.tracers = {}
        for t in tracers:
            t.disconnect()
