"""Central config store.

Mirrors the reference's ``config.js`` (/root/reference/config.js:29-104): a
key/value store seeded from constructor options with per-key validators and
defaults, emitting ``set`` and ``set.<key>`` events on mutation, plus the
protocol-constant knobs that the reference passes as plain constructor options
(/root/reference/index.js:112-120).  Protocol constants that participate in
jitted code are exposed through :class:`ProtocolParams`, a frozen dataclass
whose fields become static arguments of the compiled step function.
"""

from __future__ import annotations

import dataclasses
import math
import re
from typing import Any, Callable, Dict, List, Optional


class EventEmitter:
    """Minimal synchronous event emitter (the reference leans on Node's)."""

    def __init__(self) -> None:
        self._listeners: Dict[str, List[Callable[..., Any]]] = {}

    def on(self, event: str, fn: Callable[..., Any]) -> Callable[..., Any]:
        self._listeners.setdefault(event, []).append(fn)
        return fn

    def once(self, event: str, fn: Callable[..., Any]) -> Callable[..., Any]:
        def wrapper(*args: Any, **kw: Any) -> Any:
            self.remove_listener(event, wrapper)
            return fn(*args, **kw)

        wrapper.__wrapped__ = fn  # type: ignore[attr-defined]
        return self.on(event, wrapper)

    def remove_listener(self, event: str, fn: Callable[..., Any]) -> None:
        fns = self._listeners.get(event, [])
        for cand in list(fns):
            if cand is fn or getattr(cand, "__wrapped__", None) is fn:
                fns.remove(cand)

    def remove_all_listeners(self, event: Optional[str] = None) -> None:
        if event is None:
            self._listeners.clear()
        else:
            self._listeners.pop(event, None)

    def emit(self, event: str, *args: Any, **kw: Any) -> None:
        for fn in list(self._listeners.get(event, [])):
            fn(*args, **kw)

    def listener_count(self, event: str) -> int:
        return len(self._listeners.get(event, []))


def _num_validator(v: Any) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool) and not (
        isinstance(v, float) and math.isnan(v)
    )


def _blacklist_validator(vals: Any) -> bool:
    if not isinstance(vals, (list, tuple)):
        return False
    return all(isinstance(v, re.Pattern) for v in vals)


class Config(EventEmitter):
    """Key/value config store with seeded defaults and validators.

    Defaults follow /root/reference/config.js:54-98 exactly (including
    ``TEST_KEY``, which tests and lives depend on).
    """

    DEFAULTS: List[tuple] = [
        ("TEST_KEY", 100, None, None),
        ("autoGossip", True, None, None),
        ("dampScoringEnabled", True, None, None),
        ("dampScoringDecayEnabled", True, None, None),
        ("dampScoringDecayInterval", 1000, None, None),
        ("dampScoringHalfLife", 60, None, None),
        ("dampScoringInitial", 0, None, None),
        ("dampScoringMax", 10000, None, None),
        ("dampScoringMin", 0, None, None),
        ("dampScoringPenalty", 500, None, None),
        ("dampScoringReuseLimit", 2500, None, None),
        ("dampScoringSuppressDuration", 60 * 60 * 1000, None, None),
        ("dampScoringSuppressLimit", 5000, None, None),
        (
            "memberBlacklist",
            [],
            _blacklist_validator,
            "expected to be array of RegExp objects",
        ),
        ("maxJoinAttempts", 50, _num_validator, None),
    ]

    def __init__(self, ringpop: Any = None, seed: Optional[Dict[str, Any]] = None):
        super().__init__()
        self.ringpop = ringpop
        self.store: Dict[str, Any] = {}
        self._seed(seed or {})

    def get(self, key: str) -> Any:
        return self.store.get(key)

    def get_all(self) -> Dict[str, Any]:
        return self.store

    def set(self, key: str, value: Any) -> None:
        old = self.store.get(key)
        self.store[key] = value
        self.emit("set", key, value, old)
        self.emit("set." + key, value, old)

    def _seed(self, seed: Dict[str, Any]) -> None:
        for name, default, validator, reason in self.DEFAULTS:
            if isinstance(default, (list, dict)):
                default = default.copy()  # fresh per instance, like JS's []
            if name not in seed:
                self.set(name, default)
            elif validator is not None and not validator(seed[name]):
                if self.ringpop is not None and getattr(self.ringpop, "logger", None):
                    self.ringpop.logger.warning(
                        "ringpop using default value for config after being "
                        "passed invalid seed value",
                        extra={
                            "config": name,
                            "seedVal": repr(seed[name]),
                            "defaultVal": default,
                            "reason": reason,
                        },
                    )
                self.set(name, default)
            else:
                self.set(name, seed[name])


@dataclasses.dataclass(frozen=True)
class ProtocolParams:
    """Protocol constants, expressed in discrete simulation ticks.

    The reference is timer-driven; the simulator maps wall-clock knobs onto a
    discrete-time model where one tick == one gossip protocol period
    (>= 200 ms, /root/reference/lib/gossip/index.js:194-196).  Timeouts become
    tick counts via ceil(ms / protocol_period_ms).

    Reference values: joinSize/pingReqSize/parallelismFactor and timeouts at
    /root/reference/index.js:112-120, lib/gossip/join-sender.js:51-66,
    suspicion at lib/gossip/suspicion.js:111-113, replica points at
    lib/ring/index.js:28, piggyback factor at lib/gossip/dissemination.js:41.
    """

    join_size: int = 3
    ping_req_size: int = 3
    join_parallelism_factor: int = 2
    replica_points: int = 100
    piggyback_factor: int = 15
    min_protocol_period_ms: int = 200
    ping_timeout_ms: int = 1500
    ping_req_timeout_ms: int = 5000
    join_timeout_ms: int = 1000
    suspicion_timeout_ms: int = 5000
    proxy_req_timeout_ms: int = 30000
    max_join_duration_ms: int = 300000
    max_join_attempts: int = 50

    @property
    def suspicion_timeout_ticks(self) -> int:
        return max(1, math.ceil(self.suspicion_timeout_ms / self.min_protocol_period_ms))

    def max_piggyback_count(self, server_count: int) -> int:
        # 15 * ceil(log10(n + 1)) — lib/gossip/dissemination.js:41
        return self.piggyback_factor * math.ceil(math.log10(server_count + 1)) if server_count >= 0 else self.piggyback_factor

    @staticmethod
    def default_max_piggyback_count() -> int:
        # Dissemination.Defaults.maxPiggybackCount — dissemination.js:179
        return 1
