"""Typed error catalog.

Mirrors /root/reference/lib/errors.js:22-87 — each error carries a dotted
``type`` identifier (``ringpop.*``) and a formatted message, so control-plane
responses can discriminate on error type exactly like the reference's
TypedError instances.
"""

from __future__ import annotations

from typing import Any, Dict


class RingpopError(Exception):
    type: str = "ringpop.error"
    template: str = "ringpop error"

    def __init__(self, **fields: Any) -> None:
        self.fields: Dict[str, Any] = fields
        try:
            message = self.template.format(**fields)
        except (KeyError, IndexError):
            message = self.template
        super().__init__(message)

    def to_dict(self) -> Dict[str, Any]:
        out = {"type": self.type, "message": str(self), **self.fields}
        status = getattr(self, "status_code", None)
        if status is not None:
            # the reference's sendError maps err.statusCode onto the HTTP
            # response (request-proxy/index.js sendError: statusCode || 500)
            out["statusCode"] = status
        return out


class AppRequiredError(RingpopError):
    type = "ringpop.options-app.required"
    template = (
        "Expected `options.app` to be a non-empty string. Are you sure you "
        "specified an app?"
    )


class HostPortRequiredError(RingpopError):
    type = "ringpop.options-host-port.required"
    template = (
        "Expected `options.hostPort` to be valid. Got {hostPort} which is not "
        "{reason}."
    )


class ArgumentRequiredError(RingpopError):
    type = "ringpop.argument-required"
    template = "Expected `{argument}` to be provided"


class ChannelRequiredError(RingpopError):
    type = "ringpop.options-channel.required"
    template = "Expected `options.channel` to be provided"


class ChannelDestroyedError(RingpopError):
    type = "ringpop.options-channel.destroyed"
    template = "Expected `options.channel` to not be destroyed"


class DuplicateHookError(RingpopError):
    type = "ringpop.duplicate-hook"
    template = "Expected hook name '{name}' to not already be registered"


class InvalidJoinAppError(RingpopError):
    type = "ringpop.invalid-join.app"
    template = (
        "A node tried joining a different app cluster. The expected app "
        "({expected}) did not match the actual app ({actual})"
    )


class InvalidJoinSourceError(RingpopError):
    type = "ringpop.invalid-join.source"
    template = (
        "A node tried joining a cluster by attempting to join itself. The "
        "joiner ({actual}) must join someone else."
    )


class InvalidLocalMemberError(RingpopError):
    type = "ringpop.invalid-local-member"
    template = "Operation requires a valid local member"


class LookupKeyRequiredError(RingpopError):
    type = "ringpop.lookup.key-required"
    template = "Lookup requires a key"


class PingReqTargetUnreachableError(RingpopError):
    type = "ringpop.ping-req.target-unreachable"
    template = "Ping-req target is unreachable"


class PingReqInconclusiveError(RingpopError):
    type = "ringpop.ping-req.inconclusive"
    template = "Ping-req was inconclusive"


class DenyJoinError(RingpopError):
    type = "ringpop.deny-join"
    template = "Node is currently configured to deny joins"


class BlacklistedError(RingpopError):
    type = "ringpop.invalid-join.blacklist"
    template = "Node ({member}) is blacklisted and cannot join"


class InvalidCheckSumError(RingpopError):
    type = "ringpop.request-proxy.invalid-checksum"
    template = (
        "Expected the remote checksum to match local checksum. The "
        "expected checksum ({expected}) did not match actual checksum "
        "({actual})."
    )


class MaxRetriesExceededError(RingpopError):
    type = "ringpop.request-proxy.max-retries-exceeded"
    template = "Max number of retries exceeded. {maxRetries} retries attempted."


class KeysDivergedError(RingpopError):
    type = "ringpop.request-proxy.keys-diverged"
    template = (
        "Destinations for proxied request have diverged. These keys ({keys}) "
        "were originally intended for {origDestination}, but are now destined "
        "for these hosts ({newDestinations})."
    )


class RequestProxyDestroyedError(RingpopError):
    type = "ringpop.request-proxy.destroyed"
    template = "Request proxy was destroyed before it could proxy your request"


class BodyLimitExceededError(RingpopError):
    """The node `body` module's limit error: the reference forwards request
    bodies through body(req, res, {limit: opts.bodyLimit}, ...)
    (lib/request-proxy/index.js:88-90) and an oversized body fails the
    forward with a 413 'request entity too large'."""

    type = "ringpop.request-proxy.body-limit"
    template = "request entity too large (limit {limit}, got {length})"
    status_code = 413


class RedundantLeaveError(RingpopError):
    type = "ringpop.invalid-leave.redundant"
    template = "A node cannot leave its cluster when it has already left."


class InvalidJoinRetriesError(RingpopError):
    type = "ringpop.join-aborted"
    template = "Join aborted: {reason}"


class PropertyRequiredError(RingpopError):
    type = "ringpop.property-required"
    template = "Expected `{property}` to be defined"


class SimShapeError(RingpopError):
    """New-capability error: the batched simulator rejects incompatible shapes."""

    type = "ringpop.sim.shape-mismatch"
    template = "Simulator state shape mismatch: {reason}"
