"""Metrics primitives: EWMA meters, sampling histogram, null clients.

The reference pulls these from the npm ``metrics`` package — Meters for
client/server/total request rates (index.js:158-160) and a Histogram of
protocol-period timing that feeds the adaptive gossip delay
(lib/gossip/index.js:37,52-55).  Rebuilt minimally here: an exponentially
weighted moving-average meter and a bounded-reservoir histogram with
percentile queries.
"""

from __future__ import annotations

import math
import random
import threading
import time
from typing import Any, Dict, List, Optional


class Meter:
    """Events/second with 1/5/15-minute EWMAs."""

    TICK_S = 5.0

    def __init__(self, now=time.time):
        self._now = now
        self._start = now()
        self._last_tick = self._start
        self._count = 0
        self._uncounted = 0
        self._rates = {60: 0.0, 300: 0.0, 900: 0.0}
        self._initialized = False
        self._lock = threading.Lock()

    def mark(self, n: int = 1) -> None:
        with self._lock:
            self._tick_if_needed()
            self._count += n
            self._uncounted += n

    def _tick_if_needed(self) -> None:
        now = self._now()
        while now - self._last_tick >= self.TICK_S:
            inst = self._uncounted / self.TICK_S
            self._uncounted = 0
            for window in self._rates:
                alpha = 1 - math.exp(-self.TICK_S / window)
                if not self._initialized:
                    self._rates[window] = inst
                else:
                    self._rates[window] += alpha * (inst - self._rates[window])
            self._initialized = True
            self._last_tick += self.TICK_S

    def mean_rate(self) -> float:
        elapsed = self._now() - self._start
        return self._count / elapsed if elapsed > 0 else 0.0

    def one_minute_rate(self) -> float:
        with self._lock:
            self._tick_if_needed()
            return self._rates[60]

    def five_minute_rate(self) -> float:
        with self._lock:
            self._tick_if_needed()
            return self._rates[300]

    def fifteen_minute_rate(self) -> float:
        with self._lock:
            self._tick_if_needed()
            return self._rates[900]

    def to_dict(self) -> Dict[str, float]:
        return {
            "count": self._count,
            "m1": self.one_minute_rate(),
            "m5": self.five_minute_rate(),
            "m15": self.fifteen_minute_rate(),
            "meanRate": self.mean_rate(),
        }


class Histogram:
    """Reservoir-sampled value distribution with percentile queries."""

    def __init__(self, size: int = 1028, rng: Optional[random.Random] = None):
        self._size = size
        self._values: List[float] = []
        self._count = 0
        self._min: Optional[float] = None
        self._max: Optional[float] = None
        self._sum = 0.0
        self._rng = rng or random.Random()
        self._lock = threading.Lock()

    def update(self, value: float) -> None:
        with self._lock:
            self._count += 1
            self._sum += value
            self._min = value if self._min is None else min(self._min, value)
            self._max = value if self._max is None else max(self._max, value)
            if len(self._values) < self._size:
                self._values.append(value)
            else:
                idx = self._rng.randrange(self._count)
                if idx < self._size:
                    self._values[idx] = value

    def percentiles(self, ps) -> Dict[float, Optional[float]]:
        with self._lock:
            values = sorted(self._values)
        out: Dict[float, Optional[float]] = {}
        for p in ps:
            if not values:
                out[p] = None
                continue
            pos = p * (len(values) + 1)
            if pos < 1:
                out[p] = values[0]
            elif pos >= len(values):
                out[p] = values[-1]
            else:
                lower = values[int(pos) - 1]
                upper = values[int(pos)]
                out[p] = lower + (pos - int(pos)) * (upper - lower)
        return out

    def mean(self) -> Optional[float]:
        return self._sum / self._count if self._count else None

    def to_dict(self) -> Dict[str, Any]:
        pct = self.percentiles([0.5, 0.75, 0.95, 0.99, 0.999])
        return {
            "count": self._count,
            "min": self._min,
            "max": self._max,
            "mean": self.mean(),
            "p50": pct[0.5],
            "p75": pct[0.75],
            "p95": pct[0.95],
            "p99": pct[0.99],
            "p999": pct[0.999],
        }


class NullStatsd:
    """No-op statsd client (lib/nulls.js analog)."""

    def increment(self, key: str, value: int = 1) -> None:
        pass

    def gauge(self, key: str, value: Any) -> None:
        pass

    def timing(self, key: str, value: Any) -> None:
        pass


class CapturingStatsd:
    """Test double recording every emission."""

    def __init__(self):
        self.records: List[tuple] = []

    def increment(self, key: str, value: int = 1) -> None:
        self.records.append(("increment", key, value))

    def gauge(self, key: str, value: Any) -> None:
        self.records.append(("gauge", key, value))

    def timing(self, key: str, value: Any) -> None:
        self.records.append(("timing", key, value))


class NullLogger:
    """No-op structured logger (lib/nulls.js analog)."""

    def debug(self, *a, **k):
        pass

    def info(self, *a, **k):
        pass

    def warning(self, *a, **k):
        pass

    warn = warning

    def error(self, *a, **k):
        pass
