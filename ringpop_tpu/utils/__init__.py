"""Utility layer: config store, typed errors, stats, null objects, helpers."""
