"""Misc helpers mirroring /root/reference/lib/util.js and lib/nulls.js."""

from __future__ import annotations

import json
import logging
import re
from typing import Any, Iterable, List, Optional

HOST_CAPTURE = re.compile(r"(\d+\.\d+\.\d+\.\d+):\d+")
HOST_PORT_PATTERN = re.compile(r"^(\d+\.\d+\.\d+\.\d+):\d+$")


def capture_host(host_port: str) -> Optional[str]:
    """Extract the IP from ``ip:port`` — lib/util.js:27-30."""
    m = HOST_CAPTURE.search(host_port or "")
    return m.group(1) if m else None


def is_empty_array(arr: Any) -> bool:
    return not isinstance(arr, (list, tuple)) or len(arr) == 0


def num_or_default(num: Any, default: Any) -> Any:
    if isinstance(num, bool) or not isinstance(num, (int, float)):
        return default
    if isinstance(num, float) and num != num:  # NaN
        return default
    return num


def safe_parse(s: Any) -> Any:
    try:
        return json.loads(s)
    except (TypeError, ValueError):
        return None


def map_uniq(items: Iterable[Any], fn) -> List[Any]:
    seen = {}
    for item in items:
        seen[fn(item)] = None
    return list(seen.keys())


class NullStatsd:
    """No-op statsd client — lib/nulls.js."""

    def increment(self, *a: Any, **kw: Any) -> None:
        pass

    def gauge(self, *a: Any, **kw: Any) -> None:
        pass

    def timing(self, *a: Any, **kw: Any) -> None:
        pass


def null_logger() -> logging.Logger:
    logger = logging.getLogger("ringpop_tpu.null")
    if not logger.handlers:
        logger.addHandler(logging.NullHandler())
    logger.propagate = False
    return logger


def clear_jax_backends() -> None:
    """Drop any live JAX backends so platform config can be re-pinned.

    Shared by the driver entry points (bench retry after transient TPU-tunnel
    failures; multichip dryrun re-pinning onto virtual CPU devices after this
    image's sitecustomize pre-registers the ``axon`` TPU platform).  The
    except-guard tolerates the API moving across jax versions.
    """
    try:
        from jax.extend import backend as jeb

        jeb.clear_backends()
    except Exception:
        pass


def scrub_repo_pythonpath(repo_root: str) -> None:
    """Remove repo-pointing entries from PYTHONPATH before backend init.

    The axon tunnel's TPU discovery helper inherits PYTHONPATH and fails
    when it points into this repo — jax then silently falls back to CPU.
    Shared by the driver entry points (bench.py, tpu_measure.py), which
    put the repo on sys.path themselves; non-repo entries are preserved
    for re-exec'd children that may rely on them."""
    import os

    pp = os.environ.get("PYTHONPATH")
    if not pp:
        return
    root = os.path.abspath(repo_root)
    kept = [
        e
        for e in pp.split(os.pathsep)
        if e and not os.path.abspath(e).startswith(root)
    ]
    if kept:
        os.environ["PYTHONPATH"] = os.pathsep.join(kept)
    else:
        os.environ.pop("PYTHONPATH", None)


def wait_for_tpu(
    script: str,
    env_var: str = "TPU_WAIT_ATTEMPT",
    retries: int = 90,
    sleep_s: float = 20.0,
) -> str:
    """Grab the (single-client) axon tunnel, retrying in a FRESH process.

    When the tunnel is held by another client, backend discovery silently
    falls back to CPU and JAX memoizes the plugin failure — only a new
    interpreter can retry (see reexec_retry).  Shared by the chip-gated
    drivers (tpu_measure.py, prof scripts); raises RuntimeError when the
    retry budget is exhausted so callers can degrade to a marked artifact.
    """
    import json as _json
    import os
    import sys as _sys

    import jax

    try:
        plat = jax.devices()[0].platform
    except Exception as e:  # init raised (the other transient mode)
        print(_json.dumps({"init_err": str(e)[:120]}), file=_sys.stderr)
        plat = "cpu"
    if plat == "tpu":
        return plat
    print(
        _json.dumps(
            {"wait": os.environ.get(env_var, "0"), "platform": plat}
        ),
        file=_sys.stderr,
        flush=True,
    )
    if reexec_retry(env_var, retries, sleep_s, script) is False:
        raise RuntimeError("TPU tunnel never became available")


def reexec_retry(env_var: str, retries: int, sleep_s: float, script: str):
    """Retry a driver script in a FRESH interpreter via os.execve.

    When another client holds the single-client axon tunnel, JAX backend
    discovery silently falls back to CPU and memoizes the plugin failure —
    an in-process clear_backends + retry re-reads the cached failure in
    0 ms and can never recover.  The only reliable retry is a new process.
    Returns False when the retry budget is exhausted (caller decides how
    to degrade); otherwise sleeps and never returns (execve).
    """
    import os
    import sys
    import time

    attempt = int(os.environ.get(env_var, "0"))
    if attempt + 1 >= max(1, retries):
        return False
    time.sleep(sleep_s)
    env = dict(os.environ)
    env[env_var] = str(attempt + 1)
    os.execve(
        sys.executable,
        # forward the original flags — a re-exec must not silently
        # continue with defaults
        [sys.executable, os.path.abspath(script)] + sys.argv[1:],
        env,
    )


# transient backend failures (retryable by a caller's OUTER loop / fresh
# process, never by in-process compile-helper backoff) vs compile-helper
# 500s (retryable in-process).  One source of truth — bench.py and the
# measurement sweep share these.
TRANSIENT_BACKEND_MARKERS = (
    "Unable to initialize backend",
    "UNAVAILABLE",
    "DEADLINE_EXCEEDED",
    "RESOURCE_EXHAUSTED",
    "ABORTED",
)
COMPILE_HELPER_MARKERS = ("remote_compile", "tpu_compile_helper")


def is_transient_backend_error(exc: BaseException) -> bool:
    return any(m in str(exc) for m in TRANSIENT_BACKEND_MARKERS)


def is_compile_helper_500(exc: BaseException) -> bool:
    return any(m in str(exc) for m in COMPILE_HELPER_MARKERS)


def retry_compile_helper(fn, *args, backoffs=(0.0, 10.0, 25.0), **kwargs):
    """Call ``fn`` with backoff retries for axon remote-compile-helper
    500s ONLY (the tunnel's compile helper fails intermittently on graphs
    that compile fine seconds later — the round-3 artifact lost its
    parity headline to a single such 500).  Transient backend errors
    re-raise immediately even when their text also mentions the helper —
    an outer retry loop / fresh process owns those — as does any other
    error (real graph/engine failures).  Each raised exception carries
    ``_retry_attempts`` with the number of tries made."""
    import time

    exc = None
    for i, backoff in enumerate(backoffs):
        if backoff:
            time.sleep(backoff)
        try:
            return fn(*args, **kwargs)
        except Exception as e:
            e._retry_attempts = i + 1
            exc = e
            if is_transient_backend_error(exc) or not is_compile_helper_500(
                exc
            ):
                raise
    raise exc


_HOST_COUNT_PREFIX = "--xla_force_host_platform_device_count"


def force_host_device_count(n_devices: int, env=None) -> None:
    """Set the forced-host-device-count env flags — the ONE place the
    flag string is spelled (round 14, ISSUE 10 satellite: bench.py's
    mesh phase, tpu_measure.py's weak-scaling CPU fallback, the
    multichip dryrun via :func:`pin_cpu_platform`, and tests/conftest.py
    all route through here, so the device-count flag cannot drift
    between drivers).

    ENV-ONLY by design: XLA reads XLA_FLAGS at backend-init time, so
    this works from conftest-style pre-import hooks and for spawned
    subprocesses alike; it performs no jax import and no backend
    (re)initialization — callers that need a live re-pin use
    :func:`pin_cpu_platform`, which builds on this.  Idempotent:
    an existing count flag is replaced, other XLA_FLAGS preserved.
    """
    import os

    if env is None:
        env = os.environ
    if n_devices < 1:
        raise ValueError("n_devices must be >= 1, got %r" % (n_devices,))
    kept = [
        f
        for f in env.get("XLA_FLAGS", "").split()
        if not f.startswith(_HOST_COUNT_PREFIX)
    ]
    kept.append("%s=%d" % (_HOST_COUNT_PREFIX, n_devices))
    env["XLA_FLAGS"] = " ".join(kept)
    # jax >= 0.5 reads the env var instead of XLA_FLAGS; harmless before
    env["JAX_NUM_CPU_DEVICES"] = str(n_devices)


def pin_cpu_platform(n_devices=None) -> None:
    """Clear any live JAX backends and force the CPU platform (optionally
    with ``n_devices`` virtual devices).

    Shared by the driver entry points: the multichip dryrun re-pins onto
    virtual CPU devices, and the bench falls back to CPU when the TPU
    tunnel stays unavailable through its retries.  Raises if the pin does
    not take (e.g. a live backend blocked the config update).

    Caveat (jax < 0.5): XLA parses XLA_FLAGS once per process at the
    first client creation, so an in-process re-pin cannot SHRINK an
    already-created virtual CPU mesh — the ``n_devices=None`` branch
    still scrubs the env so child processes start at the real default.
    """
    import os

    os.environ["JAX_PLATFORMS"] = "cpu"
    # jax < 0.5 has no jax_num_cpu_devices option; the virtual CPU device
    # count comes from XLA_FLAGS, read at backend init — which
    # clear_jax_backends() below forces to happen again.  The dedicated
    # marker records that a previous pin forced the count, so a later bare
    # pin scrubs OUR env (and only ours — an ambient count, whether the
    # test harness's XLA_FLAGS mesh or a user-set JAX_NUM_CPU_DEVICES, is
    # the caller's business) instead of leaking it to children.
    marker = "RINGPOP_PINNED_CPU_DEVICES"
    stash_flag = "RINGPOP_AMBIENT_CPU_DEVICES"  # ambient XLA_FLAGS count
    stash_env = "RINGPOP_AMBIENT_JAX_NUM_CPU_DEVICES"  # ambient env count
    prefix = _HOST_COUNT_PREFIX
    flags = os.environ.get("XLA_FLAGS", "").split()
    ambient = next((f for f in flags if f.startswith(prefix)), None)
    kept = [f for f in flags if not f.startswith(prefix)]
    update_count = n_devices
    if n_devices is not None:
        if marker not in os.environ:
            # first pin in this process: remember the caller's counts so
            # a later bare pin hands them back instead of dropping them
            if ambient is not None:
                os.environ[stash_flag] = ambient.split("=", 1)[1]
            if "JAX_NUM_CPU_DEVICES" in os.environ:
                os.environ[stash_env] = os.environ["JAX_NUM_CPU_DEVICES"]
        os.environ[marker] = str(n_devices)
        # the ONE spelling of the device-count flags (round 14)
        force_host_device_count(n_devices)
    elif os.environ.pop(marker, None) is not None:
        restored_flag = os.environ.pop(stash_flag, None)
        restored_env = os.environ.pop(stash_env, None)
        if restored_flag is not None:
            kept.append(f"{prefix}={restored_flag}")
        if restored_env is not None:
            os.environ["JAX_NUM_CPU_DEVICES"] = restored_env
        else:
            os.environ.pop("JAX_NUM_CPU_DEVICES", None)
        restored = restored_flag or restored_env
        # -1 is the option's "unset" default on jax >= 0.5
        update_count = int(restored) if restored else -1
        os.environ["XLA_FLAGS"] = " ".join(kept)
    clear_jax_backends()
    import jax

    jax.config.update("jax_platforms", "cpu")
    if update_count is not None:
        try:
            jax.config.update("jax_num_cpu_devices", update_count)
        except AttributeError:
            pass  # jax < 0.5: the XLA_FLAGS path above already took effect
    devs = jax.devices()
    assert devs[0].platform == "cpu", devs
    if n_devices is not None:
        assert len(devs) >= n_devices, (len(devs), n_devices)
