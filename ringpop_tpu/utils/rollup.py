"""Membership update rollup (lib/membership/rollup.js rebuilt).

Buffers membership updates per address and flushes one batched debug-log
entry after a quiet interval (5 s, index.js:68) instead of logging every
gossip-storm update individually.  Buffer is force-flushed when it grows
past ``MAX_NUM_UPDATES`` (250, rollup.js:26) distinct addresses.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List

from ringpop_tpu.utils.config import EventEmitter

MAX_NUM_UPDATES = 250  # rollup.js:26
DEFAULT_FLUSH_INTERVAL_MS = 5000  # index.js:68


class MembershipUpdateRollup(EventEmitter):
    def __init__(self, ringpop: Any, flush_interval_ms: int = DEFAULT_FLUSH_INTERVAL_MS,
                 max_num_updates: int = MAX_NUM_UPDATES):
        super().__init__()
        self.ringpop = ringpop
        self.flush_interval_ms = flush_interval_ms
        self.max_num_updates = max_num_updates
        self.buffer: Dict[str, List[dict]] = {}
        self.first_update_time: float = 0
        self.last_flush_time: float = 0
        self.last_update_time: float = 0
        self.flush_timer = None

    def _num_updates(self) -> int:
        return sum(len(v) for v in self.buffer.values())

    def track_updates(self, updates) -> None:
        if not updates:
            return
        since_last = (
            time.time() * 1000.0 - self.last_update_time
            if self.last_update_time
            else 0
        )
        if since_last >= self.flush_interval_ms:
            self.renew_buffer()
        if not self.buffer:
            self.first_update_time = time.time() * 1000.0
        for update in updates:
            d = update.to_dict() if hasattr(update, "to_dict") else dict(update)
            self.buffer.setdefault(d["address"], []).append(d)
        if self._num_updates() >= self.max_num_updates:
            self.flush_buffer()
        else:
            self._restart_flush_timer()
        self.last_update_time = time.time() * 1000.0

    def renew_buffer(self) -> None:
        self.flush_buffer()

    def _restart_flush_timer(self) -> None:
        if self.flush_timer is not None:
            self.ringpop.timers.clear_timeout(self.flush_timer)
        self.flush_timer = self.ringpop.timers.set_timeout(
            self.flush_buffer, self.flush_interval_ms / 1000.0
        )

    def flush_buffer(self) -> None:
        if self.flush_timer is not None:
            self.ringpop.timers.clear_timeout(self.flush_timer)
            self.flush_timer = None
        if not self.buffer:
            return
        now = time.time() * 1000.0
        since_flush = now - self.last_flush_time if self.last_flush_time else None
        self.ringpop.logger.debug(
            "ringpop membership update rollup",
            extra={
                "local": self.ringpop.whoami(),
                "updateCount": self._num_updates(),
                "checksum": self.ringpop.membership.checksum,
                "sinceFirstUpdate": now - self.first_update_time,
                "sinceLastFlush": since_flush,
                "updates": self.buffer,
            },
        )
        self.buffer = {}
        self.last_flush_time = now
        self.emit("flushed")

    def destroy(self) -> None:
        if self.flush_timer is not None:
            self.ringpop.timers.clear_timeout(self.flush_timer)
            self.flush_timer = None
