"""Suspicion subprotocol (lib/gossip/suspicion.js rebuilt).

A suspect member gets a 5-second clock; on expiry it is declared faulty
with the incarnation number captured from the update that started the
suspect period (suspicion.js:58-76 closure semantics) — a concurrently
bumped incarnation must ride out a fresh period.  Timers never run for
the local member, stop wholesale when the node leaves, and re-enable on
rejoin (suspicion.js:31-44,88-109).
"""

from __future__ import annotations

from typing import Any, Dict

DEFAULT_SUSPICION_TIMEOUT_MS = 5000  # suspicion.js:111-113


class Suspicion:
    def __init__(self, ringpop: Any, timeout_ms: int = DEFAULT_SUSPICION_TIMEOUT_MS):
        self.ringpop = ringpop
        self.period_ms = timeout_ms
        self.timers: Dict[str, Any] = {}
        self.stopped = False

    def start(self, member) -> None:
        address = getattr(member, "address", None) or member["address"]
        if self.stopped:
            self.ringpop.logger.debug(
                "cannot start a suspect period because suspicion protocol is stopped"
            )
            return
        if address == self.ringpop.whoami():
            self.ringpop.logger.debug(
                "cannot start a suspect period for the local member"
            )
            return
        if address in self.timers:
            self.stop(member)

        # capture the incarnation from the update that started this suspect
        # period; a concurrently-bumped incarnation must ride out a fresh
        # period before escalation (suspicion.js:67-70 closure semantics)
        if isinstance(member, dict):
            inc = member.get("incarnationNumber")
        else:
            inc = getattr(member, "incarnation_number", None)

        def expire():
            self.timers.pop(address, None)
            self.ringpop.logger.info(
                "ringpop member declares member faulty",
                extra={"local": self.ringpop.whoami(), "faulty": address},
            )
            self.ringpop.membership.make_faulty(address, inc)

        self.timers[address] = self.ringpop.timers.set_timeout(
            expire, self.period_ms / 1000.0
        )

    def stop(self, member) -> None:
        address = getattr(member, "address", None) or member["address"]
        handle = self.timers.pop(address, None)
        if handle is not None:
            self.ringpop.timers.clear_timeout(handle)

    def stop_all(self) -> None:
        self.stopped = True
        for address, handle in list(self.timers.items()):
            self.ringpop.timers.clear_timeout(handle)
            del self.timers[address]

    def reenable(self) -> None:
        if self.stopped:
            self.stopped = False
