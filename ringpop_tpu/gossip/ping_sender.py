"""Direct-ping sender (lib/gossip/ping-sender.js rebuilt).

One ``/protocol/ping`` request per protocol period: body carries the local
checksum, the piggybacked changes, and the sender identity
(ping-sender.js:70-76); response changes are applied to membership
(ping-sender.js:30-43).  Default timeout 1500 ms (index.js:115).
"""

from __future__ import annotations

from typing import Any, Optional

from ringpop_tpu.net.channel import ChannelError, RemoteError

DEFAULT_PING_TIMEOUT_MS = 1500


class PingSender:
    def __init__(self, ringpop: Any, member, timeout_ms: Optional[int] = None):
        self.ringpop = ringpop
        self.address = getattr(member, "address", None) or member["address"]
        self.timeout_ms = timeout_ms or ringpop.ping_timeout_ms

    def send(self):
        """Returns (ok: bool, response_body|None)."""
        body = {
            "checksum": self.ringpop.membership.checksum,
            "changes": self.ringpop.dissemination.issue_as_sender(),
            "source": self.ringpop.whoami(),
            "sourceIncarnationNumber": self.ringpop.membership.get_incarnation_number(),
        }
        self.ringpop.stat("increment", "ping.send")
        if self.ringpop.debug_flag_enabled("ping"):
            self.ringpop.logger.info(
                "ping send",
                extra={"local": self.ringpop.whoami(), "member": self.address},
            )
        try:
            _, res = self.ringpop.channel.request(
                self.address,
                "/protocol/ping",
                head=None,
                body=body,
                timeout_s=self.timeout_ms / 1000.0,
            )
        except (ChannelError, RemoteError):
            return False, None
        if res and res.get("changes"):
            self.ringpop.membership.update(res["changes"])
        return True, res


def send_ping(ringpop: Any, member, timeout_ms: Optional[int] = None):
    return PingSender(ringpop, member, timeout_ms).send()
