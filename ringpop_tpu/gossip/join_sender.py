"""Cluster bootstrap join (lib/gossip/join-sender.js rebuilt).

Joins ``joinSize`` (3) cluster members before declaring bootstrap complete:
each round selects ``(joinSize - joined) * parallelismFactor`` targets —
preferring nodes on *other* hosts (join-sender.js:160-178,445-483) — sends
``/protocol/join`` concurrently, and retries with a delay until joinSize is
met, ``maxJoinAttempts`` (50) rounds pass, or ``maxJoinDuration`` (5 min)
elapses (join-sender.js:51-66,194-327).  All join responses are aggregated
and merged into membership once at the end (join-sender.js:250-259).
"""

from __future__ import annotations

import random
import threading
import time
from typing import Any, Dict, List, Optional

from ringpop_tpu.gossip.join_response_merge import merge_join_responses
from ringpop_tpu.net.channel import ChannelError, RemoteError
from ringpop_tpu.utils.util import capture_host

JOIN_SIZE = 3  # join-sender.js:52
JOIN_TIMEOUT_MS = 1000  # join-sender.js:56
JOIN_RETRY_DELAY_MS = 100  # join-sender.js:61
MAX_JOIN_DURATION_MS = 120000  # join-sender.js:64
PARALLELISM_FACTOR = 2  # join-sender.js:66


class JoinError(Exception):
    def __init__(self, message: str, type_: str):
        super().__init__(message)
        self.type = type_


class JoinCluster:
    def __init__(self, ringpop: Any, opts: Optional[Dict[str, Any]] = None):
        opts = opts or {}
        self.ringpop = ringpop
        self.host = capture_host(ringpop.whoami())
        self.join_size = opts.get("joinSize", JOIN_SIZE)
        self.join_timeout_ms = opts.get("joinTimeout", JOIN_TIMEOUT_MS)
        self.join_retry_delay_ms = opts.get("joinRetryDelay", JOIN_RETRY_DELAY_MS)
        self.max_join_duration_ms = opts.get("maxJoinDuration", MAX_JOIN_DURATION_MS)
        self.parallelism_factor = opts.get("parallelismFactor", PARALLELISM_FACTOR)
        self.potential_nodes = self._init_potential(ringpop.bootstrap_hosts or [])
        self.preferred_nodes: List[str] = []
        self.non_preferred_nodes: List[str] = []
        self.rng = getattr(ringpop, "rng", None) or random.Random()

    def _init_potential(self, hosts: List[str]) -> List[str]:
        return [h for h in hosts if h != self.ringpop.whoami()]

    def _select_group(self, num: int) -> List[str]:
        """Prefer nodes on other hosts (join-sender.js:445-483)."""
        self.preferred_nodes = [
            n for n in self.potential_nodes if capture_host(n) != self.host
        ]
        self.non_preferred_nodes = [
            n for n in self.potential_nodes if capture_host(n) == self.host
        ]
        pool = list(self.preferred_nodes)
        self.rng.shuffle(pool)
        group = pool[:num]
        if len(group) < num:
            rest = list(self.non_preferred_nodes)
            self.rng.shuffle(rest)
            group += rest[: num - len(group)]
        return group

    def _join_node(self, node: str):
        body = {
            "app": self.ringpop.app,
            "source": self.ringpop.whoami(),
            "incarnationNumber": self.ringpop.membership.get_incarnation_number(),
            "timeout": self.join_timeout_ms,
        }
        _, res = self.ringpop.channel.request(
            node,
            "/protocol/join",
            head=None,
            body=body,
            timeout_s=self.join_timeout_ms / 1000.0,
        )
        return res

    def join(self) -> Dict[str, Any]:
        """Blocking join; returns {nodesJoined, membership merged}."""
        if self.ringpop.destroyed:
            raise JoinError(
                "joiner was destroyed before joining cluster",
                "ringpop-tpu.joiner-destroyed",
            )
        if not self.potential_nodes:
            # single-node cluster (bootstrap handles this upstream too)
            return {"nodesJoined": []}

        start = time.time() * 1000.0
        nodes_joined: List[str] = []
        join_responses: List[Dict[str, Any]] = []
        failures: List[Dict[str, Any]] = []
        num_failed = 0
        num_groups = 0
        # like the reference, maxJoinAttempts bounds FAILED NODE attempts,
        # not retry rounds (join-sender.js:275-289 `numFailed >=
        # maxJoinAttempts`)
        max_attempts = self.ringpop.config.get("maxJoinAttempts")

        while len(nodes_joined) < self.join_size:
            if self.ringpop.destroyed:
                raise JoinError(
                    "joiner was destroyed while joining cluster",
                    "ringpop-tpu.joiner-destroyed",
                )
            elapsed = time.time() * 1000.0 - start
            if elapsed > self.max_join_duration_ms:
                self.ringpop.logger.warning(
                    "ringpop join duration exceeded",
                    extra={
                        "local": self.ringpop.whoami(),
                        "joinDuration": elapsed,
                        "maxJoinDuration": self.max_join_duration_ms,
                        "numJoined": len(nodes_joined),
                        "numFailed": num_failed,
                    },
                )
                raise JoinError(
                    "join duration exceeded", "ringpop-tpu.join-duration-exceeded"
                )
            if num_failed >= max_attempts:
                self.ringpop.logger.warning(
                    "ringpop max join attempts exceeded",
                    extra={
                        "local": self.ringpop.whoami(),
                        "joinAttempts": num_failed,
                        "maxJoinAttempts": max_attempts,
                        "numJoined": len(nodes_joined),
                        "failures": failures[-5:],
                    },
                )
                raise JoinError(
                    "max join attempts exceeded", "ringpop-tpu.join-attempts-exceeded"
                )

            remaining = [n for n in self.potential_nodes if n not in nodes_joined]
            if not remaining:
                break
            want = (self.join_size - len(nodes_joined)) * self.parallelism_factor
            self.potential_nodes = remaining
            group = self._select_group(want)
            if not group:
                break
            num_groups += 1

            results: List[Optional[Dict[str, Any]]] = [None] * len(group)
            errors_seen: List[Optional[Exception]] = [None] * len(group)

            def attempt(i: int, node: str) -> None:
                try:
                    results[i] = self._join_node(node)
                except (ChannelError, RemoteError) as e:
                    errors_seen[i] = e

            threads = [
                threading.Thread(target=attempt, args=(i, n), daemon=True)
                for i, n in enumerate(group)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(self.join_timeout_ms / 1000.0 + 1.0)

            for i, (node, res) in enumerate(zip(group, results)):
                if res is None:
                    # triage: transport failure vs application rejection
                    # (join-sender.js:233-283 error paths)
                    err = errors_seen[i]
                    if isinstance(err, RemoteError):
                        payload = err.payload
                        err_type = (
                            payload.get("type", "remote")
                            if isinstance(payload, dict)
                            else "remote"
                        )
                    elif isinstance(err, ChannelError):
                        err_type = err.type
                    else:
                        err_type = "timeout"
                    failures.append({"node": node, "errType": err_type})
                    num_failed += 1
                    self.ringpop.stat("increment", "join.failed")
                    continue
                if len(nodes_joined) >= self.join_size:
                    continue
                nodes_joined.append(node)
                join_responses.append(
                    {
                        "checksum": res.get("membershipChecksum"),
                        "members": res.get("membership") or [],
                    }
                )

            if len(nodes_joined) < self.join_size:
                candidates_left = [
                    n for n in self.potential_nodes if n not in nodes_joined
                ]
                if not candidates_left:
                    break
                self.ringpop.timers.sleep(self.join_retry_delay_ms / 1000.0)

        if not nodes_joined:
            raise JoinError("no nodes joined", "ringpop-tpu.join-failed")

        join_time_ms = time.time() * 1000.0 - start
        updates = merge_join_responses(self.ringpop, join_responses)
        self.ringpop.membership.update(updates)
        self.ringpop.stat("timing", "join", join_time_ms)
        self.ringpop.stat("increment", "join.complete")
        self.ringpop.logger.debug(
            "ringpop join complete",
            extra={
                "local": self.ringpop.whoami(),
                "joinSize": self.join_size,
                "joinTime": join_time_ms,
                "numJoined": len(nodes_joined),
                "numGroups": num_groups,
                "numFailed": num_failed,
            },
        )
        return {
            "nodesJoined": nodes_joined,
            "numJoined": len(nodes_joined),
            "numFailed": num_failed,
            "numGroups": num_groups,
            "failures": failures,
            "joinTime": join_time_ms,
        }


def join_cluster(ringpop: Any, opts: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    return JoinCluster(ringpop, opts).join()
