"""Host-side SWIM gossip engine: protocol-period loop, piggyback
dissemination, suspicion subprotocol, and the ping / ping-req / join senders
over the framed JSON channel."""

from ringpop_tpu.gossip.dissemination import Dissemination
from ringpop_tpu.gossip.gossip import Gossip
from ringpop_tpu.gossip.suspicion import Suspicion

__all__ = ["Dissemination", "Gossip", "Suspicion"]
