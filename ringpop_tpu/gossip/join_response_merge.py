"""Join-response aggregation (lib/gossip/join-response-merge.js rebuilt).

If every join response carries the same membership checksum, the first
response's membership is taken verbatim; otherwise the changesets merge,
keeping the highest incarnation per address (join-response-merge.js:24-56).
"""

from __future__ import annotations

from typing import Any, Dict, List

from ringpop_tpu.models.membership.host import (
    Update,
    merge_membership_changesets,
)


def merge_join_responses(
    ringpop: Any, responses: List[Dict[str, Any]]
) -> List[Update]:
    if not responses:
        return []
    checksums = {r.get("checksum") for r in responses}
    if len(checksums) == 1 and None not in checksums:
        members = responses[0].get("members") or []
        return [Update.from_dict(m) for m in members]
    changesets = [
        [Update.from_dict(m) for m in (r.get("members") or [])]
        for r in responses
    ]
    return merge_membership_changesets(ringpop, changesets)
