"""Piggyback change dissemination (lib/gossip/dissemination.js rebuilt).

Membership changes ride on ping/ping-req bodies until each has been issued
``15 * ceil(log10(serverCount + 1))`` times (dissemination.js:38-55), then
drop out of the buffer.  The receive side filters changes the requester
itself originated (dissemination.js:91-98) and falls back to a **full sync**
— the entire membership — when it has no changes left but the checksums
disagree (dissemination.js:101-114).

Quirk preserved: piggyback counts bump when a change is *issued*, even if
the send later fails (dissemination.js:142-155 documents this as a TODO).
"""

from __future__ import annotations

import math
from typing import Any, Dict, List, Optional

from ringpop_tpu.models.membership.host import Update
from ringpop_tpu.utils.config import EventEmitter


class Dissemination(EventEmitter):
    DEFAULT_MAX_PIGGYBACK_COUNT = 1  # dissemination.js:179
    PIGGYBACK_FACTOR = 15  # dissemination.js:180

    def __init__(self, ringpop: Any):
        super().__init__()
        self.ringpop = ringpop
        self.changes: Dict[str, Dict[str, Any]] = {}
        self.max_piggyback_count = self.DEFAULT_MAX_PIGGYBACK_COUNT

    # -- piggyback scaling ------------------------------------------------

    def adjust_max_piggyback_count(self) -> None:
        """15 * ceil(log10(serverCount + 1)) from the ring's server count
        (dissemination.js:38-55); emits when the bound changes."""
        server_count = self.ringpop.ring.get_server_count()
        prev = self.max_piggyback_count
        new = self.PIGGYBACK_FACTOR * math.ceil(math.log10(server_count + 1))
        if new != prev:
            self.max_piggyback_count = new
            self.ringpop.stat("gauge", "max-piggyback", new)
            self.emit("maxPiggybackCountAdjusted")

    # -- change buffer ----------------------------------------------------

    def record_change(self, change) -> None:
        if isinstance(change, Update):
            change = change.to_dict()
        self.changes[change["address"]] = dict(change, piggybackCount=0)

    def clear_changes(self) -> None:
        self.changes = {}

    def get_change_count(self) -> int:
        return len(self.changes)

    def full_sync(self) -> List[Dict[str, Any]]:
        """The entire membership as a changeset (dissemination.js:61-76)."""
        membership = self.ringpop.membership
        return [
            {
                "source": self.ringpop.whoami(),
                "address": m.address,
                "status": m.status,
                "incarnationNumber": m.incarnation_number,
            }
            for m in membership.members
        ]

    # -- issuing ----------------------------------------------------------

    def issue_as_sender(self) -> List[Dict[str, Any]]:
        return self._issue_changes()

    def issue_as_receiver(
        self,
        sender_addr: str,
        sender_incarnation_number: Optional[int],
        sender_checksum: Optional[int],
    ):
        """Changes for a ping response; full sync when empty + checksums
        differ.  Returns (changes, did_full_sync)."""

        def keep(change: Dict[str, Any]) -> bool:
            # filter changes the requester originated; all four fields must
            # be truthy before the comparison fires (dissemination.js:90-97)
            return not (
                sender_addr
                and sender_incarnation_number
                and change.get("source")
                and change.get("sourceIncarnationNumber")
                and change["source"] == sender_addr
                and change["sourceIncarnationNumber"]
                == sender_incarnation_number
            )

        changes = self._issue_changes(keep)
        if changes:
            return changes, False
        # a missing sender checksum still counts as a mismatch — the JS
        # `checksum !== senderChecksum` is true for undefined
        # (dissemination.js:101-114)
        if self.ringpop.membership.checksum != sender_checksum:
            self.ringpop.stat("increment", "full-sync")
            self.ringpop.logger.info(
                "ringpop dissemination full sync",
                extra={
                    "local": self.ringpop.whoami(),
                    "localChecksum": self.ringpop.membership.checksum,
                    "dest": sender_addr,
                    "destChecksum": sender_checksum,
                },
            )
            return self.full_sync(), True
        return [], False

    def _issue_changes(self, keep=None) -> List[Dict[str, Any]]:
        issued = []
        for address in list(self.changes.keys()):
            change = self.changes[address]
            # receiver-origin filter runs BEFORE the piggyback bump, so
            # filtered changes don't burn budget (dissemination.js:147-160)
            if keep is not None and not keep(change):
                self.ringpop.stat("increment", "filtered-change")
                continue
            # bump regardless of eventual send success (reference TODO quirk,
            # dissemination.js:142-155)
            change["piggybackCount"] += 1
            if change["piggybackCount"] > self.max_piggyback_count:
                del self.changes[address]
                continue
            issued.append(
                {
                    "id": change.get("id"),
                    "source": change.get("source"),
                    "sourceIncarnationNumber": change.get(
                        "sourceIncarnationNumber"
                    ),
                    "address": change["address"],
                    "status": change["status"],
                    "incarnationNumber": change["incarnationNumber"],
                }
            )
        self.ringpop.stat("gauge", "changes.disseminate", len(issued))
        return issued
