"""Indirect-probe fan-out (lib/gossip/ping-req-sender.js rebuilt).

When the direct ping fails, fan out ``/protocol/ping-req`` to
``pingReqSize`` (3) random pingable members excluding the target
(ping-req-sender.js:293-296).  Outcomes (ping-req-sender.js:148-297):

- no eligible intermediaries -> the target is suspected immediately
  (ping-req-sender.js:162-169);
- any intermediary reports ``pingStatus: true`` -> the target is reachable;
- every responding intermediary reports ``pingStatus: false`` -> suspect;
- nothing but transport errors -> inconclusive, no state change.

Responses' piggybacked changes are applied either way.  Default timeout
5000 ms (index.js:114).
"""

from __future__ import annotations

import threading
from typing import Any, List, Optional

from ringpop_tpu.net.channel import ChannelError, RemoteError

DEFAULT_PING_REQ_TIMEOUT_MS = 5000


class PingReqResult:
    def __init__(self, member, ok: bool, ping_status: Optional[bool], body=None):
        self.member = member
        self.ok = ok  # transport-level success
        self.ping_status = ping_status
        self.body = body


def send_ping_req(ringpop: Any, target, size: Optional[int] = None):
    """Returns True if the target was confirmed reachable, False if it was
    declared suspect, None if inconclusive."""
    size = size or ringpop.ping_req_size
    target_addr = getattr(target, "address", None) or target["address"]
    peers = ringpop.membership.get_random_pingable_members(
        size, excluding=[target_addr, ringpop.whoami()]
    )
    ringpop.stat("increment", "ping-req.send")

    if not peers:
        # no possible intermediaries: suspect straight away
        # (ping-req-sender.js:162-169)
        ringpop.membership.make_suspect(
            target_addr, _incarnation_of(ringpop, target)
        )
        return False

    results: List[PingReqResult] = [None] * len(peers)

    def probe(i: int, peer) -> None:
        body = {
            "checksum": ringpop.membership.checksum,
            "changes": ringpop.dissemination.issue_as_sender(),
            "source": ringpop.whoami(),
            "sourceIncarnationNumber": ringpop.membership.get_incarnation_number(),
            "target": target_addr,
        }
        try:
            _, res = ringpop.channel.request(
                peer.address,
                "/protocol/ping-req",
                head=None,
                body=body,
                timeout_s=ringpop.ping_req_timeout_ms / 1000.0,
            )
            results[i] = PingReqResult(peer, True, bool(res.get("pingStatus")), res)
        except (ChannelError, RemoteError):
            results[i] = PingReqResult(peer, False, None)

    threads = [
        threading.Thread(target=probe, args=(i, p), daemon=True)
        for i, p in enumerate(peers)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(ringpop.ping_req_timeout_ms / 1000.0 + 1.0)

    responded = [r for r in results if r is not None and r.ok]
    for r in responded:
        if r.body and r.body.get("changes"):
            ringpop.membership.update(r.body["changes"])

    if any(r.ping_status for r in responded):
        ringpop.stat("increment", "ping-req.others.ping-status.true")
        return True
    if responded:
        # all intermediaries reached the middle hop but none reached the
        # target (ping-req-sender.js:249-262)
        ringpop.stat("increment", "ping-req.others.ping-status.false")
        ringpop.logger.info(
            "ringpop member declares member suspect",
            extra={"local": ringpop.whoami(), "suspect": target_addr},
        )
        ringpop.membership.make_suspect(
            target_addr, _incarnation_of(ringpop, target)
        )
        return False
    ringpop.stat("increment", "ping-req.inconclusive")
    return None


def _incarnation_of(ringpop: Any, target) -> Optional[int]:
    addr = getattr(target, "address", None) or target["address"]
    member = ringpop.membership.find_member_by_address(addr)
    if member is not None:
        return member.incarnation_number
    return getattr(target, "incarnation_number", None)
