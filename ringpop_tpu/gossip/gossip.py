"""The SWIM protocol-period loop (lib/gossip/index.js rebuilt).

A self-rescheduling timer runs one protocol period at a time: pick the next
round-robin member (membership/iterator.js), direct-ping it, and on failure
fan out indirect probes (gossip/index.js:135-192).  The period adapts to
2x the p50 of observed tick latency, floored at ``minProtocolPeriod`` =
200 ms (gossip/index.js:42-55,194-196); the first tick is staggered by a
random 0..200 ms (gossip/index.js:48).
"""

from __future__ import annotations

import random
import threading
import time
from typing import Any, Optional

from ringpop_tpu.gossip.ping_req_sender import send_ping_req
from ringpop_tpu.gossip.ping_sender import send_ping
from ringpop_tpu.utils.stats import Histogram

MIN_PROTOCOL_PERIOD_MS = 200  # gossip/index.js:194-196


class Gossip:
    def __init__(
        self,
        ringpop: Any,
        min_protocol_period_ms: int = MIN_PROTOCOL_PERIOD_MS,
        rng: Optional[random.Random] = None,
    ):
        self.ringpop = ringpop
        self.min_protocol_period_ms = min_protocol_period_ms
        self.is_stopped = True
        self.is_pinging = False
        self.protocol_periods = 0
        self.protocol_timing = Histogram()
        self.last_protocol_period: Optional[float] = None
        self.last_protocol_rate_ms: Optional[float] = None
        self.num_changes_disseminated = 0
        self._timer = None
        self._rng = rng or random.Random()
        self._lock = threading.Lock()

    # -- rate adaptation --------------------------------------------------

    def compute_protocol_delay_ms(self) -> float:
        """gossip/index.js:42-50: adaptive once a period has run; a random
        0..minProtocolPeriod stagger for the very first tick."""
        if self.protocol_periods:
            target = (self.last_protocol_period or 0) + (
                self.last_protocol_rate_ms or 0
            )
            return max(target - time.time() * 1000.0, self.min_protocol_period_ms)
        return self._rng.random() * self.min_protocol_period_ms

    def compute_protocol_rate_ms(self) -> float:
        """gossip/index.js:52-55: 2x observed p50, floored."""
        p50 = self.protocol_timing.percentiles([0.5])[0.5] or 0.0
        return max(p50 * 2.0, self.min_protocol_period_ms)

    # -- lifecycle --------------------------------------------------------

    def start(self) -> None:
        if not self.is_stopped:
            self.ringpop.logger.debug(
                "gossip has already started", extra={"local": self.ringpop.whoami()}
            )
            return
        self.ringpop.membership.shuffle()
        self.is_stopped = False
        self._run()
        self.ringpop.logger.debug(
            "ringpop gossip protocol started", extra={"local": self.ringpop.whoami()}
        )

    def stop(self) -> None:
        if self.is_stopped:
            self.ringpop.logger.warning(
                "gossip is already stopped", extra={"local": self.ringpop.whoami()}
            )
            return
        self.ringpop.timers.clear_timeout(self._timer)
        self._timer = None
        self.is_stopped = True
        self.ringpop.logger.debug(
            "ringpop gossip protocol stopped", extra={"local": self.ringpop.whoami()}
        )

    def _run(self) -> None:
        delay_ms = self.compute_protocol_delay_ms()
        self.ringpop.stat("timing", "protocol.delay", delay_ms)

        def fire():
            if self.is_stopped:
                return
            start = time.time()
            self.tick()
            elapsed_ms = (time.time() - start) * 1000.0
            self.protocol_timing.update(elapsed_ms)
            self.ringpop.stat("timing", "protocol.frequency", elapsed_ms)
            self.protocol_periods += 1
            self.last_protocol_period = time.time() * 1000.0
            self.last_protocol_rate_ms = self.compute_protocol_rate_ms()
            if not self.is_stopped:
                self._run()

        self._timer = self.ringpop.timers.set_timeout(fire, delay_ms / 1000.0)

    # -- one protocol period ---------------------------------------------

    def tick(self) -> None:
        """One period: iterate -> ping -> (on failure) ping-req.
        Overlapping periods are skipped via the isPinging guard
        (gossip/index.js:138-141)."""
        with self._lock:
            if self.is_pinging:
                self.ringpop.stat("increment", "gossip.tick.skipped")
                return
            self.is_pinging = True
        try:
            member = self.ringpop.member_iterator.next()
            if member is None:
                return
            ok, _ = send_ping(self.ringpop, member)
            if ok:
                self.ringpop.stat("increment", "ping.success")
                return
            if self.is_stopped:
                return
            self.ringpop.stat("increment", "ping.failure")
            send_ping_req(self.ringpop, member)
        finally:
            self.is_pinging = False

    def get_stats(self) -> dict:
        return {
            "protocolRate": self.compute_protocol_rate_ms(),
            "protocolPeriods": self.protocol_periods,
            "lastProtocolRate": self.last_protocol_rate_ms,
            "numChangesDisseminated": self.num_changes_disseminated,
            "protocolTiming": self.protocol_timing.to_dict(),
        }
