"""In-jit FarmHash32 (farmhashmk) over ragged byte rows.

The device twin of :mod:`ringpop_tpu.ops.farmhash32`: hashes each row of a
padded ``[B, L] uint8`` matrix with per-row lengths, entirely inside the jit
graph, so membership/ring checksums (lib/membership/index.js:48-75,
lib/ring/index.js:96-105) can live in the same compiled step as the SWIM
update rule.

TPU-first design notes:

- All state is ``uint32`` lanes vectorized across the row (batch) axis — the
  block loop of the long-path hash is sequential *per row* but runs B lanes
  wide, so a 1k-node cluster computes 1k checksums in lockstep on the VPU.
- The main 20-byte block loop reads at offsets ``20*i + {0,4,8,12,16}``,
  all 4-aligned: the byte matrix is pre-packed once into an aligned
  little-endian ``uint32`` word view, turning 20 byte-gathers per block into
  5 word-gathers.  Only the five unaligned tail fetches gather bytes.
- The loop is a ``lax.scan`` over pre-sliced word blocks with trip count
  ``(L-1)//20`` (static from the padded width) and per-row active masks —
  no dynamic shapes.  A Pallas TPU kernel for the same loop is opt-in via
  RINGPOP_TPU_PALLAS=1 (:mod:`ringpop_tpu.ops.pallas_farmhash`).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

# numpy scalars, not jnp: importing this module must stay device-free (the
# ambient env pins a single-client TPU tunnel; backend init belongs to callers)
C1 = np.uint32(0xCC9E2D51)
C2 = np.uint32(0x1B873593)
FIVE = np.uint32(5)
MAGIC = np.uint32(0xE6546B64)


def _impl_from_env() -> str:
    """Block-loop implementation: 'pallas' (opt-in via RINGPOP_TPU_PALLAS=1),
    'pallas_nogrid' (RINGPOP_TPU_PALLAS=nogrid — the gridless variant the
    axon tunnel's compile helper accepts; interpret mode off-TPU so tests
    validate the kernels everywhere) or 'scan'.  With the env unset the
    default is backend-dependent: 'pallas_nogrid' on a real TPU (21x the
    scan lowering at the parity bench shape, RESULTS_TPU_r04.json —
    measured, digest-validated), 'scan' elsewhere (interpret-mode Pallas
    on CPU is orders slower than the scan lowering)."""
    import os

    val = os.environ.get("RINGPOP_TPU_PALLAS", "")
    if val == "1":
        return "pallas"
    if val == "nogrid":
        return "pallas_nogrid"
    if val:
        # any other explicit value (incl. "0"/"scan") disables the
        # kernels — the natural inverse of the documented opt-ins
        return "scan"
    import jax

    return "pallas_nogrid" if jax.default_backend() == "tpu" else "scan"


def _rot(x: jax.Array, r: int) -> jax.Array:
    if r == 0:
        return x
    return (x >> jnp.uint32(r)) | (x << jnp.uint32(32 - r))


def _fmix(h: jax.Array) -> jax.Array:
    h = h ^ (h >> jnp.uint32(16))
    h = h * jnp.uint32(0x85EBCA6B)
    h = h ^ (h >> jnp.uint32(13))
    h = h * jnp.uint32(0xC2B2AE35)
    h = h ^ (h >> jnp.uint32(16))
    return h


def _mur(a: jax.Array, h: jax.Array) -> jax.Array:
    a = a * C1
    a = _rot(a, 17)
    a = a * C2
    h = h ^ a
    h = _rot(h, 19)
    return h * FIVE + MAGIC


def _fetch32(mat: jax.Array, off: jax.Array) -> jax.Array:
    """Per-row little-endian 4-byte fetch at (possibly unaligned) offsets."""
    off = jnp.clip(off, 0, mat.shape[1] - 4)
    idx = off[:, None] + jnp.arange(4, dtype=jnp.int32)[None, :]
    b = jnp.take_along_axis(mat, idx, axis=1).astype(jnp.uint32)
    return b[:, 0] | (b[:, 1] << 8) | (b[:, 2] << 16) | (b[:, 3] << 24)


def pack_words(mat: jax.Array) -> jax.Array:
    """Pack ``[B, L] uint8`` into aligned LE ``[B, ceil(L/4)] uint32`` words."""
    B, L = mat.shape
    pad = (-L) % 4
    if pad:
        mat = jnp.pad(mat, ((0, 0), (0, pad)))
    w = mat.reshape(B, -1, 4).astype(jnp.uint32)
    return w[..., 0] | (w[..., 1] << 8) | (w[..., 2] << 16) | (w[..., 3] << 24)


def _hash_0_4(mat: jax.Array, lens: jax.Array) -> jax.Array:
    n = lens.astype(jnp.uint32)
    B = mat.shape[0]
    b = jnp.zeros(B, jnp.uint32)
    c = jnp.full(B, 9, jnp.uint32)
    for i in range(4):
        active = lens > i
        # signed char semantics: sign-extend bytes >= 0x80
        v = mat[:, min(i, mat.shape[1] - 1)].astype(jnp.int8).astype(jnp.int32).astype(jnp.uint32)
        nb = b * C1 + v
        b = jnp.where(active, nb, b)
        c = jnp.where(active, c ^ nb, c)
    return _fmix(_mur(b, _mur(n, c)))


def _hash_5_12(mat: jax.Array, lens: jax.Array) -> jax.Array:
    n = lens.astype(jnp.uint32)
    zeros = jnp.zeros_like(lens)
    a = n + _fetch32(mat, zeros)
    b = n * FIVE + _fetch32(mat, lens - 4)
    c = jnp.uint32(9) + _fetch32(mat, (lens >> 1) & 4)
    d = n * FIVE  # seed = 0
    return _fmix(_mur(c, _mur(b, _mur(a, d))))


def _hash_13_24(mat: jax.Array, lens: jax.Array) -> jax.Array:
    n = lens.astype(jnp.uint32)
    a = _fetch32(mat, (lens >> 1) - 4)
    b = _fetch32(mat, jnp.full_like(lens, 4))
    c = _fetch32(mat, lens - 8)
    d = _fetch32(mat, lens >> 1)
    e = _fetch32(mat, jnp.zeros_like(lens))
    f = _fetch32(mat, lens - 4)
    h = d * C1 + n  # seed = 0
    a = _rot(a, 12) + f
    h = _mur(c, h) + a
    a = _rot(a, 3) + c
    h = _mur(e, h) + a
    a = _rot(a + f, 12) + d
    h = _mur(b, h) + a  # b ^ seed, seed = 0
    return _fmix(h)


def _hash_long(
    mat: jax.Array,
    words: jax.Array,
    lens: jax.Array,
    impl: str = "scan",
) -> jax.Array:
    n32 = lens.astype(jnp.uint32)
    h = n32
    g = C1 * n32
    f = g

    def tail(off_from_end: int) -> jax.Array:
        v = _fetch32(mat, lens - off_from_end)
        return _rot(v * C1, 17) * C2

    a0, a1, a2, a3, a4 = tail(4), tail(8), tail(16), tail(12), tail(20)
    h = h ^ a0
    h = _rot(h, 19) * FIVE + MAGIC
    h = h ^ a2
    h = _rot(h, 19) * FIVE + MAGIC
    g = g ^ a1
    g = _rot(g, 19) * FIVE + MAGIC
    g = g ^ a3
    g = _rot(g, 19) * FIVE + MAGIC
    f = f + a4
    f = _rot(f, 19) + jnp.uint32(113)

    iters = (lens - 1) // 20
    max_iters = max((mat.shape[1] - 1) // 20, 1)
    # pre-slice the aligned word stream into per-iteration blocks: each
    # step reads its block directly instead of issuing five dynamic
    # word-gathers (the former fori_loop body was gather-bound — ~6x the
    # per-tick cost at 1k nodes)
    need = 5 * max_iters
    w = words
    if w.shape[1] < need:
        w = jnp.pad(w, ((0, 0), (0, need - w.shape[1])))

    if impl in ("pallas", "pallas_nogrid"):
        from ringpop_tpu.ops import pallas_farmhash

        blocks_bi5 = w[:, :need].reshape(w.shape[0], max_iters, 5)
        loop = (
            pallas_farmhash.block_loop_nogrid
            if impl == "pallas_nogrid"
            else pallas_farmhash.block_loop
        )
        h, g, f = loop(
            h,
            g,
            f,
            blocks_bi5,
            iters.astype(jnp.int32),
            interpret=jax.devices()[0].platform != "tpu",
        )
    else:
        blocks = (
            w[:, :need].reshape(w.shape[0], max_iters, 5).transpose(1, 0, 2)
        )

        def body(state, blk):
            h, g, f, i = state
            active = i < iters
            a, b, c, d, e = (blk[:, j] for j in range(5))
            nh = h + a
            ng = g + b
            nf = f + c
            nh = _mur(d, nh) + e
            ng = _mur(c, ng) + a
            nf = _mur(b + e * C1, nf) + d
            nf = nf + ng
            ng = ng + nf
            return (
                jnp.where(active, nh, h),
                jnp.where(active, ng, g),
                jnp.where(active, nf, f),
                i + 1,
            ), None

        (h, g, f, _), _ = jax.lax.scan(
            body, (h, g, f, jnp.int32(0)), blocks
        )

    g = _rot(g, 11) * C1
    g = _rot(g, 17) * C1
    f = _rot(f, 11) * C1
    f = _rot(f, 17) * C1
    h = _rot(h + g, 19)
    h = h * FIVE + MAGIC
    h = _rot(h, 17) * C1
    h = _rot(h + f, 19)
    h = h * FIVE + MAGIC
    h = _rot(h, 17) * C1
    return h


def hash32_rows(
    mat: jax.Array, lens: jax.Array, impl: "str | None" = None
) -> jax.Array:
    """farmhashmk::Hash32 of each padded row — jit-friendly, ``[B] uint32``.

    ``mat`` must carry >= 4 bytes of zero slack beyond the longest row (use
    :func:`ringpop_tpu.ops.farmhash32.encode_rows` on host, or allocate the
    device buffer with slack).  ``impl`` selects the block-loop lowering
    ('scan' default, 'pallas' opt-in); None reads RINGPOP_TPU_PALLAS at
    trace time.
    """
    if impl is None:
        impl = _impl_from_env()
    mat = mat.astype(jnp.uint8)
    lens = lens.astype(jnp.int32) if lens.dtype not in (jnp.int32, jnp.int64) else lens
    words = pack_words(mat)
    out = _hash_0_4(mat, lens)
    out = jnp.where(lens > 4, _hash_5_12(mat, lens), out)
    out = jnp.where(lens > 12, _hash_13_24(mat, lens), out)
    out = jnp.where(lens > 24, _hash_long(mat, words, lens, impl), out)
    return out


@functools.lru_cache(maxsize=None)
def _jitted_rows(impl: str):
    return jax.jit(functools.partial(hash32_rows, impl=impl))


def hash32_rows_jit(mat: jax.Array, lens: jax.Array) -> jax.Array:
    """Jitted :func:`hash32_rows`; the env-selected impl is part of the jit
    cache key, so toggling RINGPOP_TPU_PALLAS mid-process takes effect."""
    return _jitted_rows(_impl_from_env())(mat, lens)


def hash32_strings_device(strings) -> np.ndarray:
    """Host convenience: encode on host, hash on device (for tests)."""
    from ringpop_tpu.ops.farmhash32 import encode_rows

    mat, lens = encode_rows(strings)
    return np.asarray(hash32_rows_jit(jnp.asarray(mat), jnp.asarray(lens)))
