"""ctypes loader for the native FarmHash32 oracle.

Builds ``_native/libfarmhash.so`` on first use (g++ is in the base image;
pybind11 is not, hence the plain C ABI).  Falls back to the numpy
implementation transparently if the toolchain is unavailable, so the package
stays importable everywhere.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Sequence, Union

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "_native", "farmhash.cc")
_LIB = os.path.join(_HERE, "_native", "libfarmhash.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_build_failed = False


def _build() -> bool:
    try:
        subprocess.run(
            ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", _SRC, "-o", _LIB],
            check=True,
            capture_output=True,
        )
        return True
    except (subprocess.CalledProcessError, FileNotFoundError):
        return False


def get_lib() -> Optional[ctypes.CDLL]:
    """Load (building if needed) the native library; None if unavailable."""
    global _lib, _build_failed
    if _lib is not None:
        return _lib
    if _build_failed:
        return None
    with _lock:
        if _lib is not None:
            return _lib
        have_src = os.path.exists(_SRC)
        stale = (
            not os.path.exists(_LIB)
            or (have_src and os.path.getmtime(_LIB) < os.path.getmtime(_SRC))
        )
        if stale:
            if not have_src or not _build():
                _build_failed = True
                return None
        try:
            lib = ctypes.CDLL(_LIB)
        except OSError:
            _build_failed = True
            return None
        lib.rp_farmhash32.restype = ctypes.c_uint32
        lib.rp_farmhash32.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
        lib.rp_farmhash32_batch.restype = None
        lib.rp_farmhash32_batch.argtypes = [
            ctypes.c_void_p,
            ctypes.c_uint64,
            ctypes.c_void_p,
            ctypes.c_uint64,
            ctypes.c_void_p,
        ]
        lib.rp_replica_hashes.restype = None
        lib.rp_replica_hashes.argtypes = [
            ctypes.c_char_p,
            ctypes.c_uint64,
            ctypes.c_uint64,
            ctypes.c_void_p,
        ]
        _lib = lib
        return _lib


def available() -> bool:
    return get_lib() is not None


def hash32(data: Union[bytes, str]) -> int:
    """Native farmhashmk::Hash32; falls back to pure Python."""
    if isinstance(data, str):
        data = data.encode("utf-8")
    lib = get_lib()
    if lib is None:
        from ringpop_tpu.ops import farmhash32 as py

        return py.hash32(data)
    return int(lib.rp_farmhash32(data, len(data)))


def hash32_batch(mat: np.ndarray, lens: np.ndarray) -> np.ndarray:
    """Native batch hash over padded rows; falls back to numpy."""
    lib = get_lib()
    if lib is None:
        from ringpop_tpu.ops import farmhash32 as py

        return py.hash32_batch(mat, lens)
    mat = np.ascontiguousarray(mat, dtype=np.uint8)
    lens64 = np.ascontiguousarray(lens, dtype=np.uint64)
    max_len = int(lens64.max(initial=0))
    if max_len > mat.shape[1]:
        raise ValueError(
            "lens exceed matrix width (%d > %d)" % (max_len, mat.shape[1])
        )
    out = np.empty(mat.shape[0], dtype=np.uint32)
    lib.rp_farmhash32_batch(
        mat.ctypes.data,
        mat.shape[1],
        lens64.ctypes.data,
        mat.shape[0],
        out.ctypes.data,
    )
    return out


def replica_hashes(name: Union[bytes, str], replica_points: int) -> np.ndarray:
    """hash32(f"{name}{i}") for i in range(replica_points) — the ring's
    replica expansion (lib/ring/index.js:54-57)."""
    if isinstance(name, str):
        name = name.encode("utf-8")
    lib = get_lib()
    if lib is None or len(name) > 480:
        from ringpop_tpu.ops import farmhash32 as py

        return np.array(
            [py.hash32(name + str(i).encode()) for i in range(replica_points)],
            dtype=np.uint32,
        )
    out = np.empty(replica_points, dtype=np.uint32)
    lib.rp_replica_hashes(name, len(name), replica_points, out.ctypes.data)
    return out
