"""Pallas TPU kernel for the FarmHash32 20-byte block loop.

The long-string path of farmhashmk chains (h, g, f) through one mixing
round per 20-byte block — sequential per row, embarrassingly parallel
across rows.  This kernel streams the pre-packed word blocks from HBM
through VMEM one iteration tile at a time (grid = row-tiles x iterations,
Pallas double-buffers the fetches), keeps the three carries in VMEM
scratch across the iteration axis, and writes each row-tile's result once
on the last iteration.

Layout: rows are tiled (8 sublanes x 128 lanes) = 1024 rows per grid row;
each iteration step loads a [8, 128, 5] uint32 block (20 KB), so VMEM
holds carries + two in-flight blocks regardless of string length.

Used by :func:`ringpop_tpu.ops.jax_farmhash.hash32_rows` when
``RINGPOP_TPU_PALLAS=1`` (interpret mode off-TPU keeps tests hermetic);
the default remains the `lax.scan` lowering, which the dirty-row checksum
cache already keeps off the critical path on quiet ticks.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

# one source of truth for the farmhashmk constants and mixing primitives
# (no import cycle: jax_farmhash imports this module lazily)
from ringpop_tpu.ops.jax_farmhash import C1, _mur

SUB, LANE = 8, 128
TILE = SUB * LANE  # rows per grid step


def _kernel(iters_ref, blocks_ref, h0_ref, g0_ref, f0_ref,
            oh_ref, og_ref, of_ref, h_s, g_s, f_s, *, max_iters: int):
    from jax.experimental import pallas as pl

    i = pl.program_id(1)

    @pl.when(i == 0)
    def _():
        h_s[:] = h0_ref[0]
        g_s[:] = g0_ref[0]
        f_s[:] = f0_ref[0]

    blk = blocks_ref[0, 0]  # [SUB, LANE, 5] uint32
    a = blk[:, :, 0]
    b = blk[:, :, 1]
    c = blk[:, :, 2]
    d = blk[:, :, 3]
    e = blk[:, :, 4]

    h = h_s[:]
    g = g_s[:]
    f = f_s[:]
    nh = h + a
    ng = g + b
    nf = f + c
    nh = _mur(d, nh) + e
    ng = _mur(c, ng) + a
    nf = _mur(b + e * C1, nf) + d
    nf = nf + ng
    ng = ng + nf

    active = i < iters_ref[0]
    h_s[:] = jnp.where(active, nh, h)
    g_s[:] = jnp.where(active, ng, g)
    f_s[:] = jnp.where(active, nf, f)

    @pl.when(i == max_iters - 1)
    def _():
        oh_ref[0] = h_s[:]
        og_ref[0] = g_s[:]
        of_ref[0] = f_s[:]


def block_loop(h0, g0, f0, blocks, iters, *, interpret: bool = False):
    """Run the farmhashmk block loop on TPU via Pallas.

    ``h0/g0/f0``: [B] uint32 carries after tail mixing; ``blocks``:
    [B, max_iters, 5] uint32 aligned words; ``iters``: [B] per-row trip
    counts.  Returns (h, g, f) [B] uint32.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, max_iters, five = blocks.shape
    assert five == 5
    pad = (-B) % TILE
    if pad:
        h0 = jnp.pad(h0, (0, pad))
        g0 = jnp.pad(g0, (0, pad))
        f0 = jnp.pad(f0, (0, pad))
        blocks = jnp.pad(blocks, ((0, pad), (0, 0), (0, 0)))
        iters = jnp.pad(iters, (0, pad))
    bp = B + pad
    gsz = bp // TILE

    def rows(x):
        return x.reshape(gsz, SUB, LANE)

    blocks_t = (
        blocks.reshape(gsz, SUB, LANE, max_iters, 5)
        .transpose(0, 3, 1, 2, 4)  # [G, I, SUB, LANE, 5]
    )

    row_spec = pl.BlockSpec(
        (1, SUB, LANE), lambda g, i: (g, 0, 0), memory_space=pltpu.VMEM
    )
    out = pl.pallas_call(
        functools.partial(_kernel, max_iters=max_iters),
        grid=(gsz, max_iters),
        in_specs=[
            row_spec,  # iters
            pl.BlockSpec(
                (1, 1, SUB, LANE, 5),
                lambda g, i: (g, i, 0, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            row_spec,  # h0
            row_spec,  # g0
            row_spec,  # f0
        ],
        out_specs=[row_spec, row_spec, row_spec],
        out_shape=[
            jax.ShapeDtypeStruct((gsz, SUB, LANE), jnp.uint32)
            for _ in range(3)
        ],
        scratch_shapes=[
            pltpu.VMEM((SUB, LANE), jnp.uint32) for _ in range(3)
        ],
        interpret=interpret,
    )(
        rows(iters.astype(jnp.int32)),
        blocks_t,
        rows(h0),
        rows(g0),
        rows(f0),
    )
    h, g, f = (x.reshape(bp)[:B] for x in out)
    return h, g, f


def _nogrid_kernel(blk_ref, act_ref, h0_ref, g0_ref, f0_ref,
                   oh_ref, og_ref, of_ref):
    """One gridless call = ``chunk`` mixing rounds over a [S, LANE] row
    tile, carries entering/leaving as plain operands."""

    def body(k, carry):
        h, g, f = carry
        a = blk_ref[k, 0]
        b = blk_ref[k, 1]
        c = blk_ref[k, 2]
        d = blk_ref[k, 3]
        e = blk_ref[k, 4]
        nh = h + a
        ng = g + b
        nf = f + c
        nh = _mur(d, nh) + e
        ng = _mur(c, ng) + a
        nf = _mur(b + e * C1, nf) + d
        nf = nf + ng
        ng = ng + nf
        act = act_ref[k] != 0
        return (
            jnp.where(act, nh, h),
            jnp.where(act, ng, g),
            jnp.where(act, nf, f),
        )

    h, g, f = jax.lax.fori_loop(
        0, blk_ref.shape[0], body, (h0_ref[:], g0_ref[:], f0_ref[:])
    )
    oh_ref[:] = h
    og_ref[:] = g
    of_ref[:] = f


def block_loop_nogrid(
    h0,
    g0,
    f0,
    blocks,
    iters,
    *,
    chunk: int = 64,
    interpret: bool = False,
    vmem_budget: int = 8 * 1024 * 1024,
):
    """Gridless variant of :func:`block_loop` for the axon tunnel, whose
    remote-compile helper deterministically 500s on ANY grid'd Pallas
    kernel while compiling gridless ones fine (PALLAS_BISECT.json: `copy`/
    `nogrid_*` ok, every `grid*` rung and the grid'd farmhash fail).

    The iteration axis moves out of the Pallas grid into an outer XLA
    ``lax.scan``; each scan step is ONE gridless pallas_call running
    ``chunk`` mixing rounds via an in-kernel ``fori_loop`` with the whole
    [chunk, 5, S, LANE] block slab resident in VMEM.  Same signature and
    bit-exact results as :func:`block_loop`.
    """
    from jax.experimental import pallas as pl

    B, max_iters, five = blocks.shape
    assert five == 5
    pad = (-B) % TILE
    if pad:
        h0 = jnp.pad(h0, (0, pad))
        g0 = jnp.pad(g0, (0, pad))
        f0 = jnp.pad(f0, (0, pad))
        blocks = jnp.pad(blocks, ((0, pad), (0, 0), (0, 0)))
        iters = jnp.pad(iters, (0, pad))
    bp = B + pad
    s = bp // LANE  # sublane count; TILE-padding keeps it a multiple of 8

    # keep the per-call VMEM slab (chunk * 5 * S_t * LANE u32 words + the
    # uint8 mask) within a few MiB as the row count grows, and never pad
    # the iteration axis past the actual trip count.  Two levers, applied
    # in order: shrink the iteration chunk, then (for very large row
    # counts, where even a chunk=1 slab of 5*s*LANE words overflows —
    # B beyond ~420k rows) tile the row/sublane axis too, mapping
    # independent row tiles through the same gridless kernel.
    BUDGET = vmem_budget
    chunk = max(1, min(chunk, max_iters))
    while chunk > 1 and chunk * 5 * s * LANE * 4 > BUDGET:
        chunk //= 2
    s_t = s
    while s_t > 8 and chunk * 5 * s_t * LANE * 4 > BUDGET:
        s_t = ((s_t + 1) // 2 + 7) // 8 * 8  # halve, sublane-aligned
    rt = -(-s // s_t)  # row tiles
    if rt > 1 and rt * s_t > s:  # pad rows up to a whole tile grid
        extra = (rt * s_t - s) * LANE
        h0 = jnp.pad(h0, (0, extra))
        g0 = jnp.pad(g0, (0, extra))
        f0 = jnp.pad(f0, (0, extra))
        blocks = jnp.pad(blocks, ((0, extra), (0, 0), (0, 0)))
        iters = jnp.pad(iters, (0, extra))
        s = rt * s_t
    ipad = (-max_iters) % chunk
    if ipad:
        blocks = jnp.pad(blocks, ((0, 0), (0, ipad), (0, 0)))
    n_iter = max_iters + ipad
    steps = n_iter // chunk

    # [B, I, 5] -> [steps, chunk, 5, S, LANE]
    slabs = (
        blocks.reshape(s, LANE, n_iter, 5)
        .transpose(2, 3, 0, 1)
        .reshape(steps, chunk, 5, s, LANE)
    )
    # active mask per iteration: i < iters  (uint8: TPU Pallas vector
    # loads want a byte-addressable dtype, not i1)
    it2d = iters.astype(jnp.int32).reshape(s, LANE)
    idx = jnp.arange(n_iter, dtype=jnp.int32)
    acts = (
        (idx[:, None, None] < it2d[None])
        .astype(jnp.uint8)
        .reshape(steps, chunk, s, LANE)
    )

    call = pl.pallas_call(
        _nogrid_kernel,
        out_shape=[
            jax.ShapeDtypeStruct((s_t, LANE), jnp.uint32) for _ in range(3)
        ],
        interpret=interpret,
    )

    def rows(x):
        return x.reshape(s, LANE)

    def step(carry, x):
        slab, act = x
        h, g, f = carry
        h, g, f = call(slab, act, h, g, f)
        return (h, g, f), None

    if rt == 1:
        (h, g, f), _ = jax.lax.scan(
            step, (rows(h0), rows(g0), rows(f0)), (slabs, acts)
        )
    else:
        # row-tiled: scan over [rt] tiles (initial rows ride as xs, final
        # rows come back as ys — tiles are independent), inner scan over
        # iteration steps, each step one gridless pallas_call on an
        # [chunk, 5, s_t, LANE] slab that fits the budget
        slabs_rt = slabs.reshape(steps, chunk, 5, rt, s_t, LANE).transpose(
            3, 0, 1, 2, 4, 5
        )
        acts_rt = acts.reshape(steps, chunk, rt, s_t, LANE).transpose(
            2, 0, 1, 3, 4
        )

        def tiles(x):
            return x.reshape(rt, s_t, LANE)

        def outer(_, tile):
            slab_t, act_t, ht, gt, ft = tile
            out, __ = jax.lax.scan(step, (ht, gt, ft), (slab_t, act_t))
            return None, out

        _, (h, g, f) = jax.lax.scan(
            outer,
            None,
            (slabs_rt, acts_rt, tiles(h0), tiles(g0), tiles(f0)),
        )
    h, g, f = (x.reshape(s * LANE)[:B] for x in (h, g, f))
    return h, g, f
