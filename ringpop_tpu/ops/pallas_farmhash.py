"""Pallas TPU kernel for the FarmHash32 20-byte block loop.

The long-string path of farmhashmk chains (h, g, f) through one mixing
round per 20-byte block — sequential per row, embarrassingly parallel
across rows.  This kernel streams the pre-packed word blocks from HBM
through VMEM one iteration tile at a time (grid = row-tiles x iterations,
Pallas double-buffers the fetches), keeps the three carries in VMEM
scratch across the iteration axis, and writes each row-tile's result once
on the last iteration.

Layout: rows are tiled (8 sublanes x 128 lanes) = 1024 rows per grid row;
each iteration step loads a [8, 128, 5] uint32 block (20 KB), so VMEM
holds carries + two in-flight blocks regardless of string length.

Used by :func:`ringpop_tpu.ops.jax_farmhash.hash32_rows` when
``RINGPOP_TPU_PALLAS=1`` (interpret mode off-TPU keeps tests hermetic);
the default remains the `lax.scan` lowering, which the dirty-row checksum
cache already keeps off the critical path on quiet ticks.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

# one source of truth for the farmhashmk constants and mixing primitives
# (no import cycle: jax_farmhash imports this module lazily)
from ringpop_tpu.ops.jax_farmhash import C1, _mur

SUB, LANE = 8, 128
TILE = SUB * LANE  # rows per grid step


def _kernel(iters_ref, blocks_ref, h0_ref, g0_ref, f0_ref,
            oh_ref, og_ref, of_ref, h_s, g_s, f_s, *, max_iters: int):
    from jax.experimental import pallas as pl

    i = pl.program_id(1)

    @pl.when(i == 0)
    def _():
        h_s[:] = h0_ref[0]
        g_s[:] = g0_ref[0]
        f_s[:] = f0_ref[0]

    blk = blocks_ref[0, 0]  # [SUB, LANE, 5] uint32
    a = blk[:, :, 0]
    b = blk[:, :, 1]
    c = blk[:, :, 2]
    d = blk[:, :, 3]
    e = blk[:, :, 4]

    h = h_s[:]
    g = g_s[:]
    f = f_s[:]
    nh = h + a
    ng = g + b
    nf = f + c
    nh = _mur(d, nh) + e
    ng = _mur(c, ng) + a
    nf = _mur(b + e * C1, nf) + d
    nf = nf + ng
    ng = ng + nf

    active = i < iters_ref[0]
    h_s[:] = jnp.where(active, nh, h)
    g_s[:] = jnp.where(active, ng, g)
    f_s[:] = jnp.where(active, nf, f)

    @pl.when(i == max_iters - 1)
    def _():
        oh_ref[0] = h_s[:]
        og_ref[0] = g_s[:]
        of_ref[0] = f_s[:]


def block_loop(h0, g0, f0, blocks, iters, *, interpret: bool = False):
    """Run the farmhashmk block loop on TPU via Pallas.

    ``h0/g0/f0``: [B] uint32 carries after tail mixing; ``blocks``:
    [B, max_iters, 5] uint32 aligned words; ``iters``: [B] per-row trip
    counts.  Returns (h, g, f) [B] uint32.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, max_iters, five = blocks.shape
    assert five == 5
    pad = (-B) % TILE
    if pad:
        h0 = jnp.pad(h0, (0, pad))
        g0 = jnp.pad(g0, (0, pad))
        f0 = jnp.pad(f0, (0, pad))
        blocks = jnp.pad(blocks, ((0, pad), (0, 0), (0, 0)))
        iters = jnp.pad(iters, (0, pad))
    bp = B + pad
    gsz = bp // TILE

    def rows(x):
        return x.reshape(gsz, SUB, LANE)

    blocks_t = (
        blocks.reshape(gsz, SUB, LANE, max_iters, 5)
        .transpose(0, 3, 1, 2, 4)  # [G, I, SUB, LANE, 5]
    )

    row_spec = pl.BlockSpec(
        (1, SUB, LANE), lambda g, i: (g, 0, 0), memory_space=pltpu.VMEM
    )
    out = pl.pallas_call(
        functools.partial(_kernel, max_iters=max_iters),
        grid=(gsz, max_iters),
        in_specs=[
            row_spec,  # iters
            pl.BlockSpec(
                (1, 1, SUB, LANE, 5),
                lambda g, i: (g, i, 0, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            row_spec,  # h0
            row_spec,  # g0
            row_spec,  # f0
        ],
        out_specs=[row_spec, row_spec, row_spec],
        out_shape=[
            jax.ShapeDtypeStruct((gsz, SUB, LANE), jnp.uint32)
            for _ in range(3)
        ],
        scratch_shapes=[
            pltpu.VMEM((SUB, LANE), jnp.uint32) for _ in range(3)
        ],
        interpret=interpret,
    )(
        rows(iters.astype(jnp.int32)),
        blocks_t,
        rows(h0),
        rows(g0),
        rows(f0),
    )
    h, g, f = (x.reshape(bp)[:B] for x in out)
    return h, g, f


# ---------------------------------------------------------------------------
# Fused encode+hash streaming: assemble checksum rows from per-member record
# words IN VMEM and block-walk them in the same kernel, so the [B, row_bytes]
# string buffer never exists in HBM (the ~100 MB/s XLA byte-assembly floor —
# VERDICT.md round 5 "Next round" item 1).
#
# The stream state per row is tiny: the three mixing carries, a <RES_W-word
# residual of not-yet-consumed bytes, the residual byte count, and the count
# of 20-byte blocks already mixed.  Appending a member's record is a per-lane
# variable byte shift (word shift Wq in [0, 4] + bit shift, both vectorized);
# consuming a block is a 5-word shift-down (20 bytes are word-aligned, so no
# bit shifting).  Invariant: residual bytes at or beyond ``res_len`` are zero
# (records are zero-padded past their length), so append is a plain OR and
# consume needs no re-zeroing.
# ---------------------------------------------------------------------------


def stream_geometry(rec_words: int):
    """(RES_W, ROUNDS) for a record capacity of ``rec_words`` uint32 words:
    residual capacity covers 19 carried bytes + one full record; ROUNDS is
    the most 20-byte blocks one append can complete."""
    cap = 19 + 4 * rec_words
    return (cap + 3) // 4, cap // 20


def stream_member_step(carry, rec, rec_len):
    """Append ONE member record to each row's residual and consume every
    completed 20-byte block (bit-exact farmhashmk mixing order).

    ``carry``: (h, g, f, res tuple[RES_W], res_len, done, total_blocks) —
    all arrays of one broadcast row shape; ``rec``: tuple[RW] of uint32
    record words (zero-padded past ``rec_len``); ``rec_len``: int32 record
    byte length (0 for an absent member).  Shape-agnostic: the same
    function body runs inside the gridless Pallas kernel on [S, LANE]
    tiles and inside the pure-XLA ``lax.scan`` fallback on [B] vectors.
    """
    h, g, f, res, res_len, done, total_blocks = carry
    res_w = len(res)
    rw = len(rec)
    rounds = stream_geometry(rw)[1]

    # -- append: shift the record up by res_len bytes and OR it in --------
    appending = done < total_blocks
    q = res_len
    wq = q >> 2  # word shift, in [0, 4] (res_len <= 19 while appending)
    bq = ((q & 3) << 3).astype(jnp.uint32)  # bit shift within the word

    def rec_ext(k):
        if 0 <= k < rw:
            return rec[k]
        return jnp.zeros_like(rec[0])

    new_res = []
    zero32 = jnp.uint32(0)
    for w in range(res_w):
        cand = jnp.zeros_like(rec[0])
        prev = jnp.zeros_like(rec[0])
        for k in range(min(w, 4) + 1):
            sel = wq == k
            cand = jnp.where(sel, rec_ext(w - k), cand)
            prev = jnp.where(sel, rec_ext(w - k - 1), prev)
        # (32 - bq) & 31 keeps the shift amount defined at bq == 0; the
        # where() discards that lane's value anyway
        spill = prev >> ((jnp.uint32(32) - bq) & jnp.uint32(31))
        shifted = jnp.where(bq == 0, cand, (cand << bq) | spill)
        new_res.append(res[w] | jnp.where(appending, shifted, zero32))
    res = new_res
    res_len = res_len + jnp.where(appending, rec_len, 0)

    # -- consume completed blocks (at most ``rounds`` per append) ---------
    for _ in range(rounds):
        can = (res_len >= 20) & (done < total_blocks)
        a, b, c, d, e = res[0], res[1], res[2], res[3], res[4]
        nh = h + a
        ng = g + b
        nf = f + c
        nh = _mur(d, nh) + e
        ng = _mur(c, ng) + a
        nf = _mur(b + e * C1, nf) + d
        nf = nf + ng
        ng = ng + nf
        h = jnp.where(can, nh, h)
        g = jnp.where(can, ng, g)
        f = jnp.where(can, nf, f)
        res = [
            jnp.where(
                can,
                res[w + 5] if w + 5 < res_w else zero32,
                res[w],
            )
            for w in range(res_w)
        ]
        res_len = res_len - jnp.where(can, 20, 0)
        done = done + jnp.where(can, 1, 0)
    return (h, g, f, tuple(res), res_len, done, total_blocks)


def _fused_stream_kernel(slab_ref, len_ref, tb_ref, h0_ref, g0_ref, f0_ref,
                         res0_ref, rl0_ref, dn0_ref,
                         oh_ref, og_ref, of_ref, ores_ref, orl_ref, odn_ref):
    """One gridless call streams ``cm`` member records through the row
    tile's residual state: slab [CM, RW, S, LANE] uint32, len [CM, S, LANE]
    int32, carries in/out as plain operands (the only Pallas shape the
    axon tunnel's compile helper accepts — PALLAS_BISECT.json)."""
    cm = slab_ref.shape[0]
    rw = slab_ref.shape[1]
    res_w = res0_ref.shape[0]

    def body(k, carry):
        h, g, f, res, rl, dn, tb = carry
        rec = tuple(slab_ref[k, w] for w in range(rw))
        return stream_member_step(
            (h, g, f, res, rl, dn, tb), rec, len_ref[k]
        )

    out = jax.lax.fori_loop(
        0,
        cm,
        body,
        (
            h0_ref[:],
            g0_ref[:],
            f0_ref[:],
            tuple(res0_ref[w] for w in range(res_w)),
            rl0_ref[:],
            dn0_ref[:],
            tb_ref[:],
        ),
    )
    h, g, f, res, rl, dn, _ = out
    oh_ref[:] = h
    og_ref[:] = g
    of_ref[:] = f
    for w in range(res_w):
        ores_ref[w] = res[w]
    orl_ref[:] = rl
    odn_ref[:] = dn


def fused_stream_nogrid(
    h0,
    g0,
    f0,
    rec_words,  # [B, N, RW] uint32 — per-member record words, zero-padded
    rec_len,  # [B, N] int32 — record byte lengths (0 = absent)
    total_blocks,  # [B] int32 — (len-1)//20 for long rows, 0 otherwise
    *,
    chunk: int = 64,
    interpret: bool = False,
    vmem_budget: int = 8 * 1024 * 1024,
):
    """Fused encode+hash block walk: returns the (h, g, f) carries after
    streaming every member record through the farmhashmk 20-byte mixing
    loop, rows vectorized [S, LANE]-wide, the assembled string living
    only in the VMEM residual.  Gridless (tunnel-compilable): the member
    axis rides an outer ``lax.scan`` of ``chunk``-member slabs; large row
    counts tile the sublane axis through the same kernel."""
    from jax.experimental import pallas as pl

    B, N, RW = rec_words.shape
    res_w, _ = stream_geometry(RW)
    pad = (-B) % TILE
    if pad:
        h0 = jnp.pad(h0, (0, pad))
        g0 = jnp.pad(g0, (0, pad))
        f0 = jnp.pad(f0, (0, pad))
        rec_words = jnp.pad(rec_words, ((0, pad), (0, 0), (0, 0)))
        rec_len = jnp.pad(rec_len, ((0, pad), (0, 0)))
        total_blocks = jnp.pad(total_blocks, (0, pad))
    bp = B + pad
    s = bp // LANE

    # VMEM levers, in order: shrink the member chunk, then tile rows
    chunk = max(1, min(chunk, N))
    while chunk > 1 and chunk * (RW + 1) * s * LANE * 4 > vmem_budget:
        chunk //= 2
    s_t = s
    while s_t > 8 and chunk * (RW + 1) * s_t * LANE * 4 > vmem_budget:
        s_t = ((s_t + 1) // 2 + 7) // 8 * 8
    rt = -(-s // s_t)
    if rt > 1 and rt * s_t > s:
        extra = (rt * s_t - s) * LANE
        h0 = jnp.pad(h0, (0, extra))
        g0 = jnp.pad(g0, (0, extra))
        f0 = jnp.pad(f0, (0, extra))
        rec_words = jnp.pad(rec_words, ((0, extra), (0, 0), (0, 0)))
        rec_len = jnp.pad(rec_len, ((0, extra), (0, 0)))
        total_blocks = jnp.pad(total_blocks, (0, extra))
        s = rt * s_t
    mpad = (-N) % chunk
    if mpad:
        # zero-length pad members append nothing
        rec_words = jnp.pad(rec_words, ((0, 0), (0, mpad), (0, 0)))
        rec_len = jnp.pad(rec_len, ((0, 0), (0, mpad)))
    nm = N + mpad
    steps = nm // chunk

    # [B, N, RW] -> [rt, steps, CM, RW, s_t, LANE]
    slabs = (
        rec_words.reshape(rt, s_t, LANE, steps, chunk, RW)
        .transpose(0, 3, 4, 5, 1, 2)
    )
    lens = (
        rec_len.reshape(rt, s_t, LANE, steps, chunk)
        .transpose(0, 3, 4, 1, 2)
    )

    call = pl.pallas_call(
        _fused_stream_kernel,
        out_shape=[
            jax.ShapeDtypeStruct((s_t, LANE), jnp.uint32),  # h
            jax.ShapeDtypeStruct((s_t, LANE), jnp.uint32),  # g
            jax.ShapeDtypeStruct((s_t, LANE), jnp.uint32),  # f
            jax.ShapeDtypeStruct((res_w, s_t, LANE), jnp.uint32),
            jax.ShapeDtypeStruct((s_t, LANE), jnp.int32),  # res_len
            jax.ShapeDtypeStruct((s_t, LANE), jnp.int32),  # done
        ],
        interpret=interpret,
    )

    def tiles(x):
        return x.reshape(rt, s_t, LANE)

    def inner(carry, x):
        slab, ln = x
        h, g, f, res, rl, dn, tb = carry
        h, g, f, res, rl, dn = call(slab, ln, tb, h, g, f, res, rl, dn)
        return (h, g, f, res, rl, dn, tb), None

    def outer(_, tile):
        slab_t, len_t, ht, gt, ft, tbt = tile
        izero = jnp.zeros((s_t, LANE), jnp.int32)
        res0 = jnp.zeros((res_w, s_t, LANE), jnp.uint32)
        (h, g, f, _, _, _, _), __ = jax.lax.scan(
            inner, (ht, gt, ft, res0, izero, izero, tbt), (slab_t, len_t)
        )
        return None, (h, g, f)

    _, (h, g, f) = jax.lax.scan(
        outer,
        None,
        (
            slabs,
            lens,
            tiles(h0),
            tiles(g0),
            tiles(f0),
            tiles(total_blocks.astype(jnp.int32)),
        ),
    )
    h, g, f = (x.reshape(s * LANE)[:B] for x in (h, g, f))
    return h, g, f


def fused_stream_xla(h0, g0, f0, rec_words, rec_len, total_blocks):
    """Pure-XLA twin of :func:`fused_stream_nogrid`: the same
    ``stream_member_step`` scanned over the member axis with [B]-vector
    rows — the CPU fallback and the off-chip reference the interpret
    tests pin the kernel against.  Bit-exact by construction (shared
    step function)."""
    B, N, RW = rec_words.shape
    res_w, _ = stream_geometry(RW)
    tb = total_blocks.astype(jnp.int32)
    res0 = tuple(jnp.zeros(B, jnp.uint32) for _ in range(res_w))
    izero = jnp.zeros(B, jnp.int32)

    def body(carry, x):
        rec_m, len_m = x
        return (
            stream_member_step(
                carry, tuple(rec_m[:, w] for w in range(RW)), len_m
            ),
            None,
        )

    (h, g, f, _, _, _, _), __ = jax.lax.scan(
        body,
        (h0, g0, f0, res0, izero, izero, tb),
        (rec_words.transpose(1, 0, 2), rec_len.T),
    )
    return h, g, f


def _nogrid_kernel(blk_ref, act_ref, h0_ref, g0_ref, f0_ref,
                   oh_ref, og_ref, of_ref):
    """One gridless call = ``chunk`` mixing rounds over a [S, LANE] row
    tile, carries entering/leaving as plain operands."""

    def body(k, carry):
        h, g, f = carry
        a = blk_ref[k, 0]
        b = blk_ref[k, 1]
        c = blk_ref[k, 2]
        d = blk_ref[k, 3]
        e = blk_ref[k, 4]
        nh = h + a
        ng = g + b
        nf = f + c
        nh = _mur(d, nh) + e
        ng = _mur(c, ng) + a
        nf = _mur(b + e * C1, nf) + d
        nf = nf + ng
        ng = ng + nf
        act = act_ref[k] != 0
        return (
            jnp.where(act, nh, h),
            jnp.where(act, ng, g),
            jnp.where(act, nf, f),
        )

    h, g, f = jax.lax.fori_loop(
        0, blk_ref.shape[0], body, (h0_ref[:], g0_ref[:], f0_ref[:])
    )
    oh_ref[:] = h
    og_ref[:] = g
    of_ref[:] = f


def block_loop_nogrid(
    h0,
    g0,
    f0,
    blocks,
    iters,
    *,
    chunk: int = 64,
    interpret: bool = False,
    vmem_budget: int = 8 * 1024 * 1024,
):
    """Gridless variant of :func:`block_loop` for the axon tunnel, whose
    remote-compile helper deterministically 500s on ANY grid'd Pallas
    kernel while compiling gridless ones fine (PALLAS_BISECT.json: `copy`/
    `nogrid_*` ok, every `grid*` rung and the grid'd farmhash fail).

    The iteration axis moves out of the Pallas grid into an outer XLA
    ``lax.scan``; each scan step is ONE gridless pallas_call running
    ``chunk`` mixing rounds via an in-kernel ``fori_loop`` with the whole
    [chunk, 5, S, LANE] block slab resident in VMEM.  Same signature and
    bit-exact results as :func:`block_loop`.
    """
    from jax.experimental import pallas as pl

    B, max_iters, five = blocks.shape
    assert five == 5
    pad = (-B) % TILE
    if pad:
        h0 = jnp.pad(h0, (0, pad))
        g0 = jnp.pad(g0, (0, pad))
        f0 = jnp.pad(f0, (0, pad))
        blocks = jnp.pad(blocks, ((0, pad), (0, 0), (0, 0)))
        iters = jnp.pad(iters, (0, pad))
    bp = B + pad
    s = bp // LANE  # sublane count; TILE-padding keeps it a multiple of 8

    # keep the per-call VMEM slab (chunk * 5 * S_t * LANE u32 words + the
    # uint8 mask) within a few MiB as the row count grows, and never pad
    # the iteration axis past the actual trip count.  Two levers, applied
    # in order: shrink the iteration chunk, then (for very large row
    # counts, where even a chunk=1 slab of 5*s*LANE words overflows —
    # B beyond ~420k rows) tile the row/sublane axis too, mapping
    # independent row tiles through the same gridless kernel.
    BUDGET = vmem_budget
    chunk = max(1, min(chunk, max_iters))
    while chunk > 1 and chunk * 5 * s * LANE * 4 > BUDGET:
        chunk //= 2
    s_t = s
    while s_t > 8 and chunk * 5 * s_t * LANE * 4 > BUDGET:
        s_t = ((s_t + 1) // 2 + 7) // 8 * 8  # halve, sublane-aligned
    rt = -(-s // s_t)  # row tiles
    if rt > 1 and rt * s_t > s:  # pad rows up to a whole tile grid
        extra = (rt * s_t - s) * LANE
        h0 = jnp.pad(h0, (0, extra))
        g0 = jnp.pad(g0, (0, extra))
        f0 = jnp.pad(f0, (0, extra))
        blocks = jnp.pad(blocks, ((0, extra), (0, 0), (0, 0)))
        iters = jnp.pad(iters, (0, extra))
        s = rt * s_t
    ipad = (-max_iters) % chunk
    if ipad:
        blocks = jnp.pad(blocks, ((0, 0), (0, ipad), (0, 0)))
    n_iter = max_iters + ipad
    steps = n_iter // chunk

    # [B, I, 5] -> [steps, chunk, 5, S, LANE]
    slabs = (
        blocks.reshape(s, LANE, n_iter, 5)
        .transpose(2, 3, 0, 1)
        .reshape(steps, chunk, 5, s, LANE)
    )
    # active mask per iteration: i < iters  (uint8: TPU Pallas vector
    # loads want a byte-addressable dtype, not i1)
    it2d = iters.astype(jnp.int32).reshape(s, LANE)
    idx = jnp.arange(n_iter, dtype=jnp.int32)
    acts = (
        (idx[:, None, None] < it2d[None])
        .astype(jnp.uint8)
        .reshape(steps, chunk, s, LANE)
    )

    call = pl.pallas_call(
        _nogrid_kernel,
        out_shape=[
            jax.ShapeDtypeStruct((s_t, LANE), jnp.uint32) for _ in range(3)
        ],
        interpret=interpret,
    )

    def rows(x):
        return x.reshape(s, LANE)

    def step(carry, x):
        slab, act = x
        h, g, f = carry
        h, g, f = call(slab, act, h, g, f)
        return (h, g, f), None

    if rt == 1:
        (h, g, f), _ = jax.lax.scan(
            step, (rows(h0), rows(g0), rows(f0)), (slabs, acts)
        )
    else:
        # row-tiled: scan over [rt] tiles (initial rows ride as xs, final
        # rows come back as ys — tiles are independent), inner scan over
        # iteration steps, each step one gridless pallas_call on an
        # [chunk, 5, s_t, LANE] slab that fits the budget
        slabs_rt = slabs.reshape(steps, chunk, 5, rt, s_t, LANE).transpose(
            3, 0, 1, 2, 4, 5
        )
        acts_rt = acts.reshape(steps, chunk, rt, s_t, LANE).transpose(
            2, 0, 1, 3, 4
        )

        def tiles(x):
            return x.reshape(rt, s_t, LANE)

        def outer(_, tile):
            slab_t, act_t, ht, gt, ft = tile
            out, __ = jax.lax.scan(step, (ht, gt, ft), (slab_t, act_t))
            return None, out

        _, (h, g, f) = jax.lax.scan(
            outer,
            None,
            (slabs_rt, acts_rt, tiles(h0), tiles(g0), tiles(f0)),
        )
    h, g, f = (x.reshape(s * LANE)[:B] for x in (h, g, f))
    return h, g, f
