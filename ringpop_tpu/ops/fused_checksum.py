"""Fused membership-checksum pipeline: per-member record encode + in-VMEM
string assembly + FarmHash32 block walk, with no [B, row_bytes] buffer.

The classic parity path (:mod:`checksum_encode` + :mod:`jax_farmhash`)
materializes every observer's checksum string through HBM and assembles it
with byte-granular scatter/gather — the ~100 MB/s floor that capped TPU
parity throughput (VERDICT.md round 5).  This module splits the work the
way the bytes actually flow:

1. **Record encode** (:func:`member_records` / :func:`member_records_at`):
   each member's ``addr + status + incarnation + ';'`` record is built
   independently at RECORD granularity — position within a record is a
   short static axis, so every byte is an elementwise select + a gather
   into a tiny table.  No cross-member scatter exists; the serialized XLA
   scatter of the row form is gone.  Records are cacheable: a member's
   record only changes when its ``(known, status, incarnation)`` cell
   changes, so a churn wave re-encodes O(wave) records, not O(N*N) bytes
   (the engine keeps a per-(observer, subject) byte cache — see
   ``SimParams.fused_checksum``).

2. **Fused assemble+hash** (:func:`fused_hash_rows`): the gridless Pallas
   streaming kernel (:func:`ringpop_tpu.ops.pallas_farmhash.
   fused_stream_nogrid`) concatenates record words into each row's
   20-byte block stream inside VMEM and runs the farmhashmk mixing round
   in the same kernel.  The checksum string as a whole never exists in
   memory; only the <24-byte head/tail windows (for the short-length
   buckets and the tail mix) are gathered, and those come straight from
   the record words.

Bit-exactness contract: identical ``uint32`` output to
``jax_farmhash.hash32_rows(*checksum_encode.membership_rows(...))`` for
every input — pinned by tests/ops/test_fused_checksum.py across status,
incarnation-digit and membership edge cases, and by the lockstep parity
suite end-to-end.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ringpop_tpu.ops import checksum_encode as ce
from ringpop_tpu.ops import jax_farmhash as jfh

MAX_DIGITS = ce.MAX_DIGITS


def record_width(universe: ce.Universe, max_digits: int = MAX_DIGITS) -> int:
    """Static byte capacity of one member record:
    ``addr + status + digits + ';'`` (the separator is carried by every
    record; the stream consumer never reads past ``len-1``, so the final
    record's trailing ';' is naturally dropped)."""
    return universe.addr_width + ce._STATUS_W + max_digits + 1


def record_word_width(
    universe: ce.Universe, max_digits: int = MAX_DIGITS
) -> int:
    return (record_width(universe, max_digits) + 3) // 4


def _records_core(
    addr_pad: jax.Array,  # [..., R] uint8 — member address bytes, padded
    addr_len: jax.Array,  # [...] int32
    status: jax.Array,  # [...] int codes
    inc_ms: jax.Array,  # [...] int64 epoch-ms incarnations
    present: jax.Array,  # [...] bool
    max_digits: int,
    width: int,
):
    """Elementwise record build over any cell shape; returns
    (bytes [..., width] uint8 zero-padded past len, len [...] int32)."""
    status = status.astype(jnp.int32)
    al = addr_len.astype(jnp.int32)
    sl = jnp.asarray(ce.STATUS_LEN)[status]
    dl = ce._ndigits(inc_ms)
    rec_len = (al + sl + dl + 1) * present.astype(jnp.int32)

    p = jnp.arange(width, dtype=jnp.int32)
    shape = al.shape + (width,)
    pb = jnp.broadcast_to(p, shape)
    alb = al[..., None]
    slb = sl[..., None]
    dlb = dl[..., None]

    sbytes = jnp.asarray(ce.STATUS_BYTES)[status]  # [..., 7]
    digits = ce._digit_bytes(inc_ms, dl, max_digits)  # [..., D]

    s_off = pb - alb
    d_off = s_off - slb
    byte_status = jnp.take_along_axis(
        sbytes, jnp.clip(s_off, 0, ce._STATUS_W - 1), axis=-1
    )
    byte_digit = jnp.take_along_axis(
        digits, jnp.clip(d_off, 0, max_digits - 1), axis=-1
    )
    out = jnp.where(
        pb < alb,
        addr_pad,
        jnp.where(
            s_off < slb,
            byte_status,
            jnp.where(d_off < dlb, byte_digit, jnp.uint8(ord(";"))),
        ),
    )
    out = jnp.where(pb < rec_len[..., None], out, jnp.uint8(0))
    return out.astype(jnp.uint8), rec_len


def member_records(
    universe: ce.Universe,
    present: jax.Array,  # [..., N] bool
    status: jax.Array,  # [..., N] int codes
    inc_ms: jax.Array,  # [..., N] int64
    max_digits: int = MAX_DIGITS,
):
    """Dense per-member records for full rows (member = last-axis index).

    Returns ``(rec_bytes [..., N, R] uint8, rec_len [..., N] int32)``;
    absent members have length 0 and all-zero bytes."""
    width = record_width(universe, max_digits)
    addr_pad = np.zeros((universe.n, width), np.uint8)
    addr_pad[:, : universe.addr_width] = universe.addr_bytes
    lead = present.shape[:-1]
    ap = jnp.broadcast_to(jnp.asarray(addr_pad), lead + addr_pad.shape)
    al = jnp.broadcast_to(
        jnp.asarray(universe.addr_len), lead + (universe.n,)
    )
    return _records_core(
        ap, al, status, inc_ms, present, max_digits, width
    )


def member_records_at(
    universe: ce.Universe,
    subject: jax.Array,  # [...] int32 member (universe) indices
    status: jax.Array,
    inc_ms: jax.Array,
    present: jax.Array,
    max_digits: int = MAX_DIGITS,
):
    """Sparse form: records for an arbitrary set of (subject, status,
    incarnation) cells — the incremental cache-update path (a churn tick
    re-encodes only the cells whose view changed)."""
    width = record_width(universe, max_digits)
    addr_pad = np.zeros((universe.n, width), np.uint8)
    addr_pad[:, : universe.addr_width] = universe.addr_bytes
    subj = jnp.clip(subject.astype(jnp.int32), 0, universe.n - 1)
    ap = jnp.asarray(addr_pad)[subj]
    al = jnp.asarray(universe.addr_len)[subj]
    return _records_core(
        ap, al, status, inc_ms, present, max_digits, width
    )


def pack_record_words(rec_bytes: jax.Array) -> jax.Array:
    """[..., R] uint8 -> [..., ceil(R/4)] uint32 little-endian words (the
    stream kernel's input form)."""
    r = rec_bytes.shape[-1]
    pad = (-r) % 4
    if pad:
        rec_bytes = jnp.pad(
            rec_bytes, [(0, 0)] * (rec_bytes.ndim - 1) + [(0, pad)]
        )
    w = rec_bytes.reshape(rec_bytes.shape[:-1] + (-1, 4)).astype(jnp.uint32)
    return (
        w[..., 0]
        | (w[..., 1] << 8)
        | (w[..., 2] << 16)
        | (w[..., 3] << 24)
    )


def _row_bytes_at(
    rec_words: jax.Array,  # [B, N, RW] uint32
    seg_len: jax.Array,  # [B, N] int32
    ends: jax.Array,  # [B, N] int32 inclusive cumsum of seg_len
    pos: jax.Array,  # [B, P] int32 stream byte positions
    total: jax.Array,  # [B] int32 string length (sans trailing ';')
) -> jax.Array:
    """Gather individual assembled-string bytes without assembling the
    string: position -> owning member (binary search over the segment-end
    cumsum) -> byte within that member's record words.  Used only for the
    <=28-byte head/tail windows, so the per-byte search cost is capped."""
    n = rec_words.shape[1]
    m = jax.vmap(
        lambda e, p: jnp.searchsorted(e, p, side="right")
    )(ends, pos).astype(jnp.int32)
    mc = jnp.clip(m, 0, n - 1)
    off = jnp.take_along_axis(ends, mc, axis=1) - jnp.take_along_axis(
        seg_len, mc, axis=1
    )
    local = pos - off
    wi = jnp.clip(local, 0, 4 * rec_words.shape[2] - 1) >> 2
    sh = ((local & 3) << 3).astype(jnp.uint32)
    word = jax.vmap(lambda rw, mm, ww: rw[mm, ww])(rec_words, mc, wi)
    byte = (word >> sh) & jnp.uint32(0xFF)
    valid = (pos >= 0) & (pos < total[:, None])
    return jnp.where(valid, byte, 0).astype(jnp.uint8)


def _le32(win: jax.Array, i: int) -> jax.Array:
    """Little-endian uint32 at static byte offset ``i`` of a [B, W] window."""
    b = win[:, i : i + 4].astype(jnp.uint32)
    return b[:, 0] | (b[:, 1] << 8) | (b[:, 2] << 16) | (b[:, 3] << 24)


def _stream_impl_from_env() -> str:
    """"pallas" on a real TPU (the gridless streaming kernel), "xla"
    elsewhere (interpret-mode Pallas is orders slower than the scanned
    twin on CPU)."""
    import jax as _jax

    return "pallas" if _jax.default_backend() == "tpu" else "xla"


def fused_hash_rows(
    rec_words: jax.Array,  # [B, N, RW] uint32
    rec_len: jax.Array,  # [B, N] int32 (0 = absent member)
    impl: Optional[str] = None,  # "pallas" | "xla" | None = by backend
    chunk: int = 64,
) -> jax.Array:
    """FarmHash32 of each row's membership checksum string, computed from
    per-member record words without materializing the string.

    Returns ``[B] uint32`` — bit-identical to
    ``hash32_rows(*membership_rows(...))`` on the same views."""
    if impl is None:
        impl = _stream_impl_from_env()
    seg = rec_len.astype(jnp.int32)
    ends = jnp.cumsum(seg, axis=1, dtype=jnp.int32)
    total = jnp.maximum(ends[:, -1] - 1, 0)  # no trailing ';'
    B = rec_words.shape[0]

    # head window: the complete string for every short-bucket row (<= 24
    # bytes + 4 bytes fetch slack)
    head_pos = jnp.broadcast_to(
        jnp.arange(28, dtype=jnp.int32), (B, 28)
    )
    head = _row_bytes_at(rec_words, seg, ends, head_pos, total)
    # tail window: the last 24 bytes, feeding the long-path tail mixes
    tail_pos = total[:, None] - 24 + jnp.arange(24, dtype=jnp.int32)
    tail = _row_bytes_at(rec_words, seg, ends, tail_pos, total)

    # ---- long path (total > 24): init carries + tail mixes ------------
    n32 = total.astype(jnp.uint32)
    h0 = n32
    g0 = jfh.C1 * n32
    f0 = g0

    def tw(off_from_end: int) -> jax.Array:
        v = _le32(tail, 24 - off_from_end)
        return jfh._rot(v * jfh.C1, 17) * jfh.C2

    a0, a1, a2, a3, a4 = tw(4), tw(8), tw(16), tw(12), tw(20)
    h0 = h0 ^ a0
    h0 = jfh._rot(h0, 19) * jfh.FIVE + jfh.MAGIC
    h0 = h0 ^ a2
    h0 = jfh._rot(h0, 19) * jfh.FIVE + jfh.MAGIC
    g0 = g0 ^ a1
    g0 = jfh._rot(g0, 19) * jfh.FIVE + jfh.MAGIC
    g0 = g0 ^ a3
    g0 = jfh._rot(g0, 19) * jfh.FIVE + jfh.MAGIC
    f0 = f0 + a4
    f0 = jfh._rot(f0, 19) + jnp.uint32(113)

    total_blocks = jnp.where(total > 24, (total - 1) // 20, 0)

    from ringpop_tpu.ops import pallas_farmhash as pfh

    if impl == "pallas":
        h, g, f = pfh.fused_stream_nogrid(
            h0,
            g0,
            f0,
            rec_words,
            rec_len,
            total_blocks,
            chunk=chunk,
            interpret=jax.devices()[0].platform != "tpu",
        )
    else:
        h, g, f = pfh.fused_stream_xla(
            h0, g0, f0, rec_words, rec_len, total_blocks
        )

    g = jfh._rot(g, 11) * jfh.C1
    g = jfh._rot(g, 17) * jfh.C1
    f = jfh._rot(f, 11) * jfh.C1
    f = jfh._rot(f, 17) * jfh.C1
    h = jfh._rot(h + g, 19)
    h = h * jfh.FIVE + jfh.MAGIC
    h = jfh._rot(h, 17) * jfh.C1
    h = jfh._rot(h + f, 19)
    h = h * jfh.FIVE + jfh.MAGIC
    long_out = jfh._rot(h, 17) * jfh.C1

    out = jfh._hash_0_4(head, total)
    out = jnp.where(total > 4, jfh._hash_5_12(head, total), out)
    out = jnp.where(total > 12, jfh._hash_13_24(head, total), out)
    return jnp.where(total > 24, long_out, out)


def membership_checksums(
    universe: ce.Universe,
    present: jax.Array,  # [B, N] bool
    status: jax.Array,  # [B, N] int codes
    inc_ms: jax.Array,  # [B, N] int64
    max_digits: int = MAX_DIGITS,
    impl: Optional[str] = None,
) -> jax.Array:
    """One-shot convenience: encode all records densely and hash — the
    fused twin of ``hash32_rows(*membership_rows(...))``."""
    rec_b, rec_l = member_records(
        universe, present, status, inc_ms, max_digits
    )
    return fused_hash_rows(pack_record_words(rec_b), rec_l, impl=impl)
