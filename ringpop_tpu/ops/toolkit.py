"""Shared fused-op toolkit — the twice-proven streaming-kernel pattern
as reusable parts.

Rounds 7 (fused checksum) and 10/14 (fused exchange) each hand-built the
same four-piece pattern:

1. a **gridless Pallas streaming kernel** — rows tiled onto the VPU's
   [8 sublanes x 128 lanes] geometry, tiles beyond the VMEM budget
   mapped through an outer ``lax.scan``, never a grid (the only Pallas
   shape the axon tunnel's compile helper accepts — PALLAS_BISECT.json);
2. a **bit-exact pure-XLA twin** — the same exact integer arithmetic as
   plain vector ops: the CPU production path, the partitionable GSPMD
   form, and the reference every interpret-mode kernel test pins
   against;
3. **auto resolution** — a per-backend table pinned to concrete values
   at driver construction (shared executable caches key on params, so a
   trace-time backend read would alias cache entries), surfaced as an
   observable runlog event + statsd gauge instead of a silent drop
   (the round-14 lesson: the PR-5 sharded engine silently fell back to
   XLA for two rounds);
4. **registration** — jaxpr-audit entries, astlint TRACED_ENTRIES,
   retrace probes, COST_BUDGET rows, and a gate-equivalence test, so
   every kernel is machine-checked callback-free, uint32-disciplined,
   retrace-budgeted, cost-pinned, and bitwise-twinned.

This module is the single source for pieces 1-3 plus the twin REGISTRY
that piece 4's machine-checked coverage rule
(:mod:`ringpop_tpu.analysis.kernel_coverage`) enforces: every
``pallas_call`` under ``ops/`` must appear here with a bit-exact twin
and a gate-equivalence test, or the analysis prong fails tier-1.

Backend-gated donation (the PR 8 CPU find — XLA-cache-deserialized CPU
executables mis-execute buffer donation) stays in
``models/sim/storm.donate_state_argnums``: donation is a property of
the jitted *driver* call, not of an individual op, but it is part of
the pattern contract documented here and in README "Kernel toolkit".
"""

from __future__ import annotations

import dataclasses
import math
from typing import Callable, Iterable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

# the VPU tile geometry every streaming kernel in this repo tiles to
SUB, LANE = 8, 128
DEFAULT_VMEM_BUDGET = 8 * 1024 * 1024


# ---------------------------------------------------------------------------
# piece 3: the ONE auto-resolution table + observability shape
#
# Every fused-op knob in the repo resolves through resolve_impl:
# engine.resolve_fused_checksum, engine.resolve_fused_tick,
# engine_scalable.resolve_fused_exchange / resolve_sharded_exchange.
# Each wrapper owns its table and validation; the mechanics (explicit
# values honored + validated, "auto" looked up per backend) live here
# exactly once.


def resolve_impl(
    knob: str,
    requested: str,
    backend: str,
    *,
    auto: dict,
    allowed: Sequence[str],
) -> str:
    """Resolve a fused-op knob to a concrete impl.

    ``requested`` is the raw param value: anything but "auto" is honored
    as-is after validation against ``allowed``; "auto" is looked up in
    the ``auto`` table by ``backend`` ("*" is the fallback row)."""
    if requested != "auto":
        if requested not in allowed:
            raise ValueError(
                "%s must be auto|%s, got %r"
                % (knob, "|".join(allowed), requested)
            )
        return requested
    return auto.get(backend, auto["*"])


def resolution_note(
    knob: str,
    requested: str,
    resolved: str,
    backend: str,
    single_device_resolution: Optional[str] = None,
    **extra,
) -> dict:
    """The runlog-ready resolution dict — the PR-9 mesh note's shape
    generalized to any fused-op knob.  ``differs_from_single_device``
    flags an "auto" request whose resolution diverged from the plain
    single-device pick (the observable replacement for a silent
    drop)."""
    sdr = resolved if single_device_resolution is None else (
        single_device_resolution
    )
    note = {
        "knob": knob,
        "requested": requested,
        "impl": resolved,
        "backend": backend,
        "single_device_resolution": sdr,
        "differs_from_single_device": (
            requested == "auto" and resolved != sdr
        ),
    }
    note.update(extra)
    return note


def emit_resolution(
    note: dict,
    recorder=None,
    statsd=None,
    *,
    event: str = "op_resolution",
    gauge_prefix: Optional[str] = None,
) -> None:
    """Publish a resolution note through the obs stack: one runlog event
    row (obs.RunRecorder) + the PR-9 statsd gauge shape
    (``<prefix>.resolution_differs`` 1/0, plus ``<prefix>.cap`` when the
    note carries a static cap).  Either sink may be None."""
    if recorder is not None:
        recorder.record_event(event, **note)
    if statsd is not None and gauge_prefix is not None:
        statsd.gauge(
            "%s.resolution_differs" % gauge_prefix,
            int(bool(note.get("differs_from_single_device", False))),
        )
        if note.get("cap") is not None:
            statsd.gauge("%s.cap" % gauge_prefix, int(note["cap"]))


# ---------------------------------------------------------------------------
# pieces 1-2: kernel spec + tile/VMEM-budget row-streaming scaffold


def default_interpret() -> bool:
    """Interpret mode off-TPU keeps kernel tests hermetic (the exchange
    / farmhash convention)."""
    return jax.devices()[0].platform != "tpu"


def packed_width(n_cols: int) -> int:
    """Words per row of a :func:`pack_bool_rows` bitmask."""
    return -(-n_cols // 32)


def pack_bool_rows(mask: jax.Array) -> jax.Array:
    """[N, M] bool -> [N, ceil(M/32)] uint32 row bitmask (bit c%32 of
    word c//32 = mask[:, c] — the engine_scalable._pack_mask layout).
    The shared dense-mask compression for accumulator planes that cross
    phase boundaries: 8x smaller than a bool plane, exact (popcount
    sums reproduce bool-mask counts bit-for-bit)."""
    n, m = mask.shape
    pad = (-m) % 32
    if pad:
        mask = jnp.pad(mask, ((0, 0), (0, pad)))
    w = mask.reshape(n, -1, 32)
    bits = (jnp.uint32(1) << jnp.arange(32, dtype=jnp.uint32))[
        None, None, :
    ]
    return jnp.sum(
        jnp.where(w, bits, jnp.uint32(0)), axis=2, dtype=jnp.uint32
    )


def pad_rows(x: jax.Array, rows: int) -> jax.Array:
    """Zero-pad the leading axis to a multiple of ``rows``."""
    pad = (-x.shape[0]) % rows
    if not pad:
        return x
    return jnp.pad(x, ((0, pad),) + ((0, 0),) * (x.ndim - 1))


def pad_cols(x: jax.Array, lane: int = LANE) -> jax.Array:
    """Zero-pad the trailing axis to a multiple of ``lane``."""
    pad = (-x.shape[-1]) % lane
    if not pad:
        return x
    return jnp.pad(x, ((0, 0),) * (x.ndim - 1) + ((0, pad),))


def pick_row_tile(
    row_bytes: int,
    *,
    vmem_budget: int = DEFAULT_VMEM_BUDGET,
    max_rows: Optional[int] = None,
    name: str = "kernel",
) -> int:
    """The VMEM-budget lever shared by every row-streaming kernel: the
    largest SUB-multiple row tile whose working set (``row_bytes`` per
    row, inputs + outputs + double-buffer slack included by the caller)
    fits the budget.  Refuses loudly — like the exchange kernel — when
    even one sublane group does not fit, instead of issuing a kernel
    that OOMs VMEM on chip."""
    if row_bytes <= 0:
        raise ValueError("row_bytes must be positive, got %d" % row_bytes)
    tile = (vmem_budget // row_bytes) // SUB * SUB
    if max_rows is not None:
        cap = -(-max_rows // SUB) * SUB
        tile = min(tile, cap)
    if tile < SUB:
        raise ValueError(
            "%s: one [%d]-row sublane tile needs %d bytes of VMEM > "
            "budget %d — use the bit-exact XLA twin for shapes this "
            "wide" % (name, SUB, SUB * row_bytes, vmem_budget)
        )
    return tile


def stream_row_tiles(
    kernel: Callable,
    inputs: Sequence[jax.Array],
    out_widths: Sequence[object],  # "plane" or int trailing width
    out_dtypes: Sequence[object],
    *,
    n_cols: int,
    in_planes: Optional[Sequence[bool]] = None,
    row_tile: Optional[int] = None,
    vmem_budget: int = DEFAULT_VMEM_BUDGET,
    interpret: Optional[bool] = None,
) -> List[jax.Array]:
    """The gridless row-streaming scaffold (pattern piece 1), extracted
    from ``ops.exchange._exchange_pallas`` / ``ops.pallas_farmhash``:

    - every input is ``[N, C]``; "plane" inputs (trailing width
      ``n_cols``) are column-padded to a LANE multiple so the lane axis
      is register-shaped, narrow per-row vectors ride unpadded.
      ``in_planes`` flags which inputs are planes EXPLICITLY — pass it
      whenever a narrow input's width could collide with ``n_cols``
      (e.g. a packed accumulator at tiny n); when omitted, width ==
      ``n_cols`` is used as the test;
    - rows are zero-padded to the row tile and the kernel is invoked
      once per ``[row_tile, C]`` tile — a single gridless
      ``pallas_call`` when one tile covers all rows, otherwise an outer
      ``lax.scan`` over tiles (never a grid: the tunnel-validated
      shape);
    - outputs are declared by trailing width: the string ``"plane"``
      means padded-``n_cols`` wide (cropped back to ``n_cols``), an int
      is a narrow per-row output (row-ORs, per-row counts).  Padded
      rows/columns are zero on every input, so reductions over them are
      exact — kernels must preserve that (mask work by an input, not by
      position).

    Returns the outputs cropped back to ``[N, width]``.
    """
    if interpret is None:
        interpret = default_interpret()
    from jax.experimental import pallas as pl

    n = inputs[0].shape[0]
    ncp = -(-n_cols // LANE) * LANE
    if in_planes is None:
        in_planes = [x.shape[-1] == n_cols for x in inputs]
    elif len(in_planes) != len(inputs):
        raise ValueError(
            "in_planes must flag every input: %d flags for %d inputs"
            % (len(in_planes), len(inputs))
        )
    padded = [
        pad_cols(x) if is_plane else x
        for x, is_plane in zip(inputs, in_planes)
    ]
    # out_widths are static Python ints/strings (op-shape metadata,
    # never traced values)
    widths = [ncp if w == "plane" else int(w) for w in out_widths]  # jaxgate: ignore[host-coerce]
    row_bytes = sum(
        x.shape[-1] * x.dtype.itemsize for x in padded
    ) + sum(
        w * jnp.dtype(dt).itemsize for w, dt in zip(widths, out_dtypes)
    )
    if row_tile is None:
        # x2: double-buffered HBM<->VMEM copies in flight
        row_tile = pick_row_tile(
            2 * row_bytes,
            vmem_budget=vmem_budget,
            max_rows=n,
            name="stream_row_tiles",
        )
    padded = [pad_rows(x, row_tile) for x in padded]
    nrt = padded[0].shape[0] // row_tile
    tiles = tuple(
        x.reshape(nrt, row_tile, x.shape[-1]) for x in padded
    )
    out_shape = [
        jax.ShapeDtypeStruct((row_tile, w), dt)
        for w, dt in zip(widths, out_dtypes)
    ]
    call = pl.pallas_call(kernel, out_shape=out_shape, interpret=interpret)
    if nrt == 1:
        outs = call(*(t[0] for t in tiles))
        outs = tuple(o[None] for o in outs)
    else:
        def step(_, xs):
            return None, tuple(call(*xs))

        _, outs = jax.lax.scan(step, None, tiles)
    cropped = []
    for o, w, want in zip(outs, widths, out_widths):
        flat = o.reshape(nrt * row_tile, w)[:n]
        cropped.append(flat[:, :n_cols] if want == "plane" else flat)
    return cropped


# ---------------------------------------------------------------------------
# piece 4: the machine-checked twin registry
#
# Every Pallas kernel under ops/ MUST be registered here with its
# bit-exact twin and the test that gates their equivalence — the
# analysis.kernel_coverage prong walks ops/ for pallas_call sites and
# fails tier-1 on any unregistered kernel (mutation-tested in
# tests/analysis/test_kernel_coverage.py).


@dataclasses.dataclass(frozen=True)
class KernelTwin:
    """One registered pallas kernel <-> bit-exact twin pair.

    ``module``: ops/ module basename holding the ``pallas_call``;
    ``kernel_entry``: the public entry that lowers to it;
    ``twin_entry``: the pure-XLA twin (``twin_module`` when it lives in
    a sibling ops/ module); ``gate_test``: repo-relative test file that
    pins kernel-vs-twin bitwise equality and mentions ``kernel_entry``
    by name."""

    module: str
    kernel_entry: str
    twin_entry: str
    gate_test: str
    twin_module: Optional[str] = None


TWIN_REGISTRY: Tuple[KernelTwin, ...] = (
    # round 2/7: the farmhash block walk (grid + gridless forms) twins
    # the scanned XLA lowering in jax_farmhash.hash32_rows
    KernelTwin(
        "pallas_farmhash",
        "block_loop",
        "hash32_rows",
        "tests/ops/test_jax_farmhash.py",
        twin_module="jax_farmhash",
    ),
    KernelTwin(
        "pallas_farmhash",
        "block_loop_nogrid",
        "hash32_rows",
        "tests/ops/test_jax_farmhash.py",
        twin_module="jax_farmhash",
    ),
    # round 7: the fused checksum assemble+hash streaming kernel
    KernelTwin(
        "pallas_farmhash",
        "fused_stream_nogrid",
        "fused_stream_xla",
        "tests/ops/test_fused_checksum.py",
    ),
    # round 10/14: the fused push-pull exchange megakernel
    KernelTwin(
        "exchange",
        "exchange",
        "exchange_xla",
        "tests/ops/test_exchange.py",
    ),
    # round 16: the fused full-tick membership-update pass
    KernelTwin(
        "fused_apply",
        "apply_updates",
        "apply_updates_xla",
        "tests/ops/test_fused_apply.py",
    ),
    # round 16: the fused dissemination budget pass
    KernelTwin(
        "fused_piggyback",
        "pb_budget",
        "pb_budget_xla",
        "tests/ops/test_fused_piggyback.py",
    ),
)


def twins_for_module(module: str) -> Tuple[KernelTwin, ...]:
    return tuple(t for t in TWIN_REGISTRY if t.module == module)


def assert_twin_bitwise(  # jaxgate: host — test helper, never traced
    op: Callable,
    args: tuple,
    *,
    impls: Iterable[str] = ("xla", "pallas"),
    **kwargs,
) -> None:
    """The shared gate-equivalence assertion: call ``op(*args,
    impl=...)`` for every impl (interpret mode handles Pallas off-TPU)
    and require every output array bitwise-identical to the first
    impl's.  Ops taking an ``impl`` kwarg and returning a pytree of
    arrays (None leaves allowed) plug in directly."""
    import numpy as np

    impls = list(impls)
    ref = jax.tree.leaves(op(*args, impl=impls[0], **kwargs))
    for impl in impls[1:]:
        got = jax.tree.leaves(op(*args, impl=impl, **kwargs))
        assert len(ref) == len(got), (impls[0], impl)
        for i, (a, b) in enumerate(zip(ref, got)):
            assert np.array_equal(np.asarray(a), np.asarray(b)), (
                "output %d differs between impl=%r and impl=%r"
                % (i, impls[0], impl)
            )
