"""Device-side log2-bucketed latency histograms for the scanned tick.

The reference treats timing distributions as protocol INPUTS, not just
telemetry: the gossip loop's adaptive protocol period is ``p50 of the
ping-timing histogram x 2`` (lib/gossip/index.js:42-50), per-tick
duration rides a ``metrics.Histogram`` surfaced through ``getStats()``,
and the convergence benchmark reports count/min/max/mean/p75/p95/p99.
The scanned engines cannot call a host histogram per event (the jaxgate
purity contract forbids callbacks in the tick), so this module is the
device half: a fixed-shape ``[tracks, NBUCKETS]`` uint32 counter array
carried through the scan as an ordinary state field and bumped with
masked scatter-adds under the *same masks that drive the trajectory* —
the flight-recorder pattern (models/sim/flight.py), so recording is
trajectory-neutral by construction (write-only: nothing in the protocol
reads the counts) and gate-equivalence-safe.

Bucketing: log2 buckets over non-negative int32 values.  Bucket 0 holds
exactly the value 0; bucket ``b >= 1`` holds ``[2^(b-1), 2^b - 1]``.
With ``NBUCKETS = 32`` every non-negative int32 lands in a bucket — no
overflow bucket is needed (the top bucket 31 covers ``[2^30, 2^31-1]``).
Negative values are invalid observations and must be masked out by the
caller (``record`` additionally guards with ``v >= 0``).

Host half — exact percentile extraction, summaries, and the runlog /
statsd / Prometheus rendering — lives in :mod:`ringpop_tpu.obs.histograms`.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

NBUCKETS = 32


def init(tracks: int) -> jax.Array:
    """Zeroed ``[tracks, NBUCKETS]`` uint32 counter array.

    uint32, not int32: a 1M-node storm recording an [N, U]-masked track
    for thousands of ticks can pass 2^31 observations per bucket."""
    return jnp.zeros((tracks, NBUCKETS), jnp.uint32)


def bucket_index(values: jax.Array) -> jax.Array:
    """[...] int32 -> [...] int32 bucket index (bit length of the value).

    ``bucket_index(v) == 0 if v == 0 else floor(log2(v)) + 1`` for
    ``v >= 0`` — computed as a threshold-count sum (no integer log on
    TPU vector units; 31 compares fuse into one elementwise pass).
    Negative values clamp to bucket 0; callers mask them out."""
    v = values.astype(jnp.int32)
    count = jnp.zeros(v.shape, jnp.int32)
    for b in range(NBUCKETS - 1):  # thresholds 2^0 .. 2^30
        count = count + (v >= jnp.int32(1 << b)).astype(jnp.int32)
    return count


def record(
    hist: jax.Array,  # [H, NBUCKETS] uint32
    track: int,  # static track index
    values: jax.Array,  # [M] int32 observations
    mask: jax.Array,  # [M] bool — which lanes are real observations
) -> jax.Array:
    """Masked scatter-add of up to M observations into one track.

    Duplicate buckets within one call accumulate (``.add`` scatter
    semantics); masked-out and negative lanes land in a dropped slot
    past the bucket axis.  Static shapes throughout — scan-safe."""
    values = values.reshape(-1)
    mask = mask.reshape(-1)
    ok = mask & (values >= 0)
    idx = jnp.where(ok, bucket_index(values), NBUCKETS)  # NBUCKETS drops
    return hist.at[track, idx].add(
        ok.astype(jnp.uint32), mode="drop"
    )


def record_count(
    hist: jax.Array, track: int, value: jax.Array
) -> jax.Array:
    """One scalar observation per call (per-tick size metrics — dirty
    rows, dirty buckets): records ``value`` once, unconditionally."""
    v = value.astype(jnp.int32).reshape(1)
    return record(hist, track, v, jnp.ones(1, bool))


# -- host-side bucket arithmetic (shared with obs.histograms) -------------


def bucket_lo(b: int) -> int:
    """Smallest value bucket ``b`` holds."""
    return 0 if b == 0 else 1 << (b - 1)


def bucket_hi(b: int) -> int:
    """Largest value bucket ``b`` holds."""
    return 0 if b == 0 else (1 << b) - 1


def bucket_index_np(values) -> np.ndarray:
    """Host/numpy reference of :func:`bucket_index` — the oracle the
    device op is tested against (tests/ops/test_histogram.py)."""
    v = np.asarray(values, np.int64)
    out = np.zeros(v.shape, np.int64)
    nz = v > 0
    out[nz] = np.floor(np.log2(v[nz])).astype(np.int64) + 1
    return np.clip(out, 0, NBUCKETS - 1).astype(np.int32)
