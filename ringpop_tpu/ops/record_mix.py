"""Commutative per-member record hash for fast (non-parity) checksums.

Both simulator engines need a cheap uint32 hash of a member record
``(subject, status, incarnation)`` whose per-node SUM discriminates
membership views (the fast twin of the reference's order-sensitive
FarmHash32-of-joined-string checksum, lib/membership/index.js:48-75).
One definition lives here so the two engines cannot drift.
"""

from __future__ import annotations

import jax.numpy as jnp


def record_mix(subject, status, inc):
    """[...]-shaped int arrays -> uint32 record hash (elementwise).

    ``inc`` is an int32 tick stamp from both simulator engines' hot paths;
    int64 inputs (storm.py's ring-key mixing) still hash the high word.
    32-bit inputs skip it so the whole mix stays in 32-bit lanes on TPU."""
    x = subject.astype(jnp.uint32) * jnp.uint32(0x9E3779B1)
    x ^= status.astype(jnp.uint32) * jnp.uint32(0x85EBCA77)
    x ^= inc.astype(jnp.uint32) * jnp.uint32(0xC2B2AE3D)
    if inc.dtype.itemsize > 4:
        x ^= ((inc >> 32) & 0xFFFFFFFF).astype(jnp.uint32) * jnp.uint32(
            0x27D4EB2F
        )
    x ^= x >> 15
    x *= jnp.uint32(0x2C1B3C6D)
    x ^= x >> 13
    return x
