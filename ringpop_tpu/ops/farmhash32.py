"""Bit-exact FarmHash32 (the ``farmhashmk::Hash32`` variant).

The reference hashes every ring replica point, ring checksum, key lookup and
membership checksum with the npm ``farmhash`` addon's ``hash32``
(/root/reference/lib/ring/index.js:21,29,102,146 and
/root/reference/lib/membership/index.js:24,65).  That addon wraps Google
FarmHash; both ``farmhash::Hash32`` (portable build, no -msse4 flags — the
node-gyp default) and ``farmhash::Fingerprint32`` dispatch to
``farmhashmk::Hash32``, so farmhashmk is the variant to match.

This module provides:

- :func:`hash32` — scalar pure-Python implementation (the readable spec).
- :func:`hash32_batch` — numpy-vectorized implementation over a padded
  ``[B, L] uint8`` byte matrix with per-row lengths.  This is the host-side
  batch oracle used by tests and by host ring/membership code.

A C++ shared-library twin lives in ``ringpop_tpu/ops/_native`` (the native
oracle, matching the reference's native-addon substrate), and an in-jit JAX
twin in :mod:`ringpop_tpu.ops.jax_farmhash`.

All four implementations are cross-checked in tests/ops/test_farmhash32.py
over every length class (0-4, 5-12, 13-24, >24, multi-block).
"""

from __future__ import annotations

from typing import Sequence, Tuple, Union

import numpy as np

C1 = 0xCC9E2D51
C2 = 0x1B873593
MASK = 0xFFFFFFFF


# ---------------------------------------------------------------------------
# Scalar pure-Python implementation (readable spec; python ints mod 2^32)
# ---------------------------------------------------------------------------

def _rot32(x: int, r: int) -> int:
    """Right-rotate, matching FarmHash's Rotate32.  Masks the input first:
    callers pass sums that may exceed 32 bits and the carry must not leak
    into the right-shift."""
    x &= MASK
    if r == 0:
        return x
    return ((x >> r) | (x << (32 - r))) & MASK


def _fmix(h: int) -> int:
    h &= MASK
    h ^= h >> 16
    h = (h * 0x85EBCA6B) & MASK
    h ^= h >> 13
    h = (h * 0xC2B2AE35) & MASK
    h ^= h >> 16
    return h


def _mur(a: int, h: int) -> int:
    a = (a * C1) & MASK
    a = _rot32(a, 17)
    a = (a * C2) & MASK
    h ^= a
    h = _rot32(h, 19)
    return (h * 5 + 0xE6546B64) & MASK


def _fetch32(data: bytes, i: int) -> int:
    return int.from_bytes(data[i : i + 4], "little")


def _hash32_len_0_to_4(s: bytes, seed: int = 0) -> int:
    b = seed
    c = 9
    for ch in s:
        # signed char semantics: bytes >= 0x80 are negative
        v = ch - 256 if ch >= 128 else ch
        b = (b * C1 + v) & MASK
        c ^= b
    return _fmix(_mur(b, _mur(len(s), c)))


def _hash32_len_5_to_12(s: bytes, seed: int = 0) -> int:
    n = len(s)
    a = (n + _fetch32(s, 0)) & MASK
    b = (n * 5 + _fetch32(s, n - 4)) & MASK
    c = (9 + _fetch32(s, (n >> 1) & 4)) & MASK
    d = (n * 5 + seed) & MASK
    return _fmix(seed ^ _mur(c, _mur(b, _mur(a, d))))


def _hash32_len_13_to_24(s: bytes, seed: int = 0) -> int:
    n = len(s)
    a = _fetch32(s, (n >> 1) - 4)
    b = _fetch32(s, 4)
    c = _fetch32(s, n - 8)
    d = _fetch32(s, n >> 1)
    e = _fetch32(s, 0)
    f = _fetch32(s, n - 4)
    h = (d * C1 + n + seed) & MASK
    a = (_rot32(a, 12) + f) & MASK
    h = (_mur(c, h) + a) & MASK
    a = (_rot32(a, 3) + c) & MASK
    h = (_mur(e, h) + a) & MASK
    a = (_rot32(a + f, 12) + d) & MASK
    h = (_mur(b ^ seed, h) + a) & MASK
    return _fmix(h)


def hash32(data: Union[bytes, str]) -> int:
    """farmhashmk::Hash32 over ``data``; strings are UTF-8 encoded (the npm
    addon converts JS strings to utf-8 buffers before hashing)."""
    if isinstance(data, str):
        data = data.encode("utf-8")
    n = len(data)
    if n <= 4:
        return _hash32_len_0_to_4(data)
    if n <= 12:
        return _hash32_len_5_to_12(data)
    if n <= 24:
        return _hash32_len_13_to_24(data)

    # len > 24
    h = n & MASK
    g = (C1 * n) & MASK
    f = g
    a0 = (_rot32((_fetch32(data, n - 4) * C1) & MASK, 17) * C2) & MASK
    a1 = (_rot32((_fetch32(data, n - 8) * C1) & MASK, 17) * C2) & MASK
    a2 = (_rot32((_fetch32(data, n - 16) * C1) & MASK, 17) * C2) & MASK
    a3 = (_rot32((_fetch32(data, n - 12) * C1) & MASK, 17) * C2) & MASK
    a4 = (_rot32((_fetch32(data, n - 20) * C1) & MASK, 17) * C2) & MASK
    h ^= a0
    h = _rot32(h, 19)
    h = (h * 5 + 0xE6546B64) & MASK
    h ^= a2
    h = _rot32(h, 19)
    h = (h * 5 + 0xE6546B64) & MASK
    g ^= a1
    g = _rot32(g, 19)
    g = (g * 5 + 0xE6546B64) & MASK
    g ^= a3
    g = _rot32(g, 19)
    g = (g * 5 + 0xE6546B64) & MASK
    f = (f + a4) & MASK
    f = (_rot32(f, 19) + 113) & MASK
    iters = (n - 1) // 20
    off = 0
    for _ in range(iters):
        a = _fetch32(data, off)
        b = _fetch32(data, off + 4)
        c = _fetch32(data, off + 8)
        d = _fetch32(data, off + 12)
        e = _fetch32(data, off + 16)
        h = (h + a) & MASK
        g = (g + b) & MASK
        f = (f + c) & MASK
        h = (_mur(d, h) + e) & MASK
        g = (_mur(c, g) + a) & MASK
        f = (_mur((b + (e * C1 & MASK)) & MASK, f) + d) & MASK
        f = (f + g) & MASK
        g = (g + f) & MASK
        off += 20
    g = (_rot32(g, 11) * C1) & MASK
    g = (_rot32(g, 17) * C1) & MASK
    f = (_rot32(f, 11) * C1) & MASK
    f = (_rot32(f, 17) * C1) & MASK
    h = _rot32((h + g) & MASK, 19)
    h = (h * 5 + 0xE6546B64) & MASK
    h = (_rot32(h, 17) * C1) & MASK
    h = _rot32((h + f) & MASK, 19)
    h = (h * 5 + 0xE6546B64) & MASK
    h = (_rot32(h, 17) * C1) & MASK
    return h


# ---------------------------------------------------------------------------
# numpy-vectorized batch implementation over padded byte rows
# ---------------------------------------------------------------------------

U32 = np.uint32
U64 = np.uint64


def encode_rows(strings: Sequence[Union[bytes, str]], pad_to: int = 0) -> Tuple[np.ndarray, np.ndarray]:
    """Encode strings into a zero-padded ``[B, L] uint8`` matrix + lengths.

    L is ``max(len) + 4`` rounded up (slack so vectorized 4-byte fetches never
    index out of bounds), or at least ``pad_to``.
    """
    rows = [s.encode("utf-8") if isinstance(s, str) else bytes(s) for s in strings]
    lens = np.array([len(r) for r in rows], dtype=np.int64)
    width = max(int(lens.max(initial=0)) + 4, pad_to, 8)
    mat = np.zeros((len(rows), width), dtype=np.uint8)
    for i, r in enumerate(rows):
        mat[i, : len(r)] = np.frombuffer(r, dtype=np.uint8)
    return mat, lens


def _np_rot(x: np.ndarray, r: int) -> np.ndarray:
    if r == 0:
        return x
    return ((x >> U32(r)) | (x << U32(32 - r))).astype(U32)


def _np_fmix(h: np.ndarray) -> np.ndarray:
    h = h ^ (h >> U32(16))
    h = (h * U32(0x85EBCA6B)).astype(U32)
    h = h ^ (h >> U32(13))
    h = (h * U32(0xC2B2AE35)).astype(U32)
    h = h ^ (h >> U32(16))
    return h


def _np_mur(a: np.ndarray, h: np.ndarray) -> np.ndarray:
    a = (a * U32(C1)).astype(U32)
    a = _np_rot(a, 17)
    a = (a * U32(C2)).astype(U32)
    h = h ^ a
    h = _np_rot(h, 19)
    return (h * U32(5) + U32(0xE6546B64)).astype(U32)


def _np_fetch32(mat: np.ndarray, off: np.ndarray) -> np.ndarray:
    """Per-row little-endian 4-byte fetch at per-row offsets.

    ``off`` may be negative or out-of-range for rows where the value is
    ultimately discarded; clamp for safety.
    """
    off = np.clip(off, 0, mat.shape[1] - 4).astype(np.int64)
    b0 = np.take_along_axis(mat, off[:, None], axis=1)[:, 0].astype(U32)
    b1 = np.take_along_axis(mat, off[:, None] + 1, axis=1)[:, 0].astype(U32)
    b2 = np.take_along_axis(mat, off[:, None] + 2, axis=1)[:, 0].astype(U32)
    b3 = np.take_along_axis(mat, off[:, None] + 3, axis=1)[:, 0].astype(U32)
    return (b0 | (b1 << U32(8)) | (b2 << U32(16)) | (b3 << U32(24))).astype(U32)


def _np_hash_0_4(mat: np.ndarray, lens: np.ndarray) -> np.ndarray:
    n = lens.astype(U32)
    b = np.zeros(mat.shape[0], dtype=U32)
    c = np.full(mat.shape[0], 9, dtype=U32)
    for i in range(4):
        active = lens > i
        v = mat[:, min(i, mat.shape[1] - 1)].astype(np.int8).astype(np.int32).astype(U32)
        nb = (b * U32(C1) + v).astype(U32)
        b = np.where(active, nb, b)
        c = np.where(active, c ^ nb, c)
    return _np_fmix(_np_mur(b, _np_mur(n, c)))


def _np_hash_5_12(mat: np.ndarray, lens: np.ndarray) -> np.ndarray:
    n = lens.astype(U32)
    a = (n + _np_fetch32(mat, np.zeros_like(lens))).astype(U32)
    b = (n * U32(5) + _np_fetch32(mat, lens - 4)).astype(U32)
    c = (U32(9) + _np_fetch32(mat, (lens >> 1) & 4)).astype(U32)
    d = (n * U32(5)).astype(U32)  # seed = 0
    return _np_fmix(_np_mur(c, _np_mur(b, _np_mur(a, d))))


def _np_hash_13_24(mat: np.ndarray, lens: np.ndarray) -> np.ndarray:
    n = lens.astype(U32)
    a = _np_fetch32(mat, (lens >> 1) - 4)
    b = _np_fetch32(mat, np.full_like(lens, 4))
    c = _np_fetch32(mat, lens - 8)
    d = _np_fetch32(mat, lens >> 1)
    e = _np_fetch32(mat, np.zeros_like(lens))
    f = _np_fetch32(mat, lens - 4)
    h = (d * U32(C1) + n).astype(U32)  # seed = 0
    a = (_np_rot(a, 12) + f).astype(U32)
    h = (_np_mur(c, h) + a).astype(U32)
    a = (_np_rot(a, 3) + c).astype(U32)
    h = (_np_mur(e, h) + a).astype(U32)
    a = (_np_rot((a + f).astype(U32), 12) + d).astype(U32)
    h = (_np_mur(b, h) + a).astype(U32)  # b ^ seed, seed = 0
    return _np_fmix(h)


def _np_hash_long(mat: np.ndarray, lens: np.ndarray) -> np.ndarray:
    n32 = lens.astype(U32)
    h = n32.copy()
    g = (U32(C1) * n32).astype(U32)
    f = g.copy()

    def tail(off_from_end: int) -> np.ndarray:
        v = _np_fetch32(mat, lens - off_from_end)
        return (_np_rot((v * U32(C1)).astype(U32), 17) * U32(C2)).astype(U32)

    a0, a1, a2, a3, a4 = tail(4), tail(8), tail(16), tail(12), tail(20)
    h ^= a0
    h = _np_rot(h, 19)
    h = (h * U32(5) + U32(0xE6546B64)).astype(U32)
    h ^= a2
    h = _np_rot(h, 19)
    h = (h * U32(5) + U32(0xE6546B64)).astype(U32)
    g ^= a1
    g = _np_rot(g, 19)
    g = (g * U32(5) + U32(0xE6546B64)).astype(U32)
    g ^= a3
    g = _np_rot(g, 19)
    g = (g * U32(5) + U32(0xE6546B64)).astype(U32)
    f = (f + a4).astype(U32)
    f = (_np_rot(f, 19) + U32(113)).astype(U32)

    iters = (lens - 1) // 20
    max_iters = int(iters.max(initial=0))
    zeros = np.zeros_like(lens)
    for i in range(max_iters):
        active = iters > i
        base = zeros + 20 * i
        a = _np_fetch32(mat, base)
        b = _np_fetch32(mat, base + 4)
        c = _np_fetch32(mat, base + 8)
        d = _np_fetch32(mat, base + 12)
        e = _np_fetch32(mat, base + 16)
        nh = (h + a).astype(U32)
        ng = (g + b).astype(U32)
        nf = (f + c).astype(U32)
        nh = (_np_mur(d, nh) + e).astype(U32)
        ng = (_np_mur(c, ng) + a).astype(U32)
        nf = (_np_mur((b + (e * U32(C1)).astype(U32)).astype(U32), nf) + d).astype(U32)
        nf = (nf + ng).astype(U32)
        ng = (ng + nf).astype(U32)
        h = np.where(active, nh, h)
        g = np.where(active, ng, g)
        f = np.where(active, nf, f)

    g = (_np_rot(g, 11) * U32(C1)).astype(U32)
    g = (_np_rot(g, 17) * U32(C1)).astype(U32)
    f = (_np_rot(f, 11) * U32(C1)).astype(U32)
    f = (_np_rot(f, 17) * U32(C1)).astype(U32)
    h = _np_rot((h + g).astype(U32), 19)
    h = (h * U32(5) + U32(0xE6546B64)).astype(U32)
    h = (_np_rot(h, 17) * U32(C1)).astype(U32)
    h = _np_rot((h + f).astype(U32), 19)
    h = (h * U32(5) + U32(0xE6546B64)).astype(U32)
    h = (_np_rot(h, 17) * U32(C1)).astype(U32)
    return h


def hash32_batch(mat: np.ndarray, lens: np.ndarray) -> np.ndarray:
    """farmhashmk::Hash32 of each padded row; returns ``[B] uint32``."""
    mat = np.ascontiguousarray(mat, dtype=np.uint8)
    lens = np.asarray(lens, dtype=np.int64)
    if mat.ndim != 2 or lens.shape != (mat.shape[0],):
        raise ValueError("expected mat [B, L] and lens [B]")
    if mat.shape[1] < int(lens.max(initial=0)) + 4:
        # re-pad with slack so fetches past the end stay in-bounds
        extra = int(lens.max(initial=0)) + 4 - mat.shape[1]
        mat = np.pad(mat, ((0, 0), (0, extra)))

    with np.errstate(over="ignore"):
        out = _np_hash_0_4(mat, lens)
        out = np.where(lens > 4, _np_hash_5_12(mat, lens), out)
        out = np.where(lens > 12, _np_hash_13_24(mat, lens), out)
        if (lens > 24).any():
            out = np.where(lens > 24, _np_hash_long(mat, lens), out)
    return out.astype(U32)


def hash32_strings(strings: Sequence[Union[bytes, str]]) -> np.ndarray:
    """Convenience: batch-hash a list of strings."""
    mat, lens = encode_rows(strings)
    return hash32_batch(mat, lens)
