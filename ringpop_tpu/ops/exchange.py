"""Fused push-pull exchange megakernel for the scalable O(N·U) engine.

The scalable engine's gossip exchange is a handful of elementwise passes
over the ``[N, U/32]`` heard bitmask: OR the pulled partner rows in, OR
the pushed rows in, XOR against the pre-exchange mask for the new-bit
diff, and reduce each row's new bits against the rumor delta table for
the incremental checksum update.  Under XLA each pass materializes an
``[N, U/32]`` temporary in HBM — and the delta reduction's bit expansion
is 32x bigger than the mask itself — so a 1M-node storm tick streams the
mask several times per tick (engine_scalable.py round-4 notes;
PROF_PARITY_ROOFLINE.json storm phase).  This module fuses everything
after the partner-row gathers into ONE pass:

per ``[N_tile, U/32]`` VMEM tile::

    new  = heard | pulled | pushed      # push/pull OR
    diff = new ^ heard                  # new-bit mask (bits only turn ON)
    out rows: new, Σ_{set bits of diff} r_delta[bit]  (mod 2^32),
              popcount(diff)            # per-row new-bit count

so the heard mask is read from HBM once and written once, the diff and
its 32x bit expansion never exist outside VMEM, and the checksum delta
comes back as one ``[N]`` uint32 vector.  The delta reduction is exact
integer arithmetic — uint32 multiplies by {0, 1} bits with wrapping adds
— so every implementation here agrees bit-for-bit with the engine's limb
matmul (:func:`ringpop_tpu.models.sim.engine_scalable._bit_delta_sum`):
all of them compute the same mod-2^32 sum exactly.

Two implementations, selected by ``impl``:

- ``"pallas"`` — a gridless TPU kernel (the only Pallas shape the axon
  tunnel's compile helper accepts — PALLAS_BISECT.json): rows tiled
  [8 sublanes x 128 lanes] like ops.pallas_farmhash, the word axis walked
  by an in-kernel ``fori_loop``, row tiles beyond the VMEM budget mapped
  through an outer ``lax.scan``.  Interpret mode off-TPU keeps tests
  hermetic.
- ``"xla"`` — the bit-exact pure-XLA twin (same role as
  ``fused_stream_xla``): the same arithmetic as chunked vector ops, the
  CPU fallback and the reference the interpret tests pin the kernel
  against.

The partner-row gathers stay OUTSIDE this op: a dynamic cross-row gather
cannot live inside a row-tiled kernel (row i's partner may sit in any
other tile), and XLA's gather is already a single optimized read of the
mask.  What the op removes is every pass AFTER the gathers.

Sharded use (round 14): a ``pallas_call`` does not partition under GSPMD
— the sharded engine used to silently drop to the XLA twin.  The
shard_map'd exchange plane (:mod:`ringpop_tpu.parallel.mesh`) now
delivers the partner rows with explicit collectives and calls
:func:`exchange_local` on purely shard-local ``[N/S, U/32]`` tiles, so
the megakernel runs one VMEM pass per shard.  :func:`exchange_xla`
doubles as the PARTITIONABLE twin — identical exact mod-2^32 arithmetic
whose vector ops GSPMD shards by rows — and is the fallback gate every
sharded configuration is bitwise-compared against
(tests/parallel/test_shard_exchange.py).
"""

from __future__ import annotations

import math
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

SUB, LANE = 8, 128
TILE = SUB * LANE  # rows per kernel tile
WORD = 32


def popcount_u32(x: jax.Array) -> jax.Array:
    """SWAR popcount of a uint32 array — the ONE shared copy
    (engine_scalable imports this for its heard-coverage metric)."""
    x = x - ((x >> 1) & jnp.uint32(0x55555555))
    x = (x & jnp.uint32(0x33333333)) + ((x >> 2) & jnp.uint32(0x33333333))
    x = (x + (x >> 4)) & jnp.uint32(0x0F0F0F0F)
    return (x * jnp.uint32(0x01010101)) >> 24


def _exchange_kernel(heard_ref, pull_ref, push_ref, delta_ref,
                     onew_ref, oacc_ref, ocnt_ref=None):
    """One gridless call fuses OR + diff + popcount + delta-sum for a
    [W, S, LANE] row tile (rows flattened onto sublanes x lanes, the
    word axis walked by ``fori_loop``).  ``delta_ref`` is the rumor
    delta table pre-broadcast to [W, 32, 1, LANE] so the per-bit
    accumulate is a plain vector multiply — no scalar loads, keeping
    the kernel inside the tunnel-validated plain-operand shape.
    ``ocnt_ref`` is absent when the caller skipped the counts output
    (the engine's hot path — the popcount and its [N] write drop out of
    the program entirely)."""
    w_words = heard_ref.shape[0]
    rows_shape = heard_ref.shape[1:]
    want_counts = ocnt_ref is not None

    def body(w, carry):
        acc, cnt = carry
        h = heard_ref[w]
        new = h | pull_ref[w] | push_ref[w]
        diff = new ^ h
        onew_ref[w] = new
        for b in range(WORD):
            bit = (diff >> jnp.uint32(b)) & jnp.uint32(1)
            acc = acc + bit * delta_ref[w, b]
        if want_counts:
            cnt = cnt + popcount_u32(diff).astype(jnp.int32)
        return acc, cnt

    acc, cnt = jax.lax.fori_loop(
        0,
        w_words,
        body,
        (
            jnp.zeros(rows_shape, jnp.uint32),
            jnp.zeros(rows_shape, jnp.int32)
            if want_counts
            else jnp.int32(0),
        ),
    )
    oacc_ref[:] = acc
    if want_counts:
        ocnt_ref[:] = cnt


def _exchange_pallas(
    heard,
    pulled,
    pushed,
    r_delta,
    *,
    interpret: bool = False,
    vmem_budget: int = 8 * 1024 * 1024,
    want_counts: bool = True,
):
    from jax.experimental import pallas as pl

    n, w = heard.shape
    pad = (-n) % TILE
    if pad:
        zeros = ((0, pad), (0, 0))
        heard = jnp.pad(heard, zeros)
        pulled = jnp.pad(pulled, zeros)
        pushed = jnp.pad(pushed, zeros)
    s = (n + pad) // LANE

    # VMEM lever (same scheme as block_loop_nogrid): shrink the sublane
    # tile until 4 [W, s_t, LANE] mask planes + the broadcast delta
    # table + the two [s_t, LANE] accumulators fit the budget
    def tile_bytes(s_t):
        return 4 * (
            4 * w * s_t * LANE + w * WORD * LANE + 2 * s_t * LANE
        )

    s_t = s
    while s_t > SUB and tile_bytes(s_t) > vmem_budget:
        s_t = ((s_t + 1) // 2 + SUB - 1) // SUB * SUB  # halve, aligned
    if tile_bytes(s_t) > vmem_budget:
        # the shrink lever bottomed out at one sublane tile: the
        # lane-broadcast delta table scales with W alone (u > ~8k words
        # at the default budget) and no row tiling can recover — refuse
        # loudly instead of issuing a kernel that OOMs VMEM on chip
        raise ValueError(
            "exchange: [%d-word] delta table + minimum row tile need "
            "%d bytes of VMEM > budget %d — use impl='xla' (the "
            "bit-exact twin) for masks this wide"
            % (w, tile_bytes(s_t), vmem_budget)
        )
    rt = -(-s // s_t)  # row tiles
    if rt * s_t > s:
        extra = (rt * s_t - s) * LANE
        zeros = ((0, extra), (0, 0))
        heard = jnp.pad(heard, zeros)
        pulled = jnp.pad(pulled, zeros)
        pushed = jnp.pad(pushed, zeros)
        s = rt * s_t

    def tiles(x):  # [s*LANE, W] -> [rt, W, s_t, LANE]
        return x.reshape(rt, s_t, LANE, w).transpose(0, 3, 1, 2)

    delta_bc = jnp.broadcast_to(
        r_delta.reshape(w, WORD)[:, :, None, None], (w, WORD, 1, LANE)
    )
    out_shape = [
        jax.ShapeDtypeStruct((w, s_t, LANE), jnp.uint32),  # new mask
        jax.ShapeDtypeStruct((s_t, LANE), jnp.uint32),  # row delta
    ]
    if want_counts:
        out_shape.append(
            jax.ShapeDtypeStruct((s_t, LANE), jnp.int32)  # new bits
        )
    call = pl.pallas_call(
        _exchange_kernel,
        out_shape=out_shape,
        interpret=interpret,
    )
    if rt == 1:
        outs = call(
            tiles(heard)[0], tiles(pulled)[0], tiles(pushed)[0], delta_bc
        )
        outs = tuple(o[None] for o in outs)
    else:

        def step(_, x):
            ht, pt, qt = x
            return None, tuple(call(ht, pt, qt, delta_bc))

        _, outs = jax.lax.scan(
            step, None, (tiles(heard), tiles(pulled), tiles(pushed))
        )
    nh, acc = outs[0], outs[1]
    new_heard = nh.transpose(0, 2, 3, 1).reshape(-1, w)[:n]
    cnt = outs[2].reshape(-1)[:n] if want_counts else None
    return new_heard, acc.reshape(-1)[:n], cnt


def exchange_xla(
    heard,
    pulled,
    pushed,
    r_delta,
    _chunk_rows: int = 65536,
    want_counts: bool = True,
):
    """Pure-XLA twin of the fused exchange: identical outputs (exact
    mod-2^32 integer arithmetic throughout), chunked over rows so the
    32x bit expansion of the diff never materializes at full [N, U].
    ``want_counts=False`` drops the per-row popcount reduction from the
    program (the engine's hot path consumes only the delta).

    This is also the PARTITIONABLE twin: every op here is a vector op
    GSPMD shards by rows (the exactness of wrapping uint32 adds makes
    any partitioning bit-identical), so ``fused_exchange="xla"`` under
    a mesh is the fallback gate the shard_map'd exchange plane is
    bitwise-compared against."""
    n, w = heard.shape
    new = heard | pulled | pushed
    diff = new ^ heard
    tbl = r_delta.reshape(w, WORD)
    bit_ids = jnp.arange(WORD, dtype=jnp.uint32)[None, None, :]

    def per_chunk(d):  # [C, W] uint32 -> ([C] uint32, [C] int32?)
        bits = (d[:, :, None] >> bit_ids) & jnp.uint32(1)  # [C, W, 32]
        acc = jnp.sum(bits * tbl[None], axis=(1, 2), dtype=jnp.uint32)
        if not want_counts:
            return acc
        cnt = jnp.sum(popcount_u32(d), axis=1, dtype=jnp.uint32).astype(
            jnp.int32
        )
        return acc, cnt

    chunk = max(1, min(n, _chunk_rows))
    pad = (-n) % chunk
    rows = jnp.pad(diff, ((0, pad), (0, 0))) if pad else diff
    out = jax.lax.map(per_chunk, rows.reshape(-1, chunk, w))
    if not want_counts:
        return new, out.reshape(-1)[:n], None
    acc, cnt = out
    return new, acc.reshape(-1)[:n], cnt.reshape(-1)[:n]


def exchange_local(
    heard,
    pulled,
    pushed,
    r_delta,
    *,
    impl: str,
    interpret: "bool | None" = None,
    vmem_budget: int = 8 * 1024 * 1024,
):
    """Shard-local entry for the fused exchange: the megakernel on one
    shard's ``[N/S, U/32]`` row tile, inside a ``shard_map`` body.

    Identical arithmetic to :func:`exchange` (exact mod-2^32 — the
    bitwise-equality contract across impls and shard counts rests on
    it); the differences are contractual, not computational:

    - the caller has ALREADY delivered the cross-shard partner rows
      (``pulled``/``pushed`` are shard-local dense planes produced by
      the mesh plane's all_to_all / all-gather routing), so no global
      row index appears here and the kernel's row tiling sees only
      local rows — one VMEM pass per shard;
    - ``impl`` is required ("pallas" or "xla"): inside ``shard_map``
      there is no auto resolution — the driver pinned the kernel at
      plane construction (ScalableParams.fused_exchange);
    - counts are never requested (the engine's hot path consumes only
      the mask + delta).

    Returns ``(new_heard [N/S, U/32] uint32, row_delta [N/S] uint32)``.
    """
    new_heard, delta, _ = exchange(
        heard,
        pulled,
        pushed,
        r_delta,
        impl=impl,
        interpret=interpret,
        vmem_budget=vmem_budget,
        want_counts=False,
    )
    return new_heard, delta


def step_traffic_bytes(n: int, w: int) -> int:
    """Modeled HBM bytes per exchange step — the op's one-pass contract:
    3 mask reads (heard + the two partner-row planes the engine
    gathers) + 1 mask write + the [N] delta/count outputs; the delta
    table is negligible.  A LOWER bound (fusion can only reduce traffic
    below it, so derived GB/s is conservative).  The ONE copy of the
    model every bandwidth artifact shares — bench.py's scalable phase,
    benchmarks/tpu_measure.py's fused_exchange phase, and
    scripts/prof_exchange_roofline.py — so a change to the op's traffic
    contract lands in all three at once."""
    return (3 + 1) * n * w * 4 + 2 * n * 4


def exchange_cap(local_rows: int, shards: int) -> int:
    """Static per-(src shard, dst shard) row cap for the mesh exchange
    plane's all_to_all buckets — the ONE definition (parallel/mesh.py
    imports it; it lives here, next to the traffic model that charges
    the capped buffers, because ops never imports upward while mesh
    already imports ops).

    The PRP base permutation spreads each shard's ``local_rows`` sends
    ~Binomial(local_rows, 1/shards) per destination — mean ``L/S``, std
    ``sqrt(L/S)``.  The cap pads to mean + 6·sqrt + 8: statically sized
    buffers (no data-dependent shapes inside the compiled tick),
    overflow probability astronomically small, and the rare overflow
    falls back — under ``lax.cond``, all shards together — to the
    bit-identical all-gather route (the route plane's dirty-bucket
    fallback scheme).  Never exceeds ``local_rows`` (a bucket cannot
    receive more rows than a shard owns), which also makes the
    single-shard mesh exact."""
    if shards <= 1:
        return local_rows
    mean = -(-local_rows // shards)
    return min(local_rows, mean + 6 * math.isqrt(mean) + 8)


def cross_shard_traffic_bytes(
    n: int, w: int, shards: int, cap: "int | None" = None
) -> dict:
    """Modeled per-tick interconnect vs shard-local bytes for the
    shard_map'd exchange plane — the ONE copy of the cross-shard model
    (scripts/prof_exchange_roofline.py, bench.py's mesh phase, and
    tpu_measure.py's weak_scaling phase all read this), the sharded
    companion of :func:`step_traffic_bytes`.

    The plane routes rows by DESTINATION with one all_to_all per
    direction (pull + push).  Each shard contributes ``cap`` row slots
    per peer shard and direction; of those, the ``(shards-1)/shards``
    fraction addressed to OTHER shards actually crosses the interconnect
    (ICI within a slice, DCN across hosts — the self-addressed block
    stays local), plus a [shards, cap] int32 position plane per
    direction.  For a PRP permutation the expected occupancy per
    (src, dst) bucket is ``L/S`` rows, so the cap (default:
    :func:`exchange_cap`'s mean + 6·sqrt slack) bounds the
    wire bytes statically — padding slots ride the wire too, which is
    why the model charges ``cap``, not the mean.  Shard-local bytes are
    the fused megakernel's one-pass contract on the local tile
    (:func:`step_traffic_bytes` at N/S rows).  Returns the itemized
    dict; ``interconnect_total`` is per TICK across all shards.
    """
    local_rows = n // shards
    if cap is None:
        cap = exchange_cap(local_rows, shards)
    cross_frac = (shards - 1) / shards
    row_slots = shards * cap * shards  # per direction, all shards
    out = {
        "shards": shards,
        "local_rows": local_rows,
        "cap": cap,
        # two directions (pull + push): routed row payloads that cross
        # shard boundaries, padded slots included
        "interconnect_rows": int(2 * row_slots * w * 4 * cross_frac),
        # the [S, cap] int32 destination-position planes, both directions
        "interconnect_pos": int(2 * row_slots * 4 * cross_frac),
        # per-shard fused kernel pass over the local tile, all shards
        "local_fused_total": shards * step_traffic_bytes(local_rows, w),
    }
    out["interconnect_total"] = (
        out["interconnect_rows"] + out["interconnect_pos"]
    )
    return out


# ---------------------------------------------------------------------------
# Per-shard exchange telemetry (round 17): the measured twin of the
# traffic model above.  Device half: a [S, len(EXCH_COUNTERS)] uint32
# counter plane plus [S, len(EXCH_HIST_TRACKS), NBUCKETS] cap-utilization
# histograms, carried through the scanned tick exactly like the
# flight-recorder/histogram planes (write-only, OFF by default,
# trajectory-neutral — ScalableParams.exchange_metrics).  Host half:
# drain_exchange_counters turns the drained counters into
# ExchangeMetrics rows with EXACT wire-byte totals (trips x static trip
# size — the byte math stays on the host so uint32 counters never
# overflow mid-scan).
# ---------------------------------------------------------------------------

# device counter layout, one row per shard (order IS the wire format —
# the engine's inline twin and the mesh plane bump by this index table,
# and the schema gate pins ExchangeMetrics._fields against it):
# - ticks: instrumented exchange rounds accumulated
# - a2a_pull/push: rounds routed through the capped all_to_all fast path
# - fallback_pull/push: rounds that overflowed the cap and took the
#   all-gather route (pmax-agreed, so every shard logs the same trip)
# - pull_rows: rows this shard's receivers accepted under direct_ok
# - push_rows: ok-masked push rows DELIVERED to this shard
# - dest_shards_pull/push: destination-shard spread (distinct shards
#   addressed this round, summed over rounds — /ticks = mean fan-out)
EXCH_COUNTERS = (
    "ticks",
    "a2a_pull",
    "a2a_push",
    "fallback_pull",
    "fallback_push",
    "pull_rows",
    "push_rows",
    "dest_shards_pull",
    "dest_shards_push",
)

# cap-utilization histogram tracks (ops/histogram.py log2 buckets): one
# observation per (round, destination shard) — the occupancy of that
# destination's all_to_all bucket BEFORE capping.  Mask- and
# cap-independent (routing always routes all rows; masking only zeroes
# payloads), so the single-device twin reproduces the plane bitwise.
EXCH_HIST_TRACKS = ("cap_util_pull", "cap_util_push")


class ExchangeMetrics(NamedTuple):
    """One drained per-shard telemetry row (host ints, not arrays).

    The device counters (EXCH_COUNTERS order) plus the shard id and the
    derived EXACT wire-byte totals; the runlog ``mesh.exchange.drain``
    event schema is pinned to ``ExchangeMetrics._fields`` by
    scripts/check_metrics_schema.py + tests/obs/test_runlog_schema.py."""

    shard: int
    ticks: int
    a2a_pull: int
    a2a_push: int
    fallback_pull: int
    fallback_push: int
    pull_rows: int
    push_rows: int
    dest_shards_pull: int
    dest_shards_push: int
    wire_bytes_pull: int
    wire_bytes_push: int


def init_exchange_counters(shards: int) -> jax.Array:
    """Zeroed [shards, len(EXCH_COUNTERS)] uint32 device counter plane."""
    return jnp.zeros((shards, len(EXCH_COUNTERS)), jnp.uint32)


def init_exchange_hist(shards: int) -> jax.Array:
    """Zeroed [shards, len(EXCH_HIST_TRACKS), NBUCKETS] uint32
    cap-utilization histogram plane (ops/histogram.py buckets)."""
    from ringpop_tpu.ops import histogram as hg

    return jnp.zeros(
        (shards, len(EXCH_HIST_TRACKS), hg.NBUCKETS), jnp.uint32
    )


def a2a_trip_bytes(w: int, shards: int, cap: int) -> int:
    """EXACT wire bytes one shard moves per all_to_all routing trip, one
    direction: the [S, cap, w] uint32 row payload plus the [S, cap]
    int32 destination-position plane — a2a payload x cap, padding slots
    included (they ride the wire; that is why the model charges the cap).
    ``cross_shard_traffic_bytes`` charges exactly
    ``2 directions x shards x this x (S-1)/S`` per tick — the identity
    the reconciliation gate (scripts/check_traffic_model.py) checks."""
    return shards * cap * (w * 4 + 4)


def fallback_trip_bytes(local_rows: int, w: int, shards: int) -> int:
    """EXACT bytes one shard RECEIVES per all-gather fallback trip, one
    direction: the full [N, w] tiled gather (own tile included — the
    (S-1)/S cross fraction is applied by the reconciliation, same as
    for the a2a path)."""
    return shards * local_rows * w * 4


def drain_exchange_counters(
    counters,  # [S, len(EXCH_COUNTERS)] uint32 (host array)
    *,
    w: int,
    cap: "int | None",
    local_rows: int,
) -> "list[ExchangeMetrics]":
    """Drained device counters -> per-shard ExchangeMetrics rows.

    Wire bytes are computed HERE (trips x static per-trip size) so the
    device plane stays uint32-safe over long scans; ``cap=None`` (the
    inline/GSPMD twin, which never routes) prices the a2a trips at the
    default :func:`exchange_cap` — the same cap the plane would use."""
    counters = np.asarray(counters)
    shards = counters.shape[0]
    if counters.shape != (shards, len(EXCH_COUNTERS)):
        raise ValueError(
            "counters must be [S, %d], got %r"
            % (len(EXCH_COUNTERS), counters.shape)
        )
    if cap is None:
        cap = exchange_cap(local_rows, shards)
    a2a_b = a2a_trip_bytes(w, shards, cap)
    fb_b = fallback_trip_bytes(local_rows, w, shards)
    col = {name: i for i, name in enumerate(EXCH_COUNTERS)}
    rows = []
    for s in range(shards):
        c = {name: int(counters[s, i]) for name, i in col.items()}
        rows.append(
            ExchangeMetrics(
                shard=s,
                wire_bytes_pull=c["a2a_pull"] * a2a_b
                + c["fallback_pull"] * fb_b,
                wire_bytes_push=c["a2a_push"] * a2a_b
                + c["fallback_push"] * fb_b,
                **c,
            )
        )
    return rows


def measure_bandwidth(  # jaxgate: host — wall-clock probe, never traced
    heard, pulled, pushed, r_delta, *, impl: str, iters: int = 16
):
    """In-scan bandwidth probe on the caller's mask shape: one jitted
    ``lax.scan`` of ``iters`` exchange steps (``h ^ pulled`` re-dirties
    bits every step so no iteration is a converged no-op), timed warm
    with a DIFFERENT starting mask than the warm-up call (the tunneled
    chip memoizes identical (executable, inputs) executions —
    RESULTS.md round 4).  Returns ``(gbps, seconds_per_step)`` with
    bytes from :func:`step_traffic_bytes`."""
    import time

    @jax.jit
    def run(h0):
        def body(h, _):
            nh, acc, _cnt = exchange(
                h ^ pulled, pulled, pushed, r_delta, impl=impl
            )
            return nh, acc[0]

        return jax.lax.scan(body, h0, None, length=iters)

    # jaxgate: ignore[block-until-ready] x2 — this IS the measurement
    # harness (the one shared copy of the probe the bench/roofline/
    # tpu_measure artifacts call); never reached from traced code
    jax.block_until_ready(run(heard))  # jaxgate: ignore[block-until-ready]
    t0 = time.perf_counter()
    jax.block_until_ready(run(pushed))  # jaxgate: ignore[block-until-ready]
    sec_per_step = (time.perf_counter() - t0) / iters
    n, w = heard.shape
    return step_traffic_bytes(n, w) / sec_per_step / 1e9, sec_per_step


def exchange(
    heard,
    pulled,
    pushed,
    r_delta,
    *,
    impl: "str | None" = None,
    interpret: "bool | None" = None,
    vmem_budget: int = 8 * 1024 * 1024,
    want_counts: bool = True,
):
    """Fused push-pull exchange step.

    ``heard``: [N, U/32] uint32 pre-exchange reception bitmask;
    ``pulled`` / ``pushed``: [N, U/32] uint32 partner-row contributions,
    already masked by delivery and active-rumor words (bits here may
    only ADD to ``heard``); ``r_delta``: [U] uint32 rumor delta table.

    Returns ``(new_heard [N, U/32] uint32, row_delta [N] uint32,
    new_bits [N] int32)`` where ``new_heard = heard | pulled | pushed``,
    ``row_delta[i] = Σ r_delta[r] (mod 2^32)`` over row i's newly-set
    bits, and ``new_bits[i]`` their count.  ``impl``: "pallas" (gridless
    TPU kernel; interpret mode off-TPU) or "xla" (the bit-exact twin);
    None picks per backend.  ``want_counts=False`` returns ``new_bits``
    as None and drops the popcount + its [N] output from the program —
    the engine's hot path consumes only the delta.
    """
    u = r_delta.shape[0]
    assert heard.shape[1] * WORD == u, "delta table must cover the mask"
    if impl is None:
        impl = "pallas" if jax.default_backend() == "tpu" else "xla"
    if impl == "xla":
        return exchange_xla(
            heard, pulled, pushed, r_delta, want_counts=want_counts
        )
    if impl != "pallas":
        raise ValueError("unknown exchange impl %r" % (impl,))
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    return _exchange_pallas(
        heard,
        pulled,
        pushed,
        r_delta,
        interpret=interpret,
        vmem_budget=vmem_budget,
        want_counts=want_counts,
    )
