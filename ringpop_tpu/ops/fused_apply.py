"""Fused membership-update application — one row-streaming pass over
the full-fidelity engine's hottest ``[N, N]`` phase.

The full engine applies ``Member.evaluateUpdate`` at six points per tick
(ping receive, responses, three ping-req legs, suspicion expiry).  The
classic shape (``engine._apply_updates``) materializes ~a dozen dense
``[N, N]`` temporaries per call — the precedence gate, ten updated state
planes, plus ``started`` / ``stop`` / ``refuted`` masks that leave the
phase's ``lax.cond`` boundary only to be consumed by one more pass each
(the suspicion-deadline stamp, the refute diagonal read, the metric
sums).  At n >= 4k every such plane is tens of MB and the tick is
memory-bound: the boundary crossings ARE the cost.

This op fuses the whole site into one pass per ``[N_tile, N]`` tile:

- the SWIM precedence gate (:func:`overrides` — the ONE copy of the
  member.js:171-202 table; ``engine._overrides`` aliases it), refute
  detection, change-table recording, and suspicion timer starts/stops
  INCLUDING the deadline stamp (the classic path's separate
  ``where(started, deadline, susp)`` pass folds in);
- ``started`` / ``stop`` / full ``refuted`` never exist outside the
  tile: the op returns the refute DIAGONAL (``[N]`` — refutes only live
  on self cells) and a per-row applied OR (the dirty-row feed), both
  opt-in per site, plus an opt-in applied-cell count (the
  suspects/faulties metric feed);
- an optional running applied-cells union accumulated in-pass as a
  PACKED ``[N, ceil(N/32)]`` uint32 row bitmask
  (``toolkit.pack_bool_rows`` — 8x smaller than the bool plane it
  replaces), so ``changes_applied`` needs no per-site ``[N, N]`` masks
  and the accumulator crossing every phase boundary stays cheap;
- the full per-site ``applied`` mask is emitted ONLY under
  ``want_masks`` (flight recorder / histograms / fused-checksum cell
  tracking need it; the perf path does not).

Two implementations, the toolkit pattern (``ops.toolkit``):

- ``"pallas"`` — gridless row-streaming kernel, rows tiled to the VPU
  [8 x 128] geometry by ``toolkit.stream_row_tiles``, tiles beyond the
  VMEM budget mapped through an outer ``lax.scan``; interpret mode
  off-TPU keeps tests hermetic.
- ``"xla"`` — the bit-exact pure-XLA twin: the same formula
  (:func:`_formula` is shared verbatim between kernel and twin) as
  plain vector ops — the CPU production path.

Everything here is small-integer/bool arithmetic (selects, compares,
ORs, bit packs), so every impl agrees bit-for-bit with the classic
phase code — pinned by tests/ops/test_fused_apply.py and the
engine-level gate-equivalence suite (tests/models/test_fused_tick.py).
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from ringpop_tpu.ops import toolkit

# status codes (== engine / checksum_encode order): rank order IS
# override priority at equal incarnation
ALIVE, SUSPECT, FAULTY, LEAVE = 0, 1, 2, 3


def overrides(u_status, u_inc, c_status, c_inc):
    """The exact SWIM precedence table (member.js:171-202), vectorized —
    the single source (``engine._overrides`` is an alias)."""
    alive_ov = (u_status == ALIVE) & (u_inc > c_inc)
    suspect_ov = (u_status == SUSPECT) & (
        ((c_status == SUSPECT) & (u_inc > c_inc))
        | ((c_status == FAULTY) & (u_inc > c_inc))
        | ((c_status == ALIVE) & (u_inc >= c_inc))
    )
    faulty_ov = (u_status == FAULTY) & (
        ((c_status == SUSPECT) & (u_inc >= c_inc))
        | ((c_status == FAULTY) & (u_inc > c_inc))
        | ((c_status == ALIVE) & (u_inc >= c_inc))
    )
    leave_ov = (u_status == LEAVE) & (c_status != LEAVE) & (u_inc >= c_inc)
    return alive_ov | suspect_ov | faulty_ov | leave_ov


class ApplyState(NamedTuple):
    """The ten per-(observer, subject) planes an application site reads
    and writes — field order is the kernel's ref order."""

    known: jax.Array  # [N, N] bool
    status: jax.Array  # [N, N] int32
    inc: jax.Array  # [N, N] int32
    ch_active: jax.Array  # [N, N] bool
    ch_status: jax.Array  # [N, N] int32
    ch_inc: jax.Array  # [N, N] int32
    ch_source: jax.Array  # [N, N] int32
    ch_source_inc: jax.Array  # [N, N] int32
    ch_pb: jax.Array  # [N, N] int32
    susp_deadline: jax.Array  # [N, N] int32


class ApplyOut(NamedTuple):
    state: ApplyState
    union: Optional[jax.Array]  # [N, W] uint32 packed union, or None
    applied: Optional[jax.Array]  # [N, N] bool — only under want_masks
    applied_rows: Optional[jax.Array]  # [N] bool — per-row applied OR
    applied_count: Optional[jax.Array]  # [] int32 — opt-in
    refute_diag: Optional[jax.Array]  # [N] bool — opt-in


def _formula(
    st: ApplyState,
    recv_mask,
    u_status,
    u_inc,
    u_source,
    u_source_inc,
    row_ids,  # [rows, 1] int32 — absolute observer ids
    now,  # [rows, 1] int32 (or scalar) — this tick's incarnation stamp
    deadline,  # [rows, 1] int32 (or scalar) — suspicion deadline stamp
):
    """One application site's exact cell arithmetic — shared verbatim by
    the Pallas kernel (on [rows, N] VMEM tiles) and the XLA twin (on
    full [N, N] planes); bitwise-identical to engine._apply_updates +
    the caller-side deadline stamp by construction."""
    cols = jax.lax.broadcasted_iota(jnp.int32, st.status.shape, 1)
    is_self = cols == row_ids

    refute = (
        recv_mask
        & is_self
        & ((u_status == SUSPECT) | (u_status == FAULTY))
    )
    eff_status = jnp.where(refute, ALIVE, u_status)
    eff_inc = jnp.where(refute, now, u_inc)

    new_member = recv_mask & ~st.known
    gate = recv_mask & (
        refute
        | new_member
        | overrides(eff_status, eff_inc, st.status, st.inc)
    )

    status = jnp.where(gate, eff_status, st.status)
    inc = jnp.where(gate, eff_inc, st.inc)
    start_t = gate & (status == SUSPECT) & ~is_self
    stop_t = gate & (status != SUSPECT)
    out = ApplyState(
        known=st.known | new_member,
        status=status,
        inc=inc,
        ch_active=st.ch_active | gate,
        ch_status=jnp.where(gate, status, st.ch_status),
        ch_inc=jnp.where(gate, inc, st.ch_inc),
        ch_source=jnp.where(gate, u_source, st.ch_source),
        ch_source_inc=jnp.where(gate, u_source_inc, st.ch_source_inc),
        ch_pb=jnp.where(gate, 0, st.ch_pb),
        # starts and stops are disjoint (status == SUSPECT vs !=), so
        # folding the stamp in is order-free — bit-identical to the
        # classic stop-then-start sequence
        susp_deadline=jnp.where(
            start_t, deadline, jnp.where(stop_t, -1, st.susp_deadline)
        ),
    )
    return out, gate, refute & is_self


def _make_kernel(
    want_union: bool, want_masks: bool, want_count: bool, want_refute: bool
):
    def kernel(*refs):
        st = ApplyState(*(r[...] for r in refs[:10]))
        recv, us, ui, usrc, usi = (r[...] for r in refs[10:15])
        meta = refs[15][...]
        idx = 16
        union = None
        if want_union:
            union = refs[idx][...]
            idx += 1
        outs = refs[idx:]
        new_st, gate, refute = _formula(
            st,
            recv,
            us,
            ui,
            usrc,
            usi,
            meta[:, 0:1],
            meta[:, 1:2],
            meta[:, 2:3],
        )
        o = 0
        for plane in new_st:
            outs[o][...] = plane
            o += 1
        if want_union:
            outs[o][...] = union | toolkit.pack_bool_rows(gate)
            o += 1
        if want_masks:
            outs[o][...] = gate
            o += 1
        outs[o][...] = jnp.any(gate, axis=1, keepdims=True)
        o += 1
        if want_count:
            outs[o][...] = jnp.sum(
                gate.astype(jnp.int32),
                axis=1,
                keepdims=True,
                dtype=jnp.int32,
            )
            o += 1
        if want_refute:
            outs[o][...] = jnp.any(refute, axis=1, keepdims=True)

    return kernel


def apply_updates_xla(
    st: ApplyState,
    recv_mask,
    u_status,
    u_inc,
    u_source,
    u_source_inc,
    now,
    deadline,
    union=None,
    *,
    want_masks: bool = False,
    want_count: bool = False,
    want_refute: bool = True,
) -> ApplyOut:
    """The bit-exact pure-XLA twin: full-plane vector ops, one shared
    formula with the kernel."""
    n = st.status.shape[0]
    row_ids = jnp.arange(n, dtype=jnp.int32)[:, None]
    new_st, gate, refute = _formula(
        st,
        recv_mask,
        u_status,
        u_inc,
        u_source,
        u_source_inc,
        row_ids,
        jnp.asarray(now, jnp.int32),
        jnp.asarray(deadline, jnp.int32),
    )
    return ApplyOut(
        state=new_st,
        union=None if union is None else (
            union | toolkit.pack_bool_rows(gate)
        ),
        applied=gate if want_masks else None,
        applied_rows=jnp.any(gate, axis=1),
        applied_count=(
            jnp.sum(gate, dtype=jnp.int32) if want_count else None
        ),
        refute_diag=(
            jnp.any(refute, axis=1) if want_refute else None
        ),
    )


def apply_updates(
    st: ApplyState,
    recv_mask,
    u_status,
    u_inc,
    u_source,
    u_source_inc,
    now,
    deadline,
    union=None,
    *,
    impl: Optional[str] = None,
    want_masks: bool = False,
    want_count: bool = False,
    want_refute: bool = True,
    interpret: Optional[bool] = None,
    vmem_budget: int = toolkit.DEFAULT_VMEM_BUDGET,
) -> ApplyOut:
    """Fused membership-update application at one site.

    ``st``: the ten state planes; ``recv_mask`` [N, N] bool + the four
    ``u_*`` [N, N] int32 update planes (consumed only under
    ``recv_mask``); ``now`` / ``deadline``: traced int32 scalars (this
    tick's incarnation stamp and suspicion-deadline stamp); ``union``:
    optional [N, ceil(N/32)] uint32 packed running-union accumulator
    (``toolkit.pack_bool_rows`` layout; None skips the accumulate and
    returns None).  ``impl``: "pallas" (gridless streaming kernel;
    interpret off-TPU) or "xla" (the bit-exact twin); None picks per
    backend.  ``want_masks`` additionally emits the full per-site
    applied mask (the obs planes' feed); ``want_count`` /
    ``want_refute`` opt into the applied-cell count and refute-diagonal
    reductions — sites that don't consume them keep the reduction out
    of the program entirely.
    """
    if len(set(p.shape for p in st)) != 1:
        raise ValueError("ApplyState planes must share one [N, N] shape")
    if st.status.shape[0] != st.status.shape[1]:
        raise ValueError(
            "apply_updates wants square [N, N] planes, got %r"
            % (st.status.shape,)
        )
    n = st.status.shape[0]
    if union is not None and union.shape != (n, toolkit.packed_width(n)):
        raise ValueError(
            "union must be a packed [N, ceil(N/32)] uint32 bitmask, "
            "got %r" % (union.shape,)
        )
    if impl is None:
        impl = "pallas" if jax.default_backend() == "tpu" else "xla"
    if impl == "xla":
        return apply_updates_xla(
            st,
            recv_mask,
            u_status,
            u_inc,
            u_source,
            u_source_inc,
            now,
            deadline,
            union,
            want_masks=want_masks,
            want_count=want_count,
            want_refute=want_refute,
        )
    if impl != "pallas":
        raise ValueError("unknown apply_updates impl %r" % (impl,))
    want_union = union is not None
    meta = jnp.stack(
        [
            jnp.arange(n, dtype=jnp.int32),
            jnp.full((n,), now, jnp.int32),
            jnp.full((n,), deadline, jnp.int32),
        ],
        axis=1,
    )
    inputs = list(st) + [
        recv_mask,
        u_status,
        u_inc,
        u_source,
        u_source_inc,
        meta,
    ]
    # explicit plane flags: meta and the packed union are narrow per-row
    # inputs even when their widths collide with n at tiny sizes
    in_planes = [True] * 15 + [False]
    ncp_w = (-(-n // toolkit.LANE) * toolkit.LANE) // 32
    if want_union:
        # align the packed accumulator to the column-padded tile width
        # (zero words — exact; cropped back below)
        w = toolkit.packed_width(n)
        inputs.append(
            jnp.pad(union, ((0, 0), (0, ncp_w - w))) if ncp_w > w
            else union
        )
        in_planes.append(False)
    out_widths: list = ["plane"] * 10
    out_dtypes: list = [p.dtype for p in st]
    if want_union:
        # the kernel packs over the column-padded tile; padded columns
        # carry gate=0, so cropping back to ceil(n/32) words is exact
        out_widths.append(ncp_w)
        out_dtypes.append(jnp.uint32)
    if want_masks:
        out_widths.append("plane")
        out_dtypes.append(jnp.bool_)
    out_widths.append(1)
    out_dtypes.append(jnp.bool_)
    if want_count:
        out_widths.append(1)
        out_dtypes.append(jnp.int32)
    if want_refute:
        out_widths.append(1)
        out_dtypes.append(jnp.bool_)
    outs = toolkit.stream_row_tiles(
        _make_kernel(want_union, want_masks, want_count, want_refute),
        inputs,
        out_widths,
        out_dtypes,
        n_cols=n,
        in_planes=in_planes,
        vmem_budget=vmem_budget,
        interpret=interpret,
    )
    new_st = ApplyState(*outs[:10])
    idx = 10
    new_union = None
    if want_union:
        new_union = outs[idx][:, : toolkit.packed_width(n)]
        idx += 1
    applied = None
    if want_masks:
        applied = outs[idx]
        idx += 1
    rows = outs[idx][:, 0]
    idx += 1
    cnt = None
    if want_count:
        cnt = jnp.sum(outs[idx][:, 0], dtype=jnp.int32)
        idx += 1
    refute_diag = outs[idx][:, 0] if want_refute else None
    return ApplyOut(
        state=new_st,
        union=new_union,
        applied=applied,
        applied_rows=rows,
        applied_count=cnt,
        refute_diag=refute_diag,
    )
