"""Fused dissemination budget pass — sender piggyback selection,
receiver bumps, and retirement accounting in one ``[N_tile, N]`` sweep.

The full engine touches the change table's budget planes at four points
per tick (sender select in phase 3, receiver bump in phase 5.5, and the
two ping-req budget bumps), each the same arithmetic: add this round's
bump count to ``ch_pb``, retire cells past the ``15*ceil(log10(n+1))``
bound (dissemination.js:41), emit the surviving message-content mask,
and count the drops.  The classic shape materializes the bump plane,
the post-bump ``ch_pb``, the ``over`` mask and the content mask as
separate ``[N, N]`` temporaries per site — this op fuses each site into
one pass per tile and returns the drop count as a per-row reduction
(never a dense mask crossing the phase's ``lax.cond`` boundary).

One formula covers all four sites (bitwise-pinned against the classic
phase code by tests/ops/test_fused_piggyback.py and the engine
gate-equivalence suite):

- sender select: ``nbump = valid_send`` (0/1), no hits — ``content``
  is the ``sendable`` mask;
- receiver bump: ``nbump = nrecv``, ``hits`` = the origin-filter counts
  (dissemination.js:147-160; computed OUTSIDE the op — the per-cell
  gathers by ``ch_source`` stay in XLA, the toolkit convention that
  dynamic gathers never live inside a row-tiled kernel) — ``content``
  is the ``respondable`` mask;
- ping-req leg-1: ``nbump = n_slots`` (several bumps per selected
  intermediary, the bump-even-if-unreachable quirk), content unused;
- ping-req leg-3: ``nbump = prrecv`` with its own hits plane.

Implementations (the ``ops.toolkit`` pattern): ``"pallas"`` — gridless
row-streaming kernel via ``toolkit.stream_row_tiles`` — and ``"xla"``,
the bit-exact twin sharing :func:`_formula` verbatim.  All small-int
arithmetic: every impl is bitwise-identical.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from ringpop_tpu.ops import toolkit


class BudgetOut(NamedTuple):
    ch_pb: jax.Array  # [N, N] int32 — post-bump piggyback counts
    ch_active: jax.Array  # [N, N] bool — with over-budget cells retired
    content: Optional[jax.Array]  # [N, N] bool — surviving message mask
    drops: jax.Array  # [] int32 — cells retired at this site


def _formula(active, pb, hits, nbump_col, max_pb_col):
    """One budget site's exact cell arithmetic (shared kernel/twin).

    ``nbump_col`` / ``max_pb_col``: [rows, 1] int32 columns; ``hits``:
    [rows, N] int32 origin-filter counts or None (sites without an
    origin filter — the None keeps the zeros plane out of the program
    entirely).  Matches the classic phase code cell-for-cell: rows
    with ``nbump == 0`` add 0 either way, so gating the add on
    ``nbump > 0`` is bit-neutral (phase 3 gates, ping-req leg 1 does
    not)."""
    has = nbump_col > 0
    eff = jnp.where(
        active & has,
        nbump_col - hits if hits is not None else nbump_col,
        0,
    )
    pb2 = pb + eff
    over = active & (pb2 > max_pb_col)
    return (
        pb2,
        active & ~over,
        active & has & ~over,  # content: bumped cells that survived
        over,
    )


def _make_kernel(want_hits: bool, want_content: bool):
    def kernel(*refs):
        active = refs[0][...]
        pb = refs[1][...]
        meta = refs[2][...]
        idx = 3
        if want_hits:
            hits = refs[idx][...]
            idx += 1
        else:
            hits = None
        outs = refs[idx:]
        pb2, active2, content, over = _formula(
            active, pb, hits, meta[:, 0:1], meta[:, 1:2]
        )
        outs[0][...] = pb2
        outs[1][...] = active2
        o = 2
        if want_content:
            outs[o][...] = content
            o += 1
        outs[o][...] = jnp.sum(
            over.astype(jnp.int32), axis=1, keepdims=True, dtype=jnp.int32
        )

    return kernel


def pb_budget_xla(
    ch_active,
    ch_pb,
    nbump,
    max_pb,
    hits=None,
    *,
    want_content: bool = True,
) -> BudgetOut:
    """The bit-exact pure-XLA twin: full-plane vector ops, one shared
    formula with the kernel."""
    pb2, active2, content, over = _formula(
        ch_active, ch_pb, hits, nbump[:, None], max_pb[:, None]
    )
    return BudgetOut(
        ch_pb=pb2,
        ch_active=active2,
        content=content if want_content else None,
        drops=jnp.sum(over, dtype=jnp.int32),
    )


def pb_budget(
    ch_active,
    ch_pb,
    nbump,
    max_pb,
    hits=None,
    *,
    impl: Optional[str] = None,
    want_content: bool = True,
    interpret: Optional[bool] = None,
    vmem_budget: int = toolkit.DEFAULT_VMEM_BUDGET,
) -> BudgetOut:
    """Fused piggyback budget pass at one dissemination site.

    ``ch_active`` [N, N] bool / ``ch_pb`` [N, N] int32: the change
    table's budget planes; ``nbump`` [N] int32: this site's per-row
    bump count; ``max_pb`` [N] int32: the per-row retirement bound;
    ``hits``: optional [N, N] int32 origin-filter counts subtracted
    from bumped cells.  ``impl``: "pallas" (gridless streaming kernel;
    interpret off-TPU) or "xla" (the bit-exact twin); None picks per
    backend.  ``want_content=False`` drops the [N, N] content-mask
    output from the program (the ping-req leg-1 site consumes only the
    budget planes)."""
    if ch_active.shape != ch_pb.shape or ch_active.ndim != 2:
        raise ValueError(
            "pb_budget wants matching [N, N] planes, got %r / %r"
            % (ch_active.shape, ch_pb.shape)
        )
    if nbump.shape != (ch_pb.shape[0],) or max_pb.shape != nbump.shape:
        raise ValueError(
            "nbump/max_pb must be [N] vectors, got %r / %r"
            % (nbump.shape, max_pb.shape)
        )
    if impl is None:
        impl = "pallas" if jax.default_backend() == "tpu" else "xla"
    if impl == "xla":
        return pb_budget_xla(
            ch_active,
            ch_pb,
            nbump,
            max_pb,
            hits,
            want_content=want_content,
        )
    if impl != "pallas":
        raise ValueError("unknown pb_budget impl %r" % (impl,))
    n = ch_pb.shape[0]
    meta = jnp.stack(
        [nbump.astype(jnp.int32), max_pb.astype(jnp.int32)], axis=1
    )
    inputs = [ch_active, ch_pb, meta]
    # explicit plane flags: meta is a narrow per-row input even when
    # its width collides with n at tiny sizes
    in_planes = [True, True, False]
    want_hits = hits is not None
    if want_hits:
        inputs.append(hits)
        in_planes.append(True)
    out_widths = ["plane", "plane"]
    out_dtypes = [jnp.int32, jnp.bool_]
    if want_content:
        out_widths.append("plane")
        out_dtypes.append(jnp.bool_)
    out_widths.append(1)
    out_dtypes.append(jnp.int32)
    outs = toolkit.stream_row_tiles(
        _make_kernel(want_hits, want_content),
        inputs,
        out_widths,
        out_dtypes,
        n_cols=n,
        in_planes=in_planes,
        vmem_budget=vmem_budget,
        interpret=interpret,
    )
    content = outs[2] if want_content else None
    drops = jnp.sum(outs[-1][:, 0], dtype=jnp.int32)
    return BudgetOut(
        ch_pb=outs[0], ch_active=outs[1], content=content, drops=drops
    )
