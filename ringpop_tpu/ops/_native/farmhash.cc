// Native FarmHash32 oracle for ringpop_tpu.
//
// Implements farmhashmk::Hash32 — the variant behind the npm `farmhash`
// addon's hash32() that the reference uses for every ring/membership hash
// (/root/reference/lib/ring/index.js:21, lib/membership/index.js:24).  Both
// farmhash::Hash32 (portable, non-SSE build) and farmhash::Fingerprint32
// dispatch here, so this is the bit pattern the Node reference produces.
//
// Exposed as a plain C ABI consumed from Python via ctypes (no pybind11 in
// the image).  Batch entry points operate on a padded row-major byte matrix
// so large membership/ring checksum workloads stay in native code.

#include <cstddef>
#include <cstdint>
#include <cstring>

namespace {

constexpr uint32_t c1 = 0xcc9e2d51;
constexpr uint32_t c2 = 0x1b873593;

inline uint32_t Fetch32(const uint8_t* p) {
  uint32_t v;
  std::memcpy(&v, p, sizeof(v));  // little-endian hosts only (x86/ARM LE)
  return v;
}

inline uint32_t Rotate32(uint32_t val, int shift) {
  return shift == 0 ? val : ((val >> shift) | (val << (32 - shift)));
}

inline uint32_t fmix(uint32_t h) {
  h ^= h >> 16;
  h *= 0x85ebca6b;
  h ^= h >> 13;
  h *= 0xc2b2ae35;
  h ^= h >> 16;
  return h;
}

inline uint32_t Mur(uint32_t a, uint32_t h) {
  a *= c1;
  a = Rotate32(a, 17);
  a *= c2;
  h ^= a;
  h = Rotate32(h, 19);
  return h * 5 + 0xe6546b64;
}

uint32_t Hash32Len0to4(const uint8_t* s, size_t len, uint32_t seed = 0) {
  uint32_t b = seed;
  uint32_t c = 9;
  for (size_t i = 0; i < len; i++) {
    signed char v = static_cast<signed char>(s[i]);
    b = b * c1 + static_cast<uint32_t>(v);
    c ^= b;
  }
  return fmix(Mur(b, Mur(static_cast<uint32_t>(len), c)));
}

uint32_t Hash32Len5to12(const uint8_t* s, size_t len, uint32_t seed = 0) {
  uint32_t a = static_cast<uint32_t>(len), b = a * 5, c = 9, d = b + seed;
  a += Fetch32(s);
  b += Fetch32(s + len - 4);
  c += Fetch32(s + ((len >> 1) & 4));
  return fmix(seed ^ Mur(c, Mur(b, Mur(a, d))));
}

uint32_t Hash32Len13to24(const uint8_t* s, size_t len, uint32_t seed = 0) {
  uint32_t a = Fetch32(s - 4 + (len >> 1));
  uint32_t b = Fetch32(s + 4);
  uint32_t c = Fetch32(s + len - 8);
  uint32_t d = Fetch32(s + (len >> 1));
  uint32_t e = Fetch32(s);
  uint32_t f = Fetch32(s + len - 4);
  uint32_t h = d * c1 + static_cast<uint32_t>(len) + seed;
  a = Rotate32(a, 12) + f;
  h = Mur(c, h) + a;
  a = Rotate32(a, 3) + c;
  h = Mur(e, h) + a;
  a = Rotate32(a + f, 12) + d;
  h = Mur(b ^ seed, h) + a;
  return fmix(h);
}

uint32_t Hash32(const uint8_t* s, size_t len) {
  if (len <= 24) {
    return len <= 12
               ? (len <= 4 ? Hash32Len0to4(s, len) : Hash32Len5to12(s, len))
               : Hash32Len13to24(s, len);
  }

  // len > 24
  uint32_t h = static_cast<uint32_t>(len), g = c1 * h, f = g;
  uint32_t a0 = Rotate32(Fetch32(s + len - 4) * c1, 17) * c2;
  uint32_t a1 = Rotate32(Fetch32(s + len - 8) * c1, 17) * c2;
  uint32_t a2 = Rotate32(Fetch32(s + len - 16) * c1, 17) * c2;
  uint32_t a3 = Rotate32(Fetch32(s + len - 12) * c1, 17) * c2;
  uint32_t a4 = Rotate32(Fetch32(s + len - 20) * c1, 17) * c2;
  h ^= a0;
  h = Rotate32(h, 19);
  h = h * 5 + 0xe6546b64;
  h ^= a2;
  h = Rotate32(h, 19);
  h = h * 5 + 0xe6546b64;
  g ^= a1;
  g = Rotate32(g, 19);
  g = g * 5 + 0xe6546b64;
  g ^= a3;
  g = Rotate32(g, 19);
  g = g * 5 + 0xe6546b64;
  f += a4;
  f = Rotate32(f, 19) + 113;
  size_t iters = (len - 1) / 20;
  do {
    uint32_t a = Fetch32(s);
    uint32_t b = Fetch32(s + 4);
    uint32_t c = Fetch32(s + 8);
    uint32_t d = Fetch32(s + 12);
    uint32_t e = Fetch32(s + 16);
    h += a;
    g += b;
    f += c;
    h = Mur(d, h) + e;
    g = Mur(c, g) + a;
    f = Mur(b + e * c1, f) + d;
    f += g;
    g += f;
    s += 20;
  } while (--iters != 0);
  g = Rotate32(g, 11) * c1;
  g = Rotate32(g, 17) * c1;
  f = Rotate32(f, 11) * c1;
  f = Rotate32(f, 17) * c1;
  h = Rotate32(h + g, 19);
  h = h * 5 + 0xe6546b64;
  h = Rotate32(h, 17) * c1;
  h = Rotate32(h + f, 19);
  h = h * 5 + 0xe6546b64;
  h = Rotate32(h, 17) * c1;
  return h;
}

}  // namespace

extern "C" {

uint32_t rp_farmhash32(const uint8_t* data, uint64_t len) {
  return Hash32(data, static_cast<size_t>(len));
}

// Hash each row of a padded row-major [n, stride] byte matrix.
void rp_farmhash32_batch(const uint8_t* data, uint64_t stride,
                         const uint64_t* lens, uint64_t n, uint32_t* out) {
  for (uint64_t i = 0; i < n; i++) {
    out[i] = Hash32(data + i * stride, static_cast<size_t>(lens[i]));
  }
}

// Hash `reps` replica-point strings "<name><i>" for i in [0, reps) — the
// ring's replica expansion (lib/ring/index.js:54-57) without Python overhead.
void rp_replica_hashes(const uint8_t* name, uint64_t name_len, uint64_t reps,
                       uint32_t* out) {
  uint8_t buf[512];
  if (name_len > 480) return;  // caller guards; addresses are short
  std::memcpy(buf, name, name_len);
  for (uint64_t i = 0; i < reps; i++) {
    char digits[24];
    int nd = 0;
    uint64_t v = i;
    do {
      digits[nd++] = static_cast<char>('0' + (v % 10));
      v /= 10;
    } while (v != 0);
    for (int d = 0; d < nd; d++) {
      buf[name_len + d] = static_cast<uint8_t>(digits[nd - 1 - d]);
    }
    out[i] = Hash32(buf, name_len + nd);
  }
}

}  // extern "C"
