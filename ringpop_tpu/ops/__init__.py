"""Hash and kernel ops: FarmHash32 (host oracle, numpy batch, in-jit JAX,
Pallas TPU), checksum-string encoding, and ring-table kernels."""
