"""Hash and kernel ops: FarmHash32 (host oracle, numpy batch, in-jit JAX,
Pallas TPU), checksum-string encoding, record-mix hashing, and the fused
checksum pipeline (record-granularity encode + gridless streaming
assemble+hash kernel — :mod:`ringpop_tpu.ops.fused_checksum`)."""
