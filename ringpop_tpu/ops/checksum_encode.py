"""In-jit checksum-string encoding over a static address universe.

The reference's membership checksum is ``hash32`` of
``addr+status+incarnation`` joined with ';' over members sorted by address
(/root/reference/lib/membership/index.js:100-123), and the ring checksum is
``hash32`` of sorted server names joined with ';'
(/root/reference/lib/ring/index.js:96-105).  Reproducing those bit-for-bit on
device requires building the exact byte strings inside the jit graph.

TPU-first design:

- The simulator's node *universe* (every address that can ever appear) is
  static per run.  Addresses are sorted lexicographically **once on host**
  (:class:`Universe`), so the device never sorts strings — a member subset in
  address order is just array order under a presence mask.
- Per row (= per observing node), segment lengths are computed from the
  member's status code and incarnation digit count, offsets are an exclusive
  cumsum, and bytes are scattered into a padded row buffer with out-of-range
  positions dropped.  Everything is masked arithmetic — no dynamic shapes.
- Rows are processed in chunks via ``lax.map`` to bound the [chunk, N, S]
  scatter-index intermediates, keeping peak memory ~chunk/B of the naive
  layout.  The chunked axis composes with mesh sharding of the row axis.

Status codes are fixed: 0=alive 1=suspect 2=faulty 3=leave (the wire strings
the reference embeds in checksum strings, member.js:204-209).
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

STATUS_ALIVE = 0
STATUS_SUSPECT = 1
STATUS_FAULTY = 2
STATUS_LEAVE = 3

STATUS_STRINGS = ("alive", "suspect", "faulty", "leave")
_STATUS_W = 7  # len("suspect")

STATUS_BYTES = np.zeros((4, _STATUS_W), dtype=np.uint8)
STATUS_LEN = np.zeros(4, dtype=np.int32)
for _i, _s in enumerate(STATUS_STRINGS):
    STATUS_BYTES[_i, : len(_s)] = np.frombuffer(_s.encode(), dtype=np.uint8)
    STATUS_LEN[_i] = len(_s)

MAX_DIGITS = 19  # int64 decimal digits
_POW10 = np.array([10**k for k in range(MAX_DIGITS)], dtype=np.int64)


@dataclasses.dataclass(frozen=True, eq=False)
class Universe:
    """Static, lexicographically sorted address universe of a simulation.

    ``addresses[i]`` is node i's identity; all device arrays indexed by node
    use this order, which equals checksum-string member order (the JS sort at
    membership/index.js:101-110 over ASCII host:port strings is bytewise).

    Equality/hash key on ``addresses`` alone (the byte matrix and lengths
    are derived from it), so universes can key jit-executable caches: two
    clusters over the same address list share one compiled program.
    """

    addresses: tuple
    addr_bytes: np.ndarray  # [N, A] uint8, zero-padded
    addr_len: np.ndarray  # [N] int32

    def __eq__(self, other):
        return (
            isinstance(other, Universe) and self.addresses == other.addresses
        )

    def __hash__(self):
        return hash(self.addresses)

    @staticmethod
    def from_addresses(addresses: Sequence[str]) -> "Universe":
        ordered = sorted(addresses)
        if len(set(ordered)) != len(ordered):
            raise ValueError("duplicate addresses in universe")
        encoded = [a.encode("utf-8") for a in ordered]
        width = max((len(e) for e in encoded), default=1)
        mat = np.zeros((len(encoded), width), dtype=np.uint8)
        lens = np.zeros(len(encoded), dtype=np.int32)
        for i, e in enumerate(encoded):
            mat[i, : len(e)] = np.frombuffer(e, dtype=np.uint8)
            lens[i] = len(e)
        return Universe(tuple(ordered), mat, lens)

    @property
    def n(self) -> int:
        return len(self.addresses)

    @property
    def addr_width(self) -> int:
        return self.addr_bytes.shape[1]

    def index_of(self, address: str) -> int:
        return self.addresses.index(address)

    def member_row_width(self, max_digits: int = MAX_DIGITS) -> int:
        """Static buffer width for a full-membership checksum string."""
        return int(self.addr_len.sum()) + self.n * (_STATUS_W + max_digits + 1) + 4

    def ring_row_width(self) -> int:
        return int(self.addr_len.sum()) + self.n + 4


def _ndigits(x: jax.Array) -> jax.Array:
    """Decimal digit count of non-negative int64 (0 -> 1 digit)."""
    x = x.astype(jnp.int64)
    count = jnp.ones(x.shape, jnp.int32)
    for k in range(1, MAX_DIGITS):
        count = count + (x >= _POW10[k]).astype(jnp.int32)
    return count


def _digit_bytes(x: jax.Array, dlen: jax.Array, max_digits: int) -> jax.Array:
    """[..., max_digits] ASCII digits of x, most significant first, left-
    aligned within dlen (positions >= dlen are garbage, masked by caller)."""
    x = x.astype(jnp.int64)
    k = jnp.arange(max_digits, dtype=jnp.int32)
    exp = jnp.clip(dlen[..., None] - 1 - k, 0, MAX_DIGITS - 1)
    pow10 = jnp.asarray(_POW10)[exp]
    digit = (x[..., None] // pow10) % 10
    return (digit + ord("0")).astype(jnp.uint8)


def _scatter_rows(
    width: int,
    positions: jax.Array,  # [N, S] int32 — target position per byte, >= width drops
    values: jax.Array,  # [N, S] uint8
    unique: bool = False,
) -> jax.Array:
    buf = jnp.zeros((width,), jnp.uint8)
    pos = positions.reshape(-1)
    if unique:
        # live positions are disjoint by construction (member segments
        # never overlap); promising that to XLA lets the TPU scatter skip
        # collision serialization.  Dropped bytes must stay unique too:
        # route each to its own OOB slot instead of the shared sentinel.
        flat = jnp.arange(pos.shape[0], dtype=pos.dtype)
        pos = jnp.where(pos >= width, width + flat, pos)
        return buf.at[pos].set(
            values.reshape(-1), mode="drop", unique_indices=True
        )
    return buf.at[pos].set(values.reshape(-1), mode="drop")


def membership_rows(
    universe: Universe,
    present: jax.Array,  # [B, N] bool
    status: jax.Array,  # [B, N] int32/int8 codes
    incarnation: jax.Array,  # [B, N] int64
    max_digits: int = MAX_DIGITS,
    width: Optional[int] = None,
    chunk: int = 64,
    impl: str = "scatter_unique",
):
    """Build per-row membership checksum strings; returns (buf [B,W] uint8,
    lens [B] int32), ready for ops.jax_farmhash.hash32_rows.

    ``max_digits`` defaults to 19 (any int64 encodes exactly).  Lowering it
    shrinks buffers but is only sound if the caller guarantees every
    incarnation number has at most that many decimal digits — a wider value
    would silently corrupt the string (offsets account for the true digit
    count while bytes past ``max_digits`` are never written).

    ``impl``: 'scatter_unique' (default) scatters each member segment's
    bytes to its cumsum offset AND promises XLA the indices are disjoint
    (true by construction — member segments never overlap; drops get
    private OOB slots), so the lowering skips collision handling.
    Measured in-graph (one lax.scan of salted repetitions, forced out —
    host-loop timings lie on the tunnel backend) at 1024 all-dirty rows:
    TPU 528 ms vs plain scatter's 791, CPU 718 ms vs 881; byte-exactness
    of the unique promise is validated ON the TPU lowering by the sweep
    (encode_unique_bitexact_on_device).  'scatter' is the same without
    the promise.  'gather' derives every output byte's source via
    searchsorted over the offset cumsum — no scatter anywhere.
    'gather2' replaces the per-byte binary search with a start-indicator
    scatter + cumsum (O(1) member-of-byte), keeping only [W]-sized table
    gathers.  All are A/B'd on hardware by benchmarks/tpu_measure.py."""
    if impl in ("gather", "gather2"):
        return _membership_rows_gather(
            universe,
            present,
            status,
            incarnation,
            max_digits,
            width,
            chunk,
            member_of=("cumsum" if impl == "gather2" else "searchsorted"),
        )
    unique = impl == "scatter_unique"
    width = width or universe.member_row_width(max_digits)
    A = universe.addr_width
    addr_bytes = jnp.asarray(universe.addr_bytes)
    addr_len = jnp.asarray(universe.addr_len)
    status_bytes = jnp.asarray(STATUS_BYTES)
    status_len = jnp.asarray(STATUS_LEN)

    def one_row(args):
        pres, stat, inc = args
        stat = stat.astype(jnp.int32)
        pres_i = pres.astype(jnp.int32)
        slen = status_len[stat]
        dlen = _ndigits(inc)
        seg_len = (addr_len + slen + dlen + 1) * pres_i
        offset = jnp.cumsum(seg_len, dtype=jnp.int32) - seg_len  # exclusive cumsum
        total = jnp.maximum(jnp.sum(seg_len, dtype=jnp.int32) - jnp.int32(1), 0) * (
            pres_i.sum() > 0
        ).astype(jnp.int32)

        drop = jnp.int32(width)

        # address part: [N, A]
        ka = jnp.arange(A, dtype=jnp.int32)
        pos_a = offset[:, None] + ka[None, :]
        ok_a = pres[:, None] & (ka[None, :] < addr_len[:, None])
        pos_a = jnp.where(ok_a, pos_a, drop)

        # status part: [N, 7]
        ks = jnp.arange(_STATUS_W, dtype=jnp.int32)
        pos_s = offset[:, None] + addr_len[:, None] + ks[None, :]
        ok_s = pres[:, None] & (ks[None, :] < slen[:, None])
        pos_s = jnp.where(ok_s, pos_s, drop)
        val_s = status_bytes[stat]

        # digits part: [N, D]
        kd = jnp.arange(max_digits, dtype=jnp.int32)
        pos_d = offset[:, None] + addr_len[:, None] + slen[:, None] + kd[None, :]
        ok_d = pres[:, None] & (kd[None, :] < dlen[:, None])
        pos_d = jnp.where(ok_d, pos_d, drop)
        val_d = _digit_bytes(inc, dlen, max_digits)

        # separator: [N, 1]
        pos_sep = (offset + addr_len + slen + dlen)[:, None]
        pos_sep = jnp.where(pres[:, None], pos_sep, drop)
        val_sep = jnp.full((universe.n, 1), ord(";"), jnp.uint8)

        positions = jnp.concatenate([pos_a, pos_s, pos_d, pos_sep], axis=1)
        values = jnp.concatenate(
            [jnp.broadcast_to(addr_bytes, (universe.n, A)), val_s, val_d, val_sep],
            axis=1,
        )
        return _scatter_rows(width, positions, values, unique=unique), total

    return _chunked_rows(
        one_row, present, status, incarnation, chunk, width, universe.n
    )


def _chunked_rows(one_row, present, status, incarnation, chunk, width, n):
    """vmap ``one_row`` over rows, in ``chunk``-row ``lax.map`` slabs when
    the batch is large — bounds the [chunk, N, S] intermediates (shared by
    both encoder forms)."""
    B = present.shape[0]
    if B <= chunk:
        return jax.vmap(lambda p, s, i: one_row((p, s, i)))(
            present, status, incarnation
        )
    pad = (-B) % chunk
    p = jnp.pad(present, ((0, pad), (0, 0)))
    s = jnp.pad(status, ((0, pad), (0, 0)))
    i = jnp.pad(incarnation, ((0, pad), (0, 0)))
    bufs, lens = jax.lax.map(
        lambda args: jax.vmap(lambda pp, ss, ii: one_row((pp, ss, ii)))(*args),
        (
            p.reshape(-1, chunk, n),
            s.reshape(-1, chunk, n),
            i.reshape(-1, chunk, n),
        ),
    )
    return bufs.reshape(-1, width)[:B], lens.reshape(-1)[:B]


def _membership_rows_gather(
    universe: Universe,
    present: jax.Array,  # [B, N] bool
    status: jax.Array,  # [B, N] int codes
    incarnation: jax.Array,  # [B, N] int64
    max_digits: int = MAX_DIGITS,
    width: Optional[int] = None,
    chunk: int = 64,
    member_of: str = "searchsorted",
):
    """Gather-form encoder: output byte b of a row belongs to the member
    whose [offset, offset+seg_len) interval contains b, then resolves to
    an address byte, a status byte, an ASCII digit of the incarnation, or
    ';' from its position within the segment.  No byte-level scatter —
    the scatter formulation serializes on both CPU and TPU, and at 1k
    nodes the encode (not the hash) dominated the parity-mode recompute.

    ``member_of`` picks how byte -> member is computed:
    - 'searchsorted': binary search over the segment-end cumsum ([W]
      searches of a [N] table per row);
    - 'cumsum': scatter 1 at each present member's start offset ([N]
      tiny scatter), prefix-sum over the row width, and map the rank
      back through the present-members list — O(1) per byte, no search,
      the TPU-friendly form."""
    width = width or universe.member_row_width(max_digits)
    A = universe.addr_width
    n = universe.n
    addr_bytes = jnp.asarray(universe.addr_bytes)  # [N, A]
    addr_len = jnp.asarray(universe.addr_len)  # [N]
    status_bytes = jnp.asarray(STATUS_BYTES)
    status_len = jnp.asarray(STATUS_LEN)
    b_pos = jnp.arange(width, dtype=jnp.int32)  # [W]

    def one_row(args):
        pres, stat, inc = args
        stat = stat.astype(jnp.int32)
        pres_i = pres.astype(jnp.int32)
        slen = status_len[stat]
        dlen = _ndigits(inc)
        seg_len = (addr_len + slen + dlen + 1) * pres_i
        ends = jnp.cumsum(seg_len, dtype=jnp.int32)  # inclusive: segment m covers
        offset = ends - seg_len  # [offset[m], ends[m])
        total = jnp.maximum(ends[-1] - jnp.int32(1), 0) * (
            pres_i.sum() > 0
        ).astype(jnp.int32)

        if member_of == "cumsum":
            # rank r of byte b = (# present members starting at or
            # before b) - 1; rank -> member via the compacted present
            # list.  Identical to the binary search: the winner is the
            # last present member with offset <= b (empty segments never
            # place a start indicator).
            starts = (
                jnp.zeros(width + 1, jnp.int32)
                .at[jnp.clip(offset, 0, width)]
                .add(pres_i, mode="drop")
            )
            rank_of_byte = jnp.cumsum(starts[:width], dtype=jnp.int32) - 1  # [W]
            prank = jnp.cumsum(pres_i, dtype=jnp.int32) - 1  # present-member rank
            rank_to_m = (
                jnp.zeros(n, jnp.int32)
                .at[jnp.where(pres, prank, n)]
                .set(jnp.arange(n, dtype=jnp.int32), mode="drop")
            )
            mc = rank_to_m[jnp.clip(rank_of_byte, 0, n - 1)]
        else:
            # member owning each byte: first m with ends[m] > b (empty
            # segments have ends[m] == offset of the next, never win)
            m = jnp.searchsorted(ends, b_pos, side="right").astype(
                jnp.int32
            )
            mc = jnp.clip(m, 0, n - 1)
        local = b_pos - offset[mc]
        al = addr_len[mc]
        sl = slen[mc]
        dl = dlen[mc]
        st = stat[mc]

        # segment-relative positions
        s_off = local - al  # status byte index
        d_off = s_off - sl  # digit index
        is_addr = local < al
        is_status = (s_off >= 0) & (s_off < sl)
        is_digit = (d_off >= 0) & (d_off < dl)

        byte_addr = addr_bytes[mc, jnp.clip(local, 0, A - 1)]
        byte_status = status_bytes[st, jnp.clip(s_off, 0, _STATUS_W - 1)]
        # per-member digit table ([N, D] divisions) instead of a division
        # per output byte ([W] of them)
        val_d = _digit_bytes(inc, dlen, max_digits)
        byte_digit = val_d[mc, jnp.clip(d_off, 0, max_digits - 1)]

        out = jnp.where(
            is_addr,
            byte_addr,
            jnp.where(
                is_status,
                byte_status,
                jnp.where(is_digit, byte_digit, jnp.uint8(ord(";"))),
            ),
        )
        # zero past the final separator-free length
        out = jnp.where(b_pos < total, out, jnp.uint8(0))
        return out, total

    return _chunked_rows(
        one_row, present, status, incarnation, chunk, width, n
    )


def ring_rows(
    universe: Universe,
    in_ring: jax.Array,  # [B, N] bool — servers currently in each row's ring
    width: Optional[int] = None,
):
    """Build per-row ring checksum strings (sorted names joined ';')."""
    width = width or universe.ring_row_width()
    A = universe.addr_width
    addr_bytes = jnp.asarray(universe.addr_bytes)
    addr_len = jnp.asarray(universe.addr_len)

    def one_row(pres):
        pres_i = pres.astype(jnp.int32)
        seg_len = (addr_len + 1) * pres_i
        offset = jnp.cumsum(seg_len, dtype=jnp.int32) - seg_len
        total = jnp.maximum(jnp.sum(seg_len, dtype=jnp.int32) - jnp.int32(1), 0) * (
            pres_i.sum() > 0
        ).astype(jnp.int32)
        drop = jnp.int32(width)

        ka = jnp.arange(A, dtype=jnp.int32)
        pos_a = offset[:, None] + ka[None, :]
        ok_a = pres[:, None] & (ka[None, :] < addr_len[:, None])
        pos_a = jnp.where(ok_a, pos_a, drop)

        pos_sep = (offset + addr_len)[:, None]
        pos_sep = jnp.where(pres[:, None], pos_sep, drop)
        val_sep = jnp.full((universe.n, 1), ord(";"), jnp.uint8)

        positions = jnp.concatenate([pos_a, pos_sep], axis=1)
        values = jnp.concatenate(
            [jnp.broadcast_to(addr_bytes, (universe.n, A)), val_sep], axis=1
        )
        return _scatter_rows(width, positions, values), total

    return jax.vmap(one_row)(in_ring)
