"""The Ringpop facade — the framework's public API (index.js rebuilt).

Composes every component (membership, ring, gossip, dissemination,
suspicion, request proxy, rollup, tracers, server endpoints) and wires the
event plumbing between them, mirroring the reference's constructor
(index.js:70-175) and the three event-wiring modules
(lib/on_membership_event.js, on_ring_event.js, on_ringpop_event.js).

Intended surface (index.js:27-30): ``bootstrap()``, ``lookup()``,
``whoami()`` — plus ``lookup_n``, ``handle_or_proxy(_all)``, ``proxy_req``,
``get_stats``, ``register_stats_hook``, ``setup_channel``, ``destroy`` and
the EventEmitter events (``ready``, ``membershipChanged``, ``ringChanged``,
``request``, ``lookup``).
"""

from __future__ import annotations

import json
import os
import random
import re
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Union

from ringpop_tpu.gossip.dissemination import Dissemination
from ringpop_tpu.gossip.gossip import Gossip
from ringpop_tpu.gossip.join_sender import JoinError, join_cluster
from ringpop_tpu.gossip.suspicion import Suspicion
from ringpop_tpu.models.membership.host import (
    Membership,
    MembershipIterator,
    Status,
)
from ringpop_tpu.models.ring.host import HashRing
from ringpop_tpu.net.channel import Channel
from ringpop_tpu.net.timers import Timers
from ringpop_tpu.utils.config import Config, EventEmitter
from ringpop_tpu.utils import errors
from ringpop_tpu.utils.rollup import MembershipUpdateRollup
from ringpop_tpu.utils.stats import Meter, NullLogger, NullStatsd
from ringpop_tpu.utils.trace import TracerStore
from ringpop_tpu.utils.util import HOST_PORT_PATTERN

MEMBERSHIP_UPDATE_FLUSH_INTERVAL_MS = 5000  # index.js:68


class RingpopError(Exception):
    pass


class Ringpop(EventEmitter):
    def __init__(
        self,
        app: str,
        host_port: str,
        channel: Optional[Channel] = None,
        logger: Any = None,
        statsd: Any = None,
        options: Optional[Dict[str, Any]] = None,
        timers: Optional[Timers] = None,
        seed: Optional[int] = None,
    ):
        super().__init__()
        options = dict(options or {})
        if not app or not isinstance(app, str):
            raise errors.AppRequiredError()
        if (
            not isinstance(host_port, str)
            or not HOST_PORT_PATTERN.match(host_port)
        ):
            raise errors.HostPortRequiredError(hostPort=host_port, reason='a valid host:port')

        self.app = app
        self.host_port = host_port
        self.logger = logger or NullLogger()
        self.statsd = statsd or NullStatsd()
        self.timers = timers or Timers()
        # Date.now() analog riding the timer plane, so fake-timer tests see
        # one coherent clock (Member reads it for damp-score decay deltas)
        self.now = self.timers.now_ms
        self.rng = random.Random(seed)
        self.destroyed = False
        self.is_ready = False
        self.joining = False
        self.bootstrap_hosts: Optional[List[str]] = None
        self._joins_denied = False
        self.debug_flags: Dict[str, bool] = {}
        self.start_time: Optional[float] = None

        # protocol knobs (index.js:112-120)
        self.ping_req_size = options.get("pingReqSize", 3)
        self.ping_req_timeout_ms = options.get("pingReqTimeout", 5000)
        self.ping_timeout_ms = options.get("pingTimeout", 1500)
        self.join_size = options.get("joinSize", 3)
        self.join_timeout_ms = options.get("joinTimeout", 1000)
        self.max_join_duration_ms = options.get("maxJoinDuration", 120000)
        self.proxy_req_timeout_ms = options.get("proxyReqTimeout", 30000)
        self.min_protocol_period_ms = options.get("minProtocolPeriod", 200)
        self.suspicion_timeout_ms = options.get("suspicionTimeout", 5000)

        # stats identity: ringpop.<host_port with non-alnum -> '_'>
        # (index.js:162-164)
        self.stat_host_port = re.sub(r"[.:]", "_", host_port)
        self.stat_prefix = "ringpop.%s" % self.stat_host_port
        self.stat_keys: Dict[str, str] = {}
        self.stats_hooks: Dict[str, Any] = {}

        # components (index.js:124-156)
        self.config = Config(self, options)
        self.membership = Membership(self, rng=self.rng)
        self.member_iterator = MembershipIterator(self)
        self.ring = HashRing()
        self.dissemination = Dissemination(self)
        self.suspicion = Suspicion(self, self.suspicion_timeout_ms)
        self.gossip = Gossip(self, self.min_protocol_period_ms, rng=self.rng)
        self.membership_update_rollup = MembershipUpdateRollup(
            self, MEMBERSHIP_UPDATE_FLUSH_INTERVAL_MS
        )
        self.tracers = TracerStore(self)

        from ringpop_tpu.api.request_proxy import RequestProxy

        self.request_proxy = RequestProxy(self, options.get("requestProxy") or {})

        # request-rate meters (index.js:158-160)
        self.client_rate = Meter()
        self.server_rate = Meter()
        self.total_rate = Meter()

        self.channel = channel
        self.server = None
        if channel is not None:
            self.setup_channel()

        self._wire_events()
        # "It would be more correct to start Membership's background
        # decayer once we know that a member has been penalized for a
        # flap. But it's OK to start prematurely."
        # (lib/membership/index.js:399-407)
        self.membership.start_damp_score_decayer()

    # -- event plumbing (lib/on_membership_event.js etc.) ----------------

    def _wire_events(self) -> None:
        self.membership.on("updated", self._on_membership_updated)
        self.membership.on("set", self._on_membership_set)
        self.membership.on("event", self._on_membership_event)
        # flap-damping signals (membership/index.js:406,415-417 — the
        # reference's onExceeded is a TODO'd subprotocol hook; stats +
        # facade events carry the signal here, recovery included)
        self.membership.on(
            "memberSuppressLimitExceeded", self._on_member_suppressed
        )
        self.membership.on(
            "memberSuppressRecovered", self._on_member_suppress_recovered
        )
        self.ring.on("added", self._on_ring_server_added)
        self.ring.on("removed", self._on_ring_server_removed)
        self.ring.on(
            "checksumComputed",
            lambda *a: self.stat("increment", "ring.checksum-computed"),
        )
        self.on("ready", self._on_ready)

    def _on_member_suppressed(self, member) -> None:
        self.stat("increment", "damp-score.suppress-limit-exceeded")
        self.emit("memberSuppressLimitExceeded", member)

    def _on_member_suppress_recovered(self, member, score) -> None:
        self.stat("increment", "damp-score.suppress-recovered")
        self.emit("memberSuppressRecovered", member, score)

    def _on_ready(self) -> None:
        self.start_time = time.time()
        if self.config.get("autoGossip"):
            self.gossip.start()

    def _on_membership_event(self, event: Dict[str, Any]) -> None:
        # LocalMemberLeaveEvent -> stop gossiping (on_membership_event.js:32-41)
        if event.get("name") == "LocalMemberLeaveEvent":
            self.gossip.stop()
            self.suspicion.stop_all()

    def _on_membership_set(self, updates) -> None:
        # on_membership_event.js:42-68
        servers_to_add = []
        for update in updates:
            d = update.to_dict() if hasattr(update, "to_dict") else dict(update)
            status = d.get("status")
            if status == Status.suspect:
                self.suspicion.start(d)
            if status in (Status.alive, Status.suspect):
                servers_to_add.append(d["address"])
            self.dissemination.record_change(d)
            self.stat("increment", "membership-set.%s" % (status or "unknown"))
        self.ring.add_remove_servers(servers_to_add, [])
        self.emit("membershipChanged")
        self.emit("changed")  # deprecated alias (index.js)

    def _on_membership_updated(self, updates) -> None:
        # on_membership_event.js:70-144 — three responsibilities:
        # stats/rollup, suspicion + dissemination, ring add/remove.
        servers_to_add: List[str] = []
        servers_to_remove: List[str] = []
        for update in updates:
            d = update.to_dict() if hasattr(update, "to_dict") else dict(update)
            status = d.get("status")
            address = d["address"]
            if status == Status.alive:
                self.suspicion.stop(d)
                servers_to_add.append(address)
            elif status == Status.suspect:
                self.suspicion.start(d)
                servers_to_add.append(address)
            elif status == Status.faulty:
                self.suspicion.stop(d)
                servers_to_remove.append(address)
            elif status == Status.leave:
                self.suspicion.stop(d)
                servers_to_remove.append(address)
            self.dissemination.record_change(d)
            self.stat("increment", "membership-update.%s" % (status or "unknown"))
        self.membership_update_rollup.track_updates(updates)
        self.stat("gauge", "num-members", self.membership.get_member_count())
        self.stat("timing", "updates", len(updates))
        self.ring.add_remove_servers(servers_to_add, servers_to_remove)
        self.emit("membershipChanged")
        self.emit("changed")

    def _on_ring_server_added(self, *a) -> None:
        self.stat("increment", "ring.server-added")
        self.dissemination.adjust_max_piggyback_count()
        self.emit("ringServerAdded")
        self.emit("ringChanged")

    def _on_ring_server_removed(self, *a) -> None:
        self.stat("increment", "ring.server-removed")
        self.dissemination.adjust_max_piggyback_count()
        self.emit("ringServerRemoved")
        self.emit("ringChanged")

    # -- identity ---------------------------------------------------------

    def whoami(self) -> str:
        return self.host_port

    # -- channel / server -------------------------------------------------

    def setup_channel(self) -> None:
        from ringpop_tpu.api.server import RingpopServer

        if self.channel is None:
            self.channel = Channel(self.host_port)
        self.server = RingpopServer(self, self.channel)

    # -- bootstrap --------------------------------------------------------

    def _seed_bootstrap_hosts(self, bootstrap_file) -> None:
        # index.js:483-511: array, file path, or JSON string
        if isinstance(bootstrap_file, (list, tuple)):
            self.bootstrap_hosts = list(bootstrap_file)
        elif isinstance(bootstrap_file, str) and os.path.exists(bootstrap_file):
            with open(bootstrap_file) as f:
                self.bootstrap_hosts = json.load(f)
        elif isinstance(bootstrap_file, str):
            try:
                self.bootstrap_hosts = json.loads(bootstrap_file)
            except ValueError:
                raise errors.ArgumentRequiredError(argument='bootstrapFile (readable hosts)')
        else:
            self.bootstrap_hosts = None

    def bootstrap(
        self,
        bootstrap_file_or_opts: Union[None, str, List[str], Dict[str, Any]] = None,
    ) -> Dict[str, Any]:
        """Make the local member alive, join the cluster, apply the merged
        membership atomically, and start gossip (index.js:235-378)."""
        opts: Dict[str, Any] = {}
        if isinstance(bootstrap_file_or_opts, dict):
            opts = dict(bootstrap_file_or_opts)
            bootstrap_file = opts.pop("bootstrapFile", None)
        else:
            bootstrap_file = bootstrap_file_or_opts

        if self.is_ready:
            self.logger.warning(
                "ringpop is already ready", extra={"local": self.whoami()}
            )
            return {"alreadyReady": True}
        if self.channel is None or self.channel.host_port is None:
            raise RingpopError(
                "Channel must be listening before bootstrap"
            )

        self._seed_bootstrap_hosts(bootstrap_file)
        if not self.bootstrap_hosts:
            self.bootstrap_hosts = [self.whoami()]
        if self.whoami() not in self.bootstrap_hosts:
            self.logger.warning(
                "local node missing from bootstrap hosts",
                extra={"local": self.whoami()},
            )

        bootstrap_time = time.time()
        self.membership.make_alive(self.whoami(), self.timers.now_ms())

        others = [h for h in self.bootstrap_hosts if h != self.whoami()]
        nodes_joined: List[str] = []
        if others:
            self.joining = True
            try:
                result = join_cluster(
                    self,
                    {
                        "joinSize": min(self.join_size, len(others)),
                        "joinTimeout": self.join_timeout_ms,
                        "maxJoinDuration": opts.get(
                            "maxJoinDuration", self.max_join_duration_ms
                        ),
                    },
                )
                nodes_joined = result["nodesJoined"]
            finally:
                self.joining = False

        self.membership.set()
        self.is_ready = True
        self.stat("timing", "bootstrap", bootstrap_time)
        self.stat("increment", "bootstrap-complete")
        self.emit("ready")
        return {"bootstrapTime": time.time() - bootstrap_time,
                "nodesJoined": nodes_joined}

    # -- lookup & routing -------------------------------------------------

    def lookup(self, key) -> Optional[str]:
        start = time.time()
        dest = self.ring.lookup(str(key))
        self.stat("timing", "lookup", start)
        self.emit("lookup", {"timing": time.time() - start})
        if dest is None:
            self.logger.warning(
                "could not find destination for key",
                extra={"local": self.whoami(), "key": key},
            )
            return self.whoami()
        return dest

    def lookup_n(self, key, n: int) -> List[str]:
        start = time.time()
        dests = self.ring.lookup_n(str(key), n)
        self.stat("timing", "lookupn", start)
        self.emit("lookup", {"timing": time.time() - start})
        if not dests:
            self.logger.warning(
                "could not find destinations for key",
                extra={"local": self.whoami(), "key": key},
            )
            return [self.whoami()]
        return dests

    def handle_or_proxy(self, key, req, res=None, opts: Optional[dict] = None) -> bool:
        """True -> the caller owns the key and should handle the request;
        False -> the request was proxied to its owner (index.js:580-607)."""
        dest = self.lookup(key)
        if dest == self.whoami():
            return True
        proxy_opts = dict(opts or {})
        proxy_opts.update(keys=[str(key)], dest=dest, req=req, res=res)
        self.proxy_req(proxy_opts)
        return False

    def handle_or_proxy_all(self, keys: Sequence[Any], req, handler=None) -> List[dict]:
        """Group keys by owner; handle local groups via ``handler`` (or the
        'request' event), proxy remote groups (index.js:609-667).
        Returns [{dest, keys, res|error}]."""
        whoami = self.whoami()
        groups: Dict[str, List[str]] = {}
        for key in keys:
            groups.setdefault(self.lookup(key), []).append(str(key))

        out = []
        for dest, dest_keys in groups.items():
            entry: Dict[str, Any] = {"dest": dest, "keys": dest_keys}
            try:
                if dest == whoami:
                    if handler is not None:
                        entry["res"] = handler(dest_keys, req)
                    else:
                        from ringpop_tpu.api.request_proxy import LocalResponse

                        res = LocalResponse()
                        self.emit("request", dict(req or {}, ringpopKeys=dest_keys), res, {})
                        entry["res"] = res.wait(self.proxy_req_timeout_ms / 1000.0)
                else:
                    entry["res"] = self.request_proxy.proxy_req(
                        {"keys": dest_keys, "dest": dest, "req": req}
                    )
            except Exception as e:
                entry["error"] = e
            out.append(entry)
        return out

    def proxy_req(self, opts: Dict[str, Any]):
        if not opts or not opts.get("keys") or not opts.get("dest"):
            raise errors.PropertyRequiredError(property='keys/dest')
        return self.request_proxy.proxy_req(opts)

    # -- stats ------------------------------------------------------------

    def stat(self, stat_type: str, key: str, value: Any = None) -> None:
        """statsd emission with per-key fq-name cache (index.js:527-541)."""
        fq_key = self.stat_keys.get(key)
        if fq_key is None:
            fq_key = "%s.%s" % (self.stat_prefix, key)
            self.stat_keys[key] = fq_key
        if stat_type == "increment":
            self.statsd.increment(fq_key, value if value is not None else 1)
        elif stat_type == "gauge":
            self.statsd.gauge(fq_key, value)
        elif stat_type == "timing":
            # accept either a start timestamp (seconds) or a duration
            if isinstance(value, float) and value > 1e9:
                value = (time.time() - value) * 1000.0
            self.statsd.timing(fq_key, value)

    def register_stats_hook(self, hook: Dict[str, Any]) -> None:
        """index.js:560-578: {name, fetch()} contributes to getStats()."""
        if not hook or "name" not in hook:
            raise errors.PropertyRequiredError(property='name')
        if not callable(hook.get("fetch")):
            raise errors.PropertyRequiredError(property='fetch (callable)')
        if hook["name"] in self.stats_hooks:
            raise errors.DuplicateHookError(name=hook['name'])
        self.stats_hooks[hook["name"]] = hook

    def get_stats(self) -> Dict[str, Any]:
        hooks_stats = {
            name: hook["fetch"]() for name, hook in self.stats_hooks.items()
        }
        uptime = time.time() - self.start_time if self.start_time else 0
        return {
            "hooks": hooks_stats or None,
            "membership": self.membership.get_stats(),
            "process": {"pid": os.getpid()},
            "protocol": self.gossip.get_stats(),
            "ring": sorted(self.ring.servers),
            "version": __import__("ringpop_tpu").__version__,
            "timestamp": int(time.time() * 1000),
            "uptime": uptime,
        }

    # -- debug flags (index.js:513-521) -----------------------------------

    def set_debug_flag(self, flag: str) -> None:
        self.debug_flags[flag] = True

    def clear_debug_flags(self) -> None:
        self.debug_flags = {}

    def debug_flag_enabled(self, flag: str) -> bool:
        return bool(self.debug_flags.get(flag))

    # -- join denial test hook (index.js:670-677) -------------------------

    def deny_joins(self) -> None:
        self._joins_denied = True

    def allow_joins(self) -> None:
        self._joins_denied = False

    def joins_denied(self) -> bool:
        return self._joins_denied

    # -- teardown ---------------------------------------------------------

    def destroy(self) -> None:
        if self.destroyed:
            return
        self.emit("destroying")
        self.gossip.stop()
        self.suspicion.stop_all()
        self.membership.stop_damp_score_decayer()
        self.membership_update_rollup.destroy()
        self.tracers.destroy()
        self.request_proxy.destroy()
        if self.channel is not None:
            self.channel.destroy()
        self.destroyed = True
        self.emit("destroyed")
