"""tick-cluster: operator harness for an N-node cluster
(scripts/tick-cluster.js rebuilt) with two interchangeable backends.

- ``live`` — spawns N real node processes (``python -m ringpop_tpu.api.cli``)
  and drives them over the admin endpoints, with genuine SIGKILL / SIGSTOP /
  SIGCONT fault injection (tick-cluster.js:351-470).
- ``jax-sim`` — the same command surface against the batched device
  simulator (:class:`~ringpop_tpu.models.sim.cluster.SimCluster`), the
  ``backend:'jax-sim'`` adapter of the BASELINE north star.

Commands (tick-cluster.js:249-330 key menu): ``tick`` runs one protocol
period on every live node and prints nodes GROUPED BY MEMBERSHIP CHECKSUM —
the convergence view (tick-cluster.js:87-114) — ``join`` re-joins all,
``kill i`` / ``suspend i`` / ``revive i`` inject faults, ``stats`` dumps
protocol stats.

``generate_hosts`` mirrors scripts/generate-hosts.js:23-58.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import time
from typing import Any, Dict, List, Optional

_PKG_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def generate_hosts(
    path: str, n: int, base_port: int = 3000, host: str = "127.0.0.1"
) -> List[str]:
    """Write a hosts.json bootstrap file (scripts/generate-hosts.js:23-58)."""
    hosts = ["%s:%d" % (host, base_port + i) for i in range(n)]
    with open(path, "w") as f:
        json.dump(hosts, f)
    return hosts


class LiveBackend:
    """N real node processes on 127.0.0.1, driven via admin endpoints."""

    def __init__(
        self,
        n: int,
        base_port: int = 3000,
        app: str = "ringpop",
        hosts_file: Optional[str] = None,
    ):
        import tempfile

        from ringpop_tpu.api.client import RingpopClient

        self.n = n
        self.app = app
        if hosts_file is None:
            fd, hosts_file = tempfile.mkstemp(
                prefix="ringpop-hosts-", suffix=".json"
            )
            os.close(fd)
        self.hosts_file = hosts_file
        self.hosts = generate_hosts(hosts_file, n, base_port)
        self.procs: Dict[str, Optional[subprocess.Popen]] = {}
        self.suspended: Dict[str, bool] = {}
        self.client = RingpopClient(timeout_s=5.0)

    def start(self, startup_timeout_s: float = 30.0) -> None:
        for hp in self.hosts:
            self._spawn(hp)
        # per-host deadline: nodes boot concurrently, so a slow first node
        # must not consume the probe budget of the ones after it
        for hp in self.hosts:
            deadline = time.time() + startup_timeout_s
            while True:
                try:
                    self.client.health(hp)
                    break
                except Exception:
                    if time.time() >= deadline:
                        raise RuntimeError(
                            "node %s never became healthy" % hp
                        )
                    time.sleep(0.1)

    def _spawn(self, host_port: str) -> None:
        env = dict(
            os.environ,
            RINGPOP_TPU_NO_X64="1",  # node proc is host-only: no JAX init
            JAX_PLATFORMS="cpu",
            PYTHONPATH=os.pathsep.join(
                [p for p in (_PKG_ROOT, os.environ.get("PYTHONPATH")) if p]
            ),
        )
        self.procs[host_port] = subprocess.Popen(
            [
                sys.executable,
                "-m",
                "ringpop_tpu.api.cli",
                "--listen",
                host_port,
                "--hosts",
                self.hosts_file,
                "--app",
                self.app,
                "--quiet",
            ],
            env=env,
            stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
        )
        self.suspended[host_port] = False

    # -- command surface --------------------------------------------------

    def tick_all(self) -> Dict[str, Optional[int]]:
        """One gossip period per live node; returns host -> checksum
        (None = unreachable), the '/admin/tick' sweep
        (tick-cluster.js:87-114)."""
        out: Dict[str, Optional[int]] = {}
        for hp in self.hosts:
            try:
                out[hp] = self.client.admin_gossip_tick(hp)["checksum"]
            except Exception:
                out[hp] = None
        return out

    def join_all(self) -> None:
        for hp in self.hosts:
            try:
                self.client.admin_member_join(hp)
            except Exception:
                pass

    def stats_all(self) -> Dict[str, Any]:
        out = {}
        for hp in self.hosts:
            try:
                out[hp] = self.client.admin_stats(hp)
            except Exception:
                out[hp] = None
        return out

    def lookup(self, key, node: int = 0) -> Optional[str]:
        """Key owner per node ``node``'s ring (/admin/lookup)."""
        return self.client.admin_lookup(self.hosts[node], str(key))["dest"]

    def kill(self, i: int) -> None:
        hp = self.hosts[i]
        proc = self.procs.get(hp)
        if proc is not None and proc.poll() is None:
            proc.send_signal(signal.SIGKILL)
            proc.wait(5.0)
        self.procs[hp] = None

    def suspend(self, i: int) -> None:
        hp = self.hosts[i]
        proc = self.procs.get(hp)
        if proc is not None and proc.poll() is None:
            proc.send_signal(signal.SIGSTOP)
            self.suspended[hp] = True

    def revive(self, i: int) -> None:
        """SIGCONT a suspended proc; respawn a killed one
        (tick-cluster.js:417-429)."""
        hp = self.hosts[i]
        proc = self.procs.get(hp)
        if proc is not None and self.suspended.get(hp):
            proc.send_signal(signal.SIGCONT)
            self.suspended[hp] = False
        elif proc is None or proc.poll() is not None:
            self._spawn(hp)

    def destroy(self) -> None:
        self.client.destroy()
        for hp, proc in self.procs.items():
            if proc is not None and proc.poll() is None:
                if self.suspended.get(hp):
                    proc.send_signal(signal.SIGCONT)
                proc.terminate()
        for proc in self.procs.values():
            if proc is not None:
                try:
                    proc.wait(5.0)
                except subprocess.TimeoutExpired:
                    proc.kill()


class JaxSimBackend:
    """The same command surface over the batched device simulator."""

    def __init__(self, n: int, base_port: int = 3000, **sim_kw):
        from ringpop_tpu.models.sim.cluster import SimCluster, default_addresses

        self.n = n
        self.sim = SimCluster(
            n=n, addresses=default_addresses(n, base_port=base_port), **sim_kw
        )
        # engine node indices follow the universe's lexicographically
        # sorted address order (not construction order) — expose hosts in
        # that order so index i means the same node everywhere
        self.hosts = list(self.sim.universe.addresses)
        self._dead: set = set()
        self._suspended: set = set()
        self._replica_hashes = None  # device-ring table, built on demand
        self._ring_cache: Dict[bytes, tuple] = {}  # view bytes -> (ring, n)

    def start(self) -> None:
        self.sim.bootstrap()

    def tick_all(self) -> Dict[str, Optional[int]]:
        self.sim.step()
        cs = self.sim.checksums()
        import numpy as np

        alive = np.asarray(self.sim.state.proc_alive & self.sim.state.ready)
        return {
            hp: (int(cs[i]) if alive[i] else None)
            for i, hp in enumerate(self.hosts)
        }

    def join_all(self) -> None:
        self.sim.bootstrap()

    def stats_all(self) -> Dict[str, Any]:
        import numpy as np

        alive = np.asarray(self.sim.state.proc_alive)
        return {
            hp: {"membership": self.sim.membership_of(i)}
            for i, hp in enumerate(self.hosts)
            if alive[i]
        }

    def lookup(self, key, node: int = 0) -> Optional[str]:
        """Key owner per node ``node``'s view, served from the in-jit
        device ring (the /admin/lookup analog of the jax-sim control
        plane, SURVEY §5.8).  Asking a dead node raises, matching the
        live backend's connection error.  The sorted ring is cached per
        membership view, so repeated lookups between ticks sort once."""
        import jax.numpy as jnp
        import numpy as np

        from ringpop_tpu.models.ring import device as ringdev
        from ringpop_tpu.ops import farmhash32 as fh

        st = self.sim.state
        if not bool(np.asarray(st.proc_alive)[node]):
            raise RuntimeError(
                "node %s is dead; its ring cannot serve lookups"
                % self.hosts[node]
            )
        if self._replica_hashes is None:
            self._replica_hashes = jnp.asarray(
                ringdev.replica_table(self.sim.universe.addresses)
            )
        in_ring_np = np.asarray(st.known[node]) & (
            np.asarray(st.status[node]) <= 1  # alive|suspect stay in ring
        )
        # keyed on the VIEW bytes alone: converged nodes share one ring;
        # bounded so a churny session can't grow it without limit
        cache_key = in_ring_np.tobytes()
        cached = self._ring_cache.get(cache_key)
        if cached is None:
            in_ring = jnp.asarray(in_ring_np)
            ring = ringdev.build_ring(self._replica_hashes, in_ring)
            n_points = ringdev.ring_size(
                in_ring, self._replica_hashes.shape[1]
            )
            if len(self._ring_cache) >= 8:
                self._ring_cache.pop(next(iter(self._ring_cache)))
            self._ring_cache[cache_key] = cached = (ring, n_points)
        ring, n_points = cached
        owner = int(
            ringdev.lookup(ring, n_points, jnp.uint32(fh.hash32(str(key))))
        )
        return self.sim.universe.addresses[owner] if owner >= 0 else None

    def kill(self, i: int) -> None:
        self._dead.add(i)
        self._suspended.discard(i)  # kill trumps an earlier suspend
        self.sim.kill([i])

    def suspend(self, i: int) -> None:
        self._suspended.add(i)
        self.sim.suspend([i])

    def revive(self, i: int) -> None:
        if i in self._suspended:
            self._suspended.discard(i)
            self.sim.resume([i])
        else:
            self._dead.discard(i)
            self.sim.revive([i])

    def destroy(self) -> None:
        pass


class ScalableSimBackend:
    """The tick-cluster command surface over the O(N·U) rumor engine —
    interactive operation of 100k-class clusters (the full-fidelity
    jax-sim backend's [N, N] state caps out around a few thousand).

    Scale adaptations, stated honestly:

    - node identity is the integer index (labels ``node<i>``); there is
      no per-node membership list to print, so ``stats`` reports cluster
      aggregates (live count, active rumors, coverage, distinct views),
    - ``suspend`` maps to ``kill``: the rumor engine models process death
      + fresh restart (the reference rebuilds a restarted node via join
      anyway); SIGSTOP-with-state-intact is the full engine's domain,
    - ``lookup`` serves from the device ring over integer ids
      (models/ring/device.py build_ring), hashing the key with FarmHash32 like the
      reference's ring.
    - per-tick snapshots materialize an N-entry dict for the convergence
      display; fine to ~200k interactively — beyond that, drive the
      engine with benchmarks/storm_1m.py instead.
    """

    MAX_INTERACTIVE_N = 200_000

    def __init__(self, n: int, **storm_kw):
        from ringpop_tpu.models.sim.storm import ScalableCluster

        if n > self.MAX_INTERACTIVE_N:
            raise ValueError(
                "jax-sim-scalable caps interactive use at %d nodes "
                "(per-tick host snapshots); use benchmarks/storm_1m.py "
                "for larger runs" % self.MAX_INTERACTIVE_N
            )
        self.n = n
        self.cluster = ScalableCluster(n=n, **storm_kw)
        self.hosts = ["node%d" % i for i in range(n)]
        # view-keyed ring cache (like JaxSimBackend): converged lookups
        # sort the N*R table once, not per command
        self._ring_cache: Dict[bytes, tuple] = {}

    def start(self) -> None:
        pass  # the rumor engine starts converged-alive (no join round)

    def tick_all(self) -> Dict[str, Optional[int]]:
        import numpy as np

        self.cluster.step()
        cs = self.cluster.checksums()
        alive = np.asarray(self.cluster.state.proc_alive)
        return {
            hp: (int(cs[i]) if alive[i] else None)
            for i, hp in enumerate(self.hosts)
        }

    def join_all(self) -> None:
        pass  # idem: membership is the integer universe, always joined

    def stats_all(self) -> Dict[str, Any]:
        import jax.numpy as jnp
        import numpy as np

        from ringpop_tpu.models.sim import engine_scalable as es

        st = self.cluster.state
        alive = np.asarray(st.proc_alive)
        cs = self.cluster.checksums()
        return {
            "cluster": {
                "n": self.n,
                "live_nodes": int(alive.sum()),
                "active_rumors": int(np.asarray(jnp.sum(st.r_active))),
                "distinct_checksums": int(np.unique(cs[alive]).size),
                "suspects_in_truth": int(
                    (np.asarray(st.truth_status) == es.SUSPECT).sum()
                ),
                "faulty_in_truth": int(
                    (np.asarray(st.truth_status) == es.FAULTY).sum()
                ),
                "ring_checksum": self.cluster.ring_checksum(),
            }
        }

    def lookup(self, key, node: int = 0) -> Optional[str]:
        import jax.numpy as jnp
        import numpy as np

        from ringpop_tpu.models.ring import device as ringdev
        from ringpop_tpu.models.ring.device import (
            build_ring,
            device_replica_hashes,
        )
        from ringpop_tpu.models.sim import engine_scalable as es
        from ringpop_tpu.ops import farmhash32 as fh

        st = self.cluster.state
        in_ring_np = np.asarray(st.proc_alive) & (
            np.asarray(st.truth_status) <= es.SUSPECT
        )
        cache_key = in_ring_np.tobytes()
        cached = self._ring_cache.get(cache_key)
        if cached is None:
            reps = device_replica_hashes(
                self.n, self.cluster.replica_points
            )
            ring = build_ring(reps, jnp.asarray(in_ring_np))
            n_points = int(in_ring_np.sum()) * self.cluster.replica_points
            if len(self._ring_cache) >= 8:
                self._ring_cache.pop(next(iter(self._ring_cache)))
            self._ring_cache[cache_key] = cached = (ring, n_points)
        ring, n_points = cached
        if n_points == 0:
            return None
        # build_ring's table layout is the device-ring layout
        # (hash<<32|owner, sentinel-padded, sorted) — one lookup helper
        owner = int(
            ringdev.lookup(ring, n_points, jnp.uint32(fh.hash32(str(key))))
        )
        return self.hosts[owner] if owner >= 0 else None

    def kill(self, i: int) -> None:
        # like JaxSimBackend (SimCluster.kill), fault injection rides one
        # protocol period: the event IS a tick with the kill input set
        import jax.numpy as jnp

        from ringpop_tpu.models.sim import engine_scalable as es

        kill = jnp.zeros(self.n, bool).at[i].set(True)
        self.cluster.step(
            es.ChurnInputs(kill=kill, revive=jnp.zeros(self.n, bool))
        )

    def suspend(self, i: int) -> None:
        self.kill(i)  # documented: SIGSTOP semantics are full-engine-only

    def revive(self, i: int) -> None:
        import jax.numpy as jnp

        from ringpop_tpu.models.sim import engine_scalable as es

        rv = jnp.zeros(self.n, bool).at[i].set(True)
        self.cluster.step(
            es.ChurnInputs(kill=jnp.zeros(self.n, bool), revive=rv)
        )

    def destroy(self) -> None:
        pass


class TickCluster:
    """Backend-agnostic driver with the tick-cluster command surface.

    Stepping and inspection are separate: :meth:`tick` runs ONE protocol
    round and caches the resulting host->checksum snapshot; the query
    methods (:meth:`checksum_groups`, :meth:`converged`,
    :meth:`format_groups`) read that snapshot without advancing the
    cluster.
    """

    def __init__(self, backend):
        self.backend = backend
        self._snapshot: Optional[Dict[str, Optional[int]]] = None

    @staticmethod
    def create(backend: str, n: int, **kw) -> "TickCluster":
        if backend == "live":
            return TickCluster(LiveBackend(n, **kw))
        if backend == "jax-sim":
            return TickCluster(JaxSimBackend(n, **kw))
        if backend == "jax-sim-scalable":
            kw.pop("base_port", None)  # integer-id universe: no ports
            return TickCluster(ScalableSimBackend(n, **kw))
        raise ValueError(
            "unknown backend %r (live | jax-sim | jax-sim-scalable)"
            % backend
        )

    def start(self) -> None:
        self.backend.start()

    def tick(self) -> Dict[str, Optional[int]]:
        """One gossip round on every live node; caches and returns the
        host -> checksum snapshot (None = unreachable/dead)."""
        self._snapshot = self.backend.tick_all()
        return self._snapshot

    def checksum_groups(self) -> Dict[Any, List[str]]:
        """host lists grouped by checksum from the LAST snapshot; key None
        = dead.  Purely a read: call :meth:`tick` first."""
        if self._snapshot is None:
            raise RuntimeError(
                "no snapshot yet: call tick() before querying groups"
            )
        groups: Dict[Any, List[str]] = {}
        for hp, cs in self._snapshot.items():
            groups.setdefault(cs, []).append(hp)
        return groups

    def format_groups(self, groups: Optional[Dict[Any, List[str]]] = None) -> str:
        """The tick-cluster convergence display (tick-cluster.js:87-114)."""
        if groups is None:
            groups = self.checksum_groups()
        lines = []
        for cs, hosts in sorted(
            groups.items(), key=lambda kv: (kv[0] is None, str(kv[0]))
        ):
            label = "dead" if cs is None else ("%08x" % (cs & 0xFFFFFFFF))
            lines.append("  %s  %d node(s): %s" % (label, len(hosts), " ".join(hosts)))
        n_groups = sum(1 for cs in groups if cs is not None)
        lines.append(
            "  -> %s"
            % ("CONVERGED" if n_groups <= 1 else "%d checksum groups" % n_groups)
        )
        return "\n".join(lines)

    def converged(self) -> bool:
        groups = self.checksum_groups()
        return sum(1 for cs in groups if cs is not None) <= 1

    def tick_until_converged(self, max_ticks: int = 120) -> int:
        for t in range(max_ticks):
            self.tick()
            if self.converged():
                return t + 1
        raise RuntimeError("no convergence after %d ticks" % max_ticks)

    def run_command(self, line: str) -> str:
        """Scriptable command surface (mirrors the key menu,
        tick-cluster.js:249-330)."""
        parts = line.strip().split()
        if not parts:
            return ""
        cmd, args = parts[0], parts[1:]
        if cmd in ("t", "tick"):
            self.tick()
            return self.format_groups()
        if cmd in ("j", "join"):
            self.backend.join_all()
            return "join sent to all nodes"
        if cmd in ("k", "kill"):
            i = int(args[0])
            self.backend.kill(i)
            return "killed %s" % self.backend.hosts[i]
        if cmd in ("l", "suspend"):
            i = int(args[0])
            self.backend.suspend(i)
            return "suspended %s" % self.backend.hosts[i]
        if cmd in ("K", "revive"):
            i = int(args[0])
            self.backend.revive(i)
            return "revived %s" % self.backend.hosts[i]
        if cmd in ("s", "stats"):
            return json.dumps(self.backend.stats_all(), default=str)[:2000]
        if cmd in ("w", "lookup"):
            dest = self.backend.lookup(args[0])
            return "%s -> %s" % (args[0], dest)
        if cmd in ("q", "quit"):
            raise EOFError
        return (
            "commands: tick|join|kill i|suspend i|revive i|stats|"
            "lookup key|quit"
        )

    def interactive(self, stdin=None, stdout=None) -> None:
        stdin = stdin or sys.stdin
        stdout = stdout or sys.stdout
        stdout.write(
            "tick-cluster [%s] %d nodes. Commands: t(ick) j(oin) "
            "k(ill) i, l/suspend i, K/revive i, s(tats), q(uit)\n"
            % (type(self.backend).__name__, len(self.backend.hosts))
        )
        while True:
            stdout.write("> ")
            stdout.flush()
            line = stdin.readline()
            if not line:
                break
            try:
                out = self.run_command(line)
            except EOFError:
                break
            except Exception as e:
                out = "error: %s" % e
            if out:
                stdout.write(out + "\n")

    def destroy(self) -> None:
        self.backend.destroy()


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="tick-cluster",
        description="ringpop-tpu cluster harness (scripts/tick-cluster.js)",
    )
    p.add_argument("-n", type=int, default=5, help="number of nodes")
    p.add_argument(
        "--backend",
        choices=("live", "jax-sim", "jax-sim-scalable"),
        default="live",
    )
    p.add_argument("--base-port", type=int, default=3000)
    p.add_argument(
        "--gen-hosts",
        metavar="PATH",
        help="only write a hosts.json and exit (scripts/generate-hosts.js)",
    )
    args = p.parse_args(argv)

    if args.gen_hosts:
        hosts = generate_hosts(args.gen_hosts, args.n, args.base_port)
        print(json.dumps(hosts))
        return 0

    tc = TickCluster.create(args.backend, args.n, base_port=args.base_port)
    try:
        tc.start()
        tc.interactive()
    finally:
        tc.destroy()
    return 0


if __name__ == "__main__":
    sys.exit(main())
