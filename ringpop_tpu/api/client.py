"""Admin client (client.js rebuilt): drive any node's admin endpoints over
the channel — config get/set, gossip start/stop/tick, lookup, stats,
member join/leave (client.js:37-95)."""

from __future__ import annotations

from typing import Any, Dict, Optional

from ringpop_tpu.net.channel import Channel


class RingpopClient:
    def __init__(self, channel: Optional[Channel] = None, timeout_s: float = 5.0):
        self._owns_channel = channel is None
        self.channel = channel or Channel()
        self.timeout_s = timeout_s

    def _call(self, host_port: str, endpoint: str, body: Any = None):
        _, res = self.channel.request(
            host_port, endpoint, head=None, body=body, timeout_s=self.timeout_s
        )
        return res

    # -- admin surface (client.js:37-95) ----------------------------------

    def admin_config_get(self, host_port: str) -> Dict[str, Any]:
        return self._call(host_port, "/admin/config/get")

    def admin_config_set(self, host_port: str, config: Dict[str, Any]):
        return self._call(host_port, "/admin/config/set", config)

    def admin_gossip_start(self, host_port: str):
        return self._call(host_port, "/admin/gossip/start")

    def admin_gossip_stop(self, host_port: str):
        return self._call(host_port, "/admin/gossip/stop")

    def admin_gossip_tick(self, host_port: str):
        return self._call(host_port, "/admin/gossip/tick")

    def admin_gossip_status(self, host_port: str):
        return self._call(host_port, "/admin/gossip/status")

    def admin_stats(self, host_port: str):
        return self._call(host_port, "/admin/stats")

    def admin_lookup(self, host_port: str, key: str):
        return self._call(host_port, "/admin/lookup", {"key": key})

    def admin_member_join(self, host_port: str):
        return self._call(host_port, "/admin/member/join")

    def admin_member_leave(self, host_port: str):
        return self._call(host_port, "/admin/member/leave")

    def health(self, host_port: str):
        return self._call(host_port, "/health")

    def destroy(self) -> None:
        if self._owns_channel:
            self.channel.destroy()
