"""Public API: the Ringpop facade, request proxy, sharding handler, server
endpoints, admin client, and CLI/tick-cluster tooling."""

from ringpop_tpu.api.ringpop import Ringpop

__all__ = ["Ringpop"]
