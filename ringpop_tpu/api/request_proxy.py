"""Request forwarding (lib/request-proxy/ rebuilt).

Client side: serialize a request's routing envelope — url, method, headers,
keys, and the local membership checksum — and send it to the key's owner
over ``/proxy/req`` (lib/request-proxy/send.js:230-307, util.js:22-35).
Failures retry on the reference's schedule (0 s, 1 s, 3.5 s,
send.js:49) after **re-looking-up the keys**: if the ring moved, the retry
reroutes to the new owner (send.js:181-208); if the keys now map to more
than one owner, the retry aborts with a keys-diverged error
(send.js:91-104); if the new owner is the local node, the request is
handled in-process (send.js:190-198).

Server side: rebuild the request, reject on membership-checksum mismatch
when ``enforceConsistency`` (lib/request-proxy/index.js:168-229), and emit
``request`` to the application.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

from ringpop_tpu.net.channel import ChannelError, RemoteError
from ringpop_tpu.utils import errors

RETRY_SCHEDULE_S = [0.0, 1.0, 3.5]  # send.js:49
DEFAULT_MAX_RETRIES = 3


class LocalResponse:
    """Response collector handed to 'request' handlers: call ``end(body)``
    (optionally ``status``) exactly once."""

    def __init__(self):
        self._event = threading.Event()
        self.status = 200
        self.body = None
        self.headers: Dict[str, Any] = {}

    def end(self, body: Any = None, status: int = 200, headers=None) -> None:
        self.body = body
        self.status = status
        if headers:
            self.headers = dict(headers)
        self._event.set()

    def wait(self, timeout_s: float):
        if not self._event.wait(timeout_s):
            raise ChannelError("request handler timed out", "ringpop-tpu.timeout")
        return {"statusCode": self.status, "headers": self.headers, "body": self.body}


class RequestProxy:
    def __init__(self, ringpop: Any, opts: Optional[Dict[str, Any]] = None):
        opts = opts or {}
        self.ringpop = ringpop
        self.retry_schedule_s = opts.get("retrySchedule", RETRY_SCHEDULE_S)
        self.max_retries = opts.get("maxRetries", DEFAULT_MAX_RETRIES)
        self.enforce_consistency = opts.get("enforceConsistency", True)
        # buffered-body cap forwarded to every send, overridable per
        # request (lib/request-proxy/index.js:88-90); None = unlimited
        self.body_limit = opts.get("bodyLimit")
        self.destroyed = False

    @staticmethod
    def _body_length(body: Any) -> int:
        """Byte length of the body as it will ride the wire — the analog
        of the reference buffering the raw request stream."""
        if body is None:
            return 0
        if isinstance(body, (bytes, bytearray)):
            return len(body)
        import json

        # everything else rides the channel as its JSON encoding
        return len(json.dumps(body).encode("utf-8"))

    def _check_body_limit(self, body: Any, limit: Optional[int]) -> None:
        if limit is None:
            return
        length = self._body_length(body)
        if length > limit:
            # reference: body-module limit error -> logger.warn
            # 'requestProxy encountered malformed body' -> sendError(res)
            # (lib/request-proxy/index.js:93-100)
            self.ringpop.logger.warning(
                "requestProxy encountered malformed body",
                extra={"limit": limit, "length": length},
            )
            raise errors.BodyLimitExceededError(limit=limit, length=length)

    # -- client side ------------------------------------------------------

    def proxy_req(self, opts: Dict[str, Any]) -> Dict[str, Any]:
        """opts: {keys, dest, req: {url, method, headers, body}, timeout?,
        maxRetries?, endpoint?}.  Returns the remote response dict."""
        if self.destroyed:
            raise errors.RequestProxyDestroyedError()
        keys: List[str] = list(opts["keys"])
        dest: str = opts["dest"]
        req = dict(opts.get("req") or {})
        timeout_s = (opts.get("timeout") or self.ringpop.proxy_req_timeout_ms) / 1000.0
        max_retries = opts.get("maxRetries", self.max_retries)
        endpoint = opts.get("endpoint", "/proxy/req")
        self._check_body_limit(
            req.get("body"), opts.get("bodyLimit", self.body_limit)
        )

        self.ringpop.stat("increment", "requestProxy.requests.outgoing")
        attempt = 0
        while True:
            if self.destroyed or getattr(
                self.ringpop.channel, "destroyed", False
            ):
                # the reference re-checks before every forwarding attempt —
                # a proxy OR channel destroyed mid-retry aborts the
                # in-flight send rather than burning the retry schedule
                # against a dead channel ('Channel was destroyed before
                # forwarding attempt', send.js:228-234,
                # test/integration/proxy-test.js:1039-1063)
                raise errors.RequestProxyDestroyedError()
            head = {
                "url": req.get("url"),
                "method": req.get("method", "GET"),
                "headers": req.get("headers") or {},
                "httpVersion": req.get("httpVersion", "1.1"),
                "ringpopChecksum": self.ringpop.membership.checksum,
                "ringpopKeys": keys,
            }
            try:
                _, res = self.ringpop.channel.request(
                    dest, endpoint, head=head, body=req.get("body"),
                    timeout_s=timeout_s,
                )
                if attempt > 0:
                    # a RETRY landed (send.js:160-166)
                    self.ringpop.stat(
                        "increment", "requestProxy.retry.succeeded"
                    )
                self.ringpop.stat("increment", "requestProxy.send.success")
                return res
            except (ChannelError, RemoteError) as e:
                if isinstance(e, RemoteError):
                    payload = e.payload or {}
                    # checksum mismatches are retryable (ring may converge);
                    # other application errors are not
                    if payload.get("type") != errors.InvalidCheckSumError.type:
                        self.ringpop.stat(
                            "increment", "requestProxy.send.error"
                        )
                        raise
                if attempt >= max_retries:
                    self.ringpop.stat(
                        "increment", "requestProxy.retry.failed"
                    )
                    self.ringpop.stat("increment", "requestProxy.send.error")
                    raise errors.MaxRetriesExceededError(maxRetries=max_retries)
                delay = self.retry_schedule_s[
                    min(attempt, len(self.retry_schedule_s) - 1)
                ]
                self.ringpop.stat("increment", "requestProxy.retry.attempted")
                self.ringpop.timers.sleep(delay)
                attempt += 1
                dest = self._relookup(keys, dest)
                if dest == self.ringpop.whoami():
                    # reroute local (send.js:190-198) — a landed retry and
                    # a completed request, so the full success accounting
                    # fires like the remote path's
                    self.ringpop.stat(
                        "increment", "requestProxy.retry.reroute.local"
                    )
                    try:
                        out = self._handle_locally(head, req.get("body"))
                    except Exception:
                        # keep the accounting closed like the remote
                        # handler-error path does
                        self.ringpop.stat(
                            "increment", "requestProxy.send.error"
                        )
                        raise
                    self.ringpop.stat(
                        "increment", "requestProxy.retry.succeeded"
                    )
                    self.ringpop.stat(
                        "increment", "requestProxy.send.success"
                    )
                    return out
                self.ringpop.stat(
                    "increment", "requestProxy.retry.reroute.remote"
                )

    def _relookup(self, keys: List[str], orig_dest: str) -> str:
        dests = {self.ringpop.lookup(k) for k in keys}
        if len(dests) > 1:
            self.ringpop.stat("increment", "requestProxy.retry.aborted")
            # the request fails permanently here: close the accounting
            self.ringpop.stat("increment", "requestProxy.send.error")
            raise errors.KeysDivergedError(
                keys=keys, origDestination=orig_dest,
                newDestinations=sorted(dests),
            )
        return next(iter(dests))

    def _handle_locally(self, head: Dict[str, Any], body: Any) -> Dict[str, Any]:
        req = {
            "url": head.get("url"),
            "method": head.get("method"),
            "headers": head.get("headers"),
            "httpVersion": head.get("httpVersion"),
            "body": body,
            "ringpopKeys": head.get("ringpopKeys"),
        }
        res = LocalResponse()
        self.ringpop.emit("request", req, res, head)
        return res.wait(self.ringpop.proxy_req_timeout_ms / 1000.0)

    # -- server side ------------------------------------------------------

    def handle_request(self, head: Dict[str, Any], body: Any) -> Dict[str, Any]:
        """The ``/proxy/req`` receive path (request-proxy/index.js:168-229)."""
        self.ringpop.stat("increment", "requestProxy.requests.incoming")
        expected = head.get("ringpopChecksum")
        if expected != self.ringpop.membership.checksum:
            # the differ STAT fires whether or not consistency is
            # enforced; only the rejection is gated
            # (lib/request-proxy/index.js:186-193)
            self.ringpop.stat("increment", "requestProxy.checksumsDiffer")
            self.ringpop.logger.warning(
                "ringpop request proxy checksums differ",
                extra={
                    "local": self.ringpop.whoami(),
                    "expected": expected,
                    "actual": self.ringpop.membership.checksum,
                },
            )
            if self.enforce_consistency:
                raise errors.InvalidCheckSumError(
                    expected=expected,
                    actual=self.ringpop.membership.checksum,
                )
        return self._handle_locally(head, body)

    def destroy(self) -> None:
        self.destroyed = True
