"""Standalone node CLI (main.js:24-85 rebuilt).

``python -m ringpop_tpu.api.cli --listen 127.0.0.1:3000 --hosts hosts.json``
starts one Ringpop node: open the channel, bootstrap against the hosts
file, gossip until terminated.  Mirrors the reference ``ringpop`` bin:
``--listen/-l`` and ``--hosts/-h`` are both required (main.js:29-37 prints
usage and exits otherwise).

The node is pure host-side control plane (sockets + SWIM objects + the C++
hash oracle) — it never touches JAX, so we default ``RINGPOP_TPU_NO_X64``
on to keep the package import from initializing a TPU backend in every
cluster process.
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import signal
import sys
import threading

os.environ.setdefault("RINGPOP_TPU_NO_X64", "1")

from ringpop_tpu.api.ringpop import Ringpop  # noqa: E402
from ringpop_tpu.net.channel import Channel  # noqa: E402


def make_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="ringpop-tpu",
        description="Start a ringpop-tpu node (reference: main.js)",
    )
    p.add_argument(
        "--listen",
        "-l",
        metavar="HOST:PORT",
        help="host and port on which the node listens",
    )
    p.add_argument(
        "--hosts",
        "-H",
        metavar="FILE|JSON",
        help="bootstrap hosts: a hosts.json path or a JSON array",
    )
    p.add_argument("--app", default="ringpop", help="app name (cluster id)")
    p.add_argument(
        "--quiet", action="store_true", help="suppress the console logger"
    )
    return p


def main(argv=None) -> int:
    parser = make_parser()
    args = parser.parse_args(argv)
    # main.js:29-37: both required, usage printed otherwise
    if not args.listen or not args.hosts:
        parser.print_usage(sys.stderr)
        return 1

    logger = None
    if not args.quiet:
        logging.basicConfig(
            level=logging.INFO,
            format="%(asctime)s %(levelname)s %(message)s",
            stream=sys.stderr,
        )
        logger = logging.getLogger("ringpop-tpu")

    done = threading.Event()

    def on_signal(signum, frame):
        done.set()

    # handlers installed before the 'ready' line: a supervisor may signal
    # the instant it reads it
    signal.signal(signal.SIGTERM, on_signal)
    signal.signal(signal.SIGINT, on_signal)

    channel = Channel(args.listen)
    host_port = channel.listen()
    ringpop = Ringpop(args.app, host_port, channel=channel, logger=logger)
    ringpop.bootstrap(args.hosts)
    print(json.dumps({"listening": host_port, "ready": True}), flush=True)
    done.wait()
    ringpop.destroy()
    return 0


if __name__ == "__main__":
    sys.exit(main())
